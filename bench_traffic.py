"""Traffic-shaped load benchmark for the network front door ->
TRAFFIC_BENCH_r17.json.

Replays ONE seeded, heavy-tailed open-loop trace twice against the same
warm process and compares the in-process serve layer with the full wire
path (``NetServer`` + ``SRClient`` over a real localhost socket):

1. **baseline** — jobs submitted straight into a ``SearchServer``
   (fleet-coalescing, r13 dedup active).
2. **wire** — the same trace through the SDK: pickle -> CRC-framed
   socket -> asyncio server -> ``SearchServer``, frames streamed back as
   subscription pushes. TTFF is measured at the CLIENT: submit() call to
   first pushed frame in hand.

The trace is what a real front door sees, not a uniform batch:

- lognormal inter-arrival gaps plus zero-gap bursts and one 12-deep
  storm (exercises admission shed / ``retry_after_s``);
- ~half the searches are duplicate HOT queries (3 hot specs) — the r13
  request-dedup + fleet-coalescing path;
- multitarget events submit 2 jobs sharing X with different targets;
- a rolling live subscription (device scheduler, ``push_rows``-style
  streaming lane) cancelled after 2 frames;
- deadline (1s / 6s) and priority (0 / 5) spreads on a slice of the
  searches so some jobs expire under backlog and high-priority arrivals
  exercise preemption ordering.

Both phases measure frame arrival the same way (a 2 ms poll of the frame
list), so the reported TTFF difference is the wire path itself, not a
measurement asymmetry. "Frontier staleness" is the proxy
``arrival_wall - (submit_wall + frame.wall_time)`` — how far behind a
just-received frontier is from the engine wall-clock that produced it,
queue wait included (identically in both phases).

Acceptance (ISSUE r17): wire ttff_p50 <= 1.25x the in-process baseline
on the same trace.

Usage::

    JAX_PLATFORMS=cpu python bench_traffic.py                 # default trace
    JAX_PLATFORMS=cpu python bench_traffic.py --quick         # short trace
    JAX_PLATFORMS=cpu python bench_traffic.py --full          # long trace
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

import numpy as np

HOT_SEEDS = (0, 1, 2)
SUB_CANCEL_AFTER = 2  # frames before a live subscription is cancelled
MAX_LIVE_SUBS = 1


def _problem(n=100, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(2, n)).astype(np.float32)
    y0 = (2 * np.cos(X[1]) + X[0] ** 2 - 2).astype(np.float32)
    y1 = (X[0] * X[1] + 0.5 * X[0]).astype(np.float32)
    return X, (y0, y1)


def _opts(seed=0):
    from symbolicregression_jl_tpu import Options

    return Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        populations=4,
        population_size=16,
        ncycles_per_iteration=40,
        maxsize=14,
        save_to_file=False,
        seed=seed,
        scheduler="device",
    )


def _pctl(values, p):
    if not values:
        return None
    v = sorted(values)
    k = min(len(v) - 1, max(0, int(round(p / 100 * (len(v) - 1)))))
    return v[k]


def _gen_trace(n_events: int, seed: int = 17) -> list[dict]:
    """One seeded open-loop arrival trace, reused verbatim by both phases."""
    rng = np.random.default_rng(seed)
    events: list[dict] = []
    storm_at = n_events // 2
    for i in range(n_events):
        gap = 0.0 if rng.random() < 0.15 else float(rng.lognormal(-2.2, 1.2))
        gap = min(gap, 2.0)
        r = rng.random()
        if r < 0.45:
            ev = {"kind": "hot", "seed": int(rng.choice(HOT_SEEDS))}
        elif r < 0.75:
            ev = {"kind": "search", "seed": 100 + i}
        elif r < 0.85:
            ev = {"kind": "multi", "seed": 200 + i}
        else:
            ev = {"kind": "sub"}
        if ev["kind"] in ("hot", "search") and rng.random() < 0.3:
            ev["deadline_s"] = float(rng.choice([1.0, 6.0]))
            ev["priority"] = int(rng.choice([0, 5]))
        ev["gap"] = round(gap, 4)
        events.append(ev)
    # one 12-deep zero-gap storm of the hottest query mid-trace: the
    # admission queue must shed (or dedup) rather than buffer unboundedly
    storm = [{"kind": "hot", "seed": HOT_SEEDS[0], "gap": 0.0}] * 12
    return events[:storm_at] + storm + events[storm_at:]


class _Rec:
    __slots__ = ("job_id", "kind", "submit_wall", "arrivals", "seen",
                 "cancelled", "state")

    def __init__(self, job_id, kind, submit_wall):
        self.job_id = job_id
        self.kind = kind
        self.submit_wall = submit_wall
        self.arrivals: list[float] = []  # wall clock per received frame
        self.seen = 0
        self.cancelled = False
        self.state: str | None = None


def _specs_for(ev, X, ys):
    """Expand one trace event into its JobSpec list."""
    from symbolicregression_jl_tpu.serve import JobSpec

    kw = {}
    if "deadline_s" in ev:
        kw = {"deadline_seconds": ev["deadline_s"], "priority": ev["priority"]}
    if ev["kind"] in ("hot", "search"):
        return [
            JobSpec(X, ys[0], options=_opts(seed=ev["seed"]), niterations=1,
                    stream_every=1, label=f"{ev['kind']}-{ev['seed']}", **kw)
        ]
    if ev["kind"] == "multi":  # M targets sharing one X
        return [
            JobSpec(X, yj, options=_opts(seed=ev["seed"]), niterations=1,
                    stream_every=1, label=f"multi-{ev['seed']}-{j}")
            for j, yj in enumerate(ys)
        ]
    return [
        JobSpec(X, ys[0], options=_opts(seed=0), kind="subscription",
                stream_config={"row_bucket": 128}, label="sub")
    ]


def _run_phase(trace, X, ys, *, submit, frames_of, cancel, wait,
               shed_errors) -> dict:
    """Replay the trace open-loop through one phase's adapters.

    ``submit(spec) -> job_id`` (raises one of ``shed_errors`` on shed;
    retried once after 0.25s), ``frames_of(job_id) -> list`` (the live
    frame list the monitor polls), ``cancel(job_id)``,
    ``wait(job_id, timeout) -> state str``.
    """
    recs: dict[str, _Rec] = {}
    counters = {"submits": 0, "shed": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def monitor():
        while not stop.is_set():
            with lock:
                live = list(recs.values())
            for rec in live:
                try:
                    frames = frames_of(rec.job_id)
                except KeyError:
                    continue
                now = time.time()
                while rec.seen < len(frames):
                    rec.arrivals.append(now)
                    rec.seen += 1
                if (rec.kind == "sub" and not rec.cancelled
                        and rec.seen >= SUB_CANCEL_AFTER):
                    rec.cancelled = True
                    try:
                        cancel(rec.job_id)
                    except Exception:
                        pass
            time.sleep(0.002)

    mon = threading.Thread(target=monitor, name="bench-monitor", daemon=True)
    mon.start()
    t_start = time.time()
    live_subs = 0
    for ev in trace:
        time.sleep(ev["gap"])
        if ev["kind"] == "sub":
            with lock:
                live_subs = sum(
                    1 for r in recs.values()
                    if r.kind == "sub" and not r.cancelled
                )
            if live_subs >= MAX_LIVE_SUBS:
                continue  # the trace says "subscribe" but the cap is hit
        for spec in _specs_for(ev, X, ys):
            counters["submits"] += 1
            jid = None
            for attempt in range(2):
                try:
                    jid = submit(spec)
                    break
                except shed_errors as exc:
                    if attempt == 1:
                        counters["shed"] += 1
                    else:
                        time.sleep(
                            getattr(exc, "retry_after_s", None) or 0.25
                        )
            if jid is not None:
                with lock:
                    recs[jid] = _Rec(jid, ev["kind"], time.time())

    for rec in recs.values():  # drain: every accepted job reaches terminal
        try:
            rec.state = wait(rec.job_id, 900.0)
        except TimeoutError:
            rec.state = "timeout"
    wall = time.time() - t_start
    time.sleep(0.05)  # let the monitor catch terminal frame appends
    stop.set()
    mon.join(timeout=5.0)

    done = [r for r in recs.values() if r.state == "done"]
    expired = [r for r in recs.values() if r.state == "expired"]
    ttff = [
        r.arrivals[0] - r.submit_wall for r in recs.values() if r.arrivals
    ]
    from symbolicregression_jl_tpu.utils.checkpoint import load_frontier_bytes

    staleness = []
    for rec in recs.values():
        if rec.kind == "sub" or not rec.arrivals:
            continue
        try:
            frames = frames_of(rec.job_id)
        except KeyError:
            continue
        for arrival, frame in zip(rec.arrivals, frames):
            upd = load_frontier_bytes(frame)
            staleness.append(arrival - (rec.submit_wall + upd.wall_time))
    bad = {
        r.job_id: r.state
        for r in recs.values()
        if r.state not in ("done", "expired")
    }
    assert not bad, f"jobs neither done nor expired: {bad}"
    return {
        "submits": counters["submits"],
        "accepted": len(recs),
        "shed": counters["shed"],
        "shed_rate": round(counters["shed"] / counters["submits"], 4),
        "done": len(done),
        "expired": len(expired),
        "wall_s": round(wall, 2),
        "goodput_jobs_per_hour": round(len(done) / wall * 3600, 1),
        "ttff_p50_s": round(_pctl(ttff, 50), 4),
        "ttff_p99_s": round(_pctl(ttff, 99), 4),
        "frontier_staleness_p50_s": round(_pctl(staleness, 50), 4),
        "frontier_staleness_p99_s": round(_pctl(staleness, 99), 4),
        "frames_received": sum(len(r.arrivals) for r in recs.values()),
    }


def _job_frames(srv):
    """In-process frame accessor that resolves each Job object ONCE —
    polling ``srv.job()`` per tick would hammer the server lock from the
    monitor thread and slow the very phase being measured."""
    jobs: dict[str, object] = {}

    def frames_of(jid):
        job = jobs.get(jid)
        if job is None:
            job = jobs[jid] = srv.job(jid)
        return job.frames

    return frames_of


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="TRAFFIC_BENCH_r17.json")
    ap.add_argument("--events", type=int, default=None)
    ap.add_argument("--workers", type=int,
                    default=max(4, (os.cpu_count() or 2) // 2))
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--full", action="store_true")
    args = ap.parse_args()
    n_events = args.events or (16 if args.quick else 96 if args.full else 40)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from symbolicregression_jl_tpu.serve import (
        JobSpec,
        NetServer,
        SearchServer,
        ServerOverloaded,
    )
    from symbolicregression_jl_tpu.serve.net import (
        RetryableWireError,
        SRClient,
    )

    X, ys = _problem()
    trace = _gen_trace(n_events)
    fleet_max = 8
    quota = fleet_max * args.workers

    def new_server():
        return SearchServer(
            max_concurrency=args.workers,
            fleet=True,
            fleet_max=fleet_max,
            default_quota=quota,
            queue_max_depth=24,
        )

    # -- warmup: compile every program the trace will touch -------------------
    print("warmup (hot search, distinct-seed fleet pair, multitarget, "
          "subscription)...")
    t0 = time.time()
    with new_server() as srv:
        warm = [
            srv.submit(JobSpec(X, ys[0], options=_opts(seed=s), niterations=1))
            for s in (HOT_SEEDS[0], 100, 101)
        ]
        warm.append(
            srv.submit(JobSpec(X, ys[1], options=_opts(seed=0), niterations=1))
        )
        for jid in warm:
            assert srv.wait(jid, timeout=3600).state == "done"
        sub = srv.submit(
            JobSpec(X, ys[0], options=_opts(seed=0), kind="subscription",
                    stream_config={"row_bucket": 128})
        )
        while not srv.frames(sub):
            time.sleep(0.05)
        srv.cancel(sub)
        srv.wait(sub, timeout=600)
    print(f"  warm in {time.time() - t0:.1f}s")

    # -- warm replay: the full trace once, unmeasured -------------------------
    # The trace reaches paths the batch warmup above cannot (e.g. a
    # priority-5 arrival preempting a fleet lane, whose resume then runs the
    # SOLO device program). Whichever measured phase ran first would pay
    # those residual compiles alone — replay the whole trace once so both
    # measured phases are equally warm. Gaps are capped low: compile
    # coverage depends on the job mix, not the pacing.
    print("warm replay (full trace, unmeasured, gaps capped at 50ms)...")
    t0 = time.time()
    warm_trace = [dict(ev, gap=min(ev["gap"], 0.05)) for ev in trace]
    srv = new_server().start()
    try:
        _run_phase(
            warm_trace, X, ys,
            submit=srv.submit,
            frames_of=_job_frames(srv),
            cancel=srv.cancel,
            wait=lambda jid, t: srv.wait(jid, timeout=t).state,
            shed_errors=(ServerOverloaded,),
        )
    finally:
        srv.shutdown()
    print(f"  replayed in {time.time() - t0:.1f}s")

    # -- phase 1: in-process baseline ----------------------------------------
    print(f"baseline phase: {len(trace)} events in-process...")
    srv = new_server().start()
    try:
        baseline = _run_phase(
            trace, X, ys,
            submit=srv.submit,
            frames_of=_job_frames(srv),
            cancel=srv.cancel,
            wait=lambda jid, t: srv.wait(jid, timeout=t).state,
            shed_errors=(ServerOverloaded,),
        )
    finally:
        srv.shutdown()
    print(f"  {baseline}")

    # -- phase 2: the same trace through the wire ----------------------------
    print(f"wire phase: {len(trace)} events via NetServer + SRClient...")
    srv = new_server().start()
    net = NetServer(srv, host="127.0.0.1", port=0).start()
    try:
        with SRClient("127.0.0.1", net.port, tenant="bench") as cli:
            def wire_submit(spec):
                jid = cli.submit(spec)
                cli.subscribe(jid)  # frames arrive as pushes from here on
                return jid

            def wire_wait(jid, t):
                try:
                    return cli.wait(jid, timeout=t)["state"]
                except TimeoutError:
                    return "timeout"

            wire = _run_phase(
                trace, X, ys,
                submit=wire_submit,
                frames_of=lambda jid: cli.stream_state(jid).frames,
                cancel=cli.cancel,
                wait=wire_wait,
                shed_errors=(RetryableWireError,),
            )
            net_stats = net.net_stats()
    finally:
        net.shutdown()
        srv.shutdown()
    print(f"  {wire}")

    ratio = round(wire["ttff_p50_s"] / baseline["ttff_p50_s"], 3)
    acceptance = {
        "wire_ttff_p50_s": wire["ttff_p50_s"],
        "baseline_ttff_p50_s": baseline["ttff_p50_s"],
        "wire_vs_baseline_ttff_p50": ratio,
        "target_wire_vs_baseline": 1.25,
        "pass": ratio <= 1.25,
    }
    out = {
        "bench": "traffic",
        "round": "r17",
        "platform": jax.devices()[0].platform,
        "n_devices": jax.device_count(),
        "config": {
            "problem": "2 cos(x1) + x0^2 - 2 (+ x0*x1 multitarget), n=100, "
            "float32",
            "engine": "device scheduler, populations=4 x 16, ncycles=40, "
            "maxsize=14, niterations=1 per search job",
            "trace_events": len(trace),
            "trace_seed": 17,
            "workers": args.workers,
            "fleet_max": fleet_max,
            "queue_max_depth": 24,
            "mix": "45% hot-duplicate searches (3 hot specs, r13 dedup), "
            "30% distinct searches, 10% 2-target multitarget, 15% "
            "subscription attempts (<=1 live, cancelled after "
            f"{SUB_CANCEL_AFTER} frames); 30% of searches carry "
            "deadline (1s/6s) + priority (0/5) spreads; 12-deep "
            "zero-gap hot storm mid-trace",
            "ttff": "submit call -> first frame observed by a 2ms poll of "
            "the frame list (identical instrumentation both phases; wire "
            "frames are pushed to the client, baseline frames read "
            "in-process)",
            "staleness": "frame arrival wall - (submit wall + frame's "
            "engine wall_time): how stale a just-received frontier is, "
            "queue wait included",
        },
        "baseline": baseline,
        "wire": wire,
        "net": net_stats,
        "acceptance": acceptance,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(acceptance, indent=2))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
