"""Kernel roofline measurement: TRUE execution time of the fused loss kernel.

Measurement methodology (the only reliable one found on the tunneled backend —
see ROOFLINE_r03.md "measurement pathology"): chain K kernel invocations
inside ONE jitted dispatch (CSE defeated by perturbing vals per invocation),
time the dispatch in the SYNC regime (after the first device-to-host copy),
and fit time-vs-K — the slope is pure kernel execution, the intercept absorbs
the backend's ~100ms fixed dispatch overhead. `block_until_ready` in the
async regime returns without waiting on this backend and must not be trusted
for timing.

Emits one JSON line: kernel-true evals/s, ns per (tree,slot), and the
utilization decomposition against the pure-vector floor and VPU peak.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

P, R, N = 10_240, 10_240, 20
V5E_VPU_FLOPS = 3.8e12


def _platform_stamp() -> dict:
    """Machine-readable honesty stamp on every roofline row: which backend
    actually ran, and an explicit indicative_only flag off-TPU (utilization
    is reported against the v5e VPU peak either way, so CPU/interpret rows
    are structural smoke numbers, not roofline measurements)."""
    import jax

    platform = jax.devices()[0].platform
    return {"platform": platform, "indicative_only": platform != "tpu"}


def main():
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.models.population import Population
    from symbolicregression_jl_tpu.ops import flatten_trees
    from symbolicregression_jl_tpu.ops.interp_pallas import (
        C_TILE,
        P_TILE_LOSS,
        _loss_pallas,
        _reshape_rows,
        pack_flat_fused,
    )

    rng = np.random.default_rng(0)
    X = rng.normal(size=(5, R)).astype(np.float32)
    y = np.cos(X[0]).astype(np.float32)
    opts = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp", "abs"],
        maxsize=N,
        save_to_file=False,
    )
    opset, loss_elem = opts.operators, opts.loss
    trees = Population.random_trees(P, opts, 5, rng)
    slots = float(np.mean([len(t.postorder()) for t in trees]))
    Xr, yr, wr, C, Rr = _reshape_rows(X, y, None)
    flat = flatten_trees(trees, N)
    ints, vals = pack_flat_fused(flat, opset)

    def make_chain(K):
        @jax.jit
        def fK(ints, vals):
            acc = jnp.zeros((P,), jnp.float32)
            for k in range(K):
                v = vals + (k + 1) * 1e-7  # defeat CSE between invocations
                out = _loss_pallas(
                    ints, v, Xr, yr, wr, opset, loss_elem,
                    N, P_TILE_LOSS, C_TILE, C, Rr,
                )
                acc = acc + jnp.where(jnp.isfinite(out), out, 0.0)
            return acc

        return fK

    # first readback drops the backend into the sync regime: every timed
    # np.asarray below then waits for real execution
    _ = np.asarray(make_chain(1)(ints, vals))

    pts = []
    for K in (1, 2, 4, 8):
        fK = make_chain(K)
        _ = np.asarray(fK(ints, vals))  # compile
        reps = 6
        t0 = time.time()
        for _i in range(reps):
            _ = np.asarray(fK(ints, vals))
        pts.append((K, (time.time() - t0) / reps))

    ks = np.array([p[0] for p in pts], float)
    ts = np.array([p[1] for p in pts], float)
    A = np.vstack([ks, np.ones_like(ks)]).T
    slope, intercept = np.linalg.lstsq(A, ts, rcond=None)[0]

    evals_per_sec = P / slope
    ns_per_slot = slope / P / slots * 1e9
    # pure-vector floor: 10 vregs (one (8,1280) f32 tile op) per (tree, slot)
    # at 1 vreg-op/cycle, 940 MHz
    vector_floor_s = P * slots * 10 / 0.94e9
    useful_flops = evals_per_sec * slots * R
    print(
        json.dumps(
            {
                "metric": "kernel_roofline",
                **_platform_stamp(),
                "kernel_true_evals_per_sec": round(evals_per_sec, 0),
                "kernel_exec_ms_per_sweep": round(slope * 1000, 2),
                "dispatch_overhead_ms": round(intercept * 1000, 1),
                "ns_per_tree_slot": round(ns_per_slot, 1),
                "avg_nodes_per_tree": round(slots, 2),
                "vector_floor_ms_per_sweep": round(vector_floor_s * 1000, 2),
                "scalar_control_gap": round(slope / vector_floor_s, 1),
                "vpu_utilization_true": round(useful_flops / V5E_VPU_FLOPS, 4),
            }
        )
    )


def rows_sweep(P_sweep: int = 512):
    """The last open roofline lever (VERDICT r4 task 4): kernel-true rate vs
    dataset rows. Each (tree, slot) step pays scalar opcode dispatch ONCE per
    row-tile loop — at R >> 10k the (8, C_TILE) row tiles per tree grow, so
    the scalar-control overhead should amortize and VPU utilization recover.
    Sweeps R with the same chain-K methodology as main(); P is held at 512
    (the finalize/const-opt batch scale where big-R e2e searches live).

    Emits one JSON line per R. Timing: loop_only (sync regime, slope of
    time-vs-K); single runs, ±30% tunneled-TPU variance band."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.models.population import Population
    from symbolicregression_jl_tpu.ops import flatten_trees
    from symbolicregression_jl_tpu.ops.interp_pallas import (
        C_TILE,
        P_TILE_LOSS,
        _loss_pallas,
        _reshape_rows,
        pack_flat_fused,
    )

    rng = np.random.default_rng(0)
    opts = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp", "abs"],
        maxsize=N,
        save_to_file=False,
    )
    opset, loss_elem = opts.operators, opts.loss
    trees = Population.random_trees(P_sweep, opts, 5, rng)
    slots = float(np.mean([len(t.postorder()) for t in trees]))
    flat = flatten_trees(trees, N)
    ints, vals = pack_flat_fused(flat, opset)

    rows_out = []
    for R_s in (10_240, 65_536, 262_144, 1_048_576):
        X = rng.normal(size=(5, R_s)).astype(np.float32)
        y = np.cos(X[0]).astype(np.float32)
        Xr, yr, wr, C, Rr = _reshape_rows(X, y, None)

        def make_chain(K):
            # fori_loop, not an unrolled Python loop: K must grow into the
            # hundreds at small R (the ~100ms tunnel dispatch overhead would
            # otherwise swamp a ~1ms kernel sweep and the lstsq slope goes
            # negative — observed on the first committed run of this sweep)
            @jax.jit
            def fK(ints, vals):
                def body(k, acc):
                    v = vals + (k + 1).astype(jnp.float32) * 1e-7
                    out = _loss_pallas(
                        ints, v, Xr, yr, wr, opset, loss_elem,
                        N, P_TILE_LOSS, C_TILE, C, Rr,
                    )
                    return acc + jnp.where(jnp.isfinite(out), out, 0.0)

                return jax.lax.fori_loop(
                    0, K, body, jnp.zeros((P_sweep,), jnp.float32)
                )

            return fK

        # size the chain so K_max x kernel time >> dispatch noise: calibrate
        # from a K=1 vs K=33 probe, then target ~0.5s for the longest chain
        f1, f33 = make_chain(1), make_chain(33)
        _ = np.asarray(f1(ints, vals))  # sync regime + compile
        _ = np.asarray(f1(ints, vals)); _ = np.asarray(f33(ints, vals))
        t0 = time.time(); _ = np.asarray(f1(ints, vals)); t1 = time.time() - t0
        t0 = time.time(); _ = np.asarray(f33(ints, vals)); t33 = time.time() - t0
        per_sweep = max((t33 - t1) / 32.0, 1e-5)
        K_max = int(np.clip(0.5 / per_sweep, 8, 1024))
        pts = []
        for K in (1, K_max // 4, K_max // 2, K_max):
            fK = make_chain(K)
            _ = np.asarray(fK(ints, vals))
            reps = 3
            t0 = time.time()
            for _i in range(reps):
                _ = np.asarray(fK(ints, vals))
            pts.append((K, (time.time() - t0) / reps))
        ks = np.array([p[0] for p in pts], float)
        ts = np.array([p[1] for p in pts], float)
        A = np.vstack([ks, np.ones_like(ks)]).T
        slope, intercept = np.linalg.lstsq(A, ts, rcond=None)[0]
        evals_per_sec = P_sweep / slope
        useful_flops = evals_per_sec * slots * R_s
        row = {
            "metric": "kernel_rate_vs_rows",
            **_platform_stamp(),
            "n_rows": R_s,
            "n_trees": P_sweep,
            "row_tiles_per_tree": C // C_TILE,
            "kernel_exec_ms_per_sweep": round(slope * 1000, 2),
            "dispatch_overhead_ms": round(intercept * 1000, 1),
            "tree_evals_per_sec": round(evals_per_sec, 0),
            "row_evals_per_sec": round(evals_per_sec * R_s, 0),
            "ns_per_tree_slot": round(slope / P_sweep / slots * 1e9, 2),
            "vpu_utilization_true": round(useful_flops / V5E_VPU_FLOPS, 4),
            "timing": "loop_only (chain-K slope, sync regime)",
            "variance": "single run, ~±30% tunneled-TPU band (BASELINE.md)",
        }
        print(json.dumps(row), flush=True)
        rows_out.append(row)
    return rows_out


def engine_mode(niterations: int = 4, R_e: int = 10_240):
    """IN-ENGINE utilization (round 10): the chain-K synthetic above measures
    what the kernel can do; this measures what the ENGINE actually sustains —
    a real device search (fused megaprogram + in-engine Pallas scoring under
    the default gates), with utilization derived from the engine's own eval
    accounting rather than a synthetic invocation chain.

    row_evals/s = num_evals x n_rows / loop_s (num_evals already counts
    fractional batched evals, and this config runs unbatched so every eval
    sweeps all rows); useful flops ~= row_evals/s x mean live nodes, the same
    1-flop-per-(tree,slot,row) convention as the roofline. The 2.2% chain-K
    utilization number (ROOFLINE_r05) finally gets an engine-side data point.

    On CPU hosts the line is still emitted (structure/CI) but utilization is
    reported against the v5e VPU peak and is only meaningful on TPU."""
    import jax

    from symbolicregression_jl_tpu import Options, equation_search

    rng = np.random.default_rng(0)
    X = rng.normal(size=(5, R_e)).astype(np.float32)
    y = np.cos(X[0]).astype(np.float32)
    platform = jax.devices()[0].platform
    scale = 1 if platform == "tpu" else 4  # CPU: same structure, less work
    opts = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp", "abs"],
        maxsize=N,
        populations=max(2, 8 // scale),
        population_size=max(8, 40 // scale),
        ncycles_per_iteration=max(8, 80 // scale),
        save_to_file=False,
        seed=0,
        scheduler="device",
    )
    res = equation_search(
        X, y, options=opts, niterations=niterations, verbosity=0
    )
    mean_nodes = float(
        np.mean(
            [
                m.tree.count_nodes()
                for p in res.populations
                for m in p.members
            ]
        )
    )
    row_evals_per_sec = res.num_evals * R_e / max(res.iteration_seconds, 1e-9)
    useful_flops = row_evals_per_sec * mean_nodes
    print(
        json.dumps(
            {
                "metric": "engine_utilization",
                **_platform_stamp(),
                "n_rows": R_e,
                "niterations": niterations,
                "populations": opts.populations,
                "population_size": opts.population_size,
                "ncycles_per_iteration": opts.ncycles_per_iteration,
                "SR_FUSED_ITER": os.environ.get("SR_FUSED_ITER", "1"),
                "SR_ENGINE_PALLAS": os.environ.get("SR_ENGINE_PALLAS", "1"),
                "SR_ENGINE_BLOCK": os.environ.get("SR_ENGINE_BLOCK", "auto"),
                "num_evals": float(res.num_evals),
                "loop_s": round(res.iteration_seconds, 3),
                "tree_evals_per_sec": round(
                    res.num_evals / max(res.iteration_seconds, 1e-9), 1
                ),
                "row_evals_per_sec": round(row_evals_per_sec, 0),
                "mean_live_nodes": round(mean_nodes, 2),
                "vpu_utilization_in_engine": round(
                    useful_flops / V5E_VPU_FLOPS, 4
                ),
                "timing": "whole engine loop (dispatch + host legs included)",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    if "--rows-sweep" in sys.argv:
        rows_sweep()
    elif "--engine" in sys.argv:
        engine_mode()
    else:
        main()
