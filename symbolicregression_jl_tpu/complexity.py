"""Expression complexity.

Reference: /root/reference/src/Complexity.jl:17-50 — default complexity is the
node count; custom per-operator/variable/constant complexities supported via
``Options(complexity_of_*)``.
"""

from __future__ import annotations

from .tree import Node

__all__ = ["compute_complexity", "past_complexity_limit"]


def _iter_nodes(tree: Node, unique: bool):
    return tree.iter_unique() if unique else iter(tree)


def compute_complexity(tree: Node, options) -> int:
    # GraphNode mode: shared subtrees count ONCE (reference:
    # shared-node-aware tree_mapreduce, Complexity.jl:17-50)
    unique = bool(getattr(options, "graph_nodes", False))
    mapping = options.complexity_mapping
    if mapping is None:
        return tree.count_unique_nodes() if unique else tree.count_nodes()
    total = 0.0
    for n in _iter_nodes(tree, unique):
        if n.degree == 0:
            if n.is_const:
                total += mapping["constant"]
            else:
                var = mapping["variable"]
                total += float(var) if var.ndim == 0 else float(var[n.feat])
        elif n.degree == 1:
            total += mapping["unaop"][n.op]
        else:
            total += mapping["binop"][n.op]
    return int(round(total))


def past_complexity_limit(tree: Node, options, limit: int) -> bool:
    return compute_complexity(tree, options) > limit
