"""Driver for the device-resident evolution engine (Options.scheduler="device").

Host responsibilities shrink to: build config, upload the dataset and initial
populations ONCE, dispatch one compiled program per iteration
(ops/evolve.run_iteration + the batched constant optimizer), read back ONE
packed array per iteration for the hall of fame / stop conditions, and decode
final populations at the end. Everything else — tournament, mutation,
crossover, accept, replacement, frequencies, migration — happens on device
(see ops/evolve.py for reference-semantics citations).

Transfer discipline (measured; bench.py module docstring): after the first
device-to-host copy this backend permanently charges ~12ms per dispatch and
~100ms fixed per host-to-device transfer. Hence: no per-iteration H2D at all
(even the warmup-maxsize scalar lives in device state), and all per-iteration
readbacks are packed into a single f32 array.

Compile discipline (round 4): the dataset travels through every engine
program as the TRACED ``ScoreData`` argument (arrays + the score
normalization scalar), and the engine EvoConfig canonicalizes the baseline
constants — compiled executables are therefore dataset-INDEPENDENT and
shared across outputs, warm starts, and repeat fits of the same shape
(measured: a second same-shape search runs its full loop with ZERO
compiles, 0.9s vs 80s on the CPU test box).
"""

from __future__ import annotations

import dataclasses
import os
import time
import warnings

import numpy as np

from ..analysis.ir_verify import debug_checks_enabled
from ..dataset import Dataset
from ..options import Options
from ..ops.evolve import EvoConfig, EvoState, _score_of, init_state, run_iteration
from ..ops.flat import (
    KIND_CONST, FlatTrees, batch_bucket, bucket_min, bucket_sizes,
    flatten_trees,
    length_buckets_enabled, unflatten_tree,
)
from ..ops.treeops import Tree
from .hall_of_fame import HallOfFame
from .pop_member import PopMember
from .population import Population

__all__ = [
    "device_search_one_output", "device_mode_supported", "build_evo_config",
    "FleetLaneSpec", "fleet_eligibility", "fleet_search",
]


def device_mode_supported(options: Options) -> str | None:
    """None if the device engine can honor this configuration; else a reason
    string (callers fall back to the host lockstep engine or raise). The
    answer depends only on Options now — round 5 removed the last
    dataset-dependent exclusions (units run in-jit, rows sharding grows the
    engine mesh)."""
    if options.loss_function is not None:
        return (
            "custom full-objective loss_function (host-callable per-tree "
            "objectives cannot run inside a compiled program; JAX-traceable "
            "objectives over the prediction matrix run in-engine via "
            "Options.loss_function_jit)"
        )
    if options.loss_function_jit is not None and options.data_sharding == "rows":
        return (
            "loss_function_jit with data_sharding='rows' (cross-shard "
            "combination of an arbitrary objective is undefined; the "
            "engine's psum combine is specific to weighted-mean losses)"
        )
    # custom complexity mappings are honored in-jit (round 5): every engine
    # complexity consumer routes through ops/evolve._complexity_of (score
    # parsimony, curmaxsize validation, mutation conditioning, frequency
    # histogram, tournament parsimony, best-seen frontier slots, migration)
    if options.use_recorder and options.device_mutation_attempts > 1:
        # the event log records ONE (kind, candidate) per lane; multi-attempt
        # lanes would mis-attribute the surviving candidate's kind
        return "recorder with device_mutation_attempts > 1"
    # data_sharding="rows" is honored: on multi-device hosts the engine mesh
    # grows a 'rows' axis (psum-combined scoring + const-opt); on one device
    # all rows are local anyway. Units are honored too (round 5): the engine
    # runs the WildcardQuantity abstract eval in-jit (ops/evolve._dim_violates)
    # with the additive dimensional-regularization penalty. The recorder is
    # honored too (round 5): engine programs return per-event logs that the
    # host replays into mutation/death/tuning lineage with exact
    # parent/child trees (ops/evolve.record_events +
    # models/device_recorder.py; single-process, single-device — a recorder
    # run is a debugging session, not a scale run).
    if options.graph_nodes:
        return "GraphNode shared-subtree DAGs"
    # f32 AND f64 are engine dtypes (the reference defaults to Float64,
    # /root/reference/src/SymbolicRegression.jl:360-447): f64 runs the
    # scan-interpreter scorer under jax_enable_x64 with f64 state arrays.
    # Complex stays CPU-committed on the host engines (XLA:TPU has no
    # complex arithmetic; utils/precision.py).
    if np.dtype(options.dtype) not in (np.dtype(np.float32), np.dtype(np.float64)):
        return f"unsupported engine dtype {np.dtype(options.dtype).name}"
    return None


def build_evo_config(
    options: Options,
    n_features: int,
    baseline_loss: float,
    use_baseline: bool,
    niterations: int,
    n_islands: int | None = None,
    n_rows: int | None = None,
    dataset: Dataset | None = None,
) -> EvoConfig:
    """Translate Options into the device engine's static EvoConfig.
    ``n_islands`` overrides options.populations (per-shard configs in the
    multi-device/multi-host paths).

    SR_ABLATE (comma list; bench_ablation.py) disables individual round-4
    parity fixes to quantify their contribution: ``no_copt_bs``,
    ``bernoulli_migration``, ``subbatch=K`` (score/commit a cycle's events
    in K sub-batches against fresher snapshots), ``no_simplify`` (consumed
    by device_search_one_output, not here)."""
    ablate = set(os.environ.get("SR_ABLATE", "").split(",")) - {""}
    I = options.populations if n_islands is None else n_islands
    P = options.population_size
    mw = options.mutation_weights
    tn = min(options.tournament_selection_n, P)
    tw = np.asarray(options.tournament_weights)[:tn]
    ncycles = options.ncycles_per_iteration
    events_per_cycle = max(1, -(-P // tn))
    subbatch = next(
        (int(t.split("=", 1)[1]) for t in ablate if t.startswith("subbatch=")), 1
    )
    if subbatch > 1:
        # same events-per-iteration budget, committed in smaller batches
        # against fresher population snapshots. ncycles is derived from the
        # ORIGINAL total so ceil-division of events_per_cycle cannot inflate
        # the budget (a naive ncycles*K overcounted ~30% at E=9, K=4)
        total_events = events_per_cycle * ncycles
        events_per_cycle = max(1, -(-events_per_cycle // subbatch))
        ncycles = max(1, round(total_events / events_per_cycle))
    return EvoConfig(
        n_islands=I,
        pop_size=P,
        n_slots=options.max_nodes,
        maxsize=options.maxsize,
        maxdepth=options.maxdepth,
        nfeatures=n_features,
        n_unary=options.operators.n_unary,
        n_binary=options.operators.n_binary,
        tournament_n=tn,
        tournament_weights=tuple(tw / tw.sum()),
        mutation_weights=(
            mw.mutate_constant,
            mw.mutate_operator,
            mw.swap_operands,
            mw.add_node,
            mw.insert_node,
            mw.delete_node,
            mw.randomize,
            mw.do_nothing,
        ),
        crossover_probability=options.crossover_probability,
        annealing=options.annealing,
        alpha=options.alpha,
        parsimony=options.parsimony,
        use_frequency=options.use_frequency,
        use_frequency_in_tournament=options.use_frequency_in_tournament,
        adaptive_parsimony_scaling=options.adaptive_parsimony_scaling,
        perturbation_factor=options.perturbation_factor,
        probability_negate_constant=options.probability_negate_constant,
        baseline_loss=baseline_loss,
        use_baseline=use_baseline,
        ncycles=ncycles,
        events_per_cycle=events_per_cycle,
        fraction_replaced=options.fraction_replaced,
        fraction_replaced_hof=options.fraction_replaced_hof,
        migration=options.migration,
        hof_migration=options.hof_migration,
        topn=min(options.topn, P),
        niterations=niterations,
        warmup_maxsize_by=options.warmup_maxsize_by,
        mutation_attempts=int(options.device_mutation_attempts),
        poisson_migration="bernoulli_migration" not in ablate,
        copt_updates_bs="no_copt_bs" not in ablate,
        bin_caps=tuple(tuple(c) for c in options.op_constraints[0]),
        una_caps=tuple(options.op_constraints[1]),
        nested_constraints=tuple(
            (od, oi, tuple(tuple(inner) for inner in inners))
            for od, oi, inners in (options.nested_constraints_resolved or ())
        ),
        batching=bool(options.batching),
        eval_fraction=(
            min(int(options.batch_size), n_rows) / n_rows
            if options.batching and n_rows
            else 1.0
        ),
        val_dtype=str(np.dtype(options.dtype)),
        complexity_table=_complexity_table(options, n_features),
        record_events=bool(options.use_recorder),
        **_units_config(options, dataset, n_features),
    )


def _complexity_table(options: Options, n_features: int):
    """Static per-node cost tables for the engine's mapped complexity
    (reference: ComplexityMapping, /root/reference/src/OptionsStruct.jl:21-113);
    None -> node count."""
    cm = options.complexity_mapping
    if cm is None:
        return None
    var = np.asarray(cm["variable"], dtype=np.float64)
    var_costs = (
        (float(var),) * max(n_features, 1)
        if var.ndim == 0
        else tuple(float(v) for v in var)
    )
    return (
        tuple(float(c) for c in cm["binop"]),
        tuple(float(c) for c in cm["unaop"]),
        float(cm["constant"]),
        var_costs,
    )


_DIM_BASES = (
    "length", "mass", "time", "current", "temperature", "luminosity", "amount"
)
#: power-like unary ops: output dims = input dims * p (wildcard preserved)
_UNA_DIM_POWERS = {
    "sqrt": 0.5, "sqrt_abs": 0.5, "cbrt": 1.0 / 3.0, "abs": 1.0, "neg": 1.0,
    "square": 2.0, "cube": 3.0, "inv": -1.0,
}
#: binary dim-combination codes: 0 add/sub, 1 mult, 2 div, 3 generic/pow
_BIN_DIM_CODES = {"add": 0, "sub": 0, "mult": 1, "div": 2}


def _units_config(options: Options, dataset, n_features: int) -> dict:
    """EvoConfig units fields (static tables) from the dataset's parsed SI
    units + the operator names; empty when the dataset carries no units."""
    if dataset is None or not getattr(dataset, "has_units", False):
        return {}
    from ..units import DIMENSIONLESS, Quantity

    def dim_row(dims):
        return tuple(float(getattr(dims, b)) for b in _DIM_BASES)

    xq = getattr(dataset, "X_units_parsed", None)
    if xq is None:
        xq = [Quantity(1.0, DIMENSIONLESS)] * n_features
    yq = getattr(dataset, "y_units_parsed", None)
    return dict(
        units_check=True,
        x_dims=tuple(dim_row(q.dims) for q in xq),
        y_dims=dim_row(yq.dims) if yq is not None else None,
        una_dim_pow=tuple(
            _UNA_DIM_POWERS.get(op.name) for op in options.operators.unary
        ),
        bin_dim_code=tuple(
            _BIN_DIM_CODES.get(op.name, 3) for op in options.operators.binary
        ),
        dim_penalty=(
            1000.0
            if options.dimensional_constraint_penalty is None
            else float(options.dimensional_constraint_penalty)
        ),
        allow_wildcards=not options.dimensionless_constants_only,
    )


from typing import NamedTuple

from ..serve.program_cache import global_program_cache

# Unified program cache (round 12): score fns, ScoreData uploads, and AOT
# executables all live in ONE thread-safe LRU (serve/program_cache.py) —
# replacing the three r04-r10 module dicts whose caps were hardcoded 12/12/32,
# whose evict-then-setdefault block was copy-pasted three times, and whose
# _AOT_CACHE reads ran without the lock. Capacity: SR_PROGRAM_CACHE_SIZE
# program entries; ScoreData device arrays: SR_SCORE_DATA_CACHE_MB bytes.
# Concurrent searches (multi-output fits, serve/ workers) share it; builds
# happen outside the lock and racing builders converge via put's setdefault
# semantics.
PROGRAM_CACHE = global_program_cache()


def _score_data_nbytes(data) -> int:
    """Device bytes held by a ScoreData pytree — the byte-budget charge for
    its cache entry (entry-count budgeting let twelve toy datasets evict one
    tenant's 100 MB upload)."""
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree_util.tree_leaves(data)
    )


def _engine_pallas_enabled() -> bool:
    """SR_ENGINE_PALLAS gate (default ON): bucket-sized Pallas dispatch for
    the in-engine score fn. ``=0`` recovers the exact r07 full-N kernel call.
    Read at BUILD time only and baked into the score-fn cache key (SRL004:
    never read env inside traced code)."""
    return os.environ.get("SR_ENGINE_PALLAS", "1") != "0"


def _pallas_interpret() -> bool:
    # lazy import: keep module import light (matches local-import idiom)
    from ..ops.interp_pallas import pallas_interpret_enabled

    return pallas_interpret_enabled()


def _dataset_key(X, y, weights):
    """Content key for the memoization caches (computed ONCE per search —
    tobytes() copies the arrays, so don't rebuild it per consumer). Shape
    and dtype are part of the key: byte-identical buffers with different
    layouts (e.g. (2,50) vs (50,2)) must not share a compiled score fn."""
    return (
        hash(X.tobytes()),
        X.shape,
        str(X.dtype),
        hash(y.tobytes()),
        y.shape,
        str(y.dtype),
        None
        if weights is None
        else (hash(weights.tobytes()), weights.shape, str(weights.dtype)),
    )


def _make_score_fn(
    X, y, weights, options: Options, use_pallas: bool, ds_key=None,
    norm: float = 1.0, need_raw: bool = True,
    rows_axis: str | None = None, rows_shards: int = 1, mesh=None,
    need_packed: bool = False,
):
    """Build the in-graph scoring closure + its dataset pytree.

    Returns ``(score_fn, data)``: score_fn maps (Tree batch [B, N], data) ->
    losses [B] (plus an optional PRNG key for the minibatch form) and closes
    over NO dataset values — the dataset travels as the traced ``data``
    argument (ScoreData), so ONE compiled engine executable serves every
    dataset of the same shape (multi-output fits, warm starts). score_fn and
    its jitted wrapper (``score_fn.jitted``) are memoized on the static
    shape/config key; ``data`` is memoized on the dataset bytes (device
    uploads cost ~100ms each on this backend).

    ``rows_axis``/``rows_shards``/``mesh``: rows-sharded mode — score_fn
    must run inside shard_map over ``mesh`` (it psums over ``rows_axis``),
    ``data`` is built with each shard's row block packed independently and
    placed with a rows NamedSharding, and no ``.jitted`` wrapper is attached
    (callers wrap in shard_map themselves)."""
    has_w = weights is not None
    fn_key = (
        options.operators,
        options.loss,
        options.loss_function_jit,
        options.max_nodes,
        use_pallas,
        options.batching and options.batch_size,
        X.shape,
        has_w,
        rows_axis,
        rows_shards,
        # the bucketed-dispatch gate and ladder are baked into the built
        # closure; a flipped SR_LENGTH_BUCKETS / SR_BUCKET_MIN between
        # searches must not reuse it
        length_buckets_enabled(),
        bucket_min(),
        _engine_pallas_enabled(),
        use_pallas and _pallas_interpret(),
    )
    fn = PROGRAM_CACHE.get("score_fn", fn_key)
    if fn is None:
        # build OUTSIDE the cache lock (tracing + jit wrapper are slow);
        # put() resolves build races to one canonical closure
        n_local = X.shape[1] // rows_shards if rows_shards > 1 else X.shape[1]
        fn = _build_score_fn(
            options, use_pallas, X.shape[0], n_local, has_w,
            rows_axis=rows_axis, rows_shards=rows_shards,
        )
        if rows_axis is None:
            import jax

            fn.jitted = jax.jit(fn)
        fn = PROGRAM_CACHE.put("score_fn", fn_key, fn)

    d_key = (
        ds_key if ds_key is not None else _dataset_key(X, y, weights),
        use_pallas,
        need_raw,
        need_packed,
        float(norm),  # baseline depends on the LOSS, not just the data bytes
        rows_shards,
    )
    data = PROGRAM_CACHE.get("score_data", d_key)
    if data is None:
        if rows_shards > 1:
            data = _make_score_data_rows(
                X, y, weights, mesh, use_pallas, norm=norm, need_raw=need_raw
            )
        else:
            data = _make_score_data(
                X, y, weights, use_pallas, norm=norm, need_raw=need_raw,
                need_packed=need_packed,
            )
        # charged by DEVICE BYTES, not entry count: retention stays
        # proportional to the memory actually held (SR_SCORE_DATA_CACHE_MB)
        data = PROGRAM_CACHE.put(
            "score_data", d_key, data, nbytes=_score_data_nbytes(data)
        )
    return fn, data


class ScoreData(NamedTuple):
    """The dataset as engine-program arguments. ``packed`` fields feed the
    Pallas kernels (sublane row layout); ``raw`` fields feed the scan
    interpreter and the minibatch gather. Unused slots are None (static
    pytree structure per compiled program)."""

    Xr: object = None  # f32[F*8, C] packed rows
    yr: object = None  # f32[8, C]
    wr: object = None  # f32[8, C]
    Xd: object = None  # f32[F, R]
    yd: object = None  # f32[R]
    wd: object = None  # f32[R] | None
    norm: object = None  # f32[] score normalization max(baseline, 0.01)


def _make_score_data(
    X, y, weights, use_pallas: bool, norm: float = 1.0, need_raw: bool = True,
    need_packed: bool = False,
) -> ScoreData:
    """need_raw: upload the unpacked Xd/yd/wd copies only when a consumer
    exists (minibatch gather, scan-interpreter scoring, or the non-Pallas
    const-opt fallback); on the pure-Pallas path they would double the
    HBM retention per cached dataset for nothing. need_packed: force the
    sublane row pack even when use_pallas is off — the evolve-block's XLA
    reference backend (SR_ENGINE_BLOCK=1 on CPU) scores against Xr/yr/wr."""
    import jax.numpy as jnp

    from ..ops.interp_pallas import _reshape_rows

    has_w = weights is not None
    kw = {}
    if use_pallas or need_packed:
        Xr, yr, wr, _, _ = _reshape_rows(X, y, weights)
        kw.update(Xr=Xr, yr=yr, wr=wr)
    if need_raw or not use_pallas:
        # preserve the caller's dtype (f64 engines upload f64 data; the
        # Pallas packed fields above are f32-only by construction)
        kw.update(
            Xd=jnp.asarray(X),
            yd=jnp.asarray(y),
            wd=jnp.asarray(weights) if has_w else None,
        )
    kw.update(norm=jnp.asarray(norm, np.dtype(X.dtype)))
    return ScoreData(**kw)


def _make_score_data_rows(
    X, y, weights, mesh, use_pallas: bool, norm: float = 1.0,
    need_raw: bool = True,
) -> ScoreData:
    """Rows-sharded ScoreData over ``mesh``'s 'rows' axis. Each shard's row
    block is packed INDEPENDENTLY (per-block kernel pad with w=0 masking, so
    every shard runs the identical static-C program), then the blocks
    concatenate along the packed column axis and land with a
    PartitionSpec(None, 'rows') placement — shard s gets exactly its own
    pack. Requires n_rows divisible by the rows-axis size (the caller
    chooses the axis under that constraint)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_sh = mesh.shape["rows"]
    F, R = X.shape
    assert R % n_sh == 0, (R, n_sh)
    R_local = R // n_sh
    has_w = weights is not None

    def put(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    kw = {}
    if use_pallas:
        from ..ops.interp_pallas import pack_rows_np

        packs = [
            pack_rows_np(
                X[:, s * R_local : (s + 1) * R_local],
                y[s * R_local : (s + 1) * R_local],
                None
                if weights is None
                else weights[s * R_local : (s + 1) * R_local],
            )
            for s in range(n_sh)
        ]
        kw.update(
            Xr=put(np.concatenate([p[0] for p in packs], axis=1), P(None, "rows")),
            yr=put(np.concatenate([p[1] for p in packs], axis=1), P(None, "rows")),
            wr=put(np.concatenate([p[2] for p in packs], axis=1), P(None, "rows")),
        )
    if need_raw or not use_pallas:
        kw.update(
            Xd=put(np.asarray(X), P(None, "rows")),
            yd=put(np.asarray(y), P("rows")),
            wd=put(np.asarray(weights), P("rows")) if has_w else None,
        )
    kw.update(norm=put(np.asarray(norm, np.dtype(X.dtype)), P()))
    return ScoreData(**kw)


def score_data_specs(data: ScoreData) -> ScoreData:
    """shard_map PartitionSpecs matching a rows-sharded ScoreData (None
    fields stay None — empty pytree leaves)."""
    from jax.sharding import PartitionSpec as P

    return ScoreData(
        Xr=None if data.Xr is None else P(None, "rows"),
        yr=None if data.yr is None else P(None, "rows"),
        wr=None if data.wr is None else P(None, "rows"),
        Xd=None if data.Xd is None else P(None, "rows"),
        yd=None if data.yd is None else P("rows"),
        wd=None if data.wd is None else P("rows"),
        norm=P(),
    )


def _build_score_fn(
    options: Options, use_pallas: bool, n_features: int, n_rows: int,
    has_w: bool, rows_axis: str | None = None, rows_shards: int = 1,
):
    """Score closure: (batch [B, N], data[, key]) -> losses [B]. When
    options.batching, the 3-arg form scores a fresh with-replacement row
    subset of batch_size (reference: batch_sample + eval_loss_batched,
    /root/reference/src/LossFunctions.jl:114-127); the 2-arg form always
    scores full data (finalize path).

    ``rows_axis``: dataset-row sharding over a mesh axis of that name
    (SURVEY §5.7 / the reference's row-parallel loss,
    /root/reference/src/LossFunctions.jl:114-127 scaled out). ``n_rows`` is
    then the PER-SHARD row count and the closure must run inside shard_map:
    each shard scores its local row block and the weighted means combine
    with a single scalar-pair psum over ICI — predictions never move. The
    minibatch form draws batch_size/rows_shards local rows per shard
    (decorrelated via an axis-index key fold) so the effective fresh-subset
    size stays batch_size."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    opset, loss_elem = options.operators, options.loss
    N = options.max_nodes
    bs = None
    if options.batching:
        bs_total = min(int(options.batch_size), n_rows * rows_shards)
        bs = max(1, bs_total // rows_shards)

    def _combine(local, wsum):
        """Merge per-shard weighted-mean losses into the global weighted
        mean: psum(mean*wsum)/psum(wsum). Exact for weighted and unequal
        shards; inf/nan propagate (an invalid tree on ANY shard is invalid
        globally, matching the single-device all-rows semantics)."""
        if rows_axis is None:
            return local
        num = lax.psum(local * wsum, rows_axis)
        den = lax.psum(wsum, rows_axis)
        return num / jnp.maximum(den, 1e-30)

    def _fold_rows(key):
        # decorrelate per-shard minibatch draws; deterministic per shard
        if rows_axis is None:
            return key
        return jax.random.fold_in(key, lax.axis_index(rows_axis))

    def _batch_wsum(data, idx):
        if has_w:
            return jnp.sum(data.wd[idx])
        return jnp.asarray(float(bs), jnp.float32)

    if use_pallas:
        from ..ops.interp_pallas import (
            C_TILE,
            P_TILE_LOSS,
            _loss_pallas,
            _loss_pallas_dyn,
            _round_up,
            pack_batch_jnp,
            pallas_interpret_enabled,
        )

        C = _round_up(n_rows, 8 * C_TILE) // 8
        interpret = pallas_interpret_enabled()
        bsizes = bucket_sizes(N)
        # SR_ENGINE_PALLAS (default on): bucket-sized kernel dispatch via the
        # r07 length ladder — the kernel's per-slot program loop dominates,
        # so a generation whose longest tree fits a small bucket skips the
        # dead slot tail instead of burning VPU cycles on zeros. =0 recovers
        # the exact r07 full-N launch (baked into the score-fn cache key).
        pl_bucketed = (
            _engine_pallas_enabled()
            and length_buckets_enabled()
            and len(bsizes) > 1
        )

        def _pack_pad(batch, n_b):
            # pack at bucket width n_b; truncation is bit-exact (flat-IR
            # invariant: pad slots hold exact zeros and are never read)
            B = batch.kind.shape[0]
            B_pad = _round_up(B, P_TILE_LOSS)
            Lv_b = _round_up(n_b, 128)
            ints = pack_batch_jnp(
                batch.kind[:, :n_b], batch.op[:, :n_b], batch.lhs[:, :n_b],
                batch.rhs[:, :n_b], batch.feat[:, :n_b], batch.length, opset,
            )
            vals = jnp.pad(
                batch.val[:, :n_b].astype(jnp.float32),
                ((0, 0), (0, Lv_b - n_b)),
            )
            if B_pad != B:  # pad with copies of row 0 (must be a VALID tree)
                ints = jnp.concatenate(
                    [ints, jnp.broadcast_to(ints[:1], (B_pad - B, ints.shape[1]))],
                    axis=0,
                )
                vals = jnp.concatenate(
                    [vals, jnp.broadcast_to(vals[:1], (B_pad - B, Lv_b))], axis=0
                )
            return ints, vals

        def _loss_full(batch, data, n_b):
            ints, vals = _pack_pad(batch, n_b)
            return _loss_pallas(
                ints, vals, data.Xr, data.yr, data.wr, opset, loss_elem,
                n_b, P_TILE_LOSS, C_TILE, C, n_rows, interpret=interpret,
            )

        def score_fn(batch, data: ScoreData, key=None):
            B = batch.kind.shape[0]
            if key is None:
                if pl_bucketed:
                    # score_fn is never called under vmap (see _eval_bucketed
                    # below), so the switch stays a real runtime branch
                    bidx = jnp.searchsorted(
                        jnp.asarray(bsizes, jnp.int32), jnp.max(batch.length)
                    )
                    out = lax.switch(
                        bidx,
                        [
                            (
                                lambda operands, n_b=n_b: _loss_full(
                                    operands[0], operands[1], n_b
                                )
                            )
                            for n_b in bsizes
                        ],
                        (batch, data),
                    )
                else:
                    out = _loss_full(batch, data, N)
                # wr is 0 on pad rows and the true weight (1 unweighted) on
                # real rows, so its sum IS this shard's weight total
                out = _combine(out, jnp.sum(data.wr))
            else:
                # minibatch form keeps the full-N dynamic-rows kernel: the
                # gather dominates here and per-bucket variants would
                # multiply compiled programs for no measured win
                ints, vals = _pack_pad(batch, N)
                idx = jax.random.choice(
                    _fold_rows(key), n_rows, (bs,), replace=True
                )
                out = _loss_pallas_dyn(
                    ints, vals, data.Xd[:, idx], data.yd[idx],
                    data.wd[idx] if has_w else jnp.zeros((), jnp.float32),
                    opset, loss_elem, N, has_w, bs,
                    interpret=interpret,
                )
                out = _combine(out, _batch_wsum(data, idx))
            return out[:B]

        return score_fn

    # scan-interpreter fallback (CPU tests, non-lowerable operator sets,
    # traceable full objectives)
    from ..ops.flat import slice_nodes
    from ..ops.interp import eval_trees
    from ..ops.losses import weighted_mean_loss

    objective = options.loss_function_jit
    bsizes = bucket_sizes(N)
    bucketed = length_buckets_enabled() and len(bsizes) > 1

    def _eval_bucketed(flat, Xs):
        # length-bucketed dispatch: run the scan at the smallest bucket
        # holding the batch's longest tree. score_fn is never called under
        # vmap (_event and finalize score plain batches; lax.map is a scan),
        # so the switch stays a real runtime branch — only the chosen
        # bucket's scan executes. Truncation is bit-exact: pad slots write
        # zeros and are never read by live slots.
        if not bucketed:
            return eval_trees(flat, Xs, opset)
        bidx = jnp.searchsorted(
            jnp.asarray(bsizes, jnp.int32), jnp.max(flat.length)
        )

        def mk(n_b):
            def branch(operands):
                f, X_ = operands
                return eval_trees(slice_nodes(f, n_b), X_, opset)

            return branch

        return lax.switch(bidx, [mk(n) for n in bsizes], (flat, Xs))

    def score_fn(batch, data: ScoreData, key=None):
        flat = FlatTrees(
            batch.kind, batch.op, batch.lhs, batch.rhs, batch.feat,
            batch.val.astype(data.Xd.dtype), batch.length,
        )
        if key is None:
            Xs, ys, ws = data.Xd, data.yd, data.wd
            wsum = (
                jnp.sum(data.wd)
                if has_w
                else jnp.asarray(float(n_rows), jnp.float32)
            )
        else:
            idx = jax.random.choice(_fold_rows(key), n_rows, (bs,), replace=True)
            Xs, ys = data.Xd[:, idx], data.yd[idx]
            ws = None if data.wd is None else data.wd[idx]
            wsum = _batch_wsum(data, idx)
        preds = _eval_bucketed(flat, Xs)
        if objective is not None:
            # traceable full objective (Options.loss_function_jit); rows
            # sharding is excluded by device_mode_supported so no _combine
            losses = jnp.asarray(objective(preds, ys, ws))
        else:
            elem = loss_elem(preds, ys[None, :])
            losses = weighted_mean_loss(
                elem, None if ws is None else ws[None, :]
            )
        ok = jnp.isfinite(preds).all(axis=-1)
        return _combine(jnp.where(ok, losses, jnp.inf), wsum)

    return score_fn


def _make_const_opt_fn(
    options: Options, cfg: EvoConfig, has_w: bool, axis=None, rows_axis=None,
    batch_rows: int | None = None, jit: bool = True,
):
    """Jitted per-iteration constant optimization over a fixed-size random
    member subset, fully device-side (selection, BFGS, accept, scatter-back).
    Reference semantics: optimize with prob optimizer_probability per member,
    accept if improved, reset birth
    (/root/reference/src/ConstantOptimization.jl:11-83).

    ``axis``: island-sharded shard_map mode — ``cfg`` is then the PER-SHARD
    config (local island count) and each shard optimizes its own K members;
    see _select_and_jitter for the key discipline. Returns the UNJITTED impl
    in that mode (the caller wraps it in shard_map + jit).

    ``rows_axis``: dataset rows sharded over that mesh axis — every loss and
    gradient the BFGS sees is psum-combined across rows shards (the linear
    ``combine`` hook of _bfgs_single), so the rows-replicated population
    state advances identically on every shard.

    ``batch_rows``: cfg.batching — optimize against one fresh per-call row
    subset with batch-vs-batch acceptance and fractional eval accounting
    (reference batch-sample optimization,
    /root/reference/src/ConstantOptimization.jl:13-21,44-78); the finalize
    program restores full-data losses right after."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops.constant_opt import (
        _bfgs_single,
        _neldermead_single,
        remat_tree_loss,
    )
    from ..ops.interp import _Structure

    # honor the configured algorithm (reference: opt_algorithm dispatch,
    # /root/reference/src/ConstantOptimization.jl:44-78) — Newton stays the
    # host path's 1-constant special case; the batched engine uses one
    # algorithm for the whole masked batch
    optimize_single = (
        _neldermead_single
        if options.optimizer_algorithm == "NelderMead"
        else _bfgs_single
    )

    I, P, N = cfg.n_islands, cfg.pop_size, cfg.n_slots
    # fixed-size subset (jit needs static shapes): expected count under the
    # reference's Bernoulli(p) selection
    K = max(1, int(round(options.optimizer_probability * I * P)))
    S = 1 + options.optimizer_nrestarts
    iters = int(options.optimizer_iterations)
    opset, loss_elem = options.operators, options.loss
    # chunk the BFGS batch: with jax.checkpoint (below) each instance holds
    # ~[N, R] registers fwd + recomputed bwd; budget ~500MB per chunk
    import os

    # Fallback path (kernel-incapable operator sets / CPU): empirically
    # tuned chunk 8 is fastest AND safe; larger chunks both slow down
    # (vmapped backtracking line search pays the worst lane's halvings) and
    # can fault the device at >=32. On TPU with lowerable operators the
    # Pallas loss+grad kernel path (_make_const_opt_fn_pallas) replaces
    # this entirely — no chunking, whole batch in one program.
    chunk = int(os.environ.get("SR_CONSTOPT_CHUNK", 8))
    chunk = min(chunk, K, I * P)
    n_chunks = min(-(-K // chunk), (I * P) // chunk)
    K = n_chunks * chunk
    # hot-path upgrades (each revertible via _copt_env for A/Bs and
    # identity tests): constant-aware selection, convergence gating at
    # Options.optimizer_g_tol, and length compaction — sort the K selected
    # members by length and run each chunk at the smallest node bucket
    # holding its longest tree (bucket_sizes policy, O(log N) programs)
    compat, no_compact = _copt_env()
    g_tol = 0.0 if compat else float(options.optimizer_g_tol)
    bsizes = bucket_sizes(N)
    compact = not compat and not no_compact and len(bsizes) > 1

    def const_opt(state: EvoState, data) -> EvoState:
        if batch_rows is None:
            Xd, yd = data.Xd, data.yd
            wd = data.wd if has_w else jnp.zeros((), jnp.float32)
        else:
            k_idx = jax.random.fold_in(state.key, 0xBA7C)
            if rows_axis is not None:
                k_idx = jax.random.fold_in(k_idx, lax.axis_index(rows_axis))
            idx = jax.random.choice(
                k_idx, data.Xd.shape[1], (batch_rows,), replace=True
            )
            Xd, yd = data.Xd[:, idx], data.yd[idx]
            wd = data.wd[idx] if has_w else jnp.zeros((), jnp.float32)
        # closures over traced args are trace-safe; building them here keeps
        # the executable dataset-independent
        loss_fn = remat_tree_loss(
            opset, loss_elem, Xd, yd, wd, has_w,
            objective=options.loss_function_jit,
        )
        combine = None
        if rows_axis is not None:
            wsum = (
                jnp.sum(wd)
                if has_w
                else jnp.asarray(float(Xd.shape[1]), jnp.float32)
            )

            def combine(x):  # noqa: E731 — global weighted mean of shard pieces
                return lax.psum(x * wsum, rows_axis) / jnp.maximum(
                    lax.psum(wsum, rows_axis), 1e-30
                )

        key, ii, pp, val0, mask, starts = _select_and_jitter(
            state, K, S, I, P, axis=axis, const_aware=not compat,
        )
        if compact:
            # length compaction: sorting groups similar lengths into the
            # same chunk so most chunks dispatch to a small bucket. Sorting
            # happens AFTER the jitter draw — every member keeps its own
            # starts, so results are permutation-invariant (accept/scatter
            # addresses by the co-sorted ii/pp).
            order = jnp.argsort(state.length[ii, pp])
            ii, pp = ii[order], pp[order]
            val0, mask, starts = val0[order], mask[order], starts[order]

        def field(a):
            return a[ii, pp]

        structure = _Structure(
            field(state.kind), field(state.op), field(state.lhs),
            field(state.rhs), field(state.feat), field(state.length),
        )

        def per_tree(struct_p, starts_p, mask_p):
            def per_restart(v0):
                return optimize_single(
                    loss_fn, v0, struct_p, Xd, yd, wd, has_w, mask_p, iters,
                    combine=combine, g_tol=g_tol,
                )

            vals, fs = jax.vmap(per_restart)(starts_p)
            fs = jnp.where(jnp.isfinite(fs), fs, jnp.inf)
            best = jnp.argmin(fs)
            return vals[best], fs[best]

        def per_chunk(args):
            struct_c, starts_c, mask_c = args
            if not compact:
                return jax.vmap(per_tree)(struct_c, starts_c, mask_c)
            # dispatch this chunk at the smallest bucket holding its longest
            # tree. lax.map runs chunks as a scan, so the switch is a real
            # runtime branch (switch-under-vmap would execute all branches)
            bidx = jnp.searchsorted(
                jnp.asarray(bsizes, jnp.int32), jnp.max(struct_c.length)
            )

            def mk(n_b):
                def branch(operands):
                    sc, stc, mc = operands
                    sb = _Structure(
                        sc.kind[:, :n_b], sc.op[:, :n_b], sc.lhs[:, :n_b],
                        sc.rhs[:, :n_b], sc.feat[:, :n_b], sc.length,
                    )
                    vals_b, fs_b = jax.vmap(per_tree)(
                        sb, stc[:, :, :n_b], mc[:, :n_b]
                    )
                    # pad back to [chunk, N] with each member's own val0
                    # tail (starts[:, 0] is the unjittered val0) so the
                    # accept/scatter contract sees full-width vectors
                    return (
                        jnp.concatenate([vals_b, stc[:, 0, n_b:]], axis=1),
                        fs_b,
                    )

                return branch

            return lax.switch(
                bidx, [mk(n) for n in bsizes], (struct_c, starts_c, mask_c)
            )

        chunked = jax.tree_util.tree_map(
            lambda a: a.reshape((n_chunks, chunk) + a.shape[1:]),
            (structure, starts, mask),
        )
        vals, fs = lax.map(per_chunk, chunked)
        vals = vals.reshape((K,) + vals.shape[2:])
        fs = fs.reshape((K,))
        if cfg.units_check:
            # const-opt never changes structure, so the dimensional penalty
            # is constant per tree: add it to every loss the accept rule
            # compares, keeping stored (penalized) losses consistent
            from ..ops.evolve import dim_penalty_batch
            from ..ops.treeops import Tree as _Tree

            pen_k = dim_penalty_batch(
                _Tree(
                    structure.kind, structure.op, structure.lhs,
                    structure.rhs, structure.feat, val0, structure.length,
                ),
                cfg,
            )
            fs = fs + pen_k
        n_ev = K * S * 2 * iters
        base = None
        if batch_rows is not None:
            # batch-vs-batch accept + fractional evals (reference
            # ConstantOptimization.jl:44-78,47); combine keeps the base
            # replicated across rows shards like every other loss
            # NB: _bfgs_single evaluates this same f(val0) internally as its
            # entry point but does not return it; the duplicate is one
            # K x batch_rows minibatch eval per call on this (non-Pallas
            # fallback) path — small next to the BFGS's 8x(1+ls) evals, and
            # not worth widening the shared _bfgs_single return contract
            f0 = jax.vmap(
                lambda v, s: loss_fn(v, s, Xd, yd, wd, has_w)
            )(val0, structure)
            base = f0 if combine is None else combine(f0)
            if cfg.units_check:
                base = base + pen_k
            n_ev = n_ev * cfg.eval_fraction
        return _accept_and_scatter(
            state, cfg, key, ii, pp, mask, val0, vals, fs, n_ev,
            axis=axis, norm=data.norm, base_loss=base,
        )

    # jit=False hands back the raw traceable impl so the fused iteration
    # program can inline it (SR_FUSED_ITER) instead of dispatching it
    return const_opt if (axis is not None or not jit) else jax.jit(const_opt)


def _copt_env() -> tuple[bool, bool]:
    """Trace-time env gates for the engine const-opt, read when a builder
    runs (NOT per call) and included in the AOT/jit cache keys so flipping
    them between searches can never reuse a stale executable:

    - ``SR_COPT_COMPAT=1``: restore the legacy const-opt wholesale —
      permutation selection, no length compaction, no convergence gating
      (the bench A/B's baseline side).
    - ``SR_NO_COPT_COMPACT=1``: disable ONLY the length compaction (same
      selection and gating; the compaction bit-identity test's off side).
    """
    compat = os.environ.get("SR_COPT_COMPAT") == "1"
    no_compact = os.environ.get("SR_NO_COPT_COMPACT") == "1"
    return compat, no_compact


def _select_and_jitter(
    state: EvoState, K: int, S: int, I: int, P: int, axis=None,
    const_aware: bool = False,
):
    """Shared const-opt front half: pick K distinct member slots and build
    the x(1 + 0.5*randn) restart starts [K, S, N] (reference's perturbed
    re-starts, /root/reference/src/ConstantOptimization.jl:53-68).

    ``const_aware``: bias selection to members with >=1 constant slot — the
    reference only ever optimizes trees with constants
    (/root/reference/src/ConstantOptimization.jl), while a uniform draw
    burns BFGS lanes on fully-masked no-ops. Members get priority
    uniform(0,1) + has_const and the top K are taken: const-bearing members
    always outrank const-free ones, uniformly at random within each group,
    and selection stays K distinct slots.

    ``axis``: shard_map mode — each shard folds its axis index into the
    (replicated) key so shards pick different members; the key returned here
    is shard-divergent and _accept_and_scatter re-replicates it."""
    import jax
    import jax.numpy as jnp

    base_key = state.key
    if axis is not None:
        from jax import lax

        base_key = jax.random.fold_in(base_key, lax.axis_index(axis))
    key, k_sel, k_jit = jax.random.split(base_key, 3)
    if const_aware:
        has_const = jnp.any(state.kind == KIND_CONST, axis=-1).reshape(-1)
        prio = jax.random.uniform(k_sel, (I * P,)) + has_const
        flat_idx = jnp.argsort(-prio)[:K]
    else:
        flat_idx = jax.random.permutation(k_sel, I * P)[:K]
    ii, pp = flat_idx // P, flat_idx % P
    kind = state.kind[ii, pp]
    val0 = state.val[ii, pp]  # engine dtype (f32 or f64)
    mask = kind == KIND_CONST
    N = val0.shape[1]
    jitter = 1.0 + 0.5 * jax.random.normal(k_jit, (K, S - 1, N), dtype=val0.dtype)
    starts = jnp.concatenate([val0[:, None, :], val0[:, None, :] * jitter], axis=1)
    return key, ii, pp, val0, mask, starts


def _accept_and_scatter(
    state: EvoState, cfg: EvoConfig, key, ii, pp, mask_k, val0, vals, fbest,
    n_evals, axis=None, norm=None, base_loss=None,
):
    """Shared const-opt back half: accept only improvements, scatter new
    constants/losses/scores back, reset birth (reference accept rule,
    /root/reference/src/ConstantOptimization.jl:70-78).

    ``axis``: shard_map mode — n_evals counts one shard's work so the
    replicated counter advances by the psum; the stored key is re-derived
    from the replicated entry key (the passed one is shard-divergent).

    ``base_loss``: batch mode (cfg.batching) — fbest is a minibatch loss, so
    it must compare against the member's loss ON THE SAME BATCH (the
    reference optimizes and accepts on one batch sample,
    /root/reference/src/ConstantOptimization.jl:44-78); the accepted batch
    loss lands in state and the finalize program immediately rescores on
    full data. Default None compares against the stored (full-data) loss."""
    import jax.numpy as jnp

    n_evals = jnp.asarray(n_evals, jnp.float32)
    if axis is not None:
        import jax
        from jax import lax

        n_evals = lax.psum(n_evals, axis)
        key = jax.random.fold_in(state.key, 0x0C07)

    old_loss = state.loss[ii, pp]
    base = old_loss if base_loss is None else base_loss
    has_consts = jnp.any(mask_k, axis=1)
    improved = (fbest < base) & has_consts
    new_val = jnp.where(improved[:, None], vals, val0)
    new_loss = jnp.where(improved, fbest, old_loss)
    from ..ops.evolve import _complexity_members

    # const-opt only retunes constants; mapped complexity is value-independent
    comp_m = _complexity_members(state, cfg)[ii, pp]
    new_score = _score_of(new_loss, comp_m.astype(jnp.float32), cfg, norm)
    if cfg.copt_updates_bs and not cfg.batching:
        # Fold the tuned members into the best-seen frontier. Without this,
        # optimized constants lived only in the population: the in-jit hof
        # migration spread UNtuned bs trees and the per-iteration readback
        # under-reported the front (the reference's optimize step feeds the
        # hall of fame via finalize_scores + update_hall_of_fame!,
        # /root/reference/src/SingleIteration.jl:107-174 + main loop :916-926).
        # Under cfg.batching the losses here are BATCH losses and must NOT
        # touch the frontier — a lucky draw could evict a genuinely better
        # tree that finalize cannot restore; the finalize program that runs
        # right after const-opt merges the tuned population on exact
        # full-data losses instead (reference: hall of fame is fed only
        # post-finalize).
        from ..ops.evolve import merge_best_seen

        lengths = state.length[ii, pp]
        fields = [
            state.kind[ii, pp], state.op[ii, pp], state.lhs[ii, pp],
            state.rhs[ii, pp], state.feat[ii, pp], new_val,
        ]
        valid = jnp.isfinite(new_loss) & (lengths >= 1)
        state = merge_best_seen(
            state, cfg, new_loss, valid, fields, lengths, axis=axis,
            comps=comp_m,
        )
    state = state._replace(
        val=state.val.at[ii, pp].set(new_val),
        loss=state.loss.at[ii, pp].set(new_loss),
        score=state.score.at[ii, pp].set(new_score),
        birth=state.birth.at[ii, pp].set(
            jnp.where(improved, state.step, state.birth[ii, pp])
        ),
        key=key,
        num_evals=state.num_evals + n_evals,
    )
    if not cfg.record_events:
        return state
    # recorder tuning log (reference: 'tuning' events on optimized members,
    # /root/reference/src/SingleIteration.jl:140-171); new_val lets the host
    # replay keep its tree mirror exact
    return state, {
        "ii": ii, "pp": pp, "improved": improved,
        "new_loss": new_loss, "new_val": new_val,
    }


def _make_const_opt_fn_pallas(
    options: Options, cfg: EvoConfig, n_rows: int, has_w: bool, axis=None,
    rows_axis=None, batch_rows: int | None = None, jit: bool = True,
):
    """Constant optimization through the fused Pallas loss+grad kernel
    (ops/interp_pallas._loss_grad_pallas): the whole (member, restart) batch
    runs one BFGS in lockstep, with gradients from the in-VMEM reverse
    adjoint sweep instead of jax.grad through the remat'd scan interpreter.
    Removes the chunk=8 cap that made const-opt ~17s of a 30s iteration.

    Semantics deviation (documented): the reference uses Newton+backtracking
    for single-constant trees (/root/reference/src/ConstantOptimization.jl:22-41);
    this path runs BFGS for every tree — on a 1-D problem BFGS's first
    curvature update is the same secant estimate Newton's backtracking
    protects, and the accept-only-if-improved rule bounds any difference.

    ``n_rows`` is the PER-SHARD row count when ``rows_axis`` is set: the
    kernels score this shard's block and every loss/grad the lockstep BFGS
    consumes is psum-combined across rows shards (the weighted-mean
    combination — losses and gradient components merge with the same linear
    map), keeping the rows-replicated state bitwise consistent.

    ``batch_rows``: cfg.batching — the whole BFGS runs against ONE fresh
    per-call row subset of this (per-shard) size, gathered and packed
    in-graph, exactly the reference's batch-sample optimization
    (/root/reference/src/ConstantOptimization.jl:13-21); acceptance compares
    batch-vs-batch (base_loss) and evals count fractionally. The finalize
    program that follows restores full-data losses."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops.flat import KIND_CONST
    from ..ops.interp_pallas import (
        C_TILE,
        P_TILE_LOSS,
        pack_batch_jnp,
        pallas_diff_loss,
        pallas_interpret_enabled,
        _round_up,
    )

    I, P, N = cfg.n_islands, cfg.pop_size, cfg.n_slots
    K = max(1, int(round(options.optimizer_probability * I * P)))
    S = 1 + options.optimizer_nrestarts
    B = _round_up(K * S, P_TILE_LOSS)
    iters = int(options.optimizer_iterations)
    # convergence gating + constant-aware selection (see _make_const_opt_fn;
    # SR_COPT_COMPAT=1 restores the legacy path). Length compaction does not
    # apply here: the kernel pads the node axis to 128 lanes regardless.
    compat, _ = _copt_env()
    g_tol = 0.0 if compat else float(options.optimizer_g_tol)
    opset, loss_elem = options.operators, options.loss
    Lv = _round_up(N, 128)
    R_eff = n_rows if batch_rows is None else batch_rows
    C = _round_up(R_eff, 8 * C_TILE) // 8
    F = cfg.nfeatures
    interpret = pallas_interpret_enabled()

    def const_opt(state: EvoState, data) -> EvoState:
        # kernel calls take the packed dataset from the traced `data` arg —
        # the compiled const-opt executable is dataset-independent
        if batch_rows is None:
            Xr, yr, wr = data.Xr, data.yr, data.wr
            shard_w = jnp.sum(data.wr)
        else:
            k_idx = jax.random.fold_in(state.key, 0xBA7C)
            if rows_axis is not None:
                k_idx = jax.random.fold_in(k_idx, lax.axis_index(rows_axis))
            idx = jax.random.choice(k_idx, n_rows, (batch_rows,), replace=True)
            R_pad = _round_up(batch_rows, 8 * C_TILE)
            Xr = jnp.pad(
                data.Xd[:, idx], ((0, 0), (0, R_pad - batch_rows)),
                constant_values=1.0,
            ).reshape(F * 8, C)
            yr = jnp.pad(data.yd[idx], (0, R_pad - batch_rows)).reshape(8, C)
            wv = (
                data.wd[idx]
                if has_w
                else jnp.ones((batch_rows,), jnp.float32)
            )
            wr = jnp.pad(wv, (0, R_pad - batch_rows)).reshape(8, C)
            shard_w = jnp.sum(wr)
        if rows_axis is not None:
            den = jnp.maximum(lax.psum(shard_w, rows_axis), 1e-30)

            def comb(x):
                return lax.psum(x * shard_w, rows_axis) / den

        else:

            def comb(x):
                return x

        def dloss(ints, vals):
            # custom_vjp-differentiable loss (ops/interp_pallas): the primal
            # is the forward loss kernel; the VJP is ONE fused loss+grad
            # launch whose forward residual already holds the per-slot
            # adjoints — nothing re-materializes the interpreter's SSA
            # buffer through HBM inside the BFGS while_loop
            return pallas_diff_loss(
                ints, vals, Xr, yr, wr, opset, loss_elem, N,
                C=C, R=R_eff, interpret=interpret,
            )

        key, ii, pp, val0, mask_k, starts = _select_and_jitter(
            state, K, S, I, P, axis=axis, const_aware=not compat,
        )
        starts = starts.reshape(K * S, N)

        def field(a):
            return a[ii, pp]

        ints_k = pack_batch_jnp(
            field(state.kind), field(state.op), field(state.lhs),
            field(state.rhs), field(state.feat), field(state.length), opset,
        )  # [K, L]

        # batch layout: instance b = tree (b // S), restart (b % S); pad to
        # the kernel's P tile with copies of instance 0
        ints_b = jnp.repeat(ints_k, S, axis=0)
        mask_b = jnp.repeat(mask_k, S, axis=0)
        pad = B - K * S
        if pad:
            ints_b = jnp.concatenate(
                [ints_b, jnp.broadcast_to(ints_b[:1], (pad, ints_b.shape[1]))]
            )
            mask_b = jnp.concatenate(
                [mask_b, jnp.broadcast_to(mask_b[:1], (pad, N))]
            )
            starts = jnp.concatenate(
                [starts, jnp.broadcast_to(starts[:1], (pad, N))]
            )

        def vloss(x):  # [B] losses (forward kernel only — line-search evals)
            vpad = jnp.pad(x, ((0, 0), (0, Lv - N)))
            return comb(dloss(ints_b, vpad))

        def vgrad(x):  # ([B], [B, N]) — in-kernel gradients via custom_vjp
            vpad = jnp.pad(x, ((0, 0), (0, Lv - N)))
            f, pull = jax.vjp(lambda v: dloss(ints_b, v), vpad)
            (g,) = pull(jnp.ones_like(f))
            return comb(f), jnp.where(mask_b, comb(g[:, :N]), 0.0)

        eye = jnp.broadcast_to(jnp.eye(N, dtype=jnp.float32), (B, N, N))
        f0, g0 = vgrad(starts)

        def body(carry, _):
            x, H, f, g = carry
            d = -jnp.einsum("bij,bj->bi", H, g)
            d = jnp.where(mask_b, d, 0.0)
            gtd = jnp.sum(g * d, axis=-1)
            bad = gtd >= 0
            d = jnp.where(bad[:, None], -g, d)
            gtd = jnp.where(bad, -jnp.sum(g * g, axis=-1), gtd)

            # batched Armijo backtracking (c1=1e-4, halving, <=12 steps);
            # satisfied lanes freeze their alpha while stragglers halve
            def ls_cond(s):
                alpha, f_new, k = s
                armijo = f_new <= f + 1e-4 * alpha * gtd
                return jnp.any(~armijo) & (k < 12)

            def ls_body(s):
                alpha, f_new, k = s
                armijo = f_new <= f + 1e-4 * alpha * gtd
                alpha2 = jnp.where(armijo, alpha, alpha * 0.5)
                f2 = vloss(x + alpha2[:, None] * d)
                f2 = jnp.where(armijo, f_new, f2)
                return alpha2, f2, k + 1

            f_try = vloss(x + d)
            alpha, f_new, _ = lax.while_loop(
                ls_cond, ls_body, (jnp.ones((B,), jnp.float32), f_try, 0)
            )

            ok = jnp.isfinite(f_new) & (f_new < f)
            x_new = jnp.where(ok[:, None], x + alpha[:, None] * d, x)
            f_next = jnp.where(ok, f_new, f)
            _, g_new = vgrad(x_new)

            s_ = x_new - x
            yk = g_new - g
            sy = jnp.sum(s_ * yk, axis=-1)
            good = sy > 1e-10
            rho = jnp.where(good, 1.0 / jnp.where(good, sy, 1.0), 0.0)
            outer_sy = jnp.einsum("bi,bj->bij", s_, yk)
            I_rsy = eye - rho[:, None, None] * outer_sy
            H_new = (
                jnp.einsum("bij,bjk,blk->bil", I_rsy, H, I_rsy)
                + rho[:, None, None] * jnp.einsum("bi,bj->bij", s_, s_)
            )
            H_next = jnp.where(good[:, None, None], H_new, H)
            return (x_new, H_next, f_next, g_new), None

        # convergence-gated lockstep: exit once EVERY instance's masked
        # gradient inf-norm is under g_tol (or iters is reached). The whole
        # batch advances together, so the gate is the batch max; g_tol=0
        # keeps the test false forever -> exact legacy iteration count. g in
        # the carry is already psum-combined (vgrad), so the condition runs
        # no collective.
        def w_cond(carry):
            x, H, f, g, k = carry
            return (k < iters) & ~(jnp.max(jnp.abs(g)) < g_tol)

        def w_body(carry):
            x, H, f, g, k = carry
            (x, H, f, g), _ = body((x, H, f, g), None)
            return (x, H, f, g, k + 1)

        (xs, _, fs, _, _) = lax.while_loop(
            w_cond, w_body, (starts, eye, f0, g0, jnp.asarray(0, jnp.int32))
        )

        # best restart per tree
        fs = jnp.where(jnp.isfinite(fs), fs, jnp.inf)[: K * S].reshape(K, S)
        xs = xs[: K * S].reshape(K, S, N)
        best = jnp.argmin(fs, axis=1)
        vals = jnp.take_along_axis(xs, best[:, None, None], axis=1)[:, 0]
        fbest = jnp.take_along_axis(fs, best[:, None], axis=1)[:, 0]
        if cfg.units_check:
            # structure is fixed under const-opt: one penalty per tree,
            # added to every compared loss (see the interp builder)
            from ..ops.evolve import dim_penalty_batch
            from ..ops.treeops import Tree as _Tree

            pen_k = dim_penalty_batch(
                _Tree(
                    field(state.kind), field(state.op), field(state.lhs),
                    field(state.rhs), field(state.feat), val0,
                    field(state.length),
                ),
                cfg,
            )
            fbest = fbest + pen_k
        n_ev = K * S * 2 * iters
        base = None
        if batch_rows is not None:
            # batch-vs-batch accept: restart 0 starts at val0, so its f0 IS
            # the member's loss on this batch; fractional eval accounting
            # (reference eval_fraction, ConstantOptimization.jl:47)
            base = f0[: K * S].reshape(K, S)[:, 0]
            if cfg.units_check:
                base = base + pen_k
            n_ev = n_ev * cfg.eval_fraction
        return _accept_and_scatter(
            state, cfg, key, ii, pp, mask_k, val0, vals, fbest,
            n_ev, axis=axis, norm=data.norm, base_loss=base,
        )

    # jit=False hands back the raw traceable impl so the fused iteration
    # program can inline it (SR_FUSED_ITER) instead of dispatching it
    return const_opt if (axis is not None or not jit) else jax.jit(const_opt)


# test seam: when set to a callable, the engine main loop reports each
# compiled-program dispatch by name ("fused_iter", "evolve", "const_opt",
# "finalize", "readback", "pool_extract") — the ≤2-dispatches/iteration
# invariant of the fused path is asserted through this hook
_DISPATCH_HOOK = None


def _count_dispatch(name: str):
    hook = _DISPATCH_HOOK
    if hook is not None:
        hook(name)


def _blk_row_limit() -> int:
    """Rows the evolve-block holds resident per score pass (one packed row
    tile's sublane count is applied by the caller: R <= 8 * this)."""
    from ..ops.interp_pallas import C_TILE

    return C_TILE


def _make_block_fn(opset, loss_elem, ecfg, n_rows: int, backend: str,
                   stages: int = 4):
    """Identity-stable ``(state, data) -> state`` closure over the r17
    kernel-resident evolution block (ops/evolve_block.run_block_iteration).
    Memoized in PROGRAM_CACHE: the closure travels as a jit STATIC argument
    of the fused megaprogram, so a fresh lambda per search would defeat both
    the jit cache and the AOT ``k_fused`` executable key. ``backend``:
    "kernel" scores through the Pallas evolve-block grid, "reference"
    through the vmapped XLA twin (same _block_cycle trajectory)."""
    key = (
        "block_fn", opset, loss_elem, ecfg, n_rows, backend, stages,
        _pallas_interpret(),
    )
    fn = PROGRAM_CACHE.get("block_fn", key)
    if fn is None:
        from ..ops.evolve_block import (
            make_reference_eval,
            run_block_iteration,
        )

        if backend == "kernel":
            from ..ops.interp_pallas import make_evolve_block_fn

            def fn(state, data):
                kfn = make_evolve_block_fn(
                    data.Xr, data.yr, data.wr, n_rows, opset, loss_elem,
                    ecfg, stages=stages,
                )
                return run_block_iteration(
                    state, data, ecfg, kernel_fn=kfn, stages=stages
                )
        else:

            def fn(state, data):
                eval_fn = make_reference_eval(
                    opset, loss_elem, data.Xr, data.yr, data.wr, n_rows
                )
                return run_block_iteration(
                    state, data, ecfg, eval_fn=eval_fn, stages=stages
                )
        fn = PROGRAM_CACHE.put("block_fn", key, fn)
    return fn


def _probe_fused_fractions(
    state, score_data, ecfg, score_fn, copt_impl, fin_score_fn, repeats=3,
    block_stage_fns=None,
):
    """Estimate the fused megaprogram's per-leg decomposition by timing each
    leg as its own (non-donated) program against the live pre-loop state.
    Returns {leg: fraction} summing to 1. Profiling-mode only: it compiles
    the split programs once, purely to keep ENGINE_PROFILE artifacts
    comparable — the reported ``fused_iter/<leg>`` sub-timings are this
    probe's fractions applied to each iteration's fused wall, not in-program
    measurements (XLA exposes none inside one executable).

    ``block_stage_fns``: SR_ENGINE_BLOCK probe — a 4-tuple of staged block
    closures (stages 1..4 cumulative: mutate, +check, +score, +accept). The
    evolve leg is replaced by the full block (``evolve_block``) and the
    stage walls decompose it into ``evolve_block/{mutate,check,score,
    accept}`` sub-fractions (each stage's marginal cost over the previous)."""
    import jax

    from ..ops.evolve import run_finalize, run_iteration

    if block_stage_fns is not None:
        blk_full = jax.jit(block_stage_fns[-1])
        legs = [("evolve_block", lambda st: blk_full(st, score_data))]
    else:
        legs = [
            ("evolve", lambda st: run_iteration(st, score_data, ecfg, score_fn))
        ]
    if copt_impl is not None:
        copt_jit = jax.jit(copt_impl)
        legs.append(("const_opt", lambda st: copt_jit(st, score_data)))
    if fin_score_fn is not None and ecfg.batching:
        legs.append(
            (
                "finalize",
                lambda st: run_finalize(st, score_data, ecfg, fin_score_fn),
            )
        )
    times = {}
    st = state
    for name, fn in legs:
        out = jax.block_until_ready(fn(st))  # compile + warm outside the clock
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = jax.block_until_ready(fn(st))
        times[name] = (time.perf_counter() - t0) / repeats
        st = out
    total = sum(times.values())
    if total <= 0.0:
        return None
    fracs = {k: v / total for k, v in times.items()}
    if block_stage_fns is not None:
        # inside-the-block decomposition: stage s runs stages 1..s of the
        # cycle body (earlier stages DCE-guarded), so each marginal wall is
        # that stage's cost. Reported as sub-fractions of the block leg.
        walls = []
        for sfn in block_stage_fns:
            f = jax.jit(sfn)
            jax.block_until_ready(f(state, score_data))
            t0 = time.perf_counter()
            for _ in range(repeats):
                jax.block_until_ready(f(state, score_data))
            walls.append((time.perf_counter() - t0) / repeats)
        # marginals normalized by their OWN sum (not the stage-4 wall): at
        # small scale timing noise can leave a later cumulative wall below
        # an earlier one, and dividing clamped marginals by walls[-1] would
        # let one sub-row exceed the whole block leg
        margs, prev = [], 0.0
        for wall in walls:
            margs.append(max(wall - prev, 0.0))
            prev = wall
        blk_frac, msum = fracs.get("evolve_block", 0.0), sum(margs)
        if msum > 0.0:
            for nm, m in zip(("mutate", "check", "score", "accept"), margs):
                fracs[f"evolve_block/{nm}"] = blk_frac * m / msum
    return fracs


def _shard_const_opt(mesh, impl, data_specs=None):
    """Wrap an axis-mode const-opt impl in shard_map over the 'pop' axis.
    ``data_specs``: rows-sharded ScoreData specs (score_data_specs) when the
    mesh carries a 'rows' axis; default replicated."""
    import jax

    from ..ops.evolve import evo_state_specs
    from ..parallel.mesh import shard_map_compat

    from jax.sharding import PartitionSpec as P

    specs = evo_state_specs()
    return jax.jit(
        shard_map_compat(
            impl, mesh=mesh,
            in_specs=(specs, data_specs if data_specs is not None else P()),
            out_specs=specs,
            check_vma=False,
        )
    )


def _make_readback_fn(cfg: EvoConfig):
    """Jitted packer: best-seen hall of fame + counters -> ONE array (f32,
    or f64 for f64 engines — losses/constants must not round-trip through
    f32)."""
    import jax
    import jax.numpy as jnp

    vdt = jnp.dtype(cfg.val_dtype)

    @jax.jit
    def pack(state: EvoState):
        S1 = cfg.maxsize + 1
        parts = [
            state.bs_loss,
            state.bs_exists.astype(vdt),
            state.bs_tree[6].astype(vdt),  # lengths
        ]
        for f in state.bs_tree[:6]:
            parts.append(f.astype(vdt).reshape(-1))
        parts.append(state.num_evals[None].astype(vdt))
        parts.append(state.step.astype(vdt)[None])
        return jnp.concatenate([p.reshape(-1) for p in parts])

    return pack


def _decode_readback(buf: np.ndarray, cfg: EvoConfig):
    S1 = cfg.maxsize + 1
    N = cfg.n_slots
    off = 0

    def take(n):
        nonlocal off
        out = buf[off : off + n]
        off += n
        return out

    bs_loss = take(S1)
    bs_exists = take(S1) > 0.5
    bs_len = take(S1).astype(np.int32)
    fields = [take(S1 * N).reshape(S1, N) for _ in range(6)]
    num_evals = float(take(1)[0])
    return bs_loss, bs_exists, bs_len, fields, num_evals


def _hof_pool_np(decoded_rows, cfg: EvoConfig):
    """Concatenate every process's decoded best-seen frontier into one
    migration pool (8-tuple, _topn_pool layout) as host numpy arrays."""
    vdt = np.dtype(cfg.val_dtype)
    kinds, ops, lhss, rhss, feats, vals, lens, losses = ([] for _ in range(8))
    for bs_loss, bs_exists, bs_len, fields, _ in decoded_rows:
        kind, op, lhs, rhs, feat, val = fields
        kinds.append(kind.astype(np.int32))
        ops.append(op.astype(np.int32))
        lhss.append(lhs.astype(np.int32))
        rhss.append(rhs.astype(np.int32))
        feats.append(feat.astype(np.int32))
        vals.append(val.astype(vdt))
        lens.append(np.where(bs_exists, bs_len, 0).astype(np.int32))
        losses.append(np.where(bs_exists, bs_loss, np.inf).astype(vdt))
    return (
        np.concatenate(kinds), np.concatenate(ops), np.concatenate(lhss),
        np.concatenate(rhss), np.concatenate(feats), np.concatenate(vals),
        np.concatenate(lens), np.concatenate(losses),
    )


def _bs_to_members(bs_loss, bs_exists, bs_len, fields, cfg: EvoConfig, options):
    """Decode best-seen rows into host PopMembers."""
    members = []
    kind, op, lhs, rhs, feat, val = fields
    flat = FlatTrees(
        kind.astype(np.int32), op.astype(np.int32), lhs.astype(np.int32),
        rhs.astype(np.int32), feat.astype(np.int32),
        val,  # engine dtype (f32 or f64) — no rounding on decode
        bs_len,
    )
    if debug_checks_enabled(options):
        # late import: the flag-off path makes zero verifier calls
        from ..analysis import ir_verify

        live = np.asarray(bs_exists) & (np.asarray(bs_len) >= 1)
        ir_verify.verify_flat_trees(
            # verify exactly the rows decoded below (others are never read)
            FlatTrees(*(np.asarray(a)[live] for a in flat)),
            options.operators,
            allow_empty=False,
            where="device_search._bs_to_members: ",
        )
    for s in range(len(bs_loss)):
        if not bs_exists[s] or bs_len[s] < 1:
            continue
        tree = unflatten_tree(flat, s)
        loss = float(bs_loss[s])
        if cfg.complexity_table is None:
            comp = int(bs_len[s])
        else:
            # mapped complexity: recompute host-side from the decoded tree
            # (the frontier SLOT s is already the mapped complexity, but the
            # exact value is what PopMember/hof consumers use)
            from ..complexity import compute_complexity

            comp = compute_complexity(tree, options)
        score = float(_score_of(loss, float(comp), cfg))
        m = PopMember(tree, score, loss, complexity=comp)
        members.append(m)
    return members


def _simplified_frontier_pool(members, options, cfg: EvoConfig, score_call, hof):
    """Iteration-boundary simplify (the reference runs simplify_tree! +
    combine_operators on EVERY member every iteration,
    /root/reference/src/SingleIteration.jl:107-132; the device engine has no
    in-jit tree rewriting, so the decoded best-seen frontier is simplified
    host-side and re-injected instead — compact building blocks flow back
    into evolution without a full-population readback).

    Returns (pool, n_scored): a fixed-shape [maxsize+1] migration pool of the
    strictly-simplified, rescored trees for migrate_from_pool (None when
    nothing simplified), and the eval count spent rescoring. Also folds the
    rescored members into ``hof``."""
    import jax.numpy as jnp

    from ..complexity import compute_complexity
    from .simplify import combine_operators, simplify_tree

    cand = []
    for m in members:
        t = combine_operators(simplify_tree(m.tree.copy(), options), options)
        c = compute_complexity(t, options)
        if c < m.complexity:
            cand.append((t, c, m.loss))
    if not cand:
        return None, 0
    S1 = cfg.maxsize + 1
    # the pool has S1 fixed rows; multi-host decodes can exceed that, so keep
    # the best-by-stored-loss candidates rather than arrival (process) order
    cand = sorted(cand, key=lambda tc: tc[2])[:S1]
    cand = [(t, c) for t, c, _ in cand]
    trees = [t for t, _ in cand]
    vdt = np.dtype(cfg.val_dtype)
    flat = flatten_trees(
        trees + [trees[0]] * (S1 - len(trees)), cfg.n_slots, dtype=vdt
    )
    batch = Tree(*(jnp.asarray(a) for a in flat))
    losses = np.asarray(score_call(batch)).astype(vdt).copy()
    if cfg.units_check:
        # simplify can only merge/fold nodes, but keep the penalty exact:
        # re-check each simplified tree with the SAME in-jit check the
        # engine uses (one penalty semantics per search)
        from ..ops.evolve import dim_penalty_batch_jit

        losses += np.asarray(dim_penalty_batch_jit(batch, cfg)).astype(vdt)
    losses[len(trees):] = np.inf  # pad rows are never drawn
    for (t, c), loss in zip(cand, losses):
        if np.isfinite(loss):
            hof.update(
                PopMember(
                    t,
                    float(_score_of(float(loss), float(c), cfg)),
                    float(loss),
                    complexity=int(c),
                ),
                options,
            )
    pool = (
        jnp.asarray(flat.kind), jnp.asarray(flat.op), jnp.asarray(flat.lhs),
        jnp.asarray(flat.rhs), jnp.asarray(flat.feat), jnp.asarray(flat.val),
        jnp.asarray(flat.length), jnp.asarray(losses),
    )
    return pool, len(trees)


def _decode_state_populations(state, I, P, cfg, options):
    """Decode the live EvoState into host Populations — ONE full D2H readback.

    Shared by the final population decode and the in-loop checkpoint writer
    (the state reference is always the latest output buffers, so this is
    valid even with donated/pipelined iteration executables). Returns
    ``(pops, slots, arrays)``: ``slots`` is ``(island, member, mapped
    complexity)`` per live member and ``arrays`` the decoded
    ``(kind, op, lhs, rhs, feat, val, length, loss, score)`` tuple so the
    final multi-host sync can reuse the buffers instead of re-reading."""
    kind = np.asarray(state.kind)
    opa = np.asarray(state.op)
    lhs = np.asarray(state.lhs)
    rhs = np.asarray(state.rhs)
    feat = np.asarray(state.feat)
    val = np.asarray(state.val)
    length = np.asarray(state.length)
    loss = np.asarray(state.loss).astype(np.float64)
    score = np.asarray(state.score).astype(np.float64)
    if debug_checks_enabled(options):
        # late import: the flag-off path makes zero verifier calls
        from ..analysis import ir_verify

        ir_verify.verify_flat_trees(
            FlatTrees(
                kind.reshape(I * P, -1), opa.reshape(I * P, -1),
                lhs.reshape(I * P, -1), rhs.reshape(I * P, -1),
                feat.reshape(I * P, -1), val.reshape(I * P, -1),
                length.reshape(I * P),
            ),
            options.operators,
            where="device_search._decode_state_populations: ",
        )
    pops = []
    slots = []
    for i in range(I):
        flat_i = FlatTrees(
            kind[i], opa[i], lhs[i], rhs[i], feat[i], val[i], length[i]
        )
        members = []
        for p in range(P):
            if length[i, p] < 1:
                continue
            tree = unflatten_tree(flat_i, p)
            m = PopMember(
                tree, float(score[i, p]), float(loss[i, p]),
                # node count sans mapping; None -> get_complexity computes
                # the mapped value lazily with Options.complexity_mapping
                complexity=(
                    int(length[i, p]) if cfg.complexity_table is None else None
                ),
            )
            members.append(m)
            slots.append((i, p, m.get_complexity(options)))
        pops.append(Population(members))
    return pops, slots, (kind, opa, lhs, rhs, feat, val, length, loss, score)


def device_search_one_output(
    dataset: Dataset,
    options: Options,
    niterations: int,
    rng: np.random.Generator,
    saved_state=None,
    verbosity: int = 1,
    output_file: str | None = None,
    stdin_reader=None,
    recorder=None,
    out_j: int = 1,
    checkpoint_base: str | None = None,
):
    """Run one output's search on the device engine. Returns SearchResult
    (same contract as models/../search._search_one_output)."""
    import jax
    import jax.numpy as jnp

    from ..search import SearchResult  # late import (module cycle)
    from ..utils import faults
    from ..utils.checkpoint import (
        SearchCheckpoint,
        SearchCheckpointer,
        options_fingerprint,
    )
    from ..utils.export_csv import save_hall_of_fame

    reason = device_mode_supported(options)
    if reason is not None:
        raise ValueError(
            f"scheduler='device' cannot honor this configuration ({reason}); "
            "use scheduler='lockstep'"
        )
    # counters snapshot BEFORE the compile/upload phase: engine_profile
    # reports THIS search's cache traffic (delta), not process-lifetime totals
    cache_stats0 = PROGRAM_CACHE.stats() if options.profile else None
    if options.use_recorder and jax.process_count() > 1:
        raise ValueError(
            "use_recorder is single-process: lineage replay cannot see other "
            "processes' events (run the recorder session un-distributed)"
        )
    own_recorder = recorder is None
    if own_recorder:
        from ..utils.recorder import Recorder

        recorder = Recorder(options)

    # --- multi-host (SPMD over DCN): every process runs this same function on
    # its own island slice; the only cross-host traffic is the once-per-
    # iteration migration-pool + readback allgather below (the reference
    # ships whole pickled Populations through the head process for the same
    # purpose, /root/reference/src/SymbolicRegression.jl:837-1064).
    from ..parallel import distributed as dist
    from ..parallel import membership

    # world identity: jax.distributed's process count/index, or the elastic
    # rig's SR_ELASTIC_WORLD/SR_ELASTIC_ID (a logical world over a shared
    # coordination directory, with NO jax.distributed runtime — the only way
    # a RESTARTED process can come back, since it cannot re-register with a
    # live coordination service)
    n_proc, proc_id = dist.world_shape()
    multi_host = n_proc > 1
    head = proc_id == 0

    I, P = options.populations, options.population_size
    if multi_host:
        # even split required: the per-iteration allgather needs identical
        # pool shapes on every process, and this check must raise on ALL
        # processes (an uneven raise would leave survivors deadlocked in
        # their first collective)
        if I % n_proc != 0:
            raise ValueError(
                f"multi-host search needs populations divisible by the "
                f"process count (populations={I}, processes={n_proc})"
            )
        isl_start, isl_stop = dist.process_island_slice(I)
        I = isl_stop - isl_start
        # decorrelate this process's initial populations and engine RNG
        rng = np.random.default_rng([int(rng.integers(0, 2**31 - 1)), proc_id])
    injector = (
        faults.install(options.fault_spec)
        if options.fault_spec
        else faults.active()
    )
    ckptr = None
    if checkpoint_base:
        # per-process snapshot files in multi-host mode: each process owns
        # only its island slice, so snapshots cannot merge into one file —
        # equation_search(resume_from=...) falls back to the .p{id} file
        ckptr = SearchCheckpointer.from_options(
            options,
            f"{checkpoint_base}.p{proc_id}" if multi_host else checkpoint_base,
        )
    N = options.max_nodes
    eng_dt = np.dtype(options.dtype)  # f32 or f64 (device_mode_supported)
    if eng_dt == np.float64:
        from ..utils.precision import ensure_x64_for_dtype

        ensure_x64_for_dtype(eng_dt)
    X = dataset.X.astype(eng_dt)
    y = dataset.y.astype(eng_dt)
    w = None if dataset.weights is None else dataset.weights.astype(eng_dt)

    # --- baseline loss ON DEVICE (no readback; becomes a program constant) --
    # Reference: update_baseline_loss!, /root/reference/src/LossFunctions.jl:201-215.
    # The value is folded into score arithmetic host-side only at decode time;
    # for cfg we need a concrete float, so compute it from numpy directly
    # (cheap, no device round-trip).
    avg = dataset.avg_y
    elem = np.asarray(options.loss(np.full_like(y, avg), y), np.float64)
    if w is not None:
        bl = float((elem * w).sum() / w.sum())
    else:
        bl = float(elem.mean())
    use_baseline = bool(np.isfinite(bl))
    dataset.baseline_loss = bl if use_baseline else 1.0
    dataset.use_baseline = use_baseline

    cfg = build_evo_config(
        options,
        n_features=dataset.n_features,
        baseline_loss=dataset.baseline_loss,
        use_baseline=use_baseline,
        niterations=niterations,
        n_islands=I,
        n_rows=dataset.n,
        dataset=dataset,
    )
    if cfg.warmup_maxsize_by == 0:
        # niterations only feeds the on-device warmup-maxsize schedule; with
        # the schedule off, canonicalize it so different-length searches hit
        # the same compiled-executable cache entry
        cfg = dataclasses.replace(cfg, niterations=0)
    if multi_host and (options.migration or options.hof_migration):
        # cross-host pools (injected once per iteration below) subsume the
        # in-program local migration: the pool is then GLOBAL across all
        # processes' islands, matching the reference's head-mediated
        # migration (/root/reference/src/Migration.jl:16-38)
        cfg = dataclasses.replace(cfg, migration=False, hof_migration=False)

    # --- multi-device: shard islands over 'pop' and (opt-in via
    # data_sharding="rows") dataset rows over 'rows' -------------------------
    # Each device owns I/pop_shards islands x R/rows_shards rows; per-cycle
    # cross-device traffic is the frequency-delta psum + best-seen merge
    # (pop axis) and the scalar-pair weighted-loss psum (rows axis) — see
    # ops/evolve.py and _build_score_fn. Within-device migration uses the
    # local topn pool; cross-device mixing rides the globally-merged
    # best-seen frontier (hof_migration).
    n_dev = jax.local_device_count()
    mesh = None
    rows_shards, pop_shards = 1, 1
    # ENGINE config: identical to cfg except the baseline constants are
    # canonicalized — the score normalization travels as the traced
    # ScoreData.norm, so every compiled engine/const-opt/migrate program is
    # dataset-independent and shared across outputs and warm starts of the
    # same shape. cfg (real baseline) stays for host-side score decoding.
    ecfg = dataclasses.replace(cfg, baseline_loss=1.0, use_baseline=True)
    cfg_local = ecfg
    # recorder mode stays single-device: the sharded iteration's out_specs
    # describe EvoState only, and a recorder session is a debugging run
    if n_dev > 1 and not options.use_recorder:
        if options.data_sharding == "rows":
            # rows-first split (SURVEY §5.7: big-n configs want the row axis):
            # the largest rows axis dividing the row count whose leftover pop
            # axis divides the island count
            for r in sorted(
                (d for d in range(1, n_dev + 1) if n_dev % d == 0),
                reverse=True,
            ):
                if dataset.n % r == 0 and I % (n_dev // r) == 0:
                    rows_shards, pop_shards = r, n_dev // r
                    break
        elif I % n_dev == 0:
            pop_shards = n_dev
    if pop_shards * rows_shards > 1:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(pop_shards, rows_shards, jax.local_devices())
        cfg_local = dataclasses.replace(ecfg, n_islands=I // pop_shards)
    rows_axis = "rows" if rows_shards > 1 else None
    if rows_axis and cfg.batching and cfg.eval_fraction < 1.0:
        # each rows shard draws batch_size/rows_shards local rows per cycle;
        # account the effective global fresh-subset size exactly
        eff = (
            max(1, min(int(options.batch_size), dataset.n) // rows_shards)
            * rows_shards
        )
        frac = min(eff, dataset.n) / dataset.n
        cfg = dataclasses.replace(cfg, eval_fraction=frac)
        ecfg = dataclasses.replace(ecfg, eval_fraction=frac)
        cfg_local = dataclasses.replace(cfg_local, eval_fraction=frac)

    # the Pallas kernels are f32-only; f64 engines score through the scan
    # interpreter (XLA emulates f64 on TPU — correctness over speed, like
    # the reference's Float64 default path)
    use_pallas = (
        # SR_PALLAS_INTERPRET=1 runs the kernels through the Pallas
        # interpreter on CPU — slow, but it exercises the exact kernel
        # dataflow off-TPU (parity tests, CI smoke)
        (jax.devices()[0].platform != "cpu" or _pallas_interpret())
        and eng_dt == np.float32
        # the fused kernel reduces elementwise loss in-pass; a traceable
        # full objective needs the [B, R] prediction matrix -> interp path
        and options.loss_function_jit is None
    )
    if use_pallas:
        from ..ops.interp_pallas import pallas_supported

        use_pallas = pallas_supported(
            options.operators, dataset.n_features, options.loss
        )
    use_pallas_grad = False
    # the fused Pallas loss+grad path implements BFGS only; NelderMead must
    # take the interpreter const-opt path below so the configured algorithm
    # is honored (not silently swapped for BFGS)
    if (
        use_pallas
        and options.should_optimize_constants
        and options.optimizer_algorithm == "BFGS"
    ):
        from ..ops.interp_pallas import pallas_grad_supported

        use_pallas_grad = pallas_grad_supported(
            options.operators, dataset.n_features, options.loss
        )
    ds_key = _dataset_key(X, y, w)
    norm_val = (
        dataset.baseline_loss
        if (use_baseline and dataset.baseline_loss >= 0.01)
        else 0.01
    )
    # raw Xd/yd/wd copies are consumed by the minibatch gather, the
    # interpreter scorer, and the non-Pallas const-opt fallback only
    need_raw = (
        options.batching
        or not use_pallas
        or (options.should_optimize_constants and not use_pallas_grad)
    )
    # --- kernel-resident evolution block (SR_ENGINE_BLOCK, r17) -------------
    # "0" = off; "1" = force (Pallas kernel where it compiles, XLA reference
    # backend otherwise — the CPU bench/CI path); default = auto, on exactly
    # where the kernel compiles. The block replaces the evolve leg INSIDE the
    # fused megaprogram, so every fused-iteration gate (mesh/recorder/replay)
    # applies too; block_eligible() rejects the config features the block
    # doesn't implement (batching, constraints, units, event recording, ...).
    blk_env = os.environ.get("SR_ENGINE_BLOCK", "")
    block_backend = None
    if (
        blk_env != "0"
        and os.environ.get("SR_FUSED_ITER", "1") != "0"
        and mesh is None
        and not options.use_recorder
        and not ecfg.record_events
        and options.loss_function_jit is None
        and eng_dt == np.float32
        # the whole row set must fit one resident tile: the block scores
        # every cycle against the same VMEM-held pack, no tile loop
        and dataset.n <= 8 * _blk_row_limit()
    ):
        from ..ops.evolve_block import block_eligible

        if block_eligible(ecfg)[0]:
            from ..ops.interp_pallas import evolve_block_supported

            if evolve_block_supported(
                options.operators, dataset.n_features, options.loss
            ):
                block_backend = "kernel"
            elif blk_env == "1":
                block_backend = "reference"
    score_fn, score_data = _make_score_fn(
        X, y, w, options, use_pallas, ds_key=ds_key, norm=norm_val,
        need_raw=need_raw, rows_axis=rows_axis, rows_shards=rows_shards,
        mesh=mesh, need_packed=block_backend is not None,
    )
    data_specs = score_data_specs(score_data) if rows_axis else None
    bs_local = None
    if cfg.batching:
        bs_local = max(1, min(int(options.batch_size), dataset.n) // rows_shards)
    const_opt_fn = None
    if options.should_optimize_constants:
        has_w = w is not None
        n_rows_local = dataset.n // rows_shards
        if use_pallas_grad:
            make_copt = lambda c, axis=None, jit=True: _make_const_opt_fn_pallas(  # noqa: E731
                options, c, n_rows_local, has_w, axis=axis,
                rows_axis=rows_axis, batch_rows=bs_local, jit=jit,
            )
        else:
            make_copt = lambda c, axis=None, jit=True: _make_const_opt_fn(  # noqa: E731
                options, c, has_w, axis=axis, rows_axis=rows_axis,
                batch_rows=bs_local, jit=jit,
            )
        if mesh is not None:
            const_opt_fn = _shard_const_opt(
                mesh, make_copt(cfg_local, axis="pop"), data_specs
            )
        else:
            const_opt_fn = make_copt(ecfg)
    finalize_fn = None
    if cfg.batching:
        # full-data finalize as its own program, ordered AFTER the batch
        # const-opt (reference sequence: optimize on batch -> finalize ->
        # migrate, /root/reference/src/SingleIteration.jl:107-132)
        if mesh is not None:
            from ..ops.evolve import make_sharded_finalize

            finalize_fn = make_sharded_finalize(
                mesh, cfg_local, score_fn, data_specs=data_specs
            )
        else:
            from ..ops.evolve import run_finalize

            finalize_fn = lambda st, d: run_finalize(st, d, ecfg, score_fn)  # noqa: E731
    readback_fn = _make_readback_fn(ecfg)

    # --- fused per-iteration megaprogram (SR_FUSED_ITER, default on) --------
    # evolve -> const-opt -> (batching) full-data finalize chained in ONE
    # compiled program: the per-iteration device dispatch chain collapses to
    # fused_iter + readback (<=2 dispatches/iteration). =0 recovers the exact
    # r07 split chain; unsupported modes fall back automatically (sharded
    # meshes build shard_map programs per stage, lineage replay consumes
    # per-program event logs).
    fused_iter = (
        os.environ.get("SR_FUSED_ITER", "1") != "0"
        and mesh is None
        and not options.use_recorder
        and not ecfg.record_events
    )
    copt_impl = None
    fin_sfn = None
    if fused_iter:
        if const_opt_fn is not None:
            # the raw traceable const-opt impl — inlined into the fused
            # trace instead of dispatched as its own program
            copt_impl = make_copt(ecfg, jit=False)
        if cfg.batching:
            fin_sfn = score_fn
    block_fn = None
    if fused_iter and block_backend is not None:
        block_fn = _make_block_fn(
            options.operators, options.loss, ecfg, int(dataset.n),
            block_backend,
        )

    # --- initial populations (host trees -> device state) -------------------
    if saved_state is not None:
        init_trees = [
            m.tree for pop in saved_state.populations for m in pop.members
        ][: I * P]
        if len(init_trees) < I * P:
            init_trees.extend(
                Population.random_trees(
                    I * P - len(init_trees), options, dataset.n_features, rng
                )
            )
    else:
        init_trees = Population.random_trees(I * P, options, dataset.n_features, rng)

    if rows_axis:
        # host-triggered scoring (init, warm-start rescore, simplify pool)
        # reuses the sharded dataset through a replicated-batch shard_map:
        # every shard scores the whole batch on its row block and the psum
        # inside score_fn yields replicated exact losses
        from jax.sharding import PartitionSpec as _PS

        from ..parallel.mesh import shard_map_compat

        _sc_sharded = jax.jit(
            shard_map_compat(
                lambda b, d: score_fn(b, d),
                mesh=mesh,
                in_specs=(_PS(), data_specs),
                out_specs=_PS(),
                check_vma=False,
            )
        )
        score_call = lambda batch: _sc_sharded(batch, score_data)  # noqa: E731
    else:
        score_call = lambda batch: score_fn.jitted(batch, score_data)  # noqa: E731

    seed = int(rng.integers(0, 2**31 - 1))

    def build_state(trees):
        """Host trees -> scored device EvoState. Runs at init and again when
        an elastic joiner adopts a checkpoint shard (the shard's trees
        replace the warm-up state's random ones)."""
        bflat = flatten_trees(trees, N, dtype=eng_dt)
        # score initial members on device (stay async: losses remain on device)
        batch0 = Tree(
            jnp.asarray(bflat.kind), jnp.asarray(bflat.op),
            jnp.asarray(bflat.lhs), jnp.asarray(bflat.rhs),
            jnp.asarray(bflat.feat), jnp.asarray(bflat.val),
            jnp.asarray(bflat.length),
        )
        b_losses = score_call(batch0)
        if cfg.units_check:
            # the SAME in-jit structure-only check the engine applies — host
            # legs must not mix a second (value-latching) penalty semantics
            # into one search (decoded ENGINE losses already carry the penalty)
            from ..ops.evolve import dim_penalty_batch_jit

            b_losses = b_losses + dim_penalty_batch_jit(batch0, ecfg)
        st = init_state(bflat, np.zeros(I * P), ecfg, seed)
        # overwrite host-zero losses with the device-computed ones (keeps the
        # whole init path free of device->host copies)
        from ..ops.evolve import _complexity_members

        comp = _complexity_members(st, ecfg).astype(jnp.float32)
        loss_dev = b_losses.reshape(I, P)
        return bflat, st._replace(
            loss=loss_dev, score=_score_of(loss_dev, comp, cfg)  # real-baseline
        )

    flat, state = build_state(init_trees)

    replay = None
    if options.use_recorder:
        from .device_recorder import EngineLineageReplay

        vdt_np = np.dtype(ecfg.val_dtype)
        state0 = tuple(
            np.asarray(a).reshape((I, P) + np.shape(a)[1:])
            for a in (
                flat.kind, flat.op, flat.lhs, flat.rhs, flat.feat,
                np.asarray(flat.val, vdt_np), flat.length,
            )
        )
        replay = EngineLineageReplay(
            state0, options, recorder, out_j=out_j, cfg=cfg,
            loss0=np.asarray(state.loss), score0=np.asarray(state.score),
        )

    # pipelined readback (round 6): resolved before AOT warmup so the
    # iteration executable can be compiled with donated state buffers.
    # Auto (None): on unless lineage replay (lockstep log consumption) or
    # profiling (stage fences serialize the pipeline anyway) needs the
    # synchronous path. Explicit True with either is rejected in
    # Options.__post_init__.
    async_rb = options.async_readback
    if async_rb is None:
        async_rb = replay is None and not options.profile
    if replay is not None or options.profile:
        async_rb = False
    if multi_host and membership.elastic_enabled(options):
        # elastic membership admits joiners at iteration boundaries; the
        # one-slot pipelined exchange would straddle an epoch bump (the
        # stashed payload was posted under the pre-join epoch's keys)
        async_rb = False

    if mesh is not None:
        from ..ops.evolve import make_sharded_iteration, shard_evo_state

        state = shard_evo_state(state, mesh)
        iter_fn = make_sharded_iteration(
            mesh, cfg_local, score_fn, data_specs=data_specs, donate=async_rb
        )
    else:
        iter_fn = None

    hof = HallOfFame(options.maxsize)
    if saved_state is not None:
        # re-ingest the saved hall of fame, RESCORING each member against
        # this dataset — the reference rescores on warm start precisely
        # because the dataset may have changed
        # (/root/reference/src/SymbolicRegression.jl:727-744). One extra
        # device call before the loop; the per-iteration readback below is
        # the first D2H either way.
        saved_members = [
            m.copy()
            for m in saved_state.hall_of_fame.members
            if m is not None
        ]
        if saved_members:
            # pad to a power-of-two bucket and reuse the init jit wrapper —
            # one extra compile at most, per the shared batch_bucket policy
            strees = [m.tree for m in saved_members]
            pad = batch_bucket(len(strees)) - len(strees)
            sflat = flatten_trees(strees + [strees[0]] * pad, N, dtype=eng_dt)
            sbatch = Tree(
                jnp.asarray(sflat.kind), jnp.asarray(sflat.op),
                jnp.asarray(sflat.lhs), jnp.asarray(sflat.rhs),
                jnp.asarray(sflat.feat), jnp.asarray(sflat.val),
                jnp.asarray(sflat.length),
            )
            slosses = np.asarray(score_call(sbatch))[: len(strees)]
            if cfg.units_check:
                from ..ops.evolve import dim_penalty_batch_jit

                slosses = slosses + np.asarray(
                    dim_penalty_batch_jit(sbatch, ecfg)
                )[: len(strees)]
            for m, loss in zip(saved_members, slosses):
                comp = m.get_complexity(options)
                m.loss = float(loss)
                m.score = float(_score_of(m.loss, float(comp), cfg))
                hof.update(m, options)
    early_stop = options.early_stop_fn()

    # default jit warmup: AOT-compile the iteration/const-opt/readback
    # programs (shapes are fixed for the whole search) so iteration 1 runs
    # at steady-state speed (reference precompiles its workload,
    # /root/reference/src/precompile.jl:36-93). lower().compile() builds
    # the executable without running an iteration.
    fused_step = None
    if options.jit_warmup and fused_iter:
        # AOT key for the fused megaprogram: the union of the k_iter and
        # k_copt fields below (the fused trace inlines both), plus the
        # batching/finalize leg and the kernel gates baked into the closures
        k_fused = (
            "fused", cfg_local, score_fn, async_rb, cfg.batching,
            use_pallas_grad, _pallas_interpret(),
            # kernel-resident evolve block: which backend (if any) replaced
            # the evolve leg is baked into the fused executable, and the
            # resident row count is baked into its score pass
            None if block_fn is None else ("blk", block_backend, dataset.n),
            None
            if copt_impl is None
            else (
                X.shape, w is not None, options.operators, options.loss,
                options.loss_function_jit,
                options.optimizer_probability, options.optimizer_nrestarts,
                options.optimizer_iterations, options.optimizer_algorithm,
                options.optimizer_g_tol, _copt_env(), bucket_min(),
            ),
        )
        fused_step = PROGRAM_CACHE.get("aot", k_fused)
        if fused_step is None:
            from ..ops.evolve import (
                run_iteration_fused,
                run_iteration_fused_donated,
            )

            base_fused = (
                run_iteration_fused_donated if async_rb else run_iteration_fused
            )
            fused_step = base_fused.lower(
                state, score_data, ecfg, score_fn, copt_impl, fin_sfn,
                block_fn=block_fn,
            ).compile()
            fused_step = PROGRAM_CACHE.put("aot", k_fused, fused_step)
        run_step = copt_step = fin_step = None
    elif options.jit_warmup:
        # AOT-compile (lower().compile()) bypasses the jit cache, so compiled
        # executables are memoized across equation_search calls — without
        # this every search pays the full ~40s engine compile even with
        # identical shapes/config. Keys hold the score_fn / opset / loss
        # OBJECTS (never id()): the cache entry pins them, so a recycled
        # address can never alias an executable with stale baked-in data.
        k_iter = (
            "iter", cfg_local, score_fn,
            (pop_shards, rows_shards) if mesh else 0,
            async_rb,  # donated executables are distinct programs
        )
        run_step = PROGRAM_CACHE.get("aot", k_iter)
        if run_step is None:
            from ..ops.evolve import run_iteration_donated

            base_iter = run_iteration_donated if async_rb else run_iteration
            run_step = (
                iter_fn.lower(state, score_data).compile()
                if iter_fn is not None
                else base_iter.lower(state, score_data, ecfg, score_fn).compile()
            )
            run_step = PROGRAM_CACHE.put("aot", k_iter, run_step)
        copt_step = None
        if const_opt_fn is not None:
            # dataset values travel as runtime args now — the executable is
            # shared across same-SHAPE datasets (multi-output, warm starts)
            k_copt = (
                "copt", cfg_local, X.shape, w is not None,
                options.operators, options.loss,
                # the traceable custom objective is baked into the compiled
                # const-opt program — omitting it here silently reused a
                # stale objective across searches (ADVICE r05)
                options.loss_function_jit,
                options.optimizer_probability,
                options.optimizer_nrestarts, options.optimizer_iterations,
                options.optimizer_algorithm,
                # gating tolerance, the compat/compaction env gates, and the
                # bucket ladder are baked into the compiled const-opt
                # program (while_loop bound, selection mechanism, switch)
                options.optimizer_g_tol, _copt_env(), bucket_min(),
                # which const-opt builder ran (pallas grad kernel vs scan
                # interpreter) and the interpret gate are baked into the
                # compiled program — and they change the ScoreData pytree
                # structure the executable accepts
                use_pallas_grad, _pallas_interpret(),
                (pop_shards, rows_shards) if mesh else 0,
            )
            copt_step = PROGRAM_CACHE.get("aot", k_copt)
            if copt_step is None:
                copt_step = const_opt_fn.lower(state, score_data).compile()
                copt_step = PROGRAM_CACHE.put("aot", k_copt, copt_step)
        fin_step = None
        if finalize_fn is not None:
            k_fin = (
                "fin", cfg_local, score_fn,
                (pop_shards, rows_shards) if mesh else 0,
            )
            fin_step = PROGRAM_CACHE.get("aot", k_fin)
            if fin_step is None:
                if mesh is not None:
                    fin_step = finalize_fn.lower(state, score_data).compile()
                else:
                    from ..ops.evolve import run_finalize

                    fin_step = run_finalize.lower(
                        state, score_data, ecfg, score_fn
                    ).compile()
                fin_step = PROGRAM_CACHE.put("aot", k_fin, fin_step)
    else:
        if iter_fn is not None:
            run_step = iter_fn
        elif fused_iter:
            from ..ops.evolve import (
                run_iteration_fused,
                run_iteration_fused_donated,
            )

            _fused_jit = (
                run_iteration_fused_donated if async_rb else run_iteration_fused
            )
            fused_step = lambda st, d: _fused_jit(  # noqa: E731
                st, d, ecfg, score_fn, copt_impl, fin_sfn, block_fn=block_fn
            )
            run_step = None
        else:
            from ..ops.evolve import run_iteration_donated

            _iter_jit = run_iteration_donated if async_rb else run_iteration
            run_step = lambda st, d: _iter_jit(st, d, ecfg, score_fn)  # noqa: E731
        copt_step = None if fused_step is not None else const_opt_fn
        fin_step = None if fused_step is not None else finalize_fn
        readback_step = readback_fn

    if options.jit_warmup:
        k_rb = ("rb", ecfg)
        readback_step = PROGRAM_CACHE.get("aot", k_rb)
        if readback_step is None:
            readback_step = readback_fn.lower(state).compile()
            readback_step = PROGRAM_CACHE.put("aot", k_rb, readback_step)
        if options.should_simplify:
            # prime the two lazy programs the iteration-boundary simplify
            # uses (fixed [maxsize+1] pool shapes): an all-invalid pool makes
            # the migrate a no-op and the scored dummy batch is discarded, so
            # only the jit cache is warmed
            from ..ops.evolve import migrate_from_pool as _mfp

            S1 = cfg.maxsize + 1
            zi = jnp.zeros((S1, N), jnp.int32)
            dummy_pool = (
                zi.at[:, 0].set(1), zi, zi, zi, zi,
                jnp.zeros((S1, N), jnp.dtype(ecfg.val_dtype)),
                jnp.ones((S1,), jnp.int32),
                jnp.full((S1,), jnp.inf, jnp.dtype(ecfg.val_dtype)),  # invalid -> no-op
            )
            _mfp(
                state, ecfg, dummy_pool, float(options.fraction_replaced_hof),
                score_data.norm,
            )
            score_call(
                Tree(*dummy_pool[:6], dummy_pool[6])
            ).block_until_ready()

    from ..utils.stdin_reader import StdinReader

    # an injected reader is SHARED by concurrent per-output searches ('q'
    # quits the whole fit — its sticky latch reaches every output) and is
    # closed by the owner, not here
    own_stdin = stdin_reader is None
    if own_stdin:
        stdin_reader = StdinReader()
    start_time = time.time()
    stop_reason = None
    # eval totals span the whole lineage (checkpoint / .meta.json sidecar)
    base_evals = (
        float(getattr(saved_state, "num_evals", 0.0) or 0.0)
        if saved_state is not None
        else 0.0
    )
    num_evals = base_evals
    host_evals = 0.0  # simplify-rescore evals (host-triggered, device-run)
    do_simplify = (
        options.should_simplify
        and "no_simplify" not in os.environ.get("SR_ABLATE", "").split(",")
    )

    from ..ops.evolve import extract_topn_pool, migrate_from_pool
    from ..utils.profiling import NULL_PROFILER, StageProfiler

    prof = StageProfiler() if options.profile else NULL_PROFILER
    fused_fracs = None
    if fused_step is not None and prof.enabled:
        # profiling a fused search: derive the fused wall's decomposition
        # once (probe fractions), reported as fused_iter/<leg> each iteration
        blk_stage_fns = None
        if block_fn is not None:
            blk_stage_fns = tuple(
                _make_block_fn(
                    options.operators, options.loss, ecfg, int(dataset.n),
                    block_backend, stages=s,
                )
                for s in (1, 2, 3, 4)
            )
        fused_fracs = _probe_fused_fractions(
            state, score_data, ecfg, score_fn, copt_impl, fin_sfn,
            block_stage_fns=blk_stage_fns,
        )
    device_evals = 0.0
    own_dev_evals = 0.0  # this process's cumulative device evals (group mode)
    it_start = 0

    # --- elastic membership (round 11): route the exchange through a
    # per-search ExchangeGroup whenever the KV transport carries it anyway
    # (multi-process CPU rig) or elasticity was requested. Created AFTER all
    # AOT warmup so a joiner never holds up survivors while it compiles.
    use_group = multi_host and membership.should_use_group(options)
    grp = None
    _cur_it = [0]  # shard_provider's view of the loop counter

    if use_group:

        def _shard_provider() -> bytes:
            # the leader publishes this process's state as a format-2
            # checkpoint shard when a joiner is admitted — the identical
            # (verified-on-load) encoding the on-disk snapshots use
            from ..utils.checkpoint import dump_checkpoint_bytes

            ck_pops, _, _ = _decode_state_populations(state, I, P, cfg, options)
            return dump_checkpoint_bytes(
                SearchCheckpoint(
                    iteration=int(_cur_it[0]),
                    niterations=niterations,
                    scheduler="device",
                    exact=False,
                    populations=ck_pops,
                    hall_of_fame=hof.copy(),
                    num_evals=float(num_evals),
                    options_fingerprint=options_fingerprint(options),
                    wall_time=time.time() - start_time,
                    out_j=out_j,
                )
            )

        grp = membership.ExchangeGroup(
            membership.coord_store(),
            membership.next_group_id(out_j),
            proc_id,
            n_proc,
            on_peer_loss=options.on_peer_loss,
            topology=options.exchange_topology,
            heartbeat_every=options.heartbeat_every_seconds,
            shard_provider=_shard_provider,
        )
        if membership.join_pending():
            # JOINER: announce only now — compile/warmup is done, so the
            # admission-to-first-collective gap is state rebuild only —
            # then adopt the leader's shard and re-enter at the recorded
            # iteration boundary (one-iteration-stale semantics, same as
            # the pipelined exchange)
            from ..utils.checkpoint import CheckpointError, load_checkpoint_bytes

            record, shard = grp.join()
            it_start = int(record.get("iteration", 0))
            _cur_it[0] = it_start
            if shard is not None:
                try:
                    ck = load_checkpoint_bytes(shard)
                    strees = [
                        m.tree for pop in ck.populations for m in pop.members
                    ][: I * P]
                    if len(strees) < I * P:
                        strees.extend(
                            Population.random_trees(
                                I * P - len(strees), options,
                                dataset.n_features, rng,
                            )
                        )
                    flat, state = build_state(strees)
                    for m in ck.hall_of_fame.members:
                        if m is not None:
                            hof.update(m.copy(), options)
                except CheckpointError as e:
                    warnings.warn(
                        f"rejoin shard rejected ({e}); warm-starting from "
                        "random populations instead"
                    )
            if verbosity > 0:
                print(
                    f"[device] rank {proc_id} rejoined at epoch {grp.epoch} "
                    f"(iteration {it_start}/{niterations}, live={grp.live})"
                )

    # hierarchical exchange, LOCAL stage: with a sharded mesh the per-island
    # topn shards merge on-device over ICI (donated buffers, replicated
    # output) BEFORE the host exchange, so the inter-host stage ships one
    # already-merged pool per process instead of per-device shards
    pool_merge = None
    if use_group and mesh is not None and options.migration:
        from ..parallel.mesh import intra_host_pool_merge

        pool_merge = intra_host_pool_merge(mesh)

    # pipelined-loop carry: iteration i-1's packed readback (single-host) /
    # the double-buffered exchange slot (multi-host; the group carries its
    # own one-slot buffer via roll/flush)
    pending_rb = None
    exchange = (
        dist.DoubleBufferedExchange(on_peer_loss=options.on_peer_loss)
        if (multi_host and async_rb and grp is None)
        else None
    )
    known_dead = set(dist.dead_peers())

    def _note_lost_peers():
        """Degraded-mode bookkeeping (on_peer_loss="continue"): name newly
        lost processes and re-derive this process's share of the global
        island space so logs agree on the shrunken world. The on-device
        islands themselves are untouched — survivors keep searching their
        slice with a one-iteration-stale migration pool."""
        lost = set(dist.dead_peers()) - known_dead
        if not lost:
            return
        known_dead.update(lost)
        live = dist.live_process_ids()
        try:
            s0, s1 = dist.process_island_slice(
                options.populations, live=live
            )
            span = f"; this process now covers island slice [{s0}, {s1})"
        except ValueError:
            span = ""
        warnings.warn(
            f"peer process(es) {sorted(lost)} lost mid-search; continuing "
            f"on {len(live)} survivor(s) with a one-iteration-stale "
            f"migration pool{span}"
        )

    def _consume_readback(gathered, buf, it_label):
        """Fold one iteration's packed readback — and, multi-host, the
        allgathered exchange payload — into the hall of fame, then inject
        the migration/simplify pools into the CURRENT device state. In the
        pipelined loop (async_rb) the payload is one iteration old, so the
        injected pools are one-iteration-stale — the reference's async
        snapshot-migration semantics
        (/root/reference/src/SymbolicRegression.jl:933-943)."""
        nonlocal state, host_evals, device_evals
        if multi_host:
            with prof.stage("decode_hof"):
                # one row per SURVIVING process (degraded mode shrinks the
                # gather), so iterate rows — never the launch-time n_proc
                g0 = np.asarray(gathered[0])
                decoded = [
                    _decode_readback(np.asarray(g0[pi]), cfg)
                    for pi in range(g0.shape[0])
                ]
                device_evals = sum(d[4] for d in decoded)
                decoded_members = []
                for d in decoded:
                    decoded_members.extend(
                        _bs_to_members(d[0], d[1], d[2], d[3], cfg, options)
                    )
                # under batching the decoded frontier already carries exact
                # full-data losses: the engine rescores bs in-graph at the
                # iteration boundary (_run_iteration_impl finalize)
                for m in decoded_members:
                    hof.update(m, options)
            # inject the now-global pools: all processes' topn members with
            # fraction_replaced, all processes' best-seen frontiers with
            # fraction_replaced_hof (reference migrate! semantics)
            with prof.stage("migrate"):
                if options.migration:
                    topn_pool = tuple(
                        jnp.asarray(g.reshape((-1,) + g.shape[2:]))
                        for g in gathered[1:]
                    )
                    state = migrate_from_pool(
                        state, ecfg, topn_pool,
                        float(options.fraction_replaced), score_data.norm,
                    )
                if options.hof_migration:
                    hof_pool = tuple(
                        jnp.asarray(a) for a in _hof_pool_np(decoded, cfg)
                    )
                    state = migrate_from_pool(
                        state, ecfg, hof_pool,
                        float(options.fraction_replaced_hof), score_data.norm,
                    )
                prof.fence(state)
        else:
            with prof.stage("decode_hof"):
                (
                    bs_loss, bs_exists, bs_len, fields, device_evals
                ) = _decode_readback(buf, cfg)
                decoded_members = _bs_to_members(
                    bs_loss, bs_exists, bs_len, fields, cfg, options
                )
                # frontier losses are already full-data-exact under batching
                # (in-graph finalize rescore) — no host-side re-evaluation
                for m in decoded_members:
                    hof.update(m, options)

        if do_simplify:
            # identical deterministic work on every process in multi-host
            # mode (same decoded input -> same pool -> same replicated-key
            # injection), so no extra exchange is needed
            with prof.stage("simplify"):
                pool, n_scored = _simplified_frontier_pool(
                    decoded_members, options, cfg, score_call, hof
                )
                host_evals += n_scored
                if pool is not None:
                    state = migrate_from_pool(
                        state, ecfg, pool,
                        float(options.fraction_replaced_hof), score_data.norm,
                    )
                    if replay is not None:
                        state, mig_log = state
                        replay.consume_migration(mig_log)
                prof.fence(state)

        if replay is not None:
            # authoritative per-iteration population snapshot (the recorder's
            # out{j}_pop{i} entries; host engines record per iteration too).
            # This extra full-state readback is recorder overhead only.
            replay.snapshot_populations(
                tuple(
                    np.asarray(a)
                    for a in (
                        state.kind, state.op, state.lhs, state.rhs,
                        state.feat, state.val, state.length, state.loss,
                        state.score,
                    )
                ),
                it_label,
            )

    for it in range(it_start, niterations):
        # simulated preemption (fault-injection harness); counts one call
        # per iteration on every process that carries the spec
        injector.maybe_die("peer_death")
        if injector.armed("nan_flood"):
            # poison a fraction of this process's islands' losses — the NaN
            # storm the tournament selection + pool-injection guards must
            # wash out (migrate_from_pool/hof ignore non-finite entries)
            hit = injector.fire("nan_flood")
            if hit is not None:
                frac = float(hit.get("frac", 0.75))
                k = max(1, int(round(I * frac)))
                bad = (jnp.arange(I) < k)[:, None]
                state = state._replace(
                    loss=jnp.where(bad, jnp.nan, state.loss)
                )
        if fused_step is not None:
            # SR_FUSED_ITER: evolve → const-opt → finalize as ONE dispatch
            t_f0 = time.perf_counter()
            with prof.stage("fused_iter"):
                _count_dispatch("fused_iter")
                state = fused_step(state, score_data)
                prof.fence(state)
            if fused_fracs:
                dt_f = time.perf_counter() - t_f0
                for leg, frac in fused_fracs.items():
                    prof.add_time(f"fused_iter/{leg}", dt_f * frac)
        else:
            with prof.stage("evolve"):
                _count_dispatch("evolve")
                state = run_step(state, score_data)
                if replay is not None:
                    state, iter_log = state
                    replay.consume_iteration(iter_log)
                prof.fence(state)
            if copt_step is not None:
                with prof.stage("const_opt"):
                    _count_dispatch("const_opt")
                    state = copt_step(state, score_data)
                    if replay is not None:
                        state, tuning_log = state
                        replay.consume_tuning(tuning_log)
                    prof.fence(state)
            if fin_step is not None:
                # batching: full-data finalize AFTER the batch const-opt, so
                # the readback below only ever sees exact losses
                with prof.stage("finalize"):
                    _count_dispatch("finalize")
                    state = fin_step(state, score_data)
                    if replay is not None:
                        state, fin_log = state
                        for mk in ("mig_island", "mig_hof"):
                            if mk in fin_log:
                                replay.consume_migration(fin_log[mk])
                    prof.fence(state)
        with prof.stage("readback_pack"):
            _count_dispatch("readback")
            rb = readback_step(state)  # the iteration's ONE readback
            prof.fence(rb)
        pool_dev = ()
        if multi_host and options.migration:
            # this process's topn migration pool rides the same exchange as
            # the readback buffer; skipped when migration is off (options
            # are identical on every process, so the exchange stays uniform)
            with prof.stage("pool_extract"):
                _count_dispatch("pool_extract")
                pool_dev = extract_topn_pool(state, ecfg)
                if pool_merge is not None:
                    pool_dev = pool_merge(*pool_dev)
                prof.fence(pool_dev)

        if async_rb:
            # software pipeline (round 6): start the copy stream for THIS
            # iteration's payload, then consume the PREVIOUS one while the
            # device queue (which already holds this iteration's programs)
            # keeps computing — the readback D2H and the multi-host gather
            # overlap device compute instead of serializing after it
            rb.copy_to_host_async()
            for a in pool_dev:
                a.copy_to_host_async()
            if multi_host:
                if grp is not None:
                    # srl: disable=SRL003 -- D2H after copy_to_host_async: the group transport posts host bytes, same design point as the pipelined branch below
                    payload = tuple(np.asarray(a) for a in (rb, *pool_dev))
                    own_dev_evals = float(_decode_readback(payload[0], cfg)[4])
                    gathered = grp.roll(payload)
                else:
                    gathered = exchange.roll((rb, *pool_dev))
                _note_lost_peers()
                if gathered is not None:
                    _consume_readback(gathered, None, it)
            else:
                prev_rb, pending_rb = pending_rb, rb
                if prev_rb is not None:
                    # srl: disable=SRL003 -- pipelined design point: consumes the PREVIOUS iteration's buffer after copy_to_host_async
                    _consume_readback(None, np.asarray(prev_rb), it)
        elif multi_host:
            # --- the iteration's single cross-host exchange (DCN): this
            # process's readback buffer + topn migration pool, allgathered ---
            with prof.stage("readback_d2h"):
                # srl: disable=SRL003 -- the iteration's single deliberate sync point, profiled as readback_d2h
                payload = tuple(np.asarray(a) for a in (rb, *pool_dev))
            with prof.stage("exchange"):
                if grp is not None:
                    # group transport: flat (every live row) or ring (rows
                    # [self, pred] — O(1)/step, pressure circulates the
                    # whole ring in |live| iterations)
                    own_dev_evals = float(_decode_readback(payload[0], cfg)[4])
                    gathered = grp.exchange(payload)
                else:
                    gathered = dist.all_gather_migration_pool(
                        payload, on_peer_loss=options.on_peer_loss
                    )
            _note_lost_peers()
            _consume_readback(gathered, None, it + 1)
        else:
            with prof.stage("readback_d2h"):
                buf = np.asarray(rb)  # srl: disable=SRL003 -- sync-readback mode (async_readback off): deliberate, profiled
            _consume_readback(None, buf, it + 1)

        # count AFTER the iteration's host-triggered rescore/simplify evals so
        # the max_evals stop and the returned total see them immediately (in
        # the pipelined loop both lag one iteration, like the readback)
        num_evals = base_evals + device_evals + host_evals

        if output_file and options.save_to_file and head:
            save_hall_of_fame(
                output_file, hof, options, dataset.variable_names,
                num_evals=num_evals,
            )
        if ckptr is not None and ckptr.due(it + 1):
            # best-effort snapshot (exact=False): decode the LIVE state (the
            # state reference is always the latest output buffers, valid
            # under donation) — resume rescore-warm-starts from it. In the
            # pipelined loop hof/num_evals lag one iteration, matching the
            # documented staleness of every other consumer here.
            with prof.stage("checkpoint"):
                ck_pops, _, _ = _decode_state_populations(
                    state, I, P, cfg, options
                )
                ckptr.save(
                    SearchCheckpoint(
                        iteration=it + 1,
                        niterations=niterations,
                        scheduler="device",
                        exact=False,
                        populations=ck_pops,
                        hall_of_fame=hof.copy(),
                        num_evals=float(num_evals),
                        options_fingerprint=options_fingerprint(options),
                        wall_time=time.time() - start_time,
                        out_j=out_j,
                    )
                )
        if verbosity > 0 and head:
            elapsed = time.time() - start_time
            print(
                f"[device iter {it + 1}/{niterations}] evals={num_evals:.3g} "
                f"elapsed={elapsed:.1f}s evals/s={num_evals / max(elapsed, 1e-9):.3g}"
            )
            print(
                hof.render(
                    options, dataset.variable_names, dataset.y_variable_name
                )
            )

        # stop decision — in multi-host mode it must be LOCKSTEP: any
        # process's local trigger (head's stdin, clock skew on timeout) is
        # allgathered so every process breaks on the same iteration. The
        # pipelined loop sees hof/num_evals one iteration late, so
        # early_stop/max_evals fire one iteration later than the sync path
        # (documented deviation; the stale window matches the migration lag).
        stop_code = 0
        if options.iteration_callback is not None:
            from ..search import IterationReport

            if options.iteration_callback(
                IterationReport(
                    iteration=it + 1,
                    niterations=niterations,
                    hall_of_fame=hof,
                    num_evals=float(num_evals),
                    elapsed=time.time() - start_time,
                )
            ):
                # joins the lockstep stop_sync below like every other stop:
                # in multi-host mode any process's callback stops all
                stop_code = 5
        if stop_code == 0:
            if early_stop is not None and any(
                early_stop(m.loss, m.get_complexity(options))
                for m in hof.pareto_frontier()
            ):
                stop_code = 1
            elif (
                options.timeout_in_seconds is not None
                and time.time() - start_time > options.timeout_in_seconds
            ):
                stop_code = 2
            elif options.max_evals is not None and num_evals >= options.max_evals:
                stop_code = 3
            elif head and stdin_reader.check_for_user_quit():
                stop_code = 4
        if multi_host:
            with prof.stage("stop_sync"):
                if grp is not None:
                    # the iteration's ADMISSION POINT: stop codes max-reduce,
                    # per-process cumulative evals sum-reduce (exact under
                    # ring topology, where the payload exchange only sees
                    # [self, pred] rows), and any membership change —
                    # suspects killed, announced joiners admitted — lands
                    # here, in lockstep, with an epoch bump
                    _cur_it[0] = it + 1
                    stop_code, evals_sum, _admitted = grp.stop_sync(
                        stop_code, own_dev_evals, it + 1
                    )
                    stop_code = int(stop_code)
                    device_evals = evals_sum
                    num_evals = base_evals + device_evals + host_evals
                else:
                    stop_code = int(
                        np.max(
                            dist.all_gather_migration_pool(
                                # srl: disable=SRL003 -- wraps a host int, no device transfer
                                np.asarray([stop_code], np.int32),
                                on_peer_loss=options.on_peer_loss,
                            )
                        )
                    )
            _note_lost_peers()
        prof.next_iteration()
        if stop_code:
            stop_reason = {
                1: "early_stop", 2: "timeout", 3: "max_evals", 4: "user_quit",
                5: "callback",
            }[stop_code]
            break

    if async_rb:
        # drain the pipeline: the last iteration's readback (and exchange
        # payload) is still in flight. Every process reaches here on the
        # same iteration (lockstep stop), so the final gather stays uniform.
        if multi_host:
            gathered = grp.flush() if grp is not None else exchange.flush()
            _note_lost_peers()
            if gathered is not None:
                _consume_readback(gathered, None, niterations)
        elif pending_rb is not None:
            _consume_readback(None, np.asarray(pending_rb), niterations)
        num_evals = base_evals + device_evals + host_evals

    iteration_seconds = time.time() - start_time
    if own_stdin:
        stdin_reader.close()

    # --- final population readback (host Populations for warm starts) -------
    pops, final_slots, (
        kind, opa, lhs, rhs, feat, val, length, loss, score
    ) = _decode_state_populations(state, I, P, cfg, options)
    if not multi_host:
        for pop in pops:
            hof.update_many(pop.members, options)
    # multi-host defers to the lockstep sync below (final_slots carries the
    # MAPPED complexity so the exchange bins match hof slots under
    # complexity_of_*)

    if multi_host:
        # final lockstep hof sync: the last const-opt's improvements live
        # only in state.loss/val (the bs frontier is updated by _event, not
        # const-opt), so folding LOCAL members into the hof here would make
        # per-process hofs diverge after the last exchange. Instead exchange
        # a best-per-complexity snapshot of the final populations and let
        # every process merge the same global set.
        S1 = cfg.maxsize + 1
        vdt_np = np.dtype(cfg.val_dtype)
        fl = np.full((S1,), np.inf, vdt_np)
        fn_ = np.zeros((S1,), vdt_np)
        ffields = [np.zeros((S1, N), vdt_np) for _ in range(6)]
        for i, p, comp_ip in final_slots:
            s = min(int(comp_ip), cfg.maxsize)
            if np.isfinite(loss[i, p]) and loss[i, p] < fl[s]:
                fl[s] = loss[i, p]
                fn_[s] = length[i, p]
                for arr, src in zip(
                    ffields, (kind, opa, lhs, rhs, feat, val)
                ):
                    arr[s] = src[i, p]
        if grp is not None:
            # always FLAT, even under ring topology: the once-per-search
            # final frontier merge must converge on every process
            g, _, _ = grp.allgather((fl, fn_, *ffields))
        else:
            g = dist.all_gather_migration_pool(
                (fl, fn_, *ffields), on_peer_loss=options.on_peer_loss
            )
        _note_lost_peers()
        # srl: disable=SRL003 -- final hof exchange decode: runs once per search, after the engine loop
        for pi in range(np.asarray(g[0]).shape[0]):
            bl = np.asarray(g[0][pi])  # srl: disable=SRL003 -- final hof decode, cold path
            bn = np.asarray(g[1][pi]).astype(np.int32)  # srl: disable=SRL003 -- final hof decode, cold path
            flds = [np.asarray(g[2 + j][pi]) for j in range(6)]  # srl: disable=SRL003 -- final hof decode, cold path
            for m in _bs_to_members(
                bl, np.isfinite(bl), bn, flds, cfg, options
            ):
                hof.update(m, options)

    if grp is not None:
        # stop the heartbeat thread and drop this rank's beat — the group is
        # per-search state, nothing survives into the next equation_search
        grp.close()

    # final CSV write AFTER the population decode: the decode folds the last
    # const-opt's improvements (absent from the bs-frontier readbacks) into
    # the hall of fame, and the returned frontier must match the saved file —
    # load_saved_state round-trips depend on it
    if output_file and options.save_to_file and head:
        save_hall_of_fame(
            output_file, hof, options, dataset.variable_names,
            num_evals=num_evals,
        )

    result = SearchResult(
        hall_of_fame=hof,
        populations=pops,
        dataset=dataset,
        options=options,
        num_evals=num_evals,
    )
    result.stop_reason = stop_reason
    # loop-only wall time (compile/warmup/setup excluded): the honest
    # denominator for end-to-end throughput (bench.py e2e_main)
    result.iteration_seconds = iteration_seconds
    if options.profile:
        # per-stage walls of the engine loop (utils/profiling.StageProfiler);
        # bench_engine_profile.py turns this into ENGINE_PROFILE artifacts
        cs = PROGRAM_CACHE.stats()
        prof.set_counters(
            "program_cache",
            {
                # this search's traffic, plus the live occupancy
                "hits": cs["hits"] - cache_stats0["hits"],
                "misses": cs["misses"] - cache_stats0["misses"],
                "evictions": cs["evictions"] - cache_stats0["evictions"],
                "entries": cs["entries"],
                "data_bytes": cs["data_bytes"],
            },
        )
        result.engine_profile = prof.summary()
    if own_recorder:
        recorder.dump()
    return result


# --- fleet engine (round 13): N concurrent searches as ONE megaprogram ------
#
# The serve layer's coalescing admission batches compatible jobs into a
# fleet; each lane is an independent single-output search. The per-iteration
# device work is run_fleet_iteration_fused — jit(vmap(fused impl)) over a
# leading lane axis — so N lanes cost the same <=2 dispatches per iteration
# as a solo search. Every per-lane computation (RNG included) is bitwise
# what the solo path computes: vmap slices are bit-identical per lane, and
# finished lanes freeze under a select mask (ops/evolve._freeze_inactive).


@dataclasses.dataclass
class FleetLaneSpec:
    """One lane of a fleet: a single-output dataset + its Options.

    ``options.seed`` drives the lane's RNG exactly as a solo
    ``equation_search(X, y, options=...)`` call would (same
    ``np.random.default_rng(seed)`` stream for initial trees + engine seed),
    so a lane's final frontier is bit-identical to the same search run solo
    — pinned by tests/test_fleet.py.

    ``init_trees``/``init_hof`` warm-start the lane (stream epochs: a
    session whose row bucket overflowed restarts its lane from the previous
    epoch's populations and KEEPS its live hall of fame). A warm-started
    lane is a continuation, not a replay — the solo-bitwise guarantee above
    applies only to cold lanes."""

    X: object
    y: object
    options: Options
    weights: object = None
    niterations: int = 10
    label: str = ""
    init_trees: object = None  # exactly populations*population_size trees
    init_hof: object = None  # a live HallOfFame the lane adopts (not copied)


def fleet_eligibility(options: Options) -> str | None:
    """None when a search with these Options can run as a fleet lane, else
    the reason it must run solo. The serve layer consults this before
    coalescing; any reason string routes the job to the plain per-job path
    (never an error)."""
    import jax

    reason = device_mode_supported(options)
    if reason is not None:
        return reason
    if options.scheduler != "device":
        return f"scheduler={options.scheduler!r} (fleet lanes run the device engine)"
    if options.use_recorder:
        return "use_recorder (per-lane replay logs are not demuxed)"
    if options.fault_spec:
        return "fault_spec (fault injection is a solo debugging rig)"
    if options.save_to_file:
        return "save_to_file (fleet lanes have no per-lane output file)"
    if (
        options.checkpoint_every is not None
        or options.checkpoint_every_seconds is not None
    ):
        return "checkpointing (fleet lanes snapshot via the serve spool only)"
    if os.environ.get("SR_FUSED_ITER", "1") == "0":
        return "SR_FUSED_ITER=0 (the fleet axis wraps the fused megaprogram)"
    if jax.process_count() > 1:
        return "multi-host (the per-iteration cross-host exchange is per-search)"
    n_dev = jax.local_device_count()
    if n_dev > 1:
        # Mirror the solo driver's mesh decision: a lane is only ineligible
        # when the solo run of these options would actually shard (the fleet
        # axis is single-device). With the mesh decision yielding 1x1 —
        # islands not divisible by the device count, no rows sharding — the
        # solo run is single-device too and the lane reproduces it exactly.
        if options.data_sharding == "rows":
            return (
                "data_sharding='rows' on a multi-device host (a solo search "
                "would shard rows over the mesh; the fleet axis is "
                "single-device)"
            )
        if int(options.populations) % n_dev == 0:
            return (
                "multi-device host with populations divisible by the device "
                "count (a solo search would shard islands over the mesh; "
                "the fleet axis is single-device)"
            )
    return None


class _FleetLane:
    """Per-lane host state: the solo driver's prelude (dataset, configs,
    score fn/data, initial device state) plus the per-lane loop bookkeeping
    (hall of fame, eval counters, stop conditions)."""

    def __init__(self, idx: int, spec: FleetLaneSpec, n_bucket: int,
                 force_weights: bool):
        import jax.numpy as jnp

        self.idx = idx
        self.spec = spec
        options = spec.options
        self.options = options
        self.nit = int(spec.niterations)

        X = np.asarray(spec.X)
        y = np.asarray(spec.y)
        w = None if spec.weights is None else np.asarray(spec.weights)
        self.padded = y.shape[0] < n_bucket
        if self.padded or (force_weights and w is None):
            # mixed-row-count fleet: pad to the shared row bucket with row-0
            # replicas at weight 0 (ops/scoring.pad_rows_np). The lane's
            # bitwise reference is then the SOLO run on this padded+weighted
            # dataset — the kernel-level bitwise identity of padded vs
            # truly-unpadded losses is pinned separately (tests/test_fleet.py).
            # The serve layer never pads: its admission bucket includes the
            # exact shapes, so serve-coalesced lanes keep the unconditional
            # solo-bitwise guarantee.
            from ..ops.scoring import pad_rows_np

            X, y, w = pad_rows_np(X, y, w, n_bucket)
        dataset = Dataset(X, y, weights=w)
        self.dataset = dataset

        # mirror equation_search's single-output entry: one fresh stream per
        # search, seeded from Options.seed
        rng = np.random.default_rng(options.seed)

        eng_dt = np.dtype(options.dtype)
        if eng_dt == np.float64:
            from ..utils.precision import ensure_x64_for_dtype

            ensure_x64_for_dtype(eng_dt)
        Xe = dataset.X.astype(eng_dt)
        ye = dataset.y.astype(eng_dt)
        we = None if dataset.weights is None else dataset.weights.astype(eng_dt)

        # baseline loss — identical host-side arithmetic to the solo driver
        avg = dataset.avg_y
        elem = np.asarray(options.loss(np.full_like(ye, avg), ye), np.float64)
        if we is not None:
            bl = float((elem * we).sum() / we.sum())
        else:
            bl = float(elem.mean())
        use_baseline = bool(np.isfinite(bl))
        dataset.baseline_loss = bl if use_baseline else 1.0
        dataset.use_baseline = use_baseline

        I, P = options.populations, options.population_size
        self.I, self.P = I, P
        cfg = build_evo_config(
            options,
            n_features=dataset.n_features,
            baseline_loss=dataset.baseline_loss,
            use_baseline=use_baseline,
            niterations=self.nit,
            n_islands=I,
            n_rows=dataset.n,
            dataset=dataset,
        )
        if cfg.warmup_maxsize_by == 0:
            cfg = dataclasses.replace(cfg, niterations=0)
        self.cfg = cfg
        self.ecfg = dataclasses.replace(cfg, baseline_loss=1.0, use_baseline=True)

        import jax

        use_pallas = (
            (jax.devices()[0].platform != "cpu" or _pallas_interpret())
            and eng_dt == np.float32
            and options.loss_function_jit is None
        )
        if use_pallas:
            from ..ops.interp_pallas import pallas_supported

            use_pallas = pallas_supported(
                options.operators, dataset.n_features, options.loss
            )
        use_pallas_grad = False
        if (
            use_pallas
            and options.should_optimize_constants
            and options.optimizer_algorithm == "BFGS"
        ):
            from ..ops.interp_pallas import pallas_grad_supported

            use_pallas_grad = pallas_grad_supported(
                options.operators, dataset.n_features, options.loss
            )
        self.use_pallas = use_pallas
        self.use_pallas_grad = use_pallas_grad

        ds_key = _dataset_key(Xe, ye, we)
        norm_val = (
            dataset.baseline_loss
            if (use_baseline and dataset.baseline_loss >= 0.01)
            else 0.01
        )
        need_raw = (
            options.batching
            or not use_pallas
            or (options.should_optimize_constants and not use_pallas_grad)
        )
        self.need_raw = need_raw
        self.eng_dt = eng_dt
        # kernel-resident evolve block (same resolution as the solo driver;
        # the fleet megaprogram is always fused, so no SR_FUSED_ITER gate)
        self.n_rows = int(dataset.n)
        self.block_backend = None
        blk_env = os.environ.get("SR_ENGINE_BLOCK", "")
        if (
            blk_env != "0"
            and options.loss_function_jit is None
            and eng_dt == np.float32
            and dataset.n <= 8 * _blk_row_limit()
        ):
            from ..ops.evolve_block import block_eligible

            if block_eligible(self.ecfg)[0]:
                from ..ops.interp_pallas import evolve_block_supported

                if evolve_block_supported(
                    options.operators, dataset.n_features, options.loss
                ):
                    self.block_backend = "kernel"
                elif blk_env == "1":
                    self.block_backend = "reference"
        self.score_fn, self.score_data = _make_score_fn(
            Xe, ye, we, options, use_pallas, ds_key=ds_key, norm=norm_val,
            need_raw=need_raw, need_packed=self.block_backend is not None,
        )
        self.score_call = lambda batch: self.score_fn.jitted(
            batch, self.score_data
        )

        self.bs_local = None
        if cfg.batching:
            self.bs_local = max(1, min(int(options.batch_size), dataset.n))
        has_w = we is not None
        self.copt_key = None
        self.make_copt = None
        if options.should_optimize_constants:
            if use_pallas_grad:
                self.make_copt = (
                    lambda c, jit=True: _make_const_opt_fn_pallas(
                        options, c, dataset.n, has_w,
                        batch_rows=self.bs_local, jit=jit,
                    )
                )
            else:
                self.make_copt = lambda c, jit=True: _make_const_opt_fn(
                    options, c, has_w, batch_rows=self.bs_local, jit=jit
                )
            self.copt_key = (
                Xe.shape, has_w, options.operators, options.loss,
                options.loss_function_jit,
                options.optimizer_probability, options.optimizer_nrestarts,
                options.optimizer_iterations, options.optimizer_algorithm,
                options.optimizer_g_tol, _copt_env(), bucket_min(),
            )

        # pipelined readback: the solo auto default (replay is impossible in
        # a fleet, so only profiling forces the synchronous path)
        async_rb = options.async_readback
        if async_rb is None:
            async_rb = not options.profile
        if options.profile:
            async_rb = False
        self.async_rb = bool(async_rb)

        self.do_simplify = (
            options.should_simplify
            and "no_simplify" not in os.environ.get("SR_ABLATE", "").split(",")
        )
        self.early_stop = options.early_stop_fn()
        self.hof = (
            spec.init_hof
            if spec.init_hof is not None
            else HallOfFame(options.maxsize)
        )
        self.device_evals = 0.0
        self.host_evals = 0.0
        self.num_evals = 0.0
        self.stop_reason: str | None = None

        # --- initial populations -> scored device EvoState (solo build_state)
        if spec.init_trees is not None:
            init_trees = list(spec.init_trees)
            if len(init_trees) != I * P:
                raise ValueError(
                    "init_trees must carry populations*population_size="
                    f"{I * P} trees (got {len(init_trees)})"
                )
        else:
            init_trees = Population.random_trees(
                I * P, options, dataset.n_features, rng
            )
        seed = int(rng.integers(0, 2**31 - 1))
        N = options.max_nodes
        bflat = flatten_trees(init_trees, N, dtype=eng_dt)
        batch0 = Tree(
            jnp.asarray(bflat.kind), jnp.asarray(bflat.op),
            jnp.asarray(bflat.lhs), jnp.asarray(bflat.rhs),
            jnp.asarray(bflat.feat), jnp.asarray(bflat.val),
            jnp.asarray(bflat.length),
        )
        b_losses = self.score_call(batch0)
        if cfg.units_check:
            from ..ops.evolve import dim_penalty_batch_jit

            b_losses = b_losses + dim_penalty_batch_jit(batch0, self.ecfg)
        st = init_state(bflat, np.zeros(I * P), self.ecfg, seed)
        from ..ops.evolve import _complexity_members

        comp = _complexity_members(st, self.ecfg).astype(jnp.float32)
        loss_dev = b_losses.reshape(I, P)
        self.state = st._replace(
            loss=loss_dev, score=_score_of(loss_dev, comp, cfg)
        )

    def rebuild_score_data(self, X, y, weights) -> "tuple[ScoreData, Dataset]":
        """Same-shape ScoreData for a live row swap (the stream runtime's
        between-iteration data update).

        Mirrors ``__init__``'s host arithmetic exactly — engine-dtype cast,
        weighted baseline loss, the baseline->norm clamp — so pushing the
        IDENTICAL buffer back rebuilds bit-identical device values. Bypasses
        the score_data LRU on purpose: a streaming session's per-push
        buffers would churn the cache without ever being re-requested.
        Shapes (and weight presence) must match the lane's buffers; the
        same-shape constraint is what makes the swap recompile-free — the
        dataset travels as a traced, NON-donated argument of the fleet
        program, so only a new shape forces a new executable.

        Returns ``(score_data, dataset)``: the swap payload plus the host
        Dataset the lane's final SearchResult should report."""
        X = np.asarray(X)
        y = np.asarray(y)
        w = None if weights is None else np.asarray(weights)
        if (
            X.shape != self.dataset.X.shape
            or y.shape != self.dataset.y.shape
            or (w is None) != (self.dataset.weights is None)
        ):
            raise ValueError(
                f"row swap must keep the lane's shapes: X {self.dataset.X.shape}"
                f"/y {self.dataset.y.shape}/weights "
                f"{self.dataset.weights is not None} vs swapped X {X.shape}"
                f"/y {y.shape}/weights {w is not None}"
            )
        ds = Dataset(X, y, weights=w)
        Xe = ds.X.astype(self.eng_dt)
        ye = ds.y.astype(self.eng_dt)
        we = None if ds.weights is None else ds.weights.astype(self.eng_dt)
        elem = np.asarray(
            self.options.loss(np.full_like(ye, ds.avg_y), ye), np.float64
        )
        if we is not None:
            bl = float((elem * we).sum() / we.sum())
        else:
            bl = float(elem.mean())
        use_baseline = bool(np.isfinite(bl))
        ds.baseline_loss = bl if use_baseline else 1.0
        ds.use_baseline = use_baseline
        norm_val = bl if (use_baseline and bl >= 0.01) else 0.01
        data = _make_score_data(
            Xe, ye, we, self.use_pallas, norm=norm_val, need_raw=self.need_raw
        )
        return data, ds


@dataclasses.dataclass
class LaneDataUpdate:
    """One lane's between-iteration data swap, returned by a
    ``fleet_search`` ``data_update_hook``: a same-shape ScoreData (from
    ``_FleetLane.rebuild_score_data``), the replacement host Dataset the
    final SearchResult reports, and an optional parsimony-frequency reset —
    the drift response that forgets the complexity histogram learned on the
    old data (the per-lane ``freq`` row resets to the ``init_state``
    uniform)."""

    score_data: object = None
    dataset: object = None
    reset_freq: bool = False


def _set_lane_slice(tree_f, l, new_tree):
    """Write one lane's slice of a stacked [Lb, ...] pytree."""
    import jax

    return jax.tree_util.tree_map(
        lambda a, nd: a.at[l].set(nd), tree_f, new_tree
    )


def _fleet_dummy_pool(ecfg: EvoConfig):
    """All-invalid [maxsize+1] migration pool: injected with apply=False (or
    drawn-never thanks to inf losses) — the no-op filler for lanes without a
    simplify pool this iteration."""
    import jax.numpy as jnp

    S1 = ecfg.maxsize + 1
    N = ecfg.n_slots
    zi = jnp.zeros((S1, N), jnp.int32)
    return (
        zi.at[:, 0].set(1), zi, zi, zi, zi,
        jnp.zeros((S1, N), jnp.dtype(ecfg.val_dtype)),
        jnp.ones((S1,), jnp.int32),
        jnp.full((S1,), jnp.inf, jnp.dtype(ecfg.val_dtype)),
    )


def fleet_search(
    specs,
    verbosity: int = 0,
    coalesce_wait_s: float = 0.0,
    on_lane_done=None,
    lane_bucket: int | None = None,
    data_update_hook=None,
    on_lanes_ready=None,
):
    """Run N compatible single-output searches as ONE vmapped megaprogram
    per iteration. Returns ``[SearchResult]`` in spec order.

    Every lane must be fleet-eligible (``fleet_eligibility``) and the lanes
    must share one engine configuration: equal engine EvoConfig (operators,
    sizes, cycles — everything but the per-lane baseline/seed) and one
    memoized score fn (same shapes after row-bucket padding). Per-lane
    niterations / timeout / max_evals / early-stop / iteration_callback are
    honored individually: a finished lane freezes (bitwise) under the fleet
    mask while the rest drain.

    ``lane_bucket`` pads the fleet axis to a fixed width with inert lanes
    (replicas of lane 0, never active, results discarded) so batches of
    different sizes share ONE compiled megaprogram — the fleet analogue of
    the row/length buckets. Real-lane results are unchanged: the lane axis
    is data-parallel, so extra lanes cannot perturb a real lane's values.

    ``on_lane_done(idx, result)`` fires as each lane finalizes — the serve
    layer uses it to complete jobs without waiting for the whole fleet.
    ``coalesce_wait_s`` is bookkeeping only (profiler counter).

    ``data_update_hook(it)`` (stream runtime) runs at the TOP of each
    iteration, before the fused step, with the 0-based iteration index; it
    may return ``{lane_idx: LaneDataUpdate}`` to swap lanes' datasets
    between iterations. The stacked dataset is a traced, non-donated
    program argument, so a same-shape swap reuses the resident executables
    with ZERO recompiles (pinned by tests/test_stream.py); a structure
    change raises instead of silently retracing. ``on_lanes_ready(lanes)``
    fires once after lane construction, handing the caller the live
    ``_FleetLane`` objects (the stream session uses lane.rebuild_score_data
    and the lane's warm score programs for drift probes/rescoring)."""
    import jax
    import jax.numpy as jnp

    from ..search import SearchResult  # late import (module cycle)
    from ..utils.profiling import NULL_PROFILER, StageProfiler

    specs = list(specs)
    L = len(specs)
    if L == 0:
        return []
    for spec in specs:
        reason = fleet_eligibility(spec.options)
        if reason is not None:
            raise ValueError(f"spec not fleet-eligible: {reason}")

    ns = [np.asarray(s.y).shape[0] for s in specs]
    n_bucket = max(ns)
    # mixed row counts (or mixed weight presence) force explicit weights on
    # EVERY lane so the stacked ScoreData pytree is uniform; see _FleetLane
    force_weights = any(s.weights is not None for s in specs) or any(
        n < n_bucket for n in ns
    )
    cache_stats0 = (
        PROGRAM_CACHE.stats()
        if any(s.options.profile for s in specs)
        else None
    )
    lanes = [
        _FleetLane(i, s, n_bucket, force_weights) for i, s in enumerate(specs)
    ]
    # padded fleet width: Lb >= L inert lanes so every batch size in
    # [1, lane_bucket] reuses one compiled program (cache keys use Lb)
    Lb = max(L, lane_bucket) if lane_bucket else L
    pad = Lb - L

    lead = lanes[0]
    ecfg = lead.ecfg
    score_fn = lead.score_fn
    for lane in lanes[1:]:
        if lane.ecfg != ecfg:
            raise ValueError(
                "fleet lanes must share one engine EvoConfig (operators, "
                "population geometry, cycles, maxsize, dtype, batching); "
                f"lane {lane.idx} ({lane.spec.label!r}) differs"
            )
        if lane.score_fn is not score_fn:
            raise ValueError(
                "fleet lanes must share one memoized score fn (same dataset "
                f"shapes + scoring options); lane {lane.idx} differs"
            )
        if (
            lane.async_rb != lead.async_rb
            or lane.use_pallas_grad != lead.use_pallas_grad
            or lane.copt_key != lead.copt_key
            or lane.options.jit_warmup != lead.options.jit_warmup
            or lane.block_backend != lead.block_backend
        ):
            raise ValueError(
                "fleet lanes must agree on async_readback/profile, the "
                f"const-opt configuration, and jit_warmup; lane {lane.idx} "
                "differs"
            )
    async_rb = lead.async_rb
    copt_impl = lead.make_copt(ecfg, jit=False) if lead.make_copt else None
    fin_sfn = score_fn if ecfg.batching else None
    block_fn = None
    if lead.block_backend is not None:
        # one shared closure: every lane proved the same backend/config
        # above, and the stacked data_f vmaps through it lane-by-lane
        block_fn = _make_block_fn(
            lead.options.operators, lead.options.loss, ecfg, lead.n_rows,
            lead.block_backend,
        )
    frac_hof = float(lead.options.fraction_replaced_hof)

    # stacked device state + dataset: [Lb, ...] leading fleet axis (pad
    # lanes replicate lane 0 and stay inactive for the whole run)
    state_f = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *([lane.state for lane in lanes] + [lanes[0].state] * pad),
    )
    data_f = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *([lane.score_data for lane in lanes] + [lanes[0].score_data] * pad),
    )
    for lane in lanes:
        lane.state = None  # the stacked copy is authoritative now
    if on_lanes_ready is not None:
        on_lanes_ready(lanes)

    active = [lane.nit > 0 for lane in lanes] + [False] * pad
    active_dev = jnp.asarray(np.asarray(active))

    from ..ops.evolve import (
        fleet_migrate_from_pool,
        run_fleet_iteration_fused,
        run_fleet_iteration_fused_donated,
    )

    # --- AOT warmup under the fleet-specific cache kinds ("fleet_aot"):
    # program_cache.stats()["by_kind"] then separates fleet-program traffic
    # from solo "aot" traffic, keeping serve warm-ratio stats honest
    base_fused = (
        run_fleet_iteration_fused_donated if async_rb else run_fleet_iteration_fused
    )
    rb_pack = _make_readback_fn(ecfg)
    fleet_rb = jax.jit(jax.vmap(rb_pack))
    if lead.options.jit_warmup:
        k_fused = (
            "fleet", Lb, ecfg, score_fn, async_rb, ecfg.batching,
            lead.use_pallas_grad, _pallas_interpret(), lead.copt_key,
            None
            if block_fn is None
            else ("blk", lead.block_backend, lead.n_rows),
        )
        fused_step = PROGRAM_CACHE.get("fleet_aot", k_fused)
        if fused_step is None:
            fused_step = base_fused.lower(
                state_f, active_dev, data_f, ecfg, score_fn, copt_impl,
                fin_sfn, block_fn=block_fn,
            ).compile()
            fused_step = PROGRAM_CACHE.put("fleet_aot", k_fused, fused_step)
        k_rb = ("fleet_rb", Lb, ecfg)
        rb_step = PROGRAM_CACHE.get("fleet_aot", k_rb)
        if rb_step is None:
            rb_step = fleet_rb.lower(state_f).compile()
            rb_step = PROGRAM_CACHE.put("fleet_aot", k_rb, rb_step)
        if any(lane.do_simplify for lane in lanes):
            # prime the injection + pool-rescore programs (fixed [maxsize+1]
            # shapes) exactly like the solo warmup: all-invalid pool, apply
            # nowhere, result discarded
            dummy = _fleet_dummy_pool(ecfg)
            pool_f = jax.tree_util.tree_map(
                lambda x: jnp.stack([x] * Lb), dummy
            )
            fleet_migrate_from_pool(
                state_f, ecfg, pool_f,
                jnp.zeros((Lb,), bool), frac_hof, data_f.norm,
            )
            lead.score_call(
                Tree(*dummy[:6], dummy[6])
            ).block_until_ready()
    else:
        fused_step = lambda st, act, d: base_fused(  # noqa: E731
            st, act, d, ecfg, score_fn, copt_impl, fin_sfn, block_fn=block_fn
        )
        rb_step = fleet_rb

    prof = (
        StageProfiler()
        if any(lane.options.profile for lane in lanes)
        else NULL_PROFILER
    )
    from ..search import IterationReport

    dummy_pool = None
    start_time = time.time()
    results: list = [None] * L
    pending = None  # [rb_f, consumer lane set] — the pipelined carry
    nit_max = max(lane.nit for lane in lanes)

    def _consume_rows(buf: np.ndarray, consumers) -> None:
        """Demux one stacked readback into per-lane hofs + simplify pools,
        then apply all lanes' injections as ONE masked fleet program."""
        nonlocal state_f, dummy_pool
        t0 = time.perf_counter()
        pools = {}
        for l in sorted(consumers):
            lane = lanes[l]
            bs_loss, bs_exists, bs_len, fields, dev_evals = _decode_readback(
                buf[l], lane.cfg
            )
            lane.device_evals = dev_evals
            members = _bs_to_members(
                bs_loss, bs_exists, bs_len, fields, lane.cfg, lane.options
            )
            for m in members:
                lane.hof.update(m, lane.options)
            if lane.do_simplify:
                pool, n_scored = _simplified_frontier_pool(
                    members, lane.options, lane.cfg, lane.score_call, lane.hof
                )
                lane.host_evals += n_scored
                if pool is not None:
                    pools[l] = pool
            lane.num_evals = lane.device_evals + lane.host_evals
        if pools:
            if dummy_pool is None:
                dummy_pool = _fleet_dummy_pool(ecfg)
            pool_f = tuple(
                jnp.stack([
                    pools.get(l, dummy_pool)[j] for l in range(Lb)
                ])
                for j in range(8)
            )
            apply_f = jnp.asarray(
                np.asarray([l in pools for l in range(Lb)])
            )
            state_f = fleet_migrate_from_pool(
                state_f, ecfg, pool_f, apply_f, frac_hof, data_f.norm
            )
        prof.add_time("fleet/demux", time.perf_counter() - t0)

    def _finalize_lane(l: int, stop_code: int) -> None:
        """The solo post-loop sequence for one lane: flush its pending
        readback (simplify injection included), decode its state slice, fold
        final populations into the hof, build the SearchResult."""
        nonlocal pending
        lane = lanes[l]
        active[l] = False
        if pending is not None and l in pending[1]:
            pending[1].discard(l)
            _consume_rows(np.asarray(pending[0]), (l,))
        lane_state = jax.tree_util.tree_map(lambda a: a[l], state_f)
        pops, _, _ = _decode_state_populations(
            lane_state, lane.I, lane.P, lane.cfg, lane.options
        )
        for pop in pops:
            lane.hof.update_many(pop.members, lane.options)
        result = SearchResult(
            hall_of_fame=lane.hof,
            populations=pops,
            dataset=lane.dataset,
            options=lane.options,
            num_evals=lane.num_evals,
        )
        result.stop_reason = {
            0: None, 1: "early_stop", 2: "timeout", 3: "max_evals",
            5: "callback",
        }[stop_code]
        result.iteration_seconds = time.time() - start_time
        results[l] = result
        if on_lane_done is not None:
            on_lane_done(l, result)

    for l, lane in enumerate(lanes):
        if lane.nit <= 0:
            _finalize_lane(l, 0)
    if any(active):
        active_dev = jnp.asarray(np.asarray(active))

    for it in range(nit_max):
        if not any(active):
            break
        if data_update_hook is not None:
            updates = data_update_hook(it)
            for l, upd in (updates or {}).items():
                lane = lanes[l]
                if upd.score_data is not None:
                    new_d = upd.score_data
                    if jax.tree_util.tree_structure(
                        new_d
                    ) != jax.tree_util.tree_structure(lane.score_data):
                        # structural equality is the zero-recompile contract:
                        # a mismatched pytree (weights appearing where none
                        # existed, raw fields toggling) would silently
                        # retrace the whole fleet program on next dispatch
                        raise ValueError(
                            f"lane {l} data update changes the ScoreData "
                            "structure; rebuild it with "
                            "_FleetLane.rebuild_score_data"
                        )
                    data_f = _set_lane_slice(data_f, l, new_d)
                    # score_call reads the attribute at call time, so the
                    # simplify-pool rescoring sees the swapped data too
                    lane.score_data = new_d
                if upd.dataset is not None:
                    lane.dataset = upd.dataset
                if upd.reset_freq:
                    state_f = state_f._replace(
                        freq=state_f.freq.at[l].set(1.0)
                    )
        with prof.stage("fused_iter"):
            _count_dispatch("fused_iter")
            state_f = fused_step(state_f, active_dev, data_f)
            prof.fence(state_f)
        with prof.stage("readback_pack"):
            _count_dispatch("readback")
            rb_f = rb_step(state_f)
            prof.fence(rb_f)
        if async_rb:
            rb_f.copy_to_host_async()
            prev, pending = pending, [rb_f, {l for l in range(L) if active[l]}]
            if prev is not None and prev[1]:
                # srl: disable=SRL003 -- pipelined design point: consumes the PREVIOUS iteration's buffer after copy_to_host_async
                _consume_rows(np.asarray(prev[0]), prev[1])
        else:
            with prof.stage("readback_d2h"):
                buf = np.asarray(rb_f)  # srl: disable=SRL003 -- sync-readback mode (profiling): deliberate
            _consume_rows(buf, {l for l in range(L) if active[l]})

        t_now = time.time()
        changed = False
        for l in range(L):
            if not active[l]:
                continue
            lane = lanes[l]
            stop_code = 0
            if lane.options.iteration_callback is not None:
                if lane.options.iteration_callback(
                    IterationReport(
                        iteration=it + 1,
                        niterations=lane.nit,
                        hall_of_fame=lane.hof,
                        num_evals=float(lane.num_evals),
                        elapsed=t_now - start_time,
                    )
                ):
                    stop_code = 5
            if stop_code == 0:
                if lane.early_stop is not None and any(
                    lane.early_stop(m.loss, m.get_complexity(lane.options))
                    for m in lane.hof.pareto_frontier()
                ):
                    stop_code = 1
                elif (
                    lane.options.timeout_in_seconds is not None
                    and t_now - start_time > lane.options.timeout_in_seconds
                ):
                    stop_code = 2
                elif (
                    lane.options.max_evals is not None
                    and lane.num_evals >= lane.options.max_evals
                ):
                    stop_code = 3
            if stop_code or it + 1 >= lane.nit:
                _finalize_lane(l, stop_code)
                changed = True
        if changed and any(active):
            active_dev = jnp.asarray(np.asarray(active))
        if verbosity > 0:
            live = sum(active)
            print(
                f"[fleet iter {it + 1}/{nit_max}] lanes={L} live={live}"
            )
        prof.next_iteration()

    if prof.enabled:
        cs = PROGRAM_CACHE.stats()
        prof.set_counters(
            "fleet",
            {
                "lanes": L,
                "lane_bucket": Lb,
                "coalesce_wait_s": float(coalesce_wait_s),
            },
        )
        prof.set_counters(
            "program_cache",
            {
                "hits": cs["hits"] - cache_stats0["hits"],
                "misses": cs["misses"] - cache_stats0["misses"],
                "evictions": cs["evictions"] - cache_stats0["evictions"],
                # fleet-program reuse vs solo-program reuse, separately —
                # a warm fleet shows fleet_misses == 0 even while lanes
                # still miss on their per-lane score fns
                "fleet_hits": cs["fleet"]["hits"] - cache_stats0["fleet"]["hits"],
                "fleet_misses": (
                    cs["fleet"]["misses"] - cache_stats0["fleet"]["misses"]
                ),
                "entries": cs["entries"],
                "data_bytes": cs["data_bytes"],
            },
        )
        summary = prof.summary()
        for result in results:
            result.engine_profile = summary
    return results
