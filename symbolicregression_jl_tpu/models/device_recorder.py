"""Host-side lineage replay for the device engine's recorder mode.

The reference's recorder traces every mutation/death/tuning event inline
(/root/reference/src/Mutate.jl:126-341, SingleIteration.jl:140-171,
SearchUtils.jl:377-393). The device engine batches a whole iteration into one
compiled program, so inline tracing is impossible by construction — the
TPU-native equivalent is an EVENT LOG: each engine program additionally
returns per-event arrays (chosen mutation kind, tournament winner, replaced
slot, accept flag, candidate tree fields, migration replace/src/pool rows,
const-opt accept mask + new values — ops/evolve.py `record_events`), and this
module replays them on the host into the same Recorder schema, maintaining a
tree mirror of every (island, member) slot so parent/child trees in the
record are exact.

Documented deviations from the host engines' records:
- migrated-in copies get FRESH refs (the reference's migration copies keep
  their source member's ref) — migration appears as death + unrelated birth;
- rejected events insert a parent copy under a fresh ref (host path keeps the
  parent object alive in place);
- with ``Options.batching`` the recorded per-event losses are MINIBATCH
  losses (each event scores a fresh with-replacement row subset, like the
  reference's ``score_func_batched`` accept draw), and the iteration-boundary
  finalize's exact full-data rescore is NOT replayed into the mirror — so a
  member's recorded loss can differ from the same tree's loss in the hall of
  fame / CSV output, which always come from the finalize rescore. Mirror
  losses are the engine's accept-time evidence, not the reporting losses.
"""

from __future__ import annotations

import numpy as np

from ..ops.flat import FlatTrees, unflatten_tree
from .pop_member import PopMember

__all__ = ["EngineLineageReplay", "ENGINE_MUTATION_NAMES"]

#: M_* index -> reference mutation-kind name (ops/evolve.py order)
ENGINE_MUTATION_NAMES = (
    "mutate_constant",
    "mutate_operator",
    "swap_operands",
    "add_node",
    "insert_node",
    "delete_node",
    "randomize",
    "do_nothing",
)


class EngineLineageReplay:
    """Replays device-engine event logs into a Recorder.

    ``state0_arrays``: numpy (kind, op, lhs, rhs, feat, val, length) of the
    initial populations, shapes [I, P, N] / [I, P] — the mirror's seed.
    """

    def __init__(self, state0_arrays, options, recorder, out_j: int = 1,
                 cfg=None, loss0=None, score0=None):
        kind, op, lhs, rhs, feat, val, length = state0_arrays
        self.I, self.P, self.N = kind.shape
        self.options = options
        self.recorder = recorder
        self.out_j = out_j
        self.cfg = cfg  # real-baseline EvoConfig for host-side score math
        # tree mirror: one decoded Node per slot + its (score, loss, ref);
        # initial losses/scores are the ENGINE's init values so entries for
        # first-generation members don't carry placeholder zeros
        self.trees = np.empty((self.I, self.P), dtype=object)
        self.loss = (
            np.zeros((self.I, self.P), np.float64)
            if loss0 is None else np.asarray(loss0, np.float64).copy()
        )
        self.score = (
            np.zeros((self.I, self.P), np.float64)
            if score0 is None else np.asarray(score0, np.float64).copy()
        )
        self.refs = np.zeros((self.I, self.P), dtype=np.int64)
        for i in range(self.I):
            flat_i = FlatTrees(
                kind[i], op[i], lhs[i], rhs[i], feat[i], val[i], length[i]
            )
            for p in range(self.P):
                m = PopMember(unflatten_tree(flat_i, p), 0.0, 0.0)
                self.trees[i, p] = m.tree
                self.refs[i, p] = m.ref

    # -- helpers -------------------------------------------------------------

    def _member(self, i: int, p: int) -> PopMember:
        m = PopMember.__new__(PopMember)
        m.tree = self.trees[i, p]
        m.score = float(self.score[i, p])
        m.loss = float(self.loss[i, p])
        m.birth = 0
        m.complexity = None
        m.ref = int(self.refs[i, p])
        m.parent = -1
        return m

    def _fresh(self, tree, score, loss, parent_ref: int) -> PopMember:
        m = PopMember(tree, float(score), float(loss), parent=int(parent_ref))
        return m

    # -- per-program consumers ----------------------------------------------

    def consume_iteration(self, log) -> None:
        """Replay one run_iteration log: {'events': {...[C, L, ...]},
        'mig_island'/'mig_hof': {...}} (numpy or device arrays)."""
        ev = {
            k: np.asarray(v) if not isinstance(v, tuple)
            else tuple(np.asarray(f) for f in v)
            for k, v in log["events"].items()
        }
        C, L = ev["kind"].shape
        E = L // self.I
        for c in range(C):
            cand_flat = FlatTrees(*(f[c] for f in ev["cand"]))
            # two passes per cycle: the engine batches ALL of a cycle's
            # events against ONE pre-event population snapshot, so every
            # lane's parent (and every death) must be read BEFORE any lane's
            # insert lands — a sequential replay would hand lane k a tree
            # that lane j < k already replaced
            staged = []
            for lane in range(L):
                i = lane // E
                win1 = int(ev["win1"][c, lane])
                slot1 = int(ev["slot1"][c, lane])
                kindname = ENGINE_MUTATION_NAMES[int(ev["kind"][c, lane])]
                accepted = bool(ev["accept"][c, lane])
                parent = self._member(i, win1)
                parent.loss = float(ev["ploss"][c, lane])
                parent.score = float(ev["pscore"][c, lane])
                if accepted:
                    baby_tree = unflatten_tree(cand_flat, lane)
                    b_loss = float(ev["loss"][c, lane])
                    b_score = float(ev["score"][c, lane])
                else:
                    baby_tree = parent.tree.copy()
                    b_loss, b_score = parent.loss, parent.score
                baby = self._fresh(baby_tree, b_score, b_loss, parent.ref)
                self.recorder.record_mutation(
                    parent, baby, kindname, accepted, self.options
                )
                self.recorder.record_death(self._member(i, slot1), self.options)
                staged.append((i, slot1, baby, b_loss, b_score))
            for i, slot1, baby, b_loss, b_score in staged:
                self.trees[i, slot1] = baby.tree
                self.loss[i, slot1] = b_loss
                self.score[i, slot1] = b_score
                self.refs[i, slot1] = baby.ref
        for key in ("mig_island", "mig_hof"):
            if key in log:
                self.consume_migration(log[key])

    def consume_migration(self, mig) -> None:
        replace = np.asarray(mig["replace"])
        src = np.asarray(mig["src"])
        pool = tuple(np.asarray(a) for a in mig["pool"])
        pool_flat = FlatTrees(*pool[:7])
        pool_loss = pool[7]
        for i in range(self.I):
            for p in range(self.P):
                if not replace[i, p]:
                    continue
                s = int(src[i, p])
                self.recorder.record_death(self._member(i, p), self.options)
                tree = unflatten_tree(pool_flat, s)
                loss = float(pool_loss[s])
                # real score for the migrated-in copy (the engine computes it
                # in _inject_pool via _score_of): lineage entries for these
                # members must not carry a placeholder score
                if self.cfg is not None:
                    from ..complexity import compute_complexity
                    from ..ops.evolve import _score_of

                    score = float(
                        _score_of(
                            loss,
                            float(compute_complexity(tree, self.options)),
                            self.cfg,
                        )
                    )
                else:
                    score = loss
                m = PopMember(tree, score, loss)
                self.trees[i, p] = m.tree
                self.loss[i, p] = m.loss
                self.score[i, p] = m.score
                self.refs[i, p] = m.ref

    def consume_tuning(self, tlog) -> None:
        """Replay a const-opt log: {'ii','pp','improved','new_loss','new_val'}."""
        ii = np.asarray(tlog["ii"])
        pp = np.asarray(tlog["pp"])
        improved = np.asarray(tlog["improved"])
        new_loss = np.asarray(tlog["new_loss"])
        new_val = np.asarray(tlog["new_val"])
        for k in range(len(ii)):
            i, p = int(ii[k]), int(pp[k])
            if improved[k]:
                # rewrite the mirror tree's constants in postorder slot order
                tree = self.trees[i, p]
                vals = new_val[k]
                for j, node in enumerate(tree.postorder()):
                    if node.degree == 0 and node.is_const:
                        node.val = complex(vals[j]) if np.iscomplexobj(
                            vals
                        ) else float(vals[j])
                self.loss[i, p] = float(new_loss[k])
                # keep the mirror's (loss, score) pair consistent, like the
                # engine's _accept_and_scatter recomputes _score_of
                if self.cfg is not None:
                    from ..complexity import compute_complexity
                    from ..ops.evolve import _score_of

                    self.score[i, p] = float(
                        _score_of(
                            self.loss[i, p],
                            float(compute_complexity(tree, self.options)),
                            self.cfg,
                        )
                    )
            self.recorder.record_tuning(
                self._member(i, p), bool(improved[k]), self.options
            )

    def snapshot_populations(self, state_arrays, iteration: int) -> None:
        """record_population from the AUTHORITATIVE decoded engine state
        (not the mirror): per-iteration out{j}_pop{i} entries like the host
        engines'."""
        from .population import Population

        kind, op, lhs, rhs, feat, val, length, loss, score = state_arrays
        for i in range(self.I):
            flat_i = FlatTrees(
                kind[i], op[i], lhs[i], rhs[i], feat[i], val[i], length[i]
            )
            members = []
            for p in range(self.P):
                if length[i, p] < 1:
                    continue
                m = PopMember.__new__(PopMember)
                m.tree = unflatten_tree(flat_i, p)
                m.score = float(score[i, p])
                m.loss = float(loss[i, p])
                m.birth = 0
                m.complexity = None
                m.ref = int(self.refs[i, p])
                m.parent = -1
                members.append(m)
            self.recorder.record_population(
                self.out_j, i + 1, iteration, Population(members), self.options
            )
