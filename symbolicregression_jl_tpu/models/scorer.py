"""BatchScorer: the host<->device boundary of the search.

The single most important architectural change vs. the reference: where the
reference calls ``score_func`` (one recursive eval) per mutation
(/root/reference/src/Mutate.jl:268-274), here every scoring request is queued
and evaluated as ONE batched XLA program over all candidate trees — across all
islands in a lockstep cycle. Host<->device traffic is flattened tree tensors
in, loss vectors out.

Compile discipline (SURVEY.md §7.3): candidate-batch sizes are padded to
power-of-two buckets and node counts to a fixed budget, so a whole search
compiles a handful of programs, all cached.
"""

from __future__ import annotations

import threading

import numpy as np

from ..dataset import Dataset
from ..ops.flat import batch_bucket as _bucket
from ..ops.flat import flatten_trees
from ..ops.scoring import (
    batched_loss_bucketed,
    baseline_loss,
    loss_to_score,
    objective_loss_jit,
)
from ..tree import Node

__all__ = ["BatchScorer"]


class BatchScorer:
    def __init__(self, dataset: Dataset, options):
        self.dataset = dataset
        self.options = options
        self.opset = options.operators
        self.loss_elem = options.loss
        self.dtype = options.dtype
        self.max_nodes = options.max_nodes
        X, y, w = dataset.device_arrays(self.dtype)
        self.X, self.y, self.w = X, y, w
        self._sharded = None
        if options.data_sharding == "rows":
            self._setup_row_sharding()
        # Fused Mosaic loss kernel: probe once per (operator set, loss); falls
        # back to the scan interpreter off-TPU (unless SR_PALLAS_INTERPRET=1
        # emulates the kernels via the Pallas interpreter — parity testing
        # only, orders of magnitude slower), for non-lowerable operators, or
        # for non-float32 compute dtypes (the kernel is f32-only). The hot
        # loop below holds this closure rather than calling the one-shot
        # loss_trees_pallas packing helpers (sr-lint SRL008).
        self._pallas_loss = None
        if self._sharded is None and np.dtype(self.dtype) == np.float32:
            from ..ops.interp_pallas import make_pallas_loss_fn, pallas_supported

            self.use_pallas = pallas_supported(
                self.opset, dataset.n_features, self.loss_elem
            )
            if self.use_pallas:
                self._pallas_loss = make_pallas_loss_fn(
                    dataset.X,
                    dataset.y,
                    dataset.weights,
                    self.opset,
                    self.loss_elem,
                )
        else:
            self.use_pallas = False
        bl, use = baseline_loss(dataset, self.opset, self.loss_elem, self.dtype)
        dataset.baseline_loss = bl
        dataset.use_baseline = use
        self.num_evals = 0.0
        # the async island scheduler scores from worker threads
        self._evals_lock = threading.Lock()
        # debug-checks gate resolved ONCE here: the hot path below branches on
        # a plain bool and makes zero verifier calls when off
        from ..analysis.ir_verify import debug_checks_enabled

        self._debug_checks = debug_checks_enabled(options)
        self._units_penalty = None
        if dataset.has_units:
            self._units_penalty = (
                1000.0
                if options.dimensional_constraint_penalty is None
                else float(options.dimensional_constraint_penalty)
            )

    def _setup_row_sharding(self) -> None:
        """Shard the dataset rows across all devices and route full-data
        scoring through the psum loss (SURVEY.md §5.7: the 'long axis' is the
        dataset-row axis; only scalar loss partials cross chips)."""
        import warnings

        import jax

        from ..parallel.mesh import make_mesh
        from ..parallel.sharding import make_sharded_loss, shard_dataset

        n_dev = len(jax.devices())
        if n_dev == 1:
            return
        if self.dataset.n % n_dev != 0:
            warnings.warn(
                f"data_sharding='rows' needs n ({self.dataset.n}) divisible by "
                f"device count ({n_dev}); falling back to single-device scoring"
            )
            return
        mesh = make_mesh(1, n_dev)
        self._mesh = mesh
        self._sharded = make_sharded_loss(
            mesh, self.opset, self.loss_elem, has_weights=self.w is not None
        )
        self.X, self.y, self.w = shard_dataset(
            mesh, self.dataset.X.astype(self.dtype),
            self.dataset.y.astype(self.dtype),
            None if self.dataset.weights is None
            else self.dataset.weights.astype(self.dtype),
        )

    # -- losses --------------------------------------------------------------

    def loss_many_async(self, trees: list[Node], idx: np.ndarray | None = None):
        """Dispatch a scoring batch WITHOUT blocking on the result.

        Returns a zero-arg callable that materializes the numpy losses. This is
        the latency-hiding half of the pipeline: `jax.jit` dispatch is async,
        so the host can keep proposing/applying evolution events while the
        device computes and the readback is in flight."""
        if not trees:
            return lambda: np.zeros((0,))
        if self.options.loss_function is not None:
            return self._custom_objective(trees, idx)
        P = len(trees)
        bucket = _bucket(P)
        padded = trees + [trees[0]] * (bucket - P)
        flat = flatten_trees(padded, self.max_nodes, dtype=self.dtype)
        if self._debug_checks:
            # late import so tests can monkeypatch ir_verify.verify_flat_trees
            # and count calls (and so the flag-off path never touches it)
            from ..analysis import ir_verify

            ir_verify.verify_flat_trees(
                flat,
                self.opset,
                n_features=self.dataset.n_features,
                max_nodes=self.max_nodes,
                allow_empty=False,
                where="scorer.loss_many_async: ",
            )
        if idx is None:
            X, y, w = self.X, self.y, self.w
            with self._evals_lock:
                self.num_evals += P
        else:
            X = self.X[:, idx]
            y = self.y[idx]
            w = None if self.w is None else self.w[idx]
            with self._evals_lock:
                self.num_evals += P * (len(idx) / self.dataset.n)
        if self.options.loss_function_jit is not None:
            # traceable full objective: preds matrix -> [P] losses, in-graph
            # (under GSPMD-sharded X/y the objective's row reductions become
            # global collectives automatically, so the value stays exact)
            dev_losses = objective_loss_jit(
                flat, X, y, w, self.opset, self.options.loss_function_jit
            )
        elif self._sharded is not None and idx is None:
            import jax.numpy as jnp

            from ..parallel.sharding import shard_population

            fs = shard_population(self._mesh, flat)
            w_arg = self.w if self.w is not None else jnp.zeros((), self.dtype)
            dev_losses = self._sharded(fs, self.X, self.y, w_arg)
        elif self._pallas_loss is not None and idx is None:
            dev_losses = self._pallas_loss(flat)
        elif self._pallas_loss is not None and len(idx) >= 2048:
            # Large minibatches: fused kernel with the in-graph reshape path.
            # (Its row tile is fixed at 10240, so small batches would waste
            # >5x compute in padding — those use the scan interpreter below.)
            from ..ops.interp_pallas import loss_trees_pallas_batch

            dev_losses = loss_trees_pallas_batch(
                flat, X, y, w, self.opset, self.loss_elem
            )
        else:
            # scan-interpreter fallback: length-bucketed dispatch — each
            # sub-batch pays a scan sized to its bucket, not max_nodes
            # (bit-identical losses; see ops/scoring.batched_loss_bucketed)
            dev_losses = None
            fetch = batched_loss_bucketed(
                flat, X, y, w, self.opset, self.loss_elem
            )
        if dev_losses is not None:
            try:
                dev_losses.copy_to_host_async()
            except Exception:
                pass
            fetch = lambda: np.asarray(dev_losses)  # noqa: E731

        def materialize() -> np.ndarray:
            losses = fetch()[:P].astype(np.float64)
            if self._units_penalty is not None:
                from ..dimensional_analysis import violates_dimensional_constraints

                viol = np.fromiter(
                    (
                        violates_dimensional_constraints(t, self.dataset, self.options)
                        for t in trees[:P]
                    ),
                    dtype=bool,
                    count=P,
                )
                # dimensional regularization: additive penalty, not rejection
                # (/root/reference/src/LossFunctions.jl:217-227)
                losses = losses + viol * self._units_penalty
            return losses

        return materialize

    def _custom_objective(self, trees: list[Node], idx):
        """Full-objective dispatch: the user's ``loss_function(tree, dataset,
        options)`` replaces elementwise eval entirely (reference:
        /root/reference/src/LossFunctions.jl:78-94; exercised by
        test_custom_objectives.jl). Host-side by nature — the objective sees
        the raw tree."""
        P = len(trees)
        with self._evals_lock:
            self.num_evals += P if idx is None else P * (len(idx) / self.dataset.n)
        fn = self.options.loss_function

        def materialize() -> np.ndarray:
            out = np.empty(P, dtype=np.float64)
            for k, t in enumerate(trees):
                try:
                    v = float(fn(t, self.dataset, self.options))
                except Exception:  # noqa: BLE001 — invalid tree => inf loss
                    v = np.inf
                out[k] = v if np.isfinite(v) or v == np.inf else np.inf
            return out

        return materialize

    def loss_many(self, trees: list[Node], idx: np.ndarray | None = None) -> np.ndarray:
        """Full-data (or row-subset) losses for a batch of trees. Returns
        float64 numpy [len(trees)]; inf = invalid candidate."""
        return self.loss_many_async(trees, idx=idx)()

    def apply_units_penalty(self, trees: list[Node], losses: np.ndarray) -> np.ndarray:
        """Add the dimensional-regularization penalty to externally-computed
        losses (e.g. the constant optimizer's) so unit-violating trees cannot
        enter populations/hall-of-fame un-penalized."""
        if self._units_penalty is None or not len(trees):
            return losses
        from ..dimensional_analysis import violates_dimensional_constraints

        viol = np.fromiter(
            (
                violates_dimensional_constraints(t, self.dataset, self.options)
                for t in trees
            ),
            dtype=bool,
            count=len(trees),
        )
        return np.asarray(losses) + viol * self._units_penalty

    def batch_indices(self, rng: np.random.Generator) -> np.ndarray | None:
        """With-replacement minibatch row indices (reference: batch_sample,
        /root/reference/src/LossFunctions.jl:125-127); None when not batching."""
        if not self.options.batching:
            return None
        return rng.integers(0, self.dataset.n, size=self.options.batch_size)

    # -- scores --------------------------------------------------------------

    def score_of(self, loss: np.ndarray, complexity: np.ndarray) -> np.ndarray:
        return loss_to_score(
            loss,
            complexity,
            use_baseline=self.dataset.use_baseline,
            baseline=self.dataset.baseline_loss,
            parsimony=self.options.parsimony,
        )

    def score_trees(
        self, trees: list[Node], complexities, idx: np.ndarray | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """(scores, losses) for a batch of trees."""
        losses = self.loss_many(trees, idx=idx)
        scores = self.score_of(losses, np.asarray(complexities))
        return scores, losses
