"""Island population + tournament selection.

Reference: /root/reference/src/Population.jl. Tournament: sample
``tournament_selection_n`` members without replacement, adjust scores by
``exp(adaptive_parsimony_scaling * frequency(size))``, then pick the k-th best
with geometric probability p(1-p)^k using the precomputed weights
(/root/reference/src/Population.jl:110-160).
"""

from __future__ import annotations

import numpy as np

from .adaptive_parsimony import RunningSearchStatistics
from .mutation_functions import gen_random_tree
from .pop_member import PopMember

__all__ = ["Population"]


class Population:
    def __init__(self, members: list[PopMember]):
        self.members = members

    @property
    def n(self) -> int:
        return len(self.members)

    def copy(self) -> "Population":
        return Population([m.copy() for m in self.members])

    @staticmethod
    def random_trees(
        population_size: int, options, nfeatures: int, rng: np.random.Generator, nlength: int = 3
    ):
        """The random initial trees of a population (scored by the caller in
        one device batch; reference inits with nlength=3,
        /root/reference/src/Population.jl:36-62)."""
        return [
            gen_random_tree(
                nlength, options.operators, nfeatures, rng, dtype=options.dtype
            )
            for _ in range(population_size)
        ]

    def sample_members(
        self, n: int, rng: np.random.Generator
    ) -> list[PopMember]:
        idx = rng.choice(self.n, size=min(n, self.n), replace=False)
        return [self.members[i] for i in idx]

    def best_of_sample(
        self,
        stats: RunningSearchStatistics,
        options,
        rng: np.random.Generator,
    ) -> PopMember:
        sample = self.sample_members(options.tournament_selection_n, rng)
        scores = np.empty(len(sample))
        if options.use_frequency_in_tournament:
            scaling = options.adaptive_parsimony_scaling
            for i, m in enumerate(sample):
                freq = stats.frequency_of(m.get_complexity(options))
                scores[i] = m.score * np.exp(scaling * freq)
        else:
            for i, m in enumerate(sample):
                scores[i] = m.score
        p = options.tournament_selection_p
        if p == 1.0:
            return sample[int(np.argmin(scores))]
        w = options.tournament_weights[: len(sample)]
        place = rng.choice(len(w), p=w / w.sum())
        order = np.argsort(scores, kind="stable")
        return sample[int(order[place])]

    def best_sub_pop(self, topn: int = 10) -> "Population":
        """Top-n members by score (migration candidates; reference:
        /root/reference/src/Population.jl:179-182)."""
        order = sorted(range(self.n), key=lambda i: self.members[i].score)
        return Population([self.members[i] for i in order[:topn]])

    def oldest_index(self) -> int:
        """argmin birth — regularized evolution replaces the oldest
        (/root/reference/src/RegularizedEvolution.jl:53,85)."""
        return min(range(self.n), key=lambda i: self.members[i].birth)

    def record(self, options) -> dict:
        """Snapshot for the recorder (reference: record_population,
        /root/reference/src/Population.jl:184-199)."""
        return {
            "population": [
                {
                    "id": m.ref,
                    "parent": m.parent,
                    "score": m.score,
                    "loss": m.loss,
                    "complexity": m.get_complexity(options),
                    "birth": m.birth,
                    "tree": m.tree.string_tree(options.operators),
                }
                for m in self.members
            ]
        }
