"""Algebraic simplification of trees (host side).

Role-equivalent of DynamicExpressions' ``simplify_tree!`` + ``combine_operators``
as used by the reference's optimize_and_simplify_population
(/root/reference/src/SingleIteration.jl:107-132): constant folding plus
combining of constant operands through nested +,-,*,/ chains. Operates on
operator *names* so it works for any OperatorSet that includes the arithmetic
ops; unknown operators are left untouched.
"""

from __future__ import annotations

import cmath
import math

from ..ops.operators import COMPLEX_SCALAR_IMPLS, scalar_impl
from ..tree import Node, constant

__all__ = ["simplify_tree", "combine_operators"]


def _scalar_apply(op, *args):
    """Pure-host scalar application — never dispatches to the device (a
    single-scalar device round trip costs more than the whole fold).
    Complex constants fold through cmath counterparts."""
    if any(isinstance(a, complex) for a in args):
        fn = COMPLEX_SCALAR_IMPLS.get(op.name)
        if fn is None:
            return complex("nan")  # unfoldable: caller keeps the subtree
        try:
            return complex(fn(*[complex(a) for a in args]))
        except (ValueError, OverflowError, ZeroDivisionError):
            return complex("nan")
    try:
        return float(scalar_impl(op)(*[float(a) for a in args]))
    except (ValueError, OverflowError, ZeroDivisionError):
        return float("nan")


def _finite(v) -> bool:
    return cmath.isfinite(v) if isinstance(v, complex) else math.isfinite(v)


def simplify_tree(tree: Node, options) -> Node:
    """Bottom-up constant folding: any operator whose children are all
    constants becomes a constant (kept only when finite)."""
    ops = options.operators
    for n in tree.postorder():
        if n.degree == 1 and n.l.degree == 0 and n.l.is_const:
            v = _scalar_apply(ops.unary[n.op], n.l.val)
            if _finite(v):
                _to_const(n, v)
        elif (
            n.degree == 2
            and n.l.degree == 0
            and n.l.is_const
            and n.r.degree == 0
            and n.r.is_const
        ):
            v = _scalar_apply(ops.binary[n.op], n.l.val, n.r.val)
            if _finite(v):
                _to_const(n, v)
    return tree


def _to_const(n: Node, v: float) -> None:
    n.degree = 0
    n.is_const = True
    n.val = v
    n.feat = 0
    n.op = 0
    n.l = None
    n.r = None


def _op_name(options, idx: int) -> str:
    return options.operators.binary[idx].name


def combine_operators(tree: Node, options) -> Node:
    """Combine constants through nested chains of the same +,* operator and
    through +/- and */ mixed chains: e.g. (c1 + (x + c2)) -> (x + c3),
    (c1 * (c2 * x)) -> (c3 * x), (x - c1) + c2 -> x + c3."""
    changed = True
    guard = 0
    while changed and guard < 10:
        changed = _combine_pass(tree, options)
        guard += 1
    return tree


def _is_const(n: Node) -> bool:
    return n.degree == 0 and n.is_const


def _combine_pass(tree: Node, options) -> bool:
    changed = False
    for n in tree.postorder():
        if n.degree != 2:
            continue
        name = _op_name(options, n.op)
        if name in ("add", "mult"):
            changed |= _combine_assoc(n, name, options)
        elif name == "sub":
            changed |= _combine_sub(n, options)
    return changed


def _combine_assoc(n: Node, name: str, options) -> bool:
    """(c1 op inner) where inner = (c2 op x) | (x op c2) -> (c3 op x)."""
    for const_side, tree_side in (("l", "r"), ("r", "l")):
        c = getattr(n, const_side)
        sub = getattr(n, tree_side)
        if not _is_const(c) or sub.degree != 2 or sub.op != n.op:
            continue
        for inner_const_side, inner_tree_side in (("l", "r"), ("r", "l")):
            ic = getattr(sub, inner_const_side)
            if _is_const(ic):
                merged = c.val + ic.val if name == "add" else c.val * ic.val
                x = getattr(sub, inner_tree_side)
                n.l = constant(merged)
                n.r = x
                return True
    return False


def _combine_sub(n: Node, options) -> bool:
    """Collapse constant chains through subtraction:
    (c1 - (c2 - x)) -> (x + c3) form kept as (c3' - (0 - x))? We keep it
    simple and only fold the pure-constant-with-sub-chain cases:
      (c1 - (x - c2)) -> (c3 - x) with c3 = c1 + c2
      (c1 - (c2 - x)) -> ((c1-c2) + x) when `add` is available
      ((x - c1) - c2) -> (x - c3)
      ((c1 - x) - c2) -> (c3 - x)
    """
    ops = options.operators
    try:
        add_idx = ops.binary_index("add")
    except KeyError:
        add_idx = None
    sub_idx = n.op

    l, r = n.l, n.r
    if _is_const(l) and r.degree == 2 and _op_name(options, r.op) == "sub":
        if _is_const(r.r):  # c1 - (x - c2)
            n.l = constant(l.val + r.r.val)
            n.r = r.l
            n.op = sub_idx
            return True
        if _is_const(r.l) and add_idx is not None:  # c1 - (c2 - x)
            n.op = add_idx
            n.l = constant(l.val - r.l.val)
            n.r = r.r
            return True
    if _is_const(r) and l.degree == 2 and _op_name(options, l.op) == "sub":
        if _is_const(l.r):  # (x - c1) - c2
            n.l = l.l
            n.r = constant(l.r.val + r.val)
            return True
        if _is_const(l.l):  # (c1 - x) - c2
            n.l = constant(l.l.val - r.val)
            n.r = l.r
            return True
    return False
