"""Regularized evolution: tournament -> mutate/crossover -> replace oldest.

Reference: /root/reference/src/RegularizedEvolution.jl:14-109. One evolve pass
runs ``ceil(pop.n / tournament_selection_n)`` events; each event either
mutates a tournament winner (probability 1 - crossover_probability) replacing
the oldest member, or crosses two winners replacing the two oldest.

TPU restructuring: the pass is split into propose / score / apply so the
scoring of all events (across all islands — see single_iteration) happens in
one device batch.
"""

from __future__ import annotations

import math

import numpy as np

from .adaptive_parsimony import RunningSearchStatistics
from .mutate import (
    CrossoverProposal,
    Proposal,
    accept_crossover,
    accept_mutation,
    propose_crossover,
    propose_mutation,
)
from .population import Population

__all__ = ["propose_pass", "collect_candidates", "apply_pass"]


def propose_pass(
    pop: Population,
    temperature: float,
    curmaxsize: int,
    stats: RunningSearchStatistics,
    options,
    nfeatures: int,
    rng: np.random.Generator,
) -> list:
    """Generate one evolve pass worth of events from the current snapshot."""
    n_evol = int(math.ceil(pop.n / options.tournament_selection_n))
    events = []
    for _ in range(n_evol):
        if rng.random() > options.crossover_probability:
            parent = pop.best_of_sample(stats, options, rng)
            events.append(
                propose_mutation(parent, temperature, curmaxsize, options, nfeatures, rng)
            )
        else:
            p1 = pop.best_of_sample(stats, options, rng)
            p2 = pop.best_of_sample(stats, options, rng)
            events.append(propose_crossover(p1, p2, curmaxsize, options, rng))
    return events


def collect_candidates(events: list) -> list:
    """Trees awaiting scoring, in deterministic order."""
    trees = []
    for ev in events:
        if isinstance(ev, Proposal):
            if ev.needs_score and not ev.failed:
                trees.append(ev.tree)
        elif isinstance(ev, CrossoverProposal):
            if not ev.failed:
                trees.append(ev.child1)
                trees.append(ev.child2)
    return trees


def fill_scores(events: list, scores: np.ndarray, losses: np.ndarray) -> None:
    """Write batch-computed scores back into the events (same order as
    collect_candidates)."""
    k = 0
    for ev in events:
        if isinstance(ev, Proposal):
            if ev.needs_score and not ev.failed:
                ev.score, ev.loss = float(scores[k]), float(losses[k])
                k += 1
        elif isinstance(ev, CrossoverProposal):
            if not ev.failed:
                ev.score1, ev.loss1 = float(scores[k]), float(losses[k])
                ev.score2, ev.loss2 = float(scores[k + 1]), float(losses[k + 1])
                k += 2


def apply_pass(
    pop: Population,
    events: list,
    temperature: float,
    stats: RunningSearchStatistics,
    options,
    rng: np.random.Generator,
    recorder=None,
) -> list:
    """Accept/reject each scored event and replace oldest members.
    Returns the list of newly inserted members. With a recorder, logs
    mutate events on the winner's lineage and death events for replaced
    members (reference: /root/reference/src/RegularizedEvolution.jl:55-83)."""
    new_members = []
    for ev in events:
        if isinstance(ev, Proposal):
            baby, accepted = accept_mutation(ev, temperature, stats, options, rng)
            oldest = pop.oldest_index()
            if recorder is not None:
                recorder.record_mutation(ev.parent, baby, ev.kind, accepted, options)
                recorder.record_death(pop.members[oldest], options)
            pop.members[oldest] = baby
            new_members.append(baby)
        else:
            c1, c2, _accepted = accept_crossover(ev, options)
            pop.members[pop.oldest_index()] = c1
            pop.members[pop.oldest_index()] = c2
            new_members.append(c1)
            new_members.append(c2)
    return new_members
