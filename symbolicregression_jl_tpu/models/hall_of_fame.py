"""Complexity-indexed hall of fame + Pareto frontier.

Reference: /root/reference/src/HallOfFame.jl — ``members[c]`` holds the best
member seen at complexity ``c``; the search output is the Pareto frontier
(member dominates iff its loss beats every lower-complexity member), and the
reported "score" along the frontier is ``-Δlog(loss)/Δcomplexity``.
"""

from __future__ import annotations

import math

import numpy as np

from .pop_member import PopMember

__all__ = ["HallOfFame"]


class HallOfFame:
    def __init__(self, maxsize: int):
        # capacity maxsize + 2, matching members[1:maxsize+MAX_DEGREE]
        # (/root/reference/src/HallOfFame.jl:45-63)
        self.capacity = maxsize + 2
        self.members: list[PopMember | None] = [None] * self.capacity
        self.exists = [False] * self.capacity

    def copy(self) -> "HallOfFame":
        new = HallOfFame.__new__(HallOfFame)
        new.capacity = self.capacity
        new.members = [m.copy() if m is not None else None for m in self.members]
        new.exists = list(self.exists)
        return new

    def update(self, member: PopMember, options) -> bool:
        """Insert if best-at-its-complexity (reference: update_hall_of_fame!,
        /root/reference/src/SearchUtils.jl:513-529). Returns True if inserted.

        Non-finite losses never enter: a NaN occupant would permanently block
        its slot (`finite < nan` is False) and inf members would pollute the
        returned frontier and warm-start state."""
        if not np.isfinite(member.loss):
            return False
        size = member.get_complexity(options)
        if not (0 < size <= self.capacity):
            return False
        i = size - 1
        if not self.exists[i] or member.loss < self.members[i].loss:
            self.members[i] = member.copy()
            self.exists[i] = True
            return True
        return False

    def update_many(self, members, options) -> int:
        return sum(self.update(m, options) for m in members)

    def merge(self, other: "HallOfFame", options) -> None:
        for m, e in zip(other.members, other.exists):
            if e:
                self.update(m, options)

    def pareto_frontier(self) -> list[PopMember]:
        """Members whose loss beats every smaller-complexity member
        (reference: calculate_pareto_frontier, /root/reference/src/HallOfFame.jl:74-103)."""
        out: list[PopMember] = []
        best = math.inf
        for m, e in zip(self.members, self.exists):
            if not e:
                continue
            if m.loss < best:
                out.append(m)
                best = m.loss
        return out

    def format(self, options, variable_names=None, precision=None) -> list[dict]:
        """Frontier rows with the -dlog(loss)/dcomplexity score
        (reference: format_hall_of_fame, /root/reference/src/HallOfFame.jl:155-198).
        ``precision``: constant digits (default options.print_precision; the
        CSV writer passes 17 so checkpoints round-trip float64 exactly)."""
        frontier = self.pareto_frontier()
        rows = []
        prev_loss, prev_c = None, None
        ZERO = 1e-38
        for m in frontier:
            c = m.complexity
            loss = m.loss
            if prev_loss is None:
                score = 0.0
            else:
                dc = c - prev_c
                if dc <= 0 or not (math.isfinite(loss) and loss >= 0):
                    score = 0.0
                else:
                    score = -(
                        math.log(max(loss, ZERO)) - math.log(max(prev_loss, ZERO))
                    ) / dc
            rows.append(
                {
                    "complexity": c,
                    "loss": loss,
                    "score": max(score, 0.0),
                    "equation": m.tree.string_tree(
                        options.operators,
                        variable_names,
                        precision=(
                            precision
                            if precision is not None
                            else options.print_precision
                        ),
                    ),
                    "member": m,
                }
            )
            prev_loss, prev_c = loss, c
        return rows

    def render(self, options, variable_names=None, y_variable_name=None) -> str:
        """Terminal table (reference: string_dominating_pareto_curve,
        /root/reference/src/HallOfFame.jl:105-153). Equations are prefixed
        ``<y_variable_name> = `` like the reference's live Pareto table
        (/root/reference/src/HallOfFame.jl:128-134)."""
        rows = self.format(options, variable_names)
        prefix = f"{y_variable_name} = " if y_variable_name else ""
        lines = [
            "-" * 72,
            f"{'Complexity':<12}{'Loss':<14}{'Score':<14}Equation",
        ]
        for r in rows:
            lines.append(
                f"{r['complexity']:<12}{r['loss']:<14.6g}{r['score']:<14.6g}"
                f"{prefix}{r['equation']}"
            )
        lines.append("-" * 72)
        return "\n".join(lines)

    def best(self) -> PopMember | None:
        frontier = self.pareto_frontier()
        if not frontier:
            return None
        return min(frontier, key=lambda m: m.loss)
