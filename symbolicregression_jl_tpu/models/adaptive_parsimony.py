"""Adaptive complexity-frequency parsimony statistics.

Reference: RunningSearchStatistics (/root/reference/src/AdaptiveParsimony.jl):
a per-complexity frequency histogram with a decaying window, used to bias
tournaments and mutation acceptance toward under-represented complexities.
"""

from __future__ import annotations

import numpy as np

__all__ = ["RunningSearchStatistics"]


class RunningSearchStatistics:
    def __init__(self, maxsize: int, window_size: int = 100000):
        self.window_size = window_size
        # index c-1 holds complexity c, for c in 1..maxsize
        # (reference inits all-ones, /root/reference/src/AdaptiveParsimony.jl:20-34)
        self.frequencies = np.ones(maxsize, dtype=np.float64)
        self.normalized_frequencies = self.frequencies / self.frequencies.sum()

    def copy(self) -> "RunningSearchStatistics":
        new = RunningSearchStatistics.__new__(RunningSearchStatistics)
        new.window_size = self.window_size
        new.frequencies = self.frequencies.copy()
        new.normalized_frequencies = self.normalized_frequencies.copy()
        return new

    def update(self, size: int) -> None:
        """Record an accepted member's complexity
        (reference: update_frequencies!, :42-49)."""
        if 0 < size <= len(self.frequencies):
            self.frequencies[size - 1] += 1.0

    def move_window(self) -> None:
        """Decay total mass back to window_size, preferring to remove from
        over-represented sizes (reference: move_window!, :57-89 — proportional
        smoothing variant)."""
        total = self.frequencies.sum()
        if total > self.window_size:
            self.frequencies *= self.window_size / total

    def normalize(self) -> None:
        """(reference: normalize_frequencies!, :91-95)"""
        total = self.frequencies.sum()
        if total > 0:
            self.normalized_frequencies = self.frequencies / total

    def frequency_of(self, size: int) -> float:
        if 0 < size <= len(self.normalized_frequencies):
            return float(self.normalized_frequencies[size - 1])
        return 0.0
