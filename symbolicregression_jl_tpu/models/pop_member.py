"""Population member: tree + score + loss + lineage.

Reference: PopMember (/root/reference/src/PopMember.jl:12-37): tree, score
(parsimony-adjusted), raw loss, birth order, cached complexity (invalidated on
tree replacement), and ref/parent lineage ids for the recorder.
"""

from __future__ import annotations

import threading

from ..complexity import compute_complexity
from ..tree import Node

__all__ = [
    "PopMember",
    "generate_reference",
    "counter_state",
    "restore_counter_state",
]


class _Counter:
    """Monotone id source. Thread-safe (the async island scheduler creates
    members from worker threads) and — unlike itertools.count — queryable and
    settable, which SearchCheckpointer needs: birth order drives
    ``Population.oldest_index`` replacement, so a bit-exact resume must
    restore the counters along with the populations."""

    __slots__ = ("_value", "_lock")

    def __init__(self, start: int = 1):
        self._value = start
        self._lock = threading.Lock()

    def __next__(self) -> int:
        with self._lock:
            v = self._value
            self._value = v + 1
        return v

    def peek(self) -> int:
        return self._value

    def set(self, value: int) -> None:
        with self._lock:
            self._value = int(value)


_ref_counter = _Counter()
_birth_counter = _Counter()


def generate_reference() -> int:
    return next(_ref_counter)


def next_birth() -> int:
    """Deterministic monotone birth counter. The reference uses wall-clock
    time in non-deterministic mode (/root/reference/src/Utils.jl:7-19); a
    counter gives identical ordering semantics and is always deterministic."""
    return next(_birth_counter)


def counter_state() -> tuple[int, int]:
    """(next ref, next birth) — captured by full-state checkpoints."""
    return (_ref_counter.peek(), _birth_counter.peek())


def restore_counter_state(state) -> None:
    """Restore the counters from ``counter_state()`` (bit-exact resume)."""
    ref, birth = state
    _ref_counter.set(ref)
    _birth_counter.set(birth)


class PopMember:
    __slots__ = ("tree", "score", "loss", "birth", "complexity", "ref", "parent")

    def __init__(
        self,
        tree: Node,
        score: float,
        loss: float,
        complexity: int | None = None,
        ref: int | None = None,
        parent: int = -1,
    ):
        self.tree = tree
        self.score = float(score)
        self.loss = float(loss)
        self.birth = next_birth()
        self.complexity = complexity
        self.ref = generate_reference() if ref is None else ref
        self.parent = parent

    def copy(self) -> "PopMember":
        new = PopMember.__new__(PopMember)
        new.tree = self.tree.copy()
        new.score = self.score
        new.loss = self.loss
        new.birth = self.birth
        new.complexity = self.complexity
        new.ref = self.ref
        new.parent = self.parent
        return new

    def set_tree(self, tree: Node) -> None:
        """Replace the tree, invalidating the cached complexity (the reference
        enforces this with a setproperty! guard, /root/reference/src/PopMember.jl:23-35)."""
        self.tree = tree
        self.complexity = None

    def get_complexity(self, options) -> int:
        if self.complexity is None:
            self.complexity = compute_complexity(self.tree, options)
        return self.complexity

    def reset_birth(self) -> None:
        self.birth = next_birth()

    def __repr__(self):
        return (
            f"PopMember(loss={self.loss:.4g}, score={self.score:.4g}, "
            f"complexity={self.complexity}, birth={self.birth})"
        )


def scored_member(tree: Node, score, loss, options, parent: int = -1) -> PopMember:
    m = PopMember(tree, score, loss, parent=parent)
    m.get_complexity(options)
    return m
