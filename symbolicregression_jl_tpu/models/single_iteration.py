"""One search iteration over a set of island populations, in lockstep.

Reference: s_r_cycle + optimize_and_simplify_population
(/root/reference/src/SingleIteration.jl:24-174). The reference runs each
population's ``ncycles_per_iteration`` evolve cycles independently (async
tasks); the TPU-native design steps ALL islands together so that every cycle's
candidate scoring — and the end-of-iteration constant optimization — is one
large batched device program (islands x events candidates per call).

Temperature anneals 1 -> 0 across the cycles when annealing is on, else stays
1 (/root/reference/src/SingleIteration.jl:36-62).
"""

from __future__ import annotations

import numpy as np

from ..complexity import compute_complexity
from ..ops.constant_opt import optimize_constants_batched
from .adaptive_parsimony import RunningSearchStatistics
from .hall_of_fame import HallOfFame
from .mutate import Proposal
from .population import Population
from .regularized_evolution import (
    apply_pass,
    collect_candidates,
    fill_scores,
    propose_pass,
)
from .scorer import BatchScorer
from .simplify import combine_operators, simplify_tree

__all__ = ["s_r_cycle_lockstep", "optimize_and_simplify_populations"]


def s_r_cycle_lockstep(
    pops: list[Population],
    scorer: BatchScorer,
    ncycles: int,
    curmaxsize: int,
    stats_list: list[RunningSearchStatistics],
    options,
    nfeatures: int,
    rng: np.random.Generator,
    pipeline_depth: int = 4,
    recorder=None,
) -> list[HallOfFame]:
    """Run `ncycles` evolve passes on every island; returns per-island
    best-seen halls of fame (the reference's `return_best_seen` path).

    Latency-hiding pipeline: each cycle's candidate batch is dispatched to the
    device asynchronously and its accept/apply step runs `pipeline_depth`
    cycles later, so device compute and host<->device readback overlap with
    host-side evolution. Proposals therefore see a population that is up to
    `pipeline_depth` cycles stale — the same kind of staleness the reference's
    fully-async islands already embrace (migration reads "whatever snapshot is
    current", /root/reference/src/SymbolicRegression.jl:933-943). With
    pipeline_depth=1 the behaviour is the strict lockstep sequence.
    """
    best_seen = [HallOfFame(options.maxsize) for _ in pops]

    if options.annealing and ncycles > 1:
        temperatures = np.linspace(1.0, 0.0, ncycles)
    else:
        temperatures = np.ones(ncycles)
    if options.deterministic:
        pipeline_depth = max(1, pipeline_depth)  # deterministic for fixed depth

    for s in stats_list:
        s.normalize()
    for bs, pop in zip(best_seen, pops):
        bs.update_many(pop.members, options)

    in_flight: list[tuple] = []  # (all_events, offsets, materialize_fn, T)

    def drain_one():
        all_events, offsets, materialize, T = in_flight.pop(0)
        losses = materialize()
        comps = np.array(
            [compute_complexity(t, options) for ev_trees in offsets for t in ev_trees[2]]
        )
        scores = scorer.score_of(losses, comps) if len(losses) else losses
        for (start, count, _trees), events, pop, stats, bs in zip(
            offsets, all_events, pops, stats_list, best_seen
        ):
            fill_scores(
                events, scores[start : start + count], losses[start : start + count]
            )
            new_members = apply_pass(pop, events, T, stats, options, rng, recorder)
            # best-seen update: newly inserted members may set a
            # per-complexity record (reference tracks best_seen during the
            # cycle, /root/reference/src/SingleIteration.jl:42-101)
            bs.update_many(new_members, options)

    for cycle in range(ncycles):
        T = float(temperatures[cycle])
        all_events = [
            propose_pass(pop, T, curmaxsize, stats, options, nfeatures, rng)
            for pop, stats in zip(pops, stats_list)
        ]
        # "optimize" mutations run the batched constant optimizer on their
        # trees before scoring (reference runs Optim inline per member,
        # /root/reference/src/Mutate.jl optimize branch; default weight 0).
        opt_props = [
            ev
            for events in all_events
            for ev in events
            if isinstance(ev, Proposal) and ev.kind == "optimize" and not ev.failed
        ]
        if opt_props:
            new_trees, _, _ = optimize_constants_batched(
                [ev.tree for ev in opt_props], scorer, options, rng,
                idx=scorer.batch_indices(rng),
            )
            for ev, tree in zip(opt_props, new_trees):
                ev.tree = tree
        # ONE async device dispatch for every candidate of every island.
        trees = []
        offsets = []
        for events in all_events:
            cand = collect_candidates(events)
            offsets.append((len(trees), len(cand), cand))
            trees.extend(cand)
        idx = scorer.batch_indices(rng)
        materialize = scorer.loss_many_async(trees, idx=idx)
        in_flight.append((all_events, offsets, materialize, T))
        if len(in_flight) >= pipeline_depth:
            drain_one()

    while in_flight:
        drain_one()

    return best_seen


def optimize_and_simplify_populations(
    pops: list[Population],
    scorer: BatchScorer,
    options,
    rng: np.random.Generator,
    recorder=None,
) -> None:
    """Simplify every member, then constant-optimize a
    `optimizer_probability` subset — batched across all islands — then
    restore exact scores (reference: optimize_and_simplify_population,
    /root/reference/src/SingleIteration.jl:107-174)."""
    # 1) simplify (semantics-preserving; keeps stored scores, like the
    #    reference which only re-scores after optimization)
    if options.should_simplify:
        for pop in pops:
            for m in pop.members:
                tree = simplify_tree(m.tree, options)
                tree = combine_operators(tree, options)
                m.set_tree(tree)
                m.get_complexity(options)

    # 2) batched constant optimization
    if options.should_optimize_constants:
        selected = []  # (pop, member_index)
        for pop in pops:
            for k, m in enumerate(pop.members):
                if m.tree.has_constants() and rng.random() < options.optimizer_probability:
                    selected.append((pop, k))
        if selected:
            trees = [pop.members[k].tree for pop, k in selected]
            idx = scorer.batch_indices(rng)
            new_trees, losses, improved = optimize_constants_batched(
                trees, scorer, options, rng, idx=idx
            )
            # re-apply dimensional regularization: the optimizer's device
            # losses are raw elementwise losses
            losses = scorer.apply_units_penalty(new_trees, losses)
            comps = [compute_complexity(t, options) for t in new_trees]
            scores = scorer.score_of(losses, np.asarray(comps))
            for (pop, k), tree, loss, score, imp in zip(
                selected, new_trees, losses, scores, improved
            ):
                m = pop.members[k]
                if imp:
                    m.set_tree(tree)
                    m.loss = float(loss)
                    m.score = float(score)
                    m.get_complexity(options)
                    m.reset_birth()
                if recorder is not None:
                    # constant-opt "tuning" events
                    # (reference: SingleIteration.jl:140-171)
                    recorder.record_tuning(m, bool(imp), options)

    # 3) finalize: full-data rescore when batching (reference: finalize_scores,
    #    /root/reference/src/Population.jl:162-176)
    if options.batching:
        all_members = [m for pop in pops for m in pop.members]
        trees = [m.tree for m in all_members]
        comps = [m.get_complexity(options) for m in all_members]
        scores, losses = scorer.score_trees(trees, comps, idx=None)
        for m, s, l in zip(all_members, scores, losses):
            m.score = float(s)
            m.loss = float(l)
