"""Tree-rewrite primitives (host side).

Role-equivalent to the reference's MutationFunctions
(/root/reference/src/MutationFunctions.jl:34-303). All functions are
RNG-parameterized (numpy Generator) and operate in place on trees the caller
has already copied — mirroring the reference's copy-then-mutate discipline in
next_generation. Evolution stays on the host by design (SURVEY.md §7.1):
these are cheap, irregular pointer edits; only *scoring* goes to the TPU.
"""

from __future__ import annotations

import numpy as np

from ..tree import Node, constant, feature
from ..ops.operators import OperatorSet

__all__ = [
    "swap_operands",
    "mutate_operator",
    "mutate_constant",
    "append_random_op",
    "insert_random_op",
    "prepend_random_op",
    "make_random_leaf",
    "delete_random_op",
    "gen_random_tree",
    "gen_random_tree_fixed_size",
    "crossover_trees",
    "random_node",
]


def _nodes(tree: Node, pred=None) -> list[Node]:
    out = [n for n in tree]
    if pred is not None:
        out = [n for n in out if pred(n)]
    return out


def random_node(tree: Node, rng: np.random.Generator, pred=None) -> Node | None:
    cands = _nodes(tree, pred)
    if not cands:
        return None
    return cands[rng.integers(len(cands))]


def _set_node(dst: Node, src: Node) -> None:
    dst.degree = src.degree
    dst.is_const = src.is_const
    dst.val = src.val
    dst.feat = src.feat
    dst.op = src.op
    dst.l = src.l
    dst.r = src.r


def swap_operands(tree: Node, rng: np.random.Generator) -> Node:
    node = random_node(tree, rng, lambda t: t.degree == 2)
    if node is None:
        return tree
    node.l, node.r = node.r, node.l
    return tree


def mutate_operator(tree: Node, opset: OperatorSet, rng: np.random.Generator) -> Node:
    node = random_node(tree, rng, lambda t: t.degree != 0)
    if node is None:
        return tree
    if node.degree == 1:
        node.op = int(rng.integers(opset.n_unary))
    else:
        node.op = int(rng.integers(opset.n_binary))
    return tree


def mutate_constant(
    tree: Node, temperature: float, options, rng: np.random.Generator
) -> Node:
    """Multiply or divide a random constant by `maxChange^U(0,1)`, and negate
    with probability `probability_negate_constant`.

    Reference: /root/reference/src/MutationFunctions.jl:60-89. NOTE: v0.24.5
    negates when `rand() > p_negate` (i.e. 99% of the time at the default
    0.01) — an upstream sign bug fixed in later releases; we implement the
    intended semantics (negate with probability p_negate).
    """
    node = random_node(tree, rng, lambda t: t.degree == 0 and t.is_const)
    if node is None:
        return tree
    max_change = options.perturbation_factor * temperature + 1.0 + 0.1
    if isinstance(node.val, complex) or np.dtype(options.dtype).kind == "c":
        # complex exponent rotates the phase as well as scaling the
        # magnitude — the reference's `maxChange^rand(rng, T)` draws a
        # complex uniform for complex T (MutationFunctions.jl:70), and
        # without rotation a constant's phase could only ever be negated
        factor = complex(max_change ** complex(rng.random(), rng.random()))
    else:
        factor = float(max_change ** rng.random())
    if rng.random() < 0.5:
        node.val *= factor
    else:
        node.val /= factor
    if rng.random() < options.probability_negate_constant:
        node.val *= -1.0
    return tree


def make_random_leaf(
    nfeatures: int, rng: np.random.Generator, dtype=None
) -> Node:
    """50/50 constant (randn) or random feature
    (reference: /root/reference/src/MutationFunctions.jl:167-175). For a
    complex compute dtype the constant is drawn on the complex plane —
    phase diversity has to enter through leaves, exactly as the reference's
    `randn(T)` draws complex normals."""
    if rng.random() < 0.5:
        if dtype is not None and np.dtype(dtype).kind == "c":
            return constant(
                complex(rng.standard_normal(), rng.standard_normal())
            )
        return constant(float(rng.standard_normal()))
    return feature(int(rng.integers(nfeatures)))


def _random_new_op_node(
    opset: OperatorSet,
    nfeatures: int,
    rng: np.random.Generator,
    child: Node,
    make_bin: bool | None = None,
    dtype=None,
) -> Node:
    if make_bin is None:
        total = opset.n_binary + opset.n_unary
        make_bin = rng.random() < opset.n_binary / total
    if make_bin:
        new = Node(
            2,
            op=int(rng.integers(opset.n_binary)),
            l=child,
            r=make_random_leaf(nfeatures, rng, dtype),
        )
    else:
        new = Node(1, op=int(rng.integers(opset.n_unary)), l=child)
    return new


def append_random_op(
    tree: Node,
    opset: OperatorSet,
    nfeatures: int,
    rng: np.random.Generator,
    make_bin: bool | None = None,
    dtype=None,
) -> Node:
    """Replace a random leaf by a random operator over fresh random leaves
    (reference: /root/reference/src/MutationFunctions.jl:92-121)."""
    node = random_node(tree, rng, lambda t: t.degree == 0)
    if make_bin is None:
        total = opset.n_binary + opset.n_unary
        make_bin = rng.random() < opset.n_binary / total
    if make_bin:
        new = Node(
            2,
            op=int(rng.integers(opset.n_binary)),
            l=make_random_leaf(nfeatures, rng, dtype),
            r=make_random_leaf(nfeatures, rng, dtype),
        )
    else:
        new = Node(
            1, op=int(rng.integers(opset.n_unary)),
            l=make_random_leaf(nfeatures, rng, dtype),
        )
    _set_node(node, new)
    return tree


def insert_random_op(
    tree: Node, opset: OperatorSet, nfeatures: int, rng: np.random.Generator,
    dtype=None,
) -> Node:
    """Wrap a random node in a new random operator
    (reference: /root/reference/src/MutationFunctions.jl:124-143)."""
    node = random_node(tree, rng)
    new = _random_new_op_node(opset, nfeatures, rng, node.copy(), dtype=dtype)
    _set_node(node, new)
    return tree


def prepend_random_op(
    tree: Node, opset: OperatorSet, nfeatures: int, rng: np.random.Generator,
    dtype=None,
) -> Node:
    """Wrap the root in a new random operator
    (reference: /root/reference/src/MutationFunctions.jl:146-165)."""
    new = _random_new_op_node(opset, nfeatures, rng, tree.copy(), dtype=dtype)
    _set_node(tree, new)
    return tree


def _random_node_and_parent(tree: Node, rng: np.random.Generator):
    """(node, parent, side); side 'n' when node is the root
    (reference: /root/reference/src/MutationFunctions.jl:178-188)."""
    if tree.degree == 0:
        return tree, tree, "n"
    parent = random_node(tree, rng, lambda t: t.degree != 0)
    if parent.degree == 1 or rng.random() < 0.5:
        return parent.l, parent, "l"
    return parent.r, parent, "r"


def delete_random_op(
    tree: Node, opset: OperatorSet, nfeatures: int, rng: np.random.Generator,
    dtype=None,
) -> Node:
    """Splice a random node out of the tree
    (reference: /root/reference/src/MutationFunctions.jl:191-234)."""
    node, parent, side = _random_node_and_parent(tree, rng)
    if node.degree == 0:
        _set_node(node, make_random_leaf(nfeatures, rng, dtype))
        return tree
    keep = node.l if (node.degree == 1 or rng.random() < 0.5) else node.r
    if side == "n":
        return keep
    if side == "l":
        parent.l = keep
    else:
        parent.r = keep
    return tree


def gen_random_tree(
    length: int, opset: OperatorSet, nfeatures: int, rng: np.random.Generator,
    dtype=None,
) -> Node:
    """Grow by repeatedly appending random ops — may exceed `length` nodes,
    like the reference (/root/reference/src/MutationFunctions.jl:237-248)."""
    tree = constant(1.0)
    for _ in range(length):
        tree = append_random_op(tree, opset, nfeatures, rng, dtype=dtype)
    return tree


def gen_random_tree_fixed_size(
    node_count: int, opset: OperatorSet, nfeatures: int, rng: np.random.Generator,
    dtype=None,
) -> Node:
    """Grow to exactly node_count nodes when possible
    (reference: /root/reference/src/MutationFunctions.jl:250-268)."""
    tree = make_random_leaf(nfeatures, rng, dtype)
    cur = tree.count_nodes()
    while cur < node_count:
        if cur == node_count - 1:  # only a unary op fits
            if opset.n_unary == 0:
                break
            tree = append_random_op(
                tree, opset, nfeatures, rng, make_bin=False, dtype=dtype
            )
        else:
            tree = append_random_op(tree, opset, nfeatures, rng, dtype=dtype)
        cur = tree.count_nodes()
    return tree


def crossover_trees(
    a: Node, b: Node, rng: np.random.Generator, preserve_sharing: bool = False
) -> tuple[Node, Node]:
    """Swap random subtrees between copies of a and b
    (reference: /root/reference/src/MutationFunctions.jl:271-303)."""
    if preserve_sharing:
        a, b = a.copy_preserve_sharing(), b.copy_preserve_sharing()
    else:
        a, b = a.copy(), b.copy()
    na, pa, sa = _random_node_and_parent(a, rng)
    nb, pb, sb = _random_node_and_parent(b, rng)
    if preserve_sharing:
        na_copy = na.copy_preserve_sharing()
        nb_copy = nb.copy_preserve_sharing()
    else:
        na_copy = na.copy()
        nb_copy = nb.copy()
    if sa == "n":
        a = nb_copy
    elif sa == "l":
        pa.l = nb_copy
    else:
        pa.r = nb_copy
    if sb == "n":
        b = na_copy
    elif sb == "l":
        pb.l = na_copy
    else:
        pb.r = na_copy
    return a, b


# -- GraphNode-only mutations (shared-subtree DAGs) ---------------------------


def form_random_connection(tree: Node, rng: np.random.Generator) -> Node:
    """Make one node's child POINT at another subtree (shared reference),
    turning the tree into a DAG. No-op for tiny trees or when every candidate
    pair would form a loop (reference: form_random_connection!,
    /root/reference/src/MutationFunctions.jl:318-336)."""
    if tree.count_nodes() < 5:
        return tree
    parents = _nodes(tree, lambda t: t.degree >= 1)
    others = _nodes(tree)
    for _ in range(10):
        parent = parents[rng.integers(len(parents))]
        new_child = others[rng.integers(len(others))]
        # loop check: parent must not be reachable from new_child
        if new_child.contains(parent):
            continue
        if parent.degree == 1 or rng.random() < 0.5:
            parent.l = new_child
        else:
            parent.r = new_child
        return tree
    return tree


def break_random_connection(tree: Node, rng: np.random.Generator) -> Node:
    """Unshare one child by copying it (reference: break_random_connection!,
    /root/reference/src/MutationFunctions.jl:337-346)."""
    if tree.degree == 0:
        return tree
    parent = random_node(tree, rng, lambda t: t.degree >= 1)
    if parent is None:
        return tree
    if parent.degree == 1 or rng.random() < 0.5:
        parent.l = parent.l.copy_preserve_sharing()
    else:
        parent.r = parent.r.copy_preserve_sharing()
    return tree
