"""Migration between island populations.

Reference: /root/reference/src/Migration.jl:16-38 — Poisson-sample the number
of members to replace (mean = frac * pop size), draw candidates with
replacement, overwrite random members, reset birth.
"""

from __future__ import annotations

import numpy as np

from .pop_member import PopMember
from .population import Population

__all__ = ["migrate"]


def migrate(
    candidates: list[PopMember],
    pop: Population,
    options,
    frac: float,
    rng: np.random.Generator,
) -> None:
    if not candidates or frac <= 0:
        return
    mean = frac * pop.n
    num_replace = int(rng.poisson(mean))
    num_replace = min(num_replace, pop.n)
    if num_replace == 0:
        return
    locations = rng.choice(pop.n, size=num_replace, replace=False)
    picks = rng.integers(0, len(candidates), size=num_replace)
    for loc, pick in zip(locations, picks):
        migrant = candidates[pick].copy()
        migrant.reset_birth()
        pop.members[loc] = migrant
