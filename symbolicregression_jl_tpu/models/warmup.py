"""Default jit warmup: compile the hot programs before the timed loop.

The reference precompiles its full search workload at package build time
(/root/reference/src/precompile.jl:36-93). XLA programs are specialized on
array *shapes*, so the equivalent here is priming the scoring and
constant-optimization programs at the exact candidate-batch buckets the
first iteration will request — after this, iteration 1 runs at steady-state
speed instead of absorbing every compile.

Batch sizes are padded to power-of-two buckets (ops/flat.batch_bucket), so
the set to prime is small, predictable, and scheduler-dependent:
- lockstep batches all islands per cycle: I*e..2*I*e candidates (e =
  ceil(P / tournament_n) events per island; 1 candidate per mutation, 2 per
  crossover), P-tree island inits, I*P full rescores, and a
  ~optimizer_probability * I * P BFGS batch
- async runs each island separately: e..2*e candidates, P-tree inits, and
  a ~optimizer_probability * P BFGS batch

Warmup draws only from a PRIVATE generator — search trajectories are
identical with jit_warmup on or off.
"""

from __future__ import annotations

import numpy as np

from ..ops.flat import batch_bucket, bucket_sizes, length_buckets_enabled
from ..tree import Node, constant

__all__ = ["warmup_host_programs"]


def _chain_tree(n_nodes: int, opset) -> Node:
    """A valid tree with close to (and never more than) ``n_nodes`` nodes
    and at least one constant — sized to land in a given length bucket so
    warmup touches that bucket's compiled program."""
    t = constant(1.0)
    size = 1
    if opset.n_binary:
        while size + 2 <= n_nodes:
            t = Node(2, op=0, l=t, r=constant(1.0))
            size += 2
    elif opset.n_unary:
        while size + 1 <= n_nodes:
            t = Node(1, op=0, l=t)
            size += 1
    return t


def _bucket_mix(count: int, options) -> list[Node]:
    """``count`` warmup trees spread across the length buckets (equal split)
    so the bucketed dispatch compiles each node-bucket program up front.
    Best-effort: runtime per-bucket sub-batch sizes vary with the length
    distribution, so uncommon (bucket, batch) pairs may still compile lazily
    — the compile-count bound O(buckets x log P) holds regardless."""
    sizes = bucket_sizes(options.max_nodes)
    if not length_buckets_enabled() or len(sizes) == 1:
        return [constant(1.0)] * count
    trees = [
        _chain_tree(sizes[k % len(sizes)] - 1, options.operators)
        for k in range(count)
    ]
    return trees


def warmup_host_programs(scorer, options) -> None:
    wrng = np.random.default_rng(0)
    I, P = options.populations, options.population_size
    e = -(-P // options.tournament_selection_n)
    if options.scheduler == "async":
        score_sizes = (e, 2 * e, P)
        opt_n = max(1, int(round(P * options.optimizer_probability)))
    else:
        score_sizes = (I * e, 2 * I * e, P, I * P)
        opt_n = max(1, int(round(I * P * options.optimizer_probability)))
    buckets = sorted({batch_bucket(c) for c in score_sizes})
    saved_evals = scorer.num_evals
    idxs: list = [None]
    if options.batching:
        idxs.append(scorer.batch_indices(wrng))
    for b in buckets:
        for idx in idxs:
            scorer.loss_many(_bucket_mix(b, options), idx=idx)
    if options.should_optimize_constants and options.optimizer_probability > 0:
        from ..ops.constant_opt import optimize_constants_batched

        # mirror the search's actual call: under batching the optimizer runs
        # on a batch_size row subset (single_iteration.py passes
        # batch_indices) — warming the full-data program instead both wastes
        # a compile AND can exhaust device memory at big n (observed: worker
        # crash at 1M rows)
        opt_idx = scorer.batch_indices(wrng) if options.batching else None
        optimize_constants_batched(
            _bucket_mix(opt_n, options), scorer, options, wrng, idx=opt_idx
        )
    # warmup evals are not real search work: keep the throughput metric honest
    scorer.num_evals = saved_evals
