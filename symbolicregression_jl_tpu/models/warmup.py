"""Default jit warmup: compile the hot programs before the timed loop.

The reference precompiles its full search workload at package build time
(/root/reference/src/precompile.jl:36-93). XLA programs are specialized on
array *shapes*, so the equivalent here is priming the scoring and
constant-optimization programs at the exact candidate-batch buckets the
first iteration will request — after this, iteration 1 runs at steady-state
speed instead of absorbing every compile.

Batch sizes are padded to power-of-two buckets (ops/flat.batch_bucket), so
the set to prime is small and predictable:
- evolve-cycle candidate batches: between I*e and 2*I*e trees, where
  e = ceil(P / tournament_n) events per island (1 candidate per mutation,
  2 per crossover event)
- per-island init / rescore batches: P trees
- iteration-boundary full rescores: I*P trees
- the BFGS constant-opt batch: ~optimizer_probability * I * P trees
"""

from __future__ import annotations

import numpy as np

from ..ops.flat import batch_bucket
from ..tree import constant

__all__ = ["warmup_host_programs"]


def warmup_host_programs(scorer, options, rng: np.random.Generator) -> None:
    # warmup must only affect speed: draw from a PRIVATE generator so the
    # caller's search trajectory is identical with jit_warmup on or off
    wrng = np.random.default_rng(0)
    I, P = options.populations, options.population_size
    e = -(-P // options.tournament_selection_n)
    buckets = sorted(
        {batch_bucket(c) for c in (I * e, 2 * I * e, P, I * P)}
    )
    saved_evals = scorer.num_evals
    dummy = constant(1.0)
    idxs: list = [None]
    if options.batching:
        idxs.append(scorer.batch_indices(wrng))
    for b in buckets:
        for idx in idxs:
            scorer.loss_many([dummy] * b, idx=idx)
    if options.should_optimize_constants and options.optimizer_probability > 0:
        from ..ops.constant_opt import optimize_constants_batched

        n = max(1, int(round(I * P * options.optimizer_probability)))
        optimize_constants_batched([dummy] * n, scorer, options, wrng)
    # warmup evals are not real search work: keep the throughput metric honest
    scorer.num_evals = saved_evals
