"""Mutation proposal + Metropolis accept/reject.

Reference: next_generation (/root/reference/src/Mutate.jl:80-358). The TPU
restructuring splits it in two phases so that scoring can be batched across
many events (and across islands) into one device program:

  1. ``propose_mutation`` — condition weights, sample a mutation kind, apply
     it with <=10 constraint-checked retries (host-side tree surgery).
  2. ``accept_mutation`` — given the batch-computed score, apply the
     simulated-annealing x complexity-frequency Metropolis rule
     (/root/reference/src/Mutate.jl:276-341).

Divergence from the reference (documented): within one evolve pass, proposals
are drawn from the same population snapshot instead of strictly sequentially;
with the default pop_size/tournament_n ratio this is ~3 concurrent events.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..complexity import compute_complexity
from ..constraints import check_constraints
from ..tree import Node
from . import mutation_functions as mf
from .adaptive_parsimony import RunningSearchStatistics
from .pop_member import PopMember
from .simplify import combine_operators, simplify_tree

__all__ = ["Proposal", "propose_mutation", "accept_mutation", "propose_crossover", "accept_crossover"]


def _copy_tree(tree: Node, options) -> Node:
    """Copy that preserves DAG sharing in graph_nodes mode (Julia's GraphNode
    copy preserves sharing; plain copy() would silently expand it and inflate
    complexity past constraints)."""
    return tree.copy_preserve_sharing() if options.graph_nodes else tree.copy()


@dataclasses.dataclass
class Proposal:
    """One evolution event awaiting batch scoring."""

    kind: str
    parent: PopMember
    tree: Node | None  # candidate (None when mutation failed entirely)
    needs_score: bool
    failed: bool = False  # constraint retries exhausted
    # filled by the scorer stage:
    score: float = np.nan
    loss: float = np.nan


def condition_mutation_weights(
    member: PopMember, options, curmaxsize: int
) -> np.ndarray:
    """Zero out mutations that are illegal in context (reference:
    condition_mutation_weights!, /root/reference/src/Mutate.jl:34-76)."""
    w = options.mutation_weights.as_vector().copy()
    names = options.mutation_weights.NAMES
    i = {n: k for k, n in enumerate(names)}
    tree = member.tree

    if not options.graph_nodes:
        # plain Node trees don't share subexpressions
        w[i["form_connection"]] = 0.0
        w[i["break_connection"]] = 0.0

    if tree.degree == 0:
        w[i["mutate_operator"]] = 0.0
        w[i["swap_operands"]] = 0.0
        w[i["delete_node"]] = 0.0
        w[i["simplify"]] = 0.0
        if not tree.is_const:
            w[i["optimize"]] = 0.0
            w[i["mutate_constant"]] = 0.0
        return w

    if not any(n.degree == 2 for n in tree):
        w[i["swap_operands"]] = 0.0

    n_constants = tree.count_constants()
    w[i["mutate_constant"]] *= min(8, n_constants) / 8.0

    if member.get_complexity(options) >= curmaxsize:
        w[i["add_node"]] = 0.0
        w[i["insert_node"]] = 0.0

    if not options.should_simplify:
        w[i["simplify"]] = 0.0

    if options.operators.n_unary == 0 and options.operators.n_binary == 0:
        w[:] = 0.0
    return w


def _apply_mutation(
    kind: str,
    tree: Node,
    temperature: float,
    options,
    nfeatures: int,
    rng: np.random.Generator,
) -> Node:
    ops = options.operators
    if kind == "mutate_constant":
        return mf.mutate_constant(tree, temperature, options, rng)
    if kind == "mutate_operator":
        return mf.mutate_operator(tree, ops, rng)
    if kind == "swap_operands":
        return mf.swap_operands(tree, rng)
    if kind == "add_node":
        return mf.append_random_op(tree, ops, nfeatures, rng, dtype=options.dtype)
    if kind == "insert_node":
        return mf.insert_random_op(tree, ops, nfeatures, rng, dtype=options.dtype)
    if kind == "delete_node":
        return mf.delete_random_op(tree, ops, nfeatures, rng, dtype=options.dtype)
    if kind == "simplify":
        tree = simplify_tree(tree, options)
        return combine_operators(tree, options)
    if kind == "randomize":
        tree_size = max(tree.count_nodes(), 3)
        return mf.gen_random_tree_fixed_size(
            int(rng.integers(1, tree_size + 1)), ops, nfeatures, rng,
            dtype=options.dtype,
        )
    if kind == "form_connection":
        return mf.form_random_connection(tree, rng)
    if kind == "break_connection":
        return mf.break_random_connection(tree, rng)
    raise ValueError(f"unhandled mutation kind {kind}")


def propose_mutation(
    member: PopMember,
    temperature: float,
    curmaxsize: int,
    options,
    nfeatures: int,
    rng: np.random.Generator,
) -> Proposal:
    weights = condition_mutation_weights(member, options, curmaxsize)
    kind = options.mutation_weights.sample(rng, weights)

    if kind == "do_nothing":
        return Proposal(kind, member, _copy_tree(member.tree, options), needs_score=False)
    if kind == "optimize":
        # routed to the batched constant optimizer by the caller
        return Proposal(kind, member, _copy_tree(member.tree, options), needs_score=True)

    # `simplify` preserves semantics and always passes constraints the parent
    # passed; others need the retry loop (reference: <=10 attempts,
    # /root/reference/src/Mutate.jl:121-244).
    attempts = 1 if kind == "simplify" else 10
    for _ in range(attempts):
        tree = _apply_mutation(
            kind, _copy_tree(member.tree, options), temperature, options,
            nfeatures, rng,
        )
        if check_constraints(tree, options, curmaxsize):
            return Proposal(kind, member, tree, needs_score=True)
    # all retries failed
    return Proposal(kind, member, None, needs_score=False, failed=True)


def accept_mutation(
    prop: Proposal,
    temperature: float,
    stats: RunningSearchStatistics,
    options,
    rng: np.random.Generator,
) -> tuple[PopMember, bool]:
    """Metropolis rule on the batch-computed score. Returns (member, accepted);
    on rejection the member is a copy of the parent (lineage preserved),
    matching the reference's return shape."""
    parent = prop.parent

    def rejected() -> tuple[PopMember, bool]:
        m = PopMember(
            _copy_tree(parent.tree, options),
            parent.score,
            parent.loss,
            complexity=parent.get_complexity(options),
            parent=parent.ref,
        )
        return m, False

    if prop.failed or prop.tree is None:
        return rejected()

    if prop.kind == "do_nothing":
        m = PopMember(
            prop.tree,
            parent.score,
            parent.loss,
            complexity=parent.get_complexity(options),
            parent=parent.ref,
        )
        return m, True

    if np.isnan(prop.score):
        return rejected()

    prob_change = 1.0
    if options.annealing:
        delta = prop.score - parent.score
        # temperature reaches exactly 0.0 on the final annealed cycle; IEEE
        # division gives +-inf and exp() then 0 or inf, matching the Julia
        # reference's float semantics instead of raising ZeroDivisionError.
        # (0/0 -> NaN -> "NaN < rand()" is False -> accept, same as Julia)
        with np.errstate(divide="ignore", over="ignore", invalid="ignore"):
            prob_change *= float(
                np.exp(-np.float64(delta) / (np.float64(temperature) * options.alpha))
            )
    if options.use_frequency:
        old_size = parent.get_complexity(options)
        new_size = compute_complexity(prop.tree, options)
        old_freq = stats.frequency_of(old_size) or 1e-6
        new_freq = stats.frequency_of(new_size) or 1e-6
        if not (0 < old_size <= options.maxsize):
            old_freq = 1e-6
        if not (0 < new_size <= options.maxsize):
            new_freq = 1e-6
        prob_change *= old_freq / new_freq

    if prob_change < rng.random():
        return rejected()

    m = PopMember(
        prop.tree,
        prop.score,
        prop.loss,
        parent=parent.ref,
    )
    m.get_complexity(options)
    return m, True


# -- crossover ---------------------------------------------------------------


@dataclasses.dataclass
class CrossoverProposal:
    parent1: PopMember
    parent2: PopMember
    child1: Node | None
    child2: Node | None
    failed: bool = False
    score1: float = np.nan
    loss1: float = np.nan
    score2: float = np.nan
    loss2: float = np.nan


def propose_crossover(
    m1: PopMember,
    m2: PopMember,
    curmaxsize: int,
    options,
    rng: np.random.Generator,
) -> CrossoverProposal:
    """Breed until both children pass constraints, <=10 tries
    (reference: crossover_generation, /root/reference/src/Mutate.jl:361-429)."""
    for _ in range(10):
        c1, c2 = mf.crossover_trees(
            m1.tree, m2.tree, rng, preserve_sharing=options.graph_nodes
        )
        if check_constraints(c1, options, curmaxsize) and check_constraints(
            c2, options, curmaxsize
        ):
            return CrossoverProposal(m1, m2, c1, c2)
    return CrossoverProposal(m1, m2, None, None, failed=True)


def accept_crossover(
    prop: CrossoverProposal, options
) -> tuple[PopMember, PopMember, bool]:
    """Crossover children are always accepted once scored (no annealing rule in
    the reference either); NaN scores fall back to parents."""
    if prop.failed or np.isnan(prop.score1) or np.isnan(prop.score2):
        p1, p2 = prop.parent1, prop.parent2
        c1 = PopMember(
            _copy_tree(p1.tree, options), p1.score, p1.loss,
            complexity=p1.get_complexity(options), parent=p1.ref,
        )
        c2 = PopMember(
            _copy_tree(p2.tree, options), p2.score, p2.loss,
            complexity=p2.get_complexity(options), parent=p2.ref,
        )
        return c1, c2, False
    c1 = PopMember(prop.child1, prop.score1, prop.loss1, parent=prop.parent1.ref)
    c2 = PopMember(prop.child2, prop.score2, prop.loss2, parent=prop.parent2.ref)
    c1.get_complexity(options)
    c2.get_complexity(options)
    return c1, c2, True
