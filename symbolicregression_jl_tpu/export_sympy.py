"""CAS export/import: expression trees <-> sympy.

Counterpart of the reference's SymbolicUtils extension
(/root/reference/ext/SymbolicRegressionSymbolicUtilsExt.jl:14-53:
node_to_symbolic / symbolic_to_node / convert glue). Safe operators un-alias
to their plain mathematical forms on export (the reference does the same for
printing/export, /root/reference/src/InterfaceDynamicExpressions.jl:283-305).

sympy is an optional integration: import errors surface only when these
functions are called.
"""

from __future__ import annotations

from .tree import Node, binary, constant, feature, unary

__all__ = ["node_to_sympy", "sympy_to_node"]


def _sym():
    try:
        import sympy
    except ImportError as e:  # pragma: no cover
        raise ImportError("sympy is required for CAS export") from e
    return sympy


_UNARY_TO_SYMPY = {
    "cos": "cos", "sin": "sin", "tan": "tan", "exp": "exp", "log": "log",
    "log2": None, "log10": None, "log1p": None, "sqrt": "sqrt", "abs": "Abs",
    "sinh": "sinh", "cosh": "cosh", "tanh": "tanh", "asin": "asin",
    "acos": "acos", "atan": "atan", "asinh": "asinh", "acosh": "acosh",
    "atanh": "atanh", "erf": "erf", "erfc": "erfc", "gamma": "gamma",
    "floor": "floor", "ceil": "ceiling", "sign": "sign",
}


def node_to_sympy(node: Node, opset, variable_names: list[str] | None = None):
    """Convert a tree to a sympy expression. Variables become symbols named
    after ``variable_names`` (default x1, x2, ...)."""
    sympy = _sym()

    def var(i: int):
        name = (
            variable_names[i]
            if variable_names is not None and i < len(variable_names)
            else f"x{i + 1}"
        )
        return sympy.Symbol(name)

    def rec(n: Node):
        if n.degree == 0:
            return sympy.Float(n.val) if n.is_const else var(n.feat)
        if n.degree == 1:
            name = opset.unary[n.op].name
            c = rec(n.l)
            if name == "neg":
                return -c
            if name == "square":
                return c**2
            if name == "cube":
                return c**3
            if name == "log2":
                return sympy.log(c, 2)
            if name == "log10":
                return sympy.log(c, 10)
            if name == "log1p":
                return sympy.log(1 + c)
            if name == "relu":
                return sympy.Max(c, 0)
            fn = _UNARY_TO_SYMPY.get(name)
            if fn is None:
                raise ValueError(f"no sympy mapping for unary operator {name!r}")
            return getattr(sympy, fn)(c)
        name = opset.binary[n.op].name
        l, r = rec(n.l), rec(n.r)
        if name in ("add", "plus"):
            return l + r
        if name == "sub":
            return l - r
        if name == "mult":
            return l * r
        if name == "div":
            return l / r
        if name in ("pow", "safe_pow"):
            return l**r
        if name == "max":
            return sympy.Max(l, r)
        if name == "min":
            return sympy.Min(l, r)
        if name == "mod":
            return sympy.Mod(l, r)
        raise ValueError(f"no sympy mapping for binary operator {name!r}")

    return rec(node)


def sympy_to_node(expr, opset, variable_names: list[str] | None = None) -> Node:
    """Convert a sympy expression back into a tree over ``opset``. Raises if
    the expression uses an operator the set lacks."""
    sympy = _sym()

    names = {}
    if variable_names is not None:
        names = {name: i for i, name in enumerate(variable_names)}

    def find_bin(name: str) -> int:
        return opset.binary_index(name)

    def find_una(name: str) -> int:
        return opset.unary_index(name)

    def nary(op_name: str, args):
        out = rec(args[0])
        i = find_bin(op_name)
        for a in args[1:]:
            out = binary(i, out, rec(a))
        return out

    def rec(e) -> Node:
        if e.is_Symbol:
            s = str(e)
            if s in names:
                return feature(names[s])
            if s.startswith("x") and s[1:].isdigit():
                return feature(int(s[1:]) - 1)
            raise ValueError(f"unknown symbol {s!r}")
        if e.is_Number:
            return constant(float(e))
        if e.is_Add:
            return nary("add", e.args)
        if e.is_Mul:
            return nary("mult", e.args)
        if e.is_Pow:
            base, exp = e.args
            # common sugar: x**2, x**3, sqrt, 1/x
            try:
                if exp == 2:
                    return unary(find_una("square"), rec(base))
            except KeyError:
                pass
            try:
                if exp == 3:
                    return unary(find_una("cube"), rec(base))
            except KeyError:
                pass
            try:
                if exp == sympy.Rational(1, 2):
                    return unary(find_una("sqrt"), rec(base))
            except KeyError:
                pass
            return binary(find_bin("pow"), rec(base), rec(exp))
        fname = type(e).__name__.lower()
        fmap = {"abs": "abs", "ceiling": "ceil"}
        fname = fmap.get(fname, fname)
        try:
            return unary(find_una(fname), *[rec(a) for a in e.args])
        except KeyError as err:
            raise ValueError(
                f"operator set has no operator for sympy node {type(e).__name__}"
            ) from err

    return rec(sympy.sympify(expr))
