"""Batched XLA interpreter for flat expression trees — the L0 kernel.

This is the TPU replacement for DynamicExpressions.jl's recursive
``eval_tree_array`` (documented at
/root/reference/src/InterfaceDynamicExpressions.jl:30-55): instead of
recursing tree-at-a-time, a whole population evaluates as ONE XLA program —
a single ``lax.scan`` over postorder slots carrying an SSA value buffer,
``vmap``-ed over the population axis and vectorized over the dataset-row axis
(rows live in the lane dimension of the VPU).

Differentiation: ``eval_grad_tree_array``-for-constants
(/root/reference/src/InterfaceDynamicExpressions.jl:90-124) is replaced by
``jax.grad`` through this interpreter. A custom VJP exploits the SSA
structure: every slot is written exactly once, so the final forward buffer IS
the complete tape, and the backward pass is one reverse scan propagating
adjoints to children — O(N·R) memory instead of the O(N²·R) a naive
scan-transpose would need.

NaN semantics: invalid math yields NaN/Inf at the root (safe operators,
ops/operators.py); ``ok = isfinite(pred).all(rows)`` reproduces the
reference's ``completed`` flag used for Inf-loss rejection
(/root/reference/src/LossFunctions.jl:55-57).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .flat import KIND_BINARY, KIND_CONST, KIND_UNARY, KIND_VAR, FlatTrees
from .operators import OperatorSet

__all__ = ["eval_trees", "eval_trees_with_ok", "eval_grad_trees", "eval_diff_trees"]


class _Structure(NamedTuple):
    """Non-differentiable portion of FlatTrees for one tree."""

    kind: jax.Array  # int32[N]
    op: jax.Array  # int32[N]
    lhs: jax.Array  # int32[N]
    rhs: jax.Array  # int32[N]
    feat: jax.Array  # int32[N]
    length: jax.Array  # int32[]


def _apply_unary(opset: OperatorSet, o, x):
    if opset.n_unary == 0:
        return x
    if opset.n_unary == 1:
        return opset.unary[0].fn(x)
    return lax.switch(jnp.clip(o, 0, opset.n_unary - 1), [op.fn for op in opset.unary], x)


def _apply_binary(opset: OperatorSet, o, l, r):
    if opset.n_binary == 0:
        return l
    if opset.n_binary == 1:
        return opset.binary[0].fn(l, r)
    return lax.switch(
        jnp.clip(o, 0, opset.n_binary - 1),
        [op.fn for op in opset.binary],
        l,
        r,
    )


def _unary_pullback(opset: OperatorSet, o, x, ct):
    """d(op(x))/dx contracted with cotangent ct, dispatched on op index."""
    if opset.n_unary == 0:
        return jnp.zeros_like(x)

    def mk(fn):
        def branch(operands):
            x_, ct_ = operands
            _, pull = jax.vjp(fn, x_)
            return pull(ct_)[0]

        return branch

    if opset.n_unary == 1:
        return mk(opset.unary[0].fn)((x, ct))
    return lax.switch(
        jnp.clip(o, 0, opset.n_unary - 1),
        [mk(op.fn) for op in opset.unary],
        (x, ct),
    )


def _binary_pullback(opset: OperatorSet, o, l, r, ct):
    if opset.n_binary == 0:
        return jnp.zeros_like(l), jnp.zeros_like(r)

    def mk(fn):
        def branch(operands):
            l_, r_, ct_ = operands
            _, pull = jax.vjp(fn, l_, r_)
            return pull(ct_)

        return branch

    if opset.n_binary == 1:
        return mk(opset.binary[0].fn)((l, r, ct))
    return lax.switch(
        jnp.clip(o, 0, opset.n_binary - 1),
        [mk(op.fn) for op in opset.binary],
        (l, r, ct),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _eval_one(opset: OperatorSet, structure: _Structure, val: jax.Array, X: jax.Array):
    """Evaluate one tree on all rows. val: float[N]; X: float[F, R] -> [R]."""
    pred, _ = _forward(opset, structure, val, X)
    return pred


def _forward(opset, structure: _Structure, val, X):
    N = structure.kind.shape[0]
    R = X.shape[1]
    dtype = X.dtype
    buf0 = jnp.zeros((N, R), dtype)
    zeros_row = jnp.zeros((R,), dtype)

    def step(buf, slot):
        i, k, o, li, ri, fi, v = slot
        l = lax.dynamic_index_in_dim(buf, li, 0, keepdims=False)
        r = lax.dynamic_index_in_dim(buf, ri, 0, keepdims=False)
        xvar = lax.dynamic_index_in_dim(X, fi, 0, keepdims=False)
        un = _apply_unary(opset, o, l)
        bi = _apply_binary(opset, o, l, r)
        res = lax.select_n(
            k,
            zeros_row,
            jnp.full((R,), v, dtype),
            xvar.astype(dtype),
            un.astype(dtype),
            bi.astype(dtype),
        )
        buf = lax.dynamic_update_index_in_dim(buf, res, i, 0)
        return buf, None

    slots = (
        jnp.arange(N, dtype=jnp.int32),
        structure.kind,
        structure.op,
        structure.lhs,
        structure.rhs,
        structure.feat,
        val.astype(dtype),
    )
    buf, _ = lax.scan(step, buf0, slots)
    pred = lax.dynamic_index_in_dim(buf, structure.length - 1, 0, keepdims=False)
    return pred, buf


def _eval_one_fwd(opset, structure, val, X):
    pred, buf = _forward(opset, structure, val, X)
    return pred, (structure, val, X, buf)


def _eval_one_bwd(opset, residuals, g_pred):
    structure, val, X, buf = residuals
    N = structure.kind.shape[0]
    dtype = buf.dtype

    gbuf0 = jnp.zeros_like(buf)
    gbuf0 = lax.dynamic_update_index_in_dim(
        gbuf0, g_pred.astype(dtype), structure.length - 1, 0
    )
    gX0 = jnp.zeros_like(X)
    gval0 = jnp.zeros_like(val)

    def step(carry, slot):
        gbuf, gX, gval = carry
        i, k, o, li, ri, fi = slot
        a = lax.dynamic_index_in_dim(gbuf, i, 0, keepdims=False)
        l = lax.dynamic_index_in_dim(buf, li, 0, keepdims=False)
        r = lax.dynamic_index_in_dim(buf, ri, 0, keepdims=False)

        is_un = k == KIND_UNARY
        is_bi = k == KIND_BINARY
        dl_un = _unary_pullback(opset, o, l, a)
        dl_bi, dr_bi = _binary_pullback(opset, o, l, r, a)
        dl = jnp.where(is_un, dl_un, 0.0) + jnp.where(is_bi, dl_bi, 0.0)
        dr = jnp.where(is_bi, dr_bi, 0.0)

        # Children are at strictly smaller slots, so adding into them before
        # they are visited (we iterate i descending) is safe; slot i itself is
        # finalized once visited.
        li_safe = jnp.where(is_un | is_bi, li, i)
        ri_safe = jnp.where(is_bi, ri, i)
        dl = jnp.where(is_un | is_bi, dl, 0.0)
        dr = jnp.where(is_bi, dr, 0.0)
        gbuf = gbuf.at[li_safe].add(dl)
        gbuf = gbuf.at[ri_safe].add(dr)

        gX = gX.at[fi].add(jnp.where(k == KIND_VAR, a, 0.0).astype(X.dtype))
        gval = gval.at[i].set(
            jnp.where(k == KIND_CONST, a.sum(), 0.0).astype(val.dtype)
        )
        return (gbuf, gX, gval), None

    slots = (
        jnp.arange(N, dtype=jnp.int32),
        structure.kind,
        structure.op,
        structure.lhs,
        structure.rhs,
        structure.feat,
    )
    (gbuf, gX, gval), _ = lax.scan(step, (gbuf0, gX0, gval0), slots, reverse=True)

    g_structure = _Structure(
        kind=_float0(structure.kind),
        op=_float0(structure.op),
        lhs=_float0(structure.lhs),
        rhs=_float0(structure.rhs),
        feat=_float0(structure.feat),
        length=_float0(structure.length),
    )
    return (g_structure, gval, gX)


def _float0(x):
    return np.zeros(np.shape(x), dtype=jax.dtypes.float0)


_eval_one.defvjp(_eval_one_fwd, _eval_one_bwd)


def eval_trees(flat: FlatTrees, X: jax.Array, opset: OperatorSet) -> jax.Array:
    """Evaluate a batch of trees: FlatTrees[P,N] x X[F,R] -> preds[P,R]."""
    # Normalize to device arrays: raw numpy leaves inside custom_vjp residuals
    # break JAX's batching rules (and would re-upload per call anyway).
    flat = FlatTrees(*(jnp.asarray(a) for a in flat))
    X = jnp.asarray(X)
    structure = _Structure(flat.kind, flat.op, flat.lhs, flat.rhs, flat.feat, flat.length)
    fn = jax.vmap(
        functools.partial(_eval_one, opset),
        in_axes=(_Structure(0, 0, 0, 0, 0, 0), 0, None),
    )
    return fn(structure, flat.val, X)


def eval_trees_with_ok(
    flat: FlatTrees, X: jax.Array, opset: OperatorSet
) -> tuple[jax.Array, jax.Array]:
    """As eval_trees, plus the per-tree `completed` flag: all rows finite."""
    preds = eval_trees(flat, X, opset)
    ok = jnp.isfinite(preds).all(axis=-1)
    return preds, ok


def eval_grad_trees(
    flat: FlatTrees, X: jax.Array, opset: OperatorSet, wrt: str = "constants"
) -> jax.Array:
    """Per-row gradients of each tree's prediction — the public counterpart
    of the reference's ``eval_grad_tree_array``
    (/root/reference/src/InterfaceDynamicExpressions.jl:118-124).

    wrt="features": d(pred)/d(X) of shape [P, F, R]. Rows are independent,
    so the rowwise jacobian is obtained in ONE reverse pass as the gradient
    of the row-sum (d sum_r pred[r] / dX[f, r'] = d pred[r'] / dX[f, r']).

    wrt="constants": d(pred)/d(val) of shape [P, N, R] — per-row, unlike the
    search path's row-aggregated VJP. Non-constant slots are zero. Computed
    by vmapping a scalar grad over the row axis.
    """
    flat = FlatTrees(*(jnp.asarray(a) for a in flat))
    X = jnp.asarray(X)
    structure = _Structure(flat.kind, flat.op, flat.lhs, flat.rhs, flat.feat, flat.length)
    tree_axes = (_Structure(0, 0, 0, 0, 0, 0), 0)

    if wrt == "features":

        def sum_pred(structure_p, val_p, X_):
            return _eval_one(opset, structure_p, val_p, X_).sum()

        fn = jax.vmap(jax.grad(sum_pred, argnums=2), in_axes=tree_axes + (None,))
        return fn(structure, flat.val, X)

    if wrt == "constants":

        def row_pred(structure_p, val_p, x_col):
            return _eval_one(opset, structure_p, val_p, x_col[:, None])[0]

        per_row = jax.vmap(jax.grad(row_pred, argnums=1), in_axes=(None, None, 1))
        fn = jax.vmap(per_row, in_axes=tree_axes + (None,))
        return jnp.moveaxis(fn(structure, flat.val, X), 1, 2)  # [P, N, R]

    raise ValueError(f"wrt must be 'features' or 'constants', got {wrt!r}")


def eval_diff_trees(
    flat: FlatTrees, X: jax.Array, opset: OperatorSet, direction: int
) -> jax.Array:
    """Directional derivative d(pred)/d(x_direction) per row: [P, R]
    (reference ``eval_diff_tree_array``,
    /root/reference/src/InterfaceDynamicExpressions.jl:71-95)."""
    return eval_grad_trees(flat, X, opset, wrt="features")[:, direction, :]
