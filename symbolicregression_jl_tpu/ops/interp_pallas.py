"""Pallas TPU kernel for batched tree evaluation — the fast forward path.

Why a kernel (vs. the lax.scan interpreter in interp.py):
  1. The scan interpreter's vmapped ``lax.switch`` computes EVERY operator
     branch for every slot and selects — ~n_ops x wasted VPU work. Here the
     opcode is a scalar per (tree, slot), so ``lax.switch`` lowers to a real
     branch and only the needed op executes.
  2. The SSA value buffer lives in VMEM scratch — zero HBM traffic for
     intermediates (the scan version round-trips [P, N, R] through HBM).
  3. The slot loop runs to each tree's actual ``length``, not the padded
     budget — pad slots cost nothing.

Memory plan: per-tree structure is packed into two lane-aligned HBM arrays —
ints [P, L] = (kind | op | lhs | rhs | feat | length) and vals [P, Lv] — so
each program DMAs exactly two (P_TILE, L) row-slices into SMEM scratch
(dynamic slicing is sublane-dim only, and DMA lane widths must be 128-aligned).
Scalar memory supports the dynamic per-slot reads the interpreter needs; each
program evaluates P_TILE trees sequentially over one row tile with the value
buffer in VMEM [N, R_TILE]. Postorder guarantees each tree overwrites every
slot it reads, so the buffer is safely reused across trees.

Not every operator lowers through Mosaic; ``pallas_supported`` probes
compilation once per operator set and scoring falls back to the scan
interpreter when unsupported.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flat import KIND_BINARY, KIND_CONST, KIND_UNARY, KIND_VAR, FlatTrees
from .operators import OperatorSet

# jax 0.4.x ships this as TPUCompilerParams; newer releases renamed it
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

__all__ = [
    "eval_trees_pallas",
    "loss_trees_pallas",
    "make_pallas_loss_fn",
    "make_packed_loss_fn",
    "make_pallas_diff_loss_fn",
    "pallas_diff_loss",
    "pallas_interpret_enabled",
    "pallas_supported",
]


def pallas_interpret_enabled() -> bool:
    """SR_PALLAS_INTERPRET=1 runs every pallas_call with ``interpret=True`` so
    the kernels execute (emulated) on CPU — the parity-test path for hosts
    without a TPU. Host-side read only: callers consult this at BUILD time and
    thread the answer through as a static argname (the env var participates in
    the jit cache keys that way; reading it inside traced code would violate
    SRL004)."""
    return os.environ.get("SR_PALLAS_INTERPRET", "0") == "1"


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _make_kernel(opset: OperatorSet, n_slots: int, p_tile: int, r_tile: int):
    unary_fns = [op.kernel_fn or op.fn for op in opset.unary]
    binary_fns = [op.kernel_fn or op.fn for op in opset.binary]
    N = n_slots

    def kernel(ints_hbm, vals_hbm, x_ref, out_ref, ints_s, vals_s, buf_ref, sems):
        p = pl.program_id(0)
        start = p * p_tile

        c1 = pltpu.make_async_copy(
            ints_hbm.at[pl.ds(start, p_tile), :], ints_s, sems.at[0]
        )
        c2 = pltpu.make_async_copy(
            vals_hbm.at[pl.ds(start, p_tile), :], vals_s, sems.at[1]
        )
        c1.start()
        c2.start()
        c1.wait()
        c2.wait()

        def tree_body(t, _):
            length = ints_s[t, 5 * N]

            def slot_body(i, _):
                k = ints_s[t, i]
                o = ints_s[t, N + i]

                def const_case():
                    return jnp.full((1, r_tile), vals_s[t, i], dtype=jnp.float32)

                def var_case():
                    return x_ref[pl.ds(ints_s[t, 4 * N + i], 1), :]

                def unary_case():
                    l = buf_ref[pl.ds(ints_s[t, 2 * N + i], 1), :]
                    if len(unary_fns) == 0:
                        return l
                    if len(unary_fns) == 1:
                        return unary_fns[0](l)
                    return lax.switch(o, unary_fns, l)

                def binary_case():
                    l = buf_ref[pl.ds(ints_s[t, 2 * N + i], 1), :]
                    r = buf_ref[pl.ds(ints_s[t, 3 * N + i], 1), :]
                    if len(binary_fns) == 0:
                        return l
                    if len(binary_fns) == 1:
                        return binary_fns[0](l, r)
                    return lax.switch(o, binary_fns, l, r)

                res = lax.switch(
                    jnp.clip(k - KIND_CONST, 0, 3),
                    [const_case, var_case, unary_case, binary_case],
                )
                buf_ref[pl.ds(i, 1), :] = res
                return 0

            lax.fori_loop(0, length, slot_body, 0)
            out_ref[pl.ds(t, 1), :] = buf_ref[pl.ds(length - 1, 1), :]
            return 0

        lax.fori_loop(0, p_tile, tree_body, 0)

    # distinct name per specialization: executable caches keyed on the kernel
    # name must not collide across (N, p_tile, r_tile, opset) variants
    kernel.__name__ = (
        f"sr_eval_n{n_slots}_p{p_tile}_r{r_tile}_h{hash(opset) & 0xFFFFFFFF:x}"
    )
    return kernel


@functools.partial(
    jax.jit, static_argnames=("opset", "n_slots", "p_tile", "r_tile", "interpret")
)
def _eval_pallas(ints, vals, X, opset, n_slots, p_tile, r_tile, interpret=False):
    P, L = ints.shape
    Lv = vals.shape[1]
    F, R_padded = X.shape
    n_r_tiles = R_padded // r_tile
    kernel = _make_kernel(opset, n_slots, p_tile, r_tile)
    if interpret:
        kernel.__name__ += "_interp"

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((P, R_padded), jnp.float32),
        grid=(P // p_tile, n_r_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # ints (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),  # vals (HBM)
            pl.BlockSpec((F, r_tile), lambda p, r: (0, r), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (p_tile, r_tile), lambda p, r: (p, r), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.SMEM((p_tile, L), jnp.int32),
            pltpu.SMEM((p_tile, Lv), jnp.float32),
            pltpu.VMEM((n_slots, r_tile), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(ints, vals, X)


def pack_flat(flat: FlatTrees):
    """Pack FlatTrees into the kernel's two lane-aligned arrays.
    ints [P, L]: kind | op | lhs | rhs | feat | length (L = roundup(5N+1, 128));
    vals [P, Lv] (Lv = roundup(N, 128))."""
    P, N = flat.kind.shape
    L = _round_up(5 * N + 1, 128)
    Lv = _round_up(N, 128)
    ints = jnp.concatenate(
        [
            jnp.asarray(flat.kind, jnp.int32),
            jnp.asarray(flat.op, jnp.int32),
            jnp.asarray(flat.lhs, jnp.int32),
            jnp.asarray(flat.rhs, jnp.int32),
            jnp.asarray(flat.feat, jnp.int32),
            jnp.asarray(flat.length, jnp.int32)[:, None],
        ],
        axis=1,
    )
    ints = jnp.pad(ints, ((0, 0), (0, L - ints.shape[1])))
    vals = jnp.pad(
        jnp.asarray(flat.val, jnp.float32), ((0, 0), (0, Lv - N))
    )
    return ints, vals


def eval_trees_pallas(
    flat: FlatTrees, X, opset: OperatorSet, r_tile: int = 1280, p_tile: int = 8
) -> jax.Array:
    """preds [P, R] via the Pallas kernel. X: [F, R] float32.

    NOTE: r_tile is intentionally FIXED at its default for all callers — this
    backend aborts when kernels with different lane widths run in the same
    process (observed empirically: a 128-lane probe followed by a 1024-lane
    call -> ABORTED). Small row counts are padded up to one full tile instead.
    """
    X = jnp.asarray(X, jnp.float32)
    P, N = flat.kind.shape
    F, R = X.shape
    R_padded = _round_up(R, r_tile)
    if R_padded != R:
        X = jnp.pad(X, ((0, 0), (0, R_padded - R)), constant_values=1.0)
    if P % p_tile != 0:
        raise ValueError(f"P={P} must be a multiple of p_tile={p_tile}")
    ints, vals = pack_flat(flat)
    preds = _eval_pallas(
        ints, vals, X, opset, N, p_tile, r_tile,
        interpret=pallas_interpret_enabled(),
    )
    return preds[:, :R]


# ---------------------------------------------------------------------------
# Fused loss kernel (v2): the scoring fast path.
#
# Differences vs. eval_trees_pallas above (which is kept for preds-shaped
# callers and tests):
#   1. Row layout (8, cols): rows are reshaped into 8 VPU sublanes x cols so
#      every per-slot vector op runs on full (8, 128)-tiles — the (1, r_tile)
#      layout above uses 1 of 8 sublanes.
#   2. The elementwise loss + masked weighted reduction + finiteness check are
#      fused into the kernel: output is per-tree partial sums, never a [P, R]
#      prediction matrix in HBM (the reference reduces eval to a loss scalar
#      per tree the same way: /root/reference/src/LossFunctions.jl:45-75).
#   3. One fused opcode switch (const | var | una_0.. | bin_0..) instead of a
#      kind-switch nesting an op-switch.
#   4. Tree structure is DMA'd once per p-tile (the r-grid above re-copied it
#      for every row tile).
#
# All vector refs share one lane width C_TILE — this backend aborts when
# kernels with different lane widths run in one process (see note on
# eval_trees_pallas).
# ---------------------------------------------------------------------------

C_TILE = 1280  # fixed lane width; row block = 8 * C_TILE = 10240 rows
P_TILE_LOSS = 16


def _make_loss_kernel(
    opset: OperatorSet, loss_elem, n_slots: int, p_tile: int, c_tile: int, C: int, R: int
):
    unary_fns = [op.kernel_fn or op.fn for op in opset.unary]
    binary_fns = [op.kernel_fn or op.fn for op in opset.binary]
    N = n_slots

    def kernel(ints_hbm, vals_hbm, x_ref, y_ref, w_ref, out_ref, ints_s, vals_s, buf_ref, sems):
        p = pl.program_id(0)
        t = pl.program_id(1)
        start = p * p_tile

        @pl.when(t == 0)
        def _init():
            # SMEM/VMEM scratch persists across the sequential t steps of one
            # p-tile, so tree structure is DMA'd once per p-tile, and the
            # output accumulator is zeroed on the first column tile.
            out_ref[...] = jnp.zeros_like(out_ref)
            c1 = pltpu.make_async_copy(
                ints_hbm.at[pl.ds(start, p_tile), :], ints_s, sems.at[0]
            )
            c2 = pltpu.make_async_copy(
                vals_hbm.at[pl.ds(start, p_tile), :], vals_s, sems.at[1]
            )
            c1.start()
            c2.start()
            c1.wait()
            c2.wait()

        yv = y_ref[...]  # (8, c_tile)
        wv = w_ref[...]
        # global row index of lane (sub, col) in this tile; rows >= R are pad
        sub = lax.broadcasted_iota(jnp.int32, (8, c_tile), 0)
        col = lax.broadcasted_iota(jnp.int32, (8, c_tile), 1)
        mask = sub * C + t * c_tile + col < R
        wm = jnp.where(mask, wv, 0.0)
        lane = lax.broadcasted_iota(jnp.int32, (1, c_tile), 1)

        def tree_body(ti, _):
            length = ints_s[ti, 4 * N]

            def slot_body(i, _):
                code = ints_s[ti, i]
                li = ints_s[ti, N + i]
                ri = ints_s[ti, 2 * N + i]
                i8 = pl.multiple_of(i * 8, 8)

                # Predicated blocks (real scalar branches) instead of a
                # value-returning lax.switch: Mosaic lowers the latter to
                # evaluate-every-branch + select, which costs n_ops x the
                # vector work per slot.
                @pl.when(code == 0)
                def _const():
                    buf_ref[pl.ds(i8, 8), :] = jnp.full(
                        (8, c_tile), vals_s[ti, i], dtype=jnp.float32
                    )

                @pl.when(code == 1)
                def _var():
                    f8 = pl.multiple_of(ints_s[ti, 3 * N + i] * 8, 8)
                    buf_ref[pl.ds(i8, 8), :] = x_ref[pl.ds(f8, 8), :]

                for k, fn in enumerate(unary_fns):

                    @pl.when(code == 2 + k)
                    def _una(fn=fn):
                        l8 = pl.multiple_of(li * 8, 8)
                        buf_ref[pl.ds(i8, 8), :] = fn(buf_ref[pl.ds(l8, 8), :])

                for k, fn in enumerate(binary_fns):

                    @pl.when(code == 2 + len(unary_fns) + k)
                    def _bin(fn=fn):
                        l8 = pl.multiple_of(li * 8, 8)
                        r8 = pl.multiple_of(ri * 8, 8)
                        buf_ref[pl.ds(i8, 8), :] = fn(
                            buf_ref[pl.ds(l8, 8), :], buf_ref[pl.ds(r8, 8), :]
                        )

                return 0

            lax.fori_loop(0, length, slot_body, 0)

            root8 = pl.multiple_of((length - 1) * 8, 8)
            pred = buf_ref[pl.ds(root8, 8), :]  # (8, c_tile)
            elem = loss_elem(pred, yv)
            loss_part = jnp.sum(jnp.where(mask, elem * wv, 0.0))
            wsum_part = jnp.sum(wm)
            nonfin_part = jnp.sum(
                jnp.where(mask & ~jnp.isfinite(pred), 1.0, 0.0)
            )
            row = (
                jnp.where(lane == 0, loss_part, 0.0)
                + jnp.where(lane == 1, wsum_part, 0.0)
                + jnp.where(lane == 2, nonfin_part, 0.0)
            )
            out_ref[pl.ds(ti, 1), :] = out_ref[pl.ds(ti, 1), :] + row
            return 0

        lax.fori_loop(0, p_tile, tree_body, 0)

    kernel.__name__ = (
        f"sr_loss_n{n_slots}_p{p_tile}_c{c_tile}_C{C}_R{R}"
        f"_h{hash(opset) & 0xFFFFFFFF:x}_l{_loss_uid(loss_elem)}"
    )
    return kernel


# Stable per-callable ids for kernel naming. Keyed on the callable OBJECT
# (strong ref — prevents GC id reuse from aliasing two different losses to one
# executable-cache name).
_LOSS_UIDS: dict = {}


def _loss_uid(loss_elem) -> int:
    if loss_elem not in _LOSS_UIDS:
        _LOSS_UIDS[loss_elem] = len(_LOSS_UIDS)
    return _LOSS_UIDS[loss_elem]


def _name_with_P(kernel, P: int):
    """The executable cache is keyed on kernel name; two programs that differ
    only in batch size P (grid size) MUST NOT share a name — observed: a small
    P=512 call before a P=10240 call makes the latter ~5x slower."""
    kernel.__name__ = f"{kernel.__name__}_P{P}"
    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "opset", "loss_elem", "n_slots", "p_tile", "c_tile", "C", "R", "interpret"
    ),
)
def _loss_pallas(
    ints, vals, Xr, yr, wr, opset, loss_elem, n_slots, p_tile, c_tile, C, R,
    interpret=False,
):
    P = ints.shape[0]
    F = Xr.shape[0] // 8  # Xr is (F*8, C): feature f occupies sublane rows 8f..8f+8
    n_c_tiles = C // c_tile
    L = ints.shape[1]
    Lv = vals.shape[1]
    kernel = _name_with_P(
        _make_loss_kernel(opset, loss_elem, n_slots, p_tile, c_tile, C, R), P
    )
    if interpret:
        kernel.__name__ += "_interp"

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((P, c_tile), jnp.float32),
        grid=(P // p_tile, n_c_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # ints (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),  # vals (HBM)
            pl.BlockSpec(
                (F * 8, c_tile), lambda p, t: (0, t), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((8, c_tile), lambda p, t: (0, t), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, c_tile), lambda p, t: (0, t), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (p_tile, c_tile), lambda p, t: (p, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.SMEM((p_tile, L), jnp.int32),
            pltpu.SMEM((p_tile, Lv), jnp.float32),
            pltpu.VMEM((n_slots * 8, c_tile), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(ints, vals, Xr, yr, wr)

    loss_sum, w_sum, nonfin = out[:, 0], out[:, 1], out[:, 2]
    return jnp.where(
        (nonfin == 0) & (w_sum > 0), loss_sum / jnp.maximum(w_sum, 1e-30), jnp.inf
    )


def pack_batch_jnp(kind, op, lhs, rhs, feat, length, opset: OperatorSet):
    """In-graph (jnp) packing of batched tree arrays [B, N] into the fused
    kernel layout: (ints [B, L], vals-shaped pad widths). THE canonical
    traced implementation of the 'code | lhs | rhs | feat | length' contract —
    pack_flat_fused (numpy) and FlatSlab.set_tree must agree with it (pinned
    by tests/test_pallas.py)."""
    B, N = kind.shape
    L = _round_up(4 * N + 1, 128)
    code = jnp.where(
        kind == KIND_VAR,
        1,
        jnp.where(
            kind == KIND_UNARY,
            2 + op,
            jnp.where(kind == KIND_BINARY, 2 + opset.n_unary + op, 0),
        ),
    ).astype(jnp.int32)
    ints = jnp.concatenate([code, lhs, rhs, feat, length[:, None]], axis=1)
    return jnp.pad(ints, ((0, 0), (0, L - ints.shape[1])))


def pack_flat_fused(flat: FlatTrees, opset: OperatorSet):
    """Pack FlatTrees into the fused-opcode layout.
    ints [P, L]: code | lhs | rhs | feat | length (L = roundup(4N+1, 128));
    code = 0 const, 1 var, 2+op unary, 2+n_unary+op binary. vals [P, Lv]."""
    kind = np.asarray(flat.kind)
    op = np.asarray(flat.op)
    P, N = kind.shape
    code = np.zeros((P, N), np.int32)
    code[kind == KIND_VAR] = 1
    m = kind == KIND_UNARY
    code[m] = 2 + op[m]
    m = kind == KIND_BINARY
    code[m] = 2 + opset.n_unary + op[m]
    L = _round_up(4 * N + 1, 128)
    Lv = _round_up(N, 128)
    ints = np.concatenate(
        [
            code,
            np.asarray(flat.lhs, np.int32),
            np.asarray(flat.rhs, np.int32),
            np.asarray(flat.feat, np.int32),
            np.asarray(flat.length, np.int32)[:, None],
        ],
        axis=1,
    )
    ints = np.pad(ints, ((0, 0), (0, L - ints.shape[1])))
    vals = np.pad(np.asarray(flat.val, np.float32), ((0, 0), (0, Lv - N)))
    return jnp.asarray(ints), jnp.asarray(vals)


def pack_rows_np(X, y, weights, n_bucket=None):
    """THE numpy core of the kernel row layout: pad rows to a multiple of
    8*C_TILE (X pads with 1.0 so no operator domain-faults on pads; w pads
    with 0 so pads never weigh in) and fold into (8, cols) VPU sublane
    layout. Returns host arrays (Xp [F*8,C], yp [8,C], wp [8,C]); feature f
    occupies Xp sublane rows 8f..8f+8. Shared by _reshape_rows (device
    upload) and the rows-sharded per-block packer
    (models/device_search._make_score_data_rows) — ONE implementation of
    the layout invariants.

    ``n_bucket`` (fleet path) first pads the ROW axis to a shared fleet row
    bucket via ``scoring.pad_rows_np`` — pad rows replicate row 0 with
    weight 0 — so lanes with fewer rows run at the bucket's static R.
    The kernels' in-tile masking (``iota < R`` with the masked loss summing
    ``where(mask, elem * w, 0)`` and ``wsum = sum(w_masked)``) then treats
    those in-bucket pads exactly like real rows, and their zero weight makes
    their contribution an exact 0.0 in both the numerator and the weight
    sum; because the padded R lands in the same 8*C_TILE tile bucket, the
    compiled program and reduction ORDER are identical too — losses and
    gradients stay bit-identical to the lane's solo run (pinned by
    tests/test_fleet.py)."""
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    if n_bucket is not None:
        from .scoring import pad_rows_np

        X, y, weights = pad_rows_np(X, y, weights, n_bucket)
    F, R = X.shape
    R_pad = _round_up(R, 8 * C_TILE)
    C = R_pad // 8
    Xp = np.full((F, R_pad), 1.0, np.float32)
    Xp[:, :R] = X
    yp = np.zeros((R_pad,), np.float32)
    yp[:R] = y
    wp = np.zeros((R_pad,), np.float32)
    wp[:R] = 1.0 if weights is None else np.asarray(weights, np.float32)
    return Xp.reshape(F * 8, C), yp.reshape(8, C), wp.reshape(8, C)


def _reshape_rows(X, y, weights):
    """pack_rows_np + device upload. Returns (Xr, yr, wr, C, R)."""
    F, R = np.asarray(X).shape
    Xp, yp, wp = pack_rows_np(X, y, weights)
    return (
        jnp.asarray(Xp),
        jnp.asarray(yp),
        jnp.asarray(wp),
        Xp.shape[1],
        R,
    )


def make_pallas_loss_fn(X, y, weights, opset: OperatorSet, loss_elem):
    """Build the scoring-loop fast path: reshapes the dataset into sublane
    layout ONCE (device-resident), returns ``fn(flat) -> losses [P]``.

    Matches batched_loss semantics: weighted normalized mean of
    loss_elem(pred, y) over real rows, inf where any pred is non-finite
    (/root/reference/src/LossFunctions.jl:45-75)."""
    Xr, yr, wr, C, R = _reshape_rows(X, y, weights)
    interpret = pallas_interpret_enabled()

    def fn(flat: FlatTrees) -> jax.Array:
        P, N = flat.kind.shape
        if P % P_TILE_LOSS != 0:
            raise ValueError(f"P={P} must be a multiple of {P_TILE_LOSS}")
        ints, vals = pack_flat_fused(flat, opset)
        return _loss_pallas(
            ints, vals, Xr, yr, wr, opset, loss_elem, N, P_TILE_LOSS, C_TILE, C, R,
            interpret=interpret,
        )

    return fn


def loss_trees_pallas(
    flat: FlatTrees, X, y, weights, opset: OperatorSet, loss_elem
) -> jax.Array:
    """One-shot convenience wrapper over make_pallas_loss_fn (host-side
    reshape per call — hot loops should hold a make_pallas_loss_fn closure)."""
    return make_pallas_loss_fn(X, y, weights, opset, loss_elem)(flat)


@functools.partial(
    jax.jit,
    static_argnames=(
        "opset", "loss_elem", "n_slots", "has_weights", "R", "interpret"
    ),
)
def _loss_pallas_dyn(
    ints, vals, X, y, w, opset, loss_elem, n_slots, has_weights, R, interpret=False
):
    """Fused loss with per-call dataset (minibatch path): the sublane pad +
    reshape happens IN-GRAPH on device, so callers can pass fresh row subsets
    without host-side repacking. One compile per (batch length R, statics)."""
    F = X.shape[0]
    R_pad = _round_up(R, 8 * C_TILE)
    C = R_pad // 8
    Xp = jnp.pad(X.astype(jnp.float32), ((0, 0), (0, R_pad - R)), constant_values=1.0)
    yp = jnp.pad(y.astype(jnp.float32), (0, R_pad - R))
    wv = w.astype(jnp.float32) if has_weights else jnp.ones((R,), jnp.float32)
    wp = jnp.pad(wv, (0, R_pad - R))
    return _loss_pallas(
        ints,
        vals,
        Xp.reshape(F * 8, C),
        yp.reshape(8, C),
        wp.reshape(8, C),
        opset,
        loss_elem,
        n_slots,
        P_TILE_LOSS,
        C_TILE,
        C,
        R,
        interpret=interpret,
    )


def loss_trees_pallas_batch(flat: FlatTrees, X, y, weights, opset, loss_elem):
    """Fused losses for a per-call row subset (minibatch scoring). X/y/weights
    may be numpy or device arrays of the batch rows only."""
    ints, vals = pack_flat_fused(flat, opset)
    has_w = weights is not None
    w = jnp.asarray(weights) if has_w else jnp.zeros((X.shape[-1],), jnp.float32)
    return _loss_pallas_dyn(
        ints,
        vals,
        jnp.asarray(X),
        jnp.asarray(y),
        w,
        opset,
        loss_elem,
        flat.kind.shape[1],
        has_w,
        int(X.shape[-1]),
        interpret=pallas_interpret_enabled(),
    )


def make_packed_loss_fn(X, y, weights, opset: OperatorSet, loss_elem, n_slots: int):
    """Like make_pallas_loss_fn, but takes pre-packed slab arrays
    (ops.flat.FlatSlab layout) — zero per-call host packing. Returns
    ``fn(ints [P, L] int32, vals [P, Lv] f32) -> losses [P]``."""
    Xr, yr, wr, C, R = _reshape_rows(X, y, weights)
    interpret = pallas_interpret_enabled()

    def fn(ints, vals) -> jax.Array:
        P = ints.shape[0]
        if P % P_TILE_LOSS != 0:
            raise ValueError(f"P={P} must be a multiple of {P_TILE_LOSS}")
        return _loss_pallas(
            jnp.asarray(ints),
            jnp.asarray(vals),
            Xr,
            yr,
            wr,
            opset,
            loss_elem,
            n_slots,
            P_TILE_LOSS,
            C_TILE,
            C,
            R,
            interpret=interpret,
        )

    return fn


_SUPPORT_CACHE: dict = {}


def pallas_supported(opset: OperatorSet, n_features: int = 2, loss_elem=None) -> bool:
    """Probe whether the fused loss kernel lowers through Mosaic for this
    (operator set, loss) — by COMPILING it, not by platform-string matching
    (the TPU registers under the experimental 'axon' plugin on some hosts).
    Cached per (opset, loss, interpret)."""
    from .losses import L2DistLoss

    loss_elem = loss_elem or L2DistLoss
    interpret = pallas_interpret_enabled()
    if jax.devices()[0].platform == "cpu" and not interpret:
        return False  # Mosaic needs a TPU; the scan interpreter is the CPU path
    key = (opset, loss_elem, interpret)
    if key in _SUPPORT_CACHE:
        return _SUPPORT_CACHE[key]
    try:
        from .flat import flatten_trees
        from ..tree import binary, constant, feature, unary as unary_node

        # a probe batch touching every operator
        t = constant(1.0)
        for i in range(opset.n_binary):
            t = binary(i, t, feature(0))
        for i in range(opset.n_unary):
            t = unary_node(i, t)
        n_nodes = 1 + 2 * opset.n_binary + opset.n_unary
        flat = flatten_trees([t] * P_TILE_LOSS, _round_up(n_nodes, 8))
        X = np.ones((max(n_features, 1), 128), np.float32)
        y = np.ones((128,), np.float32)
        out = loss_trees_pallas(flat, X, y, None, opset, loss_elem)
        out.block_until_ready()
        _SUPPORT_CACHE[key] = True  # srl: disable=SRL009 -- boolean Mosaic-probe memo, not a program store
    except Exception as e:  # noqa: BLE001 — any lowering failure means fallback
        import warnings

        warnings.warn(f"Pallas eval unavailable for {opset}: {type(e).__name__}: {e}")
        _SUPPORT_CACHE[key] = False  # srl: disable=SRL009 -- boolean Mosaic-probe memo, not a program store
    return _SUPPORT_CACHE[key]


# ---------------------------------------------------------------------------
# Fused loss + d(loss)/d(constants) kernel: the constant-optimization fast
# path (round-3 priority). One pass computes the forward values into VMEM,
# then a reverse-postorder adjoint sweep over the SAME resident values —
# replacing jax.grad through the scan interpreter (which re-materializes
# every branch and capped the BFGS batch at chunk=8 with remat).
#
# Adjoint algebra: every node in a tree has exactly one parent, so the
# adjoint buffer needs neither zero-init nor accumulation — the parent
# WRITES each child's adjoint before the reverse sweep reaches the child
# (reverse slot order visits parents first; the root's adjoint is the loss
# cotangent w * dl/dpred). A constant slot's gradient is the row-sum of its
# adjoint (the constant broadcasts across rows). Per-operator derivatives
# come from jax.vjp of the same Mosaic-safe kernel lambdas the forward uses.
#
# The gradient output block is c_tile lanes wide (only the first n_slots
# lanes carry data) because this backend aborts when kernels with different
# vector lane widths share a process (see note on eval_trees_pallas).
# ---------------------------------------------------------------------------


def _make_loss_grad_kernel(
    opset: OperatorSet, loss_elem, n_slots: int, p_tile: int, c_tile: int, C: int, R: int
):
    unary_fns = [op.kernel_fn or op.fn for op in opset.unary]
    binary_fns = [op.kernel_fn or op.fn for op in opset.binary]
    N = n_slots

    def kernel(
        ints_hbm, vals_hbm, x_ref, y_ref, w_ref,
        out_ref, grad_ref, ints_s, vals_s, buf_ref, adj_ref, sems,
    ):
        p = pl.program_id(0)
        t = pl.program_id(1)
        start = p * p_tile

        @pl.when(t == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
            grad_ref[...] = jnp.zeros_like(grad_ref)
            c1 = pltpu.make_async_copy(
                ints_hbm.at[pl.ds(start, p_tile), :], ints_s, sems.at[0]
            )
            c2 = pltpu.make_async_copy(
                vals_hbm.at[pl.ds(start, p_tile), :], vals_s, sems.at[1]
            )
            c1.start()
            c2.start()
            c1.wait()
            c2.wait()

        yv = y_ref[...]  # (8, c_tile)
        wv = w_ref[...]
        sub = lax.broadcasted_iota(jnp.int32, (8, c_tile), 0)
        col = lax.broadcasted_iota(jnp.int32, (8, c_tile), 1)
        mask = sub * C + t * c_tile + col < R
        wm = jnp.where(mask, wv, 0.0)
        lane = lax.broadcasted_iota(jnp.int32, (1, c_tile), 1)

        def tree_body(ti, _):
            length = ints_s[ti, 4 * N]

            # ---- forward sweep (identical to the fused loss kernel) --------
            def slot_body(i, _):
                code = ints_s[ti, i]
                li = ints_s[ti, N + i]
                ri = ints_s[ti, 2 * N + i]
                i8 = pl.multiple_of(i * 8, 8)

                @pl.when(code == 0)
                def _const():
                    buf_ref[pl.ds(i8, 8), :] = jnp.full(
                        (8, c_tile), vals_s[ti, i], dtype=jnp.float32
                    )

                @pl.when(code == 1)
                def _var():
                    f8 = pl.multiple_of(ints_s[ti, 3 * N + i] * 8, 8)
                    buf_ref[pl.ds(i8, 8), :] = x_ref[pl.ds(f8, 8), :]

                for k, fn in enumerate(unary_fns):

                    @pl.when(code == 2 + k)
                    def _una(fn=fn):
                        l8 = pl.multiple_of(li * 8, 8)
                        buf_ref[pl.ds(i8, 8), :] = fn(buf_ref[pl.ds(l8, 8), :])

                for k, fn in enumerate(binary_fns):

                    @pl.when(code == 2 + len(unary_fns) + k)
                    def _bin(fn=fn):
                        l8 = pl.multiple_of(li * 8, 8)
                        r8 = pl.multiple_of(ri * 8, 8)
                        buf_ref[pl.ds(i8, 8), :] = fn(
                            buf_ref[pl.ds(l8, 8), :], buf_ref[pl.ds(r8, 8), :]
                        )

                return 0

            lax.fori_loop(0, length, slot_body, 0)

            root8 = pl.multiple_of((length - 1) * 8, 8)
            pred = buf_ref[pl.ds(root8, 8), :]
            elem = loss_elem(pred, yv)
            loss_part = jnp.sum(jnp.where(mask, elem * wv, 0.0))
            wsum_part = jnp.sum(wm)
            nonfin_part = jnp.sum(jnp.where(mask & ~jnp.isfinite(pred), 1.0, 0.0))
            row = (
                jnp.where(lane == 0, loss_part, 0.0)
                + jnp.where(lane == 1, wsum_part, 0.0)
                + jnp.where(lane == 2, nonfin_part, 0.0)
            )
            out_ref[pl.ds(ti, 1), :] = out_ref[pl.ds(ti, 1), :] + row

            # ---- reverse adjoint sweep ------------------------------------
            _, loss_vjp = jax.vjp(lambda pr: loss_elem(pr, yv), pred)
            (ct,) = loss_vjp(wm)
            adj_ref[pl.ds(root8, 8), :] = ct

            def rev_body(j, _):
                i = length - 1 - j
                code = ints_s[ti, i]
                li = ints_s[ti, N + i]
                ri = ints_s[ti, 2 * N + i]
                i8 = pl.multiple_of(i * 8, 8)
                adj_i = adj_ref[pl.ds(i8, 8), :]

                @pl.when(code == 0)
                def _const_g():
                    # mask padded columns: their loss cotangent is 0, but a
                    # tree singular exactly at the pad value (X=1) makes the
                    # upstream vjp chain produce inf*0=NaN there; columns
                    # never mix elsewhere, so masking this reduction is the
                    # one place the pad lanes could leak into the gradient
                    gval = jnp.sum(jnp.where(mask, adj_i, 0.0))
                    grad_ref[pl.ds(ti, 1), :] = grad_ref[
                        pl.ds(ti, 1), :
                    ] + jnp.where(lane == i, gval, 0.0)

                for k, fn in enumerate(unary_fns):

                    @pl.when(code == 2 + k)
                    def _una_b(fn=fn):
                        l8 = pl.multiple_of(li * 8, 8)
                        _, fvjp = jax.vjp(fn, buf_ref[pl.ds(l8, 8), :])
                        (dl,) = fvjp(adj_i)
                        adj_ref[pl.ds(l8, 8), :] = dl

                for k, fn in enumerate(binary_fns):

                    @pl.when(code == 2 + len(unary_fns) + k)
                    def _bin_b(fn=fn):
                        l8 = pl.multiple_of(li * 8, 8)
                        r8 = pl.multiple_of(ri * 8, 8)
                        _, fvjp = jax.vjp(
                            fn, buf_ref[pl.ds(l8, 8), :], buf_ref[pl.ds(r8, 8), :]
                        )
                        dl, dr = fvjp(adj_i)
                        adj_ref[pl.ds(l8, 8), :] = dl
                        adj_ref[pl.ds(r8, 8), :] = dr

                return 0

            lax.fori_loop(0, length, rev_body, 0)
            return 0

        lax.fori_loop(0, p_tile, tree_body, 0)

    kernel.__name__ = (
        f"sr_lossgrad_n{n_slots}_p{p_tile}_c{c_tile}_C{C}_R{R}"
        f"_h{hash(opset) & 0xFFFFFFFF:x}_l{_loss_uid(loss_elem)}"
    )
    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "opset", "loss_elem", "n_slots", "p_tile", "c_tile", "C", "R", "interpret"
    ),
)
def _loss_grad_pallas(
    ints, vals, Xr, yr, wr, opset, loss_elem, n_slots, p_tile, c_tile, C, R,
    interpret=False,
):
    """Returns (losses [P], grads [P, n_slots]): weighted-mean loss and its
    gradient w.r.t. every val slot (nonzero only on constant slots)."""
    P = ints.shape[0]
    F = Xr.shape[0] // 8
    n_c_tiles = C // c_tile
    L = ints.shape[1]
    Lv = vals.shape[1]
    kernel = _name_with_P(
        _make_loss_grad_kernel(opset, loss_elem, n_slots, p_tile, c_tile, C, R), P
    )
    if interpret:
        kernel.__name__ += "_interp"

    out, grad = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((P, c_tile), jnp.float32),
            jax.ShapeDtypeStruct((P, c_tile), jnp.float32),
        ),
        grid=(P // p_tile, n_c_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # ints (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),  # vals (HBM)
            pl.BlockSpec(
                (F * 8, c_tile), lambda p, t: (0, t), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((8, c_tile), lambda p, t: (0, t), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, c_tile), lambda p, t: (0, t), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(
                (p_tile, c_tile), lambda p, t: (p, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (p_tile, c_tile), lambda p, t: (p, 0), memory_space=pltpu.VMEM
            ),
        ),
        scratch_shapes=[
            pltpu.SMEM((p_tile, L), jnp.int32),
            pltpu.SMEM((p_tile, Lv), jnp.float32),
            pltpu.VMEM((n_slots * 8, c_tile), jnp.float32),
            pltpu.VMEM((n_slots * 8, c_tile), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(ints, vals, Xr, yr, wr)

    loss_sum, w_sum, nonfin = out[:, 0], out[:, 1], out[:, 2]
    ok = (nonfin == 0) & (w_sum > 0)
    denom = jnp.maximum(w_sum, 1e-30)
    losses = jnp.where(ok, loss_sum / denom, jnp.inf)
    grads = jnp.where(ok[:, None], grad[:, :n_slots] / denom[:, None], 0.0)
    return losses, grads


def make_pallas_loss_grad_fn(X, y, weights, opset: OperatorSet, loss_elem):
    """Build the const-opt fast path: dataset resident in sublane layout,
    returns ``fn(ints [B, L], vals [B, N]) -> (losses [B], grads [B, N])``.
    Gradient convention matches jax.grad through the scan interpreter's loss
    (weighted normalized mean, inf/zero-grad on non-finite predictions)."""
    Xr, yr, wr, C, R = _reshape_rows(X, y, weights)
    interpret = pallas_interpret_enabled()

    def fn(ints, vals, n_slots: int):
        B = ints.shape[0]
        if B % P_TILE_LOSS != 0:
            raise ValueError(f"B={B} must be a multiple of {P_TILE_LOSS}")
        Lv = _round_up(n_slots, 128)
        vpad = jnp.pad(vals.astype(jnp.float32), ((0, 0), (0, Lv - n_slots)))
        return _loss_grad_pallas(
            ints, vpad, Xr, yr, wr, opset, loss_elem, n_slots,
            P_TILE_LOSS, C_TILE, C, R, interpret=interpret,
        )

    return fn


def pallas_grad_supported(
    opset: OperatorSet, n_features: int = 2, loss_elem=None
) -> bool:
    """Probe-compile the loss+grad kernel (per-operator jax.vjp lambdas must
    also lower through Mosaic). Cached per (opset, loss, interpret)."""
    from .losses import L2DistLoss

    loss_elem = loss_elem or L2DistLoss
    interpret = pallas_interpret_enabled()
    if jax.devices()[0].platform == "cpu" and not interpret:
        return False
    key = ("grad", opset, loss_elem, interpret)
    if key in _SUPPORT_CACHE:
        return _SUPPORT_CACHE[key]
    try:
        from ..tree import binary, constant, feature, unary as unary_node
        from .flat import flatten_trees

        t = constant(1.0)
        for i in range(opset.n_binary):
            t = binary(i, t, feature(0))
        for i in range(opset.n_unary):
            t = unary_node(i, t)
        n_nodes = 1 + 2 * opset.n_binary + opset.n_unary
        flat = flatten_trees([t] * P_TILE_LOSS, _round_up(n_nodes, 8))
        X = np.ones((max(n_features, 1), 128), np.float32)
        y = np.ones((128,), np.float32)
        fn = make_pallas_loss_grad_fn(X, y, None, opset, loss_elem)
        ints, _ = pack_flat_fused(flat, opset)
        losses, grads = fn(ints, jnp.asarray(flat.val), flat.kind.shape[1])
        losses.block_until_ready()
        grads.block_until_ready()
        _SUPPORT_CACHE[key] = True  # srl: disable=SRL009 -- boolean Mosaic-probe memo, not a program store
    except Exception as e:  # noqa: BLE001 — any lowering failure means fallback
        import warnings

        warnings.warn(
            f"Pallas loss+grad unavailable for {opset}: {type(e).__name__}: {e}"
        )
        _SUPPORT_CACHE[key] = False  # srl: disable=SRL009 -- boolean Mosaic-probe memo, not a program store
    return _SUPPORT_CACHE[key]


# ---------------------------------------------------------------------------
# custom_vjp wrapper: a DIFFERENTIABLE batch loss whose backward pass is the
# fused loss+grad kernel. jax.grad / jax.value_and_grad through this function
# consume in-kernel gradients — the scan interpreter's SSA buffer is never
# re-materialized through HBM, and value_and_grad costs ONE kernel launch
# (the forward residual already holds the gradient).
#
# The dataset rows (Xr, yr, wr) are explicit primals, not closure state, so
# the wrapper can be applied to TRACED data inside a jitted const-opt program
# (custom_vjp functions must not close over tracers); their cotangents are
# declared zero — constants live in `vals`, nothing differentiates the data.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def _diff_loss_cached(opset, loss_elem, n_slots, p_tile, c_tile, C, R, interpret):
    Lv = _round_up(n_slots, 128)

    @jax.custom_vjp
    def loss(ints, vals, Xr, yr, wr):
        return _loss_pallas(
            ints, vals, Xr, yr, wr, opset, loss_elem, n_slots, p_tile, c_tile,
            C, R, interpret=interpret,
        )

    def _fwd(ints, vals, Xr, yr, wr):
        losses, grads = _loss_grad_pallas(
            ints, vals, Xr, yr, wr, opset, loss_elem, n_slots, p_tile, c_tile,
            C, R, interpret=interpret,
        )
        return losses, (ints, grads, Xr, yr, wr)

    def _bwd(res, ct):
        ints, grads, Xr, yr, wr = res
        # per-instance losses are independent, so the vals cotangent is just
        # the per-row cotangent broadcast over that row's in-kernel gradient
        gv = jnp.pad(ct[:, None] * grads, ((0, 0), (0, Lv - n_slots)))
        return (
            np.zeros(ints.shape, jax.dtypes.float0),  # int primal: float0 ct
            gv,
            jnp.zeros_like(Xr),
            jnp.zeros_like(yr),
            jnp.zeros_like(wr),
        )

    loss.defvjp(_fwd, _bwd)
    return loss


def pallas_diff_loss(
    ints, vals, Xr, yr, wr, opset, loss_elem, n_slots,
    p_tile=P_TILE_LOSS, c_tile=C_TILE, *, C, R, interpret=False,
):
    """Differentiable fused loss: ``losses [P]`` = weighted-mean loss per
    instance, with d(loss)/d(vals) supplied by the Pallas loss+grad kernel via
    custom_vjp. ``vals`` must be padded to roundup(n_slots, 128) lanes (the
    cotangent comes back in that shape). Safe to call on traced data inside a
    jitted program."""
    fn = _diff_loss_cached(
        opset, loss_elem, n_slots, p_tile, c_tile, C, R, interpret
    )
    return fn(ints, vals, Xr, yr, wr)


def make_pallas_diff_loss_fn(X, y, weights, opset: OperatorSet, loss_elem):
    """Host-side convenience over pallas_diff_loss: dataset resident in
    sublane layout, returns ``fn(ints [B, L], vals [B, N], n_slots) ->
    losses [B]`` differentiable w.r.t. vals (jax.grad/value_and_grad hit the
    loss+grad kernel, never the scan interpreter)."""
    Xr, yr, wr, C, R = _reshape_rows(X, y, weights)
    interpret = pallas_interpret_enabled()

    def fn(ints, vals, n_slots: int):
        B = ints.shape[0]
        if B % P_TILE_LOSS != 0:
            raise ValueError(f"B={B} must be a multiple of {P_TILE_LOSS}")
        Lv = _round_up(n_slots, 128)
        vpad = jnp.pad(vals.astype(jnp.float32), ((0, 0), (0, Lv - n_slots)))
        return pallas_diff_loss(
            ints, vpad, Xr, yr, wr, opset, loss_elem, n_slots,
            C=C, R=R, interpret=interpret,
        )

    return fn


# ---------------------------------------------------------------------------
# Kernel-resident evolution block (r17): one Pallas program per island runs
# a WHOLE ncycles evolution block — tournament, mutation on packed int16
# words, constraint checks, loss scoring, annealing-gated accept — with the
# population resident in VMEM. The cycle driver is ops/evolve_block.
# _block_cycle, the SAME values-based code the XLA reference executes; only
# the evaluator differs, and it clones the loss kernel's scratch-slot loop
# (per-tree (8, C) tiles, pl.when predicated opcode writes), so interpret-
# mode losses match the reference at f32 tolerance.
#
# The block kernel requires the single-tile row layout (R <= 8 * C_TILE):
# one (8, C) resident tile means scoring needs no cross-tile accumulator
# in the cycle loop. models/device_search gates on that before choosing it.
# ---------------------------------------------------------------------------


def _make_evolve_block_kernel(opset, loss_elem, cfg, C, R, stages):
    from .evolve_block import _block_cycle, _block_pointers

    from .flat import PACK_KIND_BITS, PACK_KIND_MASK

    unary_fns = [op.kernel_fn or op.fn for op in opset.unary]
    binary_fns = [op.kernel_fn or op.fn for op in opset.binary]
    N, P, E, S1 = cfg.n_slots, cfg.pop_size, cfg.events_per_cycle, cfg.maxsize + 1

    def kernel(
        words_ref, consts_ref, len_ref, loss_ref, score_ref, birth_ref,
        fnorm_ref, x_ref, y_ref, w_ref, iscal_ref, fscal_ref,
        w_out, c_out, l_out, lo_out, sc_out, b_out,
        fd_out, bsl_out, bsw_out, bsc_out, bslen_out,
        buf_ref,
    ):
        isl = pl.program_id(0)
        seed = iscal_ref[0, 0].astype(jnp.uint32)
        step0 = iscal_ref[0, 1]
        curmaxsize = iscal_ref[0, 2]
        norm = fscal_ref[0, 0]

        yv = y_ref[...]
        wv = w_ref[...]
        sub = lax.broadcasted_iota(jnp.int32, (8, C), 0)
        colr = lax.broadcasted_iota(jnp.int32, (8, C), 1)
        mask = sub * C + colr < R
        wsum = jnp.sum(jnp.where(mask, wv, 0.0))
        iota_e = lax.broadcasted_iota(jnp.int32, (E,), 0)
        iota_n = lax.broadcasted_iota(jnp.int32, (N,), 0)

        def eval_fn(vw, vc, vlen):
            """Score E candidate programs sequentially against the resident
            row tile — the loss kernel's tree/slot loop, reading program
            structure from VALUES via one-hot scalar extraction."""
            lhs, rhs, _s, _d = _block_pointers(vw, vlen)

            def tree_body(e, losses):
                sel_e = iota_e == e
                row_w = jnp.sum(jnp.where(sel_e[:, None], vw, 0), axis=0)
                row_c = jnp.sum(jnp.where(sel_e[:, None], vc, 0.0), axis=0)
                row_l = jnp.sum(jnp.where(sel_e[:, None], lhs, 0), axis=0)
                row_r = jnp.sum(jnp.where(sel_e[:, None], rhs, 0), axis=0)
                tlen = jnp.sum(jnp.where(sel_e, vlen, 0))

                def slot_body(i, _):
                    sel_i = iota_n == i
                    wsc = jnp.sum(jnp.where(sel_i, row_w, 0))
                    kindc = wsc & PACK_KIND_MASK
                    payload = wsc >> PACK_KIND_BITS
                    cval = jnp.sum(jnp.where(sel_i, row_c, 0.0))
                    li = jnp.sum(jnp.where(sel_i, row_l, 0))
                    ri = jnp.sum(jnp.where(sel_i, row_r, 0))
                    i8 = pl.multiple_of(i * 8, 8)

                    @pl.when(kindc == KIND_CONST)
                    def _const():
                        buf_ref[pl.ds(i8, 8), :] = jnp.full(
                            (8, C), cval, dtype=jnp.float32
                        )

                    @pl.when(kindc == KIND_VAR)
                    def _var():
                        f8 = pl.multiple_of(payload * 8, 8)
                        buf_ref[pl.ds(i8, 8), :] = x_ref[pl.ds(f8, 8), :]

                    for k, fn in enumerate(unary_fns):

                        @pl.when((kindc == KIND_UNARY) & (payload == k))
                        def _una(fn=fn):
                            l8 = pl.multiple_of(li * 8, 8)
                            buf_ref[pl.ds(i8, 8), :] = fn(
                                buf_ref[pl.ds(l8, 8), :]
                            )

                    for k, fn in enumerate(binary_fns):

                        @pl.when((kindc == KIND_BINARY) & (payload == k))
                        def _bin(fn=fn):
                            l8 = pl.multiple_of(li * 8, 8)
                            r8 = pl.multiple_of(ri * 8, 8)
                            buf_ref[pl.ds(i8, 8), :] = fn(
                                buf_ref[pl.ds(l8, 8), :],
                                buf_ref[pl.ds(r8, 8), :],
                            )

                    return 0

                lax.fori_loop(0, tlen, slot_body, 0)
                root8 = pl.multiple_of((tlen - 1) * 8, 8)
                pred = buf_ref[pl.ds(root8, 8), :]
                elem = loss_elem(pred, yv)
                loss_part = jnp.sum(jnp.where(mask, elem * wv, 0.0))
                nonfin = jnp.sum(
                    jnp.where(mask & ~jnp.isfinite(pred), 1.0, 0.0)
                )
                l_e = jnp.where(
                    (nonfin == 0) & (wsum > 0),
                    loss_part / jnp.maximum(wsum, 1e-30),
                    jnp.inf,
                )
                return jnp.where(sel_e, l_e, losses)

            return lax.fori_loop(
                0, E, tree_body, jnp.full((E,), jnp.inf, jnp.float32)
            )

        carry0 = (
            words_ref[0], consts_ref[0], len_ref[0], loss_ref[0],
            score_ref[0], birth_ref[0],
            jnp.zeros((S1,), jnp.float32),
            jnp.full((S1,), jnp.inf, jnp.float32),
            jnp.zeros((S1, N), jnp.int32),
            jnp.zeros((S1, N), jnp.float32),
            jnp.zeros((S1,), jnp.int32),
        )

        def body(cycle, carry):
            return _block_cycle(
                carry, cycle.astype(jnp.int32), isl, seed, step0, curmaxsize,
                fnorm_ref[0], norm, cfg, eval_fn, stages,
            )

        out = lax.fori_loop(0, cfg.ncycles, body, carry0)
        w_out[...] = out[0][None]
        c_out[...] = out[1][None]
        l_out[...] = out[2][None]
        lo_out[...] = out[3][None]
        sc_out[...] = out[4][None]
        b_out[...] = out[5][None]
        fd_out[...] = out[6][None]
        bsl_out[...] = out[7][None]
        bsw_out[...] = out[8][None]
        bsc_out[...] = out[9][None]
        bslen_out[...] = out[10][None]

    kernel.__name__ = (
        f"sr_evoblk_n{N}_p{P}_e{E}_cy{cfg.ncycles}_c{C}_R{R}_s{stages}"
        f"_h{hash(cfg) & 0xFFFFFFFF:x}_o{hash(opset) & 0xFFFFFFFF:x}"
        f"_l{_loss_uid(loss_elem)}"
    )
    return kernel


def make_evolve_block_fn(Xr, yr, wr, R, opset, loss_elem, cfg, stages=4,
                         interpret=None):
    """Build kernel_fn for evolve_block.run_block_iteration's kernel path.

    ``Xr``/``yr``/``wr``: single-tile packed rows ((F*8, C), (8, C), (8, C)
    with C == C_TILE) — callers gate on R <= 8 * C_TILE. Returns
    kernel_fn(words, consts, length, loss, score, birth, fnorm, seed,
    step0, curmaxsize, norm) -> the 11-tuple block carry, stacked [I, ...].
    """
    if interpret is None:
        interpret = pallas_interpret_enabled()
    F8, C = Xr.shape
    I, P, N = cfg.n_islands, cfg.pop_size, cfg.n_slots
    S1 = cfg.maxsize + 1
    kernel = _make_evolve_block_kernel(opset, loss_elem, cfg, C, R, stages)
    if interpret:
        kernel.__name__ += "_interp"

    isl_pn = pl.BlockSpec((1, P, N), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)
    isl_p = pl.BlockSpec((1, P), lambda i: (i, 0), memory_space=pltpu.VMEM)
    fixed = lambda shape: pl.BlockSpec(
        shape, lambda i: (0,) * len(shape), memory_space=pltpu.VMEM
    )
    out_pn = pl.BlockSpec((1, P, N), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)
    out_p = pl.BlockSpec((1, P), lambda i: (i, 0), memory_space=pltpu.VMEM)
    out_s = pl.BlockSpec((1, S1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    out_sn = pl.BlockSpec((1, S1, N), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)

    call = pl.pallas_call(
        kernel,
        grid=(I,),
        in_specs=[
            isl_pn,  # words
            isl_pn,  # consts
            isl_p,   # length
            isl_p,   # loss
            isl_p,   # score
            isl_p,   # birth
            fixed((1, S1)),   # fnorm snapshot
            fixed((F8, C)),   # Xr
            fixed((8, C)),    # yr
            fixed((8, C)),    # wr
            pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((I, P, N), jnp.int32),
            jax.ShapeDtypeStruct((I, P, N), jnp.float32),
            jax.ShapeDtypeStruct((I, P), jnp.int32),
            jax.ShapeDtypeStruct((I, P), jnp.float32),
            jax.ShapeDtypeStruct((I, P), jnp.float32),
            jax.ShapeDtypeStruct((I, P), jnp.int32),
            jax.ShapeDtypeStruct((I, S1), jnp.float32),
            jax.ShapeDtypeStruct((I, S1), jnp.float32),
            jax.ShapeDtypeStruct((I, S1, N), jnp.int32),
            jax.ShapeDtypeStruct((I, S1, N), jnp.float32),
            jax.ShapeDtypeStruct((I, S1), jnp.int32),
        ],
        out_specs=[
            out_pn, out_pn, out_p, out_p, out_p, out_p,
            out_s, out_s, out_sn, out_sn, out_s,
        ],
        scratch_shapes=[pltpu.VMEM((N * 8, C), jnp.float32)],
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )

    def kernel_fn(words, consts, length, loss, score, birth, fnorm, seed,
                  step0, curmaxsize, norm):
        iscal = jnp.stack(
            [
                seed.astype(jnp.int32),
                jnp.asarray(step0, jnp.int32),
                jnp.asarray(curmaxsize, jnp.int32),
                jnp.int32(0),
            ]
        )[None]
        fscal = jnp.stack(
            [jnp.asarray(norm, jnp.float32), jnp.float32(0.0)]
        )[None]
        return tuple(
            call(
                words, consts, length, loss, score, birth,
                fnorm.reshape(1, S1).astype(jnp.float32),
                Xr, yr, wr, iscal, fscal,
            )
        )

    return kernel_fn


_EVOBLK_SUPPORT_CACHE: dict = {}


def evolve_block_supported(opset, n_features: int = 2, loss_elem=None) -> bool:
    """Probe whether the evolve-block kernel lowers through Mosaic — by
    COMPILING AND RUNNING a miniature block, like pallas_supported. The
    block leans on far more of Mosaic than the loss kernel (1-D iotas,
    [E, N, N] one-hot permutes, uint32 hashing), so a dedicated probe gates
    it independently: lowering failures here auto-fall back to the XLA
    reference backend, never to a crash. Cached per (opset, loss,
    interpret)."""
    from .losses import L2DistLoss

    loss_elem = loss_elem or L2DistLoss
    interpret = pallas_interpret_enabled()
    if jax.devices()[0].platform == "cpu" and not interpret:
        return False
    key = (opset, loss_elem, interpret)
    if key in _EVOBLK_SUPPORT_CACHE:
        return _EVOBLK_SUPPORT_CACHE[key]
    try:
        from .evolve import EvoConfig

        nf = max(n_features, 1)
        cfg = EvoConfig(
            n_islands=1, pop_size=8, n_slots=8, maxsize=7, maxdepth=6,
            nfeatures=nf, n_unary=opset.n_unary, n_binary=opset.n_binary,
            tournament_n=2, tournament_weights=(0.8, 0.2),
            mutation_weights=(0.2, 0.2, 0.1, 0.2, 0.1, 0.1, 0.0, 0.1),
            crossover_probability=0.0, annealing=True, alpha=0.1,
            parsimony=0.0, use_frequency=True,
            use_frequency_in_tournament=True,
            adaptive_parsimony_scaling=20.0, perturbation_factor=0.076,
            probability_negate_constant=0.01, baseline_loss=1.0,
            use_baseline=True, ncycles=2, events_per_cycle=2,
            fraction_replaced=0.0, fraction_replaced_hof=0.0,
            migration=False, hof_migration=False, topn=4, niterations=1,
            warmup_maxsize_by=0.0,
        )
        X = np.ones((nf, 64), np.float32)
        y = np.ones((64,), np.float32)
        Xr, yr, wr, _C, R = _reshape_rows(X, y, None)
        fn = make_evolve_block_fn(Xr, yr, wr, R, opset, loss_elem, cfg)
        P, N, S1 = cfg.pop_size, cfg.n_slots, cfg.maxsize + 1
        words = jnp.full((1, P, N), 0, jnp.int32).at[:, :, 0].set(
            2 | (0 << 3)  # KIND_VAR feature 0
        )
        out = fn(
            words,
            jnp.zeros((1, P, N), jnp.float32),
            jnp.ones((1, P), jnp.int32),
            jnp.ones((1, P), jnp.float32),
            jnp.ones((1, P), jnp.float32),
            jnp.zeros((1, P), jnp.int32),
            jnp.ones((S1,), jnp.float32) / S1,
            jnp.uint32(42),
            jnp.asarray(P, jnp.int32),
            jnp.asarray(cfg.maxsize, jnp.int32),
            jnp.float32(1.0),
        )
        jax.block_until_ready(out)
        _EVOBLK_SUPPORT_CACHE[key] = True  # srl: disable=SRL009 -- boolean Mosaic-probe memo, not a program store
    except Exception as e:  # noqa: BLE001 — any lowering failure means fallback
        import warnings

        warnings.warn(
            f"evolve-block kernel unavailable for {opset}: "
            f"{type(e).__name__}: {e}"
        )
        _EVOBLK_SUPPORT_CACHE[key] = False  # srl: disable=SRL009 -- boolean Mosaic-probe memo, not a program store
    return _EVOBLK_SUPPORT_CACHE[key]


__all__ += ["make_evolve_block_fn", "evolve_block_supported"]
