"""Pallas TPU kernel for batched tree evaluation — the fast forward path.

Why a kernel (vs. the lax.scan interpreter in interp.py):
  1. The scan interpreter's vmapped ``lax.switch`` computes EVERY operator
     branch for every slot and selects — ~n_ops x wasted VPU work. Here the
     opcode is a scalar per (tree, slot), so ``lax.switch`` lowers to a real
     branch and only the needed op executes.
  2. The SSA value buffer lives in VMEM scratch — zero HBM traffic for
     intermediates (the scan version round-trips [P, N, R] through HBM).
  3. The slot loop runs to each tree's actual ``length``, not the padded
     budget — pad slots cost nothing.

Memory plan: per-tree structure is packed into two lane-aligned HBM arrays —
ints [P, L] = (kind | op | lhs | rhs | feat | length) and vals [P, Lv] — so
each program DMAs exactly two (P_TILE, L) row-slices into SMEM scratch
(dynamic slicing is sublane-dim only, and DMA lane widths must be 128-aligned).
Scalar memory supports the dynamic per-slot reads the interpreter needs; each
program evaluates P_TILE trees sequentially over one row tile with the value
buffer in VMEM [N, R_TILE]. Postorder guarantees each tree overwrites every
slot it reads, so the buffer is safely reused across trees.

Not every operator lowers through Mosaic; ``pallas_supported`` probes
compilation once per operator set and scoring falls back to the scan
interpreter when unsupported.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flat import KIND_CONST, FlatTrees
from .operators import OperatorSet

__all__ = ["eval_trees_pallas", "pallas_supported"]


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _make_kernel(opset: OperatorSet, n_slots: int, p_tile: int, r_tile: int):
    unary_fns = [op.kernel_fn or op.fn for op in opset.unary]
    binary_fns = [op.kernel_fn or op.fn for op in opset.binary]
    N = n_slots

    def kernel(ints_hbm, vals_hbm, x_ref, out_ref, ints_s, vals_s, buf_ref, sems):
        p = pl.program_id(0)
        start = p * p_tile

        c1 = pltpu.make_async_copy(
            ints_hbm.at[pl.ds(start, p_tile), :], ints_s, sems.at[0]
        )
        c2 = pltpu.make_async_copy(
            vals_hbm.at[pl.ds(start, p_tile), :], vals_s, sems.at[1]
        )
        c1.start()
        c2.start()
        c1.wait()
        c2.wait()

        def tree_body(t, _):
            length = ints_s[t, 5 * N]

            def slot_body(i, _):
                k = ints_s[t, i]
                o = ints_s[t, N + i]

                def const_case():
                    return jnp.full((1, r_tile), vals_s[t, i], dtype=jnp.float32)

                def var_case():
                    return x_ref[pl.ds(ints_s[t, 4 * N + i], 1), :]

                def unary_case():
                    l = buf_ref[pl.ds(ints_s[t, 2 * N + i], 1), :]
                    if len(unary_fns) == 0:
                        return l
                    if len(unary_fns) == 1:
                        return unary_fns[0](l)
                    return lax.switch(o, unary_fns, l)

                def binary_case():
                    l = buf_ref[pl.ds(ints_s[t, 2 * N + i], 1), :]
                    r = buf_ref[pl.ds(ints_s[t, 3 * N + i], 1), :]
                    if len(binary_fns) == 0:
                        return l
                    if len(binary_fns) == 1:
                        return binary_fns[0](l, r)
                    return lax.switch(o, binary_fns, l, r)

                res = lax.switch(
                    jnp.clip(k - KIND_CONST, 0, 3),
                    [const_case, var_case, unary_case, binary_case],
                )
                buf_ref[pl.ds(i, 1), :] = res
                return 0

            lax.fori_loop(0, length, slot_body, 0)
            out_ref[pl.ds(t, 1), :] = buf_ref[pl.ds(length - 1, 1), :]
            return 0

        lax.fori_loop(0, p_tile, tree_body, 0)

    # distinct name per specialization: executable caches keyed on the kernel
    # name must not collide across (N, p_tile, r_tile, opset) variants
    kernel.__name__ = (
        f"sr_eval_n{n_slots}_p{p_tile}_r{r_tile}_h{hash(opset) & 0xFFFFFFFF:x}"
    )
    return kernel


@functools.partial(
    jax.jit, static_argnames=("opset", "n_slots", "p_tile", "r_tile")
)
def _eval_pallas(ints, vals, X, opset, n_slots, p_tile, r_tile):
    P, L = ints.shape
    Lv = vals.shape[1]
    F, R_padded = X.shape
    n_r_tiles = R_padded // r_tile
    kernel = _make_kernel(opset, n_slots, p_tile, r_tile)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((P, R_padded), jnp.float32),
        grid=(P // p_tile, n_r_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # ints (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),  # vals (HBM)
            pl.BlockSpec((F, r_tile), lambda p, r: (0, r), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (p_tile, r_tile), lambda p, r: (p, r), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.SMEM((p_tile, L), jnp.int32),
            pltpu.SMEM((p_tile, Lv), jnp.float32),
            pltpu.VMEM((n_slots, r_tile), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
    )(ints, vals, X)


def pack_flat(flat: FlatTrees):
    """Pack FlatTrees into the kernel's two lane-aligned arrays.
    ints [P, L]: kind | op | lhs | rhs | feat | length (L = roundup(5N+1, 128));
    vals [P, Lv] (Lv = roundup(N, 128))."""
    P, N = flat.kind.shape
    L = _round_up(5 * N + 1, 128)
    Lv = _round_up(N, 128)
    ints = jnp.concatenate(
        [
            jnp.asarray(flat.kind, jnp.int32),
            jnp.asarray(flat.op, jnp.int32),
            jnp.asarray(flat.lhs, jnp.int32),
            jnp.asarray(flat.rhs, jnp.int32),
            jnp.asarray(flat.feat, jnp.int32),
            jnp.asarray(flat.length, jnp.int32)[:, None],
        ],
        axis=1,
    )
    ints = jnp.pad(ints, ((0, 0), (0, L - ints.shape[1])))
    vals = jnp.pad(
        jnp.asarray(flat.val, jnp.float32), ((0, 0), (0, Lv - N))
    )
    return ints, vals


def eval_trees_pallas(
    flat: FlatTrees, X, opset: OperatorSet, r_tile: int = 1024, p_tile: int = 8
) -> jax.Array:
    """preds [P, R] via the Pallas kernel. X: [F, R] float32.

    NOTE: r_tile is intentionally FIXED at its default for all callers — this
    backend aborts when kernels with different lane widths run in the same
    process (observed empirically: a 128-lane probe followed by a 1024-lane
    call -> ABORTED). Small row counts are padded up to one full tile instead.
    """
    X = jnp.asarray(X, jnp.float32)
    P, N = flat.kind.shape
    F, R = X.shape
    R_padded = _round_up(R, r_tile)
    if R_padded != R:
        X = jnp.pad(X, ((0, 0), (0, R_padded - R)), constant_values=1.0)
    if P % p_tile != 0:
        raise ValueError(f"P={P} must be a multiple of p_tile={p_tile}")
    ints, vals = pack_flat(flat)
    preds = _eval_pallas(ints, vals, X, opset, N, p_tile, r_tile)
    return preds[:, :R]


_SUPPORT_CACHE: dict = {}


def pallas_supported(opset: OperatorSet, n_features: int = 2) -> bool:
    """Probe whether this operator set lowers through Mosaic (cached)."""
    if jax.devices()[0].platform not in ("tpu",):
        return False
    if opset in _SUPPORT_CACHE:
        return _SUPPORT_CACHE[opset]
    try:
        from .flat import flatten_trees
        from ..tree import binary, constant, feature, unary as unary_node

        # a probe batch touching every operator
        t = constant(1.0)
        for i in range(opset.n_binary):
            t = binary(i, t, feature(0))
        for i in range(opset.n_unary):
            t = unary_node(i, t)
        n_nodes = 1 + 2 * opset.n_binary + opset.n_unary
        flat = flatten_trees([t] * 8, _round_up(n_nodes, 8))
        X = np.ones((max(n_features, 1), 128), np.float32)
        out = eval_trees_pallas(flat, X, opset)
        out.block_until_ready()
        _SUPPORT_CACHE[opset] = True
    except Exception as e:  # noqa: BLE001 — any lowering failure means fallback
        import warnings

        warnings.warn(f"Pallas eval unavailable for {opset}: {type(e).__name__}: {e}")
        _SUPPORT_CACHE[opset] = False
    return _SUPPORT_CACHE[opset]
