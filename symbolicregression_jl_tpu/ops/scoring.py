"""Batched scoring: the device half of the hot loop.

Replaces the reference's score_func / eval_loss path
(/root/reference/src/LossFunctions.jl:97-194). The key restructuring vs. the
reference: scoring is *batched* — every call evaluates a whole batch of
candidate trees against the dataset as ONE jitted XLA program, instead of one
recursive eval per mutation. Incomplete evaluations (NaN/Inf at the root) get
``inf`` loss (/root/reference/src/LossFunctions.jl:55-57).

``loss_to_score`` is host-side numpy (cheap, per-candidate scalars):
score = loss / max(baseline, 0.01) + complexity * parsimony
(/root/reference/src/LossFunctions.jl:138-158).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from .flat import (
    FlatTrees,
    batch_bucket,
    length_buckets,
    length_buckets_enabled,
    slice_nodes,
)
from .interp import eval_trees
from .losses import weighted_mean_loss
from .operators import OperatorSet

__all__ = [
    "batched_loss",
    "batched_loss_jit",
    "batched_loss_bucketed",
    "objective_loss_jit",
    "loss_to_score",
    "pad_rows_np",
    "baseline_loss",
]


def batched_loss(
    flat: FlatTrees,
    X: jax.Array,
    y: jax.Array,
    weights: jax.Array | None,
    opset: OperatorSet,
    loss_elem: Callable,
    use_pallas: bool = False,
) -> jax.Array:
    """Losses for a batch of trees: [P]. inf where evaluation is invalid.

    use_pallas selects the fused Mosaic loss kernel (eval + loss + reduction
    in one pass, no [P, R] prediction matrix); callers gate it on
    `pallas_supported`. The pallas branch does host-side packing, so it must
    not be called under an outer jit — use batched_loss_jit or
    make_pallas_loss_fn for hot loops.
    """
    if use_pallas:
        from .interp_pallas import loss_trees_pallas

        return loss_trees_pallas(flat, X, y, weights, opset, loss_elem)
    preds = eval_trees(flat, X, opset)
    elem = loss_elem(preds, y[None, :])
    losses = weighted_mean_loss(elem, None if weights is None else weights[None, :])
    ok = jnp.isfinite(preds).all(axis=-1)
    return jnp.where(ok, losses, jnp.inf)


@functools.partial(jax.jit, static_argnames=("opset", "loss_elem", "has_weights"))
def _batched_loss_jit(flat, X, y, weights, opset, loss_elem, has_weights):
    return batched_loss(
        flat, X, y, weights if has_weights else None, opset, loss_elem, False
    )


def batched_loss_jit(flat, X, y, weights, opset, loss_elem, use_pallas=False) -> jax.Array:
    """Jitted entry point; weights=None handled via a static flag so the
    compiled program count stays O(1).

    The pallas path re-packs the dataset into sublane layout on the HOST every
    call (np.asarray on X — a device-to-host copy if X is device-resident,
    which permanently degrades this backend's dispatch to sync mode). It is
    for one-shot use only; hot loops MUST hold a make_pallas_loss_fn /
    make_packed_loss_fn closure instead. This contract is ENFORCED by
    sr-lint rule SRL008 (analysis/lint.py): calling this with
    ``use_pallas=True`` — or ``loss_trees_pallas*`` — inside an
    engine-driver loop fails the lint gate."""
    if use_pallas:
        return batched_loss(flat, X, y, weights, opset, loss_elem, True)
    has_weights = weights is not None
    # numpy placeholder, not jnp: jnp.zeros would eagerly allocate on the
    # DEFAULT device, which breaks CPU-committed complex data on TPU hosts
    w = weights if has_weights else np.zeros((), X.dtype)
    return _batched_loss_jit(flat, X, y, w, opset, loss_elem, has_weights)


def batched_loss_bucketed(
    flat: FlatTrees,
    X: jax.Array,
    y: jax.Array,
    weights: jax.Array | None,
    opset: OperatorSet,
    loss_elem: Callable,
) -> Callable[[], np.ndarray]:
    """Length-bucketed interpreter scoring over a HOST (numpy) flat batch.

    Partitions the batch by tree length (``length_buckets``) and runs the
    scan interpreter at each bucket's node count instead of the global
    max_nodes — a 9-node tree in a maxsize-40 search pays a 16-slot scan,
    not 40. Per-bucket sub-batches are padded to ``batch_bucket`` so the
    compile-cache population stays O(buckets x log P). Losses are
    bit-identical to the full-width program: pad slots write exact zeros and
    are never read, and the loss reduction runs over the (unchanged) row
    axis.

    Returns a zero-arg materializer (all bucket programs are dispatched
    asynchronously up front) yielding float [P] losses in input order.
    """
    lengths = np.asarray(flat.length)
    P, N = flat.kind.shape
    from ..analysis.ir_verify import debug_checks_enabled

    if debug_checks_enabled():
        # the bucketed truncation below (slice_nodes) is only bit-identical
        # when pad slots are exact zeros — verify before slicing. Late import
        # so the flag-off path makes zero verifier calls (pinned by test).
        from ..analysis import ir_verify

        ir_verify.verify_flat_trees(
            flat, opset, full_width=N, where="scoring.batched_loss_bucketed: "
        )
    parts = length_buckets(lengths, N)
    if not length_buckets_enabled() or (
        len(parts) == 1 and parts[0][0] == N and P == batch_bucket(P)
    ):
        dev = batched_loss_jit(flat, X, y, weights, opset, loss_elem)
        try:
            dev.copy_to_host_async()
        except Exception:
            pass
        return lambda: np.asarray(dev)[:P]

    pending = []
    for n_b, sel in parts:
        sub = FlatTrees(*(np.asarray(a)[sel] for a in flat))
        pad = batch_bucket(sel.size) - sel.size
        if pad:
            dup = lambda a: np.concatenate([a, np.repeat(a[:1], pad, axis=0)])
            sub = FlatTrees(*(dup(a) for a in sub))
        dev = batched_loss_jit(
            slice_nodes(sub, n_b), X, y, weights, opset, loss_elem
        )
        try:
            dev.copy_to_host_async()
        except Exception:
            pass
        pending.append((sel, dev))

    def materialize() -> np.ndarray:
        out = np.empty((P,), dtype=np.float64)
        for sel, dev in pending:
            out[sel] = np.asarray(dev)[: sel.size]
        return out

    return materialize


@functools.partial(
    jax.jit, static_argnames=("opset", "objective", "has_weights")
)
def _objective_loss_jit(flat, X, y, weights, opset, objective, has_weights):
    preds = eval_trees(flat, X, opset)
    losses = jnp.asarray(
        objective(preds, y, weights if has_weights else None)
    )
    ok = jnp.isfinite(preds).all(axis=-1)
    return jnp.where(ok, losses, jnp.inf)


def objective_loss_jit(flat, X, y, weights, opset, objective) -> jax.Array:
    """Batched losses under a JAX-traceable FULL objective
    ``objective(preds [P, R], y, weights|None) -> [P]``
    (Options.loss_function_jit — the in-graph counterpart of the
    reference's per-tree loss_function,
    /root/reference/src/LossFunctions.jl:78-94). Trees with non-finite
    predictions get inf regardless of the objective's output."""
    has_weights = weights is not None
    w = weights if has_weights else np.zeros((), X.dtype)
    return _objective_loss_jit(flat, X, y, w, opset, objective, has_weights)


def pad_rows_np(X, y, weights, n_bucket: int):
    """Pad a dataset's row axis to a fleet row bucket, host-side (numpy).

    Returns ``(Xp [F, n_bucket], yp [n_bucket], wp [n_bucket])`` where the
    pad rows REPLICATE row 0 of the real data and carry weight 0.0, and
    ``wp`` is always materialized (ones over the real rows when ``weights``
    is None). Under the weighted-mean loss reduction a zero-weight row
    contributes an exact ``0.0`` to both the loss numerator and the weight
    sum, and replicating a REAL row (rather than synthesizing values) means
    the evaluation/finiteness of the pad rows matches row 0 exactly — so the
    padded loss is bit-identical to the unpadded solo loss, on both the
    interpreter path and the Pallas kernels (whose static-R tile masking
    already zeroes out-of-bucket positions; see ``interp_pallas.pack_rows_np``).

    Known (documented) edge: if the ELEMENT loss overflows to inf on row 0
    while its prediction is finite, the pad contribution is ``inf * 0 = NaN``
    and the padded loss is NaN where the solo loss was inf — both non-finite,
    both rejected identically by the inf-guard, so candidate ordering is
    unaffected.
    """
    X = np.asarray(X)
    y = np.asarray(y)
    n = y.shape[0]
    if n_bucket < n:
        raise ValueError(f"n_bucket {n_bucket} < dataset rows {n}")
    w = (
        np.ones((n,), dtype=y.dtype)
        if weights is None
        else np.asarray(weights, dtype=y.dtype)
    )
    pad = n_bucket - n
    if pad == 0:
        return X, y, w
    Xp = np.concatenate([X, np.repeat(X[:, :1], pad, axis=1)], axis=1)
    yp = np.concatenate([y, np.repeat(y[:1], pad)])
    wp = np.concatenate([w, np.zeros((pad,), dtype=y.dtype)])
    return Xp, yp, wp


def loss_to_score(
    loss,
    complexity,
    *,
    use_baseline: bool,
    baseline: float,
    parsimony: float,
):
    """Normalized loss + parsimony penalty (host-side numpy; see module doc)."""
    normalization = baseline if (use_baseline and baseline >= 0.01) else 0.01
    return np.asarray(loss) / normalization + np.asarray(complexity) * parsimony


def baseline_loss(dataset, opset: OperatorSet, loss_elem, dtype=np.float32):
    """Loss of the constant avg_y predictor (reference: update_baseline_loss!,
    /root/reference/src/LossFunctions.jl:201-215). Returns (baseline, use)."""
    X, y, w = dataset.device_arrays(dtype)
    # build the constant predictor host-side and colocate it with y —
    # jnp.full_like would create it on the DEFAULT device, which breaks the
    # complex path (complex data is CPU-committed; XLA:TPU has no complex)
    pred = np.full((dataset.n,), dataset.avg_y, dtype)
    if hasattr(y, "devices"):
        pred = jax.device_put(pred, next(iter(y.devices())))
    elem = loss_elem(pred[None, :], y[None, :])
    val = float(weighted_mean_loss(elem, None if w is None else w[None, :])[0])
    if np.isfinite(val):
        return val, True
    return 1.0, False
