"""Device-resident regularized evolution — the whole hot loop in one program.

Motivation (measured on the tunneled-TPU backend, see bench.py): the FIRST
device-to-host copy permanently drops the client to synchronous dispatch
(~12ms/call) with ~100ms fixed cost per host-to-device transfer. A host-driven
evolution loop therefore pays ~100ms+ per scoring cycle no matter how fast the
kernel is. This module keeps populations, tournament selection, mutation,
crossover, the Metropolis accept rule, replacement, frequency statistics and
migration ALL on device: one jitted program advances every island through a
full iteration (ncycles x events), and the host reads back state once per
iteration.

Reference semantics being reproduced (with citations):
- tournament + geometric rank pick: /root/reference/src/Population.jl:103-160
- mutation weight conditioning: /root/reference/src/Mutate.jl:34-76
- mutation kinds: /root/reference/src/MutationFunctions.jl
- Metropolis accept (annealing x parsimony frequency ratio):
  /root/reference/src/Mutate.jl:276-317
- replace-oldest regularized evolution: /root/reference/src/RegularizedEvolution.jl:14-109
- crossover: /root/reference/src/Mutate.jl:361-429, crossover_trees
  /root/reference/src/MutationFunctions.jl:271-303
- adaptive parsimony histogram: /root/reference/src/AdaptiveParsimony.jl:20-95
- migration: /root/reference/src/Migration.jl:16-38

Deliberate deviations (documented for the parity suite; each one measured in
ABLATION_r04.json on the config-3 matched-budget leg):
- one mutation attempt per event with fall-back-to-skip instead of <=10
  retries (skip_mutation_failures semantics, /root/reference/src/Mutate.jl:247-266).
  In-jit retries exist (Options.device_mutation_attempts) but measured WORSE
  search quality at 3 attempts (log10_ratio 1.79 vs 0.45) and ~2x wall — keep 1;
- a cycle's events are scored/committed against one population snapshot
  instead of sequentially (staleness ~events_per_cycle). Measured NEUTRAL:
  4-way sub-batching (SR_ABLATE=subbatch=4) at a correctly matched budget
  shows no quality gain (seeds 0/1: 1.75/0.45 vs all-fixes 0.45/0.40) and
  costs more dispatches — an early 0.38 reading came from a budget-inflation
  bug since fixed in build_evo_config;
- `simplify`/`optimize` run at iteration boundaries, not in-cycle: constant
  optimization as a separate device program whose improvements merge into the
  best-seen frontier (merge_best_seen), and algebraic simplify host-side on
  the decoded frontier, re-injected via the migration pool
  (models/device_search._simplified_frontier_pool). The simplify pass is THE
  round-4 quality fix: the seed-paired on/off ablation moves config-3
  matched-budget log10 ratio 1.43 -> 0.45. (Absolute config-3 outcomes are
  widely seed-distributed — log10 0.34-1.63 over 6 seeds; see
  ABLATION_r04.json's distribution row before quoting single-seed legs.)
Migration draws a Poisson count per island like the reference (Bernoulli
ablation: no measurable difference).
Complexity = node count by default; CUSTOM complexity mappings run in-jit
too (cfg.complexity_table — _complexity_of drives score parsimony,
curmaxsize validation, mutation conditioning, the frequency histogram,
tournament parsimony, frontier slots, and migration rescore). Traceable
custom objectives (Options.loss_function_jit) run in-graph via the score
closure; only host-callable per-tree loss_function routes to the host
engine. Per-operator size caps and
nested-operator constraints ARE enforced in-jit (_constraints_ok), and
minibatching runs in-engine (cfg.batching + full-data finalize). Recorder
mode (cfg.record_events) makes every program additionally return event
logs for host-side lineage replay (models/device_recorder.py).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .flat import KIND_BINARY, KIND_CONST, KIND_PAD, KIND_UNARY, KIND_VAR
from .treeops import (
    Tree,
    extract_block,
    gather_slots,
    random_tree,
    replace_range,
    subtree_sizes,
    tree_depth,
)

__all__ = [
    "EvoConfig",
    "EvoState",
    "init_state",
    "run_iteration",
    "run_iteration_donated",
    "run_finalize",
    "scoring_cost_probe",
    "evo_state_specs",
    "shard_evo_state",
    "make_sharded_iteration",
    "make_sharded_finalize",
    "extract_topn_pool",
    "migrate_from_pool",
    "fleet_migrate_from_pool",
    "run_fleet_iteration_fused",
    "run_fleet_iteration_fused_donated",
    "merge_best_seen",
]


# Mutation kind indices for the device switch (subset of the reference's 12;
# see module docstring for how simplify/optimize/connections are handled).
M_CONST, M_OPERATOR, M_SWAP, M_ADD, M_INSERT, M_DELETE, M_RANDOMIZE, M_NOTHING = range(8)


@dataclasses.dataclass(frozen=True)
class EvoConfig:
    """Static (hashable) engine configuration — a jit static argument."""

    n_islands: int
    pop_size: int
    n_slots: int
    maxsize: int
    maxdepth: int
    nfeatures: int
    n_unary: int
    n_binary: int
    tournament_n: int
    tournament_weights: tuple  # geometric rank weights, len tournament_n
    mutation_weights: tuple  # 8 floats, M_* order
    crossover_probability: float
    annealing: bool
    alpha: float
    parsimony: float
    use_frequency: bool
    use_frequency_in_tournament: bool
    adaptive_parsimony_scaling: float
    perturbation_factor: float
    probability_negate_constant: float
    baseline_loss: float
    use_baseline: bool
    ncycles: int
    events_per_cycle: int
    fraction_replaced: float
    fraction_replaced_hof: float
    migration: bool
    hof_migration: bool
    topn: int
    niterations: int
    warmup_maxsize_by: float
    # bounded in-jit mutation retries per event (reference: <=10 host-side,
    # /root/reference/src/Mutate.jl:247-266). Default 1, matching
    # Options.device_mutation_attempts: each extra attempt unrolls into the
    # compiled event program and was measured 2.2x slower end-to-end with no
    # recovery-rate gain, so retries are opt-in.
    mutation_attempts: int = 1
    # round-4 parity fixes, individually gateable for the ablation study
    # (bench_ablation.py / ABLATION_r04.md): const-opt results merge into the
    # best-seen frontier, and migration draws a Poisson count per island
    poisson_migration: bool = True
    copt_updates_bs: bool = True
    # per-operator argument-subtree-size caps: (((lcap, rcap), ...) for binary
    # ops, (cap, ...) for unary ops), -1 = unconstrained — and illegal-nesting
    # combos ((outer_deg, outer_idx, ((inner_deg, inner_idx, max), ...)), ...)
    # (reference: /root/reference/src/CheckConstraints.jl:9-70). Checked
    # in-jit on every candidate when non-trivial.
    bin_caps: tuple = ()
    una_caps: tuple = ()
    nested_constraints: tuple = ()
    # minibatching (reference: batching + batch_size, stochastic loss during
    # evolution + full-data finalize, /root/reference/src/LossFunctions.jl:114-127
    # + src/Population.jl:162-176). When on, _event draws a fresh row subset
    # per cycle (score_fn gains a key argument), evals count fractionally via
    # eval_fraction = batch_size/n_rows, and run_iteration rescores every
    # member on full data at the iteration boundary.
    batching: bool = False
    eval_fraction: float = 1.0
    # compute dtype for constants/losses/scores ("float32" | "float64"); the
    # reference DEFAULTS to Float64 (/root/reference/src/SymbolicRegression.jl:360-447),
    # so the engine must honor it. f64 engines require jax_enable_x64 and use
    # the scan-interpreter scorer (the Pallas kernels are f32-only); tree
    # surgery keeps its int fields on the MXU one-hot path and gathers only
    # the f64 constants per-lane (treeops.gather_slots).
    val_dtype: str = "float32"
    # in-jit dimensional analysis (reference WildcardQuantity abstract eval,
    # /root/reference/src/DimensionalAnalysis.jl:45-226): one postorder pass
    # propagates (SI-exponent vector[7], wildcard, violation) per slot, and
    # violating candidates take the additive loss penalty (dimensional
    # regularization, /root/reference/src/LossFunctions.jl:217-227).
    # Documented deviation: the engine check is structure-only — the host
    # checker also latches violations on non-finite SAMPLE values, which the
    # engine leaves to ordinary inf-loss scoring. Tables built by
    # build_evo_config from operator NAMES: una_dim_pow[i] = exponent
    # multiplier for power-like unary ops (sqrt 0.5, square 2, inv -1,
    # abs/neg 1, ...) or None (generic: input must be dimensionless or
    # wildcard); bin_dim_code[i] in {0: add/sub, 1: mult, 2: div,
    # 3: generic/pow}.
    units_check: bool = False
    x_dims: tuple = ()  # F rows of 7 SI exponents (floats)
    y_dims: tuple | None = None
    una_dim_pow: tuple = ()
    bin_dim_code: tuple = ()
    dim_penalty: float = 1000.0
    allow_wildcards: bool = True
    # recorder mode (reference: RecordType lineage tracing, mutations +
    # deaths + tuning, /root/reference/src/Mutate.jl:126-341 +
    # SearchUtils.jl:377-393): every engine program additionally RETURNS a
    # per-event log (chosen mutation kind, tournament winner, replaced slot,
    # accept flag, candidate tree arrays, and migration replace/src/pool
    # rows) that the host replays into Recorder entries with true
    # parent/child trees (models/device_recorder.py). Requires
    # crossover_probability=0 (host-recorder parity; Options enforces it)
    # and mutation_attempts=1.
    record_events: bool = False
    # custom complexity mapping (reference: ComplexityMapping,
    # /root/reference/src/OptionsStruct.jl:21-113 + Complexity.jl:17-50):
    # (bin_costs[n_binary], una_costs[n_unary], const_cost,
    # var_costs[nfeatures]) as static tuples built by build_evo_config from
    # Options.complexity_of_*; None -> complexity = node count (length).
    # Every complexity consumer (score parsimony, curmaxsize/validate,
    # frequency histogram, tournament parsimony, best-seen frontier indexing,
    # migration rescore) routes through _complexity_of/complexity_batch.
    complexity_table: tuple | None = None


class EvoState(NamedTuple):
    """All mutable search state, device-resident. Tree arrays are [I, P, N]
    (islands x members x slots); per-member scalars are [I, P]."""

    kind: jax.Array
    op: jax.Array
    lhs: jax.Array
    rhs: jax.Array
    feat: jax.Array
    val: jax.Array
    length: jax.Array  # int32 [I, P]
    loss: jax.Array  # float32 [I, P]
    score: jax.Array  # float32 [I, P]
    birth: jax.Array  # int32 [I, P]
    freq: jax.Array  # float32 [S+1] complexity histogram (shared, lockstep)
    bs_loss: jax.Array  # float32 [S+1] best-seen loss per complexity
    bs_tree: tuple  # Tree-field arrays [S+1, N] (+ length [S+1]) of best-seen
    bs_exists: jax.Array  # bool [S+1]
    key: jax.Array
    step: jax.Array  # int32 event counter (birth clock)
    num_evals: jax.Array  # float32
    iteration: jax.Array  # int32 — drives the on-device warmup-maxsize schedule


def _member_tree(state: EvoState, i, p) -> Tree:
    return Tree(
        state.kind[i, p],
        state.op[i, p],
        state.lhs[i, p],
        state.rhs[i, p],
        state.feat[i, p],
        state.val[i, p],
        state.length[i, p],
    )


def _score_of(loss, complexity, cfg: EvoConfig, norm=None):
    """loss_to_score (/root/reference/src/LossFunctions.jl:138-158).

    ``norm``: pass the TRACED normalization (ScoreData.norm) inside engine
    programs so executables stay dataset-independent; host-side decode
    callers omit it and use the cfg constants."""
    if norm is None:
        norm = (
            cfg.baseline_loss
            if (cfg.use_baseline and cfg.baseline_loss >= 0.01)
            else 0.01
        )
    return loss / norm + complexity * cfg.parsimony


def init_state(
    flat_arrays, losses, cfg: EvoConfig, seed: int, freq_init=None
) -> EvoState:
    """Build device state from host-flattened populations.

    flat_arrays: FlatTrees-style tuple with shapes [I*P, N] / [I*P]
    losses: [I*P] float64/32 host losses (already scored)."""
    I, P, N, S = cfg.n_islands, cfg.pop_size, cfg.n_slots, cfg.maxsize
    vdt = jnp.dtype(cfg.val_dtype)

    def r(a, dtype):
        return jnp.asarray(np.asarray(a), dtype).reshape(I, P, *np.shape(a)[1:])

    kind = r(flat_arrays.kind, jnp.int32)
    op = r(flat_arrays.op, jnp.int32)
    lhs = r(flat_arrays.lhs, jnp.int32)
    rhs = r(flat_arrays.rhs, jnp.int32)
    feat = r(flat_arrays.feat, jnp.int32)
    val = r(flat_arrays.val, vdt)
    length = jnp.asarray(np.asarray(flat_arrays.length), jnp.int32).reshape(I, P)
    loss = jnp.asarray(np.asarray(losses), vdt).reshape(I, P)
    if cfg.complexity_table is None:
        comp = length.astype(vdt)
    else:
        comp = complexity_batch(
            Tree(
                kind.reshape(I * P, N), op.reshape(I * P, N),
                lhs.reshape(I * P, N), rhs.reshape(I * P, N),
                feat.reshape(I * P, N), val.reshape(I * P, N),
                length.reshape(I * P),
            ),
            cfg,
        ).reshape(I, P).astype(vdt)
    score = _score_of(loss, comp, cfg)
    freq = (
        jnp.asarray(freq_init, jnp.float32)
        if freq_init is not None
        else jnp.ones((S + 1,), jnp.float32)
    )
    bs_tree = (
        jnp.zeros((S + 1, N), jnp.int32),  # kind
        jnp.zeros((S + 1, N), jnp.int32),  # op
        jnp.zeros((S + 1, N), jnp.int32),  # lhs
        jnp.zeros((S + 1, N), jnp.int32),  # rhs
        jnp.zeros((S + 1, N), jnp.int32),  # feat
        jnp.zeros((S + 1, N), vdt),  # val
        jnp.zeros((S + 1,), jnp.int32),  # length
    )
    return EvoState(
        kind,
        op,
        lhs,
        rhs,
        feat,
        val,
        length,
        loss,
        score,
        birth=jnp.tile(jnp.arange(P, dtype=jnp.int32)[None], (I, 1)),
        freq=freq,
        bs_loss=jnp.full((S + 1,), jnp.inf, vdt),
        bs_tree=bs_tree,
        bs_exists=jnp.zeros((S + 1,), bool),
        key=jax.random.PRNGKey(seed),
        step=jnp.asarray(P, jnp.int32),
        num_evals=jnp.zeros((), jnp.float32),
        iteration=jnp.zeros((), jnp.int32),
    )


# ---------------------------------------------------------------------------
# Tournament selection (vmapped over islands)
# ---------------------------------------------------------------------------


def _tournament(key, score, length, freq, cfg: EvoConfig):
    """Winner index in [0, P) for ONE island.
    Reference: best_of_sample, /root/reference/src/Population.jl:110-160."""
    P = cfg.pop_size
    n = cfg.tournament_n
    k1, k2 = jax.random.split(key)
    # n distinct members via random-key argsort
    order = jnp.argsort(jax.random.uniform(k1, (P,), dtype=jnp.float32))
    cand = order[:n]
    s = score[cand]
    if cfg.use_frequency_in_tournament:
        fnorm = freq / jnp.maximum(jnp.sum(freq), 1e-30)
        sizes = jnp.clip(length[cand], 0, cfg.maxsize)
        s = s * jnp.exp(cfg.adaptive_parsimony_scaling * fnorm[sizes])
    rank = jax.random.choice(
        k2, n, p=jnp.asarray(cfg.tournament_weights, jnp.float32)
    )
    by_score = jnp.argsort(s)
    return cand[by_score[rank]]


# ---------------------------------------------------------------------------
# Mutations (single tree; vmapped over islands)
# ---------------------------------------------------------------------------


def _rand_node(key, length):
    return jax.random.randint(key, (), 0, jnp.maximum(length, 1), dtype=jnp.int32)


def _mutate_constant(key, tree: Tree, cfg: EvoConfig, temperature) -> Tree:
    """Multiply or divide one random constant by maxChange^U(0,1) with
    maxChange = perturbation_factor * T + 1.1, maybe negate — matching the
    host engine (models/mutation_functions.py:77-99) and
    /root/reference/src/MutationFunctions.jl:60-89."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    is_c = tree.kind == KIND_CONST
    n_c = jnp.sum(is_c)
    # index of a random constant slot
    ranks = jnp.cumsum(is_c.astype(jnp.int32)) - 1
    pick = jax.random.randint(k1, (), 0, jnp.maximum(n_c, 1), dtype=jnp.int32)
    slot_hits = is_c & (ranks == pick)
    max_change = cfg.perturbation_factor * temperature + 1.0 + 0.1
    factor = max_change ** jax.random.uniform(k2, (), dtype=jnp.float32)
    factor = jnp.where(jax.random.uniform(k4, (), dtype=jnp.float32) < 0.5, factor, 1.0 / factor)
    neg = jax.random.uniform(k3, (), dtype=jnp.float32) < cfg.probability_negate_constant
    newval = tree.val * jnp.where(slot_hits, factor * jnp.where(neg, -1.0, 1.0), 1.0)
    return tree._replace(val=jnp.where(n_c > 0, newval, tree.val))


def _mutate_operator(key, tree: Tree, cfg: EvoConfig) -> Tree:
    """Swap one operator for a random same-arity operator
    (/root/reference/src/MutationFunctions.jl:44-57)."""
    k1, k2, k3 = jax.random.split(key, 3)
    is_op = tree.kind >= KIND_UNARY
    n_op = jnp.sum(is_op)
    ranks = jnp.cumsum(is_op.astype(jnp.int32)) - 1
    pick = jax.random.randint(k1, (), 0, jnp.maximum(n_op, 1), dtype=jnp.int32)
    hits = is_op & (ranks == pick)
    new_un = jax.random.randint(k2, (), 0, max(cfg.n_unary, 1), dtype=jnp.int32)
    new_bin = jax.random.randint(k3, (), 0, max(cfg.n_binary, 1), dtype=jnp.int32)
    new_op = jnp.where(tree.kind == KIND_UNARY, new_un, new_bin)
    return tree._replace(op=jnp.where(hits & (n_op > 0), new_op, tree.op))


def _swap_operands(key, tree: Tree, cfg: EvoConfig, sizes) -> Tree:
    """Swap the child subtrees of one random binary node
    (/root/reference/src/MutationFunctions.jl:34-41). ``sizes`` is the
    precomputed subtree_sizes of ``tree``."""
    N = tree.n_slots
    k1 = key
    is_bin = tree.kind == KIND_BINARY
    n_b = jnp.sum(is_bin)
    ranks = jnp.cumsum(is_bin.astype(jnp.int32)) - 1
    pick = jax.random.randint(k1, (), 0, jnp.maximum(n_b, 1), dtype=jnp.int32)
    # argmax yields int64 under jax_enable_x64; pin int32 so the pointer
    # scatters below stay int32 (future JAX errors on int64->int32 updates)
    p = jnp.argmax(is_bin & (ranks == pick)).astype(jnp.int32)
    # children blocks: A = left subtree, B = right subtree; B ends at p-1
    r_root = tree.rhs[p]
    l_root = tree.lhs[p]
    lenB = sizes[r_root]
    lenA = sizes[l_root]
    al = l_root - lenA + 1  # A = [al, al+lenA), B = [al+lenA, p)
    j = lax.iota(jnp.int32, N)
    # new layout: B first (shift left by lenA), then A (shift right by lenB)
    src = jnp.clip(jnp.where(j < al + lenB, j + lenA, j - lenB), 0, N - 1)
    use_move = (j >= al) & (j < p)

    # ONE MXU one-hot gather for all six fields (per-lane dynamic gathers
    # are the engine's dominant cost — see treeops.gather_slots)
    g_kind, g_op, g_lhs, g_rhs, g_feat, g_val = gather_slots(tree, src)

    def mv(g, orig):
        return jnp.where(use_move, g, orig)

    def mv_ptr(c, orig):
        cin_a = (c >= al) & (c < al + lenA)
        c2 = jnp.where(cin_a, c + lenB, jnp.where((c >= al + lenA) & (c < p), c - lenA, c))
        return jnp.where(use_move, c2, orig)

    kind = mv(g_kind, tree.kind)
    new = tree._replace(
        kind=kind,
        op=mv(g_op, tree.op),
        lhs=jnp.where(kind >= KIND_UNARY, mv_ptr(g_lhs, tree.lhs), 0),
        rhs=jnp.where(kind == KIND_BINARY, mv_ptr(g_rhs, tree.rhs), 0),
        feat=mv(g_feat, tree.feat),
        val=jnp.where(use_move, g_val, tree.val),
    )
    # fix the chosen node's own child pointers (it did not move)
    new_lhs = new.lhs.at[p].set(al + lenB - 1)  # old B root, now first block
    new_rhs = new.rhs.at[p].set(p - 1)  # old A root, now second block
    new = new._replace(lhs=new_lhs, rhs=new_rhs)
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(n_b > 0, a, b), new, tree
    )


def _leaf_material(key, cfg: EvoConfig, n_slots: int) -> Tree:
    """One random leaf (50/50 const/feature) as a 1-node block."""
    vdt = jnp.dtype(cfg.val_dtype)
    k1, k2, k3 = jax.random.split(key, 3)
    is_const = jax.random.uniform(k1, (), dtype=jnp.float32) < 0.5
    if cfg.nfeatures <= 0:
        is_const = jnp.asarray(True)
    N = n_slots
    z = jnp.zeros((N,), jnp.int32)
    kind = z.at[0].set(jnp.where(is_const, KIND_CONST, KIND_VAR))
    feat = z.at[0].set(jax.random.randint(k2, (), 0, max(cfg.nfeatures, 1), dtype=jnp.int32))
    val = jnp.zeros((N,), vdt).at[0].set(jax.random.normal(k3, (), dtype=vdt))
    return Tree(kind, z, z, z, feat, val, jnp.asarray(1, jnp.int32))


def _add_node(key, tree: Tree, cfg: EvoConfig) -> Tree:
    """append_random_op: replace a random LEAF with a random depth-1 operator
    subtree (/root/reference/src/MutationFunctions.jl:92-121)."""
    N = tree.n_slots
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    is_leaf = (tree.kind == KIND_CONST) | (tree.kind == KIND_VAR)
    n_l = jnp.sum(is_leaf)
    ranks = jnp.cumsum(is_leaf.astype(jnp.int32)) - 1
    pick = jax.random.randint(k1, (), 0, jnp.maximum(n_l, 1), dtype=jnp.int32)
    p = jnp.argmax(is_leaf & (ranks == pick)).astype(jnp.int32)
    # material: binary(leaf, leaf) or unary(leaf)
    use_bin = jax.random.uniform(k2, (), dtype=jnp.float32) < (
        cfg.n_binary / max(cfg.n_binary + cfg.n_unary, 1)
    )
    if cfg.n_unary == 0:
        use_bin = jnp.asarray(True)
    if cfg.n_binary == 0:
        use_bin = jnp.asarray(False)
    l1 = _leaf_material(k3, cfg, N)
    l2 = _leaf_material(k4, cfg, N)
    ko1, ko2 = jax.random.split(k5)
    opb = jax.random.randint(ko1, (), 0, max(cfg.n_binary, 1), dtype=jnp.int32)
    opu = jax.random.randint(ko2, (), 0, max(cfg.n_unary, 1), dtype=jnp.int32)
    # build material arrays: [leaf1, leaf2, op] (binary) or [leaf1, op] (unary)
    m_len = jnp.where(use_bin, 3, 2)
    root = m_len - 1
    kind = jnp.zeros((N,), jnp.int32)
    kind = kind.at[0].set(l1.kind[0])
    kind = kind.at[1].set(jnp.where(use_bin, l2.kind[0], KIND_UNARY))
    kind = kind.at[2].set(jnp.where(use_bin, KIND_BINARY, KIND_PAD))
    op = jnp.zeros((N,), jnp.int32)
    op = op.at[1].set(jnp.where(use_bin, 0, opu))
    op = op.at[2].set(jnp.where(use_bin, opb, 0))
    lhs = jnp.zeros((N,), jnp.int32).at[root].set(jnp.where(use_bin, 0, 0))
    rhs = jnp.zeros((N,), jnp.int32).at[2].set(jnp.where(use_bin, 1, 0))
    feat = jnp.zeros((N,), jnp.int32)
    feat = feat.at[0].set(l1.feat[0])
    feat = feat.at[1].set(jnp.where(use_bin, l2.feat[0], 0))
    val = jnp.zeros((N,), jnp.dtype(cfg.val_dtype))
    val = val.at[0].set(l1.val[0])
    val = val.at[1].set(jnp.where(use_bin, l2.val[0], 0.0))
    mat = Tree(kind, op, lhs, rhs, feat, val, m_len.astype(jnp.int32))
    out = replace_range(tree, p, p + 1, mat)
    return jax.tree_util.tree_map(lambda a, b: jnp.where(n_l > 0, a, b), out, tree)


def _insert_node(key, tree: Tree, cfg: EvoConfig, sizes) -> Tree:
    """insert_random_op: wrap a random subtree in a new operator node
    (/root/reference/src/MutationFunctions.jl:124-143)."""
    N = tree.n_slots
    k1, k2, k3, k4 = jax.random.split(key, 4)
    p = _rand_node(k1, tree.length)
    a = p - sizes[p] + 1
    blk = extract_block(tree, a, p + 1)
    blen = blk.length
    use_bin = jax.random.uniform(k2, (), dtype=jnp.float32) < (
        cfg.n_binary / max(cfg.n_binary + cfg.n_unary, 1)
    )
    if cfg.n_unary == 0:
        use_bin = jnp.asarray(True)
    if cfg.n_binary == 0:
        use_bin = jnp.asarray(False)
    leaf = _leaf_material(k3, cfg, N)
    ko1, ko2 = jax.random.split(k4)
    opb = jax.random.randint(ko1, (), 0, max(cfg.n_binary, 1), dtype=jnp.int32)
    opu = jax.random.randint(ko2, (), 0, max(cfg.n_unary, 1), dtype=jnp.int32)
    # material: [block..., leaf?, op]; binary child order (block, leaf)
    j = lax.iota(jnp.int32, N)
    leaf_pos = blen
    op_pos = jnp.where(use_bin, blen + 1, blen)
    m_len = op_pos + 1
    kind = blk.kind
    kind = jnp.where((j == leaf_pos) & use_bin, leaf.kind[0], kind)
    kind = jnp.where(j == op_pos, jnp.where(use_bin, KIND_BINARY, KIND_UNARY), kind)
    op = jnp.where(j == op_pos, jnp.where(use_bin, opb, opu), blk.op)
    lhs = jnp.where(j == op_pos, blen - 1, blk.lhs)
    rhs = jnp.where(j == op_pos, jnp.where(use_bin, leaf_pos, 0), blk.rhs)
    feat = jnp.where((j == leaf_pos) & use_bin, leaf.feat[0], blk.feat)
    val = jnp.where((j == leaf_pos) & use_bin, leaf.val[0], blk.val)
    mat = Tree(kind, op, lhs, rhs, feat, val, m_len.astype(jnp.int32))
    return replace_range(tree, a, p + 1, mat)


def _delete_node(key, tree: Tree, cfg: EvoConfig, sizes) -> Tree:
    """delete_random_op: splice a random operator node out, promoting one of
    its children (/root/reference/src/MutationFunctions.jl:191-234)."""
    k1, k2 = jax.random.split(key)
    is_op = tree.kind >= KIND_UNARY
    n_op = jnp.sum(is_op)
    ranks = jnp.cumsum(is_op.astype(jnp.int32)) - 1
    pick = jax.random.randint(k1, (), 0, jnp.maximum(n_op, 1), dtype=jnp.int32)
    p = jnp.argmax(is_op & (ranks == pick)).astype(jnp.int32)
    keep_right = (tree.kind[p] == KIND_BINARY) & (jax.random.uniform(k2, (), dtype=jnp.float32) < 0.5)
    child = jnp.where(keep_right, tree.rhs[p], tree.lhs[p])
    ca = child - sizes[child] + 1
    blk = extract_block(tree, ca, child + 1)
    out = replace_range(tree, p - sizes[p] + 1, p + 1, blk)
    return jax.tree_util.tree_map(lambda a, b: jnp.where(n_op > 0, a, b), out, tree)


def _randomize(key, tree: Tree, cfg: EvoConfig, curmaxsize) -> Tree:
    """Fresh random tree (/root/reference/src/Mutate.jl randomize branch);
    size ~ U[1, curmaxsize] capped by slots."""
    k1, k2 = jax.random.split(key)
    m = jax.random.randint(k1, (), 1, jnp.maximum(curmaxsize, 1) + 1, dtype=jnp.int32)
    return random_tree(
        k2, m, tree.n_slots, cfg.nfeatures, cfg.n_unary, cfg.n_binary,
        dtype=jnp.dtype(cfg.val_dtype),
    )


def _crossover(key, t1: Tree, t2: Tree, cfg: EvoConfig, s1, s2):
    """Swap random subtrees between two trees; returns (child1, child2)
    (/root/reference/src/MutationFunctions.jl:271-303). s1/s2 are the
    precomputed subtree_sizes of t1/t2."""
    k1, k2 = jax.random.split(key)
    p1 = _rand_node(k1, t1.length)
    p2 = _rand_node(k2, t2.length)
    a1 = p1 - s1[p1] + 1
    a2 = p2 - s2[p2] + 1
    b1 = extract_block(t1, a1, p1 + 1)
    b2 = extract_block(t2, a2, p2 + 1)
    c1 = replace_range(t1, a1, p1 + 1, b2)
    c2 = replace_range(t2, a2, p2 + 1, b1)
    return c1, c2


def _condition_weights(tree: Tree, cfg: EvoConfig, curmaxsize) -> jax.Array:
    """Zero out illegal mutations for this tree's context
    (/root/reference/src/Mutate.jl:34-76). Returns [8] weights."""
    w = jnp.asarray(cfg.mutation_weights, jnp.float32)
    n_const = jnp.sum(tree.kind == KIND_CONST)
    n_ops = jnp.sum(tree.kind >= KIND_UNARY)
    # growth conditions on MAPPED complexity vs curmaxsize (the reference
    # conditions check_constraints complexity, /root/reference/src/Mutate.jl:34-76)
    at_max = _complexity_of(tree, cfg) >= curmaxsize
    # leaf-only tree: no operator mutation / swap / delete
    no_ops = n_ops == 0
    w = w.at[M_OPERATOR].set(jnp.where(no_ops, 0.0, w[M_OPERATOR]))
    w = w.at[M_SWAP].set(
        jnp.where(jnp.sum(tree.kind == KIND_BINARY) == 0, 0.0, w[M_SWAP])
    )
    w = w.at[M_DELETE].set(jnp.where(no_ops, 0.0, w[M_DELETE]))
    # no constants: no constant mutation; else scale by min(8, n_const)/8
    w = w.at[M_CONST].set(
        jnp.where(
            n_const == 0,
            0.0,
            w[M_CONST] * jnp.minimum(8.0, n_const.astype(jnp.float32)) / 8.0,
        )
    )
    # at maxsize: no growth
    w = w.at[M_ADD].set(jnp.where(at_max, 0.0, w[M_ADD]))
    w = w.at[M_INSERT].set(jnp.where(at_max, 0.0, w[M_INSERT]))
    return w


def _apply_mutation(
    key, tree: Tree, kind_idx, cfg: EvoConfig, curmaxsize, temperature, sizes
) -> Tree:
    """Dispatch one mutation kind (vmapped callers: all branches trace).
    ``sizes`` = precomputed subtree_sizes(tree), shared by the structural
    branches (the vmapped switch evaluates every branch, so recomputing it
    inside each one multiplied the N-step forward passes)."""
    def canon(t: Tree) -> Tree:
        # pin canonical dtypes: scalar-index arithmetic (argmax-derived
        # positions) silently promotes int32 arrays to int64 when the
        # process has jax_enable_x64 on (f64 host searches), and lax.switch
        # requires identical branch output types. No-op casts are free.
        return Tree(
            t.kind.astype(jnp.int32), t.op.astype(jnp.int32),
            t.lhs.astype(jnp.int32), t.rhs.astype(jnp.int32),
            t.feat.astype(jnp.int32), t.val.astype(jnp.dtype(cfg.val_dtype)),
            t.length.astype(jnp.int32),
        )

    branches = [
        lambda k, t: canon(_mutate_constant(k, t, cfg, temperature)),
        lambda k, t: canon(_mutate_operator(k, t, cfg)),
        lambda k, t: canon(_swap_operands(k, t, cfg, sizes)),
        lambda k, t: canon(_add_node(k, t, cfg)),
        lambda k, t: canon(_insert_node(k, t, cfg, sizes)),
        lambda k, t: canon(_delete_node(k, t, cfg, sizes)),
        lambda k, t: canon(_randomize(k, t, cfg, curmaxsize)),
        lambda k, t: canon(t),  # do_nothing
    ]
    return lax.switch(kind_idx, branches, key, tree)


def _has_op_constraints(cfg: EvoConfig) -> bool:
    return any(c != (-1, -1) for c in cfg.bin_caps) or any(
        c != -1 for c in cfg.una_caps
    )


def _nest_depth(tree: Tree, deg: int, op_idx: int) -> jax.Array:
    """nd[i] = max count of (deg, op_idx) nodes along any root-to-leaf path of
    the subtree at slot i (postorder forward pass; the in-jit analogue of
    count_max_nestedness, /root/reference/src/CheckConstraints.jl:40-52)."""
    N = tree.n_slots
    want_kind = KIND_UNARY if deg == 1 else KIND_BINARY
    is_target = (tree.kind == want_kind) & (tree.op == op_idx)
    is_un = tree.kind == KIND_UNARY
    is_bin = tree.kind == KIND_BINARY

    def body(i, nd):
        child = jnp.maximum(
            jnp.where(is_un[i] | is_bin[i], nd[tree.lhs[i]], 0),
            jnp.where(is_bin[i], nd[tree.rhs[i]], 0),
        )
        return nd.at[i].set(child + is_target[i].astype(jnp.int32))

    return lax.fori_loop(0, N, body, jnp.zeros(N, jnp.int32))


def _constraints_ok(tree: Tree, cfg: EvoConfig) -> jax.Array:
    """Per-operator subtree-size caps + illegal-nesting combos for ONE tree
    (in-jit counterpart of constraints.check_constraints; reference
    /root/reference/src/CheckConstraints.jl:9-70). Static no-op (returns
    True) when no constraints are configured."""
    ok = jnp.asarray(True)
    j = lax.iota(jnp.int32, tree.n_slots)
    live = j < tree.length
    if _has_op_constraints(cfg):
        sizes = subtree_sizes(tree)
        l_size = sizes[tree.lhs]
        r_size = sizes[tree.rhs]
        if cfg.una_caps:
            cap_u = jnp.asarray(cfg.una_caps, jnp.int32)
            opc = jnp.clip(tree.op, 0, len(cfg.una_caps) - 1)
            viol = (
                live
                & (tree.kind == KIND_UNARY)
                & (cap_u[opc] >= 0)
                & (l_size > cap_u[opc])
            )
            ok &= ~jnp.any(viol)
        if cfg.bin_caps:
            caps = np.asarray(cfg.bin_caps, np.int32)  # [n_binary, 2]
            cap_l = jnp.asarray(caps[:, 0])
            cap_r = jnp.asarray(caps[:, 1])
            opc = jnp.clip(tree.op, 0, len(cfg.bin_caps) - 1)
            is_b = live & (tree.kind == KIND_BINARY)
            viol = is_b & (
                ((cap_l[opc] >= 0) & (l_size > cap_l[opc]))
                | ((cap_r[opc] >= 0) & (r_size > cap_r[opc]))
            )
            ok &= ~jnp.any(viol)
    if cfg.nested_constraints:
        nd_cache: dict = {}
        for odeg, oidx, inners in cfg.nested_constraints:
            o_kind = KIND_UNARY if odeg == 1 else KIND_BINARY
            is_outer = live & (tree.kind == o_kind) & (tree.op == oidx)
            for ideg, iidx, maxn in inners:
                nd = nd_cache.get((ideg, iidx))
                if nd is None:
                    nd = _nest_depth(tree, ideg, iidx)
                    nd_cache[(ideg, iidx)] = nd
                child_nest = jnp.maximum(
                    nd[tree.lhs],
                    jnp.where(tree.kind == KIND_BINARY, nd[tree.rhs], 0),
                )
                ok &= ~jnp.any(is_outer & (child_nest > maxn))
    return ok


def _complexity_of(tree: Tree, cfg: EvoConfig) -> jax.Array:
    """Mapped complexity of ONE tree, int32 (reference: compute_complexity,
    /root/reference/src/Complexity.jl:17-50 — rounded sum of per-node costs).
    Static identity (node count) when no custom mapping is configured."""
    if cfg.complexity_table is None:
        return tree.length
    bin_c, una_c, const_c, var_c = cfg.complexity_table
    bc = jnp.asarray(bin_c or (1.0,), jnp.float32)
    uc = jnp.asarray(una_c or (1.0,), jnp.float32)
    vc = jnp.asarray(var_c or (1.0,), jnp.float32)
    live = jnp.arange(tree.n_slots) < tree.length
    cost = jnp.where(
        tree.kind == KIND_CONST,
        jnp.float32(const_c),
        jnp.where(
            tree.kind == KIND_VAR,
            vc[jnp.clip(tree.feat, 0, vc.shape[0] - 1)],
            jnp.where(
                tree.kind == KIND_UNARY,
                uc[jnp.clip(tree.op, 0, uc.shape[0] - 1)],
                bc[jnp.clip(tree.op, 0, bc.shape[0] - 1)],
            ),
        ),
    )
    return jnp.round(jnp.sum(jnp.where(live, cost, 0.0))).astype(jnp.int32)


def complexity_batch(batch: Tree, cfg: EvoConfig) -> jax.Array:
    """[B] mapped complexities for a [B, N] tree batch (see _complexity_of)."""
    if cfg.complexity_table is None:
        return batch.length
    return jax.vmap(lambda t: _complexity_of(t, cfg))(batch)


def _complexity_members(state: EvoState, cfg: EvoConfig) -> jax.Array:
    """[I, P] mapped complexities of the population state."""
    if cfg.complexity_table is None:
        return state.length
    I, P, N = cfg.n_islands, cfg.pop_size, cfg.n_slots
    flat = Tree(
        state.kind.reshape(I * P, N), state.op.reshape(I * P, N),
        state.lhs.reshape(I * P, N), state.rhs.reshape(I * P, N),
        state.feat.reshape(I * P, N), state.val.reshape(I * P, N),
        state.length.reshape(I * P),
    )
    return complexity_batch(flat, cfg).reshape(I, P)


_DIM_TOL = 1e-4  # SI-exponent equality tolerance (1/3 etc. live in f32)


def _dim_violates(tree: Tree, cfg: EvoConfig) -> jax.Array:
    """In-jit WildcardQuantity abstract evaluation for ONE tree: True iff
    the tree is dimensionally inconsistent with cfg.x_dims/y_dims
    (reference: violates_dimensional_constraints,
    /root/reference/src/DimensionalAnalysis.jl:45-226; see the EvoConfig
    units_check docstring for the structure-only deviation). Static no-op
    (False) when units are not configured."""
    if not cfg.units_check:
        return jnp.asarray(False)
    N = tree.n_slots
    F = max(len(cfg.x_dims), 1)
    xd = jnp.asarray(
        cfg.x_dims if cfg.x_dims else ((0.0,) * 7,), jnp.float32
    )  # [F, 7]
    nu = max(cfg.n_unary, 1)
    nb = max(cfg.n_binary, 1)
    u_pow = jnp.asarray(
        [p if p is not None else 0.0 for p in cfg.una_dim_pow] or [0.0],
        jnp.float32,
    )
    u_is_pow = jnp.asarray(
        [p is not None for p in cfg.una_dim_pow] or [False], bool
    )
    b_code = jnp.asarray(list(cfg.bin_dim_code) or [3], jnp.int32)

    def dimless(d):  # d: [7]
        return jnp.all(jnp.abs(d) < _DIM_TOL)

    def body(i, carry):
        dims, wc, vio = carry  # [N,7], [N], [N]
        k = tree.kind[i]
        o = tree.op[i]
        li, ri = tree.lhs[i], tree.rhs[i]
        ld, lw, lv = dims[li], wc[li], vio[li]
        rd, rw, rv = dims[ri], wc[ri], vio[ri]

        # leaves: constants are wildcards (unless forbidden), variables
        # carry their feature's dims and are NEVER wildcards
        leaf_dims = jnp.where(
            k == KIND_VAR, xd[jnp.clip(tree.feat[i], 0, F - 1)], 0.0
        )
        leaf_wc = (k == KIND_CONST) & cfg.allow_wildcards

        # unary
        up = u_pow[jnp.clip(o, 0, nu - 1)]
        u_ispow = u_is_pow[jnp.clip(o, 0, nu - 1)]
        u_dims = jnp.where(u_ispow, ld * up, jnp.zeros((7,), jnp.float32))
        u_wc = u_ispow & lw
        u_vio = lv | (~u_ispow & ~(dimless(ld) | lw))

        # binary
        code = b_code[jnp.clip(o, 0, nb - 1)]
        same = jnp.all(jnp.abs(ld - rd) < _DIM_TOL)
        as_dims = jnp.where(
            same,
            ld,
            jnp.where(
                lw & rw,
                jnp.zeros((7,), jnp.float32),
                jnp.where(lw, rd, ld),
            ),
        )
        as_wc = lw & rw
        as_vio = ~same & ~lw & ~rw
        mul_dims = jnp.where(code == 1, ld + rd, ld - rd)
        mul_wc = lw | rw
        gen_ok = (dimless(ld) | lw) & (dimless(rd) | rw)
        b_dims = jnp.where(
            code == 0,
            as_dims,
            jnp.where(code <= 2, mul_dims, jnp.zeros((7,), jnp.float32)),
        )
        b_wc = jnp.where(code == 0, as_wc, (code <= 2) & mul_wc)
        b_vio = lv | rv | jnp.where(
            code == 0, as_vio, jnp.where(code <= 2, False, ~gen_ok)
        )

        new_dims = jnp.where(
            k == KIND_UNARY, u_dims, jnp.where(k == KIND_BINARY, b_dims, leaf_dims)
        )
        new_wc = jnp.where(
            k == KIND_UNARY, u_wc, jnp.where(k == KIND_BINARY, b_wc, leaf_wc)
        )
        new_vio = jnp.where(
            k == KIND_UNARY, u_vio, jnp.where(k == KIND_BINARY, b_vio, False)
        )
        return (
            dims.at[i].set(new_dims),
            wc.at[i].set(new_wc),
            vio.at[i].set(new_vio),
        )

    dims, wc, vio = lax.fori_loop(
        0,
        N,
        body,
        (
            jnp.zeros((N, 7), jnp.float32),
            jnp.zeros((N,), bool),
            jnp.zeros((N,), bool),
        ),
    )
    root = jnp.clip(tree.length - 1, 0, N - 1)
    out = vio[root]
    if cfg.y_dims is not None:
        yd = jnp.asarray(cfg.y_dims, jnp.float32)
        out |= ~wc[root] & ~jnp.all(jnp.abs(dims[root] - yd) < _DIM_TOL)
    return out


def dim_penalty_batch(batch: Tree, cfg: EvoConfig):
    """Additive dimensional-regularization penalties for a tree batch [B]
    (0.0 everywhere when units are off — a static no-op under jit)."""
    if not cfg.units_check:
        return jnp.zeros((batch.kind.shape[0],), jnp.dtype(cfg.val_dtype))
    viol = jax.vmap(lambda t: _dim_violates(t, cfg))(batch)
    return jnp.where(viol, cfg.dim_penalty, 0.0).astype(jnp.dtype(cfg.val_dtype))


#: jitted twin for the HOST-scored legs (init populations, warm-start
#: rescore, simplify pool): the SAME structure-only check the engine applies
#: in-graph, so one search never mixes two penalty semantics on one tree
dim_penalty_batch_jit = functools.partial(jax.jit, static_argnames=("cfg",))(
    dim_penalty_batch
)


def merge_best_seen(
    state: EvoState, cfg: EvoConfig, losses, valid, fields, lengths, axis=None,
    comps=None,
) -> EvoState:
    """Fold a batch of scored trees into the best-seen frontier (the per-size
    mini hall of fame, /root/reference/src/SingleIteration.jl:64-100).

    ``losses``/``valid``/``lengths``: [B]; ``fields``: 6-list of [B, N]
    (kind/op/lhs/rhs/feat/val). Deterministic per-size argmin via a one-hot
    [S+1, B] mask — duplicate-index scatter order is implementation-defined
    in XLA, so last-write-wins tricks are unsafe.

    ``axis``: shard_map island-axis mode — per-shard candidates merge to a
    global min per size (pmin), then the lowest-indexed winning shard
    broadcasts its tree via a masked psum, keeping bs_* replicated."""
    S1 = cfg.maxsize + 1
    # frontier slots are indexed by MAPPED complexity when a custom mapping
    # is configured (``comps``); node count otherwise
    sizes = jnp.clip(lengths if comps is None else comps, 0, cfg.maxsize)
    size_mask = sizes[None, :] == jnp.arange(S1, dtype=sizes.dtype)[:, None]
    cand_loss = jnp.where(size_mask & valid[None, :], losses[None, :], jnp.inf)
    best_idx = jnp.argmin(cand_loss, axis=1)  # [S1]
    best_loss_s = jnp.min(cand_loss, axis=1)
    cand_fields = [field[best_idx] for field in fields]  # [S1, N]
    cand_len = lengths[best_idx]
    if axis is not None:
        g_loss = lax.pmin(best_loss_s, axis)
        idx = lax.axis_index(axis)
        win = (best_loss_s <= g_loss) & jnp.isfinite(g_loss)
        owner = lax.pmin(jnp.where(win, idx, jnp.iinfo(jnp.int32).max), axis)
        mine = win & (idx == owner)
        cand_fields = [
            lax.psum(jnp.where(mine[:, None], f, jnp.zeros_like(f)), axis)
            for f in cand_fields
        ]
        cand_len = lax.psum(jnp.where(mine, cand_len, 0), axis)
        best_loss_s = g_loss
    better = best_loss_s < state.bs_loss
    bs_loss = jnp.where(better, best_loss_s, state.bs_loss)
    bt_new = [
        jnp.where(better[:, None], f, cur)
        for cur, f in zip(state.bs_tree[:6], cand_fields)
    ]
    bs_len = jnp.where(better, cand_len, state.bs_tree[6])
    return state._replace(
        bs_loss=bs_loss,
        bs_tree=(*bt_new, bs_len),
        bs_exists=state.bs_exists | better,
    )


# ---------------------------------------------------------------------------
# One evolution event for every island in parallel
# ---------------------------------------------------------------------------


def _event(state: EvoState, data, cfg: EvoConfig, score_fn, temperature, curmaxsize, axis=None):
    """One full evolve pass: ALL of a cycle's events for ALL islands in one
    batched step. The reference runs a pass's events sequentially
    (/root/reference/src/RegularizedEvolution.jl:31-33); batching them against
    one population snapshot is the same staleness the host lockstep engine
    documents (~E concurrent events) and buys an E-fold cut in per-iteration
    dispatch count. Tournament -> mutate or crossover -> score -> Metropolis
    accept -> ALWAYS replace: event lane e replaces the (2e)-th oldest member
    (the reference replaces the oldest even on rejection — the baby is then a
    parent copy; :33-105) and a crossover's second child the (2e+1)-th.

    ``axis``: when run inside shard_map with the island axis sharded over a
    mesh axis of that name, the two cross-island structures stay lockstep via
    explicit collectives — the frequency histogram merges with a psum of the
    per-shard delta, and the best-seen frontier merges with a pmin + owner
    broadcast. Everything else is island-local and needs no communication."""
    I, P, N = cfg.n_islands, cfg.pop_size, cfg.n_slots
    E = min(cfg.events_per_cycle, P)  # host parity: ceil(P/tournament_n) <= P
    L = I * E  # event lanes
    # crossover needs a second replacement slot per lane; with 2E > P the
    # stride-2 slot scheme cannot stay collision-free, so tiny populations run
    # mutation-only (documented deviation; the reference would error earlier)
    can_pair = 2 * E <= P
    key, k_t1, k_t2, k_mut, k_kind, k_flip, k_xo, k_acc, k_bat = jax.random.split(
        state.key, 9
    )

    score_r = jnp.repeat(state.score, E, axis=0)  # [L, P], lane l -> island l//E
    comp_members = _complexity_members(state, cfg)  # [I, P] (== length sans mapping)
    comp_r = jnp.repeat(comp_members, E, axis=0)
    win1 = jax.vmap(lambda k, s, l: _tournament(k, s, l, state.freq, cfg))(
        jax.random.split(k_t1, L), score_r, comp_r
    )
    win2 = jax.vmap(lambda k, s, l: _tournament(k, s, l, state.freq, cfg))(
        jax.random.split(k_t2, L), score_r, comp_r
    )

    isl = jnp.repeat(jnp.arange(I, dtype=jnp.int32), E)  # island of each lane
    parent1 = jax.vmap(lambda i, p: _member_tree(state, i, p))(isl, win1)
    parent2 = jax.vmap(lambda i, p: _member_tree(state, i, p))(isl, win2)
    pscore1 = state.score[isl, win1]
    ploss1 = state.loss[isl, win1]
    pscore2 = state.score[isl, win2]
    ploss2 = state.loss[isl, win2]

    do_xover = (
        jax.random.uniform(k_flip, (L,), dtype=jnp.float32) < cfg.crossover_probability
        if cfg.crossover_probability > 0 and can_pair
        else jnp.zeros((L,), bool)
    )

    # mutation path
    def choose_kind(k, tree):
        w = _condition_weights(tree, cfg, curmaxsize)
        # all-zero guard: degenerate contexts fall back to do_nothing
        w = w.at[M_NOTHING].add(jnp.where(jnp.sum(w) <= 0, 1.0, 0.0))
        return jax.random.choice(k, 8, p=w / jnp.sum(w))

    sizes1 = jax.vmap(subtree_sizes)(parent1)
    sizes2 = jax.vmap(subtree_sizes)(parent2)

    def _mutate_once(kk, km):
        kinds_a = jax.vmap(choose_kind)(jax.random.split(kk, L), parent1)
        return jax.vmap(
            lambda k, t, m, sz: _apply_mutation(
                k, t, m, cfg, curmaxsize, temperature, sz
            )
        )(jax.random.split(km, L), parent1, kinds_a, sizes1), kinds_a

    mut_kinds = None
    if cfg.mutation_attempts <= 1:
        mutated, mut_kinds = _mutate_once(k_kind, k_mut)
    else:
        # bounded retries: re-draw kind + mutation for lanes whose earlier
        # attempts produced an invalid candidate — the in-jit analogue of the
        # reference's <=10 constraint-checked attempts
        # (/root/reference/src/Mutate.jl:247-266). Each attempt unrolls into
        # the program; opt-in via Options.device_mutation_attempts.
        def _valid(c):
            depth = jax.vmap(tree_depth)(c)
            ok = (
                (complexity_batch(c, cfg) <= curmaxsize)
                & (c.length <= N)
                & (depth <= cfg.maxdepth)
            )
            if _has_op_constraints(cfg) or cfg.nested_constraints:
                ok &= jax.vmap(lambda t: _constraints_ok(t, cfg))(c)
            return ok

        mutated = parent1
        mut_ok = jnp.zeros((L,), bool)
        for attempt in range(cfg.mutation_attempts):
            mutated_a, _ = _mutate_once(
                jax.random.fold_in(k_kind, attempt),
                jax.random.fold_in(k_mut, attempt),
            )
            take = _valid(mutated_a) & ~mut_ok
            mutated = jax.tree_util.tree_map(
                lambda a, b: jnp.where(
                    take.reshape((L,) + (1,) * (a.ndim - 1)), a, b
                ),
                mutated_a,
                mutated,
            )
            mut_ok = mut_ok | take

    # crossover path (children pair)
    xo1, xo2 = jax.vmap(lambda k, a, b, sa, sb: _crossover(k, a, b, cfg, sa, sb))(
        jax.random.split(k_xo, L), parent1, parent2, sizes1, sizes2
    )

    def pick(a, b, flag):
        return jax.tree_util.tree_map(
            lambda x, y: jnp.where(flag.reshape((L,) + (1,) * (x.ndim - 1)), x, y),
            a,
            b,
        )

    cand1 = pick(xo1, mutated, do_xover)
    # cand2 is only meaningful where do_xover; stub the rest down to a 1-node
    # leaf so the kernel's length-bounded slot loop does ~no work for them
    # (they are still scored — static [2L] batch — but at leaf cost)
    leaf_stub = Tree(
        kind=jnp.zeros((L, N), jnp.int32).at[:, 0].set(KIND_CONST),
        op=jnp.zeros((L, N), jnp.int32),
        lhs=jnp.zeros((L, N), jnp.int32),
        rhs=jnp.zeros((L, N), jnp.int32),
        feat=jnp.zeros((L, N), jnp.int32),
        val=jnp.zeros((L, N), jnp.dtype(cfg.val_dtype)),
        length=jnp.ones((L,), jnp.int32),
    )
    cand2 = pick(xo2, leaf_stub, do_xover)

    # validity: mapped complexity vs curmaxsize, structural slot fit, and
    # depth caps; one attempt, invalid falls back to the parent
    # (skip_mutation_failures semantics)
    def validate(c, parent):
        depth = jax.vmap(tree_depth)(c)
        ok = (
            (complexity_batch(c, cfg) <= curmaxsize)
            & (c.length <= N)
            & (depth <= cfg.maxdepth)
        )
        if _has_op_constraints(cfg) or cfg.nested_constraints:
            ok &= jax.vmap(lambda t: _constraints_ok(t, cfg))(c)
        out = pick(c, parent, ok)
        return out, ok

    cand1, ok1 = validate(cand1, parent1)
    cand2, ok2 = validate(cand2, parent2)

    # --- score both candidate sets in ONE batched call: [2L] trees ----------
    batch = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b], axis=0), cand1, cand2
    )
    if cfg.batching:
        # fresh with-replacement row subset per cycle; the parent's stored
        # loss is its own (stale-batch or finalize) loss — the same noise
        # the reference's accept rule sees (member.score vs a fresh
        # score_func_batched draw, /root/reference/src/Mutate.jl:268-274)
        losses = score_fn(batch, data, k_bat)  # [2L]
    else:
        losses = score_fn(batch, data)  # [2L]
    # dimensional regularization (static no-op without units): violating
    # candidates carry the additive penalty into accept, replacement, and
    # the frontier merge, like the reference's eval_loss
    losses = losses + dim_penalty_batch(batch, cfg)
    loss1, loss2 = losses[:L], losses[L:]
    comp1 = complexity_batch(cand1, cfg)  # [L] (== cand1.length sans mapping)
    comp2 = complexity_batch(cand2, cfg)
    score1 = _score_of(loss1, comp1.astype(jnp.float32), cfg, data.norm)
    score2 = _score_of(loss2, comp2.astype(jnp.float32), cfg, data.norm)

    # --- Metropolis accept (mutation path only; crossover children are
    # accepted whenever valid+finite, /root/reference/src/Mutate.jl:361-429) --
    fnorm = state.freq / jnp.maximum(jnp.sum(state.freq), 1e-30)
    sz_old = jnp.clip(comp_members[isl, win1], 0, cfg.maxsize)
    sz_new = jnp.clip(comp1, 0, cfg.maxsize)
    prob = jnp.ones((L,), jnp.float32)
    if cfg.annealing:
        delta = score1 - pscore1
        # temperature hits exactly 0 on the final cycle: IEEE inf/0 semantics
        # match the reference (NaN/0-division -> accept), so no epsilon guard
        prob = prob * jnp.exp(-delta / (cfg.alpha * temperature))
    if cfg.use_frequency:
        old_f = jnp.maximum(fnorm[sz_old], 1e-6)
        new_f = jnp.maximum(fnorm[sz_new], 1e-6)
        prob = prob * (old_f / new_f)
    u = jax.random.uniform(k_acc, (L,), dtype=jnp.float32)
    accept1 = ~(prob < u) & jnp.isfinite(loss1) & ok1
    accept1 = jnp.where(do_xover, jnp.isfinite(loss1) & ok1, accept1)
    accept2 = do_xover & jnp.isfinite(loss2) & ok2

    # final babies: candidate on accept, parent copy on reject
    baby1 = pick(cand1, parent1, accept1)
    baby2 = pick(cand2, parent2, accept2)
    bloss1 = jnp.where(accept1, loss1, ploss1)
    bscore1 = jnp.where(accept1, score1, pscore1)
    bloss2 = jnp.where(accept2, loss2, ploss2)
    bscore2 = jnp.where(accept2, score2, pscore2)

    # --- replacement: lane e of island i replaces the (2e)-th oldest member,
    # its crossover child the (2e+1)-th — distinct slots, so the whole pass
    # scatters without collisions ---------------------------------------------
    order = jnp.argsort(state.birth, axis=1)  # [I, P], oldest first
    stride = 2 if can_pair else 1
    lane_e = jnp.arange(L, dtype=jnp.int32) % E  # e of each lane (lanes are i*E+e)
    idx1 = jnp.clip(stride * lane_e, 0, P - 1)
    idx2 = jnp.clip(stride * lane_e + 1, 0, P - 1)  # only read when can_pair
    slot1 = order[isl, idx1]
    slot2 = order[isl, idx2]

    def insert(st: EvoState, member_idx, tree_b, loss_b, score_b, mask):
        """Scatter [L]-lane babies into per-island member slots where mask."""
        sel = lambda cur, new: cur.at[isl, member_idx].set(
            jnp.where(mask.reshape((L,) + (1,) * (new.ndim - 1)), new, cur[isl, member_idx])
        )
        return st._replace(
            kind=sel(st.kind, tree_b.kind),
            op=sel(st.op, tree_b.op),
            lhs=sel(st.lhs, tree_b.lhs),
            rhs=sel(st.rhs, tree_b.rhs),
            feat=sel(st.feat, tree_b.feat),
            val=sel(st.val, tree_b.val),
            length=st.length.at[isl, member_idx].set(
                jnp.where(mask, tree_b.length, st.length[isl, member_idx])
            ),
            loss=st.loss.at[isl, member_idx].set(
                jnp.where(mask, loss_b, st.loss[isl, member_idx])
            ),
            score=st.score.at[isl, member_idx].set(
                jnp.where(mask, score_b, st.score[isl, member_idx])
            ),
            birth=st.birth.at[isl, member_idx].set(
                jnp.where(mask, st.step, st.birth[isl, member_idx])
            ),
        )

    st = insert(state, slot1, baby1, bloss1, bscore1, jnp.ones((L,), bool))
    st = insert(st, slot2, baby2, bloss2, bscore2, do_xover)

    # --- frequency histogram (accepted inserts); cross-shard: psum the delta -
    comp_b1 = jnp.where(accept1, comp1, comp_members[isl, win1])
    comp_b2 = jnp.where(accept2, comp2, comp_members[isl, win2])
    fd = jnp.zeros_like(st.freq).at[jnp.clip(comp_b1, 0, cfg.maxsize)].add(
        jnp.where(accept1, 1.0, 0.0)
    )
    fd = fd.at[jnp.clip(comp_b2, 0, cfg.maxsize)].add(
        jnp.where(accept2, 1.0, 0.0)
    )
    if axis is not None:
        fd = lax.psum(fd, axis)
    freq = st.freq + fd

    # --- best-seen per complexity (the per-cycle mini hall of fame) ---------
    all_loss = jnp.concatenate([loss1, loss2])
    all_valid = jnp.concatenate(
        [jnp.isfinite(loss1) & ok1, jnp.isfinite(loss2) & ok2 & do_xover]
    )
    tree_fields = [batch.kind, batch.op, batch.lhs, batch.rhs, batch.feat, batch.val]
    st = merge_best_seen(
        st, cfg, all_loss, all_valid, tree_fields, batch.length, axis=axis,
        comps=jnp.concatenate([comp1, comp2]),
    )

    n_scored = (L + jnp.sum(do_xover)).astype(jnp.float32) * cfg.eval_fraction
    if axis is not None:
        n_scored = lax.psum(n_scored, axis)
    st = st._replace(
        freq=freq,
        key=key,
        step=st.step + 1,
        num_evals=st.num_evals + n_scored,
    )
    if not cfg.record_events:
        return st
    # recorder event log: everything the host replay needs to reconstruct
    # true parent/child lineage (models/device_recorder.py). Recorder mode
    # is mutation-only (crossover_probability=0, Options-enforced) and
    # single-attempt, so mut_kinds is always set.
    ev = {
        "kind": mut_kinds.astype(jnp.int32),  # [L] M_* index
        "win1": win1.astype(jnp.int32),  # [L] parent slot within island
        "slot1": slot1.astype(jnp.int32),  # [L] replaced slot
        "accept": accept1,  # [L] bool
        "loss": loss1,  # [L] candidate loss (batch loss under batching)
        "score": score1,  # [L]
        "ploss": ploss1,  # [L] parent loss at event time
        "pscore": pscore1,  # [L]
        "cand": (
            cand1.kind, cand1.op, cand1.lhs, cand1.rhs, cand1.feat,
            cand1.val, cand1.length,
        ),  # 7-tuple [L, N] / [L]
    }
    return st, ev


# ---------------------------------------------------------------------------
# Iteration program: ncycles x events, then migration — ONE compiled program
# ---------------------------------------------------------------------------


def _run_iteration_impl(
    state: EvoState, data, cfg: EvoConfig, score_fn, axis=None
) -> EvoState:
    """Advance every island through one full iteration (the reference's
    _dispatch_s_r_cycle, /root/reference/src/SymbolicRegression.jl:1088-1129):
    ncycles of regularized evolution with annealed temperature, then
    migration. Constant optimization runs as a separate device program
    (ops/constant_opt.py) driven by models/device_search.py.

    NOTE every argument is a device array or static — post-first-readback this
    backend charges ~100ms fixed per host-to-device transfer, so even scalars
    (curmaxsize) are computed ON DEVICE from state.iteration.

    ``axis``: shard_map island-axis mode (see _event). The PRNG key stays
    replicated across shards: each shard folds in its axis index for its own
    draws, and the replicated key advances by the same fold on every shard.

    ``data``: the dataset as a TRACED pytree (device_search.ScoreData) —
    compiled engine executables are therefore dataset-independent and shared
    across outputs/warm starts of the same shape (one ~40s compile serves a
    whole multi-output fit)."""
    key_in = state.key
    if axis is not None:
        state = state._replace(
            key=jax.random.fold_in(key_in, lax.axis_index(axis))
        )
    total = cfg.ncycles  # one batched _event per cycle (all events at once)

    # warmup-maxsize schedule (get_cur_maxsize,
    # /root/reference/src/SearchUtils.jl:458-470), on device
    if cfg.warmup_maxsize_by > 0:
        frac_done = state.iteration.astype(jnp.float32) / max(cfg.niterations, 1)
        in_warmup = frac_done / cfg.warmup_maxsize_by
        curmaxsize = jnp.minimum(
            3 + (in_warmup * (cfg.maxsize - 3)).astype(jnp.int32), cfg.maxsize
        )
    else:
        curmaxsize = jnp.asarray(cfg.maxsize, jnp.int32)

    def _temp(cycle):
        # linspace(1, 0, ncycles): the final cycle runs at exactly T=0
        # (host parity: models/single_iteration.py np.linspace(1.0, 0.0, n))
        frac = cycle.astype(jnp.float32) / max(cfg.ncycles - 1, 1)
        return 1.0 - frac if cfg.annealing else jnp.asarray(1.0)

    if not cfg.record_events:
        def body(cycle, st):
            return _event(
                st, data, cfg, score_fn, _temp(cycle), curmaxsize, axis=axis
            )

        state = lax.fori_loop(0, total, body, state)
        ev_log = None
    else:
        # per-cycle event-log buffers, filled by dynamic index updates so the
        # whole iteration stays ONE compiled program (readback happens once,
        # host-side, in models/device_recorder.py)
        vdt = jnp.dtype(cfg.val_dtype)
        I_, P_, N_ = cfg.n_islands, cfg.pop_size, cfg.n_slots
        L_ = I_ * min(cfg.events_per_cycle, P_)
        C_ = cfg.ncycles

        def zeros(shape, dt):
            return jnp.zeros((C_,) + shape, dt)

        log0 = {
            "kind": zeros((L_,), jnp.int32),
            "win1": zeros((L_,), jnp.int32),
            "slot1": zeros((L_,), jnp.int32),
            "accept": zeros((L_,), bool),
            "loss": zeros((L_,), vdt),
            "score": zeros((L_,), vdt),
            "ploss": zeros((L_,), vdt),
            "pscore": zeros((L_,), vdt),
            "cand": (
                zeros((L_, N_), jnp.int32), zeros((L_, N_), jnp.int32),
                zeros((L_, N_), jnp.int32), zeros((L_, N_), jnp.int32),
                zeros((L_, N_), jnp.int32), zeros((L_, N_), vdt),
                zeros((L_,), jnp.int32),
            ),
        }

        def body_rec(cycle, carry):
            st, log = carry
            st, ev = _event(
                st, data, cfg, score_fn, _temp(cycle), curmaxsize, axis=axis
            )
            log = jax.tree_util.tree_map(
                lambda buf, row: lax.dynamic_update_index_in_dim(
                    buf, row.astype(buf.dtype), cycle, 0
                ),
                log,
                ev,
            )
            return st, log

        state, ev_log = lax.fori_loop(0, total, body_rec, (state, log0))
    state = state._replace(iteration=state.iteration + 1)

    # frequency-window decay (proportional-smoothing variant of move_window!,
    # /root/reference/src/AdaptiveParsimony.jl:57-89; window 100k)
    total_f = jnp.sum(state.freq)
    window = 100_000.0
    state = state._replace(
        freq=jnp.where(total_f > window, state.freq * (window / total_f), state.freq)
    )

    # --- migration (reference: /root/reference/src/Migration.jl:16-38) ------
    # Under cfg.batching, migration moves to the FINALIZE program
    # (_finalize_impl): the reference migrates on finalized full-data scores
    # (main loop runs migrate! after optimize_and_simplify's
    # finalize_scores), and the stored losses here are still batch-noisy.
    mig_island = mig_hof = None
    if not cfg.batching:
        if cfg.migration:
            state = _migrate(state, cfg, use_hof=False, norm=data.norm)
            if cfg.record_events:
                state, mig_island = state
        if cfg.hof_migration:
            state = _migrate(state, cfg, use_hof=True, norm=data.norm)
            if cfg.record_events:
                state, mig_hof = state
    if axis is not None:
        # re-replicate the key: every shard derives the next key from the
        # same iteration-entry key (shard streams diverged via fold_in above)
        state = state._replace(key=jax.random.fold_in(key_in, 0x5EED))
    if not cfg.record_events:
        return state
    # pytree structure is static: cfg.migration/hof_migration are static
    log = {"events": ev_log}
    if mig_island is not None:
        log["mig_island"] = mig_island
    if mig_hof is not None:
        log["mig_hof"] = mig_hof
    return state, log


def _finalize_impl(
    state: EvoState, data, cfg: EvoConfig, score_fn, axis=None
) -> EvoState:
    """Full-data finalize under cfg.batching, as its OWN program so the
    driver can order it AFTER batch constant optimization — the reference's
    sequence (/root/reference/src/SingleIteration.jl:107-132: optimize on a
    batch sample, then finalize_scores on full data, then the main loop
    migrates):

    1. every member's stored loss/score becomes exact
       (finalize_scores, /root/reference/src/Population.jl:162-176);
    2. the best-seen frontier is rescored on full data and the finalized
       population folded back in, so membership competes on exact losses —
       a lucky minibatch draw can neither occupy a size slot nor reach the
       readback (the reference picks best_seen only after finalize,
       /root/reference/src/SingleIteration.jl:64-100);
    3. migration (skipped by run_iteration when batching) runs on the
       now-exact scores."""
    key_in = state.key
    if axis is not None:
        # same key discipline as _run_iteration_impl: shards diverge via an
        # axis-index fold for their own migration draws, and the stored key
        # re-replicates from the ENTRY key at the end
        state = state._replace(key=jax.random.fold_in(key_in, lax.axis_index(axis)))
    I, P, N = cfg.n_islands, cfg.pop_size, cfg.n_slots
    all_members = Tree(
        state.kind.reshape(I * P, N), state.op.reshape(I * P, N),
        state.lhs.reshape(I * P, N), state.rhs.reshape(I * P, N),
        state.feat.reshape(I * P, N), state.val.reshape(I * P, N),
        state.length.reshape(I * P),
    )
    full_loss = (
        score_fn(all_members, data) + dim_penalty_batch(all_members, cfg)
    ).reshape(I, P)
    inc = jnp.asarray(I * P, jnp.float32)
    if axis is not None:
        inc = lax.psum(inc, axis)  # per-shard I is local; count globally
    comp_m = _complexity_members(state, cfg)
    state = state._replace(
        loss=full_loss,
        score=_score_of(full_loss, comp_m.astype(jnp.float32), cfg, data.norm),
        num_evals=state.num_evals + inc,
    )
    bs_len = state.bs_tree[6]
    bs_batch = Tree(*state.bs_tree[:6], bs_len)
    bs_full = score_fn(bs_batch, data) + dim_penalty_batch(bs_batch, cfg)
    bs_valid = state.bs_exists & jnp.isfinite(bs_full) & (bs_len >= 1)
    state = state._replace(
        bs_loss=jnp.where(bs_valid, bs_full, jnp.inf),
        bs_exists=bs_valid,
        # bs is replicated across shards (rescore is duplicated work, not
        # extra evals), so count its rows once, without a psum
        num_evals=state.num_evals + jnp.asarray(bs_len.shape[0], jnp.float32),
    )
    state = merge_best_seen(
        state, cfg,
        full_loss.reshape(I * P),
        jnp.isfinite(full_loss.reshape(I * P)) & (all_members.length >= 1),
        [all_members.kind, all_members.op, all_members.lhs,
         all_members.rhs, all_members.feat, all_members.val],
        all_members.length,
        axis=axis,
        comps=comp_m.reshape(I * P),
    )
    mig_island = mig_hof = None
    if cfg.migration:
        state = _migrate(state, cfg, use_hof=False, norm=data.norm)
        if cfg.record_events:
            state, mig_island = state
    if cfg.hof_migration:
        state = _migrate(state, cfg, use_hof=True, norm=data.norm)
        if cfg.record_events:
            state, mig_hof = state
    if axis is not None:
        state = state._replace(key=jax.random.fold_in(key_in, 0xF17A))
    if not cfg.record_events:
        return state
    log = {}
    if mig_island is not None:
        log["mig_island"] = mig_island
    if mig_hof is not None:
        log["mig_hof"] = mig_hof
    return state, log


run_iteration = functools.partial(jax.jit, static_argnames=("cfg", "score_fn"))(
    _run_iteration_impl
)

# donated twin for the software-pipelined engine loop: the previous
# iteration's EvoState buffers are reused in place, so the double-buffered
# readback path doesn't hold two full population states alive. The engine
# dispatches the packed readback of state i BEFORE the donating call for
# state i+1, so every consumer of the donated buffers is already enqueued.
run_iteration_donated = functools.partial(
    jax.jit, static_argnames=("cfg", "score_fn"), donate_argnums=(0,)
)(_run_iteration_impl)

run_finalize = functools.partial(jax.jit, static_argnames=("cfg", "score_fn"))(
    _finalize_impl
)


def _run_iteration_fused_impl(
    state: EvoState, data, cfg: EvoConfig, score_fn, copt_impl=None,
    fin_score_fn=None, axis=None, block_fn=None,
) -> EvoState:
    """One engine iteration as a SINGLE program: evolve → (length-compacted)
    constant optimization → full-data finalize, chained inside one trace so
    XLA sees the whole iteration — the dispatch chain the engine used to issue
    (run_step + per-bucket copt_step + fin_step) collapses to one executable
    and the readback is the only other per-iteration dispatch (SR_FUSED_ITER,
    ≤2 dispatches/iteration).

    ``copt_impl``: the UNJITTED closure from a ``_make_const_opt_fn*`` builder
    (``(state, data) -> state``), or None. ``fin_score_fn``: full-data score_fn
    for the finalize leg, used only under ``cfg.batching`` (mirrors the
    unfused driver, which only builds fin_step when batching). The chained
    computations are the SAME traced functions the split path jits
    individually, so fused results are bit-identical to the split dispatch
    chain (pinned by tests/test_fused_iter.py).

    ``block_fn``: kernel-resident evolve leg (SR_ENGINE_BLOCK, static): an
    unjitted ``(state, data) -> state`` closure over
    ops/evolve_block.run_block_iteration that replaces the XLA event
    trajectory for the evolve stage. None keeps today's bit-exact path."""
    if cfg.record_events:
        raise ValueError(
            "fused iteration does not support record_events (replay drivers "
            "read per-program logs; use the split dispatch chain)"
        )
    if block_fn is not None:
        if axis is not None:
            raise ValueError(
                "SR_ENGINE_BLOCK does not support the sharded island axis"
            )
        state = block_fn(state, data)
    else:
        state = _run_iteration_impl(state, data, cfg, score_fn, axis=axis)
    if copt_impl is not None:
        state = copt_impl(state, data)
    if cfg.batching and fin_score_fn is not None:
        state = _finalize_impl(state, data, cfg, fin_score_fn, axis=axis)
    return state


run_iteration_fused = functools.partial(
    jax.jit,
    static_argnames=("cfg", "score_fn", "copt_impl", "fin_score_fn", "block_fn"),
)(_run_iteration_fused_impl)

# donated twin (see run_iteration_donated): the fused program consumes and
# re-emits the full EvoState, so the engine threads one set of state buffers
# through every iteration with zero copies
run_iteration_fused_donated = functools.partial(
    jax.jit,
    static_argnames=("cfg", "score_fn", "copt_impl", "fin_score_fn", "block_fn"),
    donate_argnums=(0,),
)(_run_iteration_fused_impl)


def _freeze_inactive(new: EvoState, old: EvoState, active):
    """Per-lane freeze for the fleet axis: keep ``new`` where the lane is
    active, the untouched ``old`` otherwise. ``active`` is a scalar bool
    under vmap, so the select broadcasts over every EvoState leaf — a
    stopped lane's state (INCLUDING its RNG key and counters) is bitwise
    frozen at its stop iteration, which is what lets a drained lane's final
    decode equal the solo run's."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(active, n, o), new, old
    )


def _run_fleet_iteration_fused_impl(
    state: EvoState, active, data, cfg: EvoConfig, score_fn, copt_impl=None,
    fin_score_fn=None, block_fn=None,
) -> EvoState:
    """N concurrent searches as ONE megaprogram per iteration: the fused
    per-iteration impl vmapped over a leading fleet axis of (EvoState,
    ScoreData) with per-lane ``active`` masking.

    Bitwise contract (pinned by tests/test_fleet.py): vmap adds a batch
    dimension without changing any lane's elementwise computation, so an
    active lane's state advances bit-identically to the same search run
    solo through ``run_iteration_fused`` — RNG included (each lane carries
    its own key) — and a masked lane is frozen verbatim. Per-lane datasets
    travel as the stacked traced ``data``, so one compiled fleet executable
    serves every same-shape fleet of the same width."""
    if cfg.record_events:
        raise ValueError(
            "fleet iteration does not support record_events (per-lane "
            "replay logs are not demuxed; run recorder sessions solo)"
        )

    def lane(st, act, d):
        new = _run_iteration_fused_impl(
            st, d, cfg, score_fn, copt_impl, fin_score_fn, block_fn=block_fn
        )
        return _freeze_inactive(new, st, act)

    return jax.vmap(lane)(state, active, data)


run_fleet_iteration_fused = functools.partial(
    jax.jit,
    static_argnames=("cfg", "score_fn", "copt_impl", "fin_score_fn", "block_fn"),
)(_run_fleet_iteration_fused_impl)

# donated twin (see run_iteration_fused_donated): one set of stacked fleet
# state buffers threads through every iteration with zero copies
run_fleet_iteration_fused_donated = functools.partial(
    jax.jit,
    static_argnames=("cfg", "score_fn", "copt_impl", "fin_score_fn", "block_fn"),
    donate_argnums=(0,),
)(_run_fleet_iteration_fused_impl)


def make_sharded_finalize(mesh, cfg_local: EvoConfig, score_fn, data_specs=None):
    """shard_map twin of make_sharded_iteration for the finalize program."""
    specs = evo_state_specs()
    from jax.sharding import PartitionSpec as _P

    from ..parallel.mesh import shard_map_compat

    fn = shard_map_compat(
        lambda st, data: _finalize_impl(st, data, cfg_local, score_fn, axis="pop"),
        mesh=mesh,
        in_specs=(specs, data_specs if data_specs is not None else _P()),
        out_specs=specs,
        check_vma=False,
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Multi-device: islands shard across the 'pop' mesh axis (SURVEY.md §2.2-2.3).
# The TPU-native analogue of the reference's one-population-per-worker
# assignment (/root/reference/src/SymbolicRegression.jl:837-1064): each device
# owns I/n_pop islands; the only cross-device traffic per cycle is the [S+1]
# frequency-delta psum and the [S+1, N] best-seen merge, riding ICI.
# ---------------------------------------------------------------------------


def evo_state_specs() -> EvoState:
    """PartitionSpecs for an EvoState sharded along the island axis ('pop'):
    per-member arrays shard their leading [I] dim; the frequency histogram,
    best-seen frontier, PRNG key and counters are replicated — kept lockstep
    by the collectives in _event / _run_iteration_impl."""
    from jax.sharding import PartitionSpec as P

    isl3 = P("pop", None, None)
    isl2 = P("pop", None)
    rep = P()
    return EvoState(
        kind=isl3, op=isl3, lhs=isl3, rhs=isl3, feat=isl3, val=isl3,
        length=isl2, loss=isl2, score=isl2, birth=isl2,
        freq=rep, bs_loss=rep, bs_tree=(rep,) * 7, bs_exists=rep,
        key=rep, step=rep, num_evals=rep, iteration=rep,
    )


def shard_evo_state(state: EvoState, mesh) -> EvoState:
    """Place an EvoState onto a mesh with the island axis sharded over 'pop'.
    Requires cfg.n_islands divisible by the mesh's 'pop' axis size."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec_leaves = jax.tree_util.tree_leaves(
        evo_state_specs(), is_leaf=lambda x: isinstance(x, P)
    )
    leaves, treedef = jax.tree_util.tree_flatten(state)
    placed = [
        jax.device_put(a, NamedSharding(mesh, s))
        for a, s in zip(leaves, spec_leaves, strict=True)
    ]
    return jax.tree_util.tree_unflatten(treedef, placed)


def make_sharded_iteration(
    mesh, cfg_local: EvoConfig, score_fn, data_specs=None, donate=False
):
    """Jitted run_iteration over a ('pop', 'rows') mesh via shard_map: each
    device advances its own island slice through the full iteration;
    frequency stats and the best-seen frontier stay globally lockstep via
    in-program collectives. ``cfg_local.n_islands`` is the PER-SHARD island
    count (global islands / pop-axis size).

    ``data_specs``: per-leaf PartitionSpecs for the ScoreData argument —
    pass device_search.score_data_specs(data) when the dataset rows are
    sharded over the mesh's 'rows' axis (score_fn must then psum over
    'rows', which _build_score_fn(rows_axis="rows") emits; the EvoState
    stays replicated along 'rows' because every rows-shard sees identical
    psum-combined losses and a replicated PRNG key). Default: data
    replicated (pytree-prefix spec)."""
    specs = evo_state_specs()
    from jax.sharding import PartitionSpec as _P

    from ..parallel.mesh import shard_map_compat

    fn = shard_map_compat(
        lambda st, data: _run_iteration_impl(
            st, data, cfg_local, score_fn, axis="pop"
        ),
        mesh=mesh,
        in_specs=(specs, data_specs if data_specs is not None else _P()),
        out_specs=specs,
        # replicated outputs are replicated by construction (psum/fold_in of
        # replicated inputs); VMA inference can't see that through the scan
        # interpreter, same as parallel/sharding.py
        check_vma=False,
    )
    # donate: in-place state buffers for the pipelined engine loop (see
    # run_iteration_donated)
    return jax.jit(fn, donate_argnums=(0,)) if donate else jax.jit(fn)


def _topn_pool(state: EvoState, cfg: EvoConfig):
    """Migration pool from the islands' best members: topn per island
    (best_sub_pop, /root/reference/src/Migration.jl:25-31). Returns the
    8-tuple (kind, op, lhs, rhs, feat, val, length, loss), rows [I*topn]."""
    I, N = cfg.n_islands, cfg.n_slots
    k = cfg.topn
    top_idx = jnp.argsort(state.score, axis=1)[:, :k]  # [I, k]
    isl = jnp.arange(I, dtype=jnp.int32)[:, None]
    return (
        state.kind[isl, top_idx].reshape(I * k, N),
        state.op[isl, top_idx].reshape(I * k, N),
        state.lhs[isl, top_idx].reshape(I * k, N),
        state.rhs[isl, top_idx].reshape(I * k, N),
        state.feat[isl, top_idx].reshape(I * k, N),
        state.val[isl, top_idx].reshape(I * k, N),
        state.length[isl, top_idx].reshape(I * k),
        state.loss[isl, top_idx].reshape(I * k),
    )


def _inject_pool(
    state: EvoState, cfg: EvoConfig, pool, pool_valid, frac, norm=None
) -> EvoState:
    """Replace Bernoulli(frac)-chosen members with uniform samples from the
    (masked) pool; the core of every migration variant. ``pool`` is the
    8-tuple layout of _topn_pool; rows where ~pool_valid are never drawn."""
    I, P = cfg.n_islands, cfg.pop_size
    (pool_kind, pool_op, pool_lhs, pool_rhs, pool_feat, pool_val,
     pool_len, pool_loss) = pool
    pool_n = pool_loss.shape[0]
    key, k_sel, k_pick, k_cnt = jax.random.split(state.key, 4)

    # both count-draw variants clamp at the number of distinct migrants
    # available, matching the reference's min(num_replace,
    # length(migrant_candidates)) — a near-empty pool (1-2 finite rows) must
    # not overwrite ~frac*P members with copies of the same tree
    n_valid = jnp.sum(pool_valid.astype(jnp.int32))
    u = jax.random.uniform(k_sel, (I, P), dtype=jnp.float32)
    rank = jnp.argsort(jnp.argsort(u, axis=1), axis=1)
    if cfg.poisson_migration:
        # Poisson-sampled replacement count per island, realized as "the k
        # lowest-ranked members by a uniform draw" (reference: poisson_sample
        # + sample-with-replacement overwrite,
        # /root/reference/src/Migration.jl:16-38 + src/Utils.jl:143-150).
        # Mean frac*P like Bernoulli, count variance matches the reference.
        n_rep = jax.random.poisson(k_cnt, frac * P, (I, 1), dtype=jnp.int32)
        replace = rank < jnp.minimum(n_rep, n_valid)
    else:
        # Bernoulli marks (u < frac); keeping only the n_valid lowest-u marks
        # applies the same clamp (marked members are exactly ranks < count)
        replace = (u < frac) & (rank < n_valid)
    # never replace into islands from an empty pool
    any_valid = jnp.any(pool_valid)
    replace = replace & any_valid
    probs = jnp.where(pool_valid, 1.0, 0.0)
    probs = probs / jnp.maximum(jnp.sum(probs), 1e-30)
    src = jax.random.choice(k_pick, pool_n, shape=(I, P), p=probs)

    def mix(cur, pool_f):
        take = pool_f[src]  # [I, P, ...]
        m = replace.reshape((I, P) + (1,) * (cur.ndim - 2))
        return jnp.where(m, take, cur)

    out_log = (replace, src) if cfg.record_events else None
    loss = jnp.where(replace, pool_loss[src], state.loss)
    if cfg.complexity_table is None:
        pool_comp = pool_len
        member_comp = state.length
    else:
        pool_comp = complexity_batch(
            Tree(pool_kind, pool_op, pool_lhs, pool_rhs, pool_feat, pool_val,
                 pool_len),
            cfg,
        )
        member_comp = _complexity_members(state, cfg)
    comp = jnp.where(replace, pool_comp[src], member_comp).astype(jnp.float32)
    score = jnp.where(
        replace, _score_of(pool_loss[src], comp, cfg, norm), state.score
    )
    state = state._replace(
        kind=mix(state.kind, pool_kind),
        op=mix(state.op, pool_op),
        lhs=mix(state.lhs, pool_lhs),
        rhs=mix(state.rhs, pool_rhs),
        feat=mix(state.feat, pool_feat),
        val=mix(state.val, pool_val),
        length=jnp.where(replace, pool_len[src], state.length),
        loss=loss,
        score=score,
        birth=jnp.where(replace, state.step, state.birth),
        key=key,
    )
    if out_log is not None:
        return state, out_log[0], out_log[1]
    return state


def _migrate(state: EvoState, cfg: EvoConfig, use_hof: bool, norm=None):
    """Replace random members with samples from the migration pool: topn per
    island (best_sub_pop) or the best-seen frontier (hof). Under
    cfg.record_events returns (state, migration log) — the host replay
    assigns migrated-in copies fresh refs (documented deviation: the
    reference's migration copies keep their source ref)."""
    if use_hof:
        pk, po, pl, pr, pf, pv, pln = state.bs_tree
        pool = (pk, po, pl, pr, pf, pv, pln,
                jnp.where(state.bs_exists, state.bs_loss, jnp.inf))
        pool_valid = state.bs_exists
        frac = cfg.fraction_replaced_hof
    else:
        pool = _topn_pool(state, cfg)
        pool_valid = jnp.isfinite(pool[7])
        frac = cfg.fraction_replaced
    out = _inject_pool(state, cfg, pool, pool_valid, frac, norm)
    if not cfg.record_events:
        return out
    state, replace, src = out
    return state, {"replace": replace, "src": src, "pool": pool}


@functools.partial(jax.jit, static_argnames=("cfg",))
def extract_topn_pool(state: EvoState, cfg: EvoConfig):
    """Jitted pool extraction for the cross-host exchange: this process's
    topn-per-island migration pool, read back compactly and allgathered over
    DCN once per iteration (models/device_search.py). The multi-host
    analogue of the reference shipping best_sub_pops through the head
    process (/root/reference/src/SymbolicRegression.jl:837-881)."""
    return _topn_pool(state, cfg)


def _migrate_from_pool_impl(
    state: EvoState, cfg: EvoConfig, pool, frac: float, norm=None
):
    pool_valid = jnp.isfinite(pool[7]) & (pool[6] >= 1)
    out = _inject_pool(state, cfg, pool, pool_valid, frac, norm)
    if not cfg.record_events:
        return out
    state, replace, src = out
    return state, {"replace": replace, "src": src, "pool": pool}


@functools.partial(jax.jit, static_argnames=("cfg", "frac"))
def migrate_from_pool(
    state: EvoState, cfg: EvoConfig, pool, frac: float, norm=None
) -> EvoState:
    """Jitted external-pool migration: inject an (allgathered, cross-host)
    pool into this process's islands with Poisson-count replacement.
    Invalid rows (non-finite loss or length < 1) are never drawn. ``norm``:
    traced score normalization (ScoreData.norm) so the program is
    dataset-independent."""
    return _migrate_from_pool_impl(state, cfg, pool, frac, norm)


@functools.partial(jax.jit, static_argnames=("cfg", "frac"))
def fleet_migrate_from_pool(
    state: EvoState, cfg: EvoConfig, pool, apply, frac: float, norm=None
) -> EvoState:
    """Fleet twin of migrate_from_pool: ``state``/``pool``/``norm`` carry a
    leading fleet axis and ``apply`` is a per-lane bool. Lanes with
    ``apply=False`` are frozen verbatim — crucially their RNG key is NOT
    consumed, exactly matching a solo run that skipped the migrate call
    (a lane whose simplify pass produced nothing must not diverge from its
    solo reference just because a fleetmate's did)."""
    if cfg.record_events:
        raise ValueError("fleet migration does not support record_events")

    def lane(st, pl, ap, nm):
        new = _migrate_from_pool_impl(st, cfg, pl, frac, nm)
        return _freeze_inactive(new, st, ap)

    return jax.vmap(lane)(state, pool, apply, norm)


def scoring_cost_probe(
    state: EvoState, data, cfg: EvoConfig, score_fn, repeats: int = 10, key=None
):
    """Estimate the scoring share of the fused iteration program.

    One iteration is ONE XLA executable, so host timers cannot segment
    tournament/mutation/crossover from scoring inside it. This probe times
    the exact scoring call the program makes — ``score_fn`` on a
    ``[2 * I * E]`` candidate batch, once per cycle (see ``_event``) —
    standalone, and scales by ``cfg.ncycles``. ROOFLINE-style accounting:
    the estimate ignores fusion between scoring and evolve bookkeeping, so
    treat it as the separable scoring cost, not an exact decomposition.

    Returns ``(scoring_ms_per_iteration, batch_rows)``.
    """
    import time as _time

    I, P = cfg.n_islands, cfg.pop_size
    E = min(cfg.events_per_cycle, P)
    rows = 2 * I * E
    idx = jnp.arange(rows, dtype=jnp.int32)
    ii, pp = idx % I, idx % P
    batch = Tree(
        state.kind[ii, pp], state.op[ii, pp], state.lhs[ii, pp],
        state.rhs[ii, pp], state.feat[ii, pp], state.val[ii, pp],
        state.length[ii, pp],
    )
    if cfg.batching:
        k = key if key is not None else jax.random.PRNGKey(0)
        call = jax.jit(lambda b, d, kk: score_fn(b, d, kk))
        args = (batch, data, k)
    else:
        call = jax.jit(lambda b, d: score_fn(b, d))
        args = (batch, data)
    call(*args).block_until_ready()  # compile outside the timed window
    t0 = _time.perf_counter()
    for _ in range(repeats):
        call(*args).block_until_ready()
    per_call = (_time.perf_counter() - t0) / repeats
    return per_call * cfg.ncycles * 1e3, rows
