"""Vectorized expression-tree surgery on flat postorder tensors — in-jit.

The device-resident evolution engine (ops/evolve.py) needs the reference's
tree-rewrite primitives (/root/reference/src/MutationFunctions.jl) expressed as
pure JAX index arithmetic so they run INSIDE a compiled program, vmapped over
whole populations. The enabling invariant is postorder contiguity: the subtree
rooted at slot ``p`` occupies exactly the contiguous slot range
``[p - size(p) + 1, p]``, and every child pointer targets a smaller slot.
Every structural mutation is therefore a piecewise-affine re-indexing
(``replace_range``) plus a pointer remap — one gather per field, no host.

Single-tree functions here take arrays of shape [N] (+ scalar length) and are
``jax.vmap``-ed by the engine. Layout matches ops/flat.py's FlatTrees row:
kind (KIND_*), op, lhs, rhs, feat (int32[N]), val (float32[N]), length.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .flat import KIND_BINARY, KIND_CONST, KIND_PAD, KIND_UNARY, KIND_VAR

__all__ = [
    "Tree",
    "subtree_sizes",
    "subtree_start",
    "extract_block",
    "replace_range",
    "random_tree",
    "tree_depth",
    "gather_slots",
]


class Tree(NamedTuple):
    """One flat postorder tree (unbatched; engine vmaps over a leading dim)."""

    kind: jax.Array  # int32[N]
    op: jax.Array  # int32[N]
    lhs: jax.Array  # int32[N]
    rhs: jax.Array  # int32[N]
    feat: jax.Array  # int32[N]
    val: jax.Array  # float32[N]
    length: jax.Array  # int32 scalar

    @property
    def n_slots(self) -> int:
        return self.kind.shape[0]


def _iota(n):
    return lax.iota(jnp.int32, n)


def gather_slots(tree: Tree, src: jax.Array):
    """All six field arrays gathered at per-slot indices ``src`` [N], as a
    one-hot MXU contraction.

    Why: a per-lane dynamic gather (``arr[src]`` under vmap) lowers to
    O(N^2) compare-selects on the VPU — measured ~230us per 6-field gather
    at [900, 24], which made tree surgery the device engine's dominant cost
    (ROOFLINE_r03.md). The same permutation as an (N, N) one-hot matmul
    rides the MXU below measurement noise. precision='highest' keeps the
    f32 val field bit-exact (one-hot rows have a single 1; bf16x3
    decomposition reproduces f32 exactly).

    Non-finite constants (a mutated constant can legitimately reach inf
    while its tree's loss stays finite) would poison the contraction —
    0 * inf = NaN across the whole row — so val enters the matmul
    sanitized and non-finite entries ride along as a small integer code,
    reconstructed after the gather.

    Returns (kind, op, lhs, rhs, feat, val) gathered arrays.

    f64 engines (EvoConfig.val_dtype="float64"): constants cannot ride the
    f32 matmul without rounding, so val takes the direct per-lane gather —
    slower, but only the int fields dominate the surgery cost and those
    still ride the MXU."""
    N = tree.n_slots
    oh = (src[:, None] == _iota(N)[None, :]).astype(jnp.float32)  # [N, N]
    val_f32 = tree.val.dtype == jnp.float32
    fields = [
        tree.kind.astype(jnp.float32),
        tree.op.astype(jnp.float32),
        tree.lhs.astype(jnp.float32),
        tree.rhs.astype(jnp.float32),
        tree.feat.astype(jnp.float32),
    ]
    if val_f32:
        finite = jnp.isfinite(tree.val)
        val_clean = jnp.where(finite, tree.val, 0.0)
        # 0 finite, 1 nan, 2 +inf, 3 -inf — exact in f32
        nf_code = jnp.where(
            finite,
            0,
            jnp.where(jnp.isnan(tree.val), 1, jnp.where(tree.val > 0, 2, 3)),
        ).astype(jnp.float32)
        fields += [val_clean, nf_code]
    stacked = jnp.stack(fields, axis=-1)  # [N, 5 or 7]
    out = jnp.einsum("nm,mf->nf", oh, stacked, precision="highest")
    if val_f32:
        code = out[:, 6].astype(jnp.int32)
        val = jnp.where(
            code == 0,
            out[:, 5],
            jnp.where(code == 1, jnp.nan, jnp.where(code == 2, jnp.inf, -jnp.inf)),
        )
    else:
        val = tree.val[src]
    return (
        out[:, 0].astype(jnp.int32),
        out[:, 1].astype(jnp.int32),
        out[:, 2].astype(jnp.int32),
        out[:, 3].astype(jnp.int32),
        out[:, 4].astype(jnp.int32),
        val,
    )


def subtree_sizes(tree: Tree) -> jax.Array:
    """size[i] = node count of the subtree rooted at slot i (postorder:
    children precede parents, so one forward pass suffices). Pad slots get 0."""
    N = tree.n_slots
    is_un = tree.kind == KIND_UNARY
    is_bin = tree.kind == KIND_BINARY
    live = tree.kind != KIND_PAD

    def body(i, size):
        l = size[tree.lhs[i]]
        r = size[tree.rhs[i]]
        s = jnp.where(
            is_bin[i], 1 + l + r, jnp.where(is_un[i], 1 + l, 1)
        ) * live[i].astype(jnp.int32)
        return size.at[i].set(s)

    return lax.fori_loop(0, N, body, jnp.zeros(N, jnp.int32))


def subtree_start(sizes: jax.Array, p) -> jax.Array:
    """First slot of the subtree rooted at p (inclusive)."""
    return p - sizes[p] + 1


def tree_depth(tree: Tree) -> jax.Array:
    """Max node depth (root = 1), one forward pass like subtree_sizes."""
    N = tree.n_slots
    is_un = tree.kind == KIND_UNARY
    is_bin = tree.kind == KIND_BINARY

    def body(i, d):
        l = d[tree.lhs[i]]
        r = d[tree.rhs[i]]
        di = jnp.where(is_bin[i], 1 + jnp.maximum(l, r), jnp.where(is_un[i], 1 + l, 1))
        return d.at[i].set(di)

    depths = lax.fori_loop(0, N, body, jnp.zeros(N, jnp.int32))
    return depths[tree.length - 1]


def extract_block(tree: Tree, a, b) -> Tree:
    """Materialize subtree block [a, b) at offset 0: arrays shifted left by a,
    internal child pointers rebased, root at slot b-a-1, pads beyond."""
    N = tree.n_slots
    j = _iota(N)
    src = jnp.clip(j + a, 0, N - 1)
    m = b - a
    inside = j < m

    g_kind, g_op, g_lhs, g_rhs, g_feat, g_val = gather_slots(tree, src)
    kind = jnp.where(inside, g_kind, KIND_PAD)
    return Tree(
        kind=kind,
        op=jnp.where(inside, g_op, 0),
        lhs=jnp.where(
            inside & (kind >= KIND_UNARY), jnp.maximum(g_lhs - a, 0), 0
        ),
        rhs=jnp.where(
            inside & (kind == KIND_BINARY), jnp.maximum(g_rhs - a, 0), 0
        ),
        feat=jnp.where(inside, g_feat, 0),
        val=jnp.where(inside, g_val, 0.0),
        length=m.astype(jnp.int32),
    )


def replace_range(tree: Tree, a, b, mat: Tree) -> Tree:
    """Replace slot range [a, b) — which MUST be a whole subtree block — with
    material ``mat`` (a self-contained block at offset 0, root at
    mat.length-1). Returns the re-knit tree; new length = L - (b-a) + m.

    Pointer algebra (postorder contiguity): slots < a are untouched; copied
    slots >= a+m had pointers c where c < a stays, c == b-1 (the old subtree
    root, referenced only by its direct parent) becomes a+m-1 (the new root),
    and c >= b shifts by m - (b-a). Callers must ensure the new length fits
    in n_slots (reject oversize candidates BEFORE calling)."""
    N = tree.n_slots
    m = mat.length
    shift = m - (b - a)
    new_len = tree.length + shift
    j = _iota(N)

    reg_pre = j < a
    reg_mat = (j >= a) & (j < a + m)
    reg_post = (j >= a + m) & (j < new_len)

    src_tree = jnp.clip(jnp.where(reg_pre, j, j - shift), 0, N - 1)
    src_mat = jnp.clip(j - a, 0, N - 1)

    t_kind, t_op, t_lhs, t_rhs, t_feat, t_val = gather_slots(tree, src_tree)
    m_kind, m_op, m_lhs, m_rhs, m_feat, m_val = gather_slots(mat, src_mat)

    def pick(tree_arr, mat_arr, fill):
        return jnp.where(
            reg_mat,
            mat_arr,
            jnp.where(reg_pre | reg_post, tree_arr, fill),
        )

    kind = pick(t_kind, m_kind, KIND_PAD)
    op = pick(t_op, m_op, 0)
    feat = pick(t_feat, m_feat, 0)
    val = pick(t_val, m_val, 0.0)

    def remap_ptr(c, ptr_mat):
        c_post = jnp.where(c < a, c, jnp.where(c == b - 1, a + m - 1, c + shift))
        return jnp.where(
            reg_mat,
            ptr_mat + a,
            jnp.where(reg_pre, c, jnp.where(reg_post, c_post, 0)),
        )

    # canonical form: pointer fields are 0 on non-operator slots (keeps
    # structural comparisons exact; no consumer reads them there)
    lhs = jnp.where(
        kind >= KIND_UNARY, jnp.clip(remap_ptr(t_lhs, m_lhs), 0, N - 1), 0
    )
    rhs = jnp.where(
        kind == KIND_BINARY, jnp.clip(remap_ptr(t_rhs, m_rhs), 0, N - 1), 0
    )
    return Tree(kind, op, lhs, rhs, feat, val, new_len.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Random tree generation (device-side `gen_random_tree_fixed_size`,
# /root/reference/src/MutationFunctions.jl:237-268) via the cycle lemma:
# sample an arity multiset with sum = m-1, shuffle, then the unique rotation
# whose Łukasiewicz path stays positive is a valid postorder program.
# ---------------------------------------------------------------------------


def random_tree(
    key: jax.Array,
    m,
    n_slots: int,
    nfeatures: int,
    n_unary: int,
    n_binary: int,
    dtype=jnp.float32,
) -> Tree:
    """A uniform-ish random postorder tree with exactly ``m`` nodes
    (m clamped to [1, n_slots], adjusted down by 1 when no unary operators
    exist and m is even — node counts must then be odd). Leaves are 50/50
    constant (standard normal value) / random feature, mirroring
    make_random_leaf (/root/reference/src/MutationFunctions.jl:167-175)."""
    N = n_slots
    k_b, k_shuf, k_ops, k_leaf, k_val = jax.random.split(key, 5)
    m = jnp.clip(m, 1, N)
    if n_binary == 0:
        b = jnp.zeros((), jnp.int32)
        m = jnp.where(n_unary == 0, 1, m)
    elif n_unary == 0:
        m = jnp.where(m % 2 == 0, jnp.maximum(m - 1, 1), m)  # need u = 0
        b = (m - 1) // 2
    else:
        b = jax.random.randint(k_b, (), 0, jnp.maximum((m - 1) // 2 + 1, 1), dtype=jnp.int32)
    u = m - 1 - 2 * b

    j = _iota(N)
    # arity array: b twos, then u ones, then leaves, then pad
    arity = jnp.where(
        j < b, 2, jnp.where(j < b + u, 1, jnp.where(j < m, 0, 0))
    ).astype(jnp.int32)
    live = j < m

    # shuffle the first m entries (pads sort to the end via +inf keys)
    keys = jnp.where(live, jax.random.uniform(k_shuf, (N,), dtype=jnp.float32), jnp.inf)
    perm = jnp.argsort(keys)
    arity = jnp.where(live, arity[perm], 0)

    # cycle lemma: prefix sums of (1 - arity) over live slots; rotate so the
    # sequence starts just after the (last) minimum -> all prefixes >= 1
    steps = jnp.where(live, 1 - arity, 0)
    prefix = jnp.cumsum(steps)
    masked = jnp.where(live, prefix, jnp.iinfo(jnp.int32).max)
    # last occurrence of the minimum
    minval = jnp.min(masked)
    r = (N - 1) - jnp.argmax((masked == minval)[::-1])
    rot_src = jnp.where(live, (r + 1 + j) % jnp.maximum(m, 1), 0)
    arity = jnp.where(live, arity[rot_src], 0)

    # assign kinds/ops/leaves
    is_bin = arity == 2
    is_un = arity == 1
    is_leaf = live & (arity == 0)
    const_mask = jax.random.uniform(k_leaf, (N,), dtype=jnp.float32) < 0.5
    if nfeatures <= 0:
        const_mask = jnp.ones((N,), bool)
    kind = jnp.where(
        is_bin,
        KIND_BINARY,
        jnp.where(
            is_un,
            KIND_UNARY,
            jnp.where(is_leaf & const_mask, KIND_CONST, KIND_VAR),
        ),
    ).astype(jnp.int32)
    kind = jnp.where(live, kind, KIND_PAD)
    k1, k2, k3 = jax.random.split(k_ops, 3)
    op = jnp.where(
        is_bin,
        jax.random.randint(k1, (N,), 0, max(n_binary, 1), dtype=jnp.int32),
        jax.random.randint(k2, (N,), 0, max(n_unary, 1), dtype=jnp.int32),
    ).astype(jnp.int32)
    feat = jax.random.randint(k3, (N,), 0, max(nfeatures, 1), dtype=jnp.int32).astype(jnp.int32)
    # independent key for values: reusing k_leaf here would correlate the
    # const/var coin with the value's sign (all constants would be negative)
    val = jax.random.normal(k_val, (N,), dtype)

    # child pointers via stack simulation (N small; scalar-ish per step)
    def body(i, carry):
        stack, sp, lhs, rhs = carry
        a_i = arity[i]
        inb = i < m
        top1 = stack[jnp.maximum(sp - 1, 0)]
        top2 = stack[jnp.maximum(sp - 2, 0)]
        lhs = lhs.at[i].set(
            jnp.where(inb & (a_i == 2), top2, jnp.where(inb & (a_i == 1), top1, 0))
        )
        rhs = rhs.at[i].set(jnp.where(inb & (a_i == 2), top1, 0))
        sp = jnp.where(inb, sp - a_i, sp)
        stack = jnp.where(inb, stack.at[jnp.maximum(sp, 0)].set(i), stack)
        sp = jnp.where(inb, sp + 1, sp)
        return stack, sp, lhs, rhs

    _, _, lhs, rhs = lax.fori_loop(
        0,
        N,
        body,
        (
            jnp.zeros(N, jnp.int32),
            jnp.zeros((), jnp.int32),
            jnp.zeros(N, jnp.int32),
            jnp.zeros(N, jnp.int32),
        ),
    )
    return Tree(kind, op, lhs, rhs, feat, val, m.astype(jnp.int32))
