"""Operator library with NaN-guarded "safe" semantics.

TPU-native re-design of the reference operator library
(/root/reference/src/Operators.jl:11-100): invalid math returns ``NaN`` so that
evaluation always completes and the finiteness check at the root decides
validity (the reference documents this mechanism at
/root/reference/src/InterfaceDynamicExpressions.jl:30-55).

Every operator is a pure elementwise JAX function, written with the
"double-where" pattern so that `jax.grad` through an invalid region yields a
clean NaN only where the *value* is NaN (no spurious NaN pollution of valid
lanes), which matters because constant optimization differentiates through the
batched evaluator.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Operator",
    "OperatorSet",
    "UNARY_OPS",
    "BINARY_OPS",
    "resolve_operators",
    "default_operator_set",
    "complexify_operator_set",
]


@dataclasses.dataclass(frozen=True)
class Operator:
    """A primitive operator usable inside expression trees.

    Attributes:
      name: canonical name (used in serialization and printing).
      arity: 1 or 2.
      fn: the JAX implementation (elementwise, NaN-guarded).
      display: infix symbol for binary operators (None -> function-call form).
      kernel_fn: optional Mosaic-safe variant used inside the Pallas kernel —
        some ops (pow, erf, gamma, inverse-hyperbolics) use primitives that
        don't lower through Mosaic; these float-only reformulations do.
    """

    name: str
    arity: int
    fn: Callable[..., jax.Array]
    display: str | None = None
    kernel_fn: Callable[..., jax.Array] | None = None

    def __call__(self, *args):
        return self.fn(*args)

    # Hash/eq include fn identity: OperatorSet is a static jit argument, and
    # two differently-implemented operators that happen to share a name must
    # NOT hit the same compiled-program cache entry.
    def __hash__(self):
        return hash((self.name, self.arity, id(self.fn)))

    def __eq__(self, other):
        return (
            isinstance(other, Operator)
            and self.name == other.name
            and self.arity == other.arity
            and self.fn is other.fn
        )


def _nan_like(x):
    return jnp.full_like(x, jnp.nan)


def _guard(invalid, safe_x, compute):
    """double-where: compute(compute-safe input) with NaN where invalid."""
    return jnp.where(invalid, jnp.nan, compute(safe_x))


# ---------------------------------------------------------------------------
# Unary operators
# ---------------------------------------------------------------------------


def safe_log(x):
    bad = x <= 0
    return _guard(bad, jnp.where(bad, 1.0, x), jnp.log)


def safe_log2(x):
    bad = x <= 0
    return _guard(bad, jnp.where(bad, 1.0, x), jnp.log2)


def safe_log10(x):
    bad = x <= 0
    return _guard(bad, jnp.where(bad, 1.0, x), jnp.log10)


def safe_log1p(x):
    bad = x <= -1
    return _guard(bad, jnp.where(bad, 0.0, x), jnp.log1p)


def safe_sqrt(x):
    bad = x < 0
    return _guard(bad, jnp.where(bad, 1.0, x), jnp.sqrt)


def safe_acosh(x):
    bad = x < 1
    return _guard(bad, jnp.where(bad, 1.0, x), jnp.arccosh)


def safe_asin(x):
    bad = jnp.abs(x) > 1
    return _guard(bad, jnp.where(bad, 0.0, x), jnp.arcsin)


def safe_acos(x):
    bad = jnp.abs(x) > 1
    return _guard(bad, jnp.where(bad, 0.0, x), jnp.arccos)


def safe_atanh(x):
    bad = jnp.abs(x) >= 1
    return _guard(bad, jnp.where(bad, 0.0, x), jnp.arctanh)


def atanh_clip(x):
    # atanh((x + 1) % 2 - 1), matching the reference's clipped variant
    # (/root/reference/src/Operators.jl:17).
    wrapped = jnp.mod(x + 1.0, 2.0) - 1.0
    return safe_atanh(wrapped)


def gamma_full(x):
    """Gamma with reflection for negative arguments, Inf->NaN."""
    ax = jnp.where(x < 0, 1.0 - x, x)  # >= 1 region, lgamma-safe
    pos = jnp.exp(jax.lax.lgamma(jnp.where(ax > 0, ax, 1.0)))
    sin_pix = jnp.sin(jnp.pi * x)
    refl = jnp.pi / (sin_pix * pos)
    out = jnp.where(x < 0, refl, jnp.exp(jax.lax.lgamma(jnp.where(x > 0, x, 1.0))))
    out = jnp.where(x == jnp.floor(x), jnp.where(x > 0, out, jnp.nan), out)
    out = jnp.where(jnp.isnan(x), jnp.nan, out)
    return jnp.where(jnp.isfinite(out), out, jnp.nan)


def square(x):
    return x * x


def cube(x):
    return x * x * x


def neg(x):
    return -x


def relu(x):
    # NaN -> 0, matching Julia's strong-zero `(x > 0) * x` (false * NaN == 0;
    # /root/reference/src/Operators.jl:90). `(x > 0) * x` in IEEE float math
    # would give 0 * NaN == NaN instead.
    return jnp.where(x > 0, x, 0.0)


def sign_op(x):
    return jnp.sign(x)


# ---------------------------------------------------------------------------
# Binary operators
# ---------------------------------------------------------------------------


def safe_pow(x, y):
    """Match the reference's safe_pow (/root/reference/src/Operators.jl:28-36):

    integer y:      y < 0 and x == 0       -> NaN
    non-integer y:  y > 0 and x < 0        -> NaN
                    y < 0 and x <= 0       -> NaN
    otherwise x ** y (negative base allowed for integer exponents).
    """
    yi = jnp.round(y)
    y_is_int = y == yi
    invalid = jnp.where(
        y_is_int,
        (yi < 0) & (x == 0),
        jnp.where(y > 0, x < 0, x <= 0),
    )
    ax = jnp.abs(x)
    ax_safe = jnp.where(invalid | (ax == 0), 1.0, ax)
    mag = jnp.where(ax == 0, jnp.where(y == 0, 1.0, 0.0), ax_safe**y)
    odd = jnp.mod(jnp.abs(yi), 2.0) == 1.0
    signed = jnp.where((x < 0) & odd, -mag, mag)
    return jnp.where(invalid, jnp.nan, signed)


def plus(x, y):
    return x + y


def sub(x, y):
    return x - y


def mult(x, y):
    return x * y


def div(x, y):
    # Julia float semantics: x/0 = +-Inf, 0/0 = NaN; the finiteness check at the
    # root rejects both. XLA matches IEEE here.
    return x / y


def mod_op(x, y):
    # Julia mod(x, y) has the sign of y (true floored modulo) == jnp.mod.
    return jnp.mod(x, y)


def greater(x, y):
    # NaN operands -> 0 (comparison false), Julia strong-zero semantics.
    return jnp.where(x > y, 1.0, 0.0) * jnp.ones_like(x)


def cond_op(x, y):
    # cond(NaN, y) == 0 and cond(x<=0, NaN) == 0, per Julia `(x > 0) * y`
    # where false is a strong zero (/root/reference/src/Operators.jl:88).
    return jnp.where(x > 0, y, jnp.zeros_like(y))


def logical_or(x, y):
    return jnp.where((x > 0) | (y > 0), 1.0, 0.0) * jnp.ones_like(x)


def logical_and(x, y):
    return jnp.where((x > 0) & (y > 0), 1.0, 0.0) * jnp.ones_like(x)


def max_op(x, y):
    return jnp.maximum(x, y)


def min_op(x, y):
    return jnp.minimum(x, y)


# ---------------------------------------------------------------------------
# Mosaic-safe kernel variants (float-only arithmetic; no int casts, no
# special-function primitives). Accuracy is f32-appropriate.
# ---------------------------------------------------------------------------


def k_safe_pow(x, y):
    """safe_pow using exp/log and float parity arithmetic only.

    The invalid mask is pure boolean algebra (&, |, ~ over comparisons) —
    ``jnp.where`` over boolean operands lowers to a select on i1 vectors,
    which Mosaic rejects ("Unsupported target bitwidth for truncation",
    arith.trunci i8 -> i1)."""
    yi = jnp.floor(y + 0.5)
    y_is_int = y == yi
    # ~(y > 0) rather than (y <= 0) so a NaN exponent lands in the x <= 0
    # check (NaN compares false to everything), matching the where-based mask.
    invalid = (y_is_int & (yi < 0) & (x == 0)) | (
        (~y_is_int) & (((y > 0) & (x < 0)) | ((~(y > 0)) & (x <= 0)))
    )
    ax = jnp.abs(x)
    ax_safe = jnp.where(invalid | (ax == 0), 1.0, ax)
    mag = jnp.exp(y * jnp.log(ax_safe))
    # IEEE pow: x**0 == 1 and 1**y == 1 even for NaN operands — the exp/log
    # form would give NaN there.
    mag = jnp.where(y == 0.0, 1.0, mag)
    mag = jnp.where(ax == 1.0, 1.0, mag)  # invalid lanes overridden below
    mag = jnp.where(ax == 0, jnp.where(y == 0, 1.0, 0.0), mag)
    half = yi * 0.5
    # non-finite yi makes (half - floor(half)) NaN (!= 0 -> true); IEEE
    # pow(±1, ±inf) == 1 and |x|^±inf carries no sign, so mask those lanes
    odd = ((half - jnp.floor(half)) != 0.0) & (jnp.abs(yi) < jnp.inf)
    signed = jnp.where((x < 0) & odd, -mag, mag)
    return jnp.where(invalid, jnp.nan, signed)


def k_erf(x):
    """Abramowitz & Stegun 7.1.26 rational approximation (|err| < 1.5e-7)."""
    s = jnp.sign(x)
    a = jnp.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * a)
    poly = t * (
        0.254829592
        + t * (-0.284496736 + t * (1.421413741 + t * (-1.453152027 + t * 1.061405429)))
    )
    return s * (1.0 - poly * jnp.exp(-a * a))


def k_erfc(x):
    return 1.0 - k_erf(x)


def k_asinh(x):
    a = jnp.abs(x)
    return jnp.sign(x) * jnp.log(a + jnp.sqrt(a * a + 1.0))


def k_acosh(x):
    bad = x < 1
    xs = jnp.where(bad, 1.0, x)
    return jnp.where(bad, jnp.nan, jnp.log(xs + jnp.sqrt(xs * xs - 1.0)))


def k_atanh(x):
    bad = jnp.abs(x) >= 1
    xs = jnp.where(bad, 0.0, x)
    return jnp.where(bad, jnp.nan, 0.5 * jnp.log((1.0 + xs) / (1.0 - xs)))


def k_atanh_clip(x):
    wrapped = x + 1.0
    wrapped = wrapped - 2.0 * jnp.floor(wrapped * 0.5)
    return k_atanh(wrapped - 1.0)


_LANCZOS_G = 7.0
_LANCZOS = (
    0.99999999999980993,
    676.5203681218851,
    -1259.1392167224028,
    771.32342877765313,
    -176.61502916214059,
    12.507343278686905,
    -0.13857109526572012,
    9.9843695780195716e-6,
    1.5056327351493116e-7,
)


def k_gamma(x):
    """Lanczos approximation with reflection; Inf/poles -> NaN."""
    neg = x < 0.5
    xr = jnp.where(neg, 1.0 - x, x)  # >= 0.5
    z = xr - 1.0
    series = _LANCZOS[0]
    for i, c in enumerate(_LANCZOS[1:]):
        series = series + c / (z + (i + 1.0))
    t = z + _LANCZOS_G + 0.5
    g = jnp.sqrt(2.0 * jnp.pi) * jnp.exp((z + 0.5) * jnp.log(t) - t) * series
    sin_pix = jnp.sin(jnp.pi * x)
    refl = jnp.pi / (sin_pix * g)
    out = jnp.where(neg, refl, g)
    is_pole = (x == jnp.floor(x)) & (x <= 0)
    out = jnp.where(is_pole, jnp.nan, out)
    return jnp.where(jnp.isfinite(out), out, jnp.nan)


def k_sinh(x):
    # exp(|x| - ln2) keeps the large-|x| range of f32 sinh (plain exp(x)
    # overflows ~0.7 earlier); the Taylor branch avoids the catastrophic
    # cancellation of 0.5*(e - 1/e) near 0.
    a = jnp.abs(x)
    half_e = jnp.exp(a - 0.6931471805599453)  # e^|x| / 2
    big = jnp.sign(x) * (half_e - 0.25 / half_e)
    x2 = x * x
    small = x + x * x2 * (1.0 / 6.0 + x2 * (1.0 / 120.0))
    return jnp.where(a < 0.5, small, big)


def k_cosh(x):
    a = jnp.abs(x)
    half_e = jnp.exp(a - 0.6931471805599453)
    return half_e + 0.25 / half_e


def k_atan(x):
    """Cephes atanf: octant range reduction + degree-4 minimax polynomial."""
    s = jnp.sign(x)
    a = jnp.abs(x)
    big = a > 2.414213562373095  # tan(3pi/8)
    mid = a > 0.4142135623730950  # tan(pi/8)
    t = jnp.where(
        big,
        -1.0 / jnp.where(a == 0, 1.0, a),
        jnp.where(mid, (a - 1.0) / (a + 1.0), a),
    )
    z = t * t
    p = ((8.05374449538e-2 * z - 1.38776856032e-1) * z + 1.99777106478e-1) * z
    y = (p - 3.33329491539e-1) * z * t + t
    y = y + jnp.where(big, 1.5707963267948966, jnp.where(mid, 0.7853981633974483, 0.0))
    return s * y


def k_asin(x):
    bad = jnp.abs(x) > 1
    xs = jnp.where(bad, 0.0, x)
    denom = jnp.sqrt(jnp.maximum(1.0 - xs * xs, 0.0))
    at_one = denom == 0.0
    r = k_atan(xs / jnp.where(at_one, 1.0, denom))
    r = jnp.where(at_one, jnp.sign(xs) * 1.5707963267948966, r)
    return jnp.where(bad, jnp.nan, r)


def k_acos(x):
    bad = jnp.abs(x) > 1
    r = 1.5707963267948966 - k_asin(jnp.where(bad, 0.0, x))
    return jnp.where(bad, jnp.nan, r)


def k_round(x):
    """Bankers' rounding (round-half-to-even), matching jnp.round and Julia's
    default RoundNearest, in float-only Mosaic-safe arithmetic."""
    r = jnp.floor(x + 0.5)
    tie = (r - x) == 0.5
    r_half = r * 0.5
    r_odd = (r_half - jnp.floor(r_half)) != 0.0
    r = jnp.where(tie & r_odd, r - 1.0, r)
    # |x| >= 2^23: every f32 is already an integer and x + 0.5 rounds away
    return jnp.where(jnp.abs(x) >= 8388608.0, x, r)


def _u(name, fn, display=None, kernel_fn=None):
    return Operator(name=name, arity=1, fn=fn, display=display, kernel_fn=kernel_fn)


def _b(name, fn, display=None, kernel_fn=None):
    return Operator(name=name, arity=2, fn=fn, display=display, kernel_fn=kernel_fn)


UNARY_OPS: dict[str, Operator] = {
    op.name: op
    for op in [
        _u("neg", neg, "-"),
        _u("square", square),
        _u("cube", cube),
        _u("exp", jnp.exp),
        _u("abs", jnp.abs),
        _u("log", safe_log),
        _u("log2", safe_log2),
        _u("log10", safe_log10),
        _u("log1p", safe_log1p),
        _u("sqrt", safe_sqrt),
        _u("sin", jnp.sin),
        _u("cos", jnp.cos),
        _u("tan", jnp.tan),
        _u("sinh", jnp.sinh, kernel_fn=k_sinh),
        _u("cosh", jnp.cosh, kernel_fn=k_cosh),
        _u("tanh", jnp.tanh),
        _u("asin", safe_asin, kernel_fn=k_asin),
        _u("acos", safe_acos, kernel_fn=k_acos),
        _u("atan", jnp.arctan, kernel_fn=k_atan),
        _u("asinh", jnp.arcsinh, kernel_fn=k_asinh),
        _u("acosh", safe_acosh, kernel_fn=k_acosh),
        _u("atanh", safe_atanh, kernel_fn=k_atanh),
        _u("atanh_clip", atanh_clip, kernel_fn=k_atanh_clip),
        _u("erf", jax.scipy.special.erf, kernel_fn=k_erf),
        _u("erfc", jax.scipy.special.erfc, kernel_fn=k_erfc),
        _u("gamma", gamma_full, kernel_fn=k_gamma),
        _u("relu", relu),
        _u("round", jnp.round, kernel_fn=k_round),
        _u("floor", jnp.floor),
        _u("ceil", jnp.ceil),
        _u("sign", sign_op),
    ]
}

BINARY_OPS: dict[str, Operator] = {
    op.name: op
    for op in [
        _b("add", plus, "+"),
        _b("sub", sub, "-"),
        _b("mult", mult, "*"),
        _b("div", div, "/"),
        _b("pow", safe_pow, "^", kernel_fn=k_safe_pow),
        _b("mod", mod_op),
        _b("greater", greater),
        _b("cond", cond_op),
        _b("logical_or", logical_or),
        _b("logical_and", logical_and),
        _b("max", max_op),
        _b("min", min_op),
    ]
}

# Aliases matching the reference's binopmap/unaopmap un-aliasing
# (/root/reference/src/Options.jl:92-150): users may write the plain name and
# get the safe variant.
_ALIASES = {
    "+": "add",
    "-": "sub",
    "*": "mult",
    "/": "div",
    "^": "pow",
    "safe_pow": "pow",
    "safe_log": "log",
    "safe_log2": "log2",
    "safe_log10": "log10",
    "safe_log1p": "log1p",
    "safe_sqrt": "sqrt",
    "safe_acosh": "acosh",
    "safe_asin": "asin",
    "safe_acos": "acos",
    "safe_atanh": "atanh",
    "plus": "add",
    "mult": "mult",
}


class OperatorSet:
    """The chosen operator vocabulary of a search (reference: OperatorEnum).

    Immutable and hashable: used as a static argument to jitted kernels, so a
    given operator set compiles exactly one XLA program per data shape.
    """

    __slots__ = ("unary", "binary", "_hash")

    def __init__(self, binary: Sequence[Operator], unary: Sequence[Operator]):
        self.binary = tuple(binary)
        self.unary = tuple(unary)
        self._hash = hash((self.binary, self.unary))
        names = [op.name for op in self.binary + self.unary]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate operators in set: {names}")

    def __setattr__(self, k, v):
        if hasattr(self, "_hash"):
            raise AttributeError("OperatorSet is immutable")
        object.__setattr__(self, k, v)

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return (
            isinstance(other, OperatorSet)
            and self.binary == other.binary
            and self.unary == other.unary
        )

    def __repr__(self):
        return (
            "OperatorSet(binary=[" + ", ".join(o.name for o in self.binary) + "], "
            "unary=[" + ", ".join(o.name for o in self.unary) + "])"
        )

    @property
    def n_binary(self):
        return len(self.binary)

    @property
    def n_unary(self):
        return len(self.unary)

    def binary_index(self, name: str) -> int:
        name = _ALIASES.get(name, name)
        for i, op in enumerate(self.binary):
            if op.name == name:
                return i
        raise KeyError(name)

    def unary_index(self, name: str) -> int:
        name = _ALIASES.get(name, name)
        for i, op in enumerate(self.unary):
            if op.name == name:
                return i
        raise KeyError(name)


def _resolve_one(spec, table: dict[str, Operator], kind: str) -> Operator:
    if isinstance(spec, Operator):
        return spec
    if callable(spec):  # raw python/jax function -> wrap
        name = getattr(spec, "__name__", None) or repr(spec)
        name = _ALIASES.get(name, name)
        if name in table:
            return table[name]
        return Operator(name=name, arity=1 if kind == "unary" else 2, fn=spec)
    if isinstance(spec, str):
        name = _ALIASES.get(spec, spec)
        if name not in table:
            raise KeyError(f"unknown {kind} operator {spec!r}; known: {sorted(table)}")
        return table[name]
    raise TypeError(f"cannot interpret operator spec {spec!r}")


def resolve_operators(binary_operators, unary_operators) -> OperatorSet:
    """Build an OperatorSet from names / callables / Operator instances."""
    binary = [_resolve_one(s, BINARY_OPS, "binary") for s in binary_operators]
    unary = [_resolve_one(s, UNARY_OPS, "unary") for s in unary_operators]
    return OperatorSet(binary=binary, unary=unary)


def default_operator_set() -> OperatorSet:
    # Reference default: binary [+, -, /, *], no unary
    # (/root/reference/src/Options.jl defaults).
    return resolve_operators(["add", "sub", "div", "mult"], [])


# ---------------------------------------------------------------------------
# Complex-plane variants. The reference evaluates complex datasets with the
# RAW functions — the real-line NaN guards are unnecessary (log/sqrt/pow are
# total on ℂ up to poles) and their `<` comparisons are undefined for complex
# inputs; its preflight then rejects operators that are not complex-total or
# not type-stable (/root/reference/src/Configure.jl:10,33-44 — abs: ℂ→ℝ fails
# type stability there and is rejected here too).
# ---------------------------------------------------------------------------

_COMPLEX_IMPLS: dict[str, Callable] = {
    "add": lambda x, y: x + y,
    "sub": lambda x, y: x - y,
    "mult": lambda x, y: x * y,
    "div": lambda x, y: x / y,
    "pow": lambda x, y: x**y,
    "square": lambda x: x * x,
    "cube": lambda x: x * x * x,
    "neg": lambda x: -x,
    "inv": lambda x: 1.0 / x,
    "cos": jnp.cos,
    "sin": jnp.sin,
    "tan": jnp.tan,
    "exp": jnp.exp,
    "log": jnp.log,
    "log2": lambda x: jnp.log(x) / np.log(2.0),
    "log10": lambda x: jnp.log(x) / np.log(10.0),
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "cosh": jnp.cosh,
    "sinh": jnp.sinh,
    "tanh": jnp.tanh,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh,
    "atanh": jnp.arctanh,
}


#: pure-numpy counterparts of _COMPLEX_IMPLS for host evaluation
#: (tree.eval_np / regressor predict): the jnp table would dispatch to the
#: default device, and XLA:TPU has no complex support at all
NP_COMPLEX_IMPLS: dict[str, Callable] = {
    "add": np.add,
    "sub": np.subtract,
    "mult": np.multiply,
    "div": np.divide,
    "pow": np.power,
    "square": lambda x: x * x,
    "cube": lambda x: x * x * x,
    "neg": np.negative,
    "inv": np.reciprocal,
    "cos": np.cos,
    "sin": np.sin,
    "tan": np.tan,
    "exp": np.exp,
    "log": np.log,
    "log2": lambda x: np.log(x) / np.log(2.0),
    "log10": lambda x: np.log(x) / np.log(10.0),
    "log1p": np.log1p,
    "sqrt": np.sqrt,
    "cosh": np.cosh,
    "sinh": np.sinh,
    "tanh": np.tanh,
    "asin": np.arcsin,
    "acos": np.arccos,
    "atan": np.arctan,
    "asinh": np.arcsinh,
    "acosh": np.arccosh,
    "atanh": np.arctanh,
}

import cmath as _cmath

#: scalar (host) counterparts of _COMPLEX_IMPLS for constant folding —
#: simplify must never pay a device dispatch for one scalar
COMPLEX_SCALAR_IMPLS: dict[str, Callable] = {
    "add": lambda x, y: x + y,
    "sub": lambda x, y: x - y,
    "mult": lambda x, y: x * y,
    "div": lambda x, y: x / y if y != 0 else complex("nan"),
    "pow": lambda x, y: x**y if not (x == 0 and y.real < 0) else complex("nan"),
    "square": lambda x: x * x,
    "cube": lambda x: x * x * x,
    "neg": lambda x: -x,
    "inv": lambda x: 1.0 / x if x != 0 else complex("nan"),
    "cos": _cmath.cos,
    "sin": _cmath.sin,
    "tan": _cmath.tan,
    "exp": _cmath.exp,
    "log": _cmath.log,
    "log2": lambda x: _cmath.log(x) / _math.log(2.0),
    "log10": lambda x: _cmath.log(x) / _math.log(10.0),
    "log1p": lambda x: _cmath.log(1.0 + x),
    "sqrt": _cmath.sqrt,
    "cosh": _cmath.cosh,
    "sinh": _cmath.sinh,
    "tanh": _cmath.tanh,
    "asin": _cmath.asin,
    "acos": _cmath.acos,
    "atan": _cmath.atan,
    "asinh": _cmath.asinh,
    "acosh": _cmath.acosh,
    "atanh": _cmath.atanh,
}


def complexify_operator_set(opset: OperatorSet) -> OperatorSet:
    """Swap every operator for its complex-plane implementation; raises for
    operators with no complex-total, type-stable variant (mirrors the
    reference preflight's rejection)."""
    def conv(op: Operator) -> Operator:
        fn = _COMPLEX_IMPLS.get(op.name)
        if fn is None:
            raise ValueError(
                f"operator {op.name!r} has no complex implementation "
                f"(complex-capable: {sorted(_COMPLEX_IMPLS)})"
            )
        return Operator(name=op.name, arity=op.arity, fn=fn, display=op.display)

    return OperatorSet(
        binary=[conv(op) for op in opset.binary],
        unary=[conv(op) for op in opset.unary],
    )


# ---------------------------------------------------------------------------
# Pure-Python scalar implementations (host-side constant folding & friends).
# Device dispatch of single scalars is pure overhead (and catastrophic over a
# tunneled TPU), so host passes use these. Semantics match the JAX table
# exactly, including the NaN guards.
# ---------------------------------------------------------------------------

import math as _math

_NAN = float("nan")


def _s_pow(x, y):
    if _math.isnan(x) or _math.isnan(y):
        # IEEE pow exceptions: pow(x, 0) == 1 and pow(1, y) == 1 even for NaN
        return 1.0 if (x == 1.0 or y == 0.0) else _NAN
    if _math.isinf(y):
        # jnp.round(±inf) == ±inf, so the JAX fn takes the integer-y branch:
        # NaN only for x == 0 with y == -inf; otherwise IEEE pow semantics
        if x == 0 and y < 0:
            return _NAN
        return float(_math.pow(x, y))
    yi = round(y)
    if y == yi:
        if yi < 0 and x == 0:
            return _NAN
        try:
            return float(_math.pow(abs(x), y)) * (-1.0 if (x < 0 and yi % 2) else 1.0)
        except OverflowError:
            return float("inf")
    if (y > 0 and x < 0) or (y < 0 and x <= 0):
        return _NAN
    try:
        return float(_math.pow(x, y))
    except OverflowError:
        return float("inf")


def _s_mod(x, y):
    if y == 0 or _math.isnan(x) or _math.isnan(y) or _math.isinf(x):
        return _NAN
    if _math.isinf(y):
        # floored modulo takes y's sign: x when signs agree (or x == 0), else y
        return float(x) if (x == 0 or (x > 0) == (y > 0)) else y
    return _math.fmod(_math.fmod(x, y) + y, y)


def _s_gamma(x):
    try:
        v = _math.gamma(x)
    except (ValueError, OverflowError):
        return _NAN
    return v if _math.isfinite(v) else _NAN


def _s_div(x, y):
    if y == 0:
        if x == 0 or _math.isnan(x):
            return _NAN
        return _math.copysign(float("inf"), x) * _math.copysign(1.0, y)
    return x / y


def _guard_s(fn, cond):
    def impl(x):
        if _math.isnan(x) or cond(x):
            return _NAN
        return float(fn(x))

    return impl


SCALAR_IMPLS: dict[str, Callable] = {
    "neg": lambda x: -x,
    "square": lambda x: x * x,
    "cube": lambda x: x * x * x,
    "exp": lambda x: _NAN if _math.isnan(x) else (_math.exp(x) if x < 709 else float("inf")),
    "abs": abs,
    "log": _guard_s(_math.log, lambda x: x <= 0),
    "log2": _guard_s(_math.log2, lambda x: x <= 0),
    "log10": _guard_s(_math.log10, lambda x: x <= 0),
    "log1p": _guard_s(_math.log1p, lambda x: x <= -1),
    "sqrt": _guard_s(_math.sqrt, lambda x: x < 0),
    "sin": _math.sin,
    "cos": _math.cos,
    "tan": _math.tan,
    "sinh": lambda x: _NAN if _math.isnan(x) else (
        _math.sinh(x) if abs(x) < 710 else _math.copysign(float("inf"), x)
    ),
    "cosh": lambda x: _NAN if _math.isnan(x) else (
        _math.cosh(x) if abs(x) < 710 else float("inf")
    ),
    "tanh": _math.tanh,
    "asin": _guard_s(_math.asin, lambda x: abs(x) > 1),
    "acos": _guard_s(_math.acos, lambda x: abs(x) > 1),
    "atan": _math.atan,
    "asinh": _math.asinh,
    "acosh": _guard_s(_math.acosh, lambda x: x < 1),
    "atanh": _guard_s(_math.atanh, lambda x: abs(x) >= 1),
    "atanh_clip": lambda x: _guard_s(_math.atanh, lambda v: abs(v) >= 1)(
        _math.fmod(_math.fmod(x + 1.0, 2.0) + 2.0, 2.0) - 1.0
    ),
    "erf": _math.erf,
    "erfc": _math.erfc,
    "gamma": _s_gamma,
    "relu": lambda x: x if x > 0 else 0.0,
    "round": lambda x: float(np.round(x)),  # banker's rounding, like jnp.round
    "floor": lambda x: _NAN if _math.isnan(x) else float(_math.floor(x)),
    "ceil": lambda x: _NAN if _math.isnan(x) else float(_math.ceil(x)),
    "sign": lambda x: _NAN if _math.isnan(x) else float(np.sign(x)),
    "add": lambda x, y: x + y,
    "sub": lambda x, y: x - y,
    "mult": lambda x, y: x * y,
    "div": _s_div,
    "pow": _s_pow,
    "mod": _s_mod,
    "greater": lambda x, y: 1.0 if x > y else 0.0,
    "cond": lambda x, y: y if x > 0 else 0.0,
    "logical_or": lambda x, y: 1.0 if (x > 0 or y > 0) else 0.0,
    "logical_and": lambda x, y: 1.0 if (x > 0 and y > 0) else 0.0,
    # NaN-propagating like jnp.maximum/minimum (Python's max/min would return
    # an operand arbitrarily when comparisons with NaN are false)
    "max": lambda x, y: _NAN if (_math.isnan(x) or _math.isnan(y)) else max(x, y),
    "min": lambda x, y: _NAN if (_math.isnan(x) or _math.isnan(y)) else min(x, y),
}


def scalar_impl(op: Operator) -> Callable:
    """Host scalar implementation of an operator; falls back to the JAX fn
    (slow but always correct) for user-defined operators."""
    fn = SCALAR_IMPLS.get(op.name)
    if fn is not None:
        return fn
    return lambda *args: float(np.asarray(op.fn(*[np.float64(a) for a in args])))
