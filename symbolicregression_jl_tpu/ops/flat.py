"""Flat (device) representation of expression-tree populations.

The TPU never sees pointer trees. A batch of trees is a struct-of-arrays of
padded postorder tensors — the design called for by SURVEY.md §7.1 and the
driver north star: host<->device traffic is only these tensors plus loss
vectors. Replaces the role of DynamicExpressions.jl's recursive ``Node``
storage for everything math-related.

Postorder invariant: children of slot ``i`` are at slots ``< i``; the root of
tree ``p`` is at slot ``length[p] - 1``. Padding slots have kind=PAD and write
zeros during evaluation; they are never read by live slots.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..tree import Node

__all__ = [
    "KIND_PAD",
    "KIND_CONST",
    "KIND_VAR",
    "KIND_UNARY",
    "KIND_BINARY",
    "FlatTrees",
    "flatten_trees",
    "unflatten_tree",
    "pad_bucket",
]

KIND_PAD = 0
KIND_CONST = 1
KIND_VAR = 2
KIND_UNARY = 3
KIND_BINARY = 4


class FlatTrees(NamedTuple):
    """A padded batch of postorder trees. All arrays share leading dim P.

    kind:   int32[P, N]  node kind (see KIND_*)
    op:     int32[P, N]  operator index within its arity table
    lhs:    int32[P, N]  left-child slot (< slot index); 0 for leaves
    rhs:    int32[P, N]  right-child slot; 0 for non-binary
    feat:   int32[P, N]  feature index for KIND_VAR slots
    val:    float[P, N]  constant value for KIND_CONST slots (the only
                         differentiable leaf array — `jax.grad` targets this)
    length: int32[P]     number of live slots; root at length-1
    """

    kind: np.ndarray
    op: np.ndarray
    lhs: np.ndarray
    rhs: np.ndarray
    feat: np.ndarray
    val: np.ndarray
    length: np.ndarray

    @property
    def n_trees(self) -> int:
        return self.kind.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.kind.shape[1]


def pad_bucket(n: int, multiple: int = 8) -> int:
    """Round a node budget up to a padding bucket so XLA compiles O(1)
    programs across the whole search (SURVEY.md §7.3 recompilation risk)."""
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def batch_bucket(n: int, minimum: int = 16) -> int:
    """Round a candidate-batch size up to a power-of-two bucket — the shared
    policy for every batched device program (scoring, constant optimization),
    bounding the compile-cache population to O(log P)."""
    b = minimum
    while b < n:
        b *= 2
    return b


def flatten_trees(
    trees: list[Node], max_nodes: int, dtype=np.float32
) -> FlatTrees:
    """Flatten host trees into one padded postorder batch (numpy; the caller
    device_puts / donates). Trees longer than max_nodes are a bug upstream —
    constraint checking caps sizes before anything is flattened."""
    P = len(trees)
    kind = np.zeros((P, max_nodes), dtype=np.int32)
    op = np.zeros((P, max_nodes), dtype=np.int32)
    lhs = np.zeros((P, max_nodes), dtype=np.int32)
    rhs = np.zeros((P, max_nodes), dtype=np.int32)
    feat = np.zeros((P, max_nodes), dtype=np.int32)
    val = np.zeros((P, max_nodes), dtype=dtype)
    length = np.zeros((P,), dtype=np.int32)

    for p, tree in enumerate(trees):
        post = tree.postorder()
        if len(post) > max_nodes:
            raise ValueError(
                f"tree {p} has {len(post)} nodes > max_nodes={max_nodes}"
            )
        slot_of = {}
        for i, n in enumerate(post):
            slot_of[id(n)] = i
            if n.degree == 0:
                if n.is_const:
                    kind[p, i] = KIND_CONST
                    val[p, i] = n.val
                else:
                    kind[p, i] = KIND_VAR
                    feat[p, i] = n.feat
            elif n.degree == 1:
                kind[p, i] = KIND_UNARY
                op[p, i] = n.op
                lhs[p, i] = slot_of[id(n.l)]
            else:
                kind[p, i] = KIND_BINARY
                op[p, i] = n.op
                lhs[p, i] = slot_of[id(n.l)]
                rhs[p, i] = slot_of[id(n.r)]
        length[p] = len(post)

    return FlatTrees(kind, op, lhs, rhs, feat, val, length)


def unflatten_tree(flat: FlatTrees, p: int) -> Node:
    """Rebuild a host tree from batch row p (round-trip of flatten_trees)."""
    kind = np.asarray(flat.kind[p])
    op_arr = np.asarray(flat.op[p])
    lhs = np.asarray(flat.lhs[p])
    rhs = np.asarray(flat.rhs[p])
    feat = np.asarray(flat.feat[p])
    val = np.asarray(flat.val[p])
    n = int(np.asarray(flat.length[p]))

    nodes: list[Node] = []
    for i in range(n):
        k = int(kind[i])
        if k == KIND_CONST:
            nodes.append(Node(0, is_const=True, val=float(val[i])))
        elif k == KIND_VAR:
            nodes.append(Node(0, is_const=False, feat=int(feat[i])))
        elif k == KIND_UNARY:
            nodes.append(Node(1, op=int(op_arr[i]), l=nodes[int(lhs[i])]))
        elif k == KIND_BINARY:
            nodes.append(
                Node(2, op=int(op_arr[i]), l=nodes[int(lhs[i])], r=nodes[int(rhs[i])])
            )
        else:
            raise ValueError(f"pad slot {i} inside live range of tree {p}")
    return nodes[-1]
