"""Flat (device) representation of expression-tree populations.

The TPU never sees pointer trees. A batch of trees is a struct-of-arrays of
padded postorder tensors — the design called for by SURVEY.md §7.1 and the
driver north star: host<->device traffic is only these tensors plus loss
vectors. Replaces the role of DynamicExpressions.jl's recursive ``Node``
storage for everything math-related.

Postorder invariant: children of slot ``i`` are at slots ``< i``; the root of
tree ``p`` is at slot ``length[p] - 1``. Padding slots have kind=PAD and write
zeros during evaluation; they are never read by live slots.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..tree import Node

__all__ = [
    "KIND_PAD",
    "KIND_CONST",
    "KIND_VAR",
    "KIND_UNARY",
    "KIND_BINARY",
    "PACK_KIND_BITS",
    "PACK_KIND_MASK",
    "PACK_PAYLOAD_MAX",
    "FlatTrees",
    "FlatSlab",
    "PackedPrograms",
    "flatten_trees",
    "unflatten_tree",
    "pack_programs",
    "unpack_programs",
    "pack_words",
    "pad_bucket",
    "bucket_min",
    "bucket_sizes",
    "length_buckets",
    "slice_nodes",
]

KIND_PAD = 0
KIND_CONST = 1
KIND_VAR = 2
KIND_UNARY = 3
KIND_BINARY = 4

# Packed device-IR word layout (PackedPrograms): bits 0..2 carry the KIND_*
# code, bits 3..14 carry the payload — the operator index for UNARY/BINARY
# slots, the feature index for VAR slots, 0 for CONST/PAD. An int16 word
# therefore admits payloads up to 4095, far above any realistic operator
# table or feature count; verify_packed_programs enforces the real bounds.
PACK_KIND_BITS = 3
PACK_KIND_MASK = (1 << PACK_KIND_BITS) - 1
PACK_PAYLOAD_MAX = (1 << (15 - PACK_KIND_BITS)) - 1


class FlatTrees(NamedTuple):
    """A padded batch of postorder trees. All arrays share leading dim P.

    kind:   int32[P, N]  node kind (see KIND_*)
    op:     int32[P, N]  operator index within its arity table
    lhs:    int32[P, N]  left-child slot (< slot index); 0 for leaves
    rhs:    int32[P, N]  right-child slot; 0 for non-binary
    feat:   int32[P, N]  feature index for KIND_VAR slots
    val:    float[P, N]  constant value for KIND_CONST slots (the only
                         differentiable leaf array — `jax.grad` targets this)
    length: int32[P]     number of live slots; root at length-1
    """

    kind: np.ndarray
    op: np.ndarray
    lhs: np.ndarray
    rhs: np.ndarray
    feat: np.ndarray
    val: np.ndarray
    length: np.ndarray

    @property
    def n_trees(self) -> int:
        return self.kind.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.kind.shape[1]


def pad_bucket(n: int, multiple: int = 8) -> int:
    """Round a node budget up to a padding bucket so XLA compiles O(1)
    programs across the whole search (SURVEY.md §7.3 recompilation risk)."""
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def batch_bucket(n: int, minimum: int = 16) -> int:
    """Round a candidate-batch size up to a power-of-two bucket — the shared
    policy for every batched device program (scoring, constant optimization),
    bounding the compile-cache population to O(log P)."""
    b = minimum
    while b < n:
        b *= 2
    return b


def length_buckets_enabled() -> bool:
    """Env kill-switch for the length-bucketed interpreter dispatch
    (``SR_LENGTH_BUCKETS=0`` restores single full-width programs — used by
    the bit-identity tests and the bench A/B)."""
    import os

    return os.environ.get("SR_LENGTH_BUCKETS", "1") != "0"


def bucket_min() -> int:
    """Smallest node bucket (``SR_BUCKET_MIN``, default 16). The bucket
    ladder trades compile count for scan length: every extra bucket is one
    more compiled program per hot path (scoring, BFGS, engine switch
    branches). The default keeps small-``max_nodes`` configs (<= 16 — the
    common test/tuning sizes) on a SINGLE full-width program — identical to
    the unbucketed seed — while big-maxsize searches still split; set
    ``SR_BUCKET_MIN=8`` for the full ladder when the per-iteration runtime
    dwarfs compiles (the committed engine-profile A/B does)."""
    import os

    return int(os.environ.get("SR_BUCKET_MIN", 16))


def bucket_sizes(max_nodes: int, minimum: int | None = None) -> tuple[int, ...]:
    """Node-count dispatch buckets for the interpreter hot paths: powers of
    two from ``minimum`` (default ``bucket_min()``) up, capped by (and
    always ending at) ``max_nodes`` — the node-axis analogue of
    ``batch_bucket``'s policy, so a search compiles O(log N) scan lengths
    instead of one per tree length."""
    if minimum is None:
        minimum = bucket_min()
    sizes: list[int] = []
    b = minimum
    while b < max_nodes:
        sizes.append(b)
        b *= 2
    sizes.append(max_nodes)
    return tuple(sizes)


def length_buckets(
    lengths, max_nodes: int, minimum: int | None = None
) -> list[tuple[int, np.ndarray]]:
    """Partition a batch by tree length into the ``bucket_sizes`` families.

    Returns ``[(n_b, row_indices)]`` with every row assigned to the smallest
    bucket that holds it; empty buckets are dropped. Host-side numpy — the
    caller slices the flat batch per bucket (``slice_nodes``) and dispatches
    each group to the bucket-sized compiled program.
    """
    lengths = np.asarray(lengths)
    out: list[tuple[int, np.ndarray]] = []
    prev = 0
    for n_b in bucket_sizes(max_nodes, minimum):
        if prev == 0:
            sel = np.nonzero(lengths <= n_b)[0]
        else:
            sel = np.nonzero((lengths > prev) & (lengths <= n_b))[0]
        if sel.size:
            out.append((n_b, sel))
        prev = n_b
    return out


def slice_nodes(flat: FlatTrees, n: int) -> FlatTrees:
    """Truncate the node axis to ``n`` slots. Valid whenever every row's
    length is <= n: postorder children live at strictly smaller slots and
    pad slots are never read, so evaluation (and its VJP) over the truncated
    batch is bit-identical to the full-width program. Works on numpy and
    traced arrays alike."""
    return FlatTrees(
        flat.kind[:, :n], flat.op[:, :n], flat.lhs[:, :n], flat.rhs[:, :n],
        flat.feat[:, :n], flat.val[:, :n], flat.length,
    )


def flatten_trees(
    trees: list[Node], max_nodes: int, dtype=np.float32
) -> FlatTrees:
    """Flatten host trees into one padded postorder batch (numpy; the caller
    device_puts / donates). Trees longer than max_nodes are a bug upstream —
    constraint checking caps sizes before anything is flattened.

    Uses the srcore native kernel when available (~10x; see native/)."""
    P = len(trees)
    kind = np.zeros((P, max_nodes), dtype=np.int32)
    op = np.zeros((P, max_nodes), dtype=np.int32)
    lhs = np.zeros((P, max_nodes), dtype=np.int32)
    rhs = np.zeros((P, max_nodes), dtype=np.int32)
    feat = np.zeros((P, max_nodes), dtype=np.int32)
    val = np.zeros((P, max_nodes), dtype=dtype)
    length = np.zeros((P,), dtype=np.int32)

    if P and np.dtype(dtype) == np.float32 and max_nodes <= 4096:
        from ..native import get_srcore

        core = get_srcore()
        if core is not None:
            core.flatten_batch(trees, kind, op, lhs, rhs, feat, val, length)
            return FlatTrees(kind, op, lhs, rhs, feat, val, length)

    for p, tree in enumerate(trees):
        post = tree.postorder()
        if len(post) > max_nodes:
            raise ValueError(
                f"tree {p} has {len(post)} nodes > max_nodes={max_nodes}"
            )
        slot_of = {}
        for i, n in enumerate(post):
            slot_of[id(n)] = i
            if n.degree == 0:
                if n.is_const:
                    kind[p, i] = KIND_CONST
                    val[p, i] = n.val
                else:
                    kind[p, i] = KIND_VAR
                    feat[p, i] = n.feat
            elif n.degree == 1:
                kind[p, i] = KIND_UNARY
                op[p, i] = n.op
                lhs[p, i] = slot_of[id(n.l)]
            else:
                kind[p, i] = KIND_BINARY
                op[p, i] = n.op
                lhs[p, i] = slot_of[id(n.l)]
                rhs[p, i] = slot_of[id(n.r)]
        length[p] = len(post)

    return FlatTrees(kind, op, lhs, rhs, feat, val, length)


class FlatSlab:
    """Persistent population slab in the fused Mosaic kernel's packed layout.

    Owns ints [capacity, L] (code | lhs | rhs | feat | length per tree, where
    code = 0 const, 1 var, 2+op unary, 2+n_unary+op binary) and vals
    [capacity, Lv]. Callers re-flatten ONLY the members that changed
    (``set_tree``), so steady-state host cost is proportional to the mutation
    rate, not the population size. Feeds make_packed_loss_fn directly —
    no per-sweep concatenation or re-padding.

    NOTE: this writer, flatten_trees, and pack_flat_fused (interp_pallas.py)
    must agree on the packed layout; tests/test_pallas.py's
    test_packed_slab_matches_flatten pins slab == flatten+pack agreement.
    """

    def __init__(self, capacity: int, n_slots: int, opset, dtype=np.float32):
        def _ru(n, m=128):
            return ((n + m - 1) // m) * m

        self.capacity = capacity
        self.n_slots = n_slots
        self.opset = opset
        self.L = _ru(4 * n_slots + 1)
        self.Lv = _ru(n_slots)
        self.ints = np.zeros((capacity, self.L), np.int32)
        self.vals = np.zeros((capacity, self.Lv), dtype)
        self._una_off = 2
        self._bin_off = 2 + opset.n_unary

    def set_tree(self, i: int, tree: Node) -> None:
        N = self.n_slots
        row = self.ints[i]
        vrow = self.vals[i]
        row[: 4 * N + 1] = 0
        vrow[:N] = 0
        post = tree.postorder()
        if len(post) > N:
            raise ValueError(f"tree has {len(post)} nodes > n_slots={N}")
        slot_of = {}
        for s, n in enumerate(post):
            slot_of[id(n)] = s
            if n.degree == 0:
                if n.is_const:
                    vrow[s] = n.val
                else:
                    row[s] = 1
                    row[3 * N + s] = n.feat
            elif n.degree == 1:
                row[s] = self._una_off + n.op
                row[N + s] = slot_of[id(n.l)]
            else:
                row[s] = self._bin_off + n.op
                row[N + s] = slot_of[id(n.l)]
                row[2 * N + s] = slot_of[id(n.r)]
        row[4 * N] = len(post)

    def set_trees(self, trees: list[Node], start: int = 0) -> None:
        if start < 0 or start + len(trees) > self.capacity:
            raise IndexError(
                f"slab write [{start}, {start + len(trees)}) exceeds "
                f"capacity {self.capacity}"
            )
        if trees and self.vals.dtype == np.float32 and self.n_slots <= 4096:
            from ..native import get_srcore

            core = get_srcore()
            if core is not None:
                core.slab_fill(
                    trees, self.ints, self.vals, start, self.n_slots, self._bin_off
                )
                return
        for k, t in enumerate(trees):
            self.set_tree(start + k, t)


class PackedPrograms(NamedTuple):
    """Pointerless packed device-IR for a batch of postorder programs.

    This is the kernel-resident form the evolve-block engine mutates in
    place: one int16 word per slot (kind in the low ``PACK_KIND_BITS`` bits,
    payload above — see PACK_* constants) plus a separate f32 constants lane.
    Child pointers are NOT stored: postorder contiguity makes them fully
    recomputable by a single stack pass (``unpack_programs`` /
    ``evolve_block._block_pointers``), which is what lets whole subtrees
    move as contiguous word ranges during mutation with no pointer fixups.

    words:  int16[P, N]  kind | payload << PACK_KIND_BITS
    consts: float[P, N]  constant value at KIND_CONST slots, exactly 0 elsewhere
    length: int32[P]     number of live slots; root at length-1
    """

    words: np.ndarray
    consts: np.ndarray
    length: np.ndarray

    @property
    def n_trees(self) -> int:
        return self.words.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.words.shape[1]


def pack_words(kind, op, feat, val, length=None, xp=np):
    """Elementwise packing shared by the numpy and traced paths: returns
    ``(words, consts)`` from FlatTrees-style field arrays (lhs/rhs are
    dropped — they are recomputable). ``xp`` is numpy or jax.numpy; the
    traced caller passes device arrays and gets a traced pair back.

    Payload slots outside the live range are forced to zero through the
    kind masks (pad kind is 0 everywhere the state is canonical), so packing
    a canonical population yields canonical packed programs with zeroed
    pad words/consts — the invariants verify_packed_programs pins.
    """
    kind = xp.asarray(kind)
    payload = xp.where(
        (kind == KIND_UNARY) | (kind == KIND_BINARY),
        xp.asarray(op),
        xp.where(kind == KIND_VAR, xp.asarray(feat), 0),
    )
    words = (kind | (payload << PACK_KIND_BITS)).astype(xp.int16)
    consts = xp.where(kind == KIND_CONST, xp.asarray(val), 0).astype(
        xp.asarray(val).dtype
    )
    return words, consts


def pack_programs(flat: FlatTrees) -> PackedPrograms:
    """Pack a FlatTrees batch into the pointerless device IR (numpy)."""
    words, consts = pack_words(
        np.asarray(flat.kind), np.asarray(flat.op), np.asarray(flat.feat),
        np.asarray(flat.val), xp=np,
    )
    return PackedPrograms(words, consts, np.asarray(flat.length, np.int32))


def unpack_programs(packed: PackedPrograms, dtype=None) -> FlatTrees:
    """Exact round-trip of ``pack_programs``: rebuild the FlatTrees batch,
    reconstructing lhs/rhs child pointers with a postfix stack pass (numpy).

    Raises ValueError on stack-discipline violations (a malformed packed
    row cannot silently produce a plausible tree)."""
    words = np.asarray(packed.words)
    consts = np.asarray(packed.consts)
    length = np.asarray(packed.length, np.int32)
    P, N = words.shape
    w32 = words.astype(np.int32)
    kind = (w32 & PACK_KIND_MASK).astype(np.int32)
    payload = (w32 >> PACK_KIND_BITS).astype(np.int32)

    op = np.where(
        (kind == KIND_UNARY) | (kind == KIND_BINARY), payload, 0
    ).astype(np.int32)
    feat = np.where(kind == KIND_VAR, payload, 0).astype(np.int32)
    val = np.where(kind == KIND_CONST, consts, 0).astype(
        consts.dtype if dtype is None else dtype
    )
    lhs = np.zeros((P, N), np.int32)
    rhs = np.zeros((P, N), np.int32)

    for p in range(P):
        stack: list[int] = []
        for i in range(int(length[p])):
            k = kind[p, i]
            if k == KIND_UNARY:
                if len(stack) < 1:
                    raise ValueError(f"row {p}: unary at slot {i} underflows")
                lhs[p, i] = stack.pop()
            elif k == KIND_BINARY:
                if len(stack) < 2:
                    raise ValueError(f"row {p}: binary at slot {i} underflows")
                rhs[p, i] = stack.pop()
                lhs[p, i] = stack.pop()
            elif k == KIND_PAD:
                raise ValueError(f"row {p}: pad slot {i} inside live range")
            stack.append(i)
        if int(length[p]) and len(stack) != 1:
            raise ValueError(
                f"row {p}: {len(stack)} roots after postfix pass (want 1)"
            )
    return FlatTrees(kind, op, lhs, rhs, feat, val, length)


def unflatten_tree(flat: FlatTrees, p: int) -> Node:
    """Rebuild a host tree from batch row p (round-trip of flatten_trees)."""
    kind = np.asarray(flat.kind[p])
    op_arr = np.asarray(flat.op[p])
    lhs = np.asarray(flat.lhs[p])
    rhs = np.asarray(flat.rhs[p])
    feat = np.asarray(flat.feat[p])
    val = np.asarray(flat.val[p])
    n = int(np.asarray(flat.length[p]))

    nodes: list[Node] = []
    for i in range(n):
        k = int(kind[i])
        if k == KIND_CONST:
            # .item() keeps complex constants complex (float() would raise)
            nodes.append(Node(0, is_const=True, val=val[i].item()))
        elif k == KIND_VAR:
            nodes.append(Node(0, is_const=False, feat=int(feat[i])))
        elif k == KIND_UNARY:
            nodes.append(Node(1, op=int(op_arr[i]), l=nodes[int(lhs[i])]))
        elif k == KIND_BINARY:
            nodes.append(
                Node(2, op=int(op_arr[i]), l=nodes[int(lhs[i])], r=nodes[int(rhs[i])])
            )
        else:
            raise ValueError(f"pad slot {i} inside live range of tree {p}")
    return nodes[-1]
