"""Kernel-resident evolution block over the packed int16 program IR (r17).

One iteration of regularized evolution — ncycles of tournament -> mutate ->
check -> score -> accept — expressed entirely over :class:`~.flat.
PackedPrograms` words so a whole block runs without candidates leaving the
chip. The SAME values-based implementation (`_block_cycle` and its helpers)
drives BOTH backends:

- the **Pallas kernel** (ops/interp_pallas.make_evolve_block_fn): grid over
  islands, population words live in VMEM, scoring reuses the loss kernel's
  scratch-buffer slot loop;
- the **XLA reference** (`run_block_iteration(..., kernel_fn=None)`): the
  identical cycle driver vmapped over islands with a value-based evaluator.

Only the ``eval_fn`` callback differs, and both evaluators apply the same op
sequence to identically-shaped (8, C) row tiles, so interpret-mode kernel
losses are bitwise equal to the reference and accept decisions agree
deterministically (tests/test_pallas_interpret.py pins this).

Mosaic cannot run jax.random's threefry, so the block derives every draw
from a counter hash (`_blk_bits`: murmur3-style mixing of
(seed, cycle, lane, draw-id)) — reproducible, order-independent, identical
arithmetic on both backends. The seed comes from one `jax.random.split` of
the engine key per iteration, so block runs stay deterministic per seed.

Documented divergences from the ``_event`` XLA trajectory (opt-in via
SR_ENGINE_BLOCK, quality-A/B'd by bench artifacts; SR_ENGINE_BLOCK=0 keeps
today's bit-exact path):

- tournament draws candidates WITH replacement (argsort of P uniforms is
  not kernel-expressible) and picks the rank via inverse-CDF;
- crossover and full-tree randomize are dropped (their weights fold into
  do-nothing); the mutation set is constant-perturb / operator-swap /
  rotate / add / insert / delete on packed words;
- the size-frequency histogram is SNAPSHOT at block entry (cross-island
  per-cycle merging would serialize the island grid); deltas accumulate
  per island and merge once at block exit, as does the best-seen frontier
  (per-size min is associative, so the frontier CONTENT matches).

Eligibility is gated hard (`block_eligible`): no recorder, no batching, no
sub-sampled eval, no custom complexity mapping, no operator/nesting/units
constraints, f32 values only.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .evolve import (
    EvoConfig,
    EvoState,
    M_ADD,
    M_CONST,
    M_DELETE,
    M_INSERT,
    M_NOTHING,
    M_OPERATOR,
    M_RANDOMIZE,
    M_SWAP,
    _has_op_constraints,
    _migrate,
    _score_of,
    merge_best_seen,
)
from .flat import (
    KIND_BINARY,
    KIND_CONST,
    KIND_PAD,
    KIND_UNARY,
    KIND_VAR,
    PACK_KIND_BITS,
    PACK_KIND_MASK,
    pack_words,
)

__all__ = [
    "block_eligible",
    "run_block_iteration",
    "make_reference_eval",
]

# --------------------------------------------------------------------------
# Counter-derived RNG: every draw is a pure hash of (seed, cycle, lane, id).
# Draw-id table — one slot per independent decision a lane makes in a cycle.
# Tournament draws occupy ids [0, 32); everything else is fixed below.
# --------------------------------------------------------------------------
D_RANK = 32
D_KIND = 33
D_SITE = 34
D_CHILD = 35
D_ACCEPT = 36
D_C_FACTOR = 37
D_C_INV = 38
D_C_NEG = 39
D_OP_UN = 40
D_OP_BIN = 41
D_L1_CONST = 42
D_L1_FEAT = 43
D_L1_N1 = 44
D_L1_N2 = 45
D_L2_CONST = 46
D_L2_FEAT = 47
D_L2_N1 = 48
D_L2_N2 = 49
D_M_OPB = 50
D_M_OPU = 51


def _fmix(x):
    """murmur3 finalizer on uint32 (identical integer arithmetic on every
    backend — the whole point vs jax.random inside Mosaic)."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    return x


def _blk_bits(seed, cycle, lane, draw: int):
    """uint32 hash of (seed, cycle, lane, draw). ``lane`` may be a vector;
    ``draw`` is a static python int from the D_* table."""
    x = seed.astype(jnp.uint32) ^ (
        jnp.uint32(0x9E3779B9) * (cycle.astype(jnp.uint32) + jnp.uint32(1))
    )
    x = _fmix(x)
    x = x ^ (jnp.uint32(0x85EBCA6B) * (lane.astype(jnp.uint32) + jnp.uint32(1)))
    x = _fmix(x)
    x = x ^ (jnp.uint32(0xC2B2AE35) * jnp.uint32(draw + 1))
    return _fmix(x)


def _blk_u01(bits):
    """[0, 1) f32 from the top 24 bits (exactly representable)."""
    return (bits >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(
        1.0 / 16777216.0
    )


def _blk_normal(u1, u2):
    """Box-Muller standard normal from two uniforms."""
    r = jnp.sqrt(-2.0 * jnp.log(jnp.maximum(u1, jnp.float32(1e-12))))
    return r * jnp.cos(jnp.float32(2.0 * np.pi) * u2)


def _randint(u, n):
    """Integer in [0, n) from u in [0, 1). ``n`` int scalar/array, >= 1."""
    n = jnp.asarray(n, jnp.int32)
    return jnp.minimum((u * n.astype(jnp.float32)).astype(jnp.int32), n - 1)


# --------------------------------------------------------------------------
# Mosaic-safe primitives: one-hot reads/gathers instead of argsort/dynamic
# indexing. Shapes are small ([E, P], [E, N, N]) so the masked sums are
# noise next to scoring.
# --------------------------------------------------------------------------


def _it(n):
    return lax.broadcasted_iota(jnp.int32, (n,), 0)


def _take(mat, idx):
    """mat [..., V], idx [...] int32 -> [...]: one-hot masked-sum dynamic
    read (exact for every dtype, inf/nan-safe — no multiplies)."""
    V = mat.shape[-1]
    oh = idx[..., None] == _it(V)
    # dtype pinned: integer sums otherwise widen to int64 under x64 (flipped
    # process-globally by any f64 search) and break the fori_loop carry
    return jnp.sum(
        jnp.where(oh, mat, jnp.zeros((), mat.dtype)), axis=-1, dtype=mat.dtype
    )


def _gather_vec(vec, idx):
    """vec [V], idx [...] -> [...]."""
    oh = idx[..., None] == _it(vec.shape[0])
    return jnp.sum(
        jnp.where(oh, vec, jnp.zeros((), vec.dtype)), axis=-1, dtype=vec.dtype
    )


def _gather_rows(mat, idx):
    """mat [R, N], idx [K] -> [K, N] (row gather via one-hot masked sum)."""
    oh = idx[:, None] == _it(mat.shape[0])  # [K, R]
    return jnp.sum(
        jnp.where(oh[:, :, None], mat[None, :, :], jnp.zeros((), mat.dtype)),
        axis=1,
        dtype=mat.dtype,
    )


def _permute_cols(mat, src, use_move):
    """out[e, j] = mat[e, src[e, j]] where use_move[e, j] else mat[e, j].
    The subtree-block mover every structural mutation rides."""
    N = mat.shape[-1]
    oh = src[:, :, None] == _it(N)  # [E, N, N]
    g = jnp.sum(
        jnp.where(oh, mat[:, None, :], jnp.zeros((), mat.dtype)),
        axis=-1,
        dtype=mat.dtype,
    )
    return jnp.where(use_move, g, mat)


def _first_true(mask):
    """Index of the first True along the last axis (size if none)."""
    N = mask.shape[-1]
    return jnp.min(jnp.where(mask, _it(N), N), axis=-1).astype(jnp.int32)


def _cumsum_i32(mask):
    """Inclusive cumsum of a bool mask along the last axis, int32."""
    return jnp.cumsum(mask.astype(jnp.int32), axis=-1, dtype=jnp.int32)


def _pick_ranked(mask, u, count):
    """Slot index of the ``pick``-th True in ``mask`` [E, N], where pick is
    drawn uniformly from [0, count) (count = mask row-sums, >= 1 clamped) —
    the cumsum-rank site chooser `_mutate_constant`/_mutate_operator use."""
    ranks = _cumsum_i32(mask) - 1
    pick = _randint(u, jnp.maximum(count, 1))
    return _first_true(mask & (ranks == pick[:, None]))


# --------------------------------------------------------------------------
# Pointer/extent reconstruction: ONE stack pass over the packed words gives
# lhs/rhs child slots, subtree start, and subtree depth per node. Statically
# unrolled over N (traced once per fori body).
# --------------------------------------------------------------------------


def _block_pointers(words, length):
    """words [B, N] int32 packed, length [B] -> (lhs, rhs, start, depth),
    each [B, N] int32. depth[i] is the subtree depth rooted at i (leaf=1);
    garbage-free only at live slots of stack-sound rows (mutations preserve
    soundness by construction; verify_packed_programs pins it in tests)."""
    B, N = words.shape
    D = N // 2 + 2
    kind = words & PACK_KIND_MASK
    iota_n = _it(N)
    iota_d = _it(D)
    live_all = iota_n[None, :] < length[:, None]

    sp = jnp.zeros((B,), jnp.int32)
    st_slot = jnp.zeros((B, D), jnp.int32)
    st_start = jnp.zeros((B, D), jnp.int32)
    st_depth = jnp.zeros((B, D), jnp.int32)
    lhs = jnp.zeros((B, N), jnp.int32)
    rhs = jnp.zeros((B, N), jnp.int32)
    start = jnp.zeros((B, N), jnp.int32)
    depth = jnp.zeros((B, N), jnp.int32)

    for i in range(N):
        k = kind[:, i]
        live = live_all[:, i]
        is_leaf = (k == KIND_CONST) | (k == KIND_VAR)
        is_un = k == KIND_UNARY
        is_bin = k == KIND_BINARY
        t1 = jnp.maximum(sp - 1, 0)
        t2 = jnp.maximum(sp - 2, 0)
        top1s = _take(st_slot, t1)
        top2s = _take(st_slot, t2)
        top1a = _take(st_start, t1)
        top2a = _take(st_start, t2)
        top1d = _take(st_depth, t1)
        top2d = _take(st_depth, t2)
        lhs_i = jnp.where(is_un, top1s, jnp.where(is_bin, top2s, 0))
        rhs_i = jnp.where(is_bin, top1s, 0)
        start_i = jnp.where(
            is_leaf, i, jnp.where(is_un, top1a, top2a)
        ).astype(jnp.int32)
        depth_i = jnp.where(
            is_leaf,
            1,
            jnp.where(is_un, top1d + 1, jnp.maximum(top1d, top2d) + 1),
        ).astype(jnp.int32)
        new_sp = sp + jnp.where(is_leaf, 1, jnp.where(is_bin, -1, 0))
        wr = live[:, None] & (iota_d[None, :] == (new_sp - 1)[:, None])
        st_slot = jnp.where(wr, i, st_slot)
        st_start = jnp.where(wr, start_i[:, None], st_start)
        st_depth = jnp.where(wr, depth_i[:, None], st_depth)
        sp = jnp.where(live, new_sp, sp)
        col = iota_n[None, :] == i
        lhs = jnp.where(col & live[:, None], lhs_i[:, None], lhs)
        rhs = jnp.where(col & live[:, None], rhs_i[:, None], rhs)
        start = jnp.where(col & live[:, None], start_i[:, None], start)
        depth = jnp.where(col & live[:, None], depth_i[:, None], depth)
    return lhs, rhs, start, depth


def unpack_pointers_jnp(words, length):
    """Traced FlatTrees fields from packed words: (kind, op, lhs, rhs, feat)
    int32 [B, N]. The in-program half of the pack-out (consts pass through)."""
    w32 = words.astype(jnp.int32)
    kind = w32 & PACK_KIND_MASK
    payload = w32 >> PACK_KIND_BITS
    op = jnp.where((kind == KIND_UNARY) | (kind == KIND_BINARY), payload, 0)
    feat = jnp.where(kind == KIND_VAR, payload, 0)
    lhs, rhs, _, _ = _block_pointers(w32, length)
    return kind, op, lhs, rhs, feat


def _word(kind, payload):
    return (kind | (payload << PACK_KIND_BITS)).astype(jnp.int32)

# --------------------------------------------------------------------------
# The mutation set, on packed words as values. Every mutation computes its
# full output and the chosen kind selects afterwards — the exact evaluation
# model the XLA path's vmapped lax.switch has (every branch traces), so the
# block costs the same work per event and stays branch-free for Mosaic.
# Each returns (words', consts', length') with slots >= length' zeroed.
# --------------------------------------------------------------------------


def _mut_constant(words, consts, length, kind, live, u_site, u_fac, u_inv, u_neg, cfg, temperature):
    """Mirror of evolve._mutate_constant on the constants lane."""
    is_c = live & (kind == KIND_CONST)
    n_c = jnp.sum(is_c, axis=-1)
    p = _pick_ranked(is_c, u_site, n_c)
    hits = is_c & (_it(words.shape[-1])[None, :] == p[:, None])
    max_change = cfg.perturbation_factor * temperature + 1.0 + 0.1
    factor = jnp.power(jnp.float32(max_change), u_fac)
    factor = jnp.where(u_inv < 0.5, factor, 1.0 / factor)
    neg = u_neg < cfg.probability_negate_constant
    scale = jnp.where(
        hits,
        (factor * jnp.where(neg, -1.0, 1.0))[:, None],
        jnp.ones((), consts.dtype),
    )
    newc = jnp.where(n_c[:, None] > 0, consts * scale, consts)
    return words, newc, length


def _mut_operator(words, consts, length, kind, live, u_site, u_un, u_bin, cfg):
    """Mirror of evolve._mutate_operator: same-arity operator swap."""
    is_op = live & (kind >= KIND_UNARY)
    n_op = jnp.sum(is_op, axis=-1)
    p = _pick_ranked(is_op, u_site, n_op)
    hits = is_op & (_it(words.shape[-1])[None, :] == p[:, None])
    new_un = _randint(u_un, max(cfg.n_unary, 1))
    new_bin = _randint(u_bin, max(cfg.n_binary, 1))
    payload = jnp.where(kind == KIND_UNARY, new_un[:, None], new_bin[:, None])
    new_words = jnp.where(
        hits & (n_op[:, None] > 0), _word(kind, payload), words
    )
    return new_words, consts, length


def _mut_rotate(words, consts, length, kind, live, lhs, rhs, start, u_site, cfg):
    """Mirror of evolve._swap_operands: swap the child blocks of one random
    binary node. Pure block move — pointers recompute, no fixups."""
    N = words.shape[-1]
    iota = _it(N)[None, :]
    is_bin = live & (kind == KIND_BINARY)
    n_b = jnp.sum(is_bin, axis=-1)
    p = _pick_ranked(is_bin, u_site, n_b)
    l_root = _take(lhs, p)
    r_root = _take(rhs, p)
    sizes_l = l_root - _take(start, l_root) + 1
    sizes_r = r_root - _take(start, r_root) + 1
    al = l_root - sizes_l + 1
    src = jnp.clip(
        jnp.where(
            iota < (al + sizes_r)[:, None],
            iota + sizes_l[:, None],
            iota - sizes_r[:, None],
        ),
        0,
        N - 1,
    )
    use_move = (iota >= al[:, None]) & (iota < p[:, None])
    new_words = _permute_cols(words, src, use_move)
    new_consts = _permute_cols(consts, src, use_move)
    ok = n_b[:, None] > 0
    return (
        jnp.where(ok, new_words, words),
        jnp.where(ok, new_consts, consts),
        length,
    )


def _leaf_draws(seed, cycle, lane, cfg, d_const, d_feat, d_n1, d_n2):
    """One random leaf as (word, const): 50/50 const/feature, val ~ N(0,1)
    (mirror of evolve._leaf_material)."""
    u_c = _blk_u01(_blk_bits(seed, cycle, lane, d_const))
    u_f = _blk_u01(_blk_bits(seed, cycle, lane, d_feat))
    u_n1 = _blk_u01(_blk_bits(seed, cycle, lane, d_n1))
    u_n2 = _blk_u01(_blk_bits(seed, cycle, lane, d_n2))
    is_const = u_c < 0.5
    if cfg.nfeatures <= 0:
        is_const = jnp.ones_like(is_const)
    feat = _randint(u_f, max(cfg.nfeatures, 1))
    word = jnp.where(
        is_const, jnp.int32(KIND_CONST), _word(jnp.int32(KIND_VAR), feat)
    )
    cval = jnp.where(is_const, _blk_normal(u_n1, u_n2), 0.0)
    return word, cval


def _use_bin_draw(u, cfg):
    """Binary-vs-unary material choice with the degenerate-table overrides
    evolve._add_node/_insert_node apply."""
    use_bin = u < (cfg.n_binary / max(cfg.n_binary + cfg.n_unary, 1))
    if cfg.n_unary == 0:
        use_bin = jnp.ones_like(use_bin)
    if cfg.n_binary == 0:
        use_bin = jnp.zeros_like(use_bin)
    return use_bin


def _mut_add(words, consts, length, kind, live, seed, cycle, lane, u_site, u_child, cfg):
    """Mirror of evolve._add_node: replace a random leaf with
    binary(leaf, leaf) or unary(leaf) material."""
    N = words.shape[-1]
    iota = _it(N)[None, :]
    is_leaf = live & ((kind == KIND_CONST) | (kind == KIND_VAR))
    n_l = jnp.sum(is_leaf, axis=-1)
    p = _pick_ranked(is_leaf, u_site, n_l)
    use_bin = _use_bin_draw(u_child, cfg)
    w1, c1 = _leaf_draws(seed, cycle, lane, cfg, D_L1_CONST, D_L1_FEAT, D_L1_N1, D_L1_N2)
    w2, c2 = _leaf_draws(seed, cycle, lane, cfg, D_L2_CONST, D_L2_FEAT, D_L2_N1, D_L2_N2)
    opb = _randint(_blk_u01(_blk_bits(seed, cycle, lane, D_M_OPB)), max(cfg.n_binary, 1))
    opu = _randint(_blk_u01(_blk_bits(seed, cycle, lane, D_M_OPU)), max(cfg.n_unary, 1))
    m_len = jnp.where(use_bin, 3, 2).astype(jnp.int32)
    # material slot words: [leaf1, leaf2, binop] or [leaf1, unop]
    mat1 = jnp.where(use_bin, w2, _word(jnp.int32(KIND_UNARY), opu))
    mat2 = _word(jnp.int32(KIND_BINARY), opb)
    matc1 = jnp.where(use_bin, c2, 0.0)
    # tail (old slots > p) shifts up by m_len - 1
    shift = (m_len - 1)[:, None]
    src = jnp.clip(iota - shift, 0, N - 1)
    tail = iota >= (p[:, None] + m_len[:, None])
    new_words = _permute_cols(words, src, tail)
    new_consts = _permute_cols(consts, src, tail)
    at0 = iota == p[:, None]
    at1 = iota == (p + 1)[:, None]
    at2 = (iota == (p + 2)[:, None]) & use_bin[:, None]
    new_words = jnp.where(at0, w1[:, None], new_words)
    new_words = jnp.where(at1, mat1[:, None], new_words)
    new_words = jnp.where(at2, mat2[:, None], new_words)
    new_consts = jnp.where(at0, c1[:, None], new_consts)
    new_consts = jnp.where(at1, matc1[:, None], new_consts)
    new_consts = jnp.where(at2, 0.0, new_consts)
    new_len = length + m_len - 1
    ok = (n_l > 0) & (new_len <= N)
    return (
        jnp.where(ok[:, None], new_words, words),
        jnp.where(ok[:, None], new_consts, consts),
        jnp.where(ok, new_len, length),
    )


def _mut_insert(words, consts, length, start, seed, cycle, lane, u_site, u_child, cfg):
    """Mirror of evolve._insert_node: wrap a random subtree in a fresh
    operator — unary directly, binary with a new leaf as second child."""
    N = words.shape[-1]
    iota = _it(N)[None, :]
    p = _randint(u_site, jnp.maximum(length, 1))
    use_bin = _use_bin_draw(u_child, cfg)
    wl, cl = _leaf_draws(seed, cycle, lane, cfg, D_L1_CONST, D_L1_FEAT, D_L1_N1, D_L1_N2)
    opb = _randint(_blk_u01(_blk_bits(seed, cycle, lane, D_M_OPB)), max(cfg.n_binary, 1))
    opu = _randint(_blk_u01(_blk_bits(seed, cycle, lane, D_M_OPU)), max(cfg.n_unary, 1))
    shift = jnp.where(use_bin, 2, 1).astype(jnp.int32)
    op_word = jnp.where(
        use_bin,
        _word(jnp.int32(KIND_BINARY), opb),
        _word(jnp.int32(KIND_UNARY), opu),
    )
    # block [start[p], p] stays in place; leaf (binary only) lands at p+1,
    # the wrapping op at p+shift; the tail shifts up by shift
    src = jnp.clip(iota - shift[:, None], 0, N - 1)
    tail = iota > (p + shift)[:, None]
    new_words = _permute_cols(words, src, tail)
    new_consts = _permute_cols(consts, src, tail)
    at_leaf = (iota == (p + 1)[:, None]) & use_bin[:, None]
    at_op = iota == (p + shift)[:, None]
    new_words = jnp.where(at_leaf, wl[:, None], new_words)
    new_consts = jnp.where(at_leaf, cl[:, None], new_consts)
    new_words = jnp.where(at_op, op_word[:, None], new_words)
    new_consts = jnp.where(at_op, 0.0, new_consts)
    new_len = length + shift
    ok = new_len <= N
    return (
        jnp.where(ok[:, None], new_words, words),
        jnp.where(ok[:, None], new_consts, consts),
        jnp.where(ok, new_len, length),
    )


def _mut_delete(words, consts, length, kind, live, lhs, rhs, start, u_site, u_child, cfg):
    """Mirror of evolve._delete_node: splice a random operator out,
    promoting one of its children (right w.p. 0.5 for binary)."""
    N = words.shape[-1]
    iota = _it(N)[None, :]
    is_op = live & (kind >= KIND_UNARY)
    n_op = jnp.sum(is_op, axis=-1)
    p = _pick_ranked(is_op, u_site, n_op)
    keep_right = (_take(kind, p) == KIND_BINARY) & (u_child < 0.5)
    child = jnp.where(keep_right, _take(rhs, p), _take(lhs, p))
    ca = _take(start, child)
    clen = child - ca + 1
    sub_a = _take(start, p)
    sub_len = p - sub_a + 1
    removed = sub_len - clen
    in_child = (iota >= sub_a[:, None]) & (iota < (sub_a + clen)[:, None])
    src = jnp.where(
        in_child,
        iota - sub_a[:, None] + ca[:, None],
        iota + removed[:, None],
    )
    src = jnp.clip(src, 0, N - 1)
    use_move = iota >= sub_a[:, None]
    new_words = _permute_cols(words, src, use_move)
    new_consts = _permute_cols(consts, src, use_move)
    new_len = length - removed
    ok = n_op > 0
    return (
        jnp.where(ok[:, None], new_words, words),
        jnp.where(ok[:, None], new_consts, consts),
        jnp.where(ok, new_len, length),
    )


# --------------------------------------------------------------------------
# Tournament (documented divergence: WITH replacement + inverse-CDF rank;
# the XLA path's distinct-candidate argsort is not kernel-expressible).
# --------------------------------------------------------------------------


def _blk_tournament(score, length, fnorm, seed, cycle, lane, cfg):
    """Winner member index in [0, P) per lane. score/length are [P]
    population columns; lane is the [E] lane-id vector."""
    n = cfg.tournament_n
    P = cfg.pop_size
    cand = jnp.stack(
        [
            _randint(_blk_u01(_blk_bits(seed, cycle, lane, d)), P)
            for d in range(n)
        ],
        axis=-1,
    )  # [E, n]
    s = jax.vmap(lambda c: _gather_vec(score, c))(cand)
    if cfg.use_frequency_in_tournament:
        sizes = jnp.clip(
            jax.vmap(lambda c: _gather_vec(length, c))(cand), 0, cfg.maxsize
        )
        s = s * jnp.exp(
            cfg.adaptive_parsimony_scaling * jax.vmap(
                lambda z: _gather_vec(fnorm, z)
            )(sizes)
        )
    # inverse-CDF over the STATIC rank weights, accumulated from python
    # float scalars — array constants would be captured by the Pallas
    # kernel trace, which rejects them
    w = np.asarray(cfg.tournament_weights, np.float64)
    cum = np.cumsum(w / np.sum(w))
    u = _blk_u01(_blk_bits(seed, cycle, lane, D_RANK))
    rank = jnp.zeros_like(u, jnp.int32)
    for k in range(n):
        rank = rank + (u >= jnp.float32(cum[k])).astype(jnp.int32)
    rank = jnp.clip(rank, 0, n - 1)
    # stable rank of each candidate's adjusted score (pairwise count — the
    # kernel-safe argsort for tiny n)
    less = (s[:, :, None] > s[:, None, :]).astype(jnp.int32)  # j beats i
    eq_before = (
        (s[:, :, None] == s[:, None, :])
        & (_it(n)[None, None, :] < _it(n)[None, :, None])
    ).astype(jnp.int32)
    crank = jnp.sum(less + eq_before, axis=-1)  # [E, n]
    pos = _first_true(crank == rank[:, None])
    return jax.vmap(_gather_vec)(cand, jnp.clip(pos, 0, n - 1))


def _oldest_slots(birth, E):
    """Stable ranks of ``birth`` [P]; member p hosts event e iff rank == e.
    Returns ev [P] int32 (event index, or E where the member survives)."""
    P = birth.shape[0]
    less = (birth[None, :] < birth[:, None]).astype(jnp.int32)
    eq_before = (
        (birth[None, :] == birth[:, None]) & (_it(P)[None, :] < _it(P)[:, None])
    ).astype(jnp.int32)
    rank = jnp.sum(less + eq_before, axis=-1)  # [P]
    return jnp.where(rank < E, rank, E)


# --------------------------------------------------------------------------
# One evolution cycle over one island's packed population. Pure values-in /
# values-out jnp — the Pallas kernel body and the XLA reference both call
# this exact function, so backend parity is parity of eval_fn alone.
# --------------------------------------------------------------------------


def _block_cycle(carry, cycle, isl, seed, step0, curmaxsize, fnorm, norm, cfg,
                 eval_fn, stages):
    (words, consts, length, loss, score, birth, fd,
     bs_loss, bs_w, bs_c, bs_len) = carry
    P, N = words.shape
    E = cfg.events_per_cycle
    lane = isl * jnp.int32(E) + _it(E)
    iota_n = _it(N)[None, :]

    if cfg.annealing:
        temperature = jnp.float32(1.0) - cycle.astype(jnp.float32) / max(
            cfg.ncycles - 1, 1
        )
    else:
        temperature = jnp.float32(1.0)

    # ---- stage 1: tournament + mutation draws + mutate + canonicalize ----
    parent = _blk_tournament(score, length, fnorm, seed, cycle, lane, cfg)
    pw = _gather_rows(words, parent)  # [E, N] int32
    pc = _gather_rows(consts, parent)
    plen = _gather_vec(length, parent)
    ploss = _gather_vec(loss, parent)
    pscore = _gather_vec(score, parent)
    kind = pw & PACK_KIND_MASK
    live = iota_n < plen[:, None]
    kind = jnp.where(live, kind, KIND_PAD)

    lhs, rhs, start, _depth = _block_pointers(pw, plen)

    # conditioned mutation weights (mirror _condition_weights; randomize and
    # crossover fold into do-nothing — documented divergence)
    base = np.asarray(cfg.mutation_weights, np.float32).copy()
    base[M_NOTHING] += base[M_RANDOMIZE]
    base[M_RANDOMIZE] = 0.0
    n_const = jnp.sum(live & (kind == KIND_CONST), axis=-1)
    n_ops = jnp.sum(kind >= KIND_UNARY, axis=-1)
    n_bin = jnp.sum(kind == KIND_BINARY, axis=-1)
    at_max = plen >= curmaxsize
    # per-kind weight columns from python float scalars (array constants
    # would be captured by the Pallas kernel trace, which rejects them)
    cols = [jnp.full((E,), float(base[m]), jnp.float32) for m in range(8)]
    cols[M_OPERATOR] = jnp.where(n_ops == 0, 0.0, cols[M_OPERATOR])
    cols[M_SWAP] = jnp.where(n_bin == 0, 0.0, cols[M_SWAP])
    cols[M_DELETE] = jnp.where(n_ops == 0, 0.0, cols[M_DELETE])
    cols[M_CONST] = jnp.where(
        n_const == 0,
        0.0,
        cols[M_CONST] * jnp.minimum(8.0, n_const.astype(jnp.float32)) / 8.0,
    )
    cols[M_ADD] = jnp.where(at_max, 0.0, cols[M_ADD])
    cols[M_INSERT] = jnp.where(at_max, 0.0, cols[M_INSERT])
    w = jnp.stack(cols, axis=-1)  # [E, 8]
    w = w.at[:, M_NOTHING].add(
        jnp.where(jnp.sum(w, axis=-1) <= 0, 1.0, 0.0)
    )
    cum_w = jnp.cumsum(w, axis=-1)
    u_kind = _blk_u01(_blk_bits(seed, cycle, lane, D_KIND))
    kidx = jnp.clip(
        jnp.sum(
            ((u_kind * cum_w[:, -1])[:, None] >= cum_w).astype(jnp.int32),
            axis=-1,
        ),
        0,
        7,
    )

    u_site = _blk_u01(_blk_bits(seed, cycle, lane, D_SITE))
    u_child = _blk_u01(_blk_bits(seed, cycle, lane, D_CHILD))
    u_fac = _blk_u01(_blk_bits(seed, cycle, lane, D_C_FACTOR))
    u_inv = _blk_u01(_blk_bits(seed, cycle, lane, D_C_INV))
    u_neg = _blk_u01(_blk_bits(seed, cycle, lane, D_C_NEG))
    u_un = _blk_u01(_blk_bits(seed, cycle, lane, D_OP_UN))
    u_bin = _blk_u01(_blk_bits(seed, cycle, lane, D_OP_BIN))

    muts = {
        M_CONST: _mut_constant(
            pw, pc, plen, kind, live, u_site, u_fac, u_inv, u_neg, cfg,
            temperature,
        ),
        M_OPERATOR: _mut_operator(
            pw, pc, plen, kind, live, u_site, u_un, u_bin, cfg
        ),
        M_SWAP: _mut_rotate(
            pw, pc, plen, kind, live, lhs, rhs, start, u_site, cfg
        ),
        M_ADD: _mut_add(
            pw, pc, plen, kind, live, seed, cycle, lane, u_site, u_child, cfg
        ),
        M_INSERT: _mut_insert(
            pw, pc, plen, start, seed, cycle, lane, u_site, u_child, cfg
        ),
        M_DELETE: _mut_delete(
            pw, pc, plen, kind, live, lhs, rhs, start, u_site, u_child, cfg
        ),
    }
    cw, cc, clen = pw, pc, plen  # M_NOTHING / M_RANDOMIZE base
    for m, (mw, mc, ml) in muts.items():
        sel = (kidx == m)[:, None]
        cw = jnp.where(sel, mw, cw)
        cc = jnp.where(sel, mc, cc)
        clen = jnp.where(kidx == m, ml, clen)
    # pad canonicalization: gathers can drag live garbage into tails, and
    # both the packed invariants and kernel/reference parity depend on
    # slots >= length being exactly zero
    tail = iota_n >= clen[:, None]
    cw = jnp.where(tail, 0, cw)
    cc = jnp.where(tail, 0.0, cc)

    if stages < 2:
        chk = (
            jnp.sum(cw.astype(jnp.float32))
            + jnp.sum(cc)
            + jnp.sum(clen.astype(jnp.float32))
        )
        loss = jnp.where(jnp.isnan(chk), chk, loss)
        return (words, consts, length, loss, score, birth, fd,
                bs_loss, bs_w, bs_c, bs_len)

    # ---- stage 2: candidate pointer pass + constraint/complexity check ----
    _, _, _, cdepth = _block_pointers(cw, clen)
    root_depth = _take(cdepth, jnp.maximum(clen - 1, 0))
    ok = (clen <= curmaxsize) & (clen <= N) & (root_depth <= cfg.maxdepth)
    vw = jnp.where(ok[:, None], cw, pw)
    vc = jnp.where(ok[:, None], cc, pc)
    vlen = jnp.where(ok, clen, plen)

    if stages < 3:
        chk = jnp.sum(ok.astype(jnp.float32)) + jnp.sum(
            vw.astype(jnp.float32)
        )
        loss = jnp.where(jnp.isnan(chk), chk, loss)
        return (words, consts, length, loss, score, birth, fd,
                bs_loss, bs_w, bs_c, bs_len)

    # ---- stage 3: loss scoring ----
    loss1 = eval_fn(vw, vc, vlen)  # [E]
    score1 = _score_of(loss1, vlen.astype(jnp.float32), cfg, norm)

    if stages < 4:
        chk = jnp.sum(loss1)
        loss = jnp.where(jnp.isnan(chk), chk, loss)
        return (words, consts, length, loss, score, birth, fd,
                bs_loss, bs_w, bs_c, bs_len)

    # ---- stage 4: annealing-gated accept + oldest-first replacement ----
    sz_old = jnp.clip(plen, 0, cfg.maxsize)
    sz_new = jnp.clip(vlen, 0, cfg.maxsize)
    prob = jnp.ones((E,), jnp.float32)
    if cfg.annealing:
        # temperature hits exactly 0 on the final cycle: IEEE inf/0
        # semantics match the XLA path (no epsilon guard)
        prob = prob * jnp.exp(-(score1 - pscore) / (cfg.alpha * temperature))
    if cfg.use_frequency:
        old_f = jnp.maximum(_gather_vec(fnorm, sz_old), 1e-6)
        new_f = jnp.maximum(_gather_vec(fnorm, sz_new), 1e-6)
        prob = prob * (old_f / new_f)
    u_acc = _blk_u01(_blk_bits(seed, cycle, lane, D_ACCEPT))
    accept = ~(prob < u_acc) & jnp.isfinite(loss1) & ok

    bw = jnp.where(accept[:, None], vw, pw)
    bc = jnp.where(accept[:, None], vc, pc)
    blen = jnp.where(accept, vlen, plen)
    bloss = jnp.where(accept, loss1, ploss)
    bscore = jnp.where(accept, score1, pscore)

    # insert ALWAYS (parent copy on reject) over the E oldest members
    ev = _oldest_slots(birth, E)  # [P] event id or E
    hit = ev < E
    evc = jnp.clip(ev, 0, E - 1)
    words = jnp.where(hit[:, None], _gather_rows(bw, evc), words)
    consts = jnp.where(hit[:, None], _gather_rows(bc, evc), consts)
    length = jnp.where(hit, _gather_vec(blen, evc), length)
    loss = jnp.where(hit, _gather_vec(bloss, evc), loss)
    score = jnp.where(hit, _gather_vec(bscore, evc), score)
    birth = jnp.where(hit, step0 + cycle, birth)

    # frequency delta (accepted inserts only), merged cross-island at exit
    S1 = fd.shape[0]
    oh_f = (sz_new[:, None] == _it(S1)[None, :]) & accept[:, None]
    fd = fd + jnp.sum(oh_f.astype(jnp.float32), axis=0)

    # best-seen per complexity over ALL finite valid candidates (incl.
    # rejected), first-argmin tie-break like merge_best_seen
    valid = jnp.isfinite(loss1) & ok
    m_se = valid[None, :] & (sz_new[None, :] == _it(S1)[:, None])  # [S1, E]
    loss_se = jnp.where(m_se, loss1[None, :], jnp.inf)
    min_s = jnp.min(loss_se, axis=-1)
    e_star = jnp.clip(_first_true(loss_se == min_s[:, None]), 0, E - 1)
    better = min_s < bs_loss
    bs_loss = jnp.where(better, min_s, bs_loss)
    bs_w = jnp.where(better[:, None], _gather_rows(vw, e_star), bs_w)
    bs_c = jnp.where(better[:, None], _gather_rows(vc, e_star), bs_c)
    bs_len = jnp.where(better, _gather_vec(vlen, e_star), bs_len)

    return (words, consts, length, loss, score, birth, fd,
            bs_loss, bs_w, bs_c, bs_len)


# --------------------------------------------------------------------------
# XLA reference evaluator: value-based twin of the Pallas loss kernel's
# scratch-slot loop. Identical op sequence on identically-shaped (8, C) row
# tiles (all ops computed, then selected — the value-level equivalent of the
# kernel's pl.when predicated writes), so losses agree at f32 tolerance and
# accept decisions agree deterministically.
# --------------------------------------------------------------------------


def make_reference_eval(opset, loss_elem, Xr, yr, wr, R: int):
    """Build eval_fn(words, consts, length) -> loss [E] against the packed
    row tile (Xr [F*8, C], yr/wr [8, C], R true rows). Works under vmap."""
    unary_fns = [op.kernel_fn or op.fn for op in opset.unary]
    binary_fns = [op.kernel_fn or op.fn for op in opset.binary]
    F8, C = Xr.shape
    F = F8 // 8
    X3 = jnp.asarray(Xr).reshape(F, 8, C)
    sub = lax.broadcasted_iota(jnp.int32, (8, C), 0)
    col = lax.broadcasted_iota(jnp.int32, (8, C), 1)
    mask = sub * C + col < R

    def eval_fn(words, consts, length):
        E, N = words.shape
        kind = words & PACK_KIND_MASK
        payload = words >> PACK_KIND_BITS
        lhs, rhs, _start, _depth = _block_pointers(words, length)
        buf = jnp.zeros((E, N, 8, C), jnp.float32)
        for i in range(N):
            k_i = kind[:, i]
            lv = jnp.take_along_axis(
                buf, lhs[:, i][:, None, None, None], axis=1
            )[:, 0]
            rv = jnp.take_along_axis(
                buf, rhs[:, i][:, None, None, None], axis=1
            )[:, 0]
            xv = jnp.take(X3, jnp.clip(payload[:, i], 0, F - 1), axis=0)
            val = jnp.where(
                (k_i == KIND_CONST)[:, None, None], consts[:, i][:, None, None], 0.0
            )
            val = jnp.where((k_i == KIND_VAR)[:, None, None], xv, val)
            for k, fn in enumerate(unary_fns):
                sel = (k_i == KIND_UNARY) & (payload[:, i] == k)
                val = jnp.where(sel[:, None, None], fn(lv), val)
            for k, fn in enumerate(binary_fns):
                sel = (k_i == KIND_BINARY) & (payload[:, i] == k)
                val = jnp.where(sel[:, None, None], fn(lv, rv), val)
            buf = buf.at[:, i].set(val)
        pred = jnp.take_along_axis(
            buf, jnp.maximum(length - 1, 0)[:, None, None, None], axis=1
        )[:, 0]  # [E, 8, C]
        elem = loss_elem(pred, yr)
        loss_part = jnp.sum(jnp.where(mask, elem * wr, 0.0), axis=(1, 2))
        nonfin = jnp.sum(
            jnp.where(mask & ~jnp.isfinite(pred), 1.0, 0.0), axis=(1, 2)
        )
        wsum = jnp.sum(jnp.where(mask, wr, 0.0))
        return jnp.where(
            (nonfin == 0) & (wsum > 0),
            loss_part / jnp.maximum(wsum, 1e-30),
            jnp.inf,
        )

    return eval_fn


# --------------------------------------------------------------------------
# Island wrapper, eligibility, and the iteration entry point
# --------------------------------------------------------------------------


def _island_block(pop, isl, seed, step0, curmaxsize, fnorm, norm, cfg,
                  eval_fn, stages):
    """Run cfg.ncycles cycles over ONE island. ``pop`` = (words i32 [P,N],
    consts [P,N], length, loss, score, birth [P]). Returns the 11-tuple
    block carry (population + freq delta + per-island best-seen)."""
    words, consts, length, loss, score, birth = pop
    P, N = words.shape
    S1 = cfg.maxsize + 1
    carry0 = (
        words, consts, length, loss, score, birth,
        jnp.zeros((S1,), jnp.float32),          # freq delta
        jnp.full((S1,), jnp.inf, jnp.float32),  # best-seen loss
        jnp.zeros((S1, N), jnp.int32),          # best-seen words
        jnp.zeros((S1, N), jnp.float32),        # best-seen consts
        jnp.zeros((S1,), jnp.int32),            # best-seen length
    )

    def body(cycle, carry):
        return _block_cycle(
            carry, jnp.asarray(cycle, jnp.int32), isl, seed, step0,
            curmaxsize, fnorm, norm, cfg, eval_fn, stages,
        )

    return lax.fori_loop(0, cfg.ncycles, body, carry0)


def block_eligible(cfg: EvoConfig):
    """(ok, reason): can the kernel-resident block replace the XLA event
    trajectory for this engine config? Mirrors the SR_FUSED_ITER-style
    auto-off gates; data-level gates (row count) live in device_search."""
    if cfg.record_events:
        return False, "recorder mode needs the per-event XLA log"
    if cfg.batching:
        return False, "minibatch scoring draws per-cycle row subsets"
    if cfg.eval_fraction < 1.0:
        return False, "fractional eval accounting"
    if cfg.complexity_table is not None:
        return False, "custom complexity mapping"
    if _has_op_constraints(cfg) or cfg.nested_constraints:
        return False, "operator argument/nesting constraints"
    if cfg.units_check:
        return False, "dimensional analysis"
    if cfg.mutation_attempts > 1:
        return False, "multi-attempt mutation retries"
    if cfg.val_dtype != "float32":
        return False, "f64 engine (kernels are f32-only)"
    if cfg.events_per_cycle > cfg.pop_size:
        return False, "events_per_cycle exceeds pop_size"
    return True, ""


def run_block_iteration(state: EvoState, data, cfg: EvoConfig, *,
                        eval_fn=None, kernel_fn=None, stages: int = 4):
    """One engine iteration via the kernel-resident block. Drop-in for
    `_run_iteration_fused_impl`'s evolve leg when `block_eligible(cfg)`.

    Exactly one of ``kernel_fn`` (the Pallas block from
    interp_pallas.make_evolve_block_fn) or ``eval_fn`` (the XLA reference
    evaluator from make_reference_eval) must be provided. Trace-time only —
    callers jit."""
    I, P, N = state.kind.shape
    S1 = cfg.maxsize + 1

    key, k_blk = jax.random.split(state.key)
    kd = (
        k_blk
        if jnp.issubdtype(k_blk.dtype, jnp.integer)
        else jax.random.key_data(k_blk)
    )
    kd = kd.reshape(-1).astype(jnp.uint32)
    seed = kd[0] ^ kd[1]

    if cfg.warmup_maxsize_by > 0:
        frac_done = state.iteration.astype(jnp.float32) / max(cfg.niterations, 1)
        in_warmup = frac_done / cfg.warmup_maxsize_by
        curmaxsize = jnp.minimum(
            3 + (in_warmup * (cfg.maxsize - 3)).astype(jnp.int32), cfg.maxsize
        )
    else:
        curmaxsize = jnp.asarray(cfg.maxsize, jnp.int32)

    # size-frequency histogram SNAPSHOT (documented divergence: per-cycle
    # cross-island updates would serialize the island grid)
    fnorm = state.freq / jnp.maximum(jnp.sum(state.freq), 1e-30)
    norm = data.norm

    w16, consts = pack_words(
        state.kind, state.op, state.feat, state.val, xp=jnp
    )
    words = w16.astype(jnp.int32)
    consts = consts.astype(jnp.float32)
    pop = (
        words, consts, state.length, state.loss.astype(jnp.float32),
        state.score.astype(jnp.float32), state.birth,
    )

    if kernel_fn is not None:
        out = kernel_fn(
            *pop, fnorm, seed, state.step, curmaxsize,
            jnp.asarray(norm, jnp.float32),
        )
    else:
        if eval_fn is None:
            raise ValueError("run_block_iteration needs eval_fn or kernel_fn")
        out = jax.vmap(
            lambda p, isl: _island_block(
                p, isl, seed, state.step, curmaxsize, fnorm, norm, cfg,
                eval_fn, stages,
            )
        )(pop, jnp.arange(I, dtype=jnp.int32))

    (n_words, n_consts, n_len, n_loss, n_score, n_birth, fd,
     b_loss, b_w, b_c, b_len) = out

    # unpack back to FlatTrees fields (pointers recomputed from postfix)
    kind, op, lhs, rhs, feat = unpack_pointers_jnp(
        n_words.reshape(I * P, N), n_len.reshape(I * P)
    )
    reshape = lambda a: a.reshape(I, P, N)
    state = state._replace(
        kind=reshape(kind), op=reshape(op), lhs=reshape(lhs),
        rhs=reshape(rhs), feat=reshape(feat),
        val=n_consts.astype(jnp.dtype(cfg.val_dtype)),
        length=n_len, loss=n_loss, score=n_score, birth=n_birth,
        freq=state.freq + jnp.sum(fd, axis=0),
        key=key,
        step=state.step + cfg.ncycles,
        num_evals=state.num_evals
        + jnp.float32(cfg.ncycles * I * cfg.events_per_cycle),
        iteration=state.iteration + 1,
    )

    # merge the per-island best-seen carries into the global frontier
    # (per-size min is associative -> same frontier content as per-cycle)
    bk, bo, bl, br, bf = unpack_pointers_jnp(
        b_w.reshape(I * S1, N), b_len.reshape(I * S1)
    )
    fields = [bk, bo, bl, br, bf, b_c.reshape(I * S1, N).astype(
        jnp.dtype(cfg.val_dtype)
    )]
    losses = b_loss.reshape(I * S1)
    state = merge_best_seen(
        state, cfg, losses, jnp.isfinite(losses), fields,
        b_len.reshape(I * S1),
    )

    # frequency-window decay (move_window!, window 100k) — same as the
    # XLA iteration tail
    total_f = jnp.sum(state.freq)
    state = state._replace(
        freq=jnp.where(
            total_f > 100_000.0, state.freq * (100_000.0 / total_f), state.freq
        )
    )

    if cfg.migration:
        state = _migrate(state, cfg, use_hof=False, norm=norm)
    if cfg.hof_migration:
        state = _migrate(state, cfg, use_hof=True, norm=norm)
    return state
