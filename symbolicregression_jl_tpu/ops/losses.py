"""Elementwise loss zoo — closed-form JAX implementations.

Replaces LossFunctions.jl as consumed by the reference
(/root/reference/src/LossFunctions.jl:13-33 for the weighted normalized mean;
the 26 re-exported losses at /root/reference/src/SymbolicRegression.jl:101-127).
Distance losses take (pred, target); margin losses take (target, agreement)
with targets in {-1, +1}, following the same convention.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax.numpy as jnp

__all__ = [
    "LOSSES", "resolve_loss", "weighted_mean_loss", "L2DistLoss",
    "LogisticLoss", "make_loss", "loss_zoo",
]


# -- distance-based losses: f(difference) ------------------------------------


def L2DistLoss(pred, target):
    d = pred - target
    return d * d


def L1DistLoss(pred, target):
    return jnp.abs(pred - target)


def LPDistLoss(p: float) -> Callable:
    def loss(pred, target):
        return jnp.abs(pred - target) ** p

    loss.__name__ = f"LPDistLoss({p})"
    return loss


def HuberLoss(d: float = 1.0) -> Callable:
    def loss(pred, target):
        a = jnp.abs(pred - target)
        return jnp.where(a <= d, 0.5 * a * a, d * (a - 0.5 * d))

    loss.__name__ = f"HuberLoss({d})"
    return loss


def L1EpsilonInsLoss(eps: float = 1.0) -> Callable:
    def loss(pred, target):
        return jnp.maximum(jnp.abs(pred - target) - eps, 0.0)

    loss.__name__ = f"L1EpsilonInsLoss({eps})"
    return loss


def L2EpsilonInsLoss(eps: float = 1.0) -> Callable:
    def loss(pred, target):
        e = jnp.maximum(jnp.abs(pred - target) - eps, 0.0)
        return e * e

    loss.__name__ = f"L2EpsilonInsLoss({eps})"
    return loss


def LogitDistLoss(pred, target):
    d = pred - target
    return -jnp.log(4.0) - d + 2.0 * jnp.log1p(jnp.exp(d))


def L2ComplexDistLoss(pred, target):
    """|pred - target|^2 with a REAL result — the default elementwise loss
    for complex searches (the loss type is the real base type,
    /root/reference/src/Dataset.jl:165; the reference's complex test uses
    abs2, /root/reference/test/test_abstract_numbers.jl)."""
    d = pred - target
    return (d * jnp.conj(d)).real


def LogCoshLoss(pred, target):
    # log(cosh(d)) computed as |d| + log1p(exp(-2|d|)) - log(2): the naive
    # form overflows cosh at |d| ~ 45 in f32
    a = jnp.abs(pred - target)
    return a + jnp.log1p(jnp.exp(-2.0 * a)) - jnp.log(2.0)


def PeriodicLoss(c: float = 1.0) -> Callable:
    def loss(pred, target):
        return 2.0 * jnp.sin(jnp.pi * (pred - target) / c) ** 2

    loss.__name__ = f"PeriodicLoss({c})"
    return loss


def QuantileLoss(tau: float = 0.5) -> Callable:
    def loss(pred, target):
        d = target - pred
        return jnp.where(d >= 0, tau * d, (tau - 1.0) * d)

    loss.__name__ = f"QuantileLoss({tau})"
    return loss


def LogisticLoss(pred, target):
    """Binary cross-entropy on LOGITS with targets in {0, 1} — the
    classification-SR head: the evolved expression is a decision function
    whose sign separates the classes, and sigmoid(pred) is the class-1
    probability. Computed in the overflow-safe form
    ``max(p, 0) - p*t + log1p(exp(-|p|))`` (the naive
    ``-t*log(sigmoid(p)) - (1-t)*log(1-sigmoid(p))`` saturates to inf at
    |p| ~ 90 in f32 and its gradient dies long before that)."""
    a = jnp.abs(pred)
    return jnp.maximum(pred, 0.0) - pred * target + jnp.log1p(jnp.exp(-a))


# -- margin-based losses: f(agreement = pred * target), target in {-1, 1} ----


def _margin(fn):
    def loss(pred, target):
        return fn(pred * target)

    return loss


ZeroOneLoss = _margin(lambda a: (a < 0).astype(jnp.result_type(a)))
PerceptronLoss = _margin(lambda a: jnp.maximum(-a, 0.0))
L1HingeLoss = _margin(lambda a: jnp.maximum(1.0 - a, 0.0))
L2HingeLoss = _margin(lambda a: jnp.maximum(1.0 - a, 0.0) ** 2)
ExpLoss = _margin(lambda a: jnp.exp(-a))
SigmoidLoss = _margin(lambda a: (1.0 - jnp.tanh(a)))
L2MarginLoss = _margin(lambda a: (1.0 - a) ** 2)
ModifiedHuberLoss = _margin(
    lambda a: jnp.where(a >= -1.0, jnp.maximum(1.0 - a, 0.0) ** 2, -4.0 * a)
)
LogitMarginLoss = _margin(lambda a: jnp.log1p(jnp.exp(-a)))


def SmoothedL1HingeLoss(gamma: float = 1.0) -> Callable:
    def fn(a):
        return jnp.where(
            a >= 1.0 - gamma,
            jnp.maximum(1.0 - a, 0.0) ** 2 / (2.0 * gamma),
            1.0 - gamma / 2.0 - a,
        )

    loss = _margin(fn)
    loss.__name__ = f"SmoothedL1HingeLoss({gamma})"
    return loss


def DWDMarginLoss(q: float = 1.0) -> Callable:
    def fn(a):
        thresh = q / (q + 1.0)
        const = (q**q) / ((q + 1.0) ** (q + 1.0))
        safe = jnp.where(a > 0, a, 1.0)
        return jnp.where(a <= thresh, 1.0 - a, const / safe**q)

    loss = _margin(fn)
    loss.__name__ = f"DWDMarginLoss({q})"
    return loss


LOSSES: dict[str, Callable] = {
    "L2DistLoss": L2DistLoss,
    "L1DistLoss": L1DistLoss,
    "LogisticLoss": LogisticLoss,
    "LogitDistLoss": LogitDistLoss,
    "LogCoshLoss": LogCoshLoss,
    "L2ComplexDistLoss": L2ComplexDistLoss,
    "ZeroOneLoss": ZeroOneLoss,
    "PerceptronLoss": PerceptronLoss,
    "L1HingeLoss": L1HingeLoss,
    "L2HingeLoss": L2HingeLoss,
    "ExpLoss": ExpLoss,
    "SigmoidLoss": SigmoidLoss,
    "L2MarginLoss": L2MarginLoss,
    "ModifiedHuberLoss": ModifiedHuberLoss,
    "LogitMarginLoss": LogitMarginLoss,
    # parameterized factories, default-instantiated under their plain names:
    "HuberLoss": HuberLoss(1.0),
    "L1EpsilonInsLoss": L1EpsilonInsLoss(1.0),
    "L2EpsilonInsLoss": L2EpsilonInsLoss(1.0),
    "PeriodicLoss": PeriodicLoss(1.0),
    "QuantileLoss": QuantileLoss(0.5),
    "SmoothedL1HingeLoss": SmoothedL1HingeLoss(1.0),
    "DWDMarginLoss": DWDMarginLoss(1.0),
}

# aliases the reference re-exports (LossFunctions.jl names,
# /root/reference/src/SymbolicRegression.jl:101-127)
LOSSES["HingeLoss"] = LOSSES["L1HingeLoss"]
LOSSES["EpsilonInsLoss"] = LOSSES["L1EpsilonInsLoss"]

_FACTORIES = {
    "LPDistLoss": LPDistLoss,
    "EpsilonInsLoss": L1EpsilonInsLoss,
    # NB: HingeLoss is a bare alias, not a factory — "HingeLoss(2.0)" is
    # invalid in LossFunctions.jl too and falls through to the KeyError path
    "HuberLoss": HuberLoss,
    "L1EpsilonInsLoss": L1EpsilonInsLoss,
    "L2EpsilonInsLoss": L2EpsilonInsLoss,
    "PeriodicLoss": PeriodicLoss,
    "QuantileLoss": QuantileLoss,
    "SmoothedL1HingeLoss": SmoothedL1HingeLoss,
    "DWDMarginLoss": DWDMarginLoss,
}


@functools.lru_cache(maxsize=None)
def _cached_factory(name: str, arg: float) -> Callable:
    """Memoized parameterized-loss instantiation: callable IDENTITY keys the
    compiled-program caches downstream (score-fn memoization, the Pallas
    kernel loss UID), so two Options built from the same "HuberLoss(0.5)"
    spec must share ONE closure — a fresh closure per resolve would recompile
    every engine program for an identical loss."""
    return _FACTORIES[name](arg)


def resolve_loss(spec) -> Callable:
    """name | callable | None -> elementwise loss fn(pred, target).
    Default: L2 (reference default, /root/reference/src/Options.jl:534-535)."""
    if spec is None:
        return L2DistLoss
    if callable(spec):
        return spec
    if isinstance(spec, str):
        if spec in LOSSES:
            return LOSSES[spec]
        # parameterized form "HuberLoss(0.5)"
        if "(" in spec and spec.endswith(")"):
            name, argstr = spec.split("(", 1)
            if name in _FACTORIES:
                return _cached_factory(name, float(argstr[:-1]))
        raise KeyError(f"unknown loss {spec!r}; known: {sorted(LOSSES)}")
    raise TypeError(f"cannot interpret loss spec {spec!r}")


# -- the loss zoo: task-level heads over the elementwise losses ---------------
#
# ``make_loss`` is the scenario-facing factory (streaming sessions, the
# serve layer, MultitargetSearch): short task names instead of
# LossFunctions.jl class names, memoized instantiation so equal specs share
# one callable (and therefore every compiled program keyed on it), and
# static Pallas coverage metadata. Every zoo head is closed-form
# elementwise jnp, so it traces through the scan interpreter, the batched
# scorer, const-opt gradients, AND the fused Pallas loss/grad kernels
# (which take the loss as a generic traced callable — parity pinned by
# tests/test_pallas_interpret.py).

_ZOO: dict[str, tuple] = {
    # name -> (factory(*params) -> loss, param names, defaults, task)
    "l2": (lambda: L2DistLoss, (), (), "regression"),
    "l1": (lambda: L1DistLoss, (), (), "robust regression"),
    "huber": (HuberLoss, ("delta",), (1.0,), "robust regression"),
    "quantile": (QuantileLoss, ("tau",), (0.5,), "quantile regression"),
    "pinball": (QuantileLoss, ("tau",), (0.5,), "quantile regression"),
    "logistic": (lambda: LogisticLoss, (), (), "binary classification"),
    "logcosh": (lambda: LogCoshLoss, (), (), "robust regression"),
}


@functools.lru_cache(maxsize=None)
def _zoo_instance(key: str, args: tuple) -> Callable:
    return _ZOO[key][0](*args)


def make_loss(name: str, *params: float) -> Callable:
    """Loss-zoo factory: ``make_loss("quantile", 0.9)`` ->  elementwise loss.

    Memoized per NORMALIZED (name, params) — aliases and omitted defaults
    collapse onto one closure (``make_loss("pinball") is
    make_loss("quantile", 0.5)``): callable identity keys the score-fn and
    Pallas-kernel caches, so every search/session built from an equal spec
    reuses the same compiled programs."""
    key = name.lower()
    if key == "pinball":  # alias — must share quantile's memoized closures
        key = "quantile"
    if key not in _ZOO:
        raise KeyError(f"unknown zoo loss {name!r}; known: {sorted(_ZOO)}")
    _, pnames, defaults, _ = _ZOO[key]
    if len(params) > len(pnames):
        raise TypeError(
            f"{name} takes at most {len(pnames)} parameter(s) {pnames}"
        )
    args = tuple(float(p) for p in params) + defaults[len(params):]
    return _zoo_instance(key, args)


def loss_zoo() -> dict[str, dict]:
    """Metadata for the zoo heads: parameters, task, and Pallas kernel
    status. Coverage is static truth (every head is closed-form elementwise
    jnp, which the fused loss/grad kernels trace generically); the claim is
    pinned numerically by tests/test_pallas_interpret.py."""
    return {
        name: {
            "params": dict(zip(pnames, defaults)),
            "task": task,
            "pallas": True,
            "pallas_grad": True,
        }
        for name, (_, pnames, defaults, task) in _ZOO.items()
    }


def weighted_mean_loss(elem, weights=None):
    """Weighted normalized mean, matching LossFunctions.jl `AggMode.WeightedMean`
    as used by the reference (/root/reference/src/LossFunctions.jl:27-28)."""
    if weights is None:
        return jnp.mean(elem, axis=-1)
    return jnp.sum(elem * weights, axis=-1) / jnp.sum(weights, axis=-1)
