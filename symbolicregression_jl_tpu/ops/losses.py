"""Elementwise loss zoo — closed-form JAX implementations.

Replaces LossFunctions.jl as consumed by the reference
(/root/reference/src/LossFunctions.jl:13-33 for the weighted normalized mean;
the 26 re-exported losses at /root/reference/src/SymbolicRegression.jl:101-127).
Distance losses take (pred, target); margin losses take (target, agreement)
with targets in {-1, +1}, following the same convention.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

__all__ = ["LOSSES", "resolve_loss", "weighted_mean_loss", "L2DistLoss"]


# -- distance-based losses: f(difference) ------------------------------------


def L2DistLoss(pred, target):
    d = pred - target
    return d * d


def L1DistLoss(pred, target):
    return jnp.abs(pred - target)


def LPDistLoss(p: float) -> Callable:
    def loss(pred, target):
        return jnp.abs(pred - target) ** p

    loss.__name__ = f"LPDistLoss({p})"
    return loss


def HuberLoss(d: float = 1.0) -> Callable:
    def loss(pred, target):
        a = jnp.abs(pred - target)
        return jnp.where(a <= d, 0.5 * a * a, d * (a - 0.5 * d))

    loss.__name__ = f"HuberLoss({d})"
    return loss


def L1EpsilonInsLoss(eps: float = 1.0) -> Callable:
    def loss(pred, target):
        return jnp.maximum(jnp.abs(pred - target) - eps, 0.0)

    loss.__name__ = f"L1EpsilonInsLoss({eps})"
    return loss


def L2EpsilonInsLoss(eps: float = 1.0) -> Callable:
    def loss(pred, target):
        e = jnp.maximum(jnp.abs(pred - target) - eps, 0.0)
        return e * e

    loss.__name__ = f"L2EpsilonInsLoss({eps})"
    return loss


def LogitDistLoss(pred, target):
    d = pred - target
    return -jnp.log(4.0) - d + 2.0 * jnp.log1p(jnp.exp(d))


def L2ComplexDistLoss(pred, target):
    """|pred - target|^2 with a REAL result — the default elementwise loss
    for complex searches (the loss type is the real base type,
    /root/reference/src/Dataset.jl:165; the reference's complex test uses
    abs2, /root/reference/test/test_abstract_numbers.jl)."""
    d = pred - target
    return (d * jnp.conj(d)).real


def LogCoshLoss(pred, target):
    # log(cosh(d)) computed as |d| + log1p(exp(-2|d|)) - log(2): the naive
    # form overflows cosh at |d| ~ 45 in f32
    a = jnp.abs(pred - target)
    return a + jnp.log1p(jnp.exp(-2.0 * a)) - jnp.log(2.0)


def PeriodicLoss(c: float = 1.0) -> Callable:
    def loss(pred, target):
        return 2.0 * jnp.sin(jnp.pi * (pred - target) / c) ** 2

    loss.__name__ = f"PeriodicLoss({c})"
    return loss


def QuantileLoss(tau: float = 0.5) -> Callable:
    def loss(pred, target):
        d = target - pred
        return jnp.where(d >= 0, tau * d, (tau - 1.0) * d)

    loss.__name__ = f"QuantileLoss({tau})"
    return loss


# -- margin-based losses: f(agreement = pred * target), target in {-1, 1} ----


def _margin(fn):
    def loss(pred, target):
        return fn(pred * target)

    return loss


ZeroOneLoss = _margin(lambda a: (a < 0).astype(jnp.result_type(a)))
PerceptronLoss = _margin(lambda a: jnp.maximum(-a, 0.0))
L1HingeLoss = _margin(lambda a: jnp.maximum(1.0 - a, 0.0))
L2HingeLoss = _margin(lambda a: jnp.maximum(1.0 - a, 0.0) ** 2)
ExpLoss = _margin(lambda a: jnp.exp(-a))
SigmoidLoss = _margin(lambda a: (1.0 - jnp.tanh(a)))
L2MarginLoss = _margin(lambda a: (1.0 - a) ** 2)
ModifiedHuberLoss = _margin(
    lambda a: jnp.where(a >= -1.0, jnp.maximum(1.0 - a, 0.0) ** 2, -4.0 * a)
)
LogitMarginLoss = _margin(lambda a: jnp.log1p(jnp.exp(-a)))


def SmoothedL1HingeLoss(gamma: float = 1.0) -> Callable:
    def fn(a):
        return jnp.where(
            a >= 1.0 - gamma,
            jnp.maximum(1.0 - a, 0.0) ** 2 / (2.0 * gamma),
            1.0 - gamma / 2.0 - a,
        )

    loss = _margin(fn)
    loss.__name__ = f"SmoothedL1HingeLoss({gamma})"
    return loss


def DWDMarginLoss(q: float = 1.0) -> Callable:
    def fn(a):
        thresh = q / (q + 1.0)
        const = (q**q) / ((q + 1.0) ** (q + 1.0))
        safe = jnp.where(a > 0, a, 1.0)
        return jnp.where(a <= thresh, 1.0 - a, const / safe**q)

    loss = _margin(fn)
    loss.__name__ = f"DWDMarginLoss({q})"
    return loss


LOSSES: dict[str, Callable] = {
    "L2DistLoss": L2DistLoss,
    "L1DistLoss": L1DistLoss,
    "LogitDistLoss": LogitDistLoss,
    "LogCoshLoss": LogCoshLoss,
    "L2ComplexDistLoss": L2ComplexDistLoss,
    "ZeroOneLoss": ZeroOneLoss,
    "PerceptronLoss": PerceptronLoss,
    "L1HingeLoss": L1HingeLoss,
    "L2HingeLoss": L2HingeLoss,
    "ExpLoss": ExpLoss,
    "SigmoidLoss": SigmoidLoss,
    "L2MarginLoss": L2MarginLoss,
    "ModifiedHuberLoss": ModifiedHuberLoss,
    "LogitMarginLoss": LogitMarginLoss,
    # parameterized factories, default-instantiated under their plain names:
    "HuberLoss": HuberLoss(1.0),
    "L1EpsilonInsLoss": L1EpsilonInsLoss(1.0),
    "L2EpsilonInsLoss": L2EpsilonInsLoss(1.0),
    "PeriodicLoss": PeriodicLoss(1.0),
    "QuantileLoss": QuantileLoss(0.5),
    "SmoothedL1HingeLoss": SmoothedL1HingeLoss(1.0),
    "DWDMarginLoss": DWDMarginLoss(1.0),
}

# aliases the reference re-exports (LossFunctions.jl names,
# /root/reference/src/SymbolicRegression.jl:101-127)
LOSSES["HingeLoss"] = LOSSES["L1HingeLoss"]
LOSSES["EpsilonInsLoss"] = LOSSES["L1EpsilonInsLoss"]

_FACTORIES = {
    "LPDistLoss": LPDistLoss,
    "EpsilonInsLoss": L1EpsilonInsLoss,
    # NB: HingeLoss is a bare alias, not a factory — "HingeLoss(2.0)" is
    # invalid in LossFunctions.jl too and falls through to the KeyError path
    "HuberLoss": HuberLoss,
    "L1EpsilonInsLoss": L1EpsilonInsLoss,
    "L2EpsilonInsLoss": L2EpsilonInsLoss,
    "PeriodicLoss": PeriodicLoss,
    "QuantileLoss": QuantileLoss,
    "SmoothedL1HingeLoss": SmoothedL1HingeLoss,
    "DWDMarginLoss": DWDMarginLoss,
}


def resolve_loss(spec) -> Callable:
    """name | callable | None -> elementwise loss fn(pred, target).
    Default: L2 (reference default, /root/reference/src/Options.jl:534-535)."""
    if spec is None:
        return L2DistLoss
    if callable(spec):
        return spec
    if isinstance(spec, str):
        if spec in LOSSES:
            return LOSSES[spec]
        # parameterized form "HuberLoss(0.5)"
        if "(" in spec and spec.endswith(")"):
            name, argstr = spec.split("(", 1)
            if name in _FACTORIES:
                return _FACTORIES[name](float(argstr[:-1]))
        raise KeyError(f"unknown loss {spec!r}; known: {sorted(LOSSES)}")
    raise TypeError(f"cannot interpret loss spec {spec!r}")


def weighted_mean_loss(elem, weights=None):
    """Weighted normalized mean, matching LossFunctions.jl `AggMode.WeightedMean`
    as used by the reference (/root/reference/src/LossFunctions.jl:27-28)."""
    if weights is None:
        return jnp.mean(elem, axis=-1)
    return jnp.sum(elem * weights, axis=-1) / jnp.sum(weights, axis=-1)
