from .flat import (
    KIND_BINARY,
    KIND_CONST,
    KIND_PAD,
    KIND_UNARY,
    KIND_VAR,
    FlatTrees,
    flatten_trees,
    pad_bucket,
    unflatten_tree,
)
from .interp import eval_diff_trees, eval_grad_trees, eval_trees, eval_trees_with_ok
from .operators import (
    BINARY_OPS,
    UNARY_OPS,
    Operator,
    OperatorSet,
    default_operator_set,
    resolve_operators,
)

__all__ = [
    "KIND_BINARY",
    "KIND_CONST",
    "KIND_PAD",
    "KIND_UNARY",
    "KIND_VAR",
    "FlatTrees",
    "flatten_trees",
    "pad_bucket",
    "unflatten_tree",
    "eval_trees",
    "eval_trees_with_ok",
    "eval_grad_trees",
    "eval_diff_trees",
    "BINARY_OPS",
    "UNARY_OPS",
    "Operator",
    "OperatorSet",
    "default_operator_set",
    "resolve_operators",
]
