"""Batched constant optimization: vmapped BFGS on the TPU.

Replaces the reference's Optim.jl BFGS-with-backtracking inner loop
(/root/reference/src/ConstantOptimization.jl:11-83, defaults BFGS + 8
iterations + 2 random restarts, /root/reference/src/Options.jl:429-431,692-708).
Where the reference optimizes one tree at a time on the host, here the whole
selected set — every (member, restart) pair across all islands — is one
vmapped XLA program: gradients come from ``jax.grad`` through the batched
interpreter's custom VJP, the line search is a ``lax.while_loop`` backtracking
search, and non-constant slots are masked out of the update.

This module is the interpreter (scan) gradient path. The device engine's
const-opt additionally has a Pallas gradient path: when the fused Mosaic loss
kernel is supported, ``interp_pallas.pallas_diff_loss`` (a ``jax.custom_vjp``
around the fused loss+grad kernel) replaces the interpreter VJP inside the
BFGS while_loops, so each value+gradient evaluation is ONE kernel launch
(see device_search._make_const_opt_fn_pallas). Both paths share the masking,
line-search, and accept-only-if-improved semantics here.
"""

from __future__ import annotations

import functools
import os
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .flat import (
    KIND_CONST,
    FlatTrees,
    batch_bucket,
    flatten_trees,
    length_buckets,
    length_buckets_enabled,
    slice_nodes,
)
from .interp import _Structure, _eval_one
from .operators import OperatorSet

__all__ = ["optimize_constants_batched"]


def _tree_loss_fn(opset: OperatorSet, loss_elem: Callable):
    def loss(val, structure, X, y, w, has_w):
        pred = _eval_one(opset, structure, val, X)
        elem = loss_elem(pred, y)
        if has_w:
            return jnp.sum(elem * w) / jnp.sum(w)
        return jnp.mean(elem)

    return loss


def _bfgs_single(
    loss_fn, val0, structure, X, y, w, has_w, mask, iters: int, combine=None,
    g_tol: float = 0.0,
):
    """Convergence-gated BFGS with Armijo backtracking on one tree's
    constants. mask[N]: which slots are free parameters. Returns (val, f).

    ``g_tol``: Optim.jl g_tol semantics — stop as soon as the masked
    gradient's inf-norm drops below it (or ``iters`` is reached). g_tol=0
    reproduces the legacy fixed-iteration behavior exactly: the exit test is
    ``~(|g|_inf < g_tol)`` so neither 0 nor NaN gradients trip it early.

    ``combine``: rows-sharded mode (shard_map) — ``loss_fn`` then sees only
    this shard's row block and ``combine`` merges per-shard values into the
    global weighted mean (psum(x*wsum)/psum(wsum)). The SAME linear map
    applies to losses and to every gradient component, so one callable
    covers both; it must be applied OUTSIDE jax.grad (autodiff through a
    forward psum yields only the local gradient piece, which would diverge
    the rows-replicated state). The convergence test reads the
    already-combined gradient from the carry, so no collective runs inside
    the while condition."""
    N = val0.shape[0]
    dtype = val0.dtype
    eye = jnp.eye(N, dtype=dtype)
    if combine is None:
        combine = lambda x: x  # noqa: E731

    f0, g0 = jax.value_and_grad(loss_fn)(val0, structure, X, y, w, has_w)
    f0, g0 = combine(f0), combine(g0)
    g0 = jnp.where(mask, g0, 0.0)

    def body(carry, _):
        x, H, f, g = carry
        d = -(H @ g)
        d = jnp.where(mask, d, 0.0)
        gtd = jnp.vdot(g, d)
        # fall back to steepest descent if not a descent direction
        bad_dir = gtd >= 0
        d = jnp.where(bad_dir, -g, d)
        gtd = jnp.where(bad_dir, -jnp.vdot(g, g), gtd)

        # backtracking line search (Armijo, c1=1e-4, halving, <=12 steps)
        def ls_cond(state):
            alpha, f_new, k = state
            armijo = f_new <= f + 1e-4 * alpha * gtd
            return (~armijo) & (k < 12)

        def ls_body(state):
            alpha, _, k = state
            alpha = alpha * 0.5
            f_try = combine(loss_fn(x + alpha * d, structure, X, y, w, has_w))
            return alpha, f_try, k + 1

        f_try = combine(loss_fn(x + d, structure, X, y, w, has_w))
        alpha, f_new, _ = lax.while_loop(ls_cond, ls_body, (jnp.asarray(1.0, dtype), f_try, 0))

        ok = jnp.isfinite(f_new) & (f_new < f)
        x_new = jnp.where(ok, x + alpha * d, x)
        f_next = jnp.where(ok, f_new, f)
        g_new = combine(jax.grad(loss_fn)(x_new, structure, X, y, w, has_w))
        g_new = jnp.where(mask, g_new, 0.0)

        s = x_new - x
        yk = g_new - g
        sy = jnp.vdot(s, yk)
        rho = jnp.where(sy > 1e-10, 1.0 / jnp.where(sy > 1e-10, sy, 1.0), 0.0)
        I_rsy = eye - rho * jnp.outer(s, yk)
        H_new = I_rsy @ H @ I_rsy.T + rho * jnp.outer(s, s)
        H_next = jnp.where(sy > 1e-10, H_new, H)

        return (x_new, H_next, f_next, g_new), None

    def w_cond(carry):
        x, H, f, g, k = carry
        # ~(norm < g_tol): continue on NaN and on g_tol=0 (legacy behavior)
        return (k < iters) & ~(jnp.max(jnp.abs(g)) < g_tol)

    def w_body(carry):
        x, H, f, g, k = carry
        (x, H, f, g), _ = body((x, H, f, g), None)
        return (x, H, f, g, k + 1)

    (x, _, f, _, _) = lax.while_loop(
        w_cond, w_body, (val0, eye, f0, g0, jnp.asarray(0, jnp.int32))
    )
    return x, f


def _newton_single(
    loss_fn, val0, structure, X, y, w, has_w, mask, iters: int, combine=None,
    g_tol: float = 0.0,
):
    """Newton + backtracking on a SINGLE masked constant (the reference's
    1-constant special case, /root/reference/src/ConstantOptimization.jl:22-41).
    Curvature via a Hessian-vector product along the masked direction.
    ``g_tol``: stop when the projected gradient magnitude drops below it
    (Optim.jl g_tol; 0 = legacy fixed-iteration behavior, see _bfgs_single).
    ``combine``: see _bfgs_single — applied outside grad/jvp (both are
    linear maps of the per-shard pieces); the gate reads the combined
    gradient from the carry so the while condition runs no collective."""
    e = mask.astype(val0.dtype)
    if combine is None:
        combine = lambda x: x  # noqa: E731

    def f(v):
        return loss_fn(v, structure, X, y, w, has_w)

    def fc(v):
        return combine(f(v))

    def proj_grad(v):
        return jnp.vdot(combine(jax.grad(f)(v)), e)

    def body(carry):
        x, fx, g, k = carry
        h = jnp.vdot(combine(jax.jvp(jax.grad(f), (x,), (e,))[1]), e)
        step = jnp.where(jnp.abs(h) > 1e-30, -g / h, -g)
        step = jnp.where(jnp.isfinite(step), step, 0.0)

        def ls_cond(state):
            alpha, f_new, k_ = state
            return (~(f_new < fx)) & (k_ < 8)

        def ls_body(state):
            alpha, _, k_ = state
            alpha = alpha * 0.5
            return alpha, fc(x + alpha * step * e), k_ + 1

        f_try = fc(x + step * e)
        alpha, f_new, _ = lax.while_loop(
            ls_cond, ls_body, (jnp.asarray(1.0, val0.dtype), f_try, 0)
        )
        ok = jnp.isfinite(f_new) & (f_new < fx)
        x_new = jnp.where(ok, x + alpha * step * e, x)
        return x_new, jnp.where(ok, f_new, fx), proj_grad(x_new), k + 1

    def cond(carry):
        x, fx, g, k = carry
        return (k < iters) & ~(jnp.abs(g) < g_tol)

    f0 = fc(val0)
    x, fx, _, _ = lax.while_loop(
        cond, body, (val0, f0, proj_grad(val0), jnp.asarray(0, jnp.int32))
    )
    return x, fx


def _neldermead_single(
    loss_fn, val0, structure, X, y, w, has_w, mask, iters: int, combine=None,
    g_tol: float = 0.0,
):
    """Masked Nelder–Mead simplex (the reference's configurable alternative,
    /root/reference/src/Options.jl:522-532). Non-constant slots stay pinned.
    ``g_tol`` is accepted for signature parity but unused — the simplex is
    derivative-free, so there is no gradient norm to gate on (Optim.jl's
    NelderMead likewise ignores g_tol).
    ``combine``: see _bfgs_single (derivative-free, so values only)."""
    N = val0.shape[0]
    dtype = val0.dtype
    mf = mask.astype(dtype)
    if combine is None:
        combine = lambda x: x  # noqa: E731

    def f(v):
        return combine(loss_fn(v, structure, X, y, w, has_w))

    # initial simplex: val0 plus one perturbed vertex per (masked) coordinate
    steps = jnp.where(val0 != 0, 0.05 * val0, 0.00025) * mf
    verts = jnp.concatenate([val0[None], val0[None] + jnp.diag(steps)], axis=0)
    fvals = jax.vmap(f)(verts)
    fvals = jnp.where(jnp.isfinite(fvals), fvals, jnp.inf)

    def body(carry, _):
        verts, fvals = carry
        order = jnp.argsort(fvals)
        verts = verts[order]
        fvals = fvals[order]
        best, worst = verts[0], verts[-1]
        centroid = jnp.mean(verts[:-1], axis=0)
        refl = centroid + (centroid - worst) * mf
        f_r = f(refl)
        exp_ = centroid + 2.0 * (centroid - worst) * mf
        f_e = f(exp_)
        cont = centroid - 0.5 * (centroid - worst) * mf
        f_c = f(cont)

        use_exp = (f_r < fvals[0]) & (f_e < f_r)
        use_refl = (f_r < fvals[-2]) & ~use_exp
        use_cont = (~use_exp) & (~use_refl) & (f_c < fvals[-1])
        new_v = jnp.where(
            use_exp, exp_, jnp.where(use_refl, refl, jnp.where(use_cont, cont, worst))
        )
        new_f = jnp.where(
            use_exp, f_e, jnp.where(use_refl, f_r, jnp.where(use_cont, f_c, fvals[-1]))
        )
        shrink = (~use_exp) & (~use_refl) & (~use_cont)

        verts2 = verts.at[-1].set(new_v)
        fvals2 = fvals.at[-1].set(new_f)
        # shrink toward best when nothing helped
        sv = best[None] + 0.5 * (verts - best[None]) * mf[None]
        sf = jax.vmap(f)(sv)
        verts3 = jnp.where(shrink, sv, verts2)
        fvals3 = jnp.where(shrink, jnp.where(jnp.isfinite(sf), sf, jnp.inf), fvals2)
        return (verts3, fvals3), None

    (verts, fvals), _ = lax.scan(body, (verts, fvals), None, length=iters)
    best = jnp.argmin(fvals)
    return verts[best], fvals[best]


def remat_tree_loss(opset, loss_elem, X, y, w, has_w, complex_n=None,
                    objective=None):
    """Interpreter loss closure with rematerialization: recompute the forward
    sweep in the backward pass instead of saving per-branch residuals —
    trades ~2x FLOPs for ~n_ops x less live memory, which is what bounds the
    BFGS batch size. Shared by _optimize_batch and the device engine's
    non-Pallas const-opt fallback (models/device_search.py); keeps the
    6-arg _bfgs_single signature, ignoring the already-closed-over args.

    ``complex_n``: optimize complex constants through a REAL 2N view
    (v = [real; imag]) so the BFGS/Nelder-Mead inner products stay valid —
    the reference drives Optim's BFGS for complex T the equivalent way
    (/root/reference/src/ConstantOptimization.jl:27).

    ``objective``: JAX-traceable full objective (Options.loss_function_jit)
    — constants are then tuned against the SAME objective the search
    scores with, not the elementwise loss."""
    if objective is not None:
        def raw(val, structure, X_, y_, w_, hw_):
            pred = _eval_one(opset, structure, val, X_)
            return jnp.asarray(
                objective(pred[None, :], y_, w_ if hw_ else None)
            )[0]
    else:
        raw = _tree_loss_fn(opset, loss_elem)
    if complex_n is None:
        ck = jax.checkpoint(lambda v, s: raw(v, s, X, y, w, has_w))
    else:
        N = complex_n
        ck = jax.checkpoint(
            lambda v, s: raw(v[:N] + 1j * v[N:], s, X, y, w, has_w)
        )

    def loss_fn(v, s, X_, y_, w_, hw_):
        return ck(v, s)

    return loss_fn


def _clamped_chunk(
    chunk: int, S_r: int, N_slots: int, R_rows: int, dtype, complex_vals: bool,
    budget: float = 2e9,
) -> int:
    """Row-aware chunk clamp for the BFGS lax.map: each vmapped instance
    holds ~[N_slots, R] rematerialized interpreter registers per restart.
    The itemsize comes from the actual compute dtype (f64 doubles, complex
    doubles again); a complex run driven through the real 2N view with a
    non-complex dtype still pays the pair, hence the explicit x2."""
    itemsize = np.dtype(dtype).itemsize
    if complex_vals and np.dtype(dtype).kind != "c":
        itemsize *= 2
    per_instance = max(1, S_r * N_slots * R_rows * itemsize)
    return max(1, min(chunk, int(budget // per_instance)))


def _optimize_batch(
    flat, X, y, w, starts, opset, loss_elem, iters, has_w, algorithm="BFGS",
    complex_vals=False, objective=None, g_tol=0.0,
):
    """Host wrapper: resolve the SR_CONSTOPT_CHUNK chunk size *outside* the
    jitted body so the env var is re-read on every call and participates in
    the jit cache key as a static argument (reading it at trace time froze
    the first value into every later executable — the r06 class)."""
    chunk = int(os.environ.get("SR_CONSTOPT_CHUNK", 8))
    # row-aware clamp: keep a chunk under ~2GB so big-n unbatched runs
    # degrade to smaller chunks instead of crashing the device (observed:
    # worker crash at n=1M with chunk=8); see _clamped_chunk
    chunk = _clamped_chunk(
        chunk, starts.shape[1], flat.kind.shape[1], X.shape[-1], X.dtype,
        complex_vals,
    )
    chunk = max(1, min(chunk, starts.shape[0]))
    return _optimize_batch_impl(
        flat, X, y, w, starts, opset, loss_elem, iters, has_w,
        algorithm=algorithm, complex_vals=complex_vals, objective=objective,
        g_tol=g_tol, chunk=chunk,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "opset", "loss_elem", "iters", "has_w", "algorithm", "complex_vals",
        "objective", "g_tol", "chunk",
    ),
)
def _optimize_batch_impl(
    flat, X, y, w, starts, opset, loss_elem, iters, has_w, algorithm="BFGS",
    complex_vals=False, objective=None, g_tol=0.0, chunk=8,
):
    """starts: [P, S, N] initial constant vectors (S = 1 + nrestarts).
    Returns best (val [P,N], loss [P]) over restarts per tree.

    Per reference semantics, trees with exactly ONE constant always use
    Newton+backtracking; others use the configured algorithm
    (/root/reference/src/ConstantOptimization.jl:22-41).

    Memory discipline: the batch runs as lax.map over chunks of
    SR_CONSTOPT_CHUNK trees (default 8) with the interpreter rematerialized
    in the backward pass — a fully vmapped batch materializes [P, S, N, R]
    residuals, which at the 10k-row x 100x100-population config is tens of
    GB (observed: 46G requested on a 16G chip). Same tuning as the device
    engine's fallback (models/device_search.py)."""
    N_slots = flat.kind.shape[1]
    loss_fn = remat_tree_loss(
        opset, loss_elem, X, y, w, has_w,
        complex_n=N_slots if complex_vals else None,
        objective=objective,
    )
    structure = _Structure(flat.kind, flat.op, flat.lhs, flat.rhs, flat.feat, flat.length)
    mask = flat.kind == KIND_CONST  # [P, N]
    if complex_vals:  # starts are the real 2N view [..., real; imag]
        mask = jnp.concatenate([mask, mask], axis=1)
    main = _bfgs_single if algorithm == "BFGS" else _neldermead_single

    def per_tree(struct_p, starts_p, mask_p):
        one_const = jnp.sum(mask_p) == 1

        def per_restart(v0):
            vm, fm = main(
                loss_fn, v0, struct_p, X, y, w, has_w, mask_p, iters,
                g_tol=g_tol,
            )
            vn, fn_ = _newton_single(
                loss_fn, v0, struct_p, X, y, w, has_w, mask_p, iters,
                g_tol=g_tol,
            )
            return (
                jnp.where(one_const, vn, vm),
                jnp.where(one_const, fn_, fm),
            )

        vals, fs = jax.vmap(per_restart)(starts_p)  # [S,N], [S]
        fs = jnp.where(jnp.isfinite(fs), fs, jnp.inf)
        best = jnp.argmin(fs)
        return vals[best], fs[best]

    structure = _Structure(*(jnp.asarray(a) for a in structure))
    P = starts.shape[0]
    # chunk is resolved (env read + row-aware clamp) by the _optimize_batch
    # wrapper so it participates in the jit cache key
    # Pad the batch up to a chunk multiple (duplicating tree 0) rather than
    # shrinking the chunk to a divisor of P: shrink-to-divisor degrades to
    # chunk=1 (fully serialized lax.map) whenever P and chunk are coprime.
    # The main caller buckets P to a power of two, but direct callers and
    # SR_CONSTOPT_CHUNK overrides see arbitrary (P, chunk) pairs.
    pad = -P % chunk
    if pad:
        dup = lambda a: jnp.concatenate([a, jnp.repeat(a[:1], pad, axis=0)])
        structure = _Structure(*(dup(a) for a in structure))
        starts, mask = dup(starts), dup(mask)
    n_chunks = (P + pad) // chunk
    if n_chunks == 1:
        vals, fs = jax.vmap(per_tree)(structure, starts, mask)
    else:
        chunked = jax.tree_util.tree_map(
            lambda a: a.reshape((n_chunks, chunk) + a.shape[1:]),
            (structure, starts, mask),
        )
        vals, fs = lax.map(lambda args: jax.vmap(per_tree)(*args), chunked)
        vals = vals.reshape((P + pad,) + vals.shape[2:])
        fs = fs.reshape((P + pad,))
    if complex_vals:  # back to complex [P, N]
        vals = vals[:, :N_slots] + 1j * vals[:, N_slots:]
    return vals[:P], fs[:P]


def _optimize_constants_custom_objective(trees, scorer, options, rng):
    """Host Nelder–Mead over each tree's constants against the user's full
    ``loss_function`` (which sees the raw tree, so the device BFGS cannot be
    used; the reference drives Optim with the same host objective,
    /root/reference/src/ConstantOptimization.jl:50 + LossFunctions.jl:78-94)."""
    fn = options.loss_function
    ds = scorer.dataset
    n_iters = max(20, 10 * int(options.optimizer_iterations))
    new_trees, losses, improved = [], [], []
    for tree in trees:
        c0 = tree.get_constants()
        if c0.size == 0:
            loss0 = float(fn(tree, ds, options))
            new_trees.append(tree)
            losses.append(loss0)
            improved.append(False)
            continue
        work = tree.copy()

        def obj(c):
            work.set_constants(c)
            try:
                v = float(fn(work, ds, options))
            except Exception:  # noqa: BLE001
                return np.inf
            return v if np.isfinite(v) else np.inf

        best_c, best_f = _host_neldermead(obj, c0, n_iters)
        with scorer._evals_lock:
            scorer.num_evals += n_iters * (len(c0) + 1)
        f0 = obj(c0)
        if best_f < f0:
            out = tree.copy()
            out.set_constants(best_c)
            new_trees.append(out)
            losses.append(best_f)
            improved.append(True)
        else:
            new_trees.append(tree)
            losses.append(f0)
            improved.append(False)
    return new_trees, np.asarray(losses), np.asarray(improved)


def _host_neldermead(obj, x0: np.ndarray, iters: int):
    """Minimal dependency-free Nelder–Mead."""
    n = len(x0)
    verts = [np.asarray(x0, dtype=np.float64)]
    for i in range(n):
        v = verts[0].copy()
        v[i] += 0.05 * v[i] if v[i] != 0 else 0.00025
        verts.append(v)
    fvals = [obj(v) for v in verts]
    for _ in range(iters):
        order = np.argsort(fvals)
        verts = [verts[k] for k in order]
        fvals = [fvals[k] for k in order]
        centroid = np.mean(verts[:-1], axis=0)
        refl = centroid + (centroid - verts[-1])
        f_r = obj(refl)
        if f_r < fvals[0]:
            exp_ = centroid + 2 * (centroid - verts[-1])
            f_e = obj(exp_)
            verts[-1], fvals[-1] = (exp_, f_e) if f_e < f_r else (refl, f_r)
        elif f_r < fvals[-2]:
            verts[-1], fvals[-1] = refl, f_r
        else:
            cont = centroid - 0.5 * (centroid - verts[-1])
            f_c = obj(cont)
            if f_c < fvals[-1]:
                verts[-1], fvals[-1] = cont, f_c
            else:
                verts = [verts[0]] + [
                    verts[0] + 0.5 * (v - verts[0]) for v in verts[1:]
                ]
                fvals = [fvals[0]] + [obj(v) for v in verts[1:]]
    k = int(np.argmin(fvals))
    return verts[k], fvals[k]


def optimize_constants_batched(
    trees,
    scorer,
    options,
    rng: np.random.Generator,
    idx: np.ndarray | None = None,
):
    """Optimize constants of `trees` in one device program.

    Returns (new_trees, losses, improved_mask); trees without constants pass
    through. Acceptance semantics follow the reference: keep the optimized
    constants only when the loss improved
    (/root/reference/src/ConstantOptimization.jl:70-78).
    """
    if not trees:
        return [], np.zeros((0,)), np.zeros((0,), dtype=bool)
    if options.loss_function is not None:
        return _optimize_constants_custom_objective(trees, scorer, options, rng)
    if options.graph_nodes:
        shared = [t.count_unique_nodes() != t.count_nodes() for t in trees]
    else:
        shared = None
    if shared is not None and any(shared):
        # Shared constants would expand into multiple independent device
        # parameters and the writeback would unshare the DAG; optimize only
        # the sharing-free trees and pass the rest through unchanged.
        plain = [t for t, s in zip(trees, shared) if not s]
        if plain:
            p_trees, p_losses, p_improved = optimize_constants_batched(
                plain, scorer, options, rng, idx=idx
            )
        else:
            p_trees, p_losses, p_improved = [], np.zeros(0), np.zeros(0, bool)
        shared_trees = [t for t, s in zip(trees, shared) if s]
        shared_losses = scorer.loss_many(shared_trees, idx=idx) if shared_trees else []
        out_t, out_l, out_i = [], [], []
        pi = si = 0
        for s in shared:
            if s:
                out_t.append(shared_trees[si])
                out_l.append(float(shared_losses[si]))
                out_i.append(False)
                si += 1
            else:
                out_t.append(p_trees[pi])
                out_l.append(float(p_losses[pi]))
                out_i.append(bool(p_improved[pi]))
                pi += 1
        return out_t, np.asarray(out_l), np.asarray(out_i)

    n_real = len(trees)
    # pad the batch to a power-of-two bucket so the (large) BFGS program
    # compiles O(log P) times per search instead of once per iteration
    trees = trees + [trees[0]] * (batch_bucket(n_real) - n_real)

    dtype = scorer.dtype
    max_nodes = scorer.max_nodes
    flat = flatten_trees(trees, max_nodes, dtype=dtype)
    P, N = flat.kind.shape
    S = 1 + options.optimizer_nrestarts

    # restart jitter x(1 + sigma/2 * randn), sigma=1 like the reference's
    # perturbed re-starts (/root/reference/src/ConstantOptimization.jl:53-68)
    base = flat.val[:, None, :].repeat(S, axis=1).astype(dtype)  # [P,S,N]
    if np.dtype(dtype).kind == "c":
        # complex noise: restarts must perturb PHASE as well as magnitude
        # (the reference's T-typed perturbation draws complex noise — a
        # real-only jitter can never escape a wrong-phase basin, defeating
        # the 2N-view optimizer's restarts)
        noise = (
            rng.standard_normal(size=(P, S - 1, N))
            + 1j * rng.standard_normal(size=(P, S - 1, N))
        ) / np.sqrt(2.0)
        jitter = 1.0 + 0.5 * noise.astype(dtype)
    else:
        jitter = 1.0 + 0.5 * rng.standard_normal(size=(P, S - 1, N)).astype(dtype)
    base[:, 1:, :] *= jitter

    if idx is None:
        X, y, w = scorer.X, scorer.y, scorer.w
    else:
        X, y = scorer.X[:, idx], scorer.y[idx]
        w = None if scorer.w is None else scorer.w[idx]
    has_w = w is not None

    iters = int(options.optimizer_iterations)
    if options.optimizer_f_calls_limit:
        # ~4 objective evaluations per iteration per restart (value+grad +
        # line search); the reference passes f_calls_limit to Optim.Options
        iters = max(1, min(iters, int(options.optimizer_f_calls_limit) // (4 * S)))
    complex_vals = np.dtype(dtype).kind == "c"
    to_dev = jnp.asarray
    if complex_vals:
        # optimize through the real 2N view (see remat_tree_loss); weights
        # stay real, the loss is real, only the constants are complex
        base = np.concatenate([base.real, base.imag], axis=-1)
        # colocate with the CPU-committed complex dataset (see
        # Dataset.device_arrays: XLA:TPU has no complex arithmetic).
        # device_put numpy DIRECTLY: jnp.asarray would first materialize
        # the arrays on the default (TPU) device
        dev = next(iter(X.devices())) if hasattr(X, "devices") else None
        if dev is not None:
            to_dev = lambda a: jax.device_put(np.asarray(a), dev)  # noqa: E731
    g_tol = float(options.optimizer_g_tol)
    w_arg = w if has_w else to_dev(np.zeros((), np.empty(0, dtype).real.dtype))

    def run_batch(flat_b, starts_b):
        return _optimize_batch(
            FlatTrees(*(to_dev(a) for a in flat_b)),
            X,
            y,
            w_arg,
            to_dev(starts_b),
            scorer.opset,
            scorer.loss_elem,
            iters,
            has_w,
            algorithm=options.optimizer_algorithm,
            complex_vals=complex_vals,
            objective=options.loss_function_jit,
            g_tol=g_tol,
        )

    # length-bucketed dispatch: run the BFGS (and its remat'd scan loss) at
    # each bucket's node count instead of the global max_nodes; per-bucket
    # sub-batches re-pad to batch_bucket, keeping compiles O(buckets x log P).
    # The restart jitter was drawn on the FULL [P, S, N] base above, so the
    # trajectory is identical with bucketing on or off (pad slots are masked
    # out of the update and contribute exact zeros to losses/gradients).
    parts = length_buckets(np.asarray(flat.length), N)
    if length_buckets_enabled() and not (len(parts) == 1 and parts[0][0] == N):
        vals = np.array(flat.val, dtype=dtype)
        fs = np.empty((P,), dtype=np.float64)
        for n_b, sel in parts:
            sub = FlatTrees(*(np.asarray(a)[sel] for a in flat))
            if complex_vals:  # base is the real 2N view [..., real; imag]
                sub_starts = np.concatenate(
                    [base[sel][:, :, :n_b], base[sel][:, :, N:N + n_b]],
                    axis=-1,
                )
            else:
                sub_starts = base[sel][:, :, :n_b]
            pad = batch_bucket(sel.size) - sel.size
            if pad:
                dup = lambda a: np.concatenate(  # noqa: E731
                    [a, np.repeat(a[:1], pad, axis=0)]
                )
                sub = FlatTrees(*(dup(a) for a in sub))
                sub_starts = dup(sub_starts)
            vals_b, fs_b = run_batch(slice_nodes(sub, n_b), sub_starts)
            vals[sel, :n_b] = np.asarray(vals_b)[: sel.size]
            fs[sel] = np.asarray(fs_b, dtype=np.float64)[: sel.size]
    else:
        vals, fs = run_batch(flat, base)
        vals = np.asarray(vals)
        fs = np.asarray(fs, dtype=np.float64)

    # eval accounting: ~2 evals (value+grad) per iteration per restart —
    # using the f_calls_limit-CLAMPED iteration count actually run (with
    # convergence gating this is an upper bound; early exits do less work)
    n_rows = scorer.dataset.n if idx is None else len(idx)
    with scorer._evals_lock:
        scorer.num_evals += n_real * S * 2 * iters * (
            n_rows / scorer.dataset.n
        )

    trees = trees[:n_real]
    vals, fs = vals[:n_real], fs[:n_real]
    orig_losses = scorer.loss_many(trees, idx=idx)
    improved = fs < orig_losses
    new_trees = []
    for p, tree in enumerate(trees):
        if improved[p] and tree.has_constants():
            new = tree.copy()
            consts = vals[p][np.asarray(flat.kind[p]) == KIND_CONST]
            new.set_constants(consts)
            new_trees.append(new)
        else:
            new_trees.append(tree)
    final_losses = np.where(improved, fs, orig_losses)
    return new_trees, final_losses, improved
