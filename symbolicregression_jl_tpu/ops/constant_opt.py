"""Batched constant optimization: vmapped BFGS on the TPU.

Replaces the reference's Optim.jl BFGS-with-backtracking inner loop
(/root/reference/src/ConstantOptimization.jl:11-83, defaults BFGS + 8
iterations + 2 random restarts, /root/reference/src/Options.jl:429-431,692-708).
Where the reference optimizes one tree at a time on the host, here the whole
selected set — every (member, restart) pair across all islands — is one
vmapped XLA program: gradients come from ``jax.grad`` through the batched
interpreter's custom VJP, the line search is a ``lax.while_loop`` backtracking
search, and non-constant slots are masked out of the update.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .flat import KIND_CONST, FlatTrees, batch_bucket, flatten_trees
from .interp import _Structure, _eval_one
from .losses import weighted_mean_loss
from .operators import OperatorSet

__all__ = ["optimize_constants_batched"]


def _tree_loss_fn(opset: OperatorSet, loss_elem: Callable):
    def loss(val, structure, X, y, w, has_w):
        pred = _eval_one(opset, structure, val, X)
        elem = loss_elem(pred, y)
        if has_w:
            return jnp.sum(elem * w) / jnp.sum(w)
        return jnp.mean(elem)

    return loss


def _bfgs_single(loss_fn, val0, structure, X, y, w, has_w, mask, iters: int):
    """Fixed-iteration BFGS with Armijo backtracking on one tree's constants.
    mask[N]: which slots are free parameters. Returns (val, f)."""
    N = val0.shape[0]
    dtype = val0.dtype
    eye = jnp.eye(N, dtype=dtype)

    f0, g0 = jax.value_and_grad(loss_fn)(val0, structure, X, y, w, has_w)
    g0 = jnp.where(mask, g0, 0.0)

    def body(carry, _):
        x, H, f, g = carry
        d = -(H @ g)
        d = jnp.where(mask, d, 0.0)
        gtd = jnp.vdot(g, d)
        # fall back to steepest descent if not a descent direction
        bad_dir = gtd >= 0
        d = jnp.where(bad_dir, -g, d)
        gtd = jnp.where(bad_dir, -jnp.vdot(g, g), gtd)

        # backtracking line search (Armijo, c1=1e-4, halving, <=12 steps)
        def ls_cond(state):
            alpha, f_new, k = state
            armijo = f_new <= f + 1e-4 * alpha * gtd
            return (~armijo) & (k < 12)

        def ls_body(state):
            alpha, _, k = state
            alpha = alpha * 0.5
            f_try = loss_fn(x + alpha * d, structure, X, y, w, has_w)
            return alpha, f_try, k + 1

        f_try = loss_fn(x + d, structure, X, y, w, has_w)
        alpha, f_new, _ = lax.while_loop(ls_cond, ls_body, (jnp.asarray(1.0, dtype), f_try, 0))

        ok = jnp.isfinite(f_new) & (f_new < f)
        x_new = jnp.where(ok, x + alpha * d, x)
        f_next = jnp.where(ok, f_new, f)
        g_new = jax.grad(loss_fn)(x_new, structure, X, y, w, has_w)
        g_new = jnp.where(mask, g_new, 0.0)

        s = x_new - x
        yk = g_new - g
        sy = jnp.vdot(s, yk)
        rho = jnp.where(sy > 1e-10, 1.0 / jnp.where(sy > 1e-10, sy, 1.0), 0.0)
        I_rsy = eye - rho * jnp.outer(s, yk)
        H_new = I_rsy @ H @ I_rsy.T + rho * jnp.outer(s, s)
        H_next = jnp.where(sy > 1e-10, H_new, H)

        return (x_new, H_next, f_next, g_new), None

    (x, _, f, _), _ = lax.scan(body, (val0, eye, f0, g0), None, length=iters)
    return x, f


@functools.partial(
    jax.jit, static_argnames=("opset", "loss_elem", "iters", "has_w")
)
def _optimize_batch(flat, X, y, w, starts, opset, loss_elem, iters, has_w):
    """starts: [P, S, N] initial constant vectors (S = 1 + nrestarts).
    Returns best (val [P,N], loss [P]) over restarts per tree."""
    loss_fn = _tree_loss_fn(opset, loss_elem)
    structure = _Structure(flat.kind, flat.op, flat.lhs, flat.rhs, flat.feat, flat.length)
    mask = flat.kind == KIND_CONST  # [P, N]

    def per_tree(struct_p, starts_p, mask_p):
        def per_restart(v0):
            return _bfgs_single(
                loss_fn, v0, struct_p, X, y, w, has_w, mask_p, iters
            )

        vals, fs = jax.vmap(per_restart)(starts_p)  # [S,N], [S]
        fs = jnp.where(jnp.isfinite(fs), fs, jnp.inf)
        best = jnp.argmin(fs)
        return vals[best], fs[best]

    return jax.vmap(per_tree)(
        _Structure(*(jnp.asarray(a) for a in structure)), starts, mask
    )


def optimize_constants_batched(
    trees,
    scorer,
    options,
    rng: np.random.Generator,
    idx: np.ndarray | None = None,
):
    """Optimize constants of `trees` in one device program.

    Returns (new_trees, losses, improved_mask); trees without constants pass
    through. Acceptance semantics follow the reference: keep the optimized
    constants only when the loss improved
    (/root/reference/src/ConstantOptimization.jl:70-78).
    """
    if not trees:
        return [], np.zeros((0,)), np.zeros((0,), dtype=bool)

    n_real = len(trees)
    # pad the batch to a power-of-two bucket so the (large) BFGS program
    # compiles O(log P) times per search instead of once per iteration
    trees = trees + [trees[0]] * (batch_bucket(n_real) - n_real)

    dtype = scorer.dtype
    max_nodes = scorer.max_nodes
    flat = flatten_trees(trees, max_nodes, dtype=dtype)
    P, N = flat.kind.shape
    S = 1 + options.optimizer_nrestarts

    # restart jitter x(1 + sigma/2 * randn), sigma=1 like the reference's
    # perturbed re-starts (/root/reference/src/ConstantOptimization.jl:53-68)
    base = flat.val[:, None, :].repeat(S, axis=1).astype(dtype)  # [P,S,N]
    jitter = 1.0 + 0.5 * rng.standard_normal(size=(P, S - 1, N)).astype(dtype)
    base[:, 1:, :] *= jitter

    if idx is None:
        X, y, w = scorer.X, scorer.y, scorer.w
    else:
        X, y = scorer.X[:, idx], scorer.y[idx]
        w = None if scorer.w is None else scorer.w[idx]
    has_w = w is not None
    w_arg = w if has_w else jnp.zeros((), dtype)

    vals, fs = _optimize_batch(
        FlatTrees(*(jnp.asarray(a) for a in flat)),
        X,
        y,
        w_arg,
        jnp.asarray(base),
        scorer.opset,
        scorer.loss_elem,
        int(options.optimizer_iterations),
        has_w,
    )
    vals = np.asarray(vals)
    fs = np.asarray(fs, dtype=np.float64)

    # eval accounting: ~2 evals (value+grad) per iteration per restart
    n_rows = scorer.dataset.n if idx is None else len(idx)
    scorer.num_evals += n_real * S * 2 * options.optimizer_iterations * (
        n_rows / scorer.dataset.n
    )

    trees = trees[:n_real]
    vals, fs = vals[:n_real], fs[:n_real]
    orig_losses = scorer.loss_many(trees, idx=idx)
    improved = fs < orig_losses
    new_trees = []
    for p, tree in enumerate(trees):
        if improved[p] and tree.has_constants():
            new = tree.copy()
            consts = vals[p][np.asarray(flat.kind[p]) == KIND_CONST]
            new.set_constants(consts)
            new_trees.append(new)
        else:
            new_trees.append(tree)
    final_losses = np.where(improved, fs, orig_losses)
    return new_trees, final_losses, improved
