"""SI unit parsing and rational-exponent dimension arithmetic.

Host-side counterpart of the reference's DynamicQuantities integration
(/root/reference/src/InterfaceDynamicQuantities.jl:24-66): user-supplied unit
strings (or per-feature lists) are parsed into ``Quantity`` values — a scale
factor times a ``Dimensions`` vector of rational exponents over the 7 SI base
dimensions. Small and cold: dimensional analysis runs on one sample per tree
(see dimensional_analysis.py), so plain Python fractions are plenty.
"""

from __future__ import annotations

import dataclasses
import re
from fractions import Fraction

__all__ = ["Dimensions", "Quantity", "parse_unit", "parse_units_vector"]

_BASE = ("length", "mass", "time", "current", "temperature", "luminosity", "amount")


@dataclasses.dataclass(frozen=True)
class Dimensions:
    """Rational exponents over the SI base dimensions (m kg s A K cd mol)."""

    length: Fraction = Fraction(0)
    mass: Fraction = Fraction(0)
    time: Fraction = Fraction(0)
    current: Fraction = Fraction(0)
    temperature: Fraction = Fraction(0)
    luminosity: Fraction = Fraction(0)
    amount: Fraction = Fraction(0)

    def __mul__(self, other: "Dimensions") -> "Dimensions":
        return Dimensions(
            *(getattr(self, b) + getattr(other, b) for b in _BASE)
        )

    def __truediv__(self, other: "Dimensions") -> "Dimensions":
        return Dimensions(
            *(getattr(self, b) - getattr(other, b) for b in _BASE)
        )

    def __pow__(self, p) -> "Dimensions":
        p = Fraction(p).limit_denominator(1000)
        return Dimensions(*(getattr(self, b) * p for b in _BASE))

    @property
    def dimensionless(self) -> bool:
        return all(getattr(self, b) == 0 for b in _BASE)

    def __str__(self):
        sym = dict(
            length="m", mass="kg", time="s", current="A",
            temperature="K", luminosity="cd", amount="mol",
        )
        parts = []
        for b in _BASE:
            e = getattr(self, b)
            if e != 0:
                parts.append(sym[b] if e == 1 else f"{sym[b]}^{e}")
        return " ".join(parts) if parts else "1"


DIMENSIONLESS = Dimensions()


@dataclasses.dataclass(frozen=True)
class Quantity:
    """value x dimensions (value used for unit scale factors, e.g. km = 1000 m)."""

    value: float
    dims: Dimensions

    def __mul__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.value * other.value, self.dims * other.dims)

    def __truediv__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.value / other.value, self.dims / other.dims)

    def __pow__(self, p) -> "Quantity":
        return Quantity(self.value ** float(p), self.dims**p)


def _d(**kw) -> Dimensions:
    return Dimensions(**{k: Fraction(v) for k, v in kw.items()})


# base + derived units (value = scale to SI base)
_UNITS: dict[str, Quantity] = {
    "m": Quantity(1.0, _d(length=1)),
    "g": Quantity(1e-3, _d(mass=1)),
    "s": Quantity(1.0, _d(time=1)),
    "A": Quantity(1.0, _d(current=1)),
    "K": Quantity(1.0, _d(temperature=1)),
    "cd": Quantity(1.0, _d(luminosity=1)),
    "mol": Quantity(1.0, _d(amount=1)),
    # derived
    "Hz": Quantity(1.0, _d(time=-1)),
    "N": Quantity(1.0, _d(mass=1, length=1, time=-2)),
    "Pa": Quantity(1.0, _d(mass=1, length=-1, time=-2)),
    "J": Quantity(1.0, _d(mass=1, length=2, time=-2)),
    "W": Quantity(1.0, _d(mass=1, length=2, time=-3)),
    "C": Quantity(1.0, _d(current=1, time=1)),
    "V": Quantity(1.0, _d(mass=1, length=2, time=-3, current=-1)),
    "F": Quantity(1.0, _d(mass=-1, length=-2, time=4, current=2)),
    "Ohm": Quantity(1.0, _d(mass=1, length=2, time=-3, current=-2)),
    "T": Quantity(1.0, _d(mass=1, time=-2, current=-1)),
    "Wb": Quantity(1.0, _d(mass=1, length=2, time=-2, current=-1)),
    "L": Quantity(1e-3, _d(length=3)),
    "bar": Quantity(1e5, _d(mass=1, length=-1, time=-2)),
    "eV": Quantity(1.602176634e-19, _d(mass=1, length=2, time=-2)),
    "h": Quantity(3600.0, _d(time=1)),
    "min": Quantity(60.0, _d(time=1)),
    "day": Quantity(86400.0, _d(time=1)),
}

_PREFIXES = {
    "Q": 1e30, "R": 1e27, "Y": 1e24, "Z": 1e21, "E": 1e18, "P": 1e15,
    "T": 1e12, "G": 1e9, "M": 1e6, "k": 1e3, "h": 1e2, "da": 1e1,
    "d": 1e-1, "c": 1e-2, "m": 1e-3, "u": 1e-6, "µ": 1e-6, "n": 1e-9,
    "p": 1e-12, "f": 1e-15, "a": 1e-18, "z": 1e-21, "y": 1e-24,
}

_TOKEN = re.compile(
    r"\s*([*/])?\s*([A-Za-zµΩ]+)\s*(?:\^\s*(-?\d+(?:\s*//?\s*\d+)?(?:\.\d+)?))?"
)


def _lookup(sym: str) -> Quantity:
    if sym in ("Ω",):
        sym = "Ohm"
    if sym in _UNITS:
        return _UNITS[sym]
    # prefixed form: longest-prefix match with a known remainder
    for plen in (2, 1):
        if len(sym) > plen and sym[:plen] in _PREFIXES and sym[plen:] in _UNITS:
            base = _UNITS[sym[plen:]]
            return Quantity(base.value * _PREFIXES[sym[:plen]], base.dims)
    raise ValueError(f"unknown unit {sym!r}")


def _parse_exponent(exp: str) -> Fraction:
    exp = exp.replace(" ", "").replace("//", "/")
    if "." in exp:
        return Fraction(exp).limit_denominator(1000)
    return Fraction(exp)


class _Parser:
    """Recursive-descent parser for unit expressions with grouping:
    expr := factor ((* | /) factor)* ; factor := (unit | '(' expr ')')['^'exp]."""

    def __init__(self, s: str, spec: str):
        self.s = s
        self.spec = spec
        self.pos = 0

    def _ws(self):
        while self.pos < len(self.s) and self.s[self.pos].isspace():
            self.pos += 1

    def peek(self) -> str:
        self._ws()
        return self.s[self.pos] if self.pos < len(self.s) else ""

    def expr(self) -> Quantity:
        out = self.factor()
        while True:
            ch = self.peek()
            if ch == "*":
                self.pos += 1
                out = out * self.factor()
            elif ch == "/":
                self.pos += 1
                out = out / self.factor()
            else:
                return out

    def factor(self) -> Quantity:
        self._ws()
        if self.peek() == "(":
            self.pos += 1
            q = self.expr()
            if self.peek() != ")":
                raise ValueError(f"unbalanced parentheses in unit {self.spec!r}")
            self.pos += 1
        else:
            m = re.compile(r"[A-Za-zµΩ]+").match(self.s, self.pos)
            if m is None:
                raise ValueError(
                    f"cannot parse unit {self.spec!r} at {self.s[self.pos:]!r}"
                )
            q = _lookup(m.group(0))
            self.pos = m.end()
        if self.peek() == "^":
            self.pos += 1
            self._ws()
            if self.peek() == "(":
                self.pos += 1
                m = re.compile(r"[^)]*").match(self.s, self.pos)
                exp = m.group(0)
                self.pos = m.end()
                if self.peek() != ")":
                    raise ValueError(f"unbalanced exponent parens in {self.spec!r}")
                self.pos += 1
            else:
                m = re.compile(r"-?\d+(?:\s*//?\s*\d+)?(?:\.\d+)?").match(
                    self.s, self.pos
                )
                if m is None:
                    raise ValueError(f"bad exponent in unit {self.spec!r}")
                exp = m.group(0)
                self.pos = m.end()
            q = q ** _parse_exponent(exp)
        return q


def parse_unit(spec) -> Quantity:
    """Parse a unit spec: Quantity | Dimensions | number | string like
    'km/s^2', 'kg * m^2', 'J/(mol*K)', 'm^(1//2)' (Julia-style rational
    exponents and parenthesized groups supported)."""
    if spec is None or (isinstance(spec, (int, float)) and spec == 1):
        return Quantity(1.0, DIMENSIONLESS)
    if isinstance(spec, Quantity):
        return spec
    if isinstance(spec, Dimensions):
        return Quantity(1.0, spec)
    if isinstance(spec, (int, float)):
        return Quantity(float(spec), DIMENSIONLESS)
    if not isinstance(spec, str):
        raise TypeError(f"cannot parse unit spec {spec!r}")
    s = spec.strip()
    if s in ("", "1", "one"):
        return Quantity(1.0, DIMENSIONLESS)
    p = _Parser(s, spec)
    out = p.expr()
    p._ws()
    if p.pos != len(s):
        raise ValueError(f"trailing junk in unit {spec!r}: {s[p.pos:]!r}")
    return out


def parse_units_vector(spec, n: int) -> list[Quantity] | None:
    """Per-feature unit vector from a scalar spec or a list of specs
    (reference: get_units, /root/reference/src/InterfaceDynamicQuantities.jl:24-66)."""
    if spec is None:
        return None
    if isinstance(spec, (list, tuple)):
        if len(spec) != n:
            raise ValueError(f"expected {n} unit entries, got {len(spec)}")
        return [parse_unit(u) for u in spec]
    return [parse_unit(spec)] * n
