"""Preflight checks run before a search starts.

Counterpart of the reference's Configure.jl
(/root/reference/src/Configure.jl:3-112): operator totality smoke test over a
point grid, configuration validation, dataset validation with the >10k-row
batching hint, and an optional miniature end-to-end pipeline self-test
(the reference runs one on every worker, :254-307). Run by equation_search
when ``options.runtests`` is on (the reference's default too).
"""

from __future__ import annotations

import warnings

import numpy as np

__all__ = ["test_option_configuration", "test_dataset_configuration", "test_mini_pipeline"]


def test_option_configuration(options) -> None:
    """Operator totality: every operator must be total (finite or NaN, no
    raise) over a grid of 99 points in [-100, 100] — probed on the COMPLEX
    plane (x + xi) for complex compute dtypes, like the reference
    (/root/reference/src/Configure.jl:3-44 incl. :33-38). Our safe operators
    return NaN outside their domain, so anything else is a broken custom
    operator."""
    is_complex = np.dtype(options.dtype).kind == "c"
    grid = np.linspace(-100.0, 100.0, 99).astype(np.float64)
    out_dtype = np.complex128 if is_complex else np.float64
    to_arr = np.asarray
    if is_complex:
        grid = (grid + 1j * grid).astype(np.complex64)
        # complex ops only exist on the CPU backend
        from .utils.precision import commit_complex as to_arr  # noqa: F811
    from .ops.operators import SCALAR_IMPLS

    def check(op, args):
        try:
            with np.errstate(all="ignore"):
                impl = None if is_complex else SCALAR_IMPLS.get(op.name)
                if impl is not None:
                    out = np.array([impl(*a) for a in zip(*args)], dtype=out_dtype)
                else:
                    out = np.asarray(op.fn(*[to_arr(a) for a in args]), out_dtype)
        except Exception as e:  # noqa: BLE001
            raise ValueError(
                f"operator {op.name!r} is not total: raised {type(e).__name__} "
                "on the test grid; operators must return NaN outside their "
                "domain instead of raising"
            ) from e
        bad = np.isinf(out)
        if bad.any():
            # infinities are tolerated (gamma etc. map them to NaN at eval
            # time on device); warn so custom-operator authors notice
            warnings.warn(
                f"operator {op.name!r} returns inf on {int(bad.sum())} grid points"
            )

    for op in options.operators.unary:
        check(op, [grid])
    for op in options.operators.binary:
        check(op, [np.repeat(grid, 3)[: 99 * 2 : 2], np.tile(grid, 2)[: 99 * 2 : 2]])

    if options.operators.n_unary == 0 and options.operators.n_binary == 0:
        raise ValueError("need at least one operator")
    # same operator in both arities is a reference-level error (:47-83)
    shared = {o.name for o in options.operators.unary} & {
        o.name for o in options.operators.binary
    }
    if shared:
        raise ValueError(f"operators appear as both unary and binary: {shared}")


def test_dataset_configuration(dataset, options, verbosity: int = 1) -> None:
    """Dataset sanity + the reference's >10k-row batching hint
    (/root/reference/src/Configure.jl:86-112)."""
    if dataset.n == 0:
        raise ValueError("dataset has zero rows")
    if dataset.n > 10_000 and not options.batching and verbosity > 0:
        warnings.warn(
            f"dataset has {dataset.n} rows; consider batching=True for faster "
            "evolution (full-data rescoring still happens at iteration ends)"
        )
    if dataset.weights is not None and np.any(dataset.weights < 0):
        raise ValueError("weights must be non-negative")
    if not np.all(np.isfinite(dataset.X)):
        raise ValueError("X contains non-finite values")
    if dataset.y is not None and not np.all(np.isfinite(dataset.y)):
        raise ValueError("y contains non-finite values")


def test_mini_pipeline(options) -> None:
    """Miniature end-to-end search (the reference's per-worker
    test_entire_pipeline, /root/reference/src/Configure.jl:254-307): 2
    features, tiny populations, one iteration. Raises if the full stack cannot
    run with these options. Opt-in via runtests='full' (compile cost)."""
    import dataclasses

    from .search import equation_search

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2, 32)).astype(np.float32)
    y = (X[0] + np.cos(X[1])).astype(np.float32) if options.operators.n_unary else (
        X[0] * 2
    ).astype(np.float32)
    mini = dataclasses.replace(
        options,
        populations=2,
        population_size=8,
        ncycles_per_iteration=5,
        maxsize=min(10, options.maxsize),
        save_to_file=False,
        use_recorder=False,
        runtests=False,
        timeout_in_seconds=None,
        max_evals=None,
        early_stop_condition=None,
    )
    res = equation_search(X, y, options=mini, niterations=1, verbosity=0)
    if not res.pareto_frontier:
        raise RuntimeError("preflight mini pipeline produced an empty hall of fame")
