"""TPU-native symbolic regression framework.

A from-scratch JAX/XLA re-design with the capabilities of
SymbolicRegression.jl (reference mounted at /root/reference; see SURVEY.md):
genetic-programming search over expression trees with tournament-based
regularized evolution, simulated annealing, adaptive complexity-frequency
parsimony, batched constant optimization, island populations with migration,
and a complexity-indexed hall of fame. All scoring/optimization math runs as
batched XLA programs on TPU; the evolutionary control loop stays on the host.
"""

from .dataset import Dataset
from .options import MutationWeights, Options
from .regressor import MultitargetSRRegressor, SRRegressor
from .search import SearchResult, equation_search
from .tree import Node, binary, constant, feature, unary
from .models.hall_of_fame import HallOfFame
from .models.population import Population
from .models.pop_member import PopMember
from .ops import (
    OperatorSet,
    eval_trees,
    eval_trees_with_ok,
    flatten_trees,
    resolve_operators,
)
# loss zoo re-exports (the reference re-exports the LossFunctions.jl names,
# /root/reference/src/SymbolicRegression.jl:101-127) — both the concrete
# losses and the parameterized factories (LPDistLoss(p), HuberLoss(d), ...)
# are importable from the package root and accepted by
# Options(elementwise_loss=...), by object or by string ("LPDistLoss(3)").
from .ops.losses import (
    DWDMarginLoss,
    ExpLoss,
    HuberLoss,
    L1DistLoss,
    L1EpsilonInsLoss,
    L1HingeLoss,
    L2DistLoss,
    L2EpsilonInsLoss,
    L2HingeLoss,
    L2MarginLoss,
    LogCoshLoss,
    LogisticLoss,
    LogitDistLoss,
    LogitMarginLoss,
    LPDistLoss,
    ModifiedHuberLoss,
    PerceptronLoss,
    PeriodicLoss,
    QuantileLoss,
    SigmoidLoss,
    SmoothedL1HingeLoss,
    ZeroOneLoss,
    loss_zoo,
    make_loss,
)
# streaming/online runtime (round 14): live row swaps over a resident fleet
# lane, drift-aware frontiers, and fleet-batched multi-target search (the
# engine-level counterpart of the per-output solo loop in equation_search)
from .stream import (
    DriftConfig,
    DriftDetector,
    MultitargetSearch,
    StreamSession,
    multitarget_search,
)
from .analysis.ir_verify import FlatIRError, verify_flat_trees
from .parallel.distributed import PeerLossError
from .utils.checkpoint import (
    CheckpointError,
    SearchCheckpoint,
    SearchCheckpointer,
    latest_checkpoint,
    load_checkpoint,
    load_saved_state,
)

__version__ = "0.1.0"

__all__ = [
    "Dataset",
    "MutationWeights",
    "MultitargetSRRegressor",
    "Options",
    "SRRegressor",
    "SearchResult",
    "equation_search",
    "Node",
    "binary",
    "constant",
    "feature",
    "unary",
    "HallOfFame",
    "Population",
    "PopMember",
    "OperatorSet",
    "eval_trees",
    "eval_trees_with_ok",
    "flatten_trees",
    "resolve_operators",
    "load_saved_state",
    "CheckpointError",
    "FlatIRError",
    "SearchCheckpoint",
    "SearchCheckpointer",
    "latest_checkpoint",
    "load_checkpoint",
    "verify_flat_trees",
    "PeerLossError",
    "DWDMarginLoss",
    "ExpLoss",
    "HuberLoss",
    "L1DistLoss",
    "L1EpsilonInsLoss",
    "L1HingeLoss",
    "L2DistLoss",
    "L2EpsilonInsLoss",
    "L2HingeLoss",
    "L2MarginLoss",
    "LogCoshLoss",
    "LogitDistLoss",
    "LogitMarginLoss",
    "LPDistLoss",
    "ModifiedHuberLoss",
    "PerceptronLoss",
    "PeriodicLoss",
    "QuantileLoss",
    "SigmoidLoss",
    "SmoothedL1HingeLoss",
    "ZeroOneLoss",
    "LogisticLoss",
    "loss_zoo",
    "make_loss",
    "DriftConfig",
    "DriftDetector",
    "MultitargetSearch",
    "StreamSession",
    "multitarget_search",
    "__version__",
]
