"""equation_search: the top-level search driver (L4).

Reference: /root/reference/src/SymbolicRegression.jl:360-1129. Keeps the
6-phase driver shape (validate -> create -> initialize -> warmup -> main loop
-> teardown) but replaces the async per-island task scheduler with the
TPU-native **lockstep island scheduler**: all islands of an output advance
together so that every cycle's candidate scoring, and every iteration's
constant optimization, is one large batched XLA program. (An async mode in the
reference's style remains available through `parallel/islands.py` for
multi-host runs.)

Budget semantics match the reference: ``niterations`` full iterations per
output, each = ``ncycles_per_iteration`` evolve passes per island
(/root/reference/src/SymbolicRegression.jl:575).
"""

from __future__ import annotations

import dataclasses
import time
import typing
from typing import Any

import numpy as np

from .dataset import Dataset
from .models.adaptive_parsimony import RunningSearchStatistics
from .models.hall_of_fame import HallOfFame
from .models.migration import migrate
from .models.pop_member import PopMember
from .models.population import Population
from .models.scorer import BatchScorer
from .models.single_iteration import (
    optimize_and_simplify_populations,
    s_r_cycle_lockstep,
)
from .options import Options
from .utils.export_csv import save_hall_of_fame
from .complexity import compute_complexity

__all__ = ["equation_search", "SearchResult", "IterationReport"]


class IterationReport(typing.NamedTuple):
    """What ``Options.iteration_callback`` sees after each completed
    iteration — enough for the serving layer to stream the frontier, enforce
    deadlines, and decide preemption, without exposing scheduler internals.
    ``hall_of_fame`` is the LIVE object: callbacks must copy before mutating
    or crossing a thread boundary."""

    iteration: int  # iterations COMPLETED (1-based)
    niterations: int  # this run's total budget
    hall_of_fame: HallOfFame
    num_evals: float
    elapsed: float  # seconds since the scheduler's main loop started


@dataclasses.dataclass
class SearchResult:
    """Per-output search output: hall of fame + final island populations
    (the reference's return_state tuple, /root/reference/src/SymbolicRegression.jl:1079-1086)."""

    hall_of_fame: HallOfFame
    populations: list[Population]
    dataset: Dataset
    options: Options
    num_evals: float

    @property
    def pareto_frontier(self):
        return self.hall_of_fame.pareto_frontier()

    def report(self):
        return self.hall_of_fame.format(self.options, self.dataset.variable_names)

    def best(self) -> PopMember:
        """Best expression by the reference's selection rule: highest score
        among frontier members with loss <= 1.5x min loss
        (/root/reference/src/MLJInterface.jl:399-408)."""
        rows = self.report()
        if not rows:
            raise ValueError("empty hall of fame")
        min_loss = min(r["loss"] for r in rows)
        eligible = [r for r in rows if r["loss"] <= 1.5 * min_loss]
        return max(eligible, key=lambda r: r["score"])["member"]


def get_cur_maxsize(iteration: int, niterations: int, options: Options) -> int:
    """Warmup schedule 3 -> maxsize over `warmup_maxsize_by` fraction of the
    budget (reference: get_cur_maxsize, /root/reference/src/SearchUtils.jl:458-470)."""
    if options.warmup_maxsize_by <= 0:
        return options.maxsize
    fraction = iteration / max(niterations, 1)
    in_warmup = fraction / options.warmup_maxsize_by
    cur = 3 + int(in_warmup * (options.maxsize - 3))
    return min(cur, options.maxsize)


def _init_population(
    scorer: BatchScorer, options: Options, nfeatures: int, rng: np.random.Generator
) -> Population:
    trees = Population.random_trees(options.population_size, options, nfeatures, rng)
    comps = [compute_complexity(t, options) for t in trees]
    scores, losses = scorer.score_trees(trees, comps)
    members = []
    for t, s, l, c in zip(trees, scores, losses, comps):
        m = PopMember(t, s, l, complexity=c)
        members.append(m)
    return Population(members)


def _rescore_population(
    pop: Population, scorer: BatchScorer, options: Options
) -> Population:
    trees = [m.tree for m in pop.members]
    comps = [m.get_complexity(options) for m in pop.members]
    scores, losses = scorer.score_trees(trees, comps)
    for m, s, l in zip(pop.members, scores, losses):
        m.score, m.loss = float(s), float(l)
    return pop


def _poison_populations(pops: list[Population], frac: float) -> None:
    """nan_flood fault: overwrite the leading ``frac`` of every population's
    losses/scores with NaN — the storm the quarantine must absorb."""
    for pop in pops:
        k = max(1, int(round(frac * pop.n)))
        for m in pop.members[:k]:
            m.loss = float("nan")
            m.score = float("nan")


def _quarantine_nonfinite(
    pops: list[Population], hof: HallOfFame, options: Options
) -> int:
    """Non-finite quarantine: a population whose loss vector went
    majority-NaN/Inf in one iteration (optimizer excursion, poisoned data
    batch) would wedge the tournament — every comparison against inf/NaN
    keeps the poisoned members alive forever. Reset the non-finite members
    of such populations from the hall-of-fame Pareto frontier (fresh
    PopMember copies: new ref/birth, finite losses) and return the number
    reset. Populations with only a minority of non-finite members are left
    alone — inf is the routine marker for invalid candidates and ordinary
    selection handles it. The hall of fame itself never admits non-finite
    losses (HallOfFame.update), so the frontier is always a safe donor."""
    frontier = hof.pareto_frontier()
    if not frontier:
        return 0
    n_reset = 0
    for pop in pops:
        bad = [
            k for k, m in enumerate(pop.members) if not np.isfinite(m.loss)
        ]
        if 2 * len(bad) <= pop.n:
            continue
        for j, k in enumerate(bad):
            src = frontier[j % len(frontier)]
            pop.members[k] = PopMember(
                src.tree.copy(),
                src.score,
                src.loss,
                complexity=src.get_complexity(options),
                parent=src.ref,
            )
            n_reset += 1
    return n_reset


def _search_one_output(
    dataset: Dataset,
    options: Options,
    niterations: int,
    rng: np.random.Generator,
    saved_state: SearchResult | None = None,
    verbosity: int = 1,
    output_file: str | None = None,
    stdin_reader=None,
    recorder=None,
    out_j: int = 1,
    resume=None,
    checkpoint_base: str | None = None,
) -> SearchResult:
    from .utils import faults
    from .utils.checkpoint import (
        SearchCheckpoint,
        SearchCheckpointer,
        options_fingerprint,
    )
    from .models.pop_member import counter_state, restore_counter_state

    scorer = BatchScorer(dataset, options)
    nfeatures = dataset.n_features
    injector = (
        faults.install(options.fault_spec)
        if options.fault_spec
        else faults.active()
    )
    ckptr = (
        SearchCheckpointer.from_options(options, checkpoint_base)
        if checkpoint_base
        else None
    )
    from .utils.recorder import Recorder

    # a multi-output equation_search owns ONE shared recorder (dumped once,
    # after every output finishes — concurrent per-output dumps to the same
    # recorder_file would race); standalone callers get a private one
    own_recorder = recorder is None
    if own_recorder:
        recorder = Recorder(options)

    # -- initialize (warm start re-scores saved members: reference
    #    _initialize_search!, /root/reference/src/SymbolicRegression.jl:722-795)
    hof = HallOfFame(options.maxsize)
    start_iter = 0
    if resume is not None:
        # bit-exact continuation (SearchCheckpoint, exact=True): populations,
        # hall of fame, RNG stream, and the member id counters are restored
        # VERBATIM — no rescoring, no refill — so iteration start_iter
        # proceeds exactly as the uninterrupted run's would have
        pops = list(resume.populations)
        hof = resume.hall_of_fame
        scorer.num_evals = float(resume.num_evals)
        if resume.rng_state is not None:
            rng.bit_generator.state = resume.rng_state
        if resume.counters is not None:
            restore_counter_state(resume.counters)
        start_iter = int(resume.iteration)
    elif saved_state is not None:
        # best-effort continuation: the eval budget spans the whole lineage
        scorer.num_evals = float(getattr(saved_state, "num_evals", 0.0) or 0.0)
        pops = []
        for pop in saved_state.populations:
            pop = pop.copy()
            if pop.n != options.population_size:
                pops.append(_init_population(scorer, options, nfeatures, rng))
            else:
                pops.append(_rescore_population(pop, scorer, options))
        while len(pops) < options.populations:
            pops.append(_init_population(scorer, options, nfeatures, rng))
        pops = pops[: options.populations]
        saved_members = [m.copy() for m in saved_state.hall_of_fame.members if m is not None]
        if saved_members:
            losses = scorer.loss_many([m.tree for m in saved_members])
            comps = [m.get_complexity(options) for m in saved_members]
            scores = scorer.score_of(losses, np.asarray(comps))
            for m, l, s in zip(saved_members, losses, scores):
                m.loss, m.score = float(l), float(s)
                hof.update(m, options)
    else:
        pops = [
            _init_population(scorer, options, nfeatures, rng)
            for _ in range(options.populations)
        ]

    stats = RunningSearchStatistics(options.maxsize)
    if resume is not None and resume.stats_frequencies is not None:
        stats.frequencies[:] = np.asarray(resume.stats_frequencies)
        stats.normalize()
    stats_list = [stats] * len(pops)  # shared: lockstep updates at barriers only
    early_stop = options.early_stop_fn()
    if options.jit_warmup:
        from .models.warmup import warmup_host_programs

        warmup_host_programs(scorer, options)
    from .utils.stdin_reader import StdinReader

    # an injected reader is SHARED by concurrent per-output searches ('q'
    # quits the whole fit) and is closed by its owner, not here
    own_stdin = stdin_reader is None
    if own_stdin:
        stdin_reader = StdinReader()
    start_time = time.time()
    stop_reason = None
    from .utils.progress import ProgressReporter

    reporter = ProgressReporter(
        niterations, options, use_bar=bool(options.progress), verbosity=verbosity
    )

    for iteration in range(start_iter, niterations):
        # simulated preemption (peer_death fault): fires BEFORE the
        # iteration's work, so the last completed checkpoint is the resume
        # point — exactly the window a real kill would leave
        injector.maybe_die("peer_death")
        curmaxsize = get_cur_maxsize(iteration, niterations, options)

        best_seen = s_r_cycle_lockstep(
            pops,
            scorer,
            options.ncycles_per_iteration,
            curmaxsize,
            stats_list,
            options,
            nfeatures,
            rng,
            recorder=recorder,
        )
        optimize_and_simplify_populations(pops, scorer, options, rng, recorder)
        hit = injector.fire("nan_flood")
        if hit is not None:
            _poison_populations(pops, float(hit.get("frac", 0.75)))
        if recorder.enabled:
            for i, pop in enumerate(pops):
                recorder.record_population(out_j, i + 1, iteration, pop, options)

        # merge halls of fame + frequency stats (head-side merge in the
        # reference main loop, /root/reference/src/SymbolicRegression.jl:916-926)
        for bs in best_seen:
            hof.merge(bs, options)
        for pop in pops:
            hof.update_many(pop.members, options)
            for m in pop.members:
                stats.update(m.get_complexity(options))
        stats.move_window()
        stats.normalize()

        n_quarantined = _quarantine_nonfinite(pops, hof, options)
        if n_quarantined and verbosity > 0:
            print(
                f"[quarantine] iteration {iteration + 1}: reset "
                f"{n_quarantined} non-finite members from the hall of fame"
            )

        # migration (reference: /root/reference/src/SymbolicRegression.jl:933-943)
        if options.migration:
            all_best = [
                m
                for pop in pops
                for m in pop.best_sub_pop(options.topn).members
            ]
            for pop in pops:
                migrate(all_best, pop, options, options.fraction_replaced, rng)
        if options.hof_migration:
            frontier = hof.pareto_frontier()
            for pop in pops:
                migrate(frontier, pop, options, options.fraction_replaced_hof, rng)

        if output_file and options.save_to_file:
            save_hall_of_fame(
                output_file, hof, options, dataset.variable_names,
                num_evals=scorer.num_evals,
            )

        if ckptr is not None and ckptr.due(iteration + 1):
            # end-of-iteration boundary: everything iteration+1 will consume
            # (RNG stream, counters, stats, populations, hof) is captured, so
            # the resumed run replays the remaining iterations bit-exactly
            ckptr.save(SearchCheckpoint(
                iteration=iteration + 1,
                niterations=niterations,
                scheduler="lockstep",
                exact=True,
                populations=pops,
                hall_of_fame=hof,
                num_evals=float(scorer.num_evals),
                rng_state=rng.bit_generator.state,
                stats_frequencies=stats.frequencies.copy(),
                counters=counter_state(),
                options_fingerprint=options_fingerprint(options),
                wall_time=time.time() - start_time,
                out_j=out_j,
            ))

        reporter.update(
            hof,
            scorer.num_evals,
            dataset.variable_names,
            force=iteration == niterations - 1,
            y_variable_name=dataset.y_variable_name,
        )

        # stop conditions (reference: /root/reference/src/SearchUtils.jl:190-212)
        if options.iteration_callback is not None and options.iteration_callback(
            IterationReport(
                iteration=iteration + 1,
                niterations=niterations,
                hall_of_fame=hof,
                num_evals=scorer.num_evals,
                elapsed=time.time() - start_time,
            )
        ):
            stop_reason = "callback"
            break
        if early_stop is not None and any(
            early_stop(m.loss, m.get_complexity(options))
            for m in hof.pareto_frontier()
        ):
            stop_reason = "early_stop"
            break
        if (
            options.timeout_in_seconds is not None
            and time.time() - start_time > options.timeout_in_seconds
        ):
            stop_reason = "timeout"
            break
        if options.max_evals is not None and scorer.num_evals >= options.max_evals:
            stop_reason = "max_evals"
            break
        if stdin_reader.check_for_user_quit():
            stop_reason = "user_quit"
            break

    iteration_seconds = time.time() - start_time
    if own_stdin:
        stdin_reader.close()
    if own_recorder:
        recorder.dump()
    if output_file and options.save_to_file:
        # final write: the saved file must match the returned frontier
        save_hall_of_fame(
            output_file, hof, options, dataset.variable_names,
            num_evals=scorer.num_evals,
        )
    result = SearchResult(
        hall_of_fame=hof,
        populations=pops,
        dataset=dataset,
        options=options,
        num_evals=scorer.num_evals,
    )
    result.iteration_seconds = iteration_seconds
    result.stop_reason = stop_reason
    return result


#: reference parallelism names -> scheduler (``parallelism`` resolution,
#: /root/reference/src/SymbolicRegression.jl:465-488). ``:serial`` is the
#: deterministic lockstep driver; ``:multithreading`` maps to the async
#: thread-pool island scheduler; ``:multiprocessing`` (multi-host SPMD via
#: jax.distributed) runs the lockstep driver with per-process island slicing.
_PARALLELISM_TO_SCHEDULER = {
    "serial": "lockstep",
    "multithreading": "async",
    "multiprocessing": "lockstep",
    "lockstep": "lockstep",
    "async": "async",
    "device": "device",
}


def equation_search(
    X,
    y,
    *,
    weights=None,
    options: Options | None = None,
    niterations: int = 10,
    variable_names: list[str] | None = None,
    y_variable_names=None,
    saved_state=None,
    resume_from: str | None = None,
    verbosity: int | None = None,
    parallelism: str | None = None,
    X_units=None,
    y_units=None,
) -> Any:
    """Top-level API, mirroring the reference's
    ``equation_search(X, y; kws...)`` (/root/reference/src/SymbolicRegression.jl:360-428).

    X: (n_features, n). y: (n,) or (n_outputs, n) — multi-output runs one
    independent search per output row (reference: construct_datasets,
    /root/reference/src/SearchUtils.jl:472-511). Returns SearchResult, or a
    list of SearchResult for multi-output — state (populations + hall of
    fame) is always included, so there is no ``return_state`` flag.

    ``parallelism`` accepts the reference mode names (``"serial"``,
    ``"multithreading"``, ``"multiprocessing"``) or a scheduler name and
    overrides ``options.scheduler``; ``None`` keeps the options value.
    ``y_variable_names`` names the output variable(s) for rendering (str, or
    list with one entry per output row).

    ``resume_from`` restores a full-state checkpoint written by a prior run
    with ``Options.checkpoint_every`` (a snapshot path or the checkpoint
    base, newest snapshot wins; multi-output runs append ``.out{j}`` like
    ``output_file``). On the serial (lockstep) scheduler, resuming a
    matching-options run continues BIT-EXACTLY — the final hall of fame is
    identical to the uninterrupted run's. Device/async schedulers (and any
    cross-scheduler resume) warm-start instead: populations and hall of fame
    are rescored and the remaining ``niterations - iteration`` iterations
    run. Mutually exclusive with ``saved_state``.
    """
    options = options or Options()
    # peer-death state is PER SEARCH: without this, a second equation_search
    # in the same process would silently exclude peers that died in a
    # previous search's exchange (the r08 _DEAD_PEERS module-global leak)
    from .parallel import distributed as _dist

    _dist.reset_peer_state()
    if parallelism is not None:
        try:
            scheduler = _PARALLELISM_TO_SCHEDULER[parallelism]
        except KeyError:
            raise ValueError(
                f"unknown parallelism {parallelism!r}; expected one of "
                f"{sorted(_PARALLELISM_TO_SCHEDULER)}"
            ) from None
        if scheduler != options.scheduler:
            options = dataclasses.replace(options, scheduler=scheduler)
    X = np.asarray(X)
    y = np.asarray(y)
    multi_output = y.ndim == 2
    ys = y if multi_output else y[None, :]
    nout = ys.shape[0]
    if weights is not None:
        weights = np.asarray(weights)
        if weights.ndim == 2:
            ws = weights
        else:
            # 1-D weights apply to every output row (reference reshapes
            # weights alongside y, /root/reference/src/SymbolicRegression.jl:387-398).
            ws = np.broadcast_to(weights[None, :], (nout, weights.shape[-1]))
        if ws.shape != ys.shape:
            raise ValueError(
                f"weights shape {weights.shape} incompatible with y shape {y.shape}"
            )
    else:
        ws = [None] * nout

    verbosity = 1 if verbosity is None else verbosity
    rng = np.random.default_rng(options.seed)

    # preflight (reference: _validate_options, /root/reference/src/SymbolicRegression.jl:604-633)
    if options.runtests:
        from .configure import test_mini_pipeline, test_option_configuration

        test_option_configuration(options)
        if options.runtests == "full":
            test_mini_pipeline(options)

    saved = saved_state
    if saved is not None and not isinstance(saved, (list, tuple)):
        saved = [saved]

    resumes = None
    if resume_from is not None:
        if saved is not None:
            raise ValueError(
                "resume_from and saved_state are mutually exclusive: a "
                "checkpoint already carries the populations and hall of fame"
            )
        import warnings

        from .utils.checkpoint import load_checkpoint
        from .utils.checkpoint import options_fingerprint as _ofp

        resumes = []
        for j in range(nout):
            base_j = resume_from if nout == 1 else f"{resume_from}.out{j + 1}"
            try:
                ck = load_checkpoint(base_j)
            except FileNotFoundError:
                # multi-host device runs snapshot per process (.p{pid})
                import jax

                if jax.process_count() <= 1:
                    raise
                ck = load_checkpoint(f"{base_j}.p{jax.process_index()}")
            if ck.options_fingerprint and tuple(ck.options_fingerprint) != _ofp(
                options
            ):
                warnings.warn(
                    "resume_from: checkpoint was written with different "
                    "search options (operators/sizes/seed); continuing as a "
                    "best-effort warm start — exact resume is not guaranteed",
                    stacklevel=2,
                )
                ck.exact = False  # demote: verbatim state may not even fit
            resumes.append(ck)

    if y_variable_names is None:
        y_names = [None] * nout
    elif isinstance(y_variable_names, str):
        y_names = [y_variable_names] * nout
    else:
        y_names = list(y_variable_names)
        if len(y_names) != nout:
            raise ValueError(
                f"y_variable_names has {len(y_names)} entries for {nout} outputs"
            )

    def _make_dataset(j):
        dataset = Dataset(
            X,
            ys[j],
            weights=ws[j] if weights is not None else None,
            variable_names=variable_names,
            y_variable_name=y_names[j],
            X_units=X_units,
            y_units=y_units[j] if isinstance(y_units, (list, tuple)) else y_units,
        )
        if options.runtests:
            from .configure import test_dataset_configuration

            test_dataset_configuration(dataset, options, verbosity)
        return dataset

    # the timestamped default base is computed ONCE per search: per-output
    # (and, under parallel_outputs, per-thread) regeneration could scatter a
    # multi-output fit's .out{j} files across different base names when the
    # wall clock ticks across a second boundary between calls
    _default_base = f"hall_of_fame_{time.strftime('%Y-%m-%d_%H%M%S')}.csv"

    def _output_file(j):
        if not options.save_to_file:
            return None
        base = options.output_file or _default_base
        return base if nout == 1 else f"{base}.out{j + 1}"

    def _ckpt_base(j):
        # mirrors _output_file's .out{j} convention; the schedulers gate on
        # Options.checkpoint_every / checkpoint_every_seconds being set
        base = options.checkpoint_file or "sr_checkpoint.pkl"
        return base if nout == 1 else f"{base}.out{j + 1}"

    # per-output RNG streams: multi-output fits spawn one child stream per
    # output for EVERY scheduler, so serial and concurrent execution of the
    # same fit are seed-for-seed identical (the concurrent path below cannot
    # share one sequential stream across threads)
    child_rngs = list(rng.spawn(nout)) if nout > 1 else [rng]

    # ONE recorder for the whole fit, dumped once after every output returns:
    # per-output recorders would all write options.recorder_file, and the
    # concurrent path below would race them (the reference likewise keeps one
    # record for the run, /root/reference/src/SearchUtils.jl:377-393)
    from .utils.recorder import Recorder

    shared_recorder = Recorder(options)

    def _run_one(j, dataset, reader=None, quiet=False):
        saved_j = saved[j] if saved is not None else None
        nit = niterations
        resume_kw = {}
        if resumes is not None:
            ck = resumes[j]
            if (
                options.scheduler == "lockstep"
                and ck.exact
                and ck.scheduler == "lockstep"
            ):
                # bit-exact continuation: the serial scheduler restores the
                # snapshot verbatim and runs iterations [ck.iteration,
                # niterations) on the restored RNG stream
                resume_kw["resume"] = ck
            else:
                # cross-scheduler / non-exact snapshot: rescored warm start
                # over the REMAINING budget
                saved_j = ck
                nit = max(0, niterations - int(ck.iteration))
        kw = dict(
            saved_state=saved_j,
            verbosity=0 if quiet else verbosity,
            output_file=_output_file(j),
            stdin_reader=reader,
            checkpoint_base=_ckpt_base(j),
        )
        if options.scheduler == "async":
            from .parallel.islands import async_search_one_output

            return async_search_one_output(
                dataset, options, nit, child_rngs[j],
                recorder=shared_recorder, out_j=j + 1, **kw
            )
        if options.scheduler == "device":
            from .models.device_search import device_search_one_output

            return device_search_one_output(
                dataset, options, nit, child_rngs[j],
                recorder=shared_recorder, out_j=j + 1, **kw
            )
        return _search_one_output(
            dataset, options, nit, child_rngs[j],
            recorder=shared_recorder, out_j=j + 1, **kw, **resume_kw
        )

    # --- concurrent multi-output (ALL schedulers): one search per host
    # thread; device programs / scorer dispatches and host-side work of
    # different outputs overlap. The reference interleaves (output,
    # population) work units in one scheduler for the same reason
    # (/root/reference/src/SymbolicRegression.jl:676-679,871-877).
    if nout > 1 and options.parallel_outputs is not False:
        import jax

        if jax.process_count() > 1:
            # multi-host collectives are per-output and lockstep across
            # processes — interleaving outputs would deadlock the exchange.
            # The auto default (None) falls back silently; an EXPLICIT
            # parallel_outputs=True warns (VERDICT r4 #5: the user asked
            # for concurrency and must hear why it is not happening).
            if options.parallel_outputs is True:
                import warnings

                warnings.warn(
                    "parallel_outputs=True: multi-host searches run their "
                    "outputs serially (the per-iteration cross-host "
                    "exchange is per-output)",
                    stacklevel=2,
                )
        else:
            from concurrent.futures import ThreadPoolExecutor

            from .utils.stdin_reader import StdinReader

            datasets = [_make_dataset(j) for j in range(nout)]
            reader = StdinReader()  # shared; its quit latch reaches all outputs

            try:
                with ThreadPoolExecutor(max_workers=min(nout, 8)) as pool:
                    # only output 0 narrates — interleaved progress from N
                    # threads is unreadable
                    results = list(
                        pool.map(
                            lambda j: _run_one(
                                j, datasets[j], reader=reader, quiet=j > 0
                            ),
                            range(nout),
                        )
                    )
            finally:
                reader.close()
            shared_recorder.dump()
            return results

    results = []
    for j in range(nout):
        results.append(_run_one(j, _make_dataset(j)))
        # 'q' quits the WHOLE search, not just the current output (reference:
        # one watch_stream for the run, /root/reference/src/SearchUtils.jl:140-188)
        if getattr(results[-1], "stop_reason", None) == "user_quit":
            break
    shared_recorder.dump()
    return results if multi_output else results[0]
