"""equation_search: the top-level search driver (L4).

Reference: /root/reference/src/SymbolicRegression.jl:360-1129. Keeps the
6-phase driver shape (validate -> create -> initialize -> warmup -> main loop
-> teardown) but replaces the async per-island task scheduler with the
TPU-native **lockstep island scheduler**: all islands of an output advance
together so that every cycle's candidate scoring, and every iteration's
constant optimization, is one large batched XLA program. (An async mode in the
reference's style remains available through `parallel/islands.py` for
multi-host runs.)

Budget semantics match the reference: ``niterations`` full iterations per
output, each = ``ncycles_per_iteration`` evolve passes per island
(/root/reference/src/SymbolicRegression.jl:575).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from .dataset import Dataset
from .models.adaptive_parsimony import RunningSearchStatistics
from .models.hall_of_fame import HallOfFame
from .models.migration import migrate
from .models.pop_member import PopMember
from .models.population import Population
from .models.scorer import BatchScorer
from .models.single_iteration import (
    optimize_and_simplify_populations,
    s_r_cycle_lockstep,
)
from .options import Options
from .utils.export_csv import save_hall_of_fame
from .complexity import compute_complexity

__all__ = ["equation_search", "SearchResult"]


@dataclasses.dataclass
class SearchResult:
    """Per-output search output: hall of fame + final island populations
    (the reference's return_state tuple, /root/reference/src/SymbolicRegression.jl:1079-1086)."""

    hall_of_fame: HallOfFame
    populations: list[Population]
    dataset: Dataset
    options: Options
    num_evals: float

    @property
    def pareto_frontier(self):
        return self.hall_of_fame.pareto_frontier()

    def report(self):
        return self.hall_of_fame.format(self.options, self.dataset.variable_names)

    def best(self) -> PopMember:
        """Best expression by the reference's selection rule: highest score
        among frontier members with loss <= 1.5x min loss
        (/root/reference/src/MLJInterface.jl:399-408)."""
        rows = self.report()
        if not rows:
            raise ValueError("empty hall of fame")
        min_loss = min(r["loss"] for r in rows)
        eligible = [r for r in rows if r["loss"] <= 1.5 * min_loss]
        return max(eligible, key=lambda r: r["score"])["member"]


def get_cur_maxsize(iteration: int, niterations: int, options: Options) -> int:
    """Warmup schedule 3 -> maxsize over `warmup_maxsize_by` fraction of the
    budget (reference: get_cur_maxsize, /root/reference/src/SearchUtils.jl:458-470)."""
    if options.warmup_maxsize_by <= 0:
        return options.maxsize
    fraction = iteration / max(niterations, 1)
    in_warmup = fraction / options.warmup_maxsize_by
    cur = 3 + int(in_warmup * (options.maxsize - 3))
    return min(cur, options.maxsize)


def _init_population(
    scorer: BatchScorer, options: Options, nfeatures: int, rng: np.random.Generator
) -> Population:
    trees = Population.random_trees(options.population_size, options, nfeatures, rng)
    comps = [compute_complexity(t, options) for t in trees]
    scores, losses = scorer.score_trees(trees, comps)
    members = []
    for t, s, l, c in zip(trees, scores, losses, comps):
        m = PopMember(t, s, l, complexity=c)
        members.append(m)
    return Population(members)


def _rescore_population(
    pop: Population, scorer: BatchScorer, options: Options
) -> Population:
    trees = [m.tree for m in pop.members]
    comps = [m.get_complexity(options) for m in pop.members]
    scores, losses = scorer.score_trees(trees, comps)
    for m, s, l in zip(pop.members, scores, losses):
        m.score, m.loss = float(s), float(l)
    return pop


def _search_one_output(
    dataset: Dataset,
    options: Options,
    niterations: int,
    rng: np.random.Generator,
    saved_state: SearchResult | None = None,
    verbosity: int = 1,
    output_file: str | None = None,
    stdin_reader=None,
    recorder=None,
    out_j: int = 1,
) -> SearchResult:
    scorer = BatchScorer(dataset, options)
    nfeatures = dataset.n_features
    from .utils.recorder import Recorder

    # a multi-output equation_search owns ONE shared recorder (dumped once,
    # after every output finishes — concurrent per-output dumps to the same
    # recorder_file would race); standalone callers get a private one
    own_recorder = recorder is None
    if own_recorder:
        recorder = Recorder(options)

    # -- initialize (warm start re-scores saved members: reference
    #    _initialize_search!, /root/reference/src/SymbolicRegression.jl:722-795)
    hof = HallOfFame(options.maxsize)
    if saved_state is not None:
        pops = []
        for pop in saved_state.populations:
            pop = pop.copy()
            if pop.n != options.population_size:
                pops.append(_init_population(scorer, options, nfeatures, rng))
            else:
                pops.append(_rescore_population(pop, scorer, options))
        while len(pops) < options.populations:
            pops.append(_init_population(scorer, options, nfeatures, rng))
        pops = pops[: options.populations]
        saved_members = [m.copy() for m in saved_state.hall_of_fame.members if m is not None]
        if saved_members:
            losses = scorer.loss_many([m.tree for m in saved_members])
            comps = [m.get_complexity(options) for m in saved_members]
            scores = scorer.score_of(losses, np.asarray(comps))
            for m, l, s in zip(saved_members, losses, scores):
                m.loss, m.score = float(l), float(s)
                hof.update(m, options)
    else:
        pops = [
            _init_population(scorer, options, nfeatures, rng)
            for _ in range(options.populations)
        ]

    stats = RunningSearchStatistics(options.maxsize)
    stats_list = [stats] * len(pops)  # shared: lockstep updates at barriers only
    early_stop = options.early_stop_fn()
    if options.jit_warmup:
        from .models.warmup import warmup_host_programs

        warmup_host_programs(scorer, options)
    from .utils.stdin_reader import StdinReader

    # an injected reader is SHARED by concurrent per-output searches ('q'
    # quits the whole fit) and is closed by its owner, not here
    own_stdin = stdin_reader is None
    if own_stdin:
        stdin_reader = StdinReader()
    start_time = time.time()
    stop_reason = None
    from .utils.progress import ProgressReporter

    reporter = ProgressReporter(
        niterations, options, use_bar=bool(options.progress), verbosity=verbosity
    )

    for iteration in range(niterations):
        curmaxsize = get_cur_maxsize(iteration, niterations, options)

        best_seen = s_r_cycle_lockstep(
            pops,
            scorer,
            options.ncycles_per_iteration,
            curmaxsize,
            stats_list,
            options,
            nfeatures,
            rng,
            recorder=recorder,
        )
        optimize_and_simplify_populations(pops, scorer, options, rng, recorder)
        if recorder.enabled:
            for i, pop in enumerate(pops):
                recorder.record_population(out_j, i + 1, iteration, pop, options)

        # merge halls of fame + frequency stats (head-side merge in the
        # reference main loop, /root/reference/src/SymbolicRegression.jl:916-926)
        for bs in best_seen:
            hof.merge(bs, options)
        for pop in pops:
            hof.update_many(pop.members, options)
            for m in pop.members:
                stats.update(m.get_complexity(options))
        stats.move_window()
        stats.normalize()

        # migration (reference: /root/reference/src/SymbolicRegression.jl:933-943)
        if options.migration:
            all_best = [
                m
                for pop in pops
                for m in pop.best_sub_pop(options.topn).members
            ]
            for pop in pops:
                migrate(all_best, pop, options, options.fraction_replaced, rng)
        if options.hof_migration:
            frontier = hof.pareto_frontier()
            for pop in pops:
                migrate(frontier, pop, options, options.fraction_replaced_hof, rng)

        if output_file and options.save_to_file:
            save_hall_of_fame(output_file, hof, options, dataset.variable_names)

        reporter.update(
            hof,
            scorer.num_evals,
            dataset.variable_names,
            force=iteration == niterations - 1,
            y_variable_name=dataset.y_variable_name,
        )

        # stop conditions (reference: /root/reference/src/SearchUtils.jl:190-212)
        if early_stop is not None and any(
            early_stop(m.loss, m.get_complexity(options))
            for m in hof.pareto_frontier()
        ):
            stop_reason = "early_stop"
            break
        if (
            options.timeout_in_seconds is not None
            and time.time() - start_time > options.timeout_in_seconds
        ):
            stop_reason = "timeout"
            break
        if options.max_evals is not None and scorer.num_evals >= options.max_evals:
            stop_reason = "max_evals"
            break
        if stdin_reader.check_for_user_quit():
            stop_reason = "user_quit"
            break

    iteration_seconds = time.time() - start_time
    if own_stdin:
        stdin_reader.close()
    if own_recorder:
        recorder.dump()
    if output_file and options.save_to_file:
        # final write: the saved file must match the returned frontier
        save_hall_of_fame(output_file, hof, options, dataset.variable_names)
    result = SearchResult(
        hall_of_fame=hof,
        populations=pops,
        dataset=dataset,
        options=options,
        num_evals=scorer.num_evals,
    )
    result.iteration_seconds = iteration_seconds
    result.stop_reason = stop_reason
    return result


#: reference parallelism names -> scheduler (``parallelism`` resolution,
#: /root/reference/src/SymbolicRegression.jl:465-488). ``:serial`` is the
#: deterministic lockstep driver; ``:multithreading`` maps to the async
#: thread-pool island scheduler; ``:multiprocessing`` (multi-host SPMD via
#: jax.distributed) runs the lockstep driver with per-process island slicing.
_PARALLELISM_TO_SCHEDULER = {
    "serial": "lockstep",
    "multithreading": "async",
    "multiprocessing": "lockstep",
    "lockstep": "lockstep",
    "async": "async",
    "device": "device",
}


def equation_search(
    X,
    y,
    *,
    weights=None,
    options: Options | None = None,
    niterations: int = 10,
    variable_names: list[str] | None = None,
    y_variable_names=None,
    saved_state=None,
    verbosity: int | None = None,
    parallelism: str | None = None,
    X_units=None,
    y_units=None,
) -> Any:
    """Top-level API, mirroring the reference's
    ``equation_search(X, y; kws...)`` (/root/reference/src/SymbolicRegression.jl:360-428).

    X: (n_features, n). y: (n,) or (n_outputs, n) — multi-output runs one
    independent search per output row (reference: construct_datasets,
    /root/reference/src/SearchUtils.jl:472-511). Returns SearchResult, or a
    list of SearchResult for multi-output — state (populations + hall of
    fame) is always included, so there is no ``return_state`` flag.

    ``parallelism`` accepts the reference mode names (``"serial"``,
    ``"multithreading"``, ``"multiprocessing"``) or a scheduler name and
    overrides ``options.scheduler``; ``None`` keeps the options value.
    ``y_variable_names`` names the output variable(s) for rendering (str, or
    list with one entry per output row).
    """
    options = options or Options()
    if parallelism is not None:
        try:
            scheduler = _PARALLELISM_TO_SCHEDULER[parallelism]
        except KeyError:
            raise ValueError(
                f"unknown parallelism {parallelism!r}; expected one of "
                f"{sorted(_PARALLELISM_TO_SCHEDULER)}"
            ) from None
        if scheduler != options.scheduler:
            options = dataclasses.replace(options, scheduler=scheduler)
    X = np.asarray(X)
    y = np.asarray(y)
    multi_output = y.ndim == 2
    ys = y if multi_output else y[None, :]
    nout = ys.shape[0]
    if weights is not None:
        weights = np.asarray(weights)
        if weights.ndim == 2:
            ws = weights
        else:
            # 1-D weights apply to every output row (reference reshapes
            # weights alongside y, /root/reference/src/SymbolicRegression.jl:387-398).
            ws = np.broadcast_to(weights[None, :], (nout, weights.shape[-1]))
        if ws.shape != ys.shape:
            raise ValueError(
                f"weights shape {weights.shape} incompatible with y shape {y.shape}"
            )
    else:
        ws = [None] * nout

    verbosity = 1 if verbosity is None else verbosity
    rng = np.random.default_rng(options.seed)

    # preflight (reference: _validate_options, /root/reference/src/SymbolicRegression.jl:604-633)
    if options.runtests:
        from .configure import test_mini_pipeline, test_option_configuration

        test_option_configuration(options)
        if options.runtests == "full":
            test_mini_pipeline(options)

    saved = saved_state
    if saved is not None and not isinstance(saved, (list, tuple)):
        saved = [saved]

    if y_variable_names is None:
        y_names = [None] * nout
    elif isinstance(y_variable_names, str):
        y_names = [y_variable_names] * nout
    else:
        y_names = list(y_variable_names)
        if len(y_names) != nout:
            raise ValueError(
                f"y_variable_names has {len(y_names)} entries for {nout} outputs"
            )

    def _make_dataset(j):
        dataset = Dataset(
            X,
            ys[j],
            weights=ws[j] if weights is not None else None,
            variable_names=variable_names,
            y_variable_name=y_names[j],
            X_units=X_units,
            y_units=y_units[j] if isinstance(y_units, (list, tuple)) else y_units,
        )
        if options.runtests:
            from .configure import test_dataset_configuration

            test_dataset_configuration(dataset, options, verbosity)
        return dataset

    # the timestamped default base is computed ONCE per search: per-output
    # (and, under parallel_outputs, per-thread) regeneration could scatter a
    # multi-output fit's .out{j} files across different base names when the
    # wall clock ticks across a second boundary between calls
    _default_base = f"hall_of_fame_{time.strftime('%Y-%m-%d_%H%M%S')}.csv"

    def _output_file(j):
        if not options.save_to_file:
            return None
        base = options.output_file or _default_base
        return base if nout == 1 else f"{base}.out{j + 1}"

    # per-output RNG streams: multi-output fits spawn one child stream per
    # output for EVERY scheduler, so serial and concurrent execution of the
    # same fit are seed-for-seed identical (the concurrent path below cannot
    # share one sequential stream across threads)
    child_rngs = list(rng.spawn(nout)) if nout > 1 else [rng]

    # ONE recorder for the whole fit, dumped once after every output returns:
    # per-output recorders would all write options.recorder_file, and the
    # concurrent path below would race them (the reference likewise keeps one
    # record for the run, /root/reference/src/SearchUtils.jl:377-393)
    from .utils.recorder import Recorder

    shared_recorder = Recorder(options)

    def _run_one(j, dataset, reader=None, quiet=False):
        kw = dict(
            saved_state=saved[j] if saved is not None else None,
            verbosity=0 if quiet else verbosity,
            output_file=_output_file(j),
            stdin_reader=reader,
        )
        if options.scheduler == "async":
            from .parallel.islands import async_search_one_output

            return async_search_one_output(
                dataset, options, niterations, child_rngs[j],
                recorder=shared_recorder, out_j=j + 1, **kw
            )
        if options.scheduler == "device":
            from .models.device_search import device_search_one_output

            return device_search_one_output(
                dataset, options, niterations, child_rngs[j],
                recorder=shared_recorder, out_j=j + 1, **kw
            )
        return _search_one_output(
            dataset, options, niterations, child_rngs[j],
            recorder=shared_recorder, out_j=j + 1, **kw
        )

    # --- concurrent multi-output (ALL schedulers): one search per host
    # thread; device programs / scorer dispatches and host-side work of
    # different outputs overlap. The reference interleaves (output,
    # population) work units in one scheduler for the same reason
    # (/root/reference/src/SymbolicRegression.jl:676-679,871-877).
    if nout > 1 and options.parallel_outputs is not False:
        import jax

        if jax.process_count() > 1:
            # multi-host collectives are per-output and lockstep across
            # processes — interleaving outputs would deadlock the exchange.
            # The auto default (None) falls back silently; an EXPLICIT
            # parallel_outputs=True warns (VERDICT r4 #5: the user asked
            # for concurrency and must hear why it is not happening).
            if options.parallel_outputs is True:
                import warnings

                warnings.warn(
                    "parallel_outputs=True: multi-host searches run their "
                    "outputs serially (the per-iteration cross-host "
                    "exchange is per-output)",
                    stacklevel=2,
                )
        else:
            from concurrent.futures import ThreadPoolExecutor

            from .utils.stdin_reader import StdinReader

            datasets = [_make_dataset(j) for j in range(nout)]
            reader = StdinReader()  # shared; its quit latch reaches all outputs

            try:
                with ThreadPoolExecutor(max_workers=min(nout, 8)) as pool:
                    # only output 0 narrates — interleaved progress from N
                    # threads is unreadable
                    results = list(
                        pool.map(
                            lambda j: _run_one(
                                j, datasets[j], reader=reader, quiet=j > 0
                            ),
                            range(nout),
                        )
                    )
            finally:
                reader.close()
            shared_recorder.dump()
            return results

    results = []
    for j in range(nout):
        results.append(_run_one(j, _make_dataset(j)))
        # 'q' quits the WHOLE search, not just the current output (reference:
        # one watch_stream for the run, /root/reference/src/SearchUtils.jl:140-188)
        if getattr(results[-1], "stop_reason", None) == "user_quit":
            break
    shared_recorder.dump()
    return results if multi_output else results[0]
