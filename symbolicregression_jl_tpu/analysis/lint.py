"""sr-lint: project-specific static analysis for the SR-JAX codebase.

An AST linter for the whole *classes* of bug this engine has already paid
for once: tracer-unsafe Python control flow, host math baked into compiled
programs, trace-time env reads, blocking host syncs inside the engine loop,
PRNG key reuse, donated-buffer reuse — and, above all, **incomplete
compiled-function cache keys** (the r06 regression: the ``k_copt`` AOT key
omitted ``loss_function_jit`` and silently served a stale const-opt
objective across searches).

Pure stdlib (ast + tokenize): ``scripts/sr_lint.py`` loads this module by
file path so the CI lint job runs without JAX installed.

Rules
-----
==========  ==================================================================
SRL001      Python ``if``/``while`` on a traced value inside jit/scan code
SRL002      ``np.`` / ``math.`` call on a traced value inside jit/scan code
SRL003      blocking host sync (``.item()``, ``np.asarray``,
            ``block_until_ready``) inside an engine-loop hot path
SRL004      ``os.environ`` / ``os.getenv`` read inside jit/scan code
            (trace-time constant baked into the compiled program)
SRL005      PRNG key reused after ``jax.random.split`` (without rebinding)
SRL006      donated buffer read after the donating call
SRL007      compile-cache key misses an ``Options`` field its cached body
            reads (the r06 ``k_copt`` class)
SRL008      one-shot Pallas host packing (``loss_trees_pallas`` /
            ``batched_loss_jit(use_pallas=True)``) inside an engine hot loop
            (hot loops must hold a ``make_pallas_loss_fn`` closure)
SRL009      direct mutation of a module-level program-cache dict outside the
            cache API (the pre-r12 ``_SCORE_FN_CACHE``/``_AOT_CACHE`` class:
            ad-hoc dicts fork eviction/locking policy from the unified
            ``serve.program_cache.ProgramCache``)
SRL010      host-side program-IR packing (``pack_flat`` /
            ``pack_flat_fused``) inside an engine hot loop — the per-cycle
            HBM round-trip the r17 kernel-resident evolve block removes;
            programs must stay device-resident across cycles (pack once
            outside the loop, or in-graph via ``ops.flat.pack_words``)
==========  ==================================================================

Suppressions: a trailing ``# srl: disable=SRL001[,SRL002] [-- reason]``
comment silences those rule ids on its line; a comment-only line applies to
the next line. ``sr-lint`` reports suppressed findings only with
``--show-suppressed``.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import tokenize

__all__ = ["RULES", "Finding", "lint_source", "lint_file", "lint_paths"]

RULES = {
    "SRL001": (
        "tracer-branch",
        "Python if/while on a traced value inside jitted/scanned code — "
        "tracers have no concrete truth value; use lax.cond/lax.select or "
        "hoist the branch to a static argument",
    ),
    "SRL002": (
        "host-math-in-jit",
        "np./math. call on a traced value inside jitted/scanned code — "
        "numpy forces a trace-time concretization (ConcretizationTypeError "
        "at best, a silently baked constant at worst); use jnp/lax",
    ),
    "SRL003": (
        "host-sync-in-hot-loop",
        "blocking host sync (.item(), np.asarray, block_until_ready) inside "
        "an engine-loop hot path — serializes the dispatch pipeline; move "
        "the readback off the critical path or batch it",
    ),
    "SRL004": (
        "env-read-in-jit",
        "os.environ/os.getenv read inside jitted/scanned code — the value "
        "is frozen at trace time and silently ignored afterwards; read it "
        "at build time and bake it into the compile-cache key",
    ),
    "SRL005": (
        "key-reuse-after-split",
        "PRNG key used again after jax.random.split — correlated randomness; "
        "rebind (`key, sub = jax.random.split(key)`) or use the split halves",
    ),
    "SRL006": (
        "donated-buffer-reuse",
        "buffer read after being donated to a jitted call — donated inputs "
        "are deleted by XLA; reading one returns garbage or raises",
    ),
    "SRL007": (
        "incomplete-cache-key",
        "compiled-function cache key omits an Options field the cached "
        "body reads — a second search with a different value for that field "
        "silently reuses the stale executable (the r06 k_copt incident)",
    ),
    "SRL008": (
        "pallas-pack-in-hot-loop",
        "host-side Pallas packing (loss_trees_pallas / "
        "batched_loss_jit(use_pallas=True)) inside an engine hot loop — "
        "these are one-shot conveniences that re-pack the batch on the host "
        "every call; hot loops MUST hold a make_pallas_loss_fn closure "
        "(ops/scoring.py contract, promoted to a rule in r10)",
    ),
    "SRL009": (
        "ad-hoc-program-cache",
        "module-level program-cache dict mutated directly — ad-hoc cache "
        "dicts have no lock, no bound, and no counters (the pre-r12 "
        "_SCORE_FN_CACHE/_AOT_CACHE class, including an unlocked cross-"
        "thread .get race); route compiled-program caching through "
        "serve.program_cache (global_program_cache().get/put)",
    ),
    "SRL010": (
        "host-ir-pack-in-hot-loop",
        "host-side program-IR packing (pack_flat / pack_flat_fused) inside "
        "an engine hot loop — every call round-trips candidate programs "
        "through host memory and HBM, the exact per-cycle cost the r17 "
        "kernel-resident evolve block exists to remove; pack once outside "
        "the loop or keep programs device-resident (ops.flat.pack_words "
        "in-graph)",
    ),
}

# -- project configuration ----------------------------------------------------

#: engine-driver functions whose loops are latency-critical (SRL003 scope).
#: Extend when a new scheduler loop lands.
HOT_PATH_FUNCTIONS = {
    "_search_one_output",
    "device_search_one_output",
    "async_search_one_output",
    "s_r_cycle_lockstep",
}

#: parameter names treated as the Options object for SRL007.
OPTIONS_PARAM_NAMES = {"options"}

#: attribute reads on a traced value that are static (shape metadata) and
#: therefore fine to branch on / feed to numpy.
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "device"}

#: jit-like wrappers: a function decorated with (a partial of) one of these,
#: or passed to one, traces its Python body.
JIT_WRAPPERS = {"jit", "pmap"}
#: tracing combinators whose function-valued arguments trace.
TRACING_CALLS = {
    "scan", "while_loop", "cond", "switch", "fori_loop", "map",
    "vmap", "grad", "value_and_grad", "jacfwd", "jacrev", "checkpoint",
    "remat", "custom_jvp", "custom_vjp", "shard_map", "shard_map_compat",
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str | None = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}{tag} {self.message}"


# -- suppression comments -----------------------------------------------------

def _parse_suppressions(source: str) -> dict[int, tuple[set[str], str | None]]:
    """line -> (rule ids disabled on that line, reason). A comment-only line
    also applies to the next line (long flagged lines put the pragma above)."""
    out: dict[int, tuple[set[str], str | None]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return out
    # lines that hold only a comment (and whitespace/NL)
    code_lines = {
        t.start[0]
        for t in tokens
        if t.type
        not in (
            tokenize.COMMENT,
            tokenize.NL,
            tokenize.NEWLINE,
            tokenize.INDENT,
            tokenize.DEDENT,
            tokenize.ENDMARKER,
        )
    }
    for t in tokens:
        if t.type != tokenize.COMMENT:
            continue
        text = t.string.lstrip("#").strip()
        if not text.startswith("srl:"):
            continue
        body = text[len("srl:"):].strip()
        if not body.startswith("disable="):
            continue
        body = body[len("disable="):]
        reason = None
        if "--" in body:
            body, reason = body.split("--", 1)
            reason = reason.strip()
        ids = {x.strip().upper() for x in body.split(",") if x.strip()}
        line = t.start[0]
        prev = out.get(line, (set(), None))
        out[line] = (prev[0] | ids, reason or prev[1])
        if line not in code_lines:  # standalone pragma: applies to next line
            nxt = out.get(line + 1, (set(), None))
            out[line + 1] = (nxt[0] | ids, reason or nxt[1])
    return out


# -- AST utilities ------------------------------------------------------------

def _attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._srl_parent = node  # noqa: SLF001 — private annotation


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for an Attribute/Name chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _tail(dotted: str | None) -> str | None:
    return dotted.rsplit(".", 1)[-1] if dotted else None


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this decorator/callee expression denote a jit-like wrapper?
    Matches ``jit``, ``jax.jit``, ``functools.partial(jax.jit, ...)``."""
    d = _dotted(node)
    if d is not None and _tail(d) in JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        callee = _dotted(node.func)
        if callee is not None and _tail(callee) == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        # jax.jit(f, ...) used directly as a decorator factory
        return _is_jit_expr(node.func)
    return False


def _jit_static_names(node: ast.AST) -> set[str]:
    """static_argnames declared on a jit decorator expression."""
    names: set[str] = set()
    if isinstance(node, ast.Call):
        for kw in node.keywords:
            if kw.arg == "static_argnames":
                for elt in ast.walk(kw.value):
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        names.add(elt.value)
        if node.args and isinstance(node.func, ast.Attribute | ast.Name):
            callee = _dotted(node.func)
            if callee is not None and _tail(callee) == "partial":
                names |= _jit_static_names(node.args[0])
    return names


def _param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _collect_traced_functions(tree: ast.Module):
    """Map FunctionDef -> set of static param names, for every function whose
    body runs under tracing: jit-decorated, passed to a tracing combinator,
    wrapped via ``jit(f)`` assignment, or *defined inside* a traced function
    (nested defs execute at trace time)."""
    by_name: dict[int, dict[str, ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_DEFS):
            scope = id(getattr(node, "_srl_parent", tree))
            by_name.setdefault(scope, {})[node.name] = node

    traced: dict[ast.FunctionDef, set[str]] = {}

    def _mark(fn, statics=frozenset()):
        if fn in traced:
            traced[fn] |= set(statics)
        else:
            traced[fn] = set(statics)

    def _resolve(name_node: ast.AST, scope_node: ast.AST):
        """A Name argument -> the FunctionDef it denotes, searched up the
        lexical scope chain."""
        if isinstance(name_node, ast.Lambda):
            return None  # lambdas handled via containment
        if not isinstance(name_node, ast.Name):
            return None
        cur = scope_node
        while cur is not None:
            fns = by_name.get(id(cur), {})
            if name_node.id in fns:
                return fns[name_node.id]
            cur = getattr(cur, "_srl_parent", None)
        return None

    for node in ast.walk(tree):
        if isinstance(node, _FUNC_DEFS):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    _mark(node, _jit_static_names(dec))
        elif isinstance(node, ast.Call):
            callee = _tail(_dotted(node.func))
            if callee in JIT_WRAPPERS or callee in TRACING_CALLS:
                scope = node
                while scope is not None and not isinstance(scope, _FUNC_DEFS):
                    scope = getattr(scope, "_srl_parent", None)
                statics = _jit_static_names(node) if callee in JIT_WRAPPERS else ()
                for arg in node.args:
                    fn = _resolve(arg, scope or tree)
                    if fn is not None:
                        _mark(fn, statics)

    # nested defs inside traced functions trace too (params are tracers)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(tree):
            if not isinstance(node, _FUNC_DEFS) or node in traced:
                continue
            cur = getattr(node, "_srl_parent", None)
            while cur is not None:
                if isinstance(cur, _FUNC_DEFS) and cur in traced:
                    _mark(node)
                    changed = True
                    break
                cur = getattr(cur, "_srl_parent", None)
    return traced


def _traced_param_refs(expr: ast.AST, traced_params: set[str]) -> list[ast.Name]:
    """Name loads of traced params inside ``expr`` that are NOT shielded by a
    static construct (``.shape``-style attrs, ``len()``, ``isinstance()``,
    ``is None`` comparisons)."""
    hits: list[ast.Name] = []
    for node in ast.walk(expr):
        if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
            continue
        if node.id not in traced_params:
            continue
        shielded = False
        cur, child = getattr(node, "_srl_parent", None), node
        while cur is not None and cur is not getattr(expr, "_srl_parent", None):
            if isinstance(cur, ast.Attribute) and cur.value is child and cur.attr in STATIC_ATTRS:
                shielded = True
                break
            if isinstance(cur, ast.Call):
                callee = _tail(_dotted(cur.func))
                if callee in {"len", "isinstance", "type", "id"} and child in cur.args:
                    shielded = True
                    break
            if isinstance(cur, ast.Compare):
                ops_are_identity = all(
                    isinstance(o, ast.Is | ast.IsNot) for o in cur.ops
                )
                if ops_are_identity:
                    shielded = True
                    break
            child, cur = cur, getattr(cur, "_srl_parent", None)
        if not shielded:
            hits.append(node)
    return hits


def _enclosing_function(node: ast.AST):
    cur = getattr(node, "_srl_parent", None)
    while cur is not None and not isinstance(cur, _FUNC_DEFS):
        cur = getattr(cur, "_srl_parent", None)
    return cur


def _inside(node: ast.AST, ancestor: ast.AST) -> bool:
    cur = node
    while cur is not None:
        if cur is ancestor:
            return True
        cur = getattr(cur, "_srl_parent", None)
    return False


def _is_literal(node: ast.AST) -> bool:
    try:
        ast.literal_eval(node)
        return True
    except (ValueError, TypeError, SyntaxError, MemoryError, RecursionError):
        return False


# -- rule implementations -----------------------------------------------------

def _check_traced_rules(tree, path, findings):
    """SRL001 (tracer branch), SRL002 (np/math on tracer), SRL004 (env read)
    — all scoped to traced-function bodies."""
    traced = _collect_traced_functions(tree)
    for fn, statics in traced.items():
        traced_params = set(_param_names(fn)) - statics
        # only walk THIS function's body, not nested defs twice (nested defs
        # are separate entries in `traced`)
        own_nodes = [
            n
            for n in ast.walk(fn)
            if _enclosing_function(n) is fn and n is not fn
        ]
        for node in own_nodes:
            if isinstance(node, ast.If | ast.While):
                refs = _traced_param_refs(node.test, traced_params)
                if refs:
                    findings.append(Finding(
                        "SRL001", path, node.lineno, node.col_offset,
                        f"`{'while' if isinstance(node, ast.While) else 'if'}` "
                        f"on traced value `{refs[0].id}` in traced function "
                        f"`{fn.name}` — use lax.cond/lax.select or make it "
                        "a static argument",
                    ))
            elif isinstance(node, ast.Call):
                root = _dotted(node.func)
                if root is not None and root.split(".", 1)[0] in {"np", "numpy", "math"}:
                    for arg in list(node.args) + [kw.value for kw in node.keywords]:
                        refs = _traced_param_refs(arg, traced_params)
                        if refs:
                            findings.append(Finding(
                                "SRL002", path, node.lineno, node.col_offset,
                                f"`{root}(...)` applied to traced value "
                                f"`{refs[0].id}` in traced function "
                                f"`{fn.name}` — use jnp",
                            ))
                            break
                callee = _tail(root)
                if callee in {"getenv"} and root.startswith("os"):
                    findings.append(Finding(
                        "SRL004", path, node.lineno, node.col_offset,
                        f"os.getenv read inside traced function `{fn.name}` — "
                        "frozen at trace time; read at build time and key the "
                        "cache on it",
                    ))
            elif isinstance(node, ast.Attribute):
                if _dotted(node) == "os.environ":
                    findings.append(Finding(
                        "SRL004", path, node.lineno, node.col_offset,
                        f"os.environ read inside traced function `{fn.name}` — "
                        "frozen at trace time; read at build time and key the "
                        "cache on it",
                    ))


def _check_hot_sync(tree, path, findings):
    """SRL003: blocking host syncs inside loops of engine-driver functions."""
    for fn in ast.walk(tree):
        if not isinstance(fn, _FUNC_DEFS) or fn.name not in HOT_PATH_FUNCTIONS:
            continue
        loops = [
            n
            for n in ast.walk(fn)
            if isinstance(n, ast.For | ast.While) and _enclosing_function(n) is fn
        ]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if not any(_inside(node, lp) for lp in loops):
                continue
            root = _dotted(node.func)
            name = _tail(root)
            sync = None
            if name in {"asarray", "array"} and root and root.split(".", 1)[0] in {"np", "numpy"}:
                # literal args are host data already — no device sync
                if node.args and not _is_literal(node.args[0]):
                    sync = f"{root}(...)"
            elif isinstance(node.func, ast.Attribute) and not node.args:
                if node.func.attr == "block_until_ready":
                    sync = ".block_until_ready()"
                elif node.func.attr == "item":
                    sync = ".item()"
            if sync:
                findings.append(Finding(
                    "SRL003", path, node.lineno, node.col_offset,
                    f"blocking host sync {sync} inside the `{fn.name}` "
                    "engine loop — stalls the dispatch pipeline",
                ))


#: one-shot host-packing entry points the SRL008 contract bans from hot loops
#: (ops/scoring.py: "one-shot only; hot loops MUST hold make_pallas_loss_fn")
PALLAS_ONESHOT_FUNCS = {"loss_trees_pallas", "loss_trees_pallas_batch"}


def _check_pallas_hot_packing(tree, path, findings):
    """SRL008: one-shot Pallas packing helpers called inside loops of
    engine-driver functions. ``loss_trees_pallas*`` is flagged outright;
    ``batched_loss_jit`` only when called with ``use_pallas=True`` (a literal
    — a Name flowing in is assumed build-time config, like the other rules'
    conservative literal policy)."""
    for fn in ast.walk(tree):
        if not isinstance(fn, _FUNC_DEFS) or fn.name not in HOT_PATH_FUNCTIONS:
            continue
        loops = [
            n
            for n in ast.walk(fn)
            if isinstance(n, ast.For | ast.While) and _enclosing_function(n) is fn
        ]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if not any(_inside(node, lp) for lp in loops):
                continue
            name = _tail(_dotted(node.func))
            bad = None
            if name in PALLAS_ONESHOT_FUNCS:
                bad = f"{name}(...)"
            elif name == "batched_loss_jit":
                for kw in node.keywords:
                    if (
                        kw.arg == "use_pallas"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                    ):
                        bad = "batched_loss_jit(use_pallas=True)"
            if bad:
                findings.append(Finding(
                    "SRL008", path, node.lineno, node.col_offset,
                    f"one-shot Pallas packing {bad} inside the `{fn.name}` "
                    "engine loop — re-packs the batch on the host every "
                    "call; build a make_pallas_loss_fn closure once outside "
                    "the loop",
                ))


#: host program-IR packers the SRL010 contract bans from hot loops (r17:
#: the evolve block keeps programs device-resident for a whole cycle block)
IR_PACK_FUNCS = {"pack_flat", "pack_flat_fused"}


def _check_ir_pack_hot_loop(tree, path, findings):
    """SRL010: host program-IR packing inside loops of engine-driver
    functions. ``pack_flat``/``pack_flat_fused`` pull the candidate batch to
    the host and re-upload it — per-cycle, that is the HBM round-trip the
    kernel-resident evolve block removes. Same loop/hot-function scoping as
    SRL008."""
    for fn in ast.walk(tree):
        if not isinstance(fn, _FUNC_DEFS) or fn.name not in HOT_PATH_FUNCTIONS:
            continue
        loops = [
            n
            for n in ast.walk(fn)
            if isinstance(n, ast.For | ast.While) and _enclosing_function(n) is fn
        ]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if not any(_inside(node, lp) for lp in loops):
                continue
            name = _tail(_dotted(node.func))
            if name in IR_PACK_FUNCS:
                findings.append(Finding(
                    "SRL010", path, node.lineno, node.col_offset,
                    f"host IR packing {name}(...) inside the `{fn.name}` "
                    "engine loop — round-trips candidate programs through "
                    "the host every cycle; pack once outside the loop or "
                    "keep programs device-resident (pack_words in-graph / "
                    "SR_ENGINE_BLOCK)",
                ))


def _split_key_arg(node: ast.Call) -> str | None:
    """`jax.random.split(key[, n])` -> 'key' when arg0 is a plain Name."""
    if _tail(_dotted(node.func)) != "split":
        return None
    d = _dotted(node.func)
    if d is None or "random" not in d.split("."):
        return None
    if node.args and isinstance(node.args[0], ast.Name):
        return node.args[0].id
    return None


def _check_key_reuse(tree, path, findings):
    """SRL005: linear per-function scan — after `ks = jax.random.split(key)`
    that does NOT rebind `key`, a later load of `key` is correlated reuse."""
    for fn in ast.walk(tree):
        if not isinstance(fn, _FUNC_DEFS):
            continue
        events: list[tuple[int, int, str, str, ast.AST]] = []  # (line, col, kind, name, node)
        split_args: set[int] = set()  # Name nodes that ARE a split's argument
        for node in ast.walk(fn):
            if _enclosing_function(node) is not fn and node is not fn:
                continue
            if isinstance(node, ast.Assign):
                key = None
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call) and _split_key_arg(sub):
                        key = _split_key_arg(sub)
                        for a in sub.args:
                            for n in ast.walk(a):
                                if isinstance(n, ast.Name):
                                    split_args.add(id(n))
                        break
                targets: set[str] = set()
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            targets.add(sub.id)
                if key and key not in targets:
                    events.append((node.lineno, node.col_offset, "split", key, node))
                # stores take effect AFTER the value expression evaluates:
                # anchor them at the statement's end position
                for name in targets:
                    events.append(
                        (node.end_lineno or node.lineno,
                         node.end_col_offset or node.col_offset,
                         "store", name, node)
                    )
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if id(node) not in split_args:
                    events.append((node.lineno, node.col_offset, "load", node.id, node))
        events.sort(key=lambda e: (e[0], e[1]))
        consumed: dict[str, int] = {}
        for line, col, kind, name, _node in events:
            if kind == "split":
                if name in consumed and line > consumed[name]:
                    findings.append(Finding(
                        "SRL005", path, line, col,
                        f"PRNG key `{name}` split again after jax.random.split "
                        f"on line {consumed[name]} — identical halves; rebind "
                        "the key between splits",
                    ))
                consumed[name] = line
            elif kind == "store":
                consumed.pop(name, None)
            elif kind == "load" and name in consumed and line > consumed[name]:
                findings.append(Finding(
                    "SRL005", path, line, col,
                    f"PRNG key `{name}` used after jax.random.split on line "
                    f"{consumed[name]} — rebind or use the split halves",
                ))
                consumed.pop(name)  # one finding per split


def _donating_assignments(fn: ast.FunctionDef):
    """name -> donated positional indices, from
    `f = jax.jit(g, donate_argnums=(0,))`-style assignments."""
    out: dict[str, set[int]] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        if not _is_jit_expr(call.func) and not (
            _tail(_dotted(call.func)) in JIT_WRAPPERS
        ):
            continue
        donated: set[int] = set()
        for kw in call.keywords:
            if kw.arg in {"donate_argnums", "donate_argnames"}:
                for elt in ast.walk(kw.value):
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                        donated.add(elt.value)
        if not donated:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = donated
    return out


def _check_donated_reuse(tree, path, findings):
    """SRL006: a Name passed at a donated position of a donating call must
    not be read afterwards (unless rebound)."""
    for fn in ast.walk(tree):
        if not isinstance(fn, _FUNC_DEFS):
            continue
        donors = _donating_assignments(fn)
        if not donors:
            continue
        events: list[tuple[int, int, str, str]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                positions = donors.get(node.func.id)
                if positions:
                    for i, arg in enumerate(node.args):
                        if i in positions and isinstance(arg, ast.Name):
                            events.append(
                                (node.lineno, node.col_offset, "donate", arg.id)
                            )
            elif isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    events.append((node.lineno, node.col_offset, "load", node.id))
                else:
                    # a Store name binds AFTER its statement's value expression
                    # evaluates: anchor at the enclosing statement's end
                    stmt = node
                    while stmt is not None and not isinstance(stmt, ast.stmt):
                        stmt = getattr(stmt, "_srl_parent", None)
                    anchor = stmt if stmt is not None else node
                    events.append(
                        (anchor.end_lineno or anchor.lineno,
                         anchor.end_col_offset or anchor.col_offset,
                         "store", node.id)
                    )
        events.sort(key=lambda e: (e[0], e[1]))
        dead: dict[str, int] = {}
        for line, col, kind, name in events:
            if kind == "donate":
                dead[name] = line
            elif kind == "store":
                dead.pop(name, None)
            elif kind == "load" and name in dead and line > dead[name]:
                findings.append(Finding(
                    "SRL006", path, line, col,
                    f"buffer `{name}` read after being donated on line "
                    f"{dead[name]} — donated inputs are deleted by XLA",
                ))
                dead.pop(name)


def _options_reads(fn: ast.FunctionDef) -> set[str]:
    """Attribute reads on parameters that carry the Options object."""
    params = set(_param_names(fn))
    opt_names = {
        p
        for p in params
        if p in OPTIONS_PARAM_NAMES
    }
    for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        ann = a.annotation
        if ann is not None and _tail(_dotted(ann)) == "Options":
            opt_names.add(a.arg)
    reads: set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in opt_names
            and isinstance(node.ctx, ast.Load)
        ):
            reads.add(node.attr)
    return reads


def _module_call_graph(tree: ast.Module):
    """module-level function name -> (direct option-field reads,
    module-local callee names)."""
    info: dict[str, tuple[set[str], set[str]]] = {}
    for node in tree.body:
        if isinstance(node, _FUNC_DEFS):
            callees = {
                _tail(_dotted(c.func))
                for c in ast.walk(node)
                if isinstance(c, ast.Call) and isinstance(c.func, ast.Name)
            }
            info[node.name] = (_options_reads(node), {c for c in callees if c})
    return info


def _transitive_options_reads(names, graph, _seen=None) -> set[str]:
    seen = _seen if _seen is not None else set()
    out: set[str] = set()
    for name in names:
        if name in seen or name not in graph:
            continue
        seen.add(name)
        reads, callees = graph[name]
        out |= reads
        out |= _transitive_options_reads(callees, graph, seen)
    return out


def _check_cache_keys(tree, path, findings):
    """SRL007: for each `key = (...)` tuple later used as `CACHE.get(key)`
    (or `*_cache_put(key, ...)` / `CACHE.setdefault(key, ...)`), every
    Options field read by the cache-miss branch — directly or through
    module-local calls — must appear in the key tuple."""
    graph = _module_call_graph(tree)
    for fn in ast.walk(tree):
        if not isinstance(fn, _FUNC_DEFS):
            continue
        # key-tuple assignments in this function
        key_tuples: dict[str, ast.Assign] = {}
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Tuple)
            ):
                key_tuples[node.targets[0].id] = node
        if not key_tuples:
            continue

        body = list(ast.walk(fn))
        for key_name, assign in key_tuples.items():
            # cache use: CACHE.get(key) assigned to a result name, or a
            # direct put/setdefault
            result_name = None
            used_as_key = False
            for node in body:
                if not isinstance(node, ast.Call):
                    continue
                callee = _tail(_dotted(node.func))
                takes_key = any(
                    isinstance(a, ast.Name) and a.id == key_name for a in node.args
                )
                if not takes_key:
                    continue
                if callee in {"get", "setdefault"} or (
                    callee is not None and callee.endswith("cache_put")
                ):
                    used_as_key = True
                    if callee == "get":
                        parent = getattr(node, "_srl_parent", None)
                        if (
                            isinstance(parent, ast.Assign)
                            and len(parent.targets) == 1
                            and isinstance(parent.targets[0], ast.Name)
                        ):
                            result_name = parent.targets[0].id
            if not used_as_key:
                continue

            # miss branch: `if <result> is None:` body (falls back to the
            # whole remainder of small builder functions when absent)
            miss_stmts: list[ast.stmt] = []
            if result_name is not None:
                for node in body:
                    if not isinstance(node, ast.If):
                        continue
                    t = node.test
                    if (
                        isinstance(t, ast.Compare)
                        and isinstance(t.left, ast.Name)
                        and t.left.id == result_name
                        and len(t.ops) == 1
                        and isinstance(t.ops[0], ast.Is)
                        and isinstance(t.comparators[0], ast.Constant)
                        and t.comparators[0].value is None
                    ):
                        miss_stmts.extend(node.body)
            if not miss_stmts:
                continue  # no statically-visible miss branch: nothing to diff

            in_key: set[str] = set()
            for sub in ast.walk(assign.value):
                if (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id in OPTIONS_PARAM_NAMES
                ):
                    in_key.add(sub.attr)

            direct: set[str] = set()
            callees: set[str] = set()
            for stmt in miss_stmts:
                for sub in ast.walk(stmt):
                    if (
                        isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id in OPTIONS_PARAM_NAMES
                        and isinstance(sub.ctx, ast.Load)
                    ):
                        direct.add(sub.attr)
                    elif isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                        callees.add(sub.func.id)
            body_reads = direct | _transitive_options_reads(callees, graph)
            missing = sorted(body_reads - in_key)
            if missing:
                findings.append(Finding(
                    "SRL007", path, assign.lineno, assign.col_offset,
                    f"cache key `{key_name}` omits Options field(s) its "
                    f"cached body reads: {', '.join(missing)} — a search "
                    "with a different value silently reuses the stale "
                    "compiled result",
                ))


#: dict methods that mutate in place (reads like .get/.keys are fine — the
#: rule bans forking cache POLICY, not observing the store)
_CACHE_DICT_MUTATORS = {"pop", "popitem", "setdefault", "update", "clear"}


def _check_adhoc_cache_mutation(tree, path, findings):
    """SRL009: module-level ALL-CAPS ``*CACHE*`` names bound to a dict
    literal (``= {}`` / ``= dict()`` / ``: dict = {}``) are ad-hoc program
    caches; any in-place mutation — subscript store, ``del``, or a mutating
    method call — bypasses the unified ProgramCache (lock, budgets,
    counters) and is flagged. Pure reads (membership tests, ``.get``,
    subscript loads) are allowed."""
    cache_names: set[str] = set()
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            target, value = node.target.id, node.value
        else:
            continue
        if "CACHE" not in target or target != target.upper():
            continue
        if isinstance(value, ast.Dict) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id == "dict"
        ):
            cache_names.add(target)
    if not cache_names:
        return
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id in cache_names
            and isinstance(node.ctx, (ast.Store, ast.Del))
        ):
            verb = "del on" if isinstance(node.ctx, ast.Del) else "store into"
            findings.append(Finding(
                "SRL009", path, node.lineno, node.col_offset,
                f"direct {verb} module-level cache dict "
                f"`{node.value.id}` — " + RULES["SRL009"][1].split(" — ")[1],
            ))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in cache_names
            and node.func.attr in _CACHE_DICT_MUTATORS
        ):
            findings.append(Finding(
                "SRL009", path, node.lineno, node.col_offset,
                f"`.{node.func.attr}()` on module-level cache dict "
                f"`{node.func.value.id}` — " + RULES["SRL009"][1].split(" — ")[1],
            ))


# -- driver -------------------------------------------------------------------

def lint_source(source: str, path: str = "<string>") -> list[Finding]:
    """Lint one source string. Returns ALL findings; suppressed ones carry
    ``suppressed=True`` (callers filter)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("SRL000", path, e.lineno or 0, e.offset or 0,
                        f"syntax error: {e.msg}")]
    _attach_parents(tree)
    findings: list[Finding] = []
    _check_traced_rules(tree, path, findings)
    _check_hot_sync(tree, path, findings)
    _check_pallas_hot_packing(tree, path, findings)
    _check_ir_pack_hot_loop(tree, path, findings)
    _check_key_reuse(tree, path, findings)
    _check_donated_reuse(tree, path, findings)
    _check_cache_keys(tree, path, findings)
    _check_adhoc_cache_mutation(tree, path, findings)

    suppressions = _parse_suppressions(source)
    for f in findings:
        sup = suppressions.get(f.line)
        if sup and (f.rule in sup[0] or "ALL" in sup[0]):
            f.suppressed = True
            f.reason = sup[1]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str) -> list[Finding]:
    with open(path, encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def lint_paths(paths) -> list[Finding]:
    """Lint files and/or directory trees (``*.py``, skipping ``__pycache__``
    and the lint fixture corpus, which is violations on purpose)."""
    findings: list[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames
                    if d != "__pycache__" and d != "lint_fixtures"
                ]
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        findings.extend(lint_file(os.path.join(dirpath, fname)))
        else:
            findings.extend(lint_file(p))
    return findings


def render_json(findings) -> str:
    return json.dumps([f.as_dict() for f in findings], indent=2)
