"""Static analysis & runtime debug checks.

Two legs: :mod:`.lint` (sr-lint, the AST-based JAX-footgun linter — pure
stdlib, also loadable standalone by ``scripts/sr_lint.py`` without JAX) and
:mod:`.ir_verify` (the FlatTrees invariant verifier behind the
``Options.debug_checks`` / ``SR_DEBUG_CHECKS=1`` gate).
"""

from .ir_verify import FlatIRError, debug_checks_enabled, verify_flat_trees

__all__ = ["FlatIRError", "debug_checks_enabled", "verify_flat_trees"]
