"""Flat-IR invariant verifier.

``verify_flat_trees`` enforces the documented :class:`~..ops.flat.FlatTrees`
invariants (ops/flat.py module docstring) as *named* checks, so a corrupted
snapshot or a bad host<->device decode fails with ``[postorder] tree 3 slot
5: ...`` instead of NaNs ten iterations later:

- **postorder**: children of slot ``i`` live at slots ``< i`` (and ``>= 0``);
- **root**: the root of tree ``p`` is at slot ``length[p] - 1`` (a live,
  non-PAD slot — implied by ``pad_kind``);
- **kind_range** / **op_range** / **feat_range**: kinds, operator indices,
  and feature indices are in range for the opset/dataset;
- **pad_kind** / **pad_zero**: slots ``>= length`` are ``KIND_PAD`` and
  exactly zero in every array (live slots are never PAD);
- **length_range**: ``0 <= length <= max_nodes`` (``1 <=`` with
  ``allow_empty=False``);
- **bucket**: the node-axis width is a member of the ``bucket_sizes()``
  ladder when the caller states the full width (length-bucketed dispatch).

Everything is vectorized numpy; a full population batch verifies in
microseconds. The verifier is **callable standalone** and wired — behind the
``Options.debug_checks`` / ``SR_DEBUG_CHECKS=1`` gate — into the host->device
flatten (models/scorer.py), the device->host decode boundaries
(models/device_search.py), and checkpoint load (utils/checkpoint.py, always
on: load is a cold path and a torn snapshot must never warm-start a search).
The gate is resolved ONCE per search into a plain bool; with it off the hot
paths make zero verifier calls (pinned by tests/test_ir_verify.py).
"""

from __future__ import annotations

import os

import numpy as np

from ..ops.flat import (
    KIND_BINARY,
    KIND_CONST,
    KIND_PAD,
    KIND_UNARY,
    KIND_VAR,
    PACK_KIND_BITS,
    PACK_KIND_MASK,
    bucket_sizes,
)

__all__ = [
    "FlatIRError",
    "verify_flat_trees",
    "verify_packed_programs",
    "debug_checks_enabled",
]


class FlatIRError(ValueError):
    """A violated FlatTrees invariant. ``invariant`` names the check
    (``postorder``, ``pad_zero``, ...) and always leads the message as
    ``[invariant]`` so wrapping errors keep the name visible."""

    def __init__(self, invariant: str, message: str):
        self.invariant = invariant
        super().__init__(f"[{invariant}] {message}")


def debug_checks_enabled(options=None) -> bool:
    """Resolve the debug-checks gate: ``Options.debug_checks`` when set
    (True/False), else the ``SR_DEBUG_CHECKS`` env var. Callers resolve this
    ONCE per search into a local bool — never per hot-path call."""
    if options is not None:
        explicit = getattr(options, "debug_checks", None)
        if explicit is not None:
            return bool(explicit)
    return os.environ.get("SR_DEBUG_CHECKS", "0") == "1"


def _first_bad(mask_2d: np.ndarray) -> tuple[int, int]:
    """(tree, slot) of the first True entry of a [P, N] violation mask."""
    p, s = np.unravel_index(int(np.argmax(mask_2d)), mask_2d.shape)
    return int(p), int(s)


def verify_flat_trees(
    flat,
    opset=None,
    *,
    n_features: int | None = None,
    max_nodes: int | None = None,
    full_width: int | None = None,
    allow_empty: bool = True,
    where: str = "",
) -> None:
    """Validate a FlatTrees batch against every documented invariant.

    Parameters: ``opset`` enables the op-index range checks; ``n_features``
    the feature-index upper bound; ``max_nodes`` asserts ``length <=
    max_nodes`` beyond the array width; ``full_width`` asserts the node-axis
    width sits on the ``bucket_sizes(full_width)`` ladder (length-bucketed
    dispatch); ``allow_empty`` accepts length-0 rows (dead engine slots);
    ``where`` prefixes messages with the call site. Raises
    :class:`FlatIRError` on the first violated invariant; returns None when
    the batch is sound.
    """
    kind = np.asarray(flat.kind)
    op = np.asarray(flat.op)
    lhs = np.asarray(flat.lhs)
    rhs = np.asarray(flat.rhs)
    feat = np.asarray(flat.feat)
    val = np.asarray(flat.val)
    length = np.asarray(flat.length)

    if kind.ndim != 2:
        raise FlatIRError("shape", f"{where}kind must be [P, N], got {kind.shape}")
    P, N = kind.shape
    for name, arr in (("op", op), ("lhs", lhs), ("rhs", rhs), ("feat", feat), ("val", val)):
        if arr.shape != (P, N):
            raise FlatIRError(
                "shape", f"{where}{name} shape {arr.shape} != kind shape {(P, N)}"
            )
    if length.shape != (P,):
        raise FlatIRError(
            "shape", f"{where}length shape {length.shape} != ({P},)"
        )

    lo = 0 if allow_empty else 1
    if P and (length.min() < lo or length.max() > N):
        p = int(np.argmax((length < lo) | (length > N)))
        raise FlatIRError(
            "length_range",
            f"{where}tree {p}: length={int(length[p])} outside [{lo}, {N}]",
        )
    if max_nodes is not None and P and length.max() > max_nodes:
        p = int(np.argmax(length > max_nodes))
        raise FlatIRError(
            "length_range",
            f"{where}tree {p}: length={int(length[p])} > max_nodes={max_nodes}",
        )
    if full_width is not None:
        ladder = bucket_sizes(full_width)
        if N not in ladder and N != full_width:
            raise FlatIRError(
                "bucket",
                f"{where}node-axis width {N} is not on the bucket_sizes"
                f"({full_width}) ladder {ladder}",
            )

    if (kind < KIND_PAD).any() or (kind > KIND_BINARY).any():
        p, s = _first_bad((kind < KIND_PAD) | (kind > KIND_BINARY))
        raise FlatIRError(
            "kind_range",
            f"{where}tree {p} slot {s}: kind={int(kind[p, s])} outside "
            f"[{KIND_PAD}, {KIND_BINARY}]",
        )

    cols = np.arange(N, dtype=length.dtype)[None, :]
    live = cols < length[:, None]

    # live slots are never PAD; pad slots are exactly PAD (root at length-1
    # being a real node is a corollary)
    mism = (kind != KIND_PAD) != live
    if mism.any():
        p, s = _first_bad(mism)
        what = "PAD kind in live range" if live[p, s] else "non-PAD kind in padding"
        raise FlatIRError(
            "pad_kind", f"{where}tree {p} slot {s}: {what} (kind={int(kind[p, s])})"
        )

    # pad slots write zeros and are never read — every array must be exactly
    # zero there (the length-bucketed truncation and the bit-identity A/Bs
    # rely on this; see ops/flat.slice_nodes)
    dead = ~live
    for name, arr in (("op", op), ("lhs", lhs), ("rhs", rhs), ("feat", feat), ("val", val)):
        bad = dead & (arr != 0)
        if bad.any():
            p, s = _first_bad(bad)
            raise FlatIRError(
                "pad_zero",
                f"{where}tree {p} slot {s}: {name}={arr[p, s]} nonzero in padding",
            )

    # postorder: children strictly below their parent slot
    parent = live & (kind >= KIND_UNARY)
    bad = parent & ((lhs >= cols) | (lhs < 0))
    if bad.any():
        p, s = _first_bad(bad)
        raise FlatIRError(
            "postorder",
            f"{where}tree {p} slot {s}: lhs={int(lhs[p, s])} not in [0, {s})",
        )
    isbin = live & (kind == KIND_BINARY)
    bad = isbin & ((rhs >= cols) | (rhs < 0))
    if bad.any():
        p, s = _first_bad(bad)
        raise FlatIRError(
            "postorder",
            f"{where}tree {p} slot {s}: rhs={int(rhs[p, s])} not in [0, {s})",
        )

    if opset is not None:
        bad = isbin & ((op < 0) | (op >= opset.n_binary))
        if bad.any():
            p, s = _first_bad(bad)
            raise FlatIRError(
                "op_range",
                f"{where}tree {p} slot {s}: binary op={int(op[p, s])} outside "
                f"[0, {opset.n_binary})",
            )
        isuna = live & (kind == KIND_UNARY)
        bad = isuna & ((op < 0) | (op >= opset.n_unary))
        if bad.any():
            p, s = _first_bad(bad)
            raise FlatIRError(
                "op_range",
                f"{where}tree {p} slot {s}: unary op={int(op[p, s])} outside "
                f"[0, {opset.n_unary})",
            )

    isvar = live & (kind == KIND_VAR)
    hi = n_features if n_features is not None else None
    bad = isvar & ((feat < 0) | ((feat >= hi) if hi is not None else False))
    if bad.any():
        p, s = _first_bad(bad)
        bound = f"[0, {hi})" if hi is not None else ">= 0"
        raise FlatIRError(
            "feat_range",
            f"{where}tree {p} slot {s}: feat={int(feat[p, s])} not {bound}",
        )


def verify_packed_programs(
    packed,
    opset=None,
    *,
    n_features: int | None = None,
    allow_empty: bool = True,
    where: str = "",
) -> None:
    """Validate a :class:`~..ops.flat.PackedPrograms` batch.

    The packed IR has no stored child pointers, so postorder soundness is a
    *stack discipline* over the word stream: walking slots ``0..length-1``,
    leaves push one operand, unary ops are depth-neutral, binary ops pop one
    — the running depth must stay ``>= 1`` after every live slot and end at
    exactly 1 (named **stack**). The remaining checks mirror
    ``verify_flat_trees``: **dtype** (words are int16), **kind_range**,
    **op_range** / **feat_range** on the payload bits, **pad_kind** /
    **pad_zero** (pad words AND consts are exactly zero, consts are zero on
    every non-CONST slot), and **length_range**. Vectorized numpy throughout
    (the stack pass is a cumulative sum, not a loop). Raises
    :class:`FlatIRError` on the first violation.
    """
    words = np.asarray(packed.words)
    consts = np.asarray(packed.consts)
    length = np.asarray(packed.length)

    if words.dtype != np.int16:
        raise FlatIRError(
            "dtype", f"{where}words dtype {words.dtype} != int16"
        )
    if words.ndim != 2:
        raise FlatIRError(
            "shape", f"{where}words must be [P, N], got {words.shape}"
        )
    P, N = words.shape
    if consts.shape != (P, N):
        raise FlatIRError(
            "shape", f"{where}consts shape {consts.shape} != {(P, N)}"
        )
    if length.shape != (P,):
        raise FlatIRError(
            "shape", f"{where}length shape {length.shape} != ({P},)"
        )

    lo = 0 if allow_empty else 1
    if P and (length.min() < lo or length.max() > N):
        p = int(np.argmax((length < lo) | (length > N)))
        raise FlatIRError(
            "length_range",
            f"{where}row {p}: length={int(length[p])} outside [{lo}, {N}]",
        )

    w32 = words.astype(np.int32)
    kind = w32 & PACK_KIND_MASK
    payload = w32 >> PACK_KIND_BITS

    if (kind > KIND_BINARY).any() or (w32 < 0).any():
        p, s = _first_bad((kind > KIND_BINARY) | (w32 < 0))
        raise FlatIRError(
            "kind_range",
            f"{where}row {p} slot {s}: word={int(w32[p, s])} has kind "
            f"{int(kind[p, s])} outside [{KIND_PAD}, {KIND_BINARY}]",
        )

    cols = np.arange(N, dtype=length.dtype)[None, :]
    live = cols < length[:, None]

    mism = (kind != KIND_PAD) != live
    if mism.any():
        p, s = _first_bad(mism)
        what = "PAD kind in live range" if live[p, s] else "non-PAD word in padding"
        raise FlatIRError(
            "pad_kind",
            f"{where}row {p} slot {s}: {what} (word={int(w32[p, s])})",
        )

    # payload must be zero wherever it has no meaning (CONST slots and
    # padding carry no payload), and consts exactly zero off CONST slots —
    # canonical zeros are what make packed A/B comparisons bitwise.
    bad = (kind <= KIND_CONST) & (payload != 0)
    if bad.any():
        p, s = _first_bad(bad)
        raise FlatIRError(
            "pad_zero",
            f"{where}row {p} slot {s}: payload={int(payload[p, s])} nonzero "
            f"on kind={int(kind[p, s])}",
        )
    bad = (kind != KIND_CONST) & (consts != 0)
    if bad.any():
        p, s = _first_bad(bad)
        raise FlatIRError(
            "pad_zero",
            f"{where}row {p} slot {s}: consts={consts[p, s]} nonzero on "
            f"non-CONST slot",
        )

    # stack discipline: +1 leaf, 0 unary, -1 binary; running depth >= 1 at
    # every live slot, == 1 at the root. This is the pointerless postorder
    # invariant — a cumsum over the delta stream checks every row at once.
    delta = np.where(
        live & (kind <= KIND_VAR) & (kind >= KIND_CONST),
        1,
        np.where(live & (kind == KIND_BINARY), -1, 0),
    )
    depth = np.cumsum(delta, axis=1)
    bad = live & (depth < 1)
    if bad.any():
        p, s = _first_bad(bad)
        raise FlatIRError(
            "stack",
            f"{where}row {p} slot {s}: operand stack underflows "
            f"(depth={int(depth[p, s])})",
        )
    if P:
        final = np.where(
            length > 0, depth[np.arange(P), np.maximum(length - 1, 0)], 1
        )
        if (final != 1).any():
            p = int(np.argmax(final != 1))
            raise FlatIRError(
                "stack",
                f"{where}row {p}: {int(final[p])} operands left after the "
                f"postfix pass (want 1)",
            )

    if opset is not None:
        bad = live & (kind == KIND_BINARY) & (payload >= opset.n_binary)
        if bad.any():
            p, s = _first_bad(bad)
            raise FlatIRError(
                "op_range",
                f"{where}row {p} slot {s}: binary op={int(payload[p, s])} "
                f"outside [0, {opset.n_binary})",
            )
        bad = live & (kind == KIND_UNARY) & (payload >= opset.n_unary)
        if bad.any():
            p, s = _first_bad(bad)
            raise FlatIRError(
                "op_range",
                f"{where}row {p} slot {s}: unary op={int(payload[p, s])} "
                f"outside [0, {opset.n_unary})",
            )

    if n_features is not None:
        bad = live & (kind == KIND_VAR) & (payload >= n_features)
        if bad.any():
            p, s = _first_bad(bad)
            raise FlatIRError(
                "feat_range",
                f"{where}row {p} slot {s}: feat={int(payload[p, s])} outside "
                f"[0, {n_features})",
            )
