"""Drift detection for streaming SR sessions — pure host arithmetic.

The signal is the ratio between the CURRENT best expression's loss on the
incoming rows and an exponential moving average of the frontier's best loss
on the data it was evolved against. While the generating process is
stationary, the best member generalizes and the probe ratio hovers near 1;
when the process shifts, the frontier is suddenly wrong on the new rows and
the ratio jumps. The detector deliberately compares LOSSES (not residual
distributions): it reuses the session's existing scoring programs, so a
probe costs one warm kernel call and no new compiles.

On drift the session responds with (both optional, on by default):

- **frontier re-scoring** — every hall-of-fame member's loss is recomputed
  against the post-swap buffer, so the streamed frontier frames report
  honest losses and stale members stop blocking their complexity slots;
- **parsimony-frequency reset** — the per-lane complexity histogram
  (``EvoState.freq``) returns to the ``init_state`` uniform, forgetting the
  size distribution learned on the old regime.

Everything here is numpy/stdlib and unit-testable without jax.
"""

from __future__ import annotations

import dataclasses
import math

__all__ = ["DriftConfig", "DriftDetector"]


@dataclasses.dataclass(frozen=True)
class DriftConfig:
    """Knobs for :class:`DriftDetector`.

    ``ratio``: probe loss must exceed ``ratio * ema`` to count as drift.
    ``ema_decay``: frontier-loss EMA smoothing (per observed iteration).
    ``min_obs``: EMA observations required before probes can trigger —
    the first iterations of a session have a noisy, falling best loss and
    every push would read as drift.
    ``rescore``: re-score the hall of fame against the new buffer on drift.
    ``reset_freq``: reset the lane's parsimony-frequency histogram on drift.
    """

    ratio: float = 2.0
    ema_decay: float = 0.9
    min_obs: int = 3
    eps: float = 1e-12
    rescore: bool = True
    reset_freq: bool = True

    def __post_init__(self):
        if not self.ratio > 0:
            raise ValueError("drift ratio must be > 0")
        if not 0.0 <= self.ema_decay < 1.0:
            raise ValueError("ema_decay must be in [0, 1)")
        if self.min_obs < 1:
            raise ValueError("min_obs must be >= 1")


class DriftDetector:
    """EMA of the frontier's best loss + the probe-ratio drift test."""

    def __init__(self, config: DriftConfig | None = None):
        self.config = config or DriftConfig()
        self.ema: float | None = None
        self.observations = 0
        self.drifts = 0

    def observe(self, frontier_best_loss: float) -> None:
        """Fold one iteration's frontier best loss into the EMA (non-finite
        values are skipped: a frontier mid-rescore can transiently report
        inf, which would poison the average forever)."""
        v = float(frontier_best_loss)
        if not math.isfinite(v):
            return
        d = self.config.ema_decay
        self.ema = v if self.ema is None else d * self.ema + (1.0 - d) * v
        self.observations += 1

    def probe(self, loss_on_new_rows: float) -> bool:
        """Drift decision for one incoming batch: is the current best
        member's loss on the new rows out of line with the frontier EMA?
        Non-finite probe losses ARE drift (the new rows broke the best
        expression's domain — e.g. a log/sqrt argument went negative)."""
        if self.ema is None or self.observations < self.config.min_obs:
            return False
        v = float(loss_on_new_rows)
        if not math.isfinite(v):
            self.drifts += 1
            return True
        if v > self.config.ratio * max(self.ema, self.config.eps):
            self.drifts += 1
            return True
        return False

    def rebase(self, frontier_best_loss: float) -> None:
        """Reset the EMA to the post-rescore best loss, so the iterations
        right after an acknowledged drift don't re-trigger on the same
        regime change."""
        v = float(frontier_best_loss)
        self.ema = v if math.isfinite(v) else None
        if self.ema is None:
            self.observations = 0
