"""Streaming/online SR runtime (round 14).

- :class:`StreamSession` — a long-lived fleet lane with live row swaps
  (zero recompiles within the row bucket), drift-aware frontier upkeep,
  and format-2 frontier frame streaming;
- :class:`DriftDetector`/:class:`DriftConfig` — loss-on-new-rows vs
  frontier-EMA drift detection;
- :class:`MultitargetSearch` — multi-target SR as a fleet-of-lanes over
  shared X.

The serve layer exposes sessions as deadline-less ``kind="subscription"``
jobs (``SearchServer.push_rows`` / ``cancel``).
"""

from .drift import DriftConfig, DriftDetector
from .multitarget import MultitargetSearch, multitarget_search
from .session import StreamSession, StreamStats, next_row_bucket

__all__ = [
    "DriftConfig",
    "DriftDetector",
    "MultitargetSearch",
    "multitarget_search",
    "StreamSession",
    "StreamStats",
    "next_row_bucket",
]
