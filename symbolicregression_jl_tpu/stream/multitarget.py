"""Multi-target SR: one search per target row of Y, batched as a fleet.

The reference exposes multi-target fitting as ``MultitargetSRRegressor`` —
independent searches over a shared X. On this engine that is exactly a
fleet-of-lanes: every lane shares the compiled score fn (same X shape, same
Options digest) and the per-iteration megaprogram, so T targets cost the
same <=2 dispatches per iteration as one. When the options are not
fleet-eligible (non-device scheduler, recorder, ...) the wrapper falls back
to sequential solo searches — same results, no batching.

Per-target RNG: lane t runs with ``seed + t`` (when a seed is set), so
targets explore independently instead of mutating in lockstep.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["MultitargetSearch", "multitarget_search"]


def multitarget_search(
    X,
    Y,
    options,
    niterations: int = 10,
    weights=None,
    lane_bucket: int | None = None,
    verbosity: int = 0,
):
    """Fit one expression per target row of ``Y [targets, rows]`` over a
    shared ``X [features, rows]``. ``weights`` is either [rows] (shared) or
    [targets, rows] (per-target). Returns ``[SearchResult]`` in target
    order."""
    from ..models.device_search import (
        FleetLaneSpec,
        fleet_eligibility,
        fleet_search,
    )

    X = np.asarray(X)
    Y = np.asarray(Y)
    if Y.ndim == 1:
        Y = Y[None]
    if Y.ndim != 2 or X.ndim != 2 or Y.shape[1] != X.shape[1]:
        raise ValueError(
            f"expected X [features, rows] and Y [targets, rows]; got "
            f"{X.shape} and {Y.shape}"
        )
    T = Y.shape[0]
    W = None
    if weights is not None:
        W = np.asarray(weights)
        if W.shape == (Y.shape[1],):
            W = np.broadcast_to(W, Y.shape)
        if W.shape != Y.shape:
            raise ValueError(
                f"weights must be [rows] or [targets, rows]; got {W.shape}"
            )

    def opts_for(t: int):
        if options.seed is None:
            return options
        return dataclasses.replace(options, seed=options.seed + t)

    if fleet_eligibility(options) is None:
        specs = [
            FleetLaneSpec(
                X=X,
                y=Y[t],
                weights=None if W is None else W[t],
                options=opts_for(t),
                niterations=niterations,
                label=f"target-{t}",
            )
            for t in range(T)
        ]
        return fleet_search(specs, verbosity=verbosity, lane_bucket=lane_bucket)

    # ineligible options: same searches, run solo in sequence
    from ..search import equation_search

    return [
        equation_search(
            X,
            Y[t],
            weights=None if W is None else W[t],
            options=opts_for(t),
            niterations=niterations,
            verbosity=verbosity,
        )
        for t in range(T)
    ]


class MultitargetSearch:
    """Thin OO wrapper over :func:`multitarget_search`::

        mt = MultitargetSearch(options, niterations=20)
        results = mt.run(X, Y)          # [SearchResult] per target
        mt.frontiers                    # per-target Pareto frontiers
    """

    def __init__(self, options, niterations: int = 10,
                 lane_bucket: int | None = None):
        self.options = options
        self.niterations = int(niterations)
        self.lane_bucket = lane_bucket
        self.results = None

    def run(self, X, Y, weights=None, verbosity: int = 0):
        self.results = multitarget_search(
            X,
            Y,
            self.options,
            niterations=self.niterations,
            weights=weights,
            lane_bucket=self.lane_bucket,
            verbosity=verbosity,
        )
        return self.results

    @property
    def frontiers(self):
        if self.results is None:
            raise RuntimeError("run() first")
        return [r.hall_of_fame.pareto_frontier() for r in self.results]
