"""StreamSession: a long-lived fleet lane whose dataset updates live.

The engine's shape-stability is what makes online SR cheap here: the fleet
program takes the dataset as a TRACED, non-donated argument (ScoreData), so
swapping same-shape buffers between iterations reuses the resident
executables with zero recompiles. The session therefore keeps its rows in a
power-of-two **row bucket** (``ops/scoring.pad_rows_np``: pad rows replicate
row 0 at weight 0, bit-identical losses) and turns every ``push_rows`` /
``replace_rows`` into a weight-mask + buffer update:

- updates stage host-side under a lock and are applied by ``fleet_search``'s
  ``data_update_hook`` at the next iteration boundary (the engine thread
  pulls them — no cross-thread device traffic);
- while the row count stays within the bucket, NO program recompiles
  (pinned by tests/test_stream.py against the ProgramCache miss counters);
- when rows overflow the bucket, the session ends the epoch at the next
  boundary and restarts the lane warm (previous populations + the SAME live
  hall of fame) on the next bucket — exactly one recompile event per
  growth, amortized O(log rows) over a session's lifetime;
- a :class:`~..stream.drift.DriftDetector` compares each incoming batch's
  loss under the current best expression against the frontier-loss EMA; on
  drift the hall of fame is re-scored against the new buffer and the lane's
  parsimony-frequency histogram resets, so the search re-adapts instead of
  defending stale equations.

Frontier frames stream in the serve layer's format-2 wire encoding
(``utils/checkpoint.dump_frontier_bytes``); ``SearchServer`` exposes the
whole session as a deadline-less ``kind="subscription"`` job.

Requires fleet-eligible Options (device scheduler) with
``warmup_maxsize_by == 0``: streaming sessions are open-ended, and the
maxsize warmup schedules complexity against a finite iteration budget.
``timeout_in_seconds`` / ``max_evals`` / early-stop conditions are honored
per epoch by the underlying fleet loop and end the session.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from .drift import DriftConfig, DriftDetector

__all__ = ["StreamSession", "StreamStats", "next_row_bucket"]

_ENDLESS = 1 << 30  # per-epoch iteration budget: the callback is the stop


def next_row_bucket(n: int, minimum: int = 64) -> int:
    """Power-of-two row bucket >= n. Power-of-two growth bounds the number
    of distinct compiled row shapes (and so recompile events) at O(log N)
    over any session lifetime — the same policy as the batch/length
    buckets."""
    if n < 1:
        raise ValueError("need at least one row")
    b = max(1, int(minimum))
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass
class StreamStats:
    """Host-side session counters (engine thread writes, anyone reads)."""

    iterations: int = 0
    epochs: int = 0
    rows: int = 0
    row_bucket: int = 0
    updates_applied: int = 0
    drifts: int = 0
    rescores: int = 0
    # best frontier loss right after the latest drift re-score — the HONEST
    # loss on the new regime, observed before the next evolve/const-opt
    # iteration adapts the members to it
    last_rescore_best: float | None = None
    frames: int = 0
    recompile_events: int = 0  # bucket growths: epochs - 1
    num_evals: float = 0.0

    def summary(self) -> dict:
        return dataclasses.asdict(self)


class StreamSession:
    """One long-lived search over a live dataset. Typical use::

        session = StreamSession(X0, y0, options)
        session.start()                       # engine thread
        session.push_rows(X_new, y_new)       # applied next iteration
        frame = session.wait_for_frame(after=0, timeout=30)
        ...
        result = session.stop()               # SearchResult

    ``run()`` drives the engine inline instead (the serve layer calls it on
    a worker thread); ``request_stop()`` is the non-blocking cancel either
    way. ``on_frame(bytes)`` fires for every emitted frontier frame.
    """

    def __init__(
        self,
        X,
        y,
        options,
        weights=None,
        *,
        row_bucket: int | None = None,
        min_row_bucket: int = 64,
        window: int | None = None,
        drift=None,
        stream_every: int = 1,
        on_frame=None,
        niterations: int | None = None,
        label: str = "stream",
    ):
        from ..models.device_search import fleet_eligibility
        from ..utils.checkpoint import options_fingerprint

        X = np.asarray(X)
        y = np.asarray(y)
        if X.ndim != 2 or y.ndim != 1 or X.shape[1] != y.shape[0]:
            raise ValueError(
                f"expected X [features, rows] and y [rows]; got {X.shape} "
                f"and {y.shape}"
            )
        # the session owns the weight channel: explicit weights from the
        # start keep the ScoreData pytree structure stable across every
        # future pad/swap (a None->array flip would force a retrace)
        w = (
            np.ones(y.shape, dtype=y.dtype)
            if weights is None
            else np.asarray(weights)
        )
        if w.shape != y.shape:
            raise ValueError(f"weights shape {w.shape} != y shape {y.shape}")

        base = dataclasses.replace(
            options,
            save_to_file=False,
            progress=False,
            checkpoint_every=None,
            checkpoint_every_seconds=None,
        )
        reason = fleet_eligibility(base)
        if reason is not None:
            raise ValueError(f"options are not streamable: {reason}")
        if base.warmup_maxsize_by:
            raise ValueError(
                "streaming sessions are open-ended; warmup_maxsize_by "
                "schedules curmaxsize against a finite niterations — set it "
                "to 0"
            )
        self._user_callback = base.iteration_callback
        self._options = dataclasses.replace(
            base, iteration_callback=self._on_iteration
        )
        self._fingerprint = options_fingerprint(self._options)
        self._niterations = int(niterations) if niterations else _ENDLESS
        if self._niterations < 1:
            raise ValueError("niterations must be >= 1 (or None for endless)")
        if stream_every < 0:
            raise ValueError("stream_every must be >= 0 (0 disables frames)")
        self.stream_every = int(stream_every)
        self.on_frame = on_frame
        self.label = label
        self.min_row_bucket = int(min_row_bucket)
        self.window = None if window is None else int(window)
        if self.window is not None and self.window < 1:
            raise ValueError("window must be >= 1 rows")

        if drift is False:
            self._detector = None
        else:
            if drift is None:
                cfg = DriftConfig()
            elif isinstance(drift, DriftConfig):
                cfg = drift
            elif isinstance(drift, dict):
                cfg = DriftConfig(**drift)
            else:
                raise TypeError(f"drift must be DriftConfig|dict|False: {drift!r}")
            self._detector = DriftDetector(cfg)

        self._Xh, self._yh, self._wh = X.copy(), y.copy(), w.copy()
        n = y.shape[0]
        self._bucket = (
            next_row_bucket(n, self.min_row_bucket)
            if row_bucket is None
            else int(row_bucket)
        )
        if self._bucket < n:
            raise ValueError(f"row_bucket {self._bucket} < initial rows {n}")

        from ..models.hall_of_fame import HallOfFame

        self.hof = HallOfFame(self._options.maxsize)
        self.stats = StreamStats(rows=int(n), row_bucket=self._bucket)
        self.latest_frame: bytes | None = None
        self.frame_count = 0
        self.error: str | None = None

        self._lock = threading.Lock()
        self._frame_cond = threading.Condition(self._lock)
        self._staged: list = []  # ("push"|"replace", X, y, w) in arrival order
        self._stop = threading.Event()
        self._finished = threading.Event()
        self._epoch_end = False
        self._grow_to: int | None = None
        self._lane = None
        self._thread: threading.Thread | None = None
        self._result = None
        self._evals_base = 0.0
        self._t0 = time.time()

    # -- client surface -------------------------------------------------------
    def push_rows(self, X, y, weights=None) -> None:
        """Append rows to the live dataset; applied at the next iteration
        boundary. Grows the row bucket (one recompile event) only when the
        total row count overflows it."""
        self._stage("push", X, y, weights)

    def replace_rows(self, X, y, weights=None) -> None:
        """Replace the whole dataset (same feature count) at the next
        iteration boundary."""
        self._stage("replace", X, y, weights)

    def _stage(self, kind: str, X, y, weights) -> None:
        X = np.asarray(X)
        y = np.asarray(y)
        if X.ndim != 2 or y.ndim != 1 or X.shape[1] != y.shape[0]:
            raise ValueError(
                f"expected X [features, rows] and y [rows]; got {X.shape} "
                f"and {y.shape}"
            )
        if X.shape[0] != self._Xh.shape[0]:
            raise ValueError(
                f"feature count is fixed for a session: {self._Xh.shape[0]} "
                f"!= pushed {X.shape[0]}"
            )
        w = (
            np.ones(y.shape, dtype=y.dtype)
            if weights is None
            else np.asarray(weights)
        )
        if w.shape != y.shape:
            raise ValueError(f"weights shape {w.shape} != y shape {y.shape}")
        if self._finished.is_set():
            raise RuntimeError("session has ended")
        with self._lock:
            self._staged.append((kind, X.copy(), y.copy(), w.copy()))

    def start(self) -> "StreamSession":
        if self._thread is not None:
            raise RuntimeError("session already started")
        self._thread = threading.Thread(
            target=self._run_guarded, name=f"sr-stream-{self.label}", daemon=True
        )
        self._thread.start()
        return self

    def request_stop(self) -> None:
        """Non-blocking: the engine stops at the next iteration boundary."""
        self._stop.set()

    def stop(self, wait: bool = True, timeout: float | None = 300.0):
        """Request stop and (by default) wait for the engine to finish.
        Returns the final SearchResult (None if the engine never completed
        an epoch)."""
        self._stop.set()
        if wait:
            self._finished.wait(timeout)
            if self._thread is not None:
                self._thread.join(timeout)
        return self._result

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the session ends on its own (early stop, timeout,
        max_evals, error) or via stop(). True when finished."""
        return self._finished.wait(timeout)

    @property
    def finished(self) -> bool:
        return self._finished.is_set()

    @property
    def result(self):
        return self._result

    def frontier(self) -> list:
        """Snapshot of the live Pareto frontier (copied members)."""
        return [m.copy() for m in self.hof.pareto_frontier()]

    def wait_for_frame(
        self, after: int = 0, timeout: float | None = None
    ) -> bytes | None:
        """Block until a frame with index > ``after`` exists (frames are
        1-counted); returns the LATEST frame, or None on timeout/end."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._frame_cond:
            while self.frame_count <= after and not self._finished.is_set():
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._frame_cond.wait(
                    0.2 if remaining is None else min(0.2, remaining)
                )
            return self.latest_frame if self.frame_count > after else None

    # -- engine side ----------------------------------------------------------
    def run(self):
        """Drive the engine inline until stop/termination; returns the final
        SearchResult. The serve layer calls this on a worker thread;
        ``start()`` wraps it in a thread for library use."""
        from ..models.device_search import FleetLaneSpec, fleet_search
        from ..ops.scoring import pad_rows_np

        init_trees = None
        while True:
            with self._lock:
                Xh, yh, wh = self._Xh, self._yh, self._wh
            Xp, yp, wp = pad_rows_np(Xh, yh, wh, self._bucket)
            spec = FleetLaneSpec(
                X=Xp,
                y=yp,
                weights=wp,
                options=self._options,
                niterations=self._niterations,
                label=self.label,
                init_trees=init_trees,
                init_hof=self.hof,
            )
            self.stats.epochs += 1
            self.stats.row_bucket = self._bucket
            res = fleet_search(
                [spec],
                data_update_hook=self._hook,
                on_lanes_ready=self._adopt_lanes,
            )[0]
            self._result = res
            self._evals_base += float(res.num_evals)
            self.stats.num_evals = self._evals_base
            if (
                res.stop_reason == "callback"
                and self._epoch_end
                and not self._stop.is_set()
            ):
                # row-bucket overflow: restart the lane warm on the grown
                # bucket — the ONE recompile event per growth
                self._epoch_end = False
                if self._grow_to is not None:
                    self._bucket = self._grow_to
                    self._grow_to = None
                self.stats.recompile_events += 1
                init_trees = [
                    m.tree for pop in res.populations for m in pop.members
                ]
                continue
            break
        self._finished.set()
        with self._frame_cond:
            self._frame_cond.notify_all()
        return self._result

    def _run_guarded(self) -> None:
        try:
            self.run()
        except BaseException as e:  # surfaced via .error; thread must not die silently
            self.error = f"{type(e).__name__}: {e}"
            self._finished.set()
            with self._frame_cond:
                self._frame_cond.notify_all()

    def _adopt_lanes(self, lanes) -> None:
        self._lane = lanes[0]

    def _hook(self, it: int):
        """fleet_search data_update_hook: merge staged updates into the host
        buffers and swap the lane's ScoreData (same shape, zero recompiles),
        or end the epoch on bucket overflow."""
        with self._lock:
            if not self._staged:
                return None
            staged, self._staged = self._staged, []
        pushed: list = []
        replaced = False
        Xh, yh, wh = self._Xh, self._yh, self._wh
        for kind, Xn, yn, wn in staged:
            if kind == "replace":
                Xh, yh, wh = Xn, yn, wn
                replaced, pushed = True, []
            else:
                Xh = np.concatenate([Xh, Xn], axis=1)
                yh = np.concatenate([yh, yn])
                wh = np.concatenate([wh, wn.astype(yh.dtype)])
                pushed.append((Xn, yn, wn))
        if self.window is not None and yh.shape[0] > self.window:
            k = yh.shape[0] - self.window
            Xh, yh, wh = Xh[:, k:], yh[k:], wh[k:]
        with self._lock:
            self._Xh, self._yh, self._wh = Xh, yh, wh
        n = int(yh.shape[0])
        self.stats.rows = n
        if n > self._bucket:
            self._grow_to = next_row_bucket(n, self.min_row_bucket)
            self._epoch_end = True  # consumed by the iteration callback
            return None

        from ..models.device_search import LaneDataUpdate
        from ..ops.scoring import pad_rows_np

        lane = self._lane
        drifted = False
        if self._detector is not None:
            probe = [(Xh, yh, wh)] if replaced else pushed
            if probe:
                Xn = np.concatenate([p[0] for p in probe], axis=1)
                yn = np.concatenate([p[1] for p in probe])
                wn = np.concatenate([p[2] for p in probe])
                if yn.shape[0] <= self._bucket:
                    pl = self._probe_best_loss(lane, Xn, yn, wn)
                    if pl is not None:
                        drifted = self._detector.probe(pl)
                        self.stats.drifts = self._detector.drifts

        Xp, yp, wp = pad_rows_np(Xh, yh, wh, self._bucket)
        data, ds = lane.rebuild_score_data(Xp, yp, wp)
        if drifted and self._detector.config.rescore:
            self._rescore_frontier(lane, data)
            best = [m.loss for m in lane.hof.pareto_frontier()]
            if best:
                self._detector.rebase(min(best))
        self.stats.updates_applied += 1
        return {
            0: LaneDataUpdate(
                score_data=data,
                dataset=ds,
                reset_freq=drifted and self._detector.config.reset_freq,
            )
        }

    def _score_members(self, lane, members, data) -> list:
        """Loss of each member's tree under ``data``, through the lane's
        WARM score program: batches are padded to the [maxsize+1] pool shape
        the fleet warmup already compiled, so probes/rescores cost kernel
        calls only — never compiles."""
        import jax.numpy as jnp

        from ..ops.flat import flatten_trees
        from ..ops.treeops import Tree

        S1 = lane.cfg.maxsize + 1
        vdt = np.dtype(lane.cfg.val_dtype)
        trees = [m.tree for m in members]
        out: list = []
        for i in range(0, len(trees), S1):
            chunk = trees[i : i + S1]
            flat = flatten_trees(
                chunk + [chunk[0]] * (S1 - len(chunk)),
                lane.cfg.n_slots,
                dtype=vdt,
            )
            batch = Tree(*(jnp.asarray(a) for a in flat))
            losses = lane.score_fn.jitted(batch, data)
            if lane.cfg.units_check:
                from ..ops.evolve import dim_penalty_batch_jit

                losses = losses + dim_penalty_batch_jit(batch, lane.ecfg)
            out.extend(np.asarray(losses)[: len(chunk)].tolist())
        self._evals_probe(lane, len(trees))
        return out

    def _evals_probe(self, lane, n_trees: int) -> None:
        lane.host_evals += n_trees
        lane.num_evals = lane.device_evals + lane.host_evals

    def _probe_best_loss(self, lane, Xn, yn, wn) -> float | None:
        """Current best expression's loss on the incoming rows, computed on
        a row-bucket-padded probe ScoreData so the lane's resident score
        program serves it."""
        from ..ops.scoring import pad_rows_np

        frontier = lane.hof.pareto_frontier()
        if not frontier:
            return None
        best = min(frontier, key=lambda m: m.loss)
        Xp, yp, wp = pad_rows_np(Xn, yn, wn, self._bucket)
        data, _ = lane.rebuild_score_data(Xp, yp, wp)
        return float(self._score_members(lane, [best], data)[0])

    def _rescore_frontier(self, lane, data) -> None:
        """Drift response: recompute every hall-of-fame member's loss
        against the post-swap buffer, in place. Members whose loss goes
        non-finite on the new data vacate their slot (a NaN occupant would
        block it forever — HallOfFame.update's invariant)."""
        from ..ops.evolve import _score_of

        hof = lane.hof
        idx = [i for i, e in enumerate(hof.exists) if e]
        if not idx:
            return
        members = [hof.members[i] for i in idx]
        losses = self._score_members(lane, members, data)
        norm = float(np.asarray(data.norm))
        for i, m, lo in zip(idx, members, losses):
            if not np.isfinite(lo):
                hof.exists[i] = False
                continue
            m.loss = float(lo)
            m.score = float(
                _score_of(
                    float(lo),
                    float(m.get_complexity(lane.options)),
                    lane.cfg,
                    norm,
                )
            )
        self.stats.rescores += 1
        frontier = hof.pareto_frontier()
        if frontier:
            self.stats.last_rescore_best = float(
                min(m.loss for m in frontier)
            )

    def _on_iteration(self, report):
        """The lane's iteration callback: EMA upkeep, frame emission, stop
        plumbing (user callback -> session stop -> epoch end)."""
        self.stats.iterations += 1
        if self._detector is not None:
            frontier = report.hall_of_fame.pareto_frontier()
            if frontier:
                self._detector.observe(min(m.loss for m in frontier))
        if self.stream_every and self.stats.iterations % self.stream_every == 0:
            self._emit_frame(report)
        user_stop = (
            self._user_callback(report)
            if self._user_callback is not None
            else None
        )
        if user_stop:
            self._stop.set()
        if self._stop.is_set() or self._epoch_end:
            return True
        return None

    def _emit_frame(self, report) -> None:
        from ..utils.checkpoint import dump_frontier_bytes

        if not report.hall_of_fame.pareto_frontier():
            # the pipelined device loop's first report lags the hall of
            # fame; an empty-frontier frame is useless to a subscriber
            return
        frame = dump_frontier_bytes(
            report.hall_of_fame,
            iteration=self.stats.iterations,
            niterations=0,  # sentinel: subscriptions have no budget
            num_evals=self._evals_base + float(report.num_evals),
            fingerprint=self._fingerprint,
            wall_time=time.time() - self._t0,
        )
        with self._frame_cond:
            self.latest_frame = frame
            self.frame_count += 1
            self.stats.frames = self.frame_count
            self._frame_cond.notify_all()
        if self.on_frame is not None:
            self.on_frame(frame)
