"""Network front door for the serve stack.

- ``wire`` — the CRC-framed binary codec (journal framing discipline on a
  socket): :class:`FrameDecoder`, :func:`encode_message`, :class:`WireError`.
- ``server`` — :class:`NetServer`: the asyncio socket server wrapping a
  :class:`~..server.SearchServer` (auth→tenant, frame fan-out, retryable
  overload, slow-client shed).
- ``client`` — the SDK: sync :class:`SRClient` and :class:`AsyncSRClient`,
  both with reconnect + resume-from-frame-index streams.
"""

from .client import (
    AsyncSRClient,
    AuthError,
    ConnectionLost,
    NetError,
    RemoteError,
    RetryableWireError,
    SRClient,
)
from .server import NetServer, parse_tokens
from .wire import (
    WIRE_MAGIC,
    FrameDecoder,
    WireError,
    decode_message,
    encode_frame,
    encode_message,
    max_frame_bytes,
)

__all__ = [
    "NetServer",
    "SRClient",
    "AsyncSRClient",
    "NetError",
    "AuthError",
    "RemoteError",
    "RetryableWireError",
    "ConnectionLost",
    "WireError",
    "FrameDecoder",
    "WIRE_MAGIC",
    "encode_frame",
    "encode_message",
    "decode_message",
    "max_frame_bytes",
    "parse_tokens",
]
