"""``NetServer``: the asyncio socket front door over a ``SearchServer``.

Everything below the wire is the existing serve stack — admission queue,
per-tenant quotas, fleet coalescing/dedup, journal durability, preemption.
This layer only (a) frames requests/responses with the journal's CRC
discipline (``serve/net/wire.py``), (b) authenticates a token to a tenant
so the queue's quotas apply to network callers, (c) fans frontier frames
out to subscribed connections, and (d) turns overload into a *retryable*
wire error with a retry-after hint instead of a stalled socket.

Threading model (three kinds of thread, one rule each):

- the **asyncio loop thread** owns every connection: reads, dispatch,
  per-connection bounded send queues, and the writer/pusher tasks;
- the **bridge thread** is the only poller: it sleeps on
  ``SearchServer.wait_activity()`` (one condition variable for ALL jobs)
  and tickles the loop when any frame lands or any job goes terminal —
  N subscriptions cost one thread, not N;
- the ``SearchServer``'s own worker threads never learn the network
  exists; ops that take server locks or fsync (submit, push_rows, stats)
  run via ``asyncio.to_thread`` so the loop never blocks on them.

Frame fan-out is pull-from-index, push-on-activity: each connection
remembers the next frame index per subscribed job and drains
``frames_since(job, index)`` — a single-lock consistent snapshot — on
every activity tick. Because delivery is index-addressed, a reconnecting
client resumes from exactly the first frame it never received: the server
replays the stored ``Job.frames`` suffix, and nothing is duplicated.

Backpressure: a client that stops reading fills its bounded send queue or
stalls ``drain()`` past ``SR_NET_SLOW_CLIENT_S`` — either way the
connection is shed (counted in ``dropped_slow``) rather than buffering
without bound; the SDK reconnects and resumes by index. Admission-side
overload (``ServerOverloaded``, connection cap) answers with
``{"error": "overloaded", "retryable": True, "retry_after_s": hint}``.

Env knobs: ``SR_NET_HOST`` (default 127.0.0.1), ``SR_NET_PORT`` (default
0 = ephemeral), ``SR_NET_TOKENS`` (``token=tenant,...`` — when set, ALL
clients must present a known token and their jobs are forced onto that
tenant), ``SR_NET_MAX_CONNS`` (256), ``SR_NET_SEND_QUEUE`` (256 frames),
``SR_NET_SLOW_CLIENT_S`` (10), ``SR_NET_HELLO_S`` (10),
``SR_NET_RETRY_AFTER_S`` (0.25 base hint), ``SR_NET_MAX_FRAME_MB`` (64).

Fault sites (``utils/faults.py``): ``torn_frame`` / ``net_drop`` fire per
*pushed stream frame* in the writer (deterministic counts for a single
subscribed stream); ``slow_client`` lives in the SDK's reader.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import pickle
import threading
import time
import uuid

from ...utils import faults
from ..queue import JobSpec, ServerOverloaded
from .wire import WIRE_MAGIC, FrameDecoder, WireError, encode_message

__all__ = ["NetServer", "parse_tokens"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def parse_tokens(val: str) -> dict[str, str]:
    """``"token=tenant,token2=tenant2"`` → mapping (``SR_NET_TOKENS``)."""
    out: dict[str, str] = {}
    for chunk in (val or "").split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        tok, sep, tenant = chunk.partition("=")
        if sep and tok.strip():
            out[tok.strip()] = tenant.strip() or "default"
    return out


class _Conn:
    """Loop-thread-only per-connection state."""

    def __init__(self, reader, writer, sendq_max: int):
        self.reader = reader
        self.writer = writer
        self.sendq: asyncio.Queue = asyncio.Queue(maxsize=sendq_max)
        self.tenant: str | None = None
        self.subs: dict[str, int] = {}  # job_id -> next frame index to push
        self.tasks: set[asyncio.Task] = set()
        self.alive = True


_OP_NAMES = frozenset(
    {
        "ping",
        "submit",
        "status",
        "cancel",
        "wait",
        "frames",
        "subscribe",
        "unsubscribe",
        "push_rows",
        "replace_rows",
        "stats",
    }
)


class NetServer:
    """Socket front door over a started :class:`~..server.SearchServer`.

    Usage::

        with SearchServer(max_concurrency=4) as srv:
            net = NetServer(srv, port=0).start()
            ...  # net.port is the bound port
            net.shutdown()

    The caller owns the wrapped server's lifecycle; ``shutdown()`` only
    tears down the network layer.
    """

    def __init__(
        self,
        server,
        host: str | None = None,
        port: int | None = None,
        tokens: dict[str, str] | None = None,
        max_conns: int | None = None,
        send_queue: int | None = None,
        slow_client_s: float | None = None,
    ):
        self.server = server
        self.host = (
            host if host is not None else os.environ.get("SR_NET_HOST", "127.0.0.1")
        )
        self.port = int(port) if port is not None else _env_int("SR_NET_PORT", 0)
        self.tokens = (
            dict(tokens)
            if tokens is not None
            else parse_tokens(os.environ.get("SR_NET_TOKENS", ""))
        )
        self.max_conns = (
            int(max_conns) if max_conns is not None else _env_int("SR_NET_MAX_CONNS", 256)
        )
        self.send_queue = (
            int(send_queue)
            if send_queue is not None
            else _env_int("SR_NET_SEND_QUEUE", 256)
        )
        self.slow_client_s = (
            float(slow_client_s)
            if slow_client_s is not None
            else _env_float("SR_NET_SLOW_CLIENT_S", 10.0)
        )
        self.hello_s = _env_float("SR_NET_HELLO_S", 10.0)
        # Boot id: frame indices are meaningful within one server process.
        # A client that reconnects and sees a different boot knows the
        # server restarted (journal recovery) and must restart its streams
        # from index 0 instead of resuming.
        self.boot = uuid.uuid4().hex[:12]
        self._conns: set[_Conn] = set()
        self._counters = {
            "conns": 0,
            "shed_conns": 0,
            "dropped_slow": 0,
            "auth_failures": 0,
            "requests": 0,
            "frames_pushed": 0,
            "net_faults": 0,
        }
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._bridge: threading.Thread | None = None
        self._stop = threading.Event()
        self._stop_async: asyncio.Event | None = None
        self._started = False
        # loop-thread state for the activity fan-out (condvar pattern:
        # a seq bump between a pusher's pass and its re-wait is never lost)
        self._waiters: set[asyncio.Future] = set()
        self._notify_seq = 0

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> "NetServer":
        if self._started:
            return self
        ready = threading.Event()
        boot_err: list[BaseException] = []
        self._thread = threading.Thread(
            target=self._run_loop, args=(ready, boot_err), name="sr-net-loop",
            daemon=True,
        )
        self._thread.start()
        ready.wait(30.0)
        if boot_err:
            raise boot_err[0]
        if not ready.is_set():
            raise RuntimeError("NetServer event loop failed to start in 30s")
        self._bridge = threading.Thread(
            target=self._bridge_loop, name="sr-net-bridge", daemon=True
        )
        self._bridge.start()
        self._started = True
        return self

    def shutdown(self) -> None:
        if not self._started:
            return
        self._stop.set()
        loop = self._loop
        if loop is not None and not loop.is_closed():
            with contextlib.suppress(RuntimeError):
                loop.call_soon_threadsafe(self._begin_stop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        if self._bridge is not None:
            self._bridge.join(timeout=2.0)
        self._started = False

    def __enter__(self) -> "NetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def net_stats(self) -> dict:
        return {
            "boot": self.boot,
            "host": self.host,
            "port": self.port,
            "active_conns": len(self._conns),
            **dict(self._counters),
        }

    # -- event loop ------------------------------------------------------------
    def _run_loop(self, ready: threading.Event, boot_err: list) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            loop.run_until_complete(self._main(ready, boot_err))
        finally:
            with contextlib.suppress(Exception):
                loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()

    async def _main(self, ready: threading.Event, boot_err: list) -> None:
        self._stop_async = asyncio.Event()
        try:
            srv = await asyncio.start_server(self._handle, self.host, self.port)
        except OSError as exc:
            boot_err.append(exc)
            ready.set()
            return
        self.port = srv.sockets[0].getsockname()[1]
        ready.set()
        async with srv:
            await self._stop_async.wait()
            for conn in list(self._conns):
                self._abort(conn)
            # reap EVERYTHING still on the loop (handler tasks, writers,
            # pushers, in-flight requests) so no coroutine outlives it;
            # multiple rounds because a cancelled handler's cleanup can
            # itself leave freshly-cancelled children behind
            for _ in range(3):
                pending = [
                    t
                    for t in asyncio.all_tasks()
                    if t is not asyncio.current_task()
                ]
                if not pending:
                    break
                for task in pending:
                    task.cancel()
                with contextlib.suppress(Exception):
                    await asyncio.wait(pending, timeout=1.0)

    def _begin_stop(self) -> None:
        if self._stop_async is not None:
            self._stop_async.set()
        self._notify()

    def _bridge_loop(self) -> None:
        last = 0
        while not self._stop.is_set():
            cur = self.server.wait_activity(last, timeout=0.5)
            if self._stop.is_set():
                return
            if cur == last:
                continue
            last = cur
            loop = self._loop
            if loop is not None and not loop.is_closed():
                with contextlib.suppress(RuntimeError):
                    loop.call_soon_threadsafe(self._notify)

    def _notify(self) -> None:
        self._notify_seq += 1
        for fut in list(self._waiters):
            if not fut.done():
                fut.set_result(None)
        self._waiters.clear()

    async def _wait_notify(self, seen: int, timeout: float) -> int:
        """Wait until the notify seq advances past ``seen`` (or timeout);
        returns the current seq. A bump that happened between the caller's
        last pass and this call returns immediately — no lost wakeups."""
        if self._notify_seq != seen:
            return self._notify_seq
        fut = asyncio.get_running_loop().create_future()
        self._waiters.add(fut)
        try:
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(fut, timeout)
        finally:
            self._waiters.discard(fut)
        return self._notify_seq

    # -- connection handling ---------------------------------------------------
    async def _handle(self, reader, writer) -> None:
        if len(self._conns) >= self.max_conns:
            self._counters["shed_conns"] += 1
            with contextlib.suppress(Exception):
                writer.write(
                    WIRE_MAGIC
                    + encode_message(
                        {
                            "rid": 0,
                            "ok": False,
                            "error": "overloaded",
                            "retryable": True,
                            "retry_after_s": self._retry_after(),
                            "detail": f"connection limit {self.max_conns}",
                        }
                    )
                )
                await writer.drain()
                writer.close()
            return
        conn = _Conn(reader, writer, self.send_queue)
        self._conns.add(conn)
        self._counters["conns"] += 1
        try:
            writer.write(WIRE_MAGIC)
            await writer.drain()
            magic = await asyncio.wait_for(
                reader.readexactly(len(WIRE_MAGIC)), self.hello_s
            )
            if magic != WIRE_MAGIC:
                return
            decoder = FrameDecoder()
            first = await asyncio.wait_for(
                self._read_batch(reader, decoder), self.hello_s
            )
            if not first or first[0].get("op") != "hello":
                return
            ok, resp = self._auth(first[0])
            writer.write(encode_message(resp))
            await writer.drain()
            if not ok:
                return
            conn.tenant = resp["tenant"]
            for task_fn in (self._writer_loop, self._pusher_loop):
                task = asyncio.create_task(task_fn(conn))
                conn.tasks.add(task)
                task.add_done_callback(conn.tasks.discard)
            for msg in first[1:]:  # requests pipelined behind the hello
                self._dispatch(conn, msg)
            while True:
                data = await reader.read(1 << 16)
                if not data:
                    return
                for msg in decoder.feed_messages(data):
                    self._dispatch(conn, msg)
        except (
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,
            ConnectionError,
            OSError,
            WireError,
        ):
            return
        finally:
            conn.alive = False
            tasks = [t for t in conn.tasks if t is not asyncio.current_task()]
            for task in tasks:
                task.cancel()
            if tasks:  # reap, so no coroutine outlives the loop
                with contextlib.suppress(Exception):
                    await asyncio.wait(tasks, timeout=1.0)
            self._conns.discard(conn)
            with contextlib.suppress(Exception):
                writer.close()

    async def _read_batch(self, reader, decoder: FrameDecoder) -> list[dict]:
        """Read until at least one complete message is available."""
        msgs = decoder.feed_messages(b"")
        while not msgs:
            data = await reader.read(1 << 16)
            if not data:
                return []
            msgs = decoder.feed_messages(data)
        return msgs

    def _auth(self, hello: dict) -> tuple[bool, dict]:
        rid = hello.get("rid", 0)
        if self.tokens:
            tenant = self.tokens.get(hello.get("token"))
            if tenant is None:
                self._counters["auth_failures"] += 1
                return False, {
                    "rid": rid,
                    "ok": False,
                    "error": "auth",
                    "retryable": False,
                    "detail": "unknown token",
                }
        else:
            tenant = str(hello.get("tenant") or "default")
        return True, {
            "rid": rid,
            "ok": True,
            "tenant": tenant,
            "boot": self.boot,
            "server": "srnet/1",
        }

    def _dispatch(self, conn: _Conn, msg: dict) -> None:
        self._counters["requests"] += 1
        task = asyncio.create_task(self._serve_one(conn, msg))
        conn.tasks.add(task)
        task.add_done_callback(conn.tasks.discard)

    async def _serve_one(self, conn: _Conn, msg: dict) -> None:
        rid = msg.get("rid", 0)
        op = msg.get("op")
        try:
            if not isinstance(op, str) or op not in _OP_NAMES:
                raise ValueError(f"unknown op {op!r}")
            resp = await getattr(self, f"_op_{op}")(conn, msg)
        except asyncio.CancelledError:
            raise
        except ServerOverloaded as exc:
            resp = {
                "ok": False,
                "error": "overloaded",
                "retryable": True,
                "retry_after_s": self._retry_after(),
                "detail": str(exc),
            }
        except KeyError as exc:
            resp = {"ok": False, "error": "unknown_job", "retryable": False,
                    "detail": str(exc)}
        except (ValueError, TypeError, RuntimeError, WireError) as exc:
            resp = {"ok": False, "error": "bad_request", "retryable": False,
                    "detail": str(exc)}
        except Exception as exc:  # noqa: BLE001 — never let one request kill the conn
            resp = {"ok": False, "error": "internal", "retryable": False,
                    "detail": repr(exc)}
        resp.setdefault("ok", True)
        resp["rid"] = rid
        self._send(conn, resp)

    def _send(self, conn: _Conn, msg: dict) -> None:
        if not conn.alive:
            return
        try:
            conn.sendq.put_nowait(msg)
        except asyncio.QueueFull:
            # A reader this far behind is shed, not buffered without bound;
            # the SDK reconnects and resumes its streams by frame index.
            self._counters["dropped_slow"] += 1
            self._abort(conn)

    def _abort(self, conn: _Conn) -> None:
        conn.alive = False
        with contextlib.suppress(Exception):
            conn.writer.transport.abort()

    async def _writer_loop(self, conn: _Conn) -> None:
        inj = faults.active()
        try:
            while True:
                msg = await conn.sendq.get()
                data = encode_message(msg)
                if msg.get("push") == "frame":
                    # drill sites count per PUSHED stream frame, so
                    # e.g. torn_frame@3 is deterministic for one stream
                    if inj.fire("torn_frame") is not None:
                        self._counters["net_faults"] += 1
                        conn.writer.write(data[: max(1, len(data) // 2)])
                        with contextlib.suppress(Exception):
                            await conn.writer.drain()
                        self._abort(conn)
                        return
                    if inj.fire("net_drop") is not None:
                        self._counters["net_faults"] += 1
                        self._abort(conn)
                        return
                conn.writer.write(data)
                await asyncio.wait_for(conn.writer.drain(), self.slow_client_s)
                if msg.get("push") == "frame":
                    self._counters["frames_pushed"] += 1
        except asyncio.CancelledError:
            raise
        except asyncio.TimeoutError:
            self._counters["dropped_slow"] += 1
            self._abort(conn)
        except (ConnectionError, OSError):
            self._abort(conn)

    async def _pusher_loop(self, conn: _Conn) -> None:
        seen = 0
        try:
            while conn.alive:
                self._push_pass(conn)
                seen = await self._wait_notify(seen, 0.5)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a broken fan-out sheds the conn, not the loop
            self._abort(conn)

    def _push_pass(self, conn: _Conn) -> None:
        for job_id in list(conn.subs):
            start = conn.subs[job_id]
            try:
                frames, terminal = self.server.frames_since(job_id, start)
            except KeyError:
                conn.subs.pop(job_id, None)
                continue
            for off, frame in enumerate(frames):
                self._send(
                    conn,
                    {
                        "push": "frame",
                        "job": job_id,
                        "index": start + off,
                        "frame": frame,
                        "boot": self.boot,
                    },
                )
                if not conn.alive:
                    return
            conn.subs[job_id] = start + len(frames)
            if terminal:
                job = self.server.job(job_id)
                summary = job.summary()
                summary["resumed_from_iteration"] = job.resumed_from_iteration
                self._send(
                    conn,
                    {
                        "push": "terminal",
                        "job": job_id,
                        "boot": self.boot,
                        "summary": summary,
                    },
                )
                conn.subs.pop(job_id, None)

    def _retry_after(self) -> float:
        """Retry-after hint: the base knob scaled by queue depth per
        worker, capped at 5s. (Reads the queue length directly — a full
        ``stats()`` snapshot per shed would take the big lock.)"""
        base = _env_float("SR_NET_RETRY_AFTER_S", 0.25)
        try:
            depth = len(self.server._queue)
            workers = max(1, int(self.server.max_concurrency))
        except Exception:  # noqa: BLE001
            return base
        return round(min(5.0, base * (1.0 + depth / workers)), 3)

    # -- ops -------------------------------------------------------------------
    @staticmethod
    def _job_id(msg: dict) -> str:
        jid = msg.get("job")
        if not isinstance(jid, str) or not jid:
            raise ValueError("request needs a 'job' id")
        return jid

    async def _op_ping(self, conn: _Conn, msg: dict) -> dict:
        return {"t": time.time(), "boot": self.boot}

    async def _op_submit(self, conn: _Conn, msg: dict) -> dict:
        raw = msg.get("spec")
        if not isinstance(raw, (bytes, bytearray)):
            raise ValueError("submit needs pickled JobSpec bytes under 'spec'")
        try:
            spec = pickle.loads(bytes(raw))
        except Exception as exc:  # noqa: BLE001
            raise ValueError(f"undecodable JobSpec: {exc!r}") from exc
        if not isinstance(spec, JobSpec):
            raise ValueError(f"'spec' decodes to {type(spec).__name__}, not JobSpec")
        if self.tokens:
            # the token IS the identity: quotas key off its tenant, not
            # whatever the client stamped into the spec
            spec.tenant = conn.tenant or "default"
        job_id = await asyncio.to_thread(self.server.submit, spec)
        return {"job": job_id, "tenant": spec.tenant, "boot": self.boot}

    async def _op_status(self, conn: _Conn, msg: dict) -> dict:
        job = self.server.job(self._job_id(msg))
        summary = job.summary()
        summary["resumed_from_iteration"] = job.resumed_from_iteration
        return {"summary": summary}

    async def _op_cancel(self, conn: _Conn, msg: dict) -> dict:
        self.server.cancel(self._job_id(msg))
        return {}

    async def _op_wait(self, conn: _Conn, msg: dict) -> dict:
        job_id = self._job_id(msg)
        timeout = float(msg.get("timeout", 300.0))
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        seen = 0
        while True:
            job = self.server.job(job_id)
            if job.terminal:
                return {"summary": job.summary()}
            remaining = deadline - loop.time()
            if remaining <= 0:
                return {"summary": job.summary(), "timed_out": True}
            seen = await self._wait_notify(seen, min(0.5, remaining))

    async def _op_frames(self, conn: _Conn, msg: dict) -> dict:
        start = int(msg.get("start", 0))
        frames, terminal = self.server.frames_since(self._job_id(msg), start)
        return {"start": start, "frames": frames, "terminal": terminal,
                "boot": self.boot}

    async def _op_subscribe(self, conn: _Conn, msg: dict) -> dict:
        job_id = self._job_id(msg)
        self.server.job(job_id)  # KeyError -> unknown_job before registering
        start = int(msg.get("start", 0))
        conn.subs[job_id] = start
        self._notify()  # kick the pusher for the immediate backlog replay
        return {"job": job_id, "start": start, "boot": self.boot}

    async def _op_unsubscribe(self, conn: _Conn, msg: dict) -> dict:
        conn.subs.pop(self._job_id(msg), None)
        return {}

    async def _op_push_rows(self, conn: _Conn, msg: dict) -> dict:
        await asyncio.to_thread(
            self.server.push_rows,
            self._job_id(msg), msg.get("X"), msg.get("y"), msg.get("weights"),
        )
        return {}

    async def _op_replace_rows(self, conn: _Conn, msg: dict) -> dict:
        await asyncio.to_thread(
            self.server.replace_rows,
            self._job_id(msg), msg.get("X"), msg.get("y"), msg.get("weights"),
        )
        return {}

    async def _op_stats(self, conn: _Conn, msg: dict) -> dict:
        server_stats = await asyncio.to_thread(self.server.stats)
        return {"server": server_stats, "net": self.net_stats()}
