"""Wire framing for the network front door.

The protocol carries pickled message dicts in CRC-framed binary frames —
the exact framing discipline of ``serve/journal.py`` (``u32 length |
u32 crc32 | payload`` after an 8-byte magic), applied to a socket instead
of a file. The property this buys is identical: a torn frame (connection
killed mid-write, a ``torn_frame`` fault, a proxy truncating the stream)
is *detected* — length bound, CRC, pickle validation — never mis-parsed
into a plausible-but-wrong message. A reader that cannot validate a frame
raises :class:`WireError` and drops the connection; the index-based resume
in the SDK then replays exactly the frames the client never saw.

Layout per direction (both sides send the magic first, so each end can
fail fast on a non-SRNET peer)::

    SRNET/1\\n                          8-byte connection magic
    u32 LE length | u32 LE crc32 | payload   ... repeated frames

``length`` counts payload bytes only and is bounded by
``SR_NET_MAX_FRAME_MB`` (default 64 — a pushed frontier frame is a few KB;
submit frames carry the job's dataset). Payloads are pickles of plain
dicts; :func:`decode_message` rejects non-dict payloads. Pickle implies
the classic caveat: this protocol authenticates tenants, it does NOT
sandbox peers — run it on trusted networks (localhost, a pod's VPC), the
same trust domain the journal and the pod CoordStore already assume.

:class:`FrameDecoder` is incremental: feed it whatever ``recv`` returned —
half a header, three frames and a torn tail, one byte at a time — and it
yields exactly the complete payloads, keeping partial bytes buffered.
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib

__all__ = [
    "WIRE_MAGIC",
    "WireError",
    "FrameDecoder",
    "encode_frame",
    "encode_message",
    "decode_message",
    "max_frame_bytes",
]

WIRE_MAGIC = b"SRNET/1\n"  # 8 bytes, like JOURNAL_MAGIC
_HDR = struct.Struct("<II")  # payload length, crc32(payload)


class WireError(RuntimeError):
    """The byte stream violated the framing contract (oversized length
    header, CRC mismatch, bad magic, non-dict payload). Connection-fatal:
    after garbage there is no way to resynchronise a length-prefixed
    stream, so the peer must reconnect and resume by frame index."""


def max_frame_bytes() -> int:
    """Frame payload bound (``SR_NET_MAX_FRAME_MB``, default 64). A length
    header past this is treated as corruption, exactly like the journal's
    ``_MAX_RECORD`` guard — it bounds how much a torn/garbage header can
    make a reader buffer before the CRC would catch it."""
    try:
        mb = float(os.environ.get("SR_NET_MAX_FRAME_MB", "64"))
    except ValueError:
        mb = 64.0
    return int(mb * (1 << 20))


def encode_frame(payload: bytes) -> bytes:
    """One wire frame around raw payload bytes."""
    if len(payload) > max_frame_bytes():
        raise WireError(
            f"frame payload {len(payload)} bytes exceeds "
            f"SR_NET_MAX_FRAME_MB={max_frame_bytes() >> 20}"
        )
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def encode_message(msg: dict) -> bytes:
    """Pickle a message dict and frame it."""
    return encode_frame(pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL))


def decode_message(payload: bytes) -> dict:
    """Unpickle a frame payload; :class:`WireError` on anything that is
    not a pickled dict (a CRC collision or a non-protocol peer)."""
    try:
        msg = pickle.loads(payload)
    except Exception as exc:  # noqa: BLE001 — any unpickle failure is protocol garbage
        raise WireError(f"undecodable frame payload: {exc!r}") from exc
    if not isinstance(msg, dict):
        raise WireError(f"frame payload is {type(msg).__name__}, expected dict")
    return msg


class FrameDecoder:
    """Incremental frame reassembler for one connection direction.

    ``feed(data)`` returns the list of complete payloads the new bytes
    finish (possibly empty); incomplete trailing bytes stay buffered for
    the next feed. Interleaved partial reads therefore cost nothing, and a
    stream that ENDS mid-frame simply never completes that frame — the
    torn-tail analogue of journal replay's truncation. Corruption that can
    be proven (length header over the bound, CRC mismatch) raises
    :class:`WireError` immediately.
    """

    def __init__(self, max_bytes: int | None = None):
        self._buf = bytearray()
        self._max = max_frame_bytes() if max_bytes is None else int(max_bytes)

    @property
    def buffered(self) -> int:
        """Bytes held waiting for the rest of a frame."""
        return len(self._buf)

    def feed(self, data: bytes) -> list[bytes]:
        self._buf.extend(data)
        out: list[bytes] = []
        while True:
            if len(self._buf) < _HDR.size:
                return out
            length, crc = _HDR.unpack_from(self._buf)
            if length > self._max:
                raise WireError(
                    f"frame length header {length} exceeds {self._max} bytes "
                    "(corrupt or hostile stream)"
                )
            end = _HDR.size + length
            if len(self._buf) < end:
                return out
            payload = bytes(self._buf[_HDR.size : end])
            if zlib.crc32(payload) != crc:
                raise WireError(
                    f"frame CRC mismatch over {length}-byte payload"
                )
            del self._buf[:end]
            out.append(payload)

    def feed_messages(self, data: bytes) -> list[dict]:
        """feed() + decode_message() per completed frame."""
        return [decode_message(p) for p in self.feed(data)]
