"""Client SDK for the network front door: sync ``SRClient`` and asyncio
``AsyncSRClient``.

Both speak the framed protocol of ``serve/net/wire.py`` and share one
resume discipline: every subscribed stream tracks the next frame *index*
it expects, so a dropped connection (server shed us as slow, a
``torn_frame``/``net_drop`` fault, a mid-frame kill) is survivable by
reconnecting and re-subscribing ``from index`` — the server replays the
stored suffix and the client drops nothing and double-delivers nothing.
A torn frame on the wire is detected by the CRC codec (:class:`WireError`)
and treated exactly like a dropped connection.

Boot identity: frame indices are meaningful within one server process.
The hello response carries the server's ``boot`` id; when a reconnect
lands on a *different* boot (the server crashed and journal-recovered),
in-flight stream indices are reset to 0 — the recovered job re-emits
frames from its resume point, and ``_Stream.boots`` counts the restarts
so callers can tell a resumed stream from an uninterrupted one.

The sync client is thread-safe: a background reader thread demultiplexes
rid-keyed responses and pushed frames; any number of caller threads can
submit/wait/iterate concurrently. ``iter_frames`` yields every delivered
frame exactly once and ends at the job's terminal push.

The ``slow_client`` fault site fires in the reader loop (a client that
stops draining its socket) so the server's shed-don't-buffer policy can
be drilled end to end.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import pickle
import socket
import threading
import time

from ...utils import faults
from .wire import WIRE_MAGIC, FrameDecoder, WireError, encode_message

__all__ = [
    "SRClient",
    "AsyncSRClient",
    "NetError",
    "AuthError",
    "RemoteError",
    "RetryableWireError",
    "ConnectionLost",
]


class NetError(RuntimeError):
    """Base class for SDK failures."""


class AuthError(NetError):
    """The server rejected our token — not retryable."""


class ConnectionLost(NetError):
    """The connection died and could not be (or was not) re-established."""


class RetryableWireError(NetError):
    """The server shed this request (overload / connection cap); retry
    after ``retry_after_s``."""

    def __init__(self, detail: str, retry_after_s: float):
        super().__init__(detail or "server overloaded")
        self.retry_after_s = float(retry_after_s)


class RemoteError(NetError):
    """A non-retryable error response (``error`` + ``detail``)."""

    def __init__(self, error: str, detail: str):
        super().__init__(f"{error}: {detail}")
        self.error = error
        self.detail = detail


def _env_ms(name: str, default: int) -> float:
    try:
        return float(os.environ.get(name, "") or default) / 1000.0
    except ValueError:
        return default / 1000.0


def _raise_for(resp: dict) -> dict:
    if resp.get("ok"):
        return resp
    error = str(resp.get("error") or "error")
    detail = str(resp.get("detail") or "")
    if resp.get("retryable"):
        raise RetryableWireError(detail, float(resp.get("retry_after_s", 0.5)))
    if error == "auth":
        raise AuthError(detail or "unknown token")
    if error == "unknown_job":
        raise KeyError(detail or "unknown job")
    raise RemoteError(error, detail)


class _Stream:
    """Per-job receive state: ``frames`` is the exactly-once delivery
    buffer, ``next_index`` the first server-side index not yet received."""

    def __init__(self, start: int = 0):
        self.next_index = start
        self.frames: list[bytes] = []
        self.terminal: dict | None = None
        self.boots = 0  # server restarts observed mid-stream
        self.dup_dropped = 0


class _Waiter:
    __slots__ = ("event", "resp", "exc")

    def __init__(self):
        self.event = threading.Event()
        self.resp: dict | None = None
        self.exc: BaseException | None = None


class SRClient:
    """Synchronous SDK client.

    Usage::

        with SRClient("127.0.0.1", port, token="tok") as cli:
            job = cli.submit(spec)
            for frame in cli.iter_frames(job):
                update = cli.decode_frame(frame)
            summary = cli.wait(job, timeout=120)

    ``auto_reconnect`` (default True) makes dropped connections invisible
    to stream consumers: the reader thread re-dials with exponential
    backoff (``SR_NET_RECONNECT_MS``/``SR_NET_RECONNECT_MAX_MS``, up to
    ``reconnect_deadline_s`` per outage) and re-subscribes every live
    stream from its next frame index.
    """

    def __init__(
        self,
        host: str,
        port: int,
        token: str | None = None,
        tenant: str | None = None,
        connect_timeout: float = 10.0,
        request_timeout: float = 120.0,
        auto_reconnect: bool = True,
        reconnect_deadline_s: float = 60.0,
    ):
        self.host = host
        self.port = int(port)
        self.token = token
        self.tenant = tenant
        self.connect_timeout = float(connect_timeout)
        self.request_timeout = float(request_timeout)
        self.auto_reconnect = bool(auto_reconnect)
        self.reconnect_deadline_s = float(reconnect_deadline_s)
        self.boot: str | None = None
        self._cond = threading.Condition()
        self._wlock = threading.Lock()
        self._sock: socket.socket | None = None
        self._decoder: FrameDecoder | None = None
        self._pending: dict[int, _Waiter] = {}
        self._streams: dict[str, _Stream] = {}
        self._rid = 0
        self._closed = False
        self._dead = False  # reconnect gave up — terminal for this client
        self._connected = False
        self._reconnects = 0
        self._reader: threading.Thread | None = None
        self._establish()
        self._reader = threading.Thread(
            target=self._reader_loop, name="sr-net-client", daemon=True
        )
        self._reader.start()

    # -- lifecycle -------------------------------------------------------------
    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._connected = False
            self._fail_pending(ConnectionLost("client closed"))
            sock = self._sock
            self._cond.notify_all()
        if sock is not None:
            with contextlib.suppress(OSError):
                sock.shutdown(socket.SHUT_RDWR)
            with contextlib.suppress(OSError):
                sock.close()
        if self._reader is not None and self._reader is not threading.current_thread():
            self._reader.join(timeout=5.0)

    def __enter__(self) -> "SRClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def connected(self) -> bool:
        return self._connected

    @property
    def reconnects(self) -> int:
        return self._reconnects

    def stream_state(self, job_id: str) -> _Stream:
        """The receive state for a subscribed job (drill assertions:
        ``next_index``, ``boots``, ``dup_dropped``)."""
        with self._cond:
            return self._streams[job_id]

    # -- connection plumbing ---------------------------------------------------
    def _establish(self) -> None:
        """Dial + magic exchange + hello; on success swap in the new
        socket and re-subscribe live streams from their next index."""
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout
        )
        try:
            sock.sendall(
                WIRE_MAGIC
                + encode_message(
                    {
                        "op": "hello",
                        "rid": 0,
                        "token": self.token,
                        "tenant": self.tenant,
                    }
                )
            )
            magic = self._recv_exact(sock, len(WIRE_MAGIC))
            if magic != WIRE_MAGIC:
                raise NetError(f"peer is not an SRNET server (got {magic!r})")
            decoder = FrameDecoder()
            msgs: list[dict] = []
            while not msgs:
                data = sock.recv(1 << 16)
                if not data:
                    raise ConnectionLost("server closed during hello")
                msgs = decoder.feed_messages(data)
            hello = _raise_for(msgs[0])
        except BaseException:
            with contextlib.suppress(OSError):
                sock.close()
            raise
        sock.settimeout(None)
        resubscribe: list[tuple[str, int]] = []
        with self._cond:
            self._sock = sock
            self._decoder = decoder
            prev_boot = self.boot
            self.boot = hello.get("boot")
            self.tenant = hello.get("tenant", self.tenant)
            if prev_boot is not None and prev_boot != self.boot:
                # server restarted: its frame indices start over
                for st in self._streams.values():
                    if st.terminal is None:
                        st.next_index = 0
                        st.boots += 1
            self._connected = True
            for job_id, st in self._streams.items():
                if st.terminal is None:
                    resubscribe.append((job_id, st.next_index))
            self._cond.notify_all()
        for job_id, start in resubscribe:
            # fire-and-forget: the response rid has no waiter and is dropped
            with contextlib.suppress(ConnectionLost):
                self._send_msg(
                    {
                        "op": "subscribe",
                        "rid": self._next_rid(),
                        "job": job_id,
                        "start": start,
                    }
                )

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionLost("server closed during handshake")
            buf += chunk
        return buf

    def _next_rid(self) -> int:
        with self._cond:
            self._rid += 1
            return self._rid

    def _fail_pending(self, exc: BaseException) -> None:
        # caller holds self._cond
        for waiter in self._pending.values():
            waiter.exc = exc
            waiter.event.set()
        self._pending.clear()

    def _reader_loop(self) -> None:
        while True:
            with self._cond:
                if self._closed or self._dead:
                    return
                sock = self._sock
                decoder = self._decoder
            hit = faults.active().fire("slow_client")
            if hit is not None:  # a client that stops draining its socket
                time.sleep(float(hit.get("delay_ms", 1000)) / 1000.0)
            try:
                data = sock.recv(1 << 16) if sock is not None else b""
            except OSError:
                data = b""
            if not data:
                if not self._handle_disconnect():
                    return
                continue
            try:
                msgs = decoder.feed_messages(data)
            except WireError:
                # torn/corrupt stream — same recovery as a dropped conn:
                # reconnect and resume every stream by index
                if not self._handle_disconnect():
                    return
                continue
            for msg in msgs:
                self._on_message(msg)

    def _handle_disconnect(self) -> bool:
        """Reconnect with backoff; returns False when the reader should
        exit (closed, no auto-reconnect, or the deadline ran out)."""
        with self._cond:
            self._connected = False
            sock = self._sock
            self._sock = None
            self._fail_pending(ConnectionLost("connection lost"))
            self._cond.notify_all()
            if self._closed:
                return False
        if sock is not None:
            with contextlib.suppress(OSError):
                sock.close()
        if not self.auto_reconnect:
            self._mark_dead()
            return False
        deadline = time.monotonic() + self.reconnect_deadline_s
        interval = _env_ms("SR_NET_RECONNECT_MS", 100)
        cap = _env_ms("SR_NET_RECONNECT_MAX_MS", 3000)
        while True:
            with self._cond:
                if self._closed:
                    return False
            try:
                self._establish()
                self._reconnects += 1
                return True
            except AuthError:
                self._mark_dead()
                return False
            except (OSError, NetError):
                now = time.monotonic()
                if now >= deadline:
                    self._mark_dead()
                    return False
                time.sleep(min(interval, deadline - now))
                interval = min(interval * 2.0, cap)

    def _mark_dead(self) -> None:
        with self._cond:
            self._dead = True
            self._fail_pending(ConnectionLost("reconnect gave up"))
            self._cond.notify_all()

    def _on_message(self, msg: dict) -> None:
        push = msg.get("push")
        if push is None:
            with self._cond:
                waiter = self._pending.pop(msg.get("rid"), None)
            if waiter is not None:
                waiter.resp = msg
                waiter.event.set()
            return
        job_id = msg.get("job")
        resync_from: int | None = None
        with self._cond:
            st = self._streams.get(job_id)
            if st is None:
                return
            if push == "frame":
                idx = msg.get("index")
                if idx != st.next_index:
                    # behind our cursor = replay overlap → drop (the
                    # exactly-once half of resume); ahead = a gap we can
                    # close by re-subscribing from our cursor
                    if isinstance(idx, int) and idx > st.next_index:
                        resync_from = st.next_index
                    else:
                        st.dup_dropped += 1
                else:
                    st.frames.append(msg.get("frame"))
                    st.next_index += 1
                    self._cond.notify_all()
            elif push == "terminal":
                st.terminal = msg.get("summary") or {}
                self._cond.notify_all()
        if resync_from is not None:  # send outside the cond (lock order)
            self._resync(job_id, resync_from)

    def _resync(self, job_id: str, start: int) -> None:
        with contextlib.suppress(NetError, OSError):
            self._send_msg(
                {"op": "subscribe", "rid": self._next_rid(), "job": job_id,
                 "start": start}
            )

    def _send_msg(self, msg: dict) -> None:
        with self._wlock:
            with self._cond:
                sock = self._sock if self._connected else None
            if sock is None:
                raise ConnectionLost("not connected")
            try:
                sock.sendall(encode_message(msg))
            except OSError as exc:
                raise ConnectionLost(str(exc)) from exc

    def _await_connected(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        with self._cond:
            while not self._connected:
                if self._closed or self._dead:
                    raise ConnectionLost("client is closed or gave up")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionLost(f"not connected after {timeout}s")
                self._cond.wait(min(0.1, remaining))

    def _request(self, msg: dict, timeout: float | None = None) -> dict:
        timeout = self.request_timeout if timeout is None else float(timeout)
        self._await_connected(min(timeout, self.reconnect_deadline_s))
        rid = self._next_rid()
        msg["rid"] = rid
        waiter = _Waiter()
        with self._cond:
            self._pending[rid] = waiter
        try:
            self._send_msg(msg)
            if not waiter.event.wait(timeout):
                raise NetError(
                    f"timeout ({timeout}s) waiting for {msg.get('op')!r} response"
                )
        finally:
            with self._cond:
                self._pending.pop(rid, None)
        if waiter.exc is not None:
            raise waiter.exc
        return _raise_for(waiter.resp or {})

    # -- public API ------------------------------------------------------------
    def ping(self) -> dict:
        return self._request({"op": "ping"}, timeout=10.0)

    def stats(self) -> dict:
        return self._request({"op": "stats"})

    def submit(self, spec, retries: int = 0) -> str:
        """Submit a JobSpec (pickled client-side); returns the job id.
        ``retries`` > 0 honors the server's retry-after hint on
        ``RetryableWireError`` before giving up."""
        payload = (
            bytes(spec)
            if isinstance(spec, (bytes, bytearray))
            else pickle.dumps(spec, protocol=pickle.HIGHEST_PROTOCOL)
        )
        attempt = 0
        while True:
            try:
                return self._request({"op": "submit", "spec": payload})["job"]
            except RetryableWireError as exc:
                if attempt >= retries:
                    raise
                attempt += 1
                time.sleep(max(0.01, exc.retry_after_s))

    def status(self, job_id: str) -> dict:
        return self._request({"op": "status", "job": job_id})["summary"]

    def cancel(self, job_id: str) -> None:
        self._request({"op": "cancel", "job": job_id})

    def wait(self, job_id: str, timeout: float = 300.0) -> dict:
        """Block (server-side) until the job is terminal; returns the
        summary. Raises TimeoutError if it is still running at timeout."""
        resp = self._request(
            {"op": "wait", "job": job_id, "timeout": timeout},
            timeout=timeout + 30.0,
        )
        if resp.get("timed_out"):
            raise TimeoutError(f"{job_id} not terminal in {timeout}s")
        return resp["summary"]

    def frames(self, job_id: str, start: int = 0) -> list[bytes]:
        return self._request({"op": "frames", "job": job_id, "start": start})[
            "frames"
        ]

    def push_rows(self, job_id: str, X, y, weights=None) -> None:
        self._request(
            {"op": "push_rows", "job": job_id, "X": X, "y": y, "weights": weights}
        )

    def replace_rows(self, job_id: str, X, y, weights=None) -> None:
        self._request(
            {"op": "replace_rows", "job": job_id, "X": X, "y": y,
             "weights": weights}
        )

    def subscribe(self, job_id: str, start: int = 0) -> _Stream:
        """Start (or resume) the pushed frame stream for a job."""
        with self._cond:
            st = self._streams.get(job_id)
            if st is None:
                st = _Stream(start)
                self._streams[job_id] = st
        self._request({"op": "subscribe", "job": job_id, "start": st.next_index})
        return st

    def unsubscribe(self, job_id: str) -> None:
        with self._cond:
            self._streams.pop(job_id, None)
        with contextlib.suppress(NetError):
            self._request({"op": "unsubscribe", "job": job_id}, timeout=10.0)

    def iter_frames(self, job_id: str, timeout: float | None = None):
        """Generator over a job's pushed frames — every delivered frame
        exactly once, ending after the terminal push. Auto-subscribes.
        Survives reconnects transparently; raises :class:`ConnectionLost`
        only when the reconnect loop gave up."""
        with self._cond:
            subscribed = job_id in self._streams
        if not subscribed:
            self.subscribe(job_id)
        deadline = None if timeout is None else time.monotonic() + timeout
        i = 0
        while True:
            with self._cond:
                st = self._streams[job_id]
                while len(st.frames) <= i and st.terminal is None:
                    if self._closed or self._dead:
                        raise ConnectionLost("stream interrupted and not recovered")
                    remaining = (
                        None if deadline is None else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError(
                            f"no frame for {job_id} within {timeout}s"
                        )
                    self._cond.wait(
                        0.5 if remaining is None else min(0.5, remaining)
                    )
                batch = st.frames[i:]
                done = st.terminal is not None and i + len(batch) >= len(st.frames)
            for frame in batch:
                yield frame
            i += len(batch)
            if done:
                return

    def terminal_summary(self, job_id: str) -> dict | None:
        """The pushed terminal summary for a subscribed job, if any."""
        with self._cond:
            st = self._streams.get(job_id)
            return None if st is None else st.terminal

    @staticmethod
    def decode_frame(frame: bytes):
        """Decode format-2 frontier bytes into a FrontierUpdate."""
        from ...utils.checkpoint import load_frontier_bytes

        return load_frontier_bytes(frame)


class AsyncSRClient:
    """Asyncio variant of :class:`SRClient` — same protocol, same
    index-based resume; awaitable API plus an async-iterator frame stream.

    Usage::

        cli = await AsyncSRClient.connect("127.0.0.1", port)
        job = await cli.submit(spec)
        async for frame in cli.iter_frames(job):
            ...
        await cli.close()
    """

    def __init__(
        self,
        host: str,
        port: int,
        token: str | None = None,
        tenant: str | None = None,
        connect_timeout: float = 10.0,
        request_timeout: float = 120.0,
        auto_reconnect: bool = True,
        reconnect_deadline_s: float = 60.0,
    ):
        self.host = host
        self.port = int(port)
        self.token = token
        self.tenant = tenant
        self.connect_timeout = float(connect_timeout)
        self.request_timeout = float(request_timeout)
        self.auto_reconnect = bool(auto_reconnect)
        self.reconnect_deadline_s = float(reconnect_deadline_s)
        self.boot: str | None = None
        self.reconnects = 0
        self._reader_sock = None  # (StreamReader, StreamWriter)
        self._writer = None
        self._pending: dict[int, "asyncio.Future"] = {}
        self._streams: dict[str, _Stream] = {}
        self._changed: "asyncio.Event | None" = None
        self._rid = 0
        self._closed = False
        self._dead = False
        self._connected = False
        self._reader_task = None

    @classmethod
    async def connect(cls, host: str, port: int, **kw) -> "AsyncSRClient":
        self = cls(host, port, **kw)
        self._changed = asyncio.Event()
        await self._establish()
        self._reader_task = asyncio.create_task(self._reader_loop())
        return self

    async def close(self) -> None:
        self._closed = True
        self._connected = False
        if self._reader_task is not None:
            self._reader_task.cancel()
            with contextlib.suppress(BaseException):
                await self._reader_task
        if self._writer is not None:
            with contextlib.suppress(Exception):
                self._writer.close()
        self._fail_pending(ConnectionLost("client closed"))

    async def _establish(self) -> None:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), self.connect_timeout
        )
        writer.write(
            WIRE_MAGIC
            + encode_message(
                {"op": "hello", "rid": 0, "token": self.token,
                 "tenant": self.tenant}
            )
        )
        await writer.drain()
        magic = await asyncio.wait_for(
            reader.readexactly(len(WIRE_MAGIC)), self.connect_timeout
        )
        if magic != WIRE_MAGIC:
            writer.close()
            raise NetError(f"peer is not an SRNET server (got {magic!r})")
        decoder = FrameDecoder()
        msgs: list[dict] = []
        while not msgs:
            data = await asyncio.wait_for(reader.read(1 << 16), self.connect_timeout)
            if not data:
                writer.close()
                raise ConnectionLost("server closed during hello")
            msgs = decoder.feed_messages(data)
        try:
            hello = _raise_for(msgs[0])
        except BaseException:
            writer.close()
            raise
        prev_boot = self.boot
        self.boot = hello.get("boot")
        self.tenant = hello.get("tenant", self.tenant)
        if prev_boot is not None and prev_boot != self.boot:
            for st in self._streams.values():
                if st.terminal is None:
                    st.next_index = 0
                    st.boots += 1
        self._reader_sock = (reader, decoder)
        self._writer = writer
        self._connected = True
        for job_id, st in self._streams.items():
            if st.terminal is None:
                await self._send(
                    {"op": "subscribe", "rid": self._next_rid(), "job": job_id,
                     "start": st.next_index}
                )
        self._wake()

    def _wake(self) -> None:
        if self._changed is not None:
            self._changed.set()

    def _next_rid(self) -> int:
        self._rid += 1
        return self._rid

    def _fail_pending(self, exc: BaseException) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    async def _reader_loop(self) -> None:
        while not self._closed and not self._dead:
            reader, decoder = self._reader_sock
            try:
                data = await reader.read(1 << 16)
            except (ConnectionError, OSError):
                data = b""
            if not data:
                if not await self._handle_disconnect():
                    return
                continue
            try:
                msgs = decoder.feed_messages(data)
            except WireError:
                if not await self._handle_disconnect():
                    return
                continue
            for msg in msgs:
                self._on_message(msg)

    async def _handle_disconnect(self) -> bool:
        self._connected = False
        if self._writer is not None:
            with contextlib.suppress(Exception):
                self._writer.close()
        self._fail_pending(ConnectionLost("connection lost"))
        self._wake()
        if self._closed or not self.auto_reconnect:
            self._dead = True
            return False
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.reconnect_deadline_s
        interval = _env_ms("SR_NET_RECONNECT_MS", 100)
        cap = _env_ms("SR_NET_RECONNECT_MAX_MS", 3000)
        while not self._closed:
            try:
                await self._establish()
                self.reconnects += 1
                return True
            except AuthError:
                break
            except (OSError, NetError, asyncio.TimeoutError):
                now = loop.time()
                if now >= deadline:
                    break
                await asyncio.sleep(min(interval, deadline - now))
                interval = min(interval * 2.0, cap)
        self._dead = True
        self._fail_pending(ConnectionLost("reconnect gave up"))
        self._wake()
        return False

    def _on_message(self, msg: dict) -> None:
        push = msg.get("push")
        if push is None:
            fut = self._pending.pop(msg.get("rid"), None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return
        st = self._streams.get(msg.get("job"))
        if st is None:
            return
        if push == "frame":
            idx = msg.get("index")
            if idx != st.next_index:
                if isinstance(idx, int) and idx < st.next_index:
                    st.dup_dropped += 1
                return
            st.frames.append(msg.get("frame"))
            st.next_index += 1
        elif push == "terminal":
            st.terminal = msg.get("summary") or {}
        self._wake()

    async def _send(self, msg: dict) -> None:
        if not self._connected or self._writer is None:
            raise ConnectionLost("not connected")
        try:
            self._writer.write(encode_message(msg))
            await self._writer.drain()
        except (ConnectionError, OSError) as exc:
            raise ConnectionLost(str(exc)) from exc

    async def _request(self, msg: dict, timeout: float | None = None) -> dict:
        timeout = self.request_timeout if timeout is None else float(timeout)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + min(timeout, self.reconnect_deadline_s)
        while not self._connected:
            if self._closed or self._dead or loop.time() >= deadline:
                raise ConnectionLost("not connected")
            self._changed.clear()
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._changed.wait(), 0.1)
        rid = self._next_rid()
        msg["rid"] = rid
        fut = loop.create_future()
        self._pending[rid] = fut
        try:
            await self._send(msg)
            resp = await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(rid, None)
        return _raise_for(resp)

    # -- public API ------------------------------------------------------------
    async def ping(self) -> dict:
        return await self._request({"op": "ping"}, timeout=10.0)

    async def stats(self) -> dict:
        return await self._request({"op": "stats"})

    async def submit(self, spec, retries: int = 0) -> str:
        payload = (
            bytes(spec)
            if isinstance(spec, (bytes, bytearray))
            else pickle.dumps(spec, protocol=pickle.HIGHEST_PROTOCOL)
        )
        attempt = 0
        while True:
            try:
                resp = await self._request({"op": "submit", "spec": payload})
                return resp["job"]
            except RetryableWireError as exc:
                if attempt >= retries:
                    raise
                attempt += 1
                await asyncio.sleep(max(0.01, exc.retry_after_s))

    async def status(self, job_id: str) -> dict:
        return (await self._request({"op": "status", "job": job_id}))["summary"]

    async def cancel(self, job_id: str) -> None:
        await self._request({"op": "cancel", "job": job_id})

    async def wait(self, job_id: str, timeout: float = 300.0) -> dict:
        resp = await self._request(
            {"op": "wait", "job": job_id, "timeout": timeout},
            timeout=timeout + 30.0,
        )
        if resp.get("timed_out"):
            raise TimeoutError(f"{job_id} not terminal in {timeout}s")
        return resp["summary"]

    async def frames(self, job_id: str, start: int = 0) -> list[bytes]:
        resp = await self._request(
            {"op": "frames", "job": job_id, "start": start}
        )
        return resp["frames"]

    async def push_rows(self, job_id: str, X, y, weights=None) -> None:
        await self._request(
            {"op": "push_rows", "job": job_id, "X": X, "y": y, "weights": weights}
        )

    async def replace_rows(self, job_id: str, X, y, weights=None) -> None:
        await self._request(
            {"op": "replace_rows", "job": job_id, "X": X, "y": y,
             "weights": weights}
        )

    async def subscribe(self, job_id: str, start: int = 0) -> _Stream:
        st = self._streams.get(job_id)
        if st is None:
            st = _Stream(start)
            self._streams[job_id] = st
        await self._request(
            {"op": "subscribe", "job": job_id, "start": st.next_index}
        )
        return st

    async def iter_frames(self, job_id: str, timeout: float | None = None):
        if job_id not in self._streams:
            await self.subscribe(job_id)
        loop = asyncio.get_running_loop()
        deadline = None if timeout is None else loop.time() + timeout
        i = 0
        while True:
            st = self._streams[job_id]
            while len(st.frames) <= i and st.terminal is None:
                if self._closed or self._dead:
                    raise ConnectionLost("stream interrupted and not recovered")
                remaining = None if deadline is None else deadline - loop.time()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(f"no frame for {job_id} within {timeout}s")
                self._changed.clear()
                with contextlib.suppress(asyncio.TimeoutError):
                    await asyncio.wait_for(
                        self._changed.wait(),
                        0.5 if remaining is None else min(0.5, remaining),
                    )
            batch = st.frames[i:]
            done = st.terminal is not None and i + len(batch) >= len(st.frames)
            for frame in batch:
                yield frame
            i += len(batch)
            if done:
                return
