"""SR-as-a-service: a long-lived multi-tenant search daemon.

One resident process owns the device mesh and multiplexes many concurrent
``equation_search`` jobs over it with a pool of worker threads. The engine's
compiled programs are dataset-independent (device_search.py keys them on
shapes + config, never data), so every job that lands in an already-seen
shape bucket skips the ~50s compile and runs at the ~2s warm rate (r04) —
the server's whole job is to keep that cache hot:

- **admission** (queue.py): priority, then warm-bucket affinity, then FIFO,
  under per-tenant concurrency quotas;
- **budgets**: per-job wall-clock deadline (from submit; enforced while
  queued AND while running, via the engine's own timeout stop) and eval
  budget (``max_evals``);
- **streaming**: after each iteration the search's live Pareto frontier is
  encoded with the format-2 flat checkpoint codec
  (``utils/checkpoint.dump_frontier_bytes``) and appended to the job's frame
  list — the wire format clients decode with ``load_frontier_bytes``;
- **preemption**: a higher-priority submission marks the lowest-priority
  running job; its iteration callback stops the search cooperatively at the
  next boundary, the server snapshots a format-2 checkpoint into the spool,
  and the job re-enters the queue — the next admission passes
  ``resume_from`` so the search warm-starts over its REMAINING iterations;
- **warm restarts**: ``enable_persistent_compilation_cache`` wires jax's
  on-disk XLA cache (``SR_COMPILATION_CACHE_DIR``), so even a restarted
  server re-materializes executables from disk instead of recompiling;
- **fleet coalescing** (opt-in, ``fleet=True``): a worker that pops a
  fleet-eligible job gathers up to ``SR_FLEET_MAX - 1`` (default 8 lanes
  total) further queued jobs from the SAME shape bucket — waiting up to
  ``SR_FLEET_WINDOW_S`` (default 0.05s) for stragglers — and runs them as
  ONE vmapped megaprogram via ``models.device_search.fleet_search``: N
  searches per iteration for a solo search's <=2 dispatches. Each job keeps
  its own frontier stream (frames demux from the stacked hall of fame),
  stop conditions, and terminal state; cancel/preempt evicts a single lane
  (the lane freezes under the fleet mask, survivors drain unchanged).
  Deadline-bearing jobs and preemption resumes bypass coalescing and run
  solo;
- **durability + self-healing** (opt-in, ``journal_dir=`` /
  ``SR_SERVE_JOURNAL_DIR``): every job transition is appended to a
  write-ahead ``JobJournal`` (journal.py) and running lanes snapshot into
  the spool every ``SR_SERVE_CKPT_EVERY_S`` via the engine's own
  checkpointer, so a crashed/killed server restarted on the same
  ``journal_dir`` resubmits its queue and RESUMES its running jobs instead
  of losing them. Failed runs retry with exponential backoff up to
  ``SR_JOB_RETRIES`` then terminate QUARANTINED; a supervisor thread
  restarts dead workers and a ``SR_JOB_STALL_S`` watchdog stops+retries
  runs whose iteration heartbeat froze; ``SR_QUEUE_MAX_DEPTH`` sheds
  submits with ``ServerOverloaded`` under sustained overload. All of it is
  inert (no locks, no I/O) when the journal is off;
- **subscriptions** (``kind="subscription"``): deadline-less streaming
  jobs backed by ``stream.StreamSession`` — the worker drives a long-lived
  lane whose dataset updates live (``push_rows``/``replace_rows``, zero
  recompiles within the row bucket) and whose frontier frames flow through
  the same frame channel until the client ``cancel()``s (terminal DONE,
  final result attached).

The server is in-process by design (the engine is a Python library; remote
transport is a thin shell over ``submit``/``frames``/``result`` and out of
scope here) — but every interaction goes through the queue's lock and the
jobs' events, so a transport can drive it from any thread.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import os
import pickle
import shutil
import tempfile
import threading
import time
import traceback as _tbmod

from . import queue as q
from .program_cache import (
    enable_persistent_compilation_cache,
    global_program_cache,
    is_oom_error,
)
from .journal import JournalDiskFull
from .queue import Job, JobQueue, JobSpec, ServerOverloaded

__all__ = ["SearchServer", "JobSpec", "ServerOverloaded"]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _format_error(e: BaseException) -> str:
    return f"{type(e).__name__}: {e}"


def _format_traceback(
    e: BaseException, limit: int = 25, max_chars: int = 8192
) -> str:
    """Bounded formatted traceback: deep enough to debug a failed job,
    capped so a quarantined job cannot bloat summaries or the journal."""
    tb = "".join(
        _tbmod.format_exception(type(e), e, e.__traceback__, limit=limit)
    )
    return tb[-max_chars:]


class SearchServer:
    """Multi-tenant search daemon. Typical use::

        with SearchServer(max_concurrency=2) as srv:
            jid = srv.submit(JobSpec(X, y, options=opts, niterations=5,
                                     tenant="acme", priority=1))
            job = srv.wait(jid, timeout=300)
            for frame in srv.frames(jid):
                update = load_frontier_bytes(frame)   # streaming client side
            result = job.result                        # SearchResult

    ``max_concurrency`` bounds concurrently RUNNING searches (worker
    threads); per-tenant quotas bound each tenant's share of those slots.
    """

    def __init__(
        self,
        max_concurrency: int = 2,
        default_quota: int = 2,
        quotas: dict | None = None,
        spool_dir: str | None = None,
        compilation_cache_dir: str | None = None,
        poll_seconds: float = 0.2,
        fleet: bool = False,
        fleet_max: int | None = None,
        fleet_window_s: float | None = None,
        journal_dir: str | None = None,
        ckpt_every_s: float | None = None,
        job_retries: int | None = None,
        retry_backoff_s: float | None = None,
        stall_seconds: float | None = None,
        queue_max_depth: int | None = None,
    ):
        if max_concurrency < 1:
            raise ValueError("max_concurrency must be >= 1")
        self.max_concurrency = int(max_concurrency)
        self.poll_seconds = float(poll_seconds)
        # -- durability / self-healing knobs (r15) --
        self.journal_dir = journal_dir or os.environ.get(
            "SR_SERVE_JOURNAL_DIR"
        ) or None
        self.ckpt_every_s = (
            _env_float("SR_SERVE_CKPT_EVERY_S", 30.0)
            if ckpt_every_s is None
            else float(ckpt_every_s)
        )
        self.job_retries = (
            _env_int("SR_JOB_RETRIES", 2)
            if job_retries is None
            else int(job_retries)
        )
        self.retry_backoff_s = (
            _env_float("SR_JOB_RETRY_BACKOFF_S", 0.25)
            if retry_backoff_s is None
            else float(retry_backoff_s)
        )
        self.stall_s = (
            _env_float("SR_JOB_STALL_S", 0.0)
            if stall_seconds is None
            else float(stall_seconds)
        )
        self.queue_max_depth = (
            _env_int("SR_QUEUE_MAX_DEPTH", 0)
            if queue_max_depth is None
            else int(queue_max_depth)
        )
        self.fleet = bool(fleet)
        self.fleet_max = (
            int(os.environ.get("SR_FLEET_MAX", "8"))
            if fleet_max is None
            else int(fleet_max)
        )
        if self.fleet and self.fleet_max < 2:
            raise ValueError("fleet_max must be >= 2 when fleet mode is on")
        self.fleet_window_s = (
            float(os.environ.get("SR_FLEET_WINDOW_S", "0.05"))
            if fleet_window_s is None
            else float(fleet_window_s)
        )
        self._fleet_batches = 0
        self._fleet_lanes = 0
        self._fleet_max_seen = 0
        self._fleet_deduped = 0
        self.cache = global_program_cache()
        self.compilation_cache_dir = enable_persistent_compilation_cache(
            compilation_cache_dir
        )
        # with a journal, the spool must survive restarts (the engine's
        # periodic snapshots there ARE the resume state) — default it into
        # the journal dir instead of a shutdown-deleted tempdir
        self._own_spool = spool_dir is None and self.journal_dir is None
        if spool_dir is None and self.journal_dir is not None:
            spool_dir = os.path.join(self.journal_dir, "spool")
        self.spool_dir = spool_dir or tempfile.mkdtemp(prefix="sr-serve-spool-")
        os.makedirs(self.spool_dir, exist_ok=True)
        self._queue = JobQueue(default_quota=default_quota, quotas=quotas)
        self._lock = threading.Lock()
        self._frame_cond = threading.Condition(self._lock)
        # Monotone counter bumped on every frame append / terminal
        # transition; wait_activity() lets a single external bridge thread
        # (e.g. the NetServer fan-out) sleep on ALL jobs at once instead of
        # polling each stream.
        self._activity = 0
        self._jobs: dict[str, Job] = {}
        self._running: dict[str, Job] = {}
        self._warm_buckets: set = set()
        self._seq = 0
        self._stop_event = threading.Event()
        self._workers: list[threading.Thread] = []
        self._supervisor: threading.Thread | None = None
        self._started = False
        self._retries = 0
        self._quarantined = 0
        self._shed = 0
        self._stalls = 0
        self._worker_restarts = 0
        # -- chaos-degradation telemetry (r19) --
        self._journal_shed = 0  # submits refused while the journal is read-only
        self._oom_downshifts = 0  # fleet batches halved/solo'd on compile OOM
        self._skew_suppressed = 0  # stall-watchdog passes suppressed on a
        #                            wall-clock jump (skew/NTP step)
        self._watch_clock = None  # (wall, monotonic) of the last watchdog pass
        self._admission_paused = threading.Event()
        self._recovered = {
            "queued": 0, "running": 0, "resumed": 0, "terminal": 0,
            "dropped": 0, "quarantined": 0,
        }
        self.journal = None
        if self.journal_dir:
            from .journal import JobJournal

            self.journal = JobJournal(self.journal_dir)
            self._recover()

    @property
    def _stopping(self) -> bool:
        return self._stop_event.is_set()

    # -- crash recovery --------------------------------------------------------
    def _recover(self) -> None:
        """Replay the journal and rebuild the job table: terminal jobs come
        back as queryable shells (reported exactly once, never rerun),
        queued/running searches re-enter the queue — a running job that left
        an engine/preempt checkpoint in the spool resumes over it via the
        same ``resume_from`` machinery preemption uses — and in-flight
        subscriptions finalize CANCELLED (a live stream cannot be resumed
        on behalf of a disconnected client)."""
        state = self.journal.replay()
        for job_id, st in sorted(state.items(), key=lambda kv: kv[1]["seq"]):
            self._seq = max(self._seq, int(st.get("seq", 0)))
            spec = None
            if st.get("spec") is not None:
                try:
                    spec = pickle.loads(st["spec"])
                except Exception:
                    spec = None
            if spec is None:
                self._recovered["dropped"] += 1
                continue
            job = Job(job_id, spec, seq=int(st["seq"]))
            job.submitted_at = float(st.get("submitted_at") or job.submitted_at)
            job.deadline_at = (
                None
                if spec.deadline_seconds is None
                else job.submitted_at + spec.deadline_seconds
            )
            job.attempts = int(st.get("attempts", 0))
            job.iterations_done = int(st.get("iterations_done", 0))
            job.not_before = float(st.get("not_before", 0.0))
            job.error = st.get("error")
            with self._lock:
                self._jobs[job_id] = job
            if st["state"] in q.TERMINAL_STATES:
                job.state = st["state"]
                job.finished_at = job.submitted_at
                job.done_event.set()
                self._recovered["terminal"] += 1
                continue
            if spec.kind != "search":
                # a subscription's stream died with the old process; its
                # client must resubscribe
                job.error = job.error or "server restarted mid-subscription"
                self._finalize(job, q.CANCELLED, release=False)
                self._recovered["terminal"] += 1
                continue
            if job.attempts > self.job_retries:
                # the retry budget is journaled (start/requeue records carry
                # the attempt counter): a poison job that takes the whole
                # server down must not re-enter with a fresh budget after
                # every restart — quarantine it here, exactly where
                # _retry_or_quarantine would have
                job.error = job.error or (
                    f"quarantined on recovery: {job.attempts} attempt(s) "
                    f"exceed SR_JOB_RETRIES={self.job_retries}"
                )
                with self._lock:
                    self._quarantined += 1
                self._finalize(job, q.QUARANTINED, release=False)
                self._recovered["quarantined"] += 1
                continue
            was_running = st["state"] == "running"
            if self._adopt_checkpoint(job, st.get("ckpt")):
                self._recovered["resumed"] += 1
            self._recovered["running" if was_running else "queued"] += 1
            if was_running:
                # flip the journal's view back to queued (with the adopted
                # checkpoint) so a second crash before this job runs again
                # still recovers it
                self._jappend(
                    "requeue", job.id, attempts=job.attempts,
                    not_before=0.0, ckpt=job.resume_path,
                )
            self._queue.submit(job)
        self.journal.rotate()

    def _adopt_checkpoint(self, job: Job, recorded: str | None) -> bool:
        """Point ``job.resume_path`` at the freshest usable spool snapshot:
        the engine's periodic checkpoint base first (newest ``.NNNNNN``
        wins), then the journal-recorded path, then a preemption snapshot.
        Also decides the resume REPORTING mode: an exact lockstep snapshot
        resumes bit-exact and reports ABSOLUTE iterations (base 0), anything
        else warm-starts over the remainder and reports run-relative."""
        from ..utils.checkpoint import peek_checkpoint_meta

        candidates = [os.path.join(self.spool_dir, f"{job.id}.engine")]
        if recorded:
            candidates.append(recorded)
        candidates.append(os.path.join(self.spool_dir, f"{job.id}.ckpt"))
        seen = set()
        for cand in candidates:
            if not cand or cand in seen:
                continue
            seen.add(cand)
            try:
                meta = peek_checkpoint_meta(cand)
            except Exception:
                continue
            job.resume_path = meta["path"]
            job.resumed_from_iteration = int(meta["iteration"])
            job.iterations_done = max(
                job.iterations_done, int(meta["iteration"])
            )
            job.resume_absolute = (
                bool(meta["exact"])
                and meta["scheduler"] == "lockstep"
                and job.spec.options.scheduler == "lockstep"
            )
            return True
        return False

    def _jappend(self, type_: str, job_id: str, fsync: bool = True, **fields):
        """Journal append that never takes the serve path down: on any
        append failure (including an injected torn write) the log is
        re-replayed, which truncates the torn tail so later appends land on
        a clean frame boundary."""
        jr = self.journal
        if jr is None:
            return
        try:
            jr.append(type_, job_id, fsync=fsync, **fields)
        except Exception:
            try:
                jr.replay()
            except Exception:
                pass

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "SearchServer":
        if self._started:
            return self
        self._started = True
        for i in range(self.max_concurrency):
            t = threading.Thread(
                target=self._worker_loop, name=f"sr-serve-worker-{i}", daemon=True
            )
            t.start()
            self._workers.append(t)
        self._supervisor = threading.Thread(
            target=self._supervisor_loop, name="sr-serve-supervisor", daemon=True
        )
        self._supervisor.start()
        return self

    def shutdown(self, wait: bool = True, cancel_queued: bool = True) -> None:
        """Stop accepting work and stop running jobs at their next iteration
        boundary (cooperative; running jobs finalize as CANCELLED)."""
        self._stop_event.set()
        with self._lock:
            running = list(self._running.values())
        for job in running:
            job.cancel_requested.set()
            if job.session is not None:
                job.session.request_stop()
        if cancel_queued:
            for job in self._queue.drain():
                self._finalize(job, q.CANCELLED, release=False)
        self._queue.wake_all()
        if wait:
            if self._supervisor is not None:
                self._supervisor.join(timeout=60)
            for t in list(self._workers):
                t.join(timeout=60)
            if cancel_queued:
                # a preempted job may have re-entered between drain and join
                for job in self._queue.drain():
                    self._finalize(job, q.CANCELLED, release=False)
        if self.journal is not None:
            self.journal.close()
        if self._own_spool:
            shutil.rmtree(self.spool_dir, ignore_errors=True)

    def __enter__(self) -> "SearchServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- drain (graceful handoff) ---------------------------------------------
    def pause_admission(self) -> None:
        """Stop workers from picking up queued jobs; running jobs are
        unaffected. Reversible with :meth:`resume_admission`."""
        self._admission_paused.set()

    def resume_admission(self) -> None:
        self._admission_paused.clear()
        self._queue.wake_all()

    def drain(self, timeout: float = 60.0) -> bool:
        """Graceful-handoff drain (the SIGTERM shape): pause admission,
        ask every RUNNING search to yield at its next iteration boundary
        (the preemption path: a format-2 spool snapshot + a journaled
        ``requeue``), and wait until nothing is running. Queued jobs stay
        queued — with a journal they remain durably adoptable, which is the
        point: follow with ``shutdown(cancel_queued=False)`` and another
        host can take the journal over with zero loss. Subscriptions have
        no resumable budget and are stopped like a client cancel. Returns
        True when the server went idle within ``timeout``."""
        self.pause_admission()
        with self._lock:
            running = list(self._running.values())
        for job in running:
            if job.spec.kind == "search":
                job.preempt_requested.set()
            else:
                job.cancel_requested.set()
                if job.session is not None:
                    job.session.request_stop()
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            with self._lock:
                if not self._running:
                    return True
            time.sleep(min(0.02, self.poll_seconds))
        with self._lock:
            return not self._running

    # -- federated adoption (pod runtime) --------------------------------------
    def adopt_external(
        self,
        spec: JobSpec,
        *,
        attempts: int = 0,
        iterations_done: int = 0,
        ckpt: str | None = None,
        submitted_at: float | None = None,
        error: str | None = None,
    ) -> str:
        """Admit a job recovered from ANOTHER server's journal (the pod
        runtime's lane migration): re-journal it locally under a fresh id,
        preserve its attempt counter and original submit time (deadlines
        keep measuring from the tenant's submit, and the retry budget
        cannot reset by changing hosts — the same invariant `_recover`
        enforces), and adopt the dead host's checkpoint so an exact
        lockstep snapshot resumes bit-identically. Returns the local job
        id; a job already past the retry budget finalizes QUARANTINED
        without running."""
        if self._stopping:
            raise RuntimeError("server is shutting down")
        if spec.kind != "search":
            raise ValueError("only search jobs can be adopted")
        with self._lock:
            self._seq += 1
            job_id = f"job-{self._seq:05d}"
            job = Job(job_id, spec, seq=self._seq)
            self._jobs[job_id] = job
        if submitted_at is not None:
            job.submitted_at = float(submitted_at)
            job.deadline_at = (
                None
                if spec.deadline_seconds is None
                else job.submitted_at + spec.deadline_seconds
            )
        job.attempts = int(attempts)
        job.iterations_done = int(iterations_done)
        job.error = error
        if self.journal is not None:
            try:
                self.journal.append_submit(job)
            except Exception:
                try:
                    self.journal.replay()
                except Exception:
                    pass
        if job.attempts > self.job_retries:
            job.error = error or (
                f"quarantined on adoption: {job.attempts} attempt(s) "
                f"exceed SR_JOB_RETRIES={self.job_retries}"
            )
            with self._lock:
                self._quarantined += 1
            self._finalize(job, q.QUARANTINED, release=False)
            return job_id
        if self._adopt_checkpoint(job, ckpt):
            # the adopted snapshot lives in the DEAD host's spool; requeue
            # with its path so a crash here still re-adopts it
            self._jappend(
                "requeue", job.id, attempts=job.attempts, not_before=0.0,
                ckpt=job.resume_path,
            )
        self._queue.submit(job)
        self._queue.wake_all()
        return job_id

    def warm_digests(self) -> list[str]:
        """Digests of the shape buckets this server has run (and whose
        compiled programs are therefore resident) — the warmth block of a
        pod host's load advertisement."""
        with self._lock:
            return sorted(q.bucket_digest(b) for b in self._warm_buckets)

    # -- client surface -------------------------------------------------------
    def submit(self, spec: JobSpec) -> str:
        """Enqueue a job; returns its id. May trigger preemption: when every
        worker is busy and some RUNNING job has strictly lower priority (and
        is preemptible), the lowest-priority one is asked to yield."""
        if self._stopping:
            raise RuntimeError("server is shutting down")
        if not self._started:
            raise RuntimeError("server not started (use start() or a with-block)")
        if self.queue_max_depth and len(self._queue) >= self.queue_max_depth:
            with self._lock:
                self._shed += 1
            raise ServerOverloaded(
                f"queue depth at SR_QUEUE_MAX_DEPTH={self.queue_max_depth}; "
                "resubmit later"
            )
        with self._lock:
            self._seq += 1
            job_id = f"job-{self._seq:05d}"
            job = Job(job_id, spec, seq=self._seq)
            self._jobs[job_id] = job
        if self.journal is not None:
            try:
                self.journal.append_submit(job)
            except JournalDiskFull as exc:
                # disk-full shedding: a submit that cannot be made durable is
                # refused (the append itself is the probe — the first submit
                # after space returns re-arms the journal and is accepted).
                # Running jobs are untouched; the client retries later.
                if os.environ.get("SR_CHAOS_BREAK") == "shed_silently":
                    # chaos-demo regression (scripts/chaos_soak.py --break):
                    # accept the job id but drop the job — the auditor's
                    # no_lost_jobs invariant must catch this
                    with self._lock:
                        self._jobs.pop(job_id, None)
                    return job_id
                with self._lock:
                    self._jobs.pop(job_id, None)
                    self._shed += 1
                    self._journal_shed += 1
                raise ServerOverloaded(
                    "journal is read-only (disk full); resubmit after "
                    f"retry-after={max(1.0, self.poll_seconds * 5):.1f}s"
                ) from exc
            except Exception:
                try:
                    self.journal.replay()
                except Exception:
                    pass
        self._queue.submit(job)
        self._maybe_preempt_for(job)
        return job_id

    def job(self, job_id: str) -> Job:
        with self._lock:
            return self._jobs[job_id]

    def wait(self, job_id: str, timeout: float | None = None) -> Job:
        """Block until the job reaches a terminal state (or timeout); returns
        the Job either way — check ``job.terminal``."""
        job = self.job(job_id)
        job.done_event.wait(timeout)
        return job

    def frames(self, job_id: str, start: int = 0) -> list[bytes]:
        """Snapshot of the job's frontier frames from index ``start`` —
        format-2 bytes for ``utils.checkpoint.load_frontier_bytes``."""
        job = self.job(job_id)
        with self._lock:
            return list(job.frames[start:])

    def frames_since(self, job_id: str, start: int = 0) -> tuple[list[bytes], bool]:
        """``(frames[start:], terminal)`` captured under ONE lock
        acquisition — the terminal flag is consistent with the frame
        snapshot, so a reader that sees ``terminal=True`` holds every frame
        the job will ever produce. This is the fan-out primitive for
        high-frequency network readers (``frames()`` + a separate terminal
        check would contend the server lock twice per batch and could race
        a frame appended between the two)."""
        with self._lock:
            job = self._jobs[job_id]
            return list(job.frames[start:]), job.terminal

    def wait_activity(self, last_seen: int = 0, timeout: float | None = None) -> int:
        """Block until the server-wide activity counter advances past
        ``last_seen`` (any frame append or terminal transition on any job),
        or until ``timeout``; returns the current counter. Lets one bridge
        thread multiplex wakeups for many streams."""
        with self._frame_cond:
            if self._activity == last_seen:
                self._frame_cond.wait(timeout)
            return self._activity

    def stream(self, job_id: str, timeout: float | None = None):
        """Generator over frontier frames as they arrive, ending when the job
        goes terminal (yields every frame exactly once)."""
        job = self.job(job_id)
        i = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._frame_cond:
                while len(job.frames) <= i and not job.terminal:
                    remaining = (
                        None if deadline is None else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        return
                    if not self._frame_cond.wait(
                        self.poll_seconds
                        if remaining is None
                        else min(self.poll_seconds, remaining)
                    ):
                        continue
                # One consistent snapshot: batch + terminal under the same
                # acquisition, so the post-yield exit check needs no re-lock.
                batch = list(job.frames[i:])
                terminal = job.terminal
            for frame in batch:
                yield frame
            i += len(batch)
            if terminal:
                return

    def cancel(self, job_id: str) -> None:
        """Request cancellation: queued jobs finalize on the next sweep,
        running jobs stop at the next iteration boundary. For a
        subscription this is the NORMAL way to end the stream — the job
        finalizes DONE with its final SearchResult attached."""
        job = self.job(job_id)
        job.cancel_requested.set()
        session = job.session
        if session is not None:
            session.request_stop()
        self._queue.wake_all()

    def push_rows(self, job_id: str, X, y, weights=None) -> None:
        """Append rows to a subscription's live dataset (applied at the
        next iteration boundary; zero recompiles while the row count stays
        within the session's row bucket). Rows pushed before the job is
        admitted are staged and flushed when the session starts."""
        self._stage_rows(job_id, "push", X, y, weights)

    def replace_rows(self, job_id: str, X, y, weights=None) -> None:
        """Replace a subscription's whole dataset (same feature count) at
        the next iteration boundary."""
        self._stage_rows(job_id, "replace", X, y, weights)

    def _stage_rows(self, job_id: str, kind: str, X, y, weights) -> None:
        import numpy as np

        job = self.job(job_id)
        if job.spec.kind != "subscription":
            raise ValueError(f"{job_id} is not a subscription job")
        with self._lock:
            if job.terminal:
                raise RuntimeError(f"{job_id} is terminal ({job.state})")
            session = job.session
            if session is None:  # queued: stage until the session exists
                job.pending_rows.append(
                    (
                        kind,
                        np.asarray(X),
                        np.asarray(y),
                        None if weights is None else np.asarray(weights),
                    )
                )
                return
        if kind == "push":
            session.push_rows(X, y, weights)
        else:
            session.replace_rows(X, y, weights)

    def stats(self) -> dict:
        """Server + cache health: job states, warm buckets, and the unified
        program cache's hit/miss/eviction counters (the same block the
        engine surfaces per-search via ``engine_profile``)."""
        with self._lock:
            by_state: dict[str, int] = {}
            for job in self._jobs.values():
                by_state[job.state] = by_state.get(job.state, 0) + 1
            cache = self.cache.stats()
            journal = {"enabled": self.journal is not None}
            if self.journal is not None:
                journal.update(self.journal.stats())
                journal["dir"] = self.journal_dir
                journal["recovered"] = dict(self._recovered)
            return {
                "jobs": by_state,
                "queued": len(self._queue),
                "running": len(self._running),
                "admission_paused": self._admission_paused.is_set(),
                "warm_buckets": len(self._warm_buckets),
                "retries": self._retries,
                "quarantined": self._quarantined,
                "shed": self._shed,
                "stalls": self._stalls,
                "worker_restarts": self._worker_restarts,
                # -- degradation states (r19): the chaos auditor reads these
                #    from here, never from private attributes --
                "journal_read_only": bool(journal.get("read_only", False)),
                "journal_shed": self._journal_shed,
                "oom_downshifts": self._oom_downshifts,
                "skew_suspects_suppressed": self._skew_suppressed,
                "journal": journal,
                "program_cache": cache,
                "warm_hit_ratio": cache["hit_ratio"],
                "compilation_cache_dir": self.compilation_cache_dir,
                "fleet": {
                    "enabled": self.fleet,
                    "max_lanes": self.fleet_max,
                    "window_s": self.fleet_window_s,
                    "batches": self._fleet_batches,
                    "coalesced_lanes": self._fleet_lanes,
                    "largest_batch": self._fleet_max_seen,
                    "deduped_lanes": self._fleet_deduped,
                },
            }

    # -- scheduling internals --------------------------------------------------
    def _maybe_preempt_for(self, incoming: Job) -> None:
        with self._lock:
            if len(self._running) < self.max_concurrency:
                return  # a free worker will pick the queue's best job up
            candidates = [
                j
                for j in self._running.values()
                if j.spec.preemptible
                and not j.preempt_requested.is_set()
                and j.spec.priority < incoming.spec.priority
            ]
            if not candidates:
                return
            victim = min(candidates, key=lambda j: (j.spec.priority, -j.seq))
            victim.preempt_requested.set()

    def _worker_loop(self) -> None:
        from ..utils import faults

        while not self._stopping:
            if self._admission_paused.is_set():
                # draining: running jobs keep their workers (they are past
                # this gate); idle workers stop picking the queue up
                self._stop_event.wait(self.poll_seconds)
                continue
            now = time.time()
            for job in self._queue.take_expired(now):
                state = (
                    q.CANCELLED if job.cancel_requested.is_set() else q.EXPIRED
                )
                self._finalize(job, state, release=False)
            job = self._queue.acquire(
                warm_buckets=self._warm_snapshot(), timeout=self.poll_seconds
            )
            if job is None:
                continue
            if self._stopping:
                self._queue.release(job)
                self._finalize(job, q.CANCELLED, release=False)
                return
            if faults.active().fire("worker_crash") is not None:
                # thread death between acquire and run: give the job (and
                # the tenant's quota slot) back, then die — the supervisor
                # must restart this worker
                self._queue.release(job)
                self._queue.resubmit(job)
                return
            batch = [job]
            try:
                if job.spec.kind == "subscription":
                    self._run_subscription(job)
                else:
                    mates = self._gather_fleet(job)
                    if mates:
                        batch = [job] + mates
                        self._run_fleet(batch)
                    else:
                        self._run_job(job)
            except BaseException as e:  # a worker must never die silently
                # EVERY member of a coalesced batch is accounted for — the
                # pre-r15 catch-all finalized only the lead job and left
                # take_compatible mates in limbo forever
                for member in batch:
                    self._handle_run_failure(
                        member, e, solo_retry=len(batch) > 1
                    )

    def _supervisor_loop(self) -> None:
        """Self-healing sweep: restart worker threads that died (injected
        ``worker_crash``, or a bug escaping the catch-all) and run the stall
        watchdog — a RUNNING search whose iteration heartbeat has been
        silent past ``SR_JOB_STALL_S`` gets a cooperative stop request and
        retries from its latest checkpoint. Jobs that have never produced a
        heartbeat are exempt (a first-touch compile legitimately takes
        minutes)."""
        interval = max(0.05, min(1.0, self.poll_seconds))
        while not self._stop_event.wait(interval):
            for i, t in enumerate(list(self._workers)):
                if not t.is_alive() and not self._stopping:
                    nt = threading.Thread(
                        target=self._worker_loop, name=t.name, daemon=True
                    )
                    nt.start()
                    self._workers[i] = nt
                    with self._lock:
                        self._worker_restarts += 1
            if self.stall_s > 0:
                from ..utils import faults

                # the watchdog reads the wall clock through the skewable
                # source: an injected (or real NTP-step) clock jump shows up
                # as wall time advancing far faster than the monotonic clock
                # between passes — in that window heartbeat ages are garbage,
                # so re-stamp them instead of stall-killing healthy runs
                now = faults.skewed_time(os.environ.get("SR_POD_HOST"))
                mono = time.monotonic()
                jumped = False
                if self._watch_clock is not None:
                    wall_d = now - self._watch_clock[0]
                    mono_d = mono - self._watch_clock[1]
                    jumped = abs(wall_d - mono_d) > max(1.0, 0.5 * self.stall_s)
                self._watch_clock = (now, mono)
                with self._lock:
                    running = list(self._running.values())
                if jumped:
                    with self._lock:
                        self._skew_suppressed += 1
                    for job in running:
                        if job.heartbeat is not None:
                            job.heartbeat = now
                    continue
                for job in running:
                    hb = job.heartbeat
                    if (
                        job.spec.kind == "search"
                        and hb is not None
                        and now - hb > self.stall_s
                        and not job.stall_stop.is_set()
                    ):
                        job.stall_stop.set()

    def _warm_snapshot(self) -> set:
        with self._lock:
            return set(self._warm_buckets)

    def _make_callback(self, job: Job, fingerprint: tuple, group=None):
        """Per-iteration engine hook. ``group`` is the dedup group sharing
        this run (leader first): a shared lane only stops on cancel when
        EVERY rider has cancelled — one tenant's cancel must not evict a
        search that other identical jobs are still waiting on. Preemption
        keys off the leader alone (a follower occupies no device lane, so
        evicting the shared run for it would waste everyone's progress)."""
        spec = job.spec
        # the server owns the engine's iteration_callback slot, so a
        # tenant-supplied callback is chained here instead of replaced
        # (dedup riders share the leader's lane; the leader's own callback
        # is the one that runs)
        user_cb = spec.options.iteration_callback

        def _on_iteration(report) -> bool | None:
            from ..utils import faults

            # stamped through the skewable clock so heartbeat and watchdog
            # agree once an injected skew latches (the jump itself is what
            # the watchdog's monotonic cross-check absorbs)
            job.heartbeat = faults.skewed_time(os.environ.get("SR_POD_HOST"))
            job.iterations_done = job.iteration_base + report.iteration
            user_stop = user_cb(report) if user_cb is not None else None
            hit = faults.active().fire("stall")
            if hit is not None:
                # a hung run: no heartbeat for delay_s — but poll the
                # watchdog's stop request so the stall resolves the moment
                # the supervisor notices it
                end = time.time() + float(hit.get("delay_s", 30.0))
                while time.time() < end:
                    if (
                        job.stall_stop.is_set()
                        or job.cancel_requested.is_set()
                        or self._stopping
                    ):
                        break
                    time.sleep(0.02)
            jr = self.journal
            if jr is not None and spec.kind == "search":
                nowt = time.time()
                every = self.ckpt_every_s if self.ckpt_every_s > 0 else 5.0
                if nowt - job.journal_progress_at >= every:
                    job.journal_progress_at = nowt
                    self._jappend(
                        "progress", job.id, fsync=False,
                        iterations_done=job.iterations_done,
                    )
            if (
                report.iteration % spec.stream_every == 0
                or job.iterations_done >= spec.niterations
            ):
                from ..utils.checkpoint import dump_frontier_bytes

                frame = dump_frontier_bytes(
                    report.hall_of_fame,
                    iteration=job.iterations_done,
                    niterations=spec.niterations,
                    num_evals=report.num_evals,
                    fingerprint=fingerprint,
                    wall_time=time.time() - job.submitted_at,
                )
                with self._frame_cond:
                    job.frames.append(frame)
                    if job.ttff is None:
                        job.ttff = time.time() - job.submitted_at
                    self._activity += 1
                    self._frame_cond.notify_all()
            cancelled = (
                all(j.cancel_requested.is_set() for j in group)
                if group
                else job.cancel_requested.is_set()
            )
            if (
                cancelled
                or job.preempt_requested.is_set()
                or job.stall_stop.is_set()
                or self._stopping
            ):
                return True
            return True if user_stop else None

        return _on_iteration

    def _run_job(self, job: Job, group=None) -> None:
        from ..search import equation_search
        from ..utils import faults
        from ..utils.checkpoint import options_fingerprint

        spec = job.spec
        now = time.time()
        if job.deadline_at is not None and now >= job.deadline_at:
            self._queue.release(job)
            self._finalize(job, q.EXPIRED, release=False)
            return
        with self._lock:
            self._running[job.id] = job
        job.started_at = job.started_at or now
        job.heartbeat = None
        job.stall_stop.clear()
        # exact lockstep resumes run [start_iter, niterations) and report
        # ABSOLUTE iterations; warm-start resumes run the remainder and
        # report run-relative — only the latter needs the base offset
        job.iteration_base = 0 if job.resume_absolute else job.iterations_done
        if group is None:
            job.attempts += 1

        ckpt_base = None
        if self.journal is not None:
            ckpt_base = os.path.join(self.spool_dir, f"{job.id}.engine")
            if group is None:
                self._jappend(
                    "start", job.id, attempts=job.attempts, ckpt=ckpt_base
                )
        fingerprint = options_fingerprint(spec.options)
        opts = self._lane_options(job, fingerprint, now, group, ckpt_base)
        try:
            if faults.active().fire("job_exception") is not None:
                raise faults.FaultInjected("injected job_exception")
            result = equation_search(
                spec.X,
                spec.y,
                weights=spec.weights,
                options=opts,
                niterations=spec.niterations,
                resume_from=job.resume_path,
                verbosity=0,
            )
        except BaseException as e:
            self._handle_run_failure(job, e)
            return

        self._complete_lane(job, result, fingerprint)

    def _lane_options(
        self, job: Job, fingerprint: tuple, now: float, group=None,
        ckpt_base: str | None = None,
    ):
        """The server's per-run Options replacement — shared by the solo and
        fleet paths so a coalesced job behaves exactly like a solo one.
        ``ckpt_base`` (journaled solo runs only) re-enables the engine's own
        periodic checkpointer pointed into the spool: those snapshots are
        what crash recovery resumes from, bounding work loss to one
        ``SR_SERVE_CKPT_EVERY_S`` interval."""
        spec = job.spec
        timeout = spec.options.timeout_in_seconds
        if job.deadline_at is not None:
            remaining = job.deadline_at - now
            timeout = remaining if timeout is None else min(timeout, remaining)
        return dataclasses.replace(
            spec.options,
            iteration_callback=self._make_callback(job, fingerprint, group),
            timeout_in_seconds=timeout,
            max_evals=(
                spec.max_evals
                if spec.max_evals is not None
                else spec.options.max_evals
            ),
            # the server owns persistence: no CSV sidecars, and the only
            # checkpoint cadence is the durability one the journal wires in
            # (preemption snapshots are written here either way)
            save_to_file=False,
            progress=False,
            checkpoint_every=None,
            checkpoint_every_seconds=(
                self.ckpt_every_s
                if ckpt_base and self.ckpt_every_s > 0
                else None
            ),
            checkpoint_file=(
                ckpt_base if ckpt_base else spec.options.checkpoint_file
            ),
            checkpoint_keep=2 if ckpt_base else spec.options.checkpoint_keep,
        )

    def _complete_lane(self, job: Job, result, fingerprint: tuple) -> None:
        """Post-run bookkeeping for one finished search — the identical
        terminal sequence whether the search ran solo or as a fleet lane."""
        job.result = result
        job.stop_reason = getattr(result, "stop_reason", None)
        self._release_running(job)

        if job.cancel_requested.is_set() or (
            self._stopping and job.stop_reason == "callback"
        ):
            self._finalize(job, q.CANCELLED, release=False)
            return
        if job.stop_reason == "callback" and job.preempt_requested.is_set():
            self._preempt_requeue(job, result, fingerprint)
            return
        if job.stop_reason == "callback" and job.stall_stop.is_set():
            # the stall watchdog stopped this run cooperatively: snapshot
            # what it had and send it through the retry path
            with self._lock:
                self._stalls += 1
            job.error = (
                "StallDetected: no iteration heartbeat for > "
                f"{self.stall_s:.2f}s"
            )
            job.resume_path = self._spool_snapshot(job, result, fingerprint)
            job.resume_absolute = False
            self._retry_or_quarantine(job, adopt=False)
            return
        # definitive final frame from the FINISHED result: the pipelined
        # device loop's per-iteration reports lag the hall of fame by one
        # iteration, so the last streamed frame may undersell (or, for a
        # 1-iteration job, miss) the final frontier
        self._push_final_frame(job, result, fingerprint)
        if (
            job.stop_reason == "timeout"
            and job.deadline_at is not None
            and time.time() >= job.deadline_at - 0.25
        ):
            # the engine's timeout stop was OUR deadline, not the tenant's own
            # timeout_in_seconds — terminal "expired", result still attached
            self._finalize(job, q.EXPIRED, release=False)
            return
        self._finalize(job, q.DONE, release=False)

    # -- subscriptions ---------------------------------------------------------
    def _run_subscription(self, job: Job) -> None:
        """Run a ``kind="subscription"`` job: a StreamSession driven inline
        on this worker thread (the session IS the job's lane; it occupies
        the worker slot until the client cancels or the engine stops on its
        own budget). Frames flow through the job's normal frame channel;
        pre-admission ``push_rows`` staging flushes into the live session
        the moment it exists."""
        from ..stream.session import StreamSession

        spec = job.spec
        with self._lock:
            self._running[job.id] = job
        job.started_at = job.started_at or time.time()
        job.iteration_base = job.iterations_done
        job.attempts += 1
        self._jappend("start", job.id, attempts=job.attempts)

        def _on_frame(frame: bytes) -> None:
            with self._frame_cond:
                job.frames.append(frame)
                if job.ttff is None:
                    job.ttff = time.time() - job.submitted_at
                self._activity += 1
                self._frame_cond.notify_all()

        user_cb = spec.options.iteration_callback

        def _cb(report):
            job.iterations_done = job.iteration_base + report.iteration
            stop = user_cb(report) if user_cb is not None else None
            if job.cancel_requested.is_set() or self._stopping:
                return True
            return stop

        cfg = dict(spec.stream_config or {})
        cfg.setdefault("stream_every", spec.stream_every)
        cfg.setdefault("label", job.id)
        try:
            session = StreamSession(
                spec.X,
                spec.y,
                dataclasses.replace(spec.options, iteration_callback=_cb),
                weights=spec.weights,
                on_frame=_on_frame,
                **cfg,
            )
        except BaseException as e:
            self._release_running(job)
            job.error = _format_error(e)
            job.traceback = _format_traceback(e)
            self._finalize(job, q.FAILED, release=False)
            return
        with self._lock:
            job.session = session
            pending, job.pending_rows = job.pending_rows, []
        for kind, X, y, w in pending:
            if kind == "push":
                session.push_rows(X, y, w)
            else:
                session.replace_rows(X, y, w)
        try:
            result = session.run()
        except BaseException as e:
            self._release_running(job)
            job.error = _format_error(e)
            job.traceback = _format_traceback(e)
            self._finalize(job, q.FAILED, release=False)
            return

        job.result = result
        job.iterations_done = session.stats.iterations
        self._release_running(job)
        if self._stopping:
            job.stop_reason = "cancelled"
            self._finalize(job, q.CANCELLED, release=False)
        elif job.cancel_requested.is_set():
            # client cancel is the normal end of a subscription: terminal
            # DONE, final result attached
            job.stop_reason = "cancelled"
            self._finalize(job, q.DONE, release=False)
        else:
            job.stop_reason = getattr(result, "stop_reason", None)
            self._finalize(job, q.DONE, release=False)

    # -- fleet coalescing ------------------------------------------------------
    def _gather_fleet(self, lead: Job) -> list[Job]:
        """Coalescing admission: given a just-acquired lead job, gather up to
        ``fleet_max - 1`` compatible queued jobs (same shape bucket, no
        deadline, no resume checkpoint), waiting one admission window for
        stragglers when the first sweep comes back short. Returns [] when the
        lead itself must run solo."""
        if not self.fleet or self._stopping:
            return []
        if (
            lead.deadline_at is not None
            or lead.resume_path is not None
            or lead.solo_only
            or lead.cancel_requested.is_set()
        ):
            # deadline-urgent jobs bypass coalescing (their wall budget must
            # not be hostage to fleet drain); preemption resumes warm-start
            # solo (fleet lanes take no saved_state); a job retried after a
            # fleet failure is isolated from coalescing for good
            return []
        from ..models.device_search import fleet_eligibility

        probe = dataclasses.replace(
            lead.spec.options,
            save_to_file=False,
            checkpoint_every=None,
            checkpoint_every_seconds=None,
        )
        if fleet_eligibility(probe) is not None:
            return []
        limit = self.fleet_max - 1
        mates = self._queue.take_compatible(lead, limit)
        if len(mates) < limit and self.fleet_window_s > 0:
            # interruptible admission window: shutdown must not hang a
            # worker for the full straggler wait
            self._stop_event.wait(self.fleet_window_s)
            mates += self._queue.take_compatible(lead, limit - len(mates))
        return mates

    def _content_key(self, job: Job) -> tuple:
        """Full search identity: options digest WITH seed, iteration/eval
        budget, and the dataset bytes. Jobs with equal keys are the SAME
        deterministic search and share one lane (request collapsing)."""
        from ..utils.checkpoint import options_fingerprint

        import numpy as np

        spec = job.spec
        h = hashlib.sha1()
        h.update(np.ascontiguousarray(spec.X).tobytes())
        h.update(np.ascontiguousarray(spec.y).tobytes())
        if spec.weights is not None:
            h.update(np.ascontiguousarray(spec.weights).tobytes())
        return (
            options_fingerprint(spec.options),
            spec.niterations,
            spec.max_evals,
            h.hexdigest(),
        )

    def _clone_result(self, result):
        """Per-rider result object for a dedup group: a fresh shell with its
        OWN hall of fame (what frames/frontier/stop bookkeeping touch) —
        the decoded populations and dataset arrays are shared read-only
        across riders (a full deepcopy costs ~10ms/rider and nothing in
        the serve path mutates them). ``engine_profile`` IS mutable —
        fleet_search attaches the same summary dict (with its live
        "counters" block) to every lane result — so it gets its own deep
        copy per rider (aliasing pinned by tests/test_fleet.py)."""
        clone = copy.copy(result)
        clone.hall_of_fame = copy.deepcopy(result.hall_of_fame)
        profile = getattr(result, "engine_profile", None)
        if profile is not None:
            clone.engine_profile = copy.deepcopy(profile)
        return clone

    def _fan_out(self, leader: Job, followers: list[Job], fingerprint) -> None:
        """Deliver a dedup group's shared result: each follower finishes
        with a clone of the leader's result (the engine is
        deterministic, so this IS the result its own run would produce).
        If the shared run stopped early through no fault of a follower
        (eviction, failure), live followers go back to the queue."""
        ok = (
            leader.result is not None
            and leader.state != q.FAILED
            and leader.stop_reason != "callback"
        )
        for f in followers:
            f.started_at = f.started_at or leader.started_at
            f.iterations_done = max(f.iterations_done, leader.iterations_done)
            if f.cancel_requested.is_set():
                self._release_running(f)
                self._finalize(f, q.CANCELLED, release=False)
            elif ok:
                self._complete_lane(f, self._clone_result(leader.result), fingerprint)
            elif leader.state == q.FAILED:
                self._release_running(f)
                f.error = leader.error
                f.traceback = leader.traceback
                self._finalize(f, q.FAILED, release=False)
            else:
                self._release_running(f)
                if leader.error is not None:
                    # the shared run broke: riders rerun solo, isolated
                    f.solo_only = True
                self._queue.resubmit(f)

    def _run_fleet(self, jobs: list[Job]) -> None:
        """Run coalesced jobs as one fleet. Jobs are first deduplicated by
        content (dataset + options incl. seed + budget): duplicates ride
        the leader's lane and fan out deep-copied results. Each unique lane
        finalizes through the same terminal sequence as a solo run the
        moment it finishes (``on_lane_done``) — a cancelled/preempted lane
        leaves the fleet early while the surviving lanes drain unchanged.
        A batch that collapses to ONE unique search skips the fleet program
        entirely and runs the warm solo path."""
        from ..utils.checkpoint import options_fingerprint

        grouped: dict = {}
        for job in jobs:
            grouped.setdefault(self._content_key(job), []).append(job)
        groups = list(grouped.values())

        now = time.time()
        with self._lock:
            for job in jobs:
                self._running[job.id] = job
            self._fleet_batches += 1
            self._fleet_lanes += len(jobs)
            self._fleet_max_seen = max(self._fleet_max_seen, len(jobs))
            self._fleet_deduped += len(jobs) - len(groups)
        for job in jobs:
            job.started_at = job.started_at or now
            job.heartbeat = None
            job.stall_stop.clear()
            job.iteration_base = job.iterations_done
            job.attempts += 1
            self._jappend("start", job.id, attempts=job.attempts)

        if len(groups) == 1:
            leader, followers = jobs[0], jobs[1:]
            fp = options_fingerprint(leader.spec.options)
            self._run_job(leader, group=jobs)
            self._fan_out(leader, followers, fp)
            return

        self._run_fleet_groups(groups, now)

    def _run_fleet_groups(self, groups: list, now: float) -> None:
        """Run unique-content groups as one fleet program, degrading on
        compile OOM: a ``RESOURCE_EXHAUSTED`` from the batch (real, or the
        injected ``oom_compile`` site) halves the lane set and retries each
        half; a single group that still OOMs at fleet width falls back to
        the warm SOLO path (a strictly smaller program). Jobs consume no
        retry attempt for the downshift itself — quarantine is reached only
        if the solo run fails too. Non-OOM failures keep the r15 isolation:
        every incomplete member retries solo with ``solo_only``."""
        from ..models.device_search import FleetLaneSpec, fleet_search
        from ..utils.checkpoint import options_fingerprint

        leaders = [g[0] for g in groups]
        specs, fingerprints = [], []
        for group in groups:
            leader = group[0]
            fp = options_fingerprint(leader.spec.options)
            fingerprints.append(fp)
            specs.append(
                FleetLaneSpec(
                    X=leader.spec.X,
                    y=leader.spec.y,
                    options=self._lane_options(leader, fp, now, group),
                    weights=leader.spec.weights,
                    niterations=leader.spec.niterations,
                    label=leader.id,
                )
            )
        completed = [False] * len(groups)

        def _lane_done(idx: int, result) -> None:
            completed[idx] = True
            self._complete_lane(leaders[idx], result, fingerprints[idx])
            self._fan_out(leaders[idx], groups[idx][1:], fingerprints[idx])

        try:
            fleet_search(
                specs,
                on_lane_done=_lane_done,
                coalesce_wait_s=self.fleet_window_s,
                lane_bucket=self.fleet_max,
            )
        except BaseException as e:
            pending = [
                g for flag, g in zip(completed, groups) if not flag
            ]
            if is_oom_error(e) and not self._stopping:
                with self._lock:
                    self._oom_downshifts += 1
                if len(pending) > 1:
                    # halve the batch: smaller lane counts compile smaller
                    # programs — each half re-enters this path and can halve
                    # again until it fits (or collapses to the solo leg)
                    mid = (len(pending) + 1) // 2
                    for half in (pending[:mid], pending[mid:]):
                        if half:
                            self._run_fleet_groups(half, now)
                    return
                for group in pending:
                    leader = group[0]
                    fp = options_fingerprint(leader.spec.options)
                    try:
                        self._run_job(leader, group=group)
                        self._fan_out(leader, group[1:], fp)
                    except BaseException as e2:
                        for job in group:
                            self._handle_run_failure(job, e2, solo_retry=True)
                return
            # fleet failure isolation: an exception in the coalesced batch
            # must not FAIL every incomplete lane — each member retries solo
            # (solo_only, so it never re-enters a coalesced batch)
            for group in pending:
                for job in group:
                    self._handle_run_failure(job, e, solo_retry=True)

    def _push_final_frame(self, job: Job, result, fingerprint: tuple) -> None:
        from ..utils.checkpoint import dump_frontier_bytes

        frame = dump_frontier_bytes(
            result.hall_of_fame,
            iteration=max(job.iterations_done, 1),
            niterations=job.spec.niterations,
            num_evals=float(getattr(result, "num_evals", 0.0)),
            fingerprint=fingerprint,
            wall_time=time.time() - job.submitted_at,
        )
        with self._frame_cond:
            job.frames.append(frame)
            if job.ttff is None:
                job.ttff = time.time() - job.submitted_at
            self._activity += 1
            self._frame_cond.notify_all()

    def _release_running(self, job: Job) -> None:
        with self._lock:
            self._running.pop(job.id, None)
            # the bucket's programs are resident from this run on — admission
            # prefers jobs that can reuse them
            self._warm_buckets.add(job.bucket)
        self._queue.release(job)

    def _spool_snapshot(self, job: Job, result, fingerprint: tuple) -> str:
        """Write a format-2 snapshot of a cooperatively-stopped run into the
        spool (atomic tmp+fsync+rename): the resume artifact for preemption
        and for stall retries. ``exact=False``: a decoded observation, so the
        next run rescores and warm-starts over the remaining budget."""
        from ..utils.checkpoint import SearchCheckpoint, dump_checkpoint_bytes

        ck = SearchCheckpoint(
            iteration=int(job.iterations_done),
            niterations=int(job.spec.niterations),
            scheduler=job.spec.options.scheduler,
            exact=False,  # decoded observation -> rescored warm start
            populations=result.populations,
            hall_of_fame=result.hall_of_fame,
            num_evals=float(result.num_evals),
            options_fingerprint=fingerprint,
            wall_time=time.time() - job.submitted_at,
            out_j=1,
        )
        path = os.path.join(self.spool_dir, f"{job.id}.ckpt")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(dump_checkpoint_bytes(ck))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def _preempt_requeue(self, job: Job, result, fingerprint: tuple) -> None:
        """Snapshot the evicted job's state (format-2, atomic write) and
        re-enqueue it: the next admission resumes via ``resume_from`` over
        the remaining ``niterations - iterations_done`` budget."""
        job.resume_path = self._spool_snapshot(job, result, fingerprint)
        job.resume_absolute = False
        job.preemptions += 1
        job.preempt_requested.clear()
        with self._lock:
            job.state = q.PREEMPTED
        self._jappend(
            "requeue", job.id, attempts=job.attempts, not_before=0.0,
            ckpt=job.resume_path,
        )
        self._queue.resubmit(job)

    def _handle_run_failure(
        self, job: Job, exc: BaseException, solo_retry: bool = False
    ) -> None:
        """Route one job whose run raised ``exc``: cancelled/stopping jobs
        finalize, subscriptions FAIL (a live stream has no resumable
        budget), searches go through retry-with-backoff escalating to
        QUARANTINED. No-op for jobs already finalized or already requeued —
        the worker loop's batch-wide catch-all may revisit members an inner
        handler dealt with."""
        if job.terminal or job.state in (q.QUEUED, q.PREEMPTED):
            return
        job.error = _format_error(exc)
        job.traceback = _format_traceback(exc)
        self._release_running(job)
        if job.cancel_requested.is_set():
            self._finalize(job, q.CANCELLED, release=False)
            return
        if self._stopping or job.spec.kind != "search":
            self._finalize(job, q.FAILED, release=False)
            return
        self._retry_or_quarantine(job, solo_only=solo_retry)

    def _retry_or_quarantine(
        self, job: Job, solo_only: bool = False, adopt: bool = True
    ) -> None:
        """Requeue a failed search with exponential backoff, resuming from
        the freshest spool checkpoint when one exists; once its attempts
        exceed ``SR_JOB_RETRIES`` the job is a poison job and terminates
        QUARANTINED."""
        if job.attempts > self.job_retries:
            with self._lock:
                self._quarantined += 1
            self._finalize(job, q.QUARANTINED, release=False)
            return
        with self._lock:
            self._retries += 1
        if solo_only:
            job.solo_only = True
        job.stall_stop.clear()
        job.heartbeat = None
        job.not_before = time.time() + self.retry_backoff_s * (
            2 ** max(0, job.attempts - 1)
        )
        if adopt and not self._adopt_checkpoint(job, job.resume_path):
            # nothing to resume from: the retry is a clean restart
            job.resume_path = None
            job.resume_absolute = False
            job.iterations_done = 0
        self._jappend(
            "requeue", job.id, attempts=job.attempts,
            not_before=job.not_before, error=job.error, ckpt=job.resume_path,
        )
        self._queue.resubmit(job)
        self._queue.wake_all()

    def _finalize(self, job: Job, state: str, release: bool = True) -> None:
        if release:
            self._queue.release(job)
        with self._frame_cond:
            job.state = state
            job.finished_at = time.time()
            self._activity += 1
            self._frame_cond.notify_all()
        if self.journal is not None:
            self._jappend("terminal", job.id, state=state, error=job.error)
            self._clean_spool(job)
        job.done_event.set()

    def _clean_spool(self, job: Job) -> None:
        """Drop a terminal job's spool artifacts (preempt snapshot + the
        engine checkpoint chain) — nothing will ever resume them."""
        from ..utils.checkpoint import _list_snapshots

        base = os.path.join(self.spool_dir, f"{job.id}.engine")
        paths = [p for _, p in _list_snapshots(base)]
        paths.append(os.path.join(self.spool_dir, f"{job.id}.ckpt"))
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass
