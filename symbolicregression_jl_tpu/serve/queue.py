"""Job model + admission queue for the multi-tenant search server.

A job is one single-output ``equation_search`` with a tenant, a priority,
and optional budgets (wall-clock deadline from SUBMIT time, eval budget).
The queue admits jobs to workers by, in order:

1. **priority** (higher first) — the preemption total order;
2. **shape-bucket warmth** — among equal priorities, jobs whose
   (shapes, Options-digest) bucket the server has already compiled programs
   for go first, so a mixed backlog naturally batches same-bucket jobs onto
   the resident executables instead of interleaving compiles (the r04
   measurement: warm ~2s vs cold ~53s — admission order IS the throughput
   knob);
3. **submit order** (FIFO) — fairness within a warm bucket.

Warmth ordering alone can starve a cold-bucket job indefinitely under a
steady same-priority warm stream, so warmth is bounded by **submit-age
escalation**: once a queued job has waited longer than ``SR_QUEUE_AGE_S``
(seconds, default 30; ``0`` disables aging), its warmth term is forced to
the warm value — an aged cold-bucket job competes on FIFO order alone and
the warm stream can no longer leapfrog it. Priority still dominates: aging
never promotes a job past a higher-priority one.

Per-tenant quotas bound how many of a tenant's jobs RUN concurrently (queued
jobs are unlimited): a tenant flooding the queue cannot starve others of
worker slots, only of its own.

Everything here is host-side stdlib: the queue never touches jax.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any

import numpy as np

__all__ = [
    "JobSpec", "Job", "JobQueue", "ServerOverloaded", "shape_bucket",
    "options_digest", "bucket_digest", "queue_age_seconds",
]


# -- terminal + transient job states ------------------------------------------
QUEUED = "queued"
RUNNING = "running"
PREEMPTED = "preempted"  # transient: evicted by a higher-priority tenant,
#                          checkpointed, about to re-enter the queue
DONE = "done"
FAILED = "failed"
EXPIRED = "expired"  # deadline elapsed (queued or mid-run)
CANCELLED = "cancelled"
QUARANTINED = "quarantined"  # poison job: failed on every retry attempt

TERMINAL_STATES = frozenset(
    {DONE, FAILED, EXPIRED, CANCELLED, QUARANTINED}
)


class ServerOverloaded(RuntimeError):
    """submit() rejected: the queue is at ``SR_QUEUE_MAX_DEPTH``. The job was
    never created — resubmit later (load shedding is the backpressure
    contract; an unbounded queue under sustained overload only converts
    every deadline into an expiry)."""


def queue_age_seconds() -> float:
    """SR_QUEUE_AGE_S: queued age past which a job's effective admission
    priority rises past shape-bucket warmth (head-of-line aging). 0 disables
    aging. Read per admission pass — a live server honors changes."""
    try:
        return float(os.environ.get("SR_QUEUE_AGE_S", "30"))
    except ValueError:
        return 30.0


def options_digest(options) -> tuple:
    """Hashable digest of the Options axes that select compiled programs —
    the serve-level analogue of the engine cache keys (which hold the config
    OBJECTS; a digest is enough for bucketing because two jobs with equal
    digests build equal cache keys in-process)."""
    from ..utils.checkpoint import options_fingerprint

    # The checkpoint fingerprint ends with options.seed; the seed never
    # selects a compiled program (it is runtime data, EvoConfig carries no
    # seed), so it is sliced off here — jobs differing only by seed share a
    # bucket and can coalesce into one fleet.
    return (
        options_fingerprint(options)[:-1],
        options.scheduler,
        str(np.dtype(options.dtype)),
        int(options.maxsize),
        getattr(options.loss, "__name__", repr(options.loss)),
        bool(options.batching) and int(options.batch_size),
    )


def shape_bucket(X, y, weights, options) -> tuple:
    """The admission bucket: jobs in one bucket share every compiled engine
    program (executables are dataset-independent; only shapes/dtypes and the
    Options digest select them)."""
    X = np.asarray(X)
    y = np.asarray(y)
    return (
        X.shape,
        str(X.dtype),
        y.shape,
        str(y.dtype),
        weights is not None,
        options_digest(options),
    )


def bucket_digest(bucket: tuple) -> str:
    """12-hex digest of a :func:`shape_bucket` tuple — the warmth currency
    pod hosts advertise over the CoordStore. The full tuple is big (it
    embeds the Options digest) and only equality matters cross-process;
    every element reprs deterministically (shapes, dtype strings, ints,
    bools, operator/loss *names*), so equal buckets digest equally in any
    process running the same code."""
    import hashlib

    return hashlib.sha1(repr(bucket).encode()).hexdigest()[:12]


@dataclasses.dataclass
class JobSpec:
    """What a tenant submits. ``options.scheduler`` picks the engine;
    ``deadline_seconds`` is a wall budget measured from SUBMIT (covering
    queue wait — an expired job is terminal even if it never ran).
    ``deadline_seconds=None`` means **never expires**: queue-side sweeps and
    mid-run checks alike must skip deadline-less jobs (pinned by
    tests/test_serve.py).

    ``kind="subscription"`` is the streaming job type: a deadline-less
    search over a live dataset (``stream.StreamSession``) that emits
    format-2 frontier frames indefinitely until the client cancels.
    Subscriptions are necessarily deadline-less and non-preemptible (there
    is no finite remaining-iterations budget for a preemption checkpoint to
    resume over), and never coalesce into fleets (each owns its own
    long-lived lane). ``stream_config`` passes StreamSession knobs through
    (row_bucket, window, drift=..., ...); ``niterations`` is ignored."""

    X: Any
    y: Any
    options: Any
    weights: Any = None
    niterations: int = 10
    tenant: str = "default"
    priority: int = 0  # higher runs (and preempts) first
    deadline_seconds: float | None = None
    max_evals: int | None = None
    preemptible: bool = True
    stream_every: int = 1  # frontier frame cadence, in iterations
    label: str = ""
    kind: str = "search"  # "search" | "subscription"
    stream_config: dict | None = None  # StreamSession kwargs (subscriptions)

    def __post_init__(self):
        self.X = np.asarray(self.X)
        self.y = np.asarray(self.y)
        if self.weights is not None:
            self.weights = np.asarray(self.weights)
        if self.y.ndim != 1:
            raise ValueError(
                "serve jobs are single-output (y must be 1-D); submit one "
                "job per output row"
            )
        if self.kind not in ("search", "subscription"):
            raise ValueError(
                f"unknown job kind {self.kind!r} (search | subscription)"
            )
        if self.kind == "subscription":
            if self.deadline_seconds is not None:
                raise ValueError(
                    "subscription jobs are deadline-less "
                    "(deadline_seconds must be None)"
                )
            self.preemptible = False
        elif self.stream_config is not None:
            raise ValueError("stream_config is subscription-only")
        if self.niterations < 1:
            raise ValueError("niterations must be >= 1")
        if self.deadline_seconds is not None and self.deadline_seconds <= 0:
            raise ValueError("deadline_seconds must be > 0 (or None)")
        if self.stream_every < 1:
            raise ValueError("stream_every must be >= 1")


class Job:
    """One submitted search: spec + lifecycle state + streaming channel.

    State transitions::

        queued -> running -> done | failed | expired | cancelled
        running -> preempted -> queued            (checkpoint + requeue)
        queued -> expired | cancelled             (never ran)

    ``frames`` accumulates format-2 frontier frames (bytes); ``ttff`` is the
    submit-to-first-frame wall (the serving latency metric). ``resume_path``
    points at the preemption checkpoint consumed by ``resume_from`` on the
    next admission."""

    def __init__(self, job_id: str, spec: JobSpec, seq: int):
        self.id = job_id
        self.spec = spec
        self.seq = seq  # FIFO tiebreak
        self.bucket = shape_bucket(spec.X, spec.y, spec.weights, spec.options)
        self.state = QUEUED
        self.result = None
        self.error: str | None = None
        self.stop_reason: str | None = None
        self.submitted_at = time.time()
        self.started_at: float | None = None
        self.finished_at: float | None = None
        self.deadline_at = (
            None
            if spec.deadline_seconds is None
            else self.submitted_at + spec.deadline_seconds
        )
        self.ttff: float | None = None
        self.frames: list[bytes] = []  # guarded by the owning queue's lock
        self.iterations_done = 0
        self.iteration_base = 0  # completed iterations before the current run
        self.preemptions = 0
        self.resume_path: str | None = None
        # -- durability / self-healing state (r15) --
        self.attempts = 0  # run attempts consumed (retry accounting)
        self.not_before = 0.0  # backoff: not admissible before this wall time
        self.solo_only = False  # retried fleet mate: never coalesce again
        self.traceback: str | None = None  # bounded formatted traceback
        self.heartbeat: float | None = None  # wall time of last iteration tick
        self.stall_stop = threading.Event()  # watchdog's cooperative stop
        self.quota_held = False  # tenant quota slot charged (idempotent release)
        self.resume_absolute = False  # exact lockstep resume: callback reports
        #                               ABSOLUTE iterations, not run-relative
        self.resumed_from_iteration: int | None = None
        self.journal_progress_at = 0.0  # last progress-record wall time
        self.preempt_requested = threading.Event()
        self.cancel_requested = threading.Event()
        self.done_event = threading.Event()
        # subscription plumbing: rows pushed before the session exists are
        # staged here (guarded by the server lock) and flushed on start;
        # ``session`` is the live StreamSession once the job is admitted
        self.pending_rows: list = []
        self.session = None

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    def summary(self) -> dict:
        return {
            "id": self.id,
            "tenant": self.spec.tenant,
            "label": self.spec.label,
            "kind": self.spec.kind,
            "state": self.state,
            "priority": self.spec.priority,
            "iterations_done": self.iterations_done,
            "preemptions": self.preemptions,
            "attempts": self.attempts,
            "ttff_seconds": self.ttff,
            "stop_reason": self.stop_reason,
            "error": self.error,
            "traceback": self.traceback,
            "frames": len(self.frames),
        }


class JobQueue:
    """Priority + warm-bucket + quota admission over a condition variable.

    ``acquire`` blocks a worker until an admissible job exists (or timeout);
    ``release`` returns a tenant's quota slot when its job leaves RUNNING.
    All mutation happens under one lock — the queue is the serialization
    point the serve layer hangs its bookkeeping off."""

    def __init__(self, default_quota: int = 2, quotas: dict | None = None):
        if default_quota < 1:
            raise ValueError("default_quota must be >= 1")
        self.default_quota = int(default_quota)
        self.quotas = dict(quotas or {})
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list[Job] = []
        self._running_by_tenant: dict[str, int] = {}

    def _quota(self, tenant: str) -> int:
        return int(self.quotas.get(tenant, self.default_quota))

    # -- submit side ----------------------------------------------------------
    def submit(self, job: Job) -> None:
        with self._cond:
            job.state = QUEUED
            self._pending.append(job)
            self._cond.notify_all()

    def resubmit(self, job: Job) -> None:
        """Re-enqueue a preempted job. Keeps the ORIGINAL submit seq, so a
        preempted job re-enters ahead of later arrivals of its priority."""
        self.submit(job)

    # -- worker side ----------------------------------------------------------
    def _admissible(self, warm_buckets) -> Job | None:
        # caller holds the lock
        best = None
        best_key = None
        age_s = queue_age_seconds()
        now = time.time()
        for job in self._pending:
            if job.cancel_requested.is_set():
                continue
            if job.not_before > now:
                continue  # retry backoff: deferred, not admissible yet
            tenant = job.spec.tenant
            if self._running_by_tenant.get(tenant, 0) >= self._quota(tenant):
                continue
            # head-of-line aging: a job queued past SR_QUEUE_AGE_S competes
            # as if its bucket were warm, so a steady warm stream cannot
            # starve cold-bucket submissions (priority still dominates)
            aged = age_s > 0 and now - job.submitted_at >= age_s
            key = (
                -job.spec.priority,
                0 if aged or job.bucket in warm_buckets else 1,
                job.seq,
            )
            if best is None or key < best_key:
                best, best_key = job, key
        return best

    def acquire(self, warm_buckets=(), timeout: float | None = None) -> Job | None:
        """Pop the best admissible job and charge its tenant's quota. Returns
        None on timeout (or immediately when timeout=0 and nothing fits)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                job = self._admissible(warm_buckets)
                if job is not None:
                    self._pending.remove(job)
                    t = job.spec.tenant
                    self._running_by_tenant[t] = (
                        self._running_by_tenant.get(t, 0) + 1
                    )
                    job.state = RUNNING
                    job.quota_held = True
                    return job
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        return None

    def take_compatible(self, lead: Job, limit: int) -> list[Job]:
        """Pop up to ``limit`` further queued jobs coalescible with ``lead``
        into one fleet batch, charging each tenant's quota like ``acquire``.

        Compatible means: identical shape bucket (so the lanes share every
        compiled program and need no row padding), no deadline (deadline-
        urgent jobs run solo so their wall budget is not hostage to fleet
        drain), no resume checkpoint (a preempted job warm-starts solo), and
        not cancelled. FIFO within the bucket; never blocks."""
        out: list[Job] = []
        now = time.time()
        with self._cond:
            taken = []
            for job in sorted(self._pending, key=lambda j: j.seq):
                if len(out) >= limit:
                    break
                if job.cancel_requested.is_set():
                    continue
                if job.spec.kind != "search":
                    # subscriptions own a long-lived lane of their own; they
                    # never ride a finite fleet batch
                    continue
                if job.bucket != lead.bucket:
                    continue
                if job.deadline_at is not None or job.resume_path is not None:
                    continue
                if job.solo_only or job.not_before > now:
                    # a job retried after a fleet failure is isolated: it
                    # never re-enters a coalesced batch, and backoff-deferred
                    # jobs are not admissible yet
                    continue
                tenant = job.spec.tenant
                if self._running_by_tenant.get(tenant, 0) >= self._quota(tenant):
                    continue
                taken.append(job)
                self._running_by_tenant[tenant] = (
                    self._running_by_tenant.get(tenant, 0) + 1
                )
                job.state = RUNNING
                job.quota_held = True
                out.append(job)
            for job in taken:
                self._pending.remove(job)
        return out

    def release(self, job: Job) -> None:
        """Return the tenant's quota slot when a job leaves RUNNING (to a
        terminal state or back to the queue via preemption). Idempotent:
        keyed on ``job.quota_held`` so a failure path that releases in its
        handler AND in the worker loop's catch-all cannot double-credit the
        tenant."""
        with self._cond:
            if not job.quota_held:
                return
            job.quota_held = False
            t = job.spec.tenant
            n = self._running_by_tenant.get(t, 0) - 1
            if n > 0:
                self._running_by_tenant[t] = n
            else:
                self._running_by_tenant.pop(t, None)
            self._cond.notify_all()

    # -- maintenance ----------------------------------------------------------
    def take_expired(self, now: float | None = None) -> list[Job]:
        """Remove and return queued jobs whose deadline passed while waiting
        (plus cancelled ones) — they are terminal without ever running."""
        now = time.time() if now is None else now
        out = []
        with self._cond:
            keep = []
            for job in self._pending:
                if job.cancel_requested.is_set():
                    out.append(job)
                elif job.deadline_at is not None and now >= job.deadline_at:
                    out.append(job)
                else:
                    keep.append(job)
            self._pending = keep
        return out

    def drain(self) -> list[Job]:
        """Remove and return ALL pending jobs regardless of quota/warmth
        (shutdown path — quota-blocked jobs must still reach a terminal
        state)."""
        with self._cond:
            out = self._pending
            self._pending = []
            self._cond.notify_all()
        return out

    def remove(self, job: Job) -> bool:
        with self._cond:
            try:
                self._pending.remove(job)
                return True
            except ValueError:
                return False

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)

    def wake_all(self) -> None:
        """Unblock every waiting worker (shutdown path)."""
        with self._cond:
            self._cond.notify_all()
