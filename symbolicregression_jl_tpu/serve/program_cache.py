"""Unified program cache: ONE thread-safe bounded LRU for every compiled
artifact the device engine memoizes across searches (round 12).

Replaces the three ad-hoc module dicts that grew in ``models/device_search.py``
between r04 and r10 (``_SCORE_FN_CACHE``/``_SCORE_DATA_CACHE``/``_AOT_CACHE``,
hardcoded caps 12/12/32, three copy-pasted evict-then-setdefault blocks, and
unlocked ``_AOT_CACHE.get`` reads that the multiplexing server would turn
into a live race). Entries are keyed on ``(kind, key)`` where ``key`` already
carries the shape bucket, the Options-derived config objects, and the env-gate
set (the call sites bake those in — see the ``fn_key``/``k_*`` tuples in
device_search.py), so one cache serves every artifact class:

- **Program entries** (score fns, AOT executables; ``nbytes == 0``): bounded
  by entry COUNT (``SR_PROGRAM_CACHE_SIZE``, default 64). Compiled programs
  are host-memory objects of roughly uniform cost; count is the right budget.
- **Data entries** (ScoreData device-array pytrees; ``nbytes > 0``): bounded
  by total BYTES (``SR_SCORE_DATA_CACHE_MB``, default 256). The r04-r10
  count-12 bound let twelve 1 KB toy datasets evict one tenant's 100 MB
  upload — byte accounting keeps retention proportional to device memory
  actually held.

Eviction is LRU within each class (a burst of tiny datasets can never evict a
program, and vice versa), and the most-recently-inserted entry is never
evicted — a single dataset larger than the whole byte budget is admitted
alone rather than rejected, so callers always get cache-or-build semantics
and eviction can only ever cost a recompile/re-upload, never an error.

Counters (hits/misses/evictions, per kind and total) are cheap plain ints
maintained under the same lock; ``stats()`` snapshots them for
``SearchResult.engine_profile`` and the serve-layer ``/stats`` surface.

Builds must happen OUTSIDE the lock (an engine compile is tens of seconds —
holding the lock would serialize every concurrent tenant): ``get`` then
build then ``put``, where ``put`` has setdefault semantics and returns the
winning value, so racing builders converge on one canonical executable.

r17 adds the kernel-resident evolution block to the keyed artifact classes:
``"block_fn"`` entries memoize the identity-stable block closures (they are
jit STATIC arguments, so identity IS the jit/AOT cache key), the ``"aot"``
and ``"fleet_aot"`` ``k_fused`` tuples carry a ``("blk", backend, n_rows)``
token whenever SR_ENGINE_BLOCK replaced the evolve leg (the backend choice
and resident row count are baked into the fused executable), and
``"score_data"`` keys carry ``need_packed`` (the block's XLA reference
backend consumes the packed Xr/yr/wr rows even on non-Pallas platforms).
"""

from __future__ import annotations

import os
import threading

__all__ = [
    "ProgramCache",
    "global_program_cache",
    "enable_persistent_compilation_cache",
    "is_oom_error",
]

_DEFAULT_CAPACITY = 64  # program entries (score fns + AOT executables)
_DEFAULT_DATA_MB = 256  # ScoreData device-array budget

# cache kinds whose miss means "an XLA compile is about to run" — the
# ``oom_compile`` fault site counts ONLY these misses, so a rule's call
# count addresses the Nth compile, not the Nth lookup of anything
_COMPILE_KINDS = frozenset({"aot", "fleet_aot", "fleet_rb"})


def is_oom_error(exc: BaseException) -> bool:
    """Does this exception mean the accelerator ran out of memory building
    or running a program? Matches real ``XlaRuntimeError`` texts and the
    injected :class:`~..utils.faults.ResourceExhaustedInjected` with one
    predicate — the serve layer's downshift logic keys off this, so the
    simulation exercises exactly the production path."""
    text = str(exc)
    return "RESOURCE_EXHAUSTED" in text or "Out of memory" in text


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from None


class ProgramCache:
    """Thread-safe LRU over ``(kind, key)`` with count- and byte-budgets.

    ``kind`` namespaces the artifact class ("score_fn", "score_data", "aot");
    LRU order is maintained by dict insertion order (hits re-insert at the
    MRU end, eviction pops from the LRU front — the r10 ``_cache_get_lru``
    semantics, now in one place instead of three).
    """

    def __init__(
        self,
        capacity: int | None = None,
        data_budget_bytes: int | None = None,
    ):
        self.capacity = (
            _env_int("SR_PROGRAM_CACHE_SIZE", _DEFAULT_CAPACITY)
            if capacity is None
            else int(capacity)
        )
        self.data_budget_bytes = (
            _env_int("SR_SCORE_DATA_CACHE_MB", _DEFAULT_DATA_MB) * (1 << 20)
            if data_budget_bytes is None
            else int(data_budget_bytes)
        )
        if self.capacity < 1:
            raise ValueError("program cache capacity must be >= 1")
        if self.data_budget_bytes < 0:
            raise ValueError("score-data byte budget must be >= 0")
        self._lock = threading.RLock()
        self._entries: dict = {}  # (kind, key) -> (value, nbytes)
        self._data_bytes = 0
        self._hits: dict = {}
        self._misses: dict = {}
        self._evictions: dict = {}

    # -- core API ------------------------------------------------------------
    def get(self, kind: str, key):
        """LRU lookup: a hit re-inserts at the MRU end and counts a hit;
        a miss counts a miss and returns None."""
        k = (kind, key)
        with self._lock:
            ent = self._entries.get(k)
            if ent is None:
                self._misses[kind] = self._misses.get(kind, 0) + 1
            else:
                self._entries[k] = self._entries.pop(k)  # refresh to MRU
                self._hits[kind] = self._hits.get(kind, 0) + 1
                return ent[0]
        # miss on a compile kind: the caller is about to lower().compile().
        # The oom_compile fault fires HERE (outside the lock — a real compile
        # OOM would raise outside it too) so every AOT build site inherits
        # the injection without its own hook. A rule's `kind` param restricts
        # it to one artifact class (e.g. kind=fleet_aot); the call count is
        # consumed either way, keeping schedules deterministic.
        if kind in _COMPILE_KINDS:
            from ..utils import faults

            inj = faults.active()
            if inj.armed("oom_compile"):
                hit = inj.fire("oom_compile")
                if hit is not None and (
                    "kind" not in hit or str(hit["kind"]) == kind
                ):
                    raise faults.ResourceExhaustedInjected(kind, key)
        return None

    def put(self, kind: str, key, value, nbytes: int = 0):
        """Insert with setdefault semantics: if another thread won the build
        race, the existing entry wins and is returned (and refreshed to MRU);
        the loser's build is discarded. ``nbytes > 0`` marks a data entry
        charged against the byte budget instead of the entry-count budget."""
        k = (kind, key)
        nbytes = int(nbytes)
        with self._lock:
            ent = self._entries.get(k)
            if ent is not None:
                self._entries[k] = self._entries.pop(k)
                return ent[0]
            self._entries[k] = (value, nbytes)
            if nbytes > 0:
                self._data_bytes += nbytes
            self._evict_over_budget(keep=k)
            return value

    def get_or_build(self, kind: str, key, build):
        """Convenience wrapper for call sites without side conditions: the
        build runs OUTSIDE the lock; concurrent builders converge on one
        canonical value through ``put``'s setdefault semantics."""
        value = self.get(kind, key)
        if value is not None:
            return value
        return self.put(kind, key, build())

    def _evict_over_budget(self, keep) -> None:
        # caller holds the lock. LRU within each class: over-count evicts the
        # oldest PROGRAM entry, over-bytes the oldest DATA entry — one class's
        # churn never evicts the other's entries. `keep` (the entry just
        # inserted) is exempt, so an oversized single entry is admitted alone.
        n_programs = sum(1 for (_, nb) in self._entries.values() if nb == 0)
        while n_programs > self.capacity:
            victim = next(
                (
                    k
                    for k, (_, nb) in self._entries.items()
                    if nb == 0 and k != keep
                ),
                None,
            )
            if victim is None:
                break
            self._entries.pop(victim)
            self._evictions[victim[0]] = self._evictions.get(victim[0], 0) + 1
            n_programs -= 1
        while self._data_bytes > self.data_budget_bytes:
            victim = next(
                (
                    k
                    for k, (_, nb) in self._entries.items()
                    if nb > 0 and k != keep
                ),
                None,
            )
            if victim is None:
                break
            _, nb = self._entries.pop(victim)
            self._data_bytes -= nb
            self._evictions[victim[0]] = self._evictions.get(victim[0], 0) + 1

    # -- maintenance -----------------------------------------------------------
    def evict(self, kind: str | None = None) -> int:
        """Explicitly evict every entry (or every entry of one kind).
        Returns the number evicted. A search that loses its entries mid-run
        keeps its already-fetched references and simply recompiles next time."""
        with self._lock:
            victims = [
                k
                for k in self._entries
                if kind is None or k[0] == kind
            ]
            for k in victims:
                _, nb = self._entries.pop(k)
                if nb > 0:
                    self._data_bytes -= nb
                self._evictions[k[0]] = self._evictions.get(k[0], 0) + 1
            return len(victims)

    def clear(self) -> int:
        """Evict everything AND zero the counters (test isolation)."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._data_bytes = 0
            self._hits.clear()
            self._misses.clear()
            self._evictions.clear()
            return n

    # -- introspection ---------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self, kind: str | None = None) -> list:
        with self._lock:
            return [
                k for k in self._entries if kind is None or k[0] == kind
            ]

    def stats(self) -> dict:
        """Snapshot of the counters + occupancy — the shape that lands in
        ``SearchResult.engine_profile["program_cache"]`` and the serve-layer
        stats surface."""
        with self._lock:
            kinds = set(self._hits) | set(self._misses) | set(self._evictions)
            by_kind = {
                kind: {
                    "hits": self._hits.get(kind, 0),
                    "misses": self._misses.get(kind, 0),
                    "evictions": self._evictions.get(kind, 0),
                }
                for kind in sorted(kinds)
            }
            hits = sum(self._hits.values())
            misses = sum(self._misses.values())
            # Fleet programs live under kinds prefixed "fleet" ("fleet_aot",
            # "fleet_rb") — roll them up so operators can tell fleet-program
            # reuse apart from solo "aot" reuse at a glance.
            fleet_hits = sum(v for k, v in self._hits.items() if k.startswith("fleet"))
            fleet_misses = sum(v for k, v in self._misses.items() if k.startswith("fleet"))
            return {
                "hits": hits,
                "misses": misses,
                "evictions": sum(self._evictions.values()),
                "entries": len(self._entries),
                "data_bytes": self._data_bytes,
                "capacity": self.capacity,
                "data_budget_bytes": self.data_budget_bytes,
                "hit_ratio": hits / (hits + misses) if hits + misses else 0.0,
                "by_kind": by_kind,
                "fleet": {
                    "hits": fleet_hits,
                    "misses": fleet_misses,
                    "solo_hits": hits - fleet_hits,
                    "solo_misses": misses - fleet_misses,
                },
            }


# ONE process-wide instance: concurrent searches (multi-output fits, serve
# workers) share compiled programs through it, exactly as they shared the
# r04-r10 module dicts — but now behind one lock and one budget.
_GLOBAL: ProgramCache | None = None
_GLOBAL_LOCK = threading.Lock()


def global_program_cache() -> ProgramCache:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ProgramCache()
        return _GLOBAL


def enable_persistent_compilation_cache(path: str | None = None) -> str | None:
    """Wire jax's on-disk XLA compilation cache so a restarted server starts
    warm: AOT ``lower().compile()`` results are keyed by HLO fingerprint and
    re-materialized from disk instead of recompiled (~50s -> ~2s for the
    engine megaprogram, cf. the r04 warm/cold measurement).

    ``path`` falls back to ``SR_COMPILATION_CACHE_DIR``; returns the
    directory in use, or None when neither is set (feature off). The
    min-compile-time/min-entry-size thresholds are lowered to zero so even
    the small per-bucket programs persist; each knob is set best-effort —
    older jax builds without a given config name keep the rest.
    """
    path = path or os.environ.get("SR_COMPILATION_CACHE_DIR")
    if not path:
        return None
    os.makedirs(path, exist_ok=True)
    import jax

    for name, value in (
        ("jax_compilation_cache_dir", path),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(name, value)
        except (AttributeError, ValueError):  # unknown knob on this jax build
            pass
    return path
