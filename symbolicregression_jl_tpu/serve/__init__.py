"""SR-as-a-service: multi-tenant search serving over a resident mesh.

Three layers:

- ``program_cache`` — the unified, thread-safe, capacity-bounded LRU holding
  every compiled engine program (jitted score fns, AOT executables) and the
  device-resident score datasets; replaces the three ad-hoc module dicts
  that used to live in ``models/device_search.py``. Process-global — warm
  across searches with or without a server.
- ``queue`` — the job model (``JobSpec``/``Job``) and the priority +
  warm-bucket + per-tenant-quota admission queue.
- ``server`` — ``SearchServer``: worker threads multiplexing jobs over the
  mesh, streaming frontier frames (format-2 bytes), enforcing deadlines,
  and preempting/resuming via spool checkpoints.
- ``journal`` — ``JobJournal``: the opt-in write-ahead log behind
  ``SearchServer(journal_dir=...)`` crash recovery, retries, and the
  QUARANTINED poison-job state.
- ``pod`` — ``PodNode``/``PodClient``: pod-scale federation — N servers
  over a shared CoordStore presenting one logical service, with
  warmth/load-aware admission, lane migration off dead hosts, and
  SIGTERM graceful drain.
- ``net`` — the network front door: ``NetServer`` (CRC-framed wire
  protocol over asyncio, auth-token→tenant, retryable overload shed)
  and the ``SRClient``/``AsyncSRClient`` SDK with reconnect +
  resume-from-frame-index streaming.
"""

from .journal import JobJournal
from .net import (
    AsyncSRClient,
    ConnectionLost,
    NetError,
    NetServer,
    RetryableWireError,
    SRClient,
    WireError,
)
from .pod import PodClient, PodNode
from .program_cache import (
    ProgramCache,
    enable_persistent_compilation_cache,
    global_program_cache,
)
from .queue import (
    CANCELLED,
    DONE,
    EXPIRED,
    FAILED,
    PREEMPTED,
    QUARANTINED,
    QUEUED,
    RUNNING,
    TERMINAL_STATES,
    Job,
    JobQueue,
    JobSpec,
    ServerOverloaded,
    bucket_digest,
    options_digest,
    queue_age_seconds,
    shape_bucket,
)
from .server import SearchServer

__all__ = [
    "ProgramCache",
    "global_program_cache",
    "enable_persistent_compilation_cache",
    "JobSpec",
    "Job",
    "JobQueue",
    "JobJournal",
    "SearchServer",
    "ServerOverloaded",
    "PodNode",
    "PodClient",
    "NetServer",
    "SRClient",
    "AsyncSRClient",
    "NetError",
    "WireError",
    "RetryableWireError",
    "ConnectionLost",
    "shape_bucket",
    "options_digest",
    "bucket_digest",
    "queue_age_seconds",
    "QUEUED",
    "RUNNING",
    "PREEMPTED",
    "DONE",
    "FAILED",
    "EXPIRED",
    "CANCELLED",
    "QUARANTINED",
    "TERMINAL_STATES",
]
