"""Pod-scale federated serving: N SearchServers, one logical service.

One :class:`PodNode` per host wraps a journaled
:class:`~.server.SearchServer` and federates through a shared
:class:`~..parallel.membership.CoordStore` (the same transport elastic
search membership rides). There is no central scheduler — the pod is a
peer-to-peer protocol over a handful of key namespaces:

- **advertisements** (``srpod/{pod}/ad/{host}``, mutable): each host
  republishes a heartbeat + load/warmth ad every ``SR_POD_HB_S`` — queue
  depth, running count, the digests of its warm shape buckets
  (:func:`~.queue.bucket_digest`: jobs in an advertised bucket skip the
  cold compile on that host), draining flag, and the journal generation
  it currently owns.
- **federated admission**: a :class:`PodClient` routes each submit by
  reading the ads — alive, non-draining hosts whose warmth block contains
  the job's bucket digest first, then least loaded (queue depth + running
  + submits the client itself sent since the ad was stamped) — and drops
  a pickled JobSpec envelope into the chosen host's **inbox**
  (``srpod/{pod}/inbox/{host}/{pjid}``). The host consumes its inbox into
  its local server (journal first, then envelope delete, so a crash
  between the two dedups by pod job id instead of double-running).
- **results**: hosts republish each job's newest frontier frame under
  ``srpod/{pod}/frame/{pjid}`` (mutable) and its terminal record under
  ``srpod/{pod}/done/{pjid}`` — a WRITE-ONCE key. That write-once claim
  is the zero-duplicates mechanism: if a migration ever raced a job onto
  two hosts, exactly one result publishes and the loser increments its
  ``duplicate_results`` counter (the kill drill asserts it stays 0).
- **lane migration**: when a host's ad heartbeat lapses past
  ``SR_POD_SUSPECT_S`` (or its retirement marker appears), a survivor
  claims the dead host's journal generation via an atomic
  ``set_if_absent`` lease (``srpod/{pod}/claim/{host}/gen-N`` — the
  ExchangeGroup suspicion → epoch-bump shape, with the CoordStore lease
  standing in for the lockstep vote) and replays its journal: terminal
  jobs publish their recorded outcome (never rerun), queued AND running
  search jobs re-enter the survivor's server via
  :meth:`~.server.SearchServer.adopt_external` — attempts preserved, the
  dead host's spool checkpoint adopted, so an exact lockstep snapshot
  resumes BIT-IDENTICALLY — and unconsumed inbox envelopes are drained
  too. Each adoption publishes a pod epoch record
  (``srep/pod:{pod}/{n}``, write-once like search epoch records).
- **graceful drain** (``install_sigterm_drain``): SIGTERM pauses
  admission, preempt-checkpoints every running lane at its next
  iteration boundary (journaled ``requeue`` + format-2 spool snapshot),
  closes the journal, publishes a retirement marker, and exits — a
  survivor adopts the generation exactly like a crash, except nothing is
  lost mid-iteration and the handoff is immediate (no suspicion wait).

Journal generations make restart safe: host journals live under
``{root}/{host}/gen-NNNN``. A restarting host that finds its latest
generation CLAIMED (it was adopted while the host was down) starts a
fresh generation instead of re-running jobs another host now owns.

Env knobs: ``SR_POD_ID`` (pod namespace, default ``pod0``),
``SR_POD_ROOT`` (shared journal root), ``SR_POD_HB_S`` (ad cadence,
default 0.25), ``SR_POD_SUSPECT_S`` (heartbeat lapse before adoption,
default 5). ``SR_COORD_GC_S`` sweeps the pod's coordination litter
(frames/done/ads of long-gone jobs); leases, retirement markers, and
epoch records are GC-protected.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
import uuid

from ..parallel.distributed import kv_backoff_max_ms, kv_backoff_ms
from ..parallel.membership import CoordStore, FileCoordStore, coord_store
from ..utils import faults
from . import queue as q
from .queue import JobSpec, ServerOverloaded, bucket_digest, shape_bucket
from .server import SearchServer

__all__ = ["PodNode", "PodClient", "pod_id_env"]


def pod_id_env() -> str:
    return os.environ.get("SR_POD_ID", "pod0") or "pod0"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _poll_backoff(poll: float):
    """Sleep schedule for the ``wait_*`` pollers: stay at the caller's
    ``poll`` interval for the first ``SR_KV_BACKOFF_MS`` of idle waiting
    (keeps first-frame / short-job latency tight), then double each idle
    poll up to ``SR_KV_BACKOFF_MAX_MS`` — the same knobs the KV gather's
    retry loop uses — so a long wait stops hammering the coordination
    store at a fixed interval."""
    fast_for = kv_backoff_ms() / 1000.0
    cap = max(poll, kv_backoff_max_ms() / 1000.0)
    interval = max(1e-4, poll)
    waited = 0.0
    while True:
        yield interval
        waited += interval
        if waited >= fast_for:
            interval = min(interval * 2.0, cap)


class _PodKeys:
    """Key-namespace arithmetic shared by nodes and clients."""

    def __init__(self, pod_id: str):
        self.pod_id = pod_id
        self.ns = f"srpod/{pod_id}"

    def ad(self, host: str) -> str:
        return f"{self.ns}/ad/{host}"

    def ad_prefix(self) -> str:
        return f"{self.ns}/ad/"

    def inbox(self, host: str, pjid: str) -> str:
        return f"{self.ns}/inbox/{host}/{pjid}"

    def inbox_prefix(self, host: str) -> str:
        return f"{self.ns}/inbox/{host}/"

    def frame(self, pjid: str) -> str:
        return f"{self.ns}/frame/{pjid}"

    def done(self, pjid: str) -> str:
        return f"{self.ns}/done/{pjid}"

    def done_prefix(self) -> str:
        return f"{self.ns}/done/"

    def claim(self, host: str, gen: int) -> str:
        return f"{self.ns}/claim/{host}/gen-{int(gen):04d}"

    def retire(self, host: str, gen: int) -> str:
        return f"{self.ns}/retire/{host}/gen-{int(gen):04d}"

    def epoch(self, n: int) -> str:
        # the membership module's epoch-record namespace (GC-protected,
        # write-once): the pod's adoption history is the same kind of
        # artifact as a search group's membership history
        return f"srep/pod:{self.pod_id}/{n}"


class PodNode:
    """One pod host: a journaled SearchServer + the federation loop.

    ``server_kwargs`` pass through to :class:`SearchServer` (worker count,
    fleet mode, retry budget, ...); ``journal_dir`` and ``spool_dir`` are
    owned by the node (the generation directory) and must not be passed.
    """

    def __init__(
        self,
        host_id: str,
        *,
        store: CoordStore | None = None,
        pod_id: str | None = None,
        root: str | None = None,
        hb_seconds: float | None = None,
        suspect_seconds: float | None = None,
        **server_kwargs,
    ):
        if "/" in host_id:
            raise ValueError("host_id must not contain '/'")
        self.host_id = host_id
        self.store = store if store is not None else coord_store()
        self.keys = _PodKeys(pod_id or pod_id_env())
        root = root or os.environ.get("SR_POD_ROOT") or None
        if root is None:
            # unwrap fault-injection decorators (PartitionedCoordStore):
            # the journal root lives on the real file-backed store
            inner = getattr(self.store, "inner", self.store)
            if isinstance(inner, FileCoordStore):
                root = os.path.join(inner.root, "_pod")
            else:
                raise ValueError(
                    "PodNode needs a shared journal root: pass root= or set "
                    "SR_POD_ROOT (required for lane migration — survivors "
                    "replay the dead host's journal from it)"
                )
        self.root = root
        self.hb_s = (
            _env_float("SR_POD_HB_S", 0.25)
            if hb_seconds is None
            else float(hb_seconds)
        )
        self.suspect_s = (
            _env_float("SR_POD_SUSPECT_S", 5.0)
            if suspect_seconds is None
            else float(suspect_seconds)
        )
        self._server_kwargs = dict(server_kwargs)
        self.server: SearchServer | None = None
        self.gen = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._draining = False
        self._drained = threading.Event()
        self.drain_seconds: float | None = None
        self._lock = threading.Lock()
        self._by_pjid: dict[str, str] = {}  # pjid -> local job id
        self._published_frames: dict[str, int] = {}
        self._done_published: set[str] = set()
        self._replayed: set[str] = set()  # pjids whose done may pre-exist
        self._adopted_jobs = 0
        self._adopted_hosts = 0
        self._duplicate_results = 0
        self._skew_suppressed = 0  # suspicions vetoed by ad-stamp progress
        self._last_peer_ad_t: dict[str, float] = {}  # host -> last seen stamp
        # host -> monotonic() when its ad stamp was first seen frozen; a
        # stale-looking peer is only adopted after staying frozen for a
        # full suspect window (see _scan_peers' clock-skew discipline)
        self._frozen_since: dict[str, float] = {}

    # -- generations -----------------------------------------------------------
    def _host_dir(self, host: str) -> str:
        return os.path.join(self.root, host)

    def _gen_dir(self, host: str, gen: int) -> str:
        return os.path.join(self._host_dir(host), f"gen-{int(gen):04d}")

    def _latest_gen(self, host: str) -> int:
        try:
            entries = os.listdir(self._host_dir(host))
        except OSError:
            return 0
        gens = [
            int(e[4:]) for e in entries
            if e.startswith("gen-") and e[4:].isdigit()
        ]
        return max(gens, default=0)

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> "PodNode":
        gen = max(1, self._latest_gen(self.host_id))
        if self.store.try_get(self.keys.claim(self.host_id, gen)) is not None:
            # the previous generation was adopted while this host was down:
            # its jobs belong to the adopter now — never re-run them
            gen += 1
        self.gen = gen
        jdir = self._gen_dir(self.host_id, gen)
        os.makedirs(jdir, exist_ok=True)
        self._publish_ad()  # fresh heartbeat BEFORE the (possibly slow) replay
        server = SearchServer(journal_dir=jdir, **self._server_kwargs)
        if self.store.try_get(self.keys.claim(self.host_id, self.gen)) is not None:
            # lost the boot-vs-adoption race: a survivor claimed this
            # generation while we were replaying it. Its jobs are the
            # adopter's; restart on a fresh generation before running any.
            server.shutdown(wait=False, cancel_queued=False)
            self.gen += 1
            jdir = self._gen_dir(self.host_id, self.gen)
            os.makedirs(jdir, exist_ok=True)
            server = SearchServer(journal_dir=jdir, **self._server_kwargs)
        self.server = server.start()
        self._register_recovered()
        self._publish_ad()
        self._thread = threading.Thread(
            target=self._loop, name=f"sr-pod-{self.host_id}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Hard stop for tests/teardown: no drain, no handoff marker. The
        journal stays adoptable (exactly like a crash, minus the suspicion
        wait a survivor must sit out)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self.server is not None:
            self.server.shutdown(wait=True, cancel_queued=False)

    def __enter__(self) -> "PodNode":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the federation loop ---------------------------------------------------
    def _loop(self) -> None:
        gc = getattr(self.store, "gc", None)
        while not self._stop.is_set():
            try:
                self._tick(gc)
            except Exception:  # noqa: BLE001 — the loop must survive any tick
                pass
            self._stop.wait(self.hb_s)

    def _tick(self, gc=None) -> None:
        self._publish_ad()
        if not self._draining:
            self._consume_inbox(self.host_id)
            self._scan_peers()
        self._publish_progress()
        if gc is not None:
            try:
                gc()  # SR_COORD_GC_S sweep; self-throttled, default off
            except Exception:  # noqa: BLE001
                pass

    def _publish_ad(self) -> None:
        srv = self.server
        stats = {"queued": 0, "running": 0}
        warm: list[str] = []
        if srv is not None:
            warm = srv.warm_digests()
            s = srv.stats()
            stats = {"queued": s["queued"], "running": s["running"]}
        ad = {
            "host": self.host_id,
            # the skewable clock source: a clock_skew rule shifts THIS
            # host's stamps while honest peers keep real time — exactly the
            # failure the scan-side progress veto must absorb
            "t": faults.skewed_time(self.host_id),
            "gen": self.gen,
            "pid": os.getpid(),
            "queue_depth": stats["queued"],
            "running": stats["running"],
            "warm": warm,
            "draining": self._draining,
            "adopted_jobs": self._adopted_jobs,
            "adopted_hosts": self._adopted_hosts,
            "duplicate_results": self._duplicate_results,
            "skew_suspects_suppressed": self._skew_suppressed,
        }
        try:
            self.store.set_mutable(
                self.keys.ad(self.host_id), pickle.dumps(ad)
            )
        except Exception:  # noqa: BLE001 — the next beat republishes
            pass

    def _track(self, pjid: str, local_id: str, replayed: bool = False) -> None:
        with self._lock:
            self._by_pjid[pjid] = local_id
            if replayed:
                self._replayed.add(pjid)

    def _register_recovered(self) -> None:
        """Map this server's journal-recovered jobs back to their pod ids
        (the spec label carries the pjid through the journal), so frames
        and terminals keep publishing after a restart — and so inbox
        envelopes that were journaled-but-not-deleted dedup instead of
        double-running."""
        with self.server._lock:
            jobs = list(self.server._jobs.values())
        for job in jobs:
            pjid = getattr(job.spec, "label", "")
            if pjid.startswith("pj-"):
                self._track(pjid, job.id, replayed=True)

    # -- inbox -----------------------------------------------------------------
    def _consume_inbox(self, host: str) -> None:
        for key in self.store.list(self.keys.inbox_prefix(host)):
            pjid = key.rsplit("/", 1)[-1]
            with self._lock:
                known = pjid in self._by_pjid
            if known or self.store.try_get(self.keys.done(pjid)) is not None:
                self.store.delete(key)  # journaled (or finished) already
                continue
            raw = self.store.try_get(key)
            if raw is None:
                continue
            try:
                env = pickle.loads(raw)
                spec = pickle.loads(env["spec"])
            except Exception:  # noqa: BLE001 — poison envelope
                self.store.delete(key)
                continue
            try:
                local_id = self.server.submit(spec)
            except ServerOverloaded:
                continue  # backpressure: leave the envelope for a later beat
            except RuntimeError:
                return  # shutting down
            self._track(pjid, local_id)
            # journal write happened inside submit(); deleting second means
            # a crash here re-offers the envelope and the pjid dedups above
            self.store.delete(key)

    # -- progress / results ----------------------------------------------------
    def _publish_progress(self) -> None:
        srv = self.server
        if srv is None:
            return
        with self._lock:
            tracked = dict(self._by_pjid)
        for pjid, local_id in tracked.items():
            if pjid in self._done_published:
                continue
            try:
                job = srv.job(local_id)
            except KeyError:
                continue
            start = self._published_frames.get(pjid, 0)
            frames = srv.frames(local_id, start=start)
            if frames:
                self._published_frames[pjid] = start + len(frames)
                try:
                    self.store.set_mutable(
                        self.keys.frame(pjid),
                        pickle.dumps({
                            "n": start + len(frames),
                            "frame": frames[-1],
                            "host": self.host_id,
                            "t": time.time(),
                        }),
                    )
                except Exception:  # noqa: BLE001
                    pass
            if job.terminal:
                self._publish_done(pjid, job)

    def _publish_done(self, pjid: str, job) -> None:
        frames = self.server.frames(job.id)
        rec = {
            "pjid": pjid,
            "state": job.state,
            "error": job.error,
            "stop_reason": job.stop_reason,
            "host": self.host_id,
            "attempts": job.attempts,
            "iterations_done": job.iterations_done,
            "resumed_from_iteration": job.resumed_from_iteration,
            "final_frame": frames[-1] if frames else None,
            "t": time.time(),
        }
        won = self.store.set_if_absent(self.keys.done(pjid), pickle.dumps(rec))
        if not won and pjid not in self._replayed:
            # someone else already published this job's terminal record: a
            # migration raced — count it (the kill drill pins this at 0)
            with self._lock:
                self._duplicate_results += 1
        self._done_published.add(pjid)

    # -- peer adoption ---------------------------------------------------------
    def _scan_peers(self) -> None:
        now = faults.skewed_time(self.host_id)
        for key in self.store.list(self.keys.ad_prefix()):
            host = key.rsplit("/", 1)[-1]
            if host == self.host_id:
                continue
            raw = self.store.try_get(key)
            if raw is None:
                continue
            try:
                ad = pickle.loads(raw)
            except Exception:  # noqa: BLE001
                continue
            gen = int(ad.get("gen", 1))
            retired = (
                self.store.try_get(self.keys.retire(host, gen)) is not None
            )
            ad_t = float(ad.get("t", 0.0))
            stale = now - ad_t > self.suspect_s
            if not retired and stale:
                # clock-skew veto: an ad can look ancient because OUR clock
                # (or the peer's) is skewed, not because the peer died. A
                # dead host's stamp FREEZES — so if the stamp advanced since
                # the last scan, the host is provably still publishing and
                # suspicion is suppressed. Absolute age alone never migrates
                # lanes away from a live, heartbeating host.
                prev = self._last_peer_ad_t.get(host)
                self._last_peer_ad_t[host] = ad_t
                if prev is None or ad_t > prev:
                    # advanced (or first sight): alive, or not yet observed
                    # long enough to judge — start/restart the freeze clock
                    self._frozen_since.pop(host, None)
                    if prev is not None:
                        with self._lock:
                            self._skew_suppressed += 1
                    continue
                # stamp frozen across scans. One missed beat must NOT
                # migrate lanes away from a live host whose publish jitter
                # straddled two of our scans (with our clock skewed, the
                # absolute age is garbage and this pair-compare is ALL the
                # evidence there is) — so require the stamp to stay frozen
                # for a full suspect window of LOCAL MONOTONIC time, the
                # same no-heartbeat window the honest-clock path demands.
                t_frozen = self._frozen_since.setdefault(
                    host, time.monotonic()
                )
                if time.monotonic() - t_frozen < self.suspect_s:
                    continue
            else:
                self._last_peer_ad_t[host] = ad_t
                self._frozen_since.pop(host, None)
            if not retired and not stale:
                continue
            claim_key = self.keys.claim(host, gen)
            if self.store.try_get(claim_key) is not None:
                continue  # already adopted (possibly by the host's own boot)
            lease = {"by": self.host_id, "t": now, "retired": retired}
            if not self.store.set_if_absent(claim_key, pickle.dumps(lease)):
                continue  # another survivor won the lease
            if not retired:
                # liveness re-check after the claim: ANY stamp advance since
                # we started suspecting proves a live publisher (a dead
                # host's stamp cannot move) — back off and release. No
                # absolute-age clause here: with our clock skewed, a live
                # host's fresh ad still looks ancient, and the old
                # age-qualified check waved exactly those adoptions through.
                raw2 = self.store.try_get(key)
                if raw2 is not None:
                    try:
                        ad2 = pickle.loads(raw2)
                        if float(ad2.get("t", 0.0)) > ad_t:
                            self.store.delete(claim_key)
                            self._frozen_since.pop(host, None)
                            continue
                    except Exception:  # noqa: BLE001
                        pass
            self._adopt_host(host, gen, retired=retired)
            self._frozen_since.pop(host, None)
            self.store.delete(key)  # off the routing table

    def _adopt_host(self, host: str, gen: int, retired: bool) -> None:
        """Replay a claimed generation's journal into OUR server: terminal
        jobs publish their recorded outcome exactly once and never rerun;
        live search jobs re-admit with attempts + checkpoint preserved;
        the dead host's unconsumed inbox drains into ours."""
        from .journal import JobJournal

        jdir = self._gen_dir(host, gen)
        adopted = 0
        state: dict[str, dict] = {}
        if os.path.isdir(jdir):
            journal = JobJournal(jdir)
            try:
                state = journal.replay()
            except Exception:  # noqa: BLE001 — unreadable journal: the
                state = {}  # inbox sweep below still rescues queued envelopes
            finally:
                journal.close()
        for st in sorted(state.values(), key=lambda s: s["seq"]):
            spec = None
            if st.get("spec") is not None:
                try:
                    spec = pickle.loads(st["spec"])
                except Exception:  # noqa: BLE001
                    spec = None
            pjid = getattr(spec, "label", "") if spec is not None else ""
            if not pjid.startswith("pj-"):
                continue  # not a pod job (or an undurable spec)
            with self._lock:
                if pjid in self._by_pjid:
                    continue  # chained adoption already brought it here
            if st["state"] in q.TERMINAL_STATES:
                # report once from the journal record; never rerun. The
                # victim usually published this itself — set_if_absent
                # makes the replay idempotent either way.
                self._replayed.add(pjid)
                rec = {
                    "pjid": pjid,
                    "state": st["state"],
                    "error": st.get("error"),
                    "stop_reason": None,
                    "host": host,
                    "attempts": int(st.get("attempts", 0)),
                    "iterations_done": int(st.get("iterations_done", 0)),
                    "resumed_from_iteration": None,
                    "final_frame": None,
                    "from_journal_of": host,
                    "t": time.time(),
                }
                self.store.set_if_absent(
                    self.keys.done(pjid), pickle.dumps(rec)
                )
                self._done_published.add(pjid)
                continue
            if spec.kind != "search":
                # a live stream died with its host; the client resubscribes
                self._replayed.add(pjid)
                rec = {
                    "pjid": pjid,
                    "state": q.CANCELLED,
                    "error": f"host {host} lost mid-subscription",
                    "stop_reason": None,
                    "host": host,
                    "attempts": int(st.get("attempts", 0)),
                    "iterations_done": int(st.get("iterations_done", 0)),
                    "resumed_from_iteration": None,
                    "final_frame": None,
                    "from_journal_of": host,
                    "t": time.time(),
                }
                self.store.set_if_absent(
                    self.keys.done(pjid), pickle.dumps(rec)
                )
                self._done_published.add(pjid)
                continue
            try:
                local_id = self.server.adopt_external(
                    spec,
                    attempts=int(st.get("attempts", 0)),
                    iterations_done=int(st.get("iterations_done", 0)),
                    ckpt=st.get("ckpt"),
                    submitted_at=float(st.get("submitted_at") or 0.0) or None,
                    error=st.get("error"),
                )
            except RuntimeError:
                return  # shutting down mid-adoption; lease keeps others out
            self._track(pjid, local_id)
            adopted += 1
        self._consume_inbox(host)
        with self._lock:
            self._adopted_jobs += adopted
            self._adopted_hosts += 1
        self._publish_epoch({
            "event": "handoff" if retired else "adopt",
            "host": host,
            "gen": gen,
            "by": self.host_id,
            "jobs": adopted,
            "t": time.time(),
        })

    def _publish_epoch(self, record: dict) -> None:
        for n in range(1, 100000):
            if self.store.try_get(self.keys.epoch(n)) is not None:
                continue
            record = dict(record, epoch=n)
            if self.store.set_if_absent(
                self.keys.epoch(n), pickle.dumps(record)
            ):
                return

    # -- graceful drain --------------------------------------------------------
    def drain(self, timeout: float = 60.0) -> bool:
        """SIGTERM semantics: stop admission, checkpoint every running lane
        at its next iteration boundary, close the journal, publish the
        retirement marker, and stop. A peer adopts the generation — queued
        and preempt-checkpointed jobs resume elsewhere with zero loss."""
        t0 = time.monotonic()
        self._draining = True
        self._publish_ad()  # routers see draining=True immediately
        srv = self.server
        idle = True
        if srv is not None:
            idle = srv.drain(timeout=timeout)
            self._publish_progress()  # final frames + any terminals
            srv.shutdown(wait=True, cancel_queued=False)
        self._stop.set()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=10)
        self.store.set_if_absent(
            self.keys.retire(self.host_id, self.gen),
            pickle.dumps({
                "host": self.host_id,
                "gen": self.gen,
                "t": time.time(),
                "idle": idle,
            }),
        )
        self._publish_ad()
        self._drained.set()
        self.drain_seconds = time.monotonic() - t0
        return idle

    def install_sigterm_drain(self) -> None:
        """Route SIGTERM (the preemptible-VM shape) to :meth:`drain` then
        a clean exit. The drain runs on a side thread — signal handlers
        must not block — and the process exits 0 once the handoff marker
        is published."""
        import signal

        def _drain_and_exit() -> None:
            try:
                self.drain()
            finally:
                os._exit(0)

        def _handler(signum, frame):  # noqa: ARG001
            threading.Thread(
                target=_drain_and_exit, name="sr-pod-sigterm-drain",
                daemon=True,
            ).start()

        signal.signal(signal.SIGTERM, _handler)

    # -- observability ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            out = {
                "host": self.host_id,
                "pod": self.keys.pod_id,
                "gen": self.gen,
                "draining": self._draining,
                "tracked_jobs": len(self._by_pjid),
                "adopted_jobs": self._adopted_jobs,
                "adopted_hosts": self._adopted_hosts,
                "duplicate_results": self._duplicate_results,
                "skew_suspects_suppressed": self._skew_suppressed,
            }
        if self.server is not None:
            out["server"] = self.server.stats()
        return out


class PodClient:
    """Submit-side view of the pod: route by warmth/load, poll results.

    The client is stateless apart from a routing hint (submits it has sent
    since each host's ad was stamped — ads refresh every ``SR_POD_HB_S``,
    and a burst of submits between beats would otherwise all land on the
    host that happened to look least loaded)."""

    def __init__(
        self,
        store: CoordStore | None = None,
        pod_id: str | None = None,
        suspect_seconds: float | None = None,
    ):
        self.store = store if store is not None else coord_store()
        self.keys = _PodKeys(pod_id or pod_id_env())
        self.suspect_s = (
            _env_float("SR_POD_SUSPECT_S", 5.0)
            if suspect_seconds is None
            else float(suspect_seconds)
        )
        self._sent_since: dict[str, list[float]] = {}

    # -- topology --------------------------------------------------------------
    def hosts(self) -> dict[str, dict]:
        out = {}
        for key in self.store.list(self.keys.ad_prefix()):
            raw = self.store.try_get(key)
            if raw is None:
                continue
            try:
                ad = pickle.loads(raw)
            except Exception:  # noqa: BLE001
                continue
            out[key.rsplit("/", 1)[-1]] = ad
        return out

    def live_hosts(self) -> dict[str, dict]:
        now = time.time()
        return {
            h: ad
            for h, ad in self.hosts().items()
            if not ad.get("draining")
            and now - float(ad.get("t", 0.0)) <= self.suspect_s
        }

    def _load(self, host: str, ad: dict) -> int:
        stamped = float(ad.get("t", 0.0))
        pending = [t for t in self._sent_since.get(host, ()) if t > stamped]
        self._sent_since[host] = pending
        return int(ad.get("queue_depth", 0)) + int(ad.get("running", 0)) + len(
            pending
        )

    def route(self, spec: JobSpec) -> str:
        """Warmth-first, least-loaded routing: among alive non-draining
        hosts, those advertising the job's bucket digest (their compiled
        programs fit it) win; ties and cold buckets go to the smallest
        queue+running+recently-routed load."""
        live = self.live_hosts()
        if not live:
            raise RuntimeError(
                f"pod {self.keys.pod_id}: no live hosts advertising"
            )
        digest = bucket_digest(
            shape_bucket(spec.X, spec.y, spec.weights, spec.options)
        )
        warm = {
            h: ad for h, ad in live.items() if digest in ad.get("warm", ())
        }
        pool = warm or live
        return min(pool, key=lambda h: (self._load(h, pool[h]), h))

    # -- submit / results ------------------------------------------------------
    def submit(
        self, spec: JobSpec, host: str | None = None, pjid: str | None = None
    ) -> str:
        """Route ``spec`` and drop it into the chosen host's inbox. Returns
        the pod job id (also stamped into ``spec.label`` — the identity
        that survives journals, migrations, and retries)."""
        pjid = pjid or f"pj-{uuid.uuid4().hex[:16]}"
        spec.label = pjid
        target = host or self.route(spec)
        env = {
            "pjid": pjid,
            "spec": pickle.dumps(spec, protocol=pickle.HIGHEST_PROTOCOL),
            "t": time.time(),
            "host": target,
        }
        self.store.set(self.keys.inbox(target, pjid), pickle.dumps(env))
        self._sent_since.setdefault(target, []).append(time.time())
        return pjid

    def done(self, pjid: str) -> dict | None:
        raw = self.store.try_get(self.keys.done(pjid))
        return None if raw is None else pickle.loads(raw)

    def latest_frame(self, pjid: str) -> dict | None:
        raw = self.store.try_get(self.keys.frame(pjid))
        return None if raw is None else pickle.loads(raw)

    def wait(self, pjid: str, timeout: float = 300.0, poll: float = 0.05) -> dict:
        deadline = time.monotonic() + timeout
        backoff = _poll_backoff(poll)
        while True:
            rec = self.done(pjid)
            if rec is not None:
                return rec
            now = time.monotonic()
            if now >= deadline:
                raise TimeoutError(f"pod job {pjid} not terminal in {timeout}s")
            time.sleep(min(next(backoff), deadline - now))

    def wait_first_frame(
        self, pjid: str, timeout: float = 300.0, poll: float = 0.02
    ) -> float:
        """Block until the job's first frontier frame (or terminal record)
        is visible; returns the wall-clock time it was observed — the
        client-side TTFF instant."""
        deadline = time.monotonic() + timeout
        backoff = _poll_backoff(poll)
        while True:
            if (
                self.latest_frame(pjid) is not None
                or self.done(pjid) is not None
            ):
                return time.time()
            now = time.monotonic()
            if now >= deadline:
                raise TimeoutError(f"pod job {pjid}: no frame in {timeout}s")
            time.sleep(min(next(backoff), deadline - now))

    def wait_all(
        self, pjids, timeout: float = 600.0, poll: float = 0.05
    ) -> dict[str, dict]:
        deadline = time.monotonic() + timeout
        out: dict[str, dict] = {}
        pending = list(pjids)
        backoff = _poll_backoff(poll)
        while pending:
            progressed = False
            for pjid in list(pending):
                rec = self.done(pjid)
                if rec is not None:
                    out[pjid] = rec
                    pending.remove(pjid)
                    progressed = True
            if not pending:
                break
            now = time.monotonic()
            if now >= deadline:
                raise TimeoutError(
                    f"pod jobs not terminal in {timeout}s: {pending}"
                )
            if progressed:  # results are landing — reset to the fast poll
                backoff = _poll_backoff(poll)
            time.sleep(min(next(backoff), deadline - now))
        return out

    def results(self) -> dict[str, dict]:
        """Every published terminal record in the pod (drill assertions:
        the done-key set IS the exactly-once ledger)."""
        out = {}
        for key in self.store.list(self.keys.done_prefix()):
            raw = self.store.try_get(key)
            if raw is None:
                continue
            try:
                out[key.rsplit("/", 1)[-1]] = pickle.loads(raw)
            except Exception:  # noqa: BLE001
                continue
        return out
